"""Regenerate ``tools/dslint_fixtures/`` and ``tools/dslint_baseline.json``.

The checked-in fixture sidecars are the program artifacts the
``dslint --all`` composite gate verifies on every CI run (and the
baseline's ratchet metrics — DSO704 exposed wire, DSO705 attribution,
DSS803 per-device parameter bytes — are recorded FROM them).  They are
dumps of the exact engines ``tests/unit/test_dsverify_self.py``
compiles fresh each run:

- ``offload_injit``  — dp=1 streamed offload (``DS_OFFLOAD_FORCE_INJIT``,
  uniform 1 MiB chunks, bf16 host state + error feedback), the
  ``_offload_engine`` fixture;
- ``zero2_overlap``  — dp=4 bucketed-exchange ZeRO-2
  (reduce_bucket_size=140000 / allgather_bucket_size=280000), the
  ``_zero2_overlap_engine`` fixture;
- ``zero3``          — dp=4 stage-3 sharded parameters (same bucket
  geometry; JIT per-group all-gathers inside the step), the
  ``_zero3_engine`` fixture — its DSS803 pin records the ÷dp
  ``param_bytes_per_device`` next to the zero2 fixture's replicated
  figure, and its comm-exposure pin rides the TAG-qualified key
  (``zero3|data4``) so the two overlapped ``train_step`` programs
  never collide in the baseline;
- ``serving``        — the single-replica continuous-batching
  inference engine (tiny GPT-2, one prefill bucket + the donated
  decode program, ``inference.slo`` armed), so ``dslint --all``
  verifies a serving sidecar — KV-donation aliasing (DSP601) and the
  ``serve|data1`` DSS803 residency pins — on every CI run.

Keeping the geometries identical matters: ``test_dsverify_self`` runs
its FRESH compiles against the checked-in baseline expecting exit 0, so
every recorded metric must reproduce from a fresh compile of the same
model (SimpleModel(256, nlayers=8)) on this toolchain.

Run from the repo root after any change that legitimately moves a
recorded metric (then commit the diff):

    python tools/regen_dslint_fixtures.py
"""

import os
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tools", "dslint_fixtures")
BASELINE = os.path.join(REPO, "tools", "dslint_baseline.json")

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
# the offload fixture streams in-jit on CPU (the TPU-path test mode)
os.environ["DS_OFFLOAD_FORCE_INJIT"] = "1"

sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))


def _build_engines(tmp):
    import jax

    import deepspeed_tpu as deepspeed
    import deepspeed_tpu.runtime.zero.coordinator as coord
    from deepspeed_tpu.parallel import make_mesh
    from unit.simple_model import SimpleModel, base_config, random_batches

    # small grouped host buffers, as in test_dsverify_self
    coord.HOST_GROUP_BYTES = 2 << 20
    devices = jax.devices()

    def cfg(run_name, **overrides):
        c = base_config(
            steps_per_print=10 ** 9,
            telemetry={"enabled": True,
                       "run_dir": os.path.join(tmp, run_name)},
            profiling={"comm_ledger": True, "memory_ledger": True})
        c.update(overrides)
        return c

    runs = {}

    # -- offload_injit: the _offload_engine fixture -------------------
    c = cfg("offload_injit", zero_optimization={
        "stage": 2, "cpu_offload": True, "offload_chunk_mb": 1,
        "offload_uniform_chunks": True, "offload_overlap": "auto",
        "offload_state_dtype": {"master": "bf16", "momentum": "bf16",
                                "variance": "bf16",
                                "error_feedback": True}})
    mesh = make_mesh({"data": 1}, devices=devices[:1])
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(256, nlayers=8), config=c, mesh=mesh)
    engine.train_batch(iter([random_batches(
        1, engine.train_micro_batch_size_per_gpu(), 256, seed=0)[0]]))
    engine.close()
    runs["offload_injit"] = os.path.join(tmp, "offload_injit")

    # -- zero2_overlap: the _zero2_overlap_engine fixture -------------
    c = cfg("zero2_overlap",
            zero_optimization={"stage": 2, "overlap_comm": True,
                               "reduce_bucket_size": 140000,
                               "allgather_bucket_size": 280000},
            gradient_clipping=1.0)
    mesh = make_mesh({"data": 4}, devices=devices[:4])
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(256, nlayers=8), config=c, mesh=mesh)
    engine.train_batch(iter([random_batches(
        1, engine.train_micro_batch_size_per_gpu() * 4, 256,
        seed=0)[0]]))
    engine.close()
    runs["zero2_overlap"] = os.path.join(tmp, "zero2_overlap")

    # -- zero3: the stage-3 sharded-parameter fixture (round 20) ------
    # same geometry/buckets as zero2_overlap so the DSS803 pin states
    # the ÷dp claim directly against the stage-2 fixture's figure:
    # params are the flat fp32 master (528 padded rows × 1024 lanes ×
    # 4 B = 2162688 global) sharded over dp=4 → 540672 bytes/device
    c = cfg("zero3",
            zero_optimization={"stage": 3, "overlap_comm": True,
                               "reduce_bucket_size": 140000,
                               "allgather_bucket_size": 280000},
            gradient_clipping=1.0)
    mesh = make_mesh({"data": 4}, devices=devices[:4])
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(256, nlayers=8), config=c, mesh=mesh)
    engine.train_batch(iter([random_batches(
        1, engine.train_micro_batch_size_per_gpu() * 4, 256,
        seed=0)[0]]))
    engine.close()
    runs["zero3"] = os.path.join(tmp, "zero3")

    # -- serving: the inference-engine sidecar (round 19) -------------
    from deepspeed_tpu.inference import InferenceEngine
    from deepspeed_tpu.models import GPT2Config, GPT2LMHeadTPU

    sconfig = {
        "inference": {"kv_block_size": 8, "kv_blocks": 32,
                      "max_batch_slots": 2, "max_seq_len": 32,
                      "prefill_buckets": [16], "token_budget": 64,
                      "max_new_tokens": 4,
                      "slo": {"ttft_ms": 5000, "per_token_ms": 1000}},
        "steps_per_print": 10 ** 9,
        "telemetry": {"enabled": True,
                      "run_dir": os.path.join(tmp, "serving")},
        "profiling": {"comm_ledger": True},
    }
    smodel = GPT2LMHeadTPU(GPT2Config(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
        max_position_embeddings=32, embd_dropout=0.0, attn_dropout=0.0,
        resid_dropout=0.0))
    sparams = smodel.init(jax.random.PRNGKey(0))
    serving = InferenceEngine(smodel, sparams, config=sconfig)
    # deterministic prompts: both serve programs (one prefill bucket +
    # the donated decode) compile and dump with the serve|data1 context
    for i, n in enumerate((5, 9, 13)):
        serving.submit(list(range(1, n + 1)), request_id=f"req-{i}")
    serving.run()
    serving.close()
    runs["serving"] = os.path.join(tmp, "serving")
    return runs


def main():
    from deepspeed_tpu.tools.dslint.cli import main as dslint_main

    with tempfile.TemporaryDirectory() as tmp:
        runs = _build_engines(tmp)
        for name, run_dir in runs.items():
            src = os.path.join(run_dir, "programs")
            dst = os.path.join(FIXTURES, name, "programs")
            if not os.path.isdir(src):
                print(f"error: no programs dumped under {run_dir}",
                      file=sys.stderr)
                return 1
            shutil.rmtree(os.path.join(FIXTURES, name),
                          ignore_errors=True)
            shutil.copytree(src, dst)
            print(f"fixture {name}: {len(os.listdir(dst))} file(s)")
    rc = dslint_main(["--baseline", BASELINE, "--update-baseline"]
                     + [a for name in sorted(runs)
                        for a in ("--programs",
                                  os.path.join(FIXTURES, name))])
    if rc != 0:
        return rc
    print(f"baseline rewritten: {BASELINE}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
