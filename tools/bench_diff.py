#!/usr/bin/env python
"""Repo-level entry point for the bench regression gate.

``python tools/bench_diff.py BENCH_r05.json BENCH_r06.json`` — see
``deepspeed_tpu/tools/bench_diff.py`` (the implementation; also exposed
as ``python -m deepspeed_tpu.telemetry report --diff OLD NEW``).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepspeed_tpu.tools.bench_diff import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
