"""Model-scale functional-test harness.

TPU analog of the reference's model-level test flow
(``/root/reference/tests/model/Megatron_GPT2/run_func_test.py`` — train a
real-config model under a DeepSpeed-config matrix, grep the loss curve
from the run log, compare against the baseline run — and
``/root/reference/tests/model/BingBertSquad/test_e2e_squad.py`` — drive a
QA fine-tune and assert EM/F1 thresholds).

Everything runs on fixed synthetic data (deterministic seeds) so curves
are reproducible and pinnable.  The MLM phase trains real-width BERT-base
(h768 L12 i3072, the reference's bert-pretraining config); the QA phase
is a learnable extractive-span task: each sequence carries one MARKER
token pair and the answer span is the tokens between them, so a
converged model must attend to content (the synthetic stand-in for
SQuAD's answer-span supervision).
"""

import json
import os
import re

import numpy as np

VOCAB = 30528
MARKER_OPEN, MARKER_CLOSE = 5, 6  # reserved marker token ids
LOSS_RE = re.compile(r"^step: (\d+) loss: ([0-9.eE+-]+)$")


def bert_base_config(seq=128, dropout=0.1):
    from deepspeed_tpu.models.bert import BertConfig

    return BertConfig(
        vocab_size=VOCAB, hidden_size=768, num_hidden_layers=12,
        num_attention_heads=12, intermediate_size=3072,
        max_position_embeddings=max(seq, 128),
        hidden_dropout_prob=dropout, attention_probs_dropout_prob=dropout)


def mlm_batches(seed, n_batches, batch, seq, n_pred=8):
    """Fixed synthetic MLM+NSP batches (bing_bert contract: exactly
    ``n_pred`` masked positions per row)."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        ids = rng.integers(10, VOCAB, size=(batch, seq)).astype(np.int32)
        labels = np.full((batch, seq), -100, np.int32)
        for r in range(batch):
            pos = rng.permutation(seq)[:n_pred]
            labels[r, pos] = ids[r, pos]
        out.append({
            "input_ids": ids,
            "masked_lm_labels": labels,
            "next_sentence_label": rng.integers(
                0, 2, size=(batch,)).astype(np.int32),
        })
    return out


def qa_batches(seed, n_batches, batch, seq):
    """Synthetic extractive-QA batches: one MARKER_OPEN..MARKER_CLOSE span
    per row; the gold span INCLUDES the markers (start points at
    MARKER_OPEN, end at MARKER_CLOSE).

    Task-design note (measured, round 4): pointing start/end at the span
    INTERIOR makes the target a neighbor-shift of the marker positions —
    from-scratch BERT (h64 L2 through h768 L12, repeated or fresh data,
    with or without MLM pretraining) never escapes the uniform ln(seq)
    plateau on that variant, while memorizing repeated batches through
    position embeddings alone (train EM 1.0, eval EM 0.0 — a fake pass).
    With the markers themselves as the span ends, each head's target is a
    property of the token AT the position, and the task generalizes
    (held-out EM 1.0 at toy scale in 300 steps)."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        ids = rng.integers(10, VOCAB, size=(batch, seq)).astype(np.int32)
        starts = np.zeros((batch,), np.int32)
        ends = np.zeros((batch,), np.int32)
        for r in range(batch):
            span = int(rng.integers(2, 5))  # >= 2: distinct marker slots
            s = int(rng.integers(1, seq - span - 1))
            ids[r, s] = MARKER_OPEN
            ids[r, s + span - 1] = MARKER_CLOSE
            starts[r], ends[r] = s, s + span - 1
        out.append({"input_ids": ids,
                    "attention_mask": np.ones((batch, seq), np.int32),
                    "start_positions": starts, "end_positions": ends})
    return out


def make_engine(model, ds_config, n_devices=1):
    import jax

    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.parallel import make_mesh

    mesh = make_mesh({"data": n_devices}, devices=jax.devices()[:n_devices])
    engine, *_ = deepspeed.initialize(model=model, config=ds_config,
                                      mesh=mesh)
    return engine


def train_curve(engine, data, steps, log_path=None, sample_every=1):
    """Train ``steps`` steps cycling ``data``; returns the sampled loss
    curve and (optionally) writes the reference-style run log that
    :func:`grep_loss_from_file` parses."""
    import jax

    lines = []
    losses = []
    for t in range(steps):
        loss = engine.train_batch(iter([data[t % len(data)]]))
        if t % sample_every == 0 or t == steps - 1:
            val = float(np.asarray(jax.device_get(loss)))
            losses.append(val)
            lines.append(f"step: {t} loss: {val:.6f}")
    if log_path:
        with open(log_path, "w") as f:
            f.write("\n".join(lines) + "\n")
    return losses


def grep_loss_from_file(path):
    """Parse ``step: N loss: X`` lines (the reference's
    ``run_func_test.py:20`` log-grepping contract)."""
    losses = {}
    with open(path) as f:
        for line in f:
            m = LOSS_RE.match(line.strip())
            if m:
                losses[int(m.group(1))] = float(m.group(2))
    assert losses, f"no loss lines found in {path}"
    return [losses[k] for k in sorted(losses)]


def qa_em_f1(engine, model, eval_batches):
    """Extractive-QA EM / F1 (the BingBertSquad ``test_e2e_squad.py``
    metrics): predict argmax start/end, exact-match and token-overlap F1
    against the gold span."""
    import jax

    em_hits, f1_sum, n = 0, 0.0, 0
    for b in eval_batches:
        logits = engine.eval_batch({"input_ids": b["input_ids"]})
        start_logits, end_logits = logits
        ps = np.asarray(jax.device_get(start_logits)).argmax(-1)
        pe = np.asarray(jax.device_get(end_logits)).argmax(-1)
        for r in range(len(ps)):
            gs, ge = int(b["start_positions"][r]), int(b["end_positions"][r])
            s, e = int(ps[r]), int(pe[r])
            em_hits += int(s == gs and e == ge)
            pred = set(range(s, max(e, s) + 1))
            gold = set(range(gs, ge + 1))
            inter = len(pred & gold)
            if inter:
                p_, r_ = inter / len(pred), inter / len(gold)
                f1_sum += 2 * p_ * r_ / (p_ + r_)
            n += 1
    return em_hits / n, f1_sum / n


def load_or_update_baseline(path, key, curve, update_env="DS_UPDATE_BASELINES"):
    """Pin ``curve`` under ``key`` in a JSON baseline file; regenerate with
    ``DS_UPDATE_BASELINES=1`` (the convergence suite's protocol)."""
    baselines = {}
    if os.path.isfile(path):
        with open(path) as f:
            baselines = json.load(f)
    if os.environ.get(update_env) == "1" or key not in baselines:
        baselines[key] = [round(v, 6) for v in curve]
        with open(path, "w") as f:
            json.dump(baselines, f, indent=1, sort_keys=True)
    return baselines[key]
