"""Model-scale functional-test harness.

TPU analog of the reference's model-level test flow
(``/root/reference/tests/model/Megatron_GPT2/run_func_test.py`` — train a
real-config model under a DeepSpeed-config matrix, grep the loss curve
from the run log, compare against the baseline run — and
``/root/reference/tests/model/BingBertSquad/test_e2e_squad.py`` — drive a
QA fine-tune and assert EM/F1 thresholds).

The MLM phase trains real-width BERT-base (h768 L12 i3072, the
reference's bert-pretraining config) on fixed synthetic data
(deterministic seeds) so curves are reproducible and pinnable; the QA
phase fine-tunes on the vendored REAL extractive-QA subset
(``data/qa_mini.json``, SQuAD v1.1 format) and scores SQuAD-normalized
EM/F1 — see the ``qa_mini_*`` helpers below.
"""

import json
import os
import re

import numpy as np

VOCAB = 30528
LOSS_RE = re.compile(r"^step: (\d+) loss: ([0-9.eE+-]+)$")


def bert_base_config(seq=128, dropout=0.1):
    from deepspeed_tpu.models.bert import BertConfig

    return BertConfig(
        vocab_size=VOCAB, hidden_size=768, num_hidden_layers=12,
        num_attention_heads=12, intermediate_size=3072,
        max_position_embeddings=max(seq, 128),
        hidden_dropout_prob=dropout, attention_probs_dropout_prob=dropout)


def mlm_batches(seed, n_batches, batch, seq, n_pred=8):
    """Fixed synthetic MLM+NSP batches (bing_bert contract: exactly
    ``n_pred`` masked positions per row)."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        ids = rng.integers(10, VOCAB, size=(batch, seq)).astype(np.int32)
        labels = np.full((batch, seq), -100, np.int32)
        for r in range(batch):
            pos = rng.permutation(seq)[:n_pred]
            labels[r, pos] = ids[r, pos]
        out.append({
            "input_ids": ids,
            "masked_lm_labels": labels,
            "next_sentence_label": rng.integers(
                0, 2, size=(batch,)).astype(np.int32),
        })
    return out


# ---------------------------------------------------------------------
# qa_mini: the vendored REAL extractive-QA subset (SQuAD v1.1 format,
# tests/model/data/qa_mini.json).  Natural-language passages, questions
# whose answers are exact context substrings — the round-5 replacement
# for the synthetic marker task (reference flow:
# /root/reference/tests/model/BingBertSquad/test_e2e_squad.py +
# evaluate-v1.1.py's normalize/EM/F1).
# ---------------------------------------------------------------------

QA_MINI_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "data", "qa_mini.json")
_WORD_RE = re.compile(r"[a-z0-9]+")
PAD_ID, CLS_ID, SEP_ID, UNK_ID = 0, 1, 2, 3


def _word_spans(text):
    """Lowercased word tokens with their char spans."""
    return [(m.group(0), m.start(), m.end())
            for m in _WORD_RE.finditer(text.lower())]


def qa_mini_examples():
    with open(QA_MINI_PATH) as f:
        data = json.load(f)["data"]
    out = []
    for art in data:
        for para in art["paragraphs"]:
            for qa in para["qas"]:
                ans = qa["answers"][0]
                out.append({"id": qa["id"], "context": para["context"],
                            "question": qa["question"],
                            "answer_text": ans["text"],
                            "answer_start": ans["answer_start"]})
    return out


def qa_mini_vocab(examples):
    """Deterministic word vocab over the frozen dataset (ids 0-3 are
    specials)."""
    words = set()
    for ex in examples:
        words.update(w for w, _, _ in _word_spans(ex["context"]))
        words.update(w for w, _, _ in _word_spans(ex["question"]))
    return {w: i + 4 for i, w in enumerate(sorted(words))}


def qa_mini_features(seq=96):
    """[CLS] question(padded to a FIXED slot) [SEP] context [SEP] token
    ids + per-example span labels (token indices into the packed input).
    Returns (features dict of arrays, examples, vocab_size).

    The fixed-width question slot is load-bearing for the gate's
    falsifiability: with variable-length packing the context's absolute
    positions shift with the question length, so a model whose attention
    mask is broken (cannot read the question) still distinguishes the
    three questions per passage through position embeddings alone —
    measured EM 0.70 under a fully-hidden question.  With the slot fixed,
    the question TOKENS are the only signal separating same-context
    examples and the broken-mask ceiling drops to ~1/3."""
    examples = qa_mini_examples()
    vocab = qa_mini_vocab(examples)
    n = len(examples)
    q_slot = max(len(_word_spans(ex["question"])) for ex in examples)
    ids = np.zeros((n, seq), np.int32)
    mask = np.zeros((n, seq), np.int32)
    starts = np.zeros((n,), np.int32)
    ends = np.zeros((n,), np.int32)
    ctx_tok_spans = []  # per example: list of (char_lo, char_hi) per pos
    for i, ex in enumerate(examples):
        q = [vocab.get(w, UNK_ID) for w, _, _ in _word_spans(ex["question"])]
        ctx = _word_spans(ex["context"])
        row = [CLS_ID] + q + [PAD_ID] * (q_slot - len(q)) + [SEP_ID]
        qmask = [1] * (1 + len(q)) + [0] * (q_slot - len(q)) + [1]
        ctx_base = len(row)
        row += [vocab.get(w, UNK_ID) for w, _, _ in ctx] + [SEP_ID]
        assert len(row) <= seq, (
            f"{ex['id']}: packed length {len(row)} > seq {seq}")
        ids[i, :len(row)] = row
        mask[i, :len(qmask)] = qmask
        mask[i, len(qmask):len(row)] = 1
        a_lo = ex["answer_start"]
        a_hi = a_lo + len(ex["answer_text"])
        tok_idx = [j for j, (_, lo, hi) in enumerate(ctx)
                   if lo < a_hi and hi > a_lo]
        assert tok_idx, f"{ex['id']}: answer span maps to no tokens"
        starts[i] = ctx_base + tok_idx[0]
        ends[i] = ctx_base + tok_idx[-1]
        ctx_tok_spans.append({ctx_base + j: (lo, hi)
                              for j, (_, lo, hi) in enumerate(ctx)})
    feats = {"input_ids": ids, "attention_mask": mask,
             "start_positions": starts, "end_positions": ends}
    return feats, examples, ctx_tok_spans, len(vocab) + 4


def squad_normalize(s):
    """SQuAD v1.1 answer normalization (lower, strip punctuation and
    articles, squash whitespace — evaluate-v1.1.py semantics)."""
    s = s.lower()
    s = re.sub(r"\b(a|an|the)\b", " ", s)
    s = re.sub(r"[^a-z0-9 ]", " ", s)
    return " ".join(s.split())


def squad_em_f1(pred_text, gold_text):
    p, g = squad_normalize(pred_text), squad_normalize(gold_text)
    em = float(p == g)
    pt, gt = p.split(), g.split()
    common = {}
    for w in pt:
        common[w] = common.get(w, 0) + 1
    overlap = sum(min(c, gt.count(w)) for w, c in common.items())
    if overlap == 0:
        return em, 0.0
    prec, rec = overlap / len(pt), overlap / len(gt)
    return em, 2 * prec * rec / (prec + rec)


def qa_mini_em_f1(engine, feats, examples, ctx_tok_spans, batch=32,
                  corrupt_mask=False):
    """Predict spans with the engine, reconstruct answer TEXT from the
    context char spans, score SQuAD-normalized EM/F1 against gold.
    ``corrupt_mask`` hides the question tokens at eval (the deliberate
    attention-mask break the gate must fail under)."""
    import jax

    n = len(examples)
    em_sum, f1_sum = 0.0, 0.0
    for lo in range(0, n, batch):
        hi = min(lo + batch, n)
        mask = feats["attention_mask"][lo:hi]
        if corrupt_mask:
            mask = mask.copy()
            for r, i in enumerate(range(lo, hi)):
                # zero out [CLS] + question tokens: the span heads can
                # no longer condition on WHICH question is asked
                q_end = int(np.argmax(
                    feats["input_ids"][i] == SEP_ID))
                mask[r, :q_end + 1] = 0
        logits = engine.eval_batch({"input_ids": feats["input_ids"][lo:hi],
                                    "attention_mask": mask})
        sl, el = (np.asarray(jax.device_get(x)) for x in logits)
        for r, i in enumerate(range(lo, hi)):
            spans = ctx_tok_spans[i]
            valid = sorted(spans)
            s = valid[int(np.argmax(sl[r, valid]))]
            e = valid[int(np.argmax(el[r, valid]))]
            if e < s:
                e = s
            pred = examples[i]["context"][spans[s][0]:spans[e][1]]
            em, f1 = squad_em_f1(pred, examples[i]["answer_text"])
            em_sum += em
            f1_sum += f1
    return em_sum / n, f1_sum / n


def make_engine(model, ds_config, n_devices=1):
    import jax

    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.parallel import make_mesh

    mesh = make_mesh({"data": n_devices}, devices=jax.devices()[:n_devices])
    engine, *_ = deepspeed.initialize(model=model, config=ds_config,
                                      mesh=mesh)
    return engine


def train_curve(engine, data, steps, log_path=None, sample_every=1):
    """Train ``steps`` steps cycling ``data``; returns the sampled loss
    curve and (optionally) writes the reference-style run log that
    :func:`grep_loss_from_file` parses."""
    import jax

    lines = []
    losses = []
    for t in range(steps):
        loss = engine.train_batch(iter([data[t % len(data)]]))
        if t % sample_every == 0 or t == steps - 1:
            val = float(np.asarray(jax.device_get(loss)))
            losses.append(val)
            lines.append(f"step: {t} loss: {val:.6f}")
    if log_path:
        with open(log_path, "w") as f:
            f.write("\n".join(lines) + "\n")
    return losses


def grep_loss_from_file(path):
    """Parse ``step: N loss: X`` lines (the reference's
    ``run_func_test.py:20`` log-grepping contract)."""
    losses = {}
    with open(path) as f:
        for line in f:
            m = LOSS_RE.match(line.strip())
            if m:
                losses[int(m.group(1))] = float(m.group(2))
    assert losses, f"no loss lines found in {path}"
    return [losses[k] for k in sorted(losses)]


def load_or_update_baseline(path, key, curve, update_env="DS_UPDATE_BASELINES"):
    """Pin ``curve`` under ``key`` in a JSON baseline file; regenerate with
    ``DS_UPDATE_BASELINES=1`` (the convergence suite's protocol)."""
    baselines = {}
    if os.path.isfile(path):
        with open(path) as f:
            baselines = json.load(f)
    if os.environ.get(update_env) == "1" or key not in baselines:
        baselines[key] = [round(v, 6) for v in curve]
        with open(path, "w") as f:
            json.dump(baselines, f, indent=1, sort_keys=True)
    return baselines[key]
