"""Model-scale functional test driver (standalone).

The reference's ``tests/model/Megatron_GPT2/run_func_test.py`` trains
real Megatron-GPT2 runs under a matrix of DeepSpeed configs, greps the
loss curves from the logs, and compares each DS config against the
baseline run; ``BingBertSquad/test_e2e_squad.py`` then gates a SQuAD
fine-tune on EM/F1.  This driver is that flow for the TPU framework:

1. real-config BERT-base MLM pretraining on fixed synthetic data for a
   few hundred steps, once per config in the matrix (baseline Adam,
   ZeRO-1, ZeRO-2, ZeRO-2+Lamb, bf16);
2. every config's grep'd loss curve must track the baseline's;
3. a QA (extractive-span) fine-tune gated on EM/F1.

Runs on whatever backend JAX selects (on the TPU tier this is minutes;
on CPU pass ``--steps`` to shrink).  Usage::

    python tests/model/run_func_test.py [--steps N] [--batch B] [--seq S]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
from tests.model import func_harness as H  # noqa: E402

BASELINE_KEY = "baseline_adam"

CONFIG_MATRIX = {
    BASELINE_KEY: {"optimizer": {"type": "Adam", "params": {"lr": 1e-4}}},
    "zero1_adam": {"optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                   "zero_optimization": {"stage": 1}},
    "zero2_adam": {"optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                   "zero_optimization": {"stage": 2}},
    "zero2_lamb": {"optimizer": {"type": "Lamb", "params": {"lr": 2e-3}},
                   "zero_optimization": {"stage": 2}},
    "zero2_bf16": {"optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                   "zero_optimization": {"stage": 2},
                   "bf16": {"enabled": True}},
    # ZeRO-Offload leg (round-5 matrix widening): same arithmetic as
    # zero2_bf16, state parked in host memory — streaming engages on TPU
    "zero2_offload": {"optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                      "zero_optimization": {"stage": 2, "cpu_offload": True},
                      "bf16": {"enabled": True}},
}


def run_matrix(steps, batch, seq, out_dir, n_devices=1):
    from deepspeed_tpu.models.bert import BertForPreTrainingTPU

    data = H.mlm_batches(seed=17, n_batches=8, batch=batch, seq=seq)
    curves = {}
    for name, overrides in CONFIG_MATRIX.items():
        cfg = dict({"train_batch_size": batch, "steps_per_print": 10 ** 9},
                   **overrides)
        model = BertForPreTrainingTPU(H.bert_base_config(seq))
        engine = H.make_engine(model, cfg, n_devices)
        log = os.path.join(out_dir, f"func_{name}.log")
        H.train_curve(engine, data, steps, log_path=log,
                      sample_every=max(steps // 20, 1))
        curves[name] = H.grep_loss_from_file(log)
        print(f"[{name}] first {curves[name][0]:.4f} "
              f"last {curves[name][-1]:.4f}", flush=True)
        del engine, model
    return curves


# Same-arithmetic configs (fp32 Adam, only the sharding differs): final
# loss must MATCH the baseline.  Different-arithmetic configs (LAMB's
# trust ratios at its own LR, bf16 rounding) legitimately converge on
# their own trajectory — the gate there is "trains, and ends at least as
# low as the baseline allows" (converging FASTER is not drift; the
# on-chip 120-step run measured LAMB at 0.018 vs Adam 0.611).
EXACT_PARITY = {"zero1_adam", "zero2_adam"}


def check_matrix(curves, rtol):
    """Every DS config's curve must track the baseline's (the reference's
    baseline-vs-deepspeed loss comparison)."""
    base = np.asarray(curves[BASELINE_KEY])
    assert base[-1] < base[0], "baseline did not train"
    failures = []
    for name, c in curves.items():
        if name == BASELINE_KEY:
            continue
        c = np.asarray(c)
        if name in EXACT_PARITY:
            if not np.allclose(c[-1], base[-1], rtol=rtol):
                failures.append(f"{name}: final {c[-1]:.4f} vs baseline "
                                f"{base[-1]:.4f} (rtol {rtol})")
        elif not c[-1] <= base[-1] * (1 + rtol):
            failures.append(f"{name}: final {c[-1]:.4f} worse than "
                            f"baseline {base[-1]:.4f} (+{rtol})")
        if not c[-1] < c[0]:
            failures.append(f"{name}: loss did not decrease "
                            f"({c[0]:.4f} -> {c[-1]:.4f})")
    assert not failures, "config-matrix drift:\n" + "\n".join(failures)


def run_qa_gate(steps, batch, seq, em_min, f1_min, n_devices=1, lr=1e-3,
                corrupt_mask=False, _expect_fail=False):
    """Fine-tune on the vendored REAL extractive-QA subset (qa_mini,
    SQuAD v1.1 format) and gate on SQuAD-normalized EM/F1 (reference:
    BingBertSquad/test_e2e_squad.py).

    Why this gate is attention-honest: each passage carries THREE
    questions with different answers, so any model that cannot read the
    question (a broken attention mask) is capped near EM 1/3 no matter
    how hard it memorizes — ``corrupt_mask=True`` demonstrates exactly
    that (and ``test_qa_gate_fails_under_broken_mask`` pins it)."""
    from deepspeed_tpu.models.bert import BertConfig, \
        BertForQuestionAnsweringTPU

    # seq is dataset-determined (fixed question slot + longest passage);
    # the caller's seq applies to the MLM matrix only
    feats, examples, spans, vocab = H.qa_mini_features(seq=80)
    # calibrated (CPU, 250 steps, lr 1e-3, warmup 30): healthy EM 0.94 /
    # F1 0.95; broken-mask EM 0.15 / F1 0.27 — the 0.75/0.85 gates sit
    # cleanly between
    cfg = BertConfig(
        vocab_size=max(vocab, 128), hidden_size=128, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=512,
        max_position_embeddings=128, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)
    model = BertForQuestionAnsweringTPU(cfg)
    engine = H.make_engine(
        model, {"train_batch_size": batch, "steps_per_print": 10 ** 9,
                "optimizer": {"type": "Adam", "params": {"lr": lr}},
                "scheduler": {"type": "WarmupLR",
                              "params": {"warmup_min_lr": 0.0,
                                         "warmup_max_lr": lr,
                                         "warmup_num_steps": max(steps // 5,
                                                                 10)}}},
        n_devices)
    n = len(examples)
    rng = np.random.default_rng(23)
    for t in range(steps):
        pick = rng.integers(0, n, size=(batch,))
        b = {k: v[pick] for k, v in feats.items()}
        engine.train_batch(iter([b]))
    em, f1 = H.qa_mini_em_f1(engine, feats, examples, spans,
                             corrupt_mask=corrupt_mask)
    print(f"[qa_mini] EM {em:.3f} F1 {f1:.3f} (gates: {em_min}/{f1_min}"
          f"{', corrupt mask' if corrupt_mask else ''})", flush=True)
    ok = em >= em_min and f1 >= f1_min
    if _expect_fail:
        assert not ok, (
            f"gate PASSED under a broken attention mask (EM {em:.3f}, "
            f"F1 {f1:.3f}) — it is not measuring attention")
        return em, f1
    assert ok, (
        f"QA gate failed: EM {em:.3f} < {em_min} or F1 {f1:.3f} < {f1_min}")
    return em, f1


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--qa_steps", type=int, default=250,
                    help="QA fine-tune steps (the 0.75/0.85 EM/F1 gates "
                    "are calibrated at 250)")
    ap.add_argument("--rtol", type=float, default=0.05)
    ap.add_argument("--out", type=str, default="/tmp/ds_func_test")
    args = ap.parse_args()
    os.makedirs(args.out, exist_ok=True)

    curves = run_matrix(args.steps, args.batch, args.seq, args.out)
    check_matrix(curves, args.rtol)
    run_qa_gate(args.qa_steps, args.batch, args.seq,
                em_min=0.75, f1_min=0.85)
    print("run_func_test: ALL PASS")


if __name__ == "__main__":
    main()
