"""Model-scale checkpoint-resume continuity gate (standalone driver).

The reference trains real runs, saves mid-run, resumes in a fresh
process, and asserts the resumed loss curve matches the uninterrupted
one (``/root/reference/tests/model/Megatron_GPT2/run_checkpoint_test.py``
— its ``--checkpoint-num-layers``/LR-scheduler/csv-grep flow).  This
driver is that gate for the TPU framework, per config:

- ``baseline``   stage-0 Adam, dp=2, dropout on (pins rng-stream restore)
- ``zero1``      ZeRO-1, dp=2, dropout on
- ``zero2``      ZeRO-2, dp=2, dropout on
- ``zero2_offload`` ZeRO-2 + cpu_offload (eager host-parked state on CPU)
- ``pipeline``   PipelineModule over a pipe=2 x data=2 mesh
- ``elastic_dp`` ZeRO-2 saved at dp=4, RESUMED at dp=2 (elastic restore)

Flow per config (all three runs in FRESH subprocesses):

1. uninterrupted run: ``steps`` steps, loss logged every step;
2. first half: ``steps//2`` steps, ``save_checkpoint``;
3. resume: fresh process, ``load_checkpoint``, remaining steps.

The resumed curve must match the uninterrupted run's second half
step-for-step (same-arithmetic resume; data is deterministic per
ABSOLUTE step, so a correct restore of master/optimizer/scale/rng/step
counters is exactly reproducible).  A dropped or double-counted ustep,
a stale optimizer moment, or a wrong LR-scheduler restore all shift the
curve and fail the gate.

Usage::

    python tests/model/run_checkpoint_test.py [--steps N] [--configs a,b]
"""

import argparse
import json
import os
import subprocess
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.abspath(os.path.join(HERE, "..", ".."))

VOCAB = 2048
SEQ = 32
BATCH = 8

CONFIGS = ("baseline", "zero1", "zero2", "zero2_async", "zero2_offload",
           "pipeline", "elastic_dp")
# legs that need >1 device (skipped on the single-chip TPU tier)
MULTI_DEVICE = {"baseline": 2, "zero1": 2, "zero2": 2, "zero2_async": 2,
                "zero2_offload": 1, "pipeline": 4, "elastic_dp": 4}


def _ds_config(name, dp):
    base = {"train_batch_size": BATCH, "steps_per_print": 10 ** 9,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "scheduler": {"type": "WarmupLR",
                          "params": {"warmup_min_lr": 0.0,
                                     "warmup_max_lr": 1e-3,
                                     "warmup_num_steps": 8}}}
    if name == "zero1":
        base["zero_optimization"] = {"stage": 1}
    elif name in ("zero2", "elastic_dp"):
        base["zero_optimization"] = {"stage": 2}
    elif name == "zero2_async":
        # the async checkpoint-subsystem leg: background commit +
        # retention; save-then-process-exit must still land a complete
        # checkpoint (non-daemon writer threads)
        base["zero_optimization"] = {"stage": 2}
        base["checkpoint"] = {"async_save": True, "keep_last_n": 2}
    elif name == "zero2_offload":
        base["zero_optimization"] = {"stage": 2, "cpu_offload": True}
    return base


def _dropout(name):
    # dropout ON where the leg pins the rng-stream restore (ustep); off
    # for legs where per-device generation order may differ across the
    # save/resume topology change
    return 0.1 if name in ("baseline", "zero1", "zero2",
                           "zero2_async") else 0.0


# ---------------------------------------------------------------- child
def _child(args):
    if os.environ.get("DS_CKPT_FORCE_CPU") == "1":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax

    sys.path.insert(0, REPO)
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.parallel import make_mesh

    name = args.config
    dp = args.dp
    steps = args.steps

    if name == "pipeline":
        from deepspeed_tpu.runtime.pipe import LayerSpec, PipelineModule

        class Dense:
            def __init__(self, din, dout, act=True):
                self.din, self.dout, self.act = din, dout, act

            def init(self, rng):
                import jax.numpy as jnp  # noqa: F401
                k = jax.random.normal(rng, (self.din, self.dout)) * 0.05
                return {"w": k}

            def apply(self, params, x):
                import jax.numpy as jnp
                y = x @ params["w"]
                return jnp.tanh(y) if self.act else y

        def mse(pred, target):
            import jax.numpy as jnp
            return jnp.mean((pred - target) ** 2)

        H = 64
        specs = [LayerSpec(Dense, H, H) for _ in range(3)] + [
            LayerSpec(Dense, H, H, act=False)]
        module = PipelineModule(specs, loss_fn=mse)
        mesh = make_mesh({"pipe": 2, "data": dp // 2},
                         devices=jax.devices()[:dp])
        cfg = dict(_ds_config(name, dp),
                   train_micro_batch_size_per_gpu=BATCH // (dp // 2),
                   gradient_accumulation_steps=1)
        engine, *_ = deepspeed.initialize(model=module, config=cfg,
                                          mesh=mesh)

        def batch_for(step):
            # cycle 4 fixed batches (still deterministic per absolute
            # step): a fresh random regression batch per step keeps the
            # toy loss flat, which would trip the did-it-train check
            rng = np.random.default_rng(1000 + step % 4)
            x = rng.normal(size=(BATCH, H)).astype(np.float32)
            return (x, np.tanh(x) @ np.eye(H, dtype=np.float32))
    else:
        from deepspeed_tpu.models.bert import (BertConfig,
                                               BertForPreTrainingTPU)

        cfg_m = BertConfig(
            vocab_size=VOCAB, hidden_size=128, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=256,
            max_position_embeddings=128,
            hidden_dropout_prob=_dropout(name),
            attention_probs_dropout_prob=_dropout(name))
        model = BertForPreTrainingTPU(cfg_m)
        mesh = make_mesh({"data": dp}, devices=jax.devices()[:dp])
        engine, *_ = deepspeed.initialize(
            model=model, config=_ds_config(name, dp), mesh=mesh)

        def batch_for(step):
            rng = np.random.default_rng(1000 + step)
            ids = rng.integers(10, VOCAB, size=(BATCH, SEQ)).astype(np.int32)
            labels = np.full((BATCH, SEQ), -100, np.int32)
            for r in range(BATCH):
                pos = rng.permutation(SEQ)[:4]
                labels[r, pos] = ids[r, pos]
            return {"input_ids": ids, "masked_lm_labels": labels,
                    "next_sentence_label": rng.integers(
                        0, 2, size=(BATCH,)).astype(np.int32)}

    if args.load:
        path, _ = engine.load_checkpoint(args.load)
        assert path is not None, f"load_checkpoint({args.load}) found nothing"

    lines = []
    for _ in range(steps):
        step = engine.global_steps  # absolute step drives the data
        loss = engine.train_batch(iter([batch_for(step)]))
        val = float(np.asarray(jax.device_get(loss)))
        lines.append(f"step: {step} loss: {val:.6f}")

    if args.save:
        engine.save_checkpoint(args.save)

    with open(args.log, "w") as f:
        f.write("\n".join(lines) + "\n")
    print("CHILD_OK", flush=True)


# ----------------------------------------------------------- orchestrate
def _run_child(config, steps, dp, log, save=None, load=None, force_cpu=True):
    env = dict(os.environ)
    if force_cpu:
        env["DS_CKPT_FORCE_CPU"] = "1"
        flags = env.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
    cmd = [sys.executable, "-u", os.path.abspath(__file__), "--phase", "child",
           "--config", config, "--steps", str(steps), "--dp", str(dp),
           "--log", log]
    if save:
        cmd += ["--save", save]
    if load:
        cmd += ["--load", load]
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=1200)
    if "CHILD_OK" not in proc.stdout:
        raise RuntimeError(
            f"child failed [{config} steps={steps} dp={dp}]:\n"
            f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")


def _grep(path):
    out = {}
    with open(path) as f:
        for line in f:
            if line.startswith("step: "):
                _, s, _, v = line.split()
                out[int(s)] = float(v)
    return out


def run_config(name, steps, out_dir, force_cpu=True, rtol=1e-4):
    dp = MULTI_DEVICE[name]
    resume_dp = 2 if name == "elastic_dp" else dp
    half = steps // 2
    full_log = os.path.join(out_dir, f"{name}_full.log")
    first_log = os.path.join(out_dir, f"{name}_first.log")
    resume_log = os.path.join(out_dir, f"{name}_resume.log")
    ckpt = os.path.join(out_dir, f"{name}_ckpt")

    _run_child(name, steps, dp, full_log, force_cpu=force_cpu)
    _run_child(name, half, dp, first_log, save=ckpt, force_cpu=force_cpu)
    _run_child(name, steps - half, resume_dp, resume_log, load=ckpt,
               force_cpu=force_cpu)

    full = _grep(full_log)
    first = _grep(first_log)
    resume = _grep(resume_log)
    # sanity: the first-half run reproduces the full run's first half
    for s in first:
        np.testing.assert_allclose(first[s], full[s], rtol=rtol, err_msg=(
            f"[{name}] pre-save divergence at step {s} (harness bug)"))
    assert sorted(resume) == sorted(s for s in full if s >= half), (
        f"[{name}] resumed step numbering wrong: {sorted(resume)}")
    for s in resume:
        np.testing.assert_allclose(resume[s], full[s], rtol=rtol, err_msg=(
            f"[{name}] resumed curve diverged at step {s}: "
            f"{resume[s]} vs uninterrupted {full[s]}"))
    # the run must actually train across the boundary
    fl = [full[s] for s in sorted(full)]
    assert fl[-1] < fl[0], f"[{name}] did not train: {fl}"
    return {"steps": steps, "half": half,
            "final_resumed": resume[max(resume)],
            "final_full": full[max(full)]}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--phase", default="orchestrate")
    ap.add_argument("--config", default=None)
    ap.add_argument("--configs", default=",".join(CONFIGS))
    ap.add_argument("--steps", type=int, default=16)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--log", default=None)
    ap.add_argument("--save", default=None)
    ap.add_argument("--load", default=None)
    ap.add_argument("--out", default="/tmp/ds_ckpt_test")
    ap.add_argument("--tpu", action="store_true",
                    help="run on the real chip: single-device legs only, "
                    "no CPU forcing")
    args = ap.parse_args()

    if args.phase == "child":
        return _child(args)

    os.makedirs(args.out, exist_ok=True)
    names = [c for c in args.configs.split(",") if c]
    results = {}
    for name in names:
        if args.tpu and MULTI_DEVICE[name] > 1:
            print(f"[{name}] SKIP (needs {MULTI_DEVICE[name]} devices)",
                  flush=True)
            continue
        results[name] = run_config(name, args.steps, args.out,
                                   force_cpu=not args.tpu)
        print(f"[{name}] continuity OK "
              f"(resumed final {results[name]['final_resumed']:.6f} == "
              f"uninterrupted {results[name]['final_full']:.6f})", flush=True)
    print(json.dumps({"run_checkpoint_test": "ALL PASS",
                      "configs": list(results)}))


if __name__ == "__main__":
    main()
