"""Config/batch-solver tests (modeled on reference ``tests/unit/test_config.py``
and ``test_ds_config.py``)."""

import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig, DeepSpeedConfigError


def make_config(d, world_size=1):
    return DeepSpeedConfig(d, world_size=world_size)


@pytest.mark.parametrize("num_devices,batch,micro_batch,gas,success", [
    (2, 32, 16, 1, True),
    (2, 32, 8, 2, True),
    (2, 33, 17, 2, False),
    (2, 32, 18, 1, False),
])
def test_batch_config(num_devices, batch, micro_batch, gas, success):
    ds_config = {
        "train_batch_size": batch,
        "train_micro_batch_size_per_gpu": micro_batch,
        "gradient_accumulation_steps": gas,
    }
    if success:
        cfg = make_config(ds_config, world_size=num_devices)
        assert cfg.train_batch_size == batch
    else:
        with pytest.raises(AssertionError):
            make_config(ds_config, world_size=num_devices)


def test_two_of_three_micro_derived():
    cfg = make_config({"train_batch_size": 32, "gradient_accumulation_steps": 2},
                      world_size=4)
    assert cfg.train_micro_batch_size_per_gpu == 4


def test_two_of_three_gas_derived():
    cfg = make_config({"train_batch_size": 32, "train_micro_batch_size_per_gpu": 4},
                      world_size=4)
    assert cfg.gradient_accumulation_steps == 2


def test_only_train_batch():
    cfg = make_config({"train_batch_size": 32}, world_size=4)
    assert cfg.train_micro_batch_size_per_gpu == 8
    assert cfg.gradient_accumulation_steps == 1


def test_only_micro_batch():
    cfg = make_config({"train_micro_batch_size_per_gpu": 8}, world_size=4)
    assert cfg.train_batch_size == 32
    assert cfg.gradient_accumulation_steps == 1


def test_no_batch_info_raises():
    with pytest.raises(DeepSpeedConfigError):
        make_config({"steps_per_print": 5}, world_size=1)


def test_duplicate_json_keys_rejected(tmp_path):
    p = tmp_path / "dup.json"
    p.write_text('{"train_batch_size": 8, "train_batch_size": 16}')
    with pytest.raises(ValueError):
        DeepSpeedConfig(str(p), world_size=1)


def test_zero_config_parsing():
    cfg = make_config({
        "train_batch_size": 8,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2, "cpu_offload": True},
    }, world_size=1)
    assert cfg.zero_enabled
    assert cfg.zero_optimization_stage == 2
    assert cfg.zero_config.cpu_offload


def test_zero_deprecated_bool_form():
    cfg = make_config({
        "train_batch_size": 8,
        "bf16": {"enabled": True},
        "zero_optimization": True,
    }, world_size=1)
    assert cfg.zero_optimization_stage == 1


def test_cpu_offload_requires_stage2():
    with pytest.raises(AssertionError):
        make_config({
            "train_batch_size": 8,
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1, "cpu_offload": True},
        }, world_size=1)


def test_offload_chunk_mb_rejects_bool_and_negative():
    # bool is an int subclass: "offload_chunk_mb": true must not silently
    # become 1 MB chunks; validation raises (ValueError, not a -O-stripped
    # assert)
    for bad in (True, False, -1, "512"):
        with pytest.raises((ValueError, AssertionError)):
            make_config({
                "train_batch_size": 8,
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 2, "cpu_offload": True,
                                      "offload_chunk_mb": bad},
            }, world_size=1)


def test_fp16_and_bf16_exclusive():
    with pytest.raises(AssertionError):
        make_config({
            "train_batch_size": 8,
            "fp16": {"enabled": True},
            "bf16": {"enabled": True},
        }, world_size=1)


def test_fp16_dynamic_loss_scale_args():
    cfg = make_config({
        "train_batch_size": 8,
        "fp16": {
            "enabled": True,
            "initial_scale_power": 16,
            "loss_scale_window": 500,
            "hysteresis": 4,
            "min_loss_scale": 0.5,
        },
    }, world_size=1)
    assert cfg.dynamic_loss_scale_args == {
        "init_scale": 2 ** 16,
        "scale_window": 500,
        "delayed_shift": 4,
        "min_scale": 0.5,
    }
    assert cfg.initial_dynamic_scale == 2 ** 16


def test_scheduler_optimizer_parsing():
    cfg = make_config({
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 0.001}},
        "scheduler": {"type": "WarmupLR", "params": {"warmup_num_steps": 10}},
    }, world_size=1)
    assert cfg.optimizer_name == "adam"
    assert cfg.optimizer_params == {"lr": 0.001}
    assert cfg.scheduler_name == "WarmupLR"
    assert cfg.scheduler_params == {"warmup_num_steps": 10}


def test_sparse_attention_modes():
    cfg = make_config({
        "train_batch_size": 8,
        "sparse_attention": {"mode": "fixed", "block": 32, "num_local_blocks": 8},
    }, world_size=1)
    sa = cfg.sparse_attention
    assert sa["mode"] == "fixed"
    assert sa["block"] == 32
    assert sa["num_local_blocks"] == 8
    with pytest.raises(NotImplementedError):
        make_config({
            "train_batch_size": 8,
            "sparse_attention": {"mode": "bogus"},
        }, world_size=1)


def test_pipeline_defaults():
    cfg = make_config({"train_batch_size": 8}, world_size=1)
    assert cfg.pipeline["partition"] == "best"
    assert cfg.pipeline["activation_checkpoint_interval"] == 0


def test_sparse_attention_config_builds_model_layout():
    """json sparse_attention section → SparsityConfig → trainable model."""
    import jax
    import numpy as np

    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import BertConfig, BertForPreTrainingTPU
    from deepspeed_tpu.parallel import make_mesh

    ds_config = {"train_batch_size": 2, "steps_per_print": 10 ** 9,
                 "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                 "sparse_attention": {"mode": "fixed", "block": 8,
                                      "num_local_blocks": 2,
                                      "num_global_blocks": 1}}
    sa = deepspeed.get_sparse_attention_config(ds_config, num_heads=4)
    assert type(sa).__name__ == "FixedSparsityConfig" and sa.block == 8
    model = BertForPreTrainingTPU(BertConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=4, max_position_embeddings=64,
        attn_impl="sparse", sparsity_config=sa))
    mesh = make_mesh({"data": 1}, devices=jax.devices("cpu")[:1])
    engine, *_ = deepspeed.initialize(model=model, config=ds_config, mesh=mesh)
    batch = {"input_ids": np.zeros((2, 64), np.int32),
             "attention_mask": np.ones((2, 64), np.int32),
             "masked_lm_labels": np.zeros((2, 64), np.int32)}
    loss = engine.train_batch(iter([batch]))
    assert np.isfinite(float(jax.device_get(loss)))


def test_zero_untested_optimizer_gate():
    import jax
    import pytest

    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import BertConfig, BertForPreTrainingTPU
    from deepspeed_tpu.parallel import make_mesh
    from deepspeed_tpu.ops.adam.fused_adam import FusedAdam

    class MyOpt(FusedAdam):
        pass

    def build(allow):
        cfg = {"train_batch_size": 2, "steps_per_print": 10 ** 9,
               "zero_optimization": {"stage": 1}}
        if allow:
            cfg["zero_allow_untested_optimizer"] = True
        mesh = make_mesh({"data": 1}, devices=jax.devices("cpu")[:1])
        model = BertForPreTrainingTPU(BertConfig(
            vocab_size=64, hidden_size=32, num_hidden_layers=1,
            num_attention_heads=2, max_position_embeddings=32))
        return deepspeed.initialize(model=model, optimizer=MyOpt(),
                                    config=cfg, mesh=mesh)

    with pytest.raises(ValueError, match="zero_allow_untested_optimizer"):
        build(allow=False)
    engine, *_ = build(allow=True)
    assert type(engine.optimizer).__name__ == "MyOpt"
