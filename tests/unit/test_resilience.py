"""Resilience subsystem tests (``deepspeed_tpu/resilience``): anomaly
guard policies, divergence rollback, the step watchdog, the loss-scaler
floor fix, and chaos tests proving end-to-end recovery under injected
faults — all on the virtual CPU mesh (tier-1, ``JAX_PLATFORMS=cpu``)."""

import threading
import time

import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu import checkpoint as ckpt
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.resilience import (EXIT_DIVERGENCE_ABORT, EXIT_STEP_HANG,
                                      ChaosMonkey, TrainingDivergedError)
from deepspeed_tpu.resilience.config import DeepSpeedResilienceConfig
from deepspeed_tpu.resilience.guard import (ACTION_ABORT, ACTION_NONE,
                                            ACTION_ROLLBACK, AnomalyGuard)
from deepspeed_tpu.resilience.watchdog import StepWatchdog
from deepspeed_tpu.profiling.step_profiler import StepLatencyRing

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 16


def res_config(checkpoint=None, **resilience):
    resilience.setdefault("enabled", True)
    cfg = base_config(resilience=resilience)
    if checkpoint is not None:
        cfg["checkpoint"] = checkpoint
    return cfg


def make_engine(config, cpu_devices, dp=4):
    mesh = make_mesh({"data": dp}, devices=cpu_devices[:dp])
    model = SimpleModel(HIDDEN, nlayers=2)
    engine, *_ = deepspeed.initialize(model=model, config=config, mesh=mesh)
    return engine


def run_steps(engine, batches):
    return [float(np.asarray(engine.train_batch(iter([b]))))
            for b in batches]


def master_np(engine):
    return np.asarray(jax_get(engine.get_master_params()))


def jax_get(x):
    import jax

    return jax.device_get(x)


# --------------------------------------------------------------- config
def test_resilience_config_defaults_and_parse():
    cfg = DeepSpeedResilienceConfig({})
    assert not cfg.enabled and cfg.policy == "skip"
    assert cfg.divergence_patience == 3 and cfg.max_rollbacks == 2
    assert cfg.hang_timeout_secs == 0.0 and cfg.checkpoint_dir is None
    cfg = DeepSpeedResilienceConfig({"resilience": {
        "enabled": True, "policy": "rollback", "spike_window": 32,
        "spike_zscore": 4.0, "divergence_patience": 2, "max_rollbacks": 1,
        "rollback_cooldown_steps": 10, "hang_timeout_secs": 120,
        "floor_scale_patience": 4, "checkpoint_dir": "/ckpt"}})
    assert cfg.enabled and cfg.policy == "rollback"
    assert cfg.spike_window == 32 and cfg.divergence_patience == 2
    assert cfg.hang_timeout_secs == 120 and cfg.checkpoint_dir == "/ckpt"
    with pytest.raises(AssertionError, match="policy"):
        DeepSpeedResilienceConfig({"resilience": {"policy": "explode"}})


def test_resilience_block_in_config_schema():
    """The block rides the DSC4xx schema: misspelled sub-keys get a
    'did you mean' instead of being silently ignored."""
    from deepspeed_tpu.tools.dslint import validate_config_dict

    issues = validate_config_dict({"resilience": {"polcy": "skip"}})
    assert len(issues) == 1 and issues[0].suggestion == "policy"
    assert not validate_config_dict(
        {"resilience": {"enabled": True, "policy": "abort",
                        "hang_timeout_secs": 60}})


# ---------------------------------------------------------------- guard
def test_guard_nonfinite_and_policy_escalation():
    g = AnomalyGuard(policy="rollback", divergence_patience=3)
    assert g.observe(1.0, False) is ACTION_NONE
    assert g.observe(float("nan"), False) is ACTION_NONE   # 1
    assert g.observe(1.0, True) is ACTION_NONE             # 2
    assert g.observe(1.0, True) is ACTION_ROLLBACK         # 3 = patience
    g.notify_rollback()
    assert g.consecutive_anomalies == 0
    ab = AnomalyGuard(policy="abort", divergence_patience=1)
    assert ab.observe(float("inf"), False) is ACTION_ABORT


def test_guard_skip_policy_never_escalates():
    g = AnomalyGuard(policy="skip", divergence_patience=1)
    for _ in range(5):
        assert g.observe(float("nan"), True) is ACTION_NONE
    assert g.total_anomalies == 5


def test_guard_loss_spike_zscore():
    g = AnomalyGuard(policy="abort", divergence_patience=1,
                     spike_window=32, spike_zscore=6.0)
    for i in range(12):
        assert g.observe(1.0 + 0.01 * (i % 3), False) is ACTION_NONE
    assert g.observe(100.0, False) is ACTION_ABORT
    assert g.recent_events()[-1][1] == "loss_spike"
    # spiky losses never enter the window: the baseline stays clean
    assert max(g._window) < 2.0


def test_guard_spike_detection_disabled_by_zero_window():
    g = AnomalyGuard(policy="abort", divergence_patience=1, spike_window=0)
    for _ in range(20):
        assert g.observe(1.0, False) is ACTION_NONE
    assert g.observe(1e9, False) is ACTION_NONE  # only non-finite counts


def test_guard_scale_floor_event():
    g = AnomalyGuard(policy="skip", floor_scale_patience=3, min_scale=1.0,
                     fp16=True)
    for _ in range(2):
        g.observe(1.0, True, scale=1.0)
    assert all(k != "scale_floor" for _, k, _ in g.recent_events())
    g.observe(1.0, True, scale=1.0)  # 3rd consecutive floor overflow
    assert any(k == "scale_floor" for _, k, _ in g.recent_events())
    # recovery resets the counter
    g.observe(1.0, False, scale=1.0)
    assert g._floor_overflows == 0


# ------------------------------------------------------- engine + guard
def test_engine_skip_policy_protects_weights(cpu_devices):
    """A NaN batch under policy=skip: the in-jit guard skips the update
    for a NON-fp16 run (fp32 here), weights/optimizer are untouched, the
    skipped counter advances, and training continues cleanly."""
    e = make_engine(res_config(policy="skip"), cpu_devices)
    batches = random_batches(4, 16, HIDDEN, seed=0)
    run_steps(e, batches[:1])
    before = master_np(e)
    chaos = ChaosMonkey()
    loss = run_steps(e, [chaos.nan_batch(batches[1])])[0]
    assert not np.isfinite(loss)
    np.testing.assert_array_equal(master_np(e), before)
    assert e.skipped_steps == 1
    assert np.isfinite(run_steps(e, batches[2:3])[0])
    assert np.isfinite(master_np(e)).all()
    kinds = [k for _, k, _ in e._guard.recent_events()]
    assert kinds == ["nonfinite_grads"]


def test_engine_guard_happy_path_unchanged(cpu_devices):
    """Guard on vs off: identical losses on clean data (the in-jit
    non-finite check changes nothing numerically)."""
    batches = random_batches(3, 16, HIDDEN, seed=2)
    ref = run_steps(make_engine(base_config(), cpu_devices), batches)
    got = run_steps(make_engine(res_config(policy="skip"), cpu_devices),
                    batches)
    np.testing.assert_allclose(got, ref, rtol=1e-6)


# ----------------------------------------------------- chaos: rollback
def test_chaos_nan_rollback_end_to_end(cpu_devices, tmp_path):
    """THE acceptance chaos test: injected NaN gradients under
    policy=rollback restore from the last committed checkpoint and the
    run continues to completion — post-rollback losses match a fault-free
    reference run exactly."""
    clean = random_batches(6, 16, HIDDEN, seed=3)
    cfg = res_config(policy="rollback", divergence_patience=2,
                     max_rollbacks=1)

    # fault-free reference: steps 1-2, then the 4 "after" batches
    ref_engine = make_engine(cfg, cpu_devices)
    run_steps(ref_engine, clean[:2])
    ref_losses = run_steps(ref_engine, clean[2:])

    e = make_engine(cfg, cpu_devices)
    run_steps(e, clean[:2])
    e.save_checkpoint(str(tmp_path), sync=True)
    chaos = ChaosMonkey(seed=0)
    # data plan mirrors a resumed dataloader: the two faulted batches are
    # retrained post-rollback, so the recovered run sees exactly the
    # reference's step 3..6 data
    it = chaos.wrap_iter(iter([clean[2], clean[3]] + clean[2:]),
                         nan_steps=(0, 1))
    # pulls 0,1 are NaN -> two consecutive anomalies -> rollback to
    # step 2 inside the second train_batch; pulls 2.. are clean
    nan_losses = [float(np.asarray(e.train_batch(it))) for _ in range(2)]
    assert not any(np.isfinite(nan_losses))
    assert e._rollback_mgr.rollbacks_used == 1
    assert e.global_steps == 2          # rewound to the checkpoint
    assert e.skipped_steps == 0         # counter restored too
    got = [float(np.asarray(e.train_batch(it))) for _ in range(4)]
    np.testing.assert_allclose(got, ref_losses, rtol=1e-6)
    assert e.global_steps == 6
    assert [k for _, k in chaos.log] == ["nan", "nan"]


def test_rollback_budget_exhaustion_aborts(cpu_devices, tmp_path):
    e = make_engine(res_config(policy="rollback", divergence_patience=1,
                               max_rollbacks=0), cpu_devices)
    batches = random_batches(2, 16, HIDDEN, seed=4)
    run_steps(e, batches[:1])
    e.save_checkpoint(str(tmp_path), sync=True)
    chaos = ChaosMonkey()
    with pytest.raises(TrainingDivergedError, match="budget") as exc:
        run_steps(e, [chaos.nan_batch(batches[1])])
    assert exc.value.exit_code == EXIT_DIVERGENCE_ABORT


def test_rollback_without_checkpoint_aborts(cpu_devices):
    e = make_engine(res_config(policy="rollback", divergence_patience=1),
                    cpu_devices)
    batches = random_batches(2, 16, HIDDEN, seed=5)
    run_steps(e, batches[:1])
    chaos = ChaosMonkey()
    with pytest.raises(TrainingDivergedError, match="no checkpoint"):
        run_steps(e, [chaos.nan_batch(batches[1])])


def test_abort_policy_raises_poison(cpu_devices):
    e = make_engine(res_config(policy="abort", divergence_patience=2),
                    cpu_devices)
    batches = random_batches(3, 16, HIDDEN, seed=6)
    run_steps(e, batches[:1])
    chaos = ChaosMonkey()
    run_steps(e, [chaos.nan_batch(batches[1])])  # 1st anomaly: tolerated
    with pytest.raises(TrainingDivergedError, match="diverged") as exc:
        run_steps(e, [chaos.nan_batch(batches[2])])
    assert exc.value.exit_code == EXIT_DIVERGENCE_ABORT


def test_rollback_waits_for_inflight_commit(cpu_devices, tmp_path):
    """Divergence right after an ASYNC save: rollback must drain the
    in-flight commit and restore it, not race it."""
    e = make_engine(res_config(policy="rollback", divergence_patience=1),
                    cpu_devices)
    batches = random_batches(3, 16, HIDDEN, seed=7)
    run_steps(e, batches[:2])
    chaos = ChaosMonkey()
    gate = threading.Event()
    with chaos.delayed_commit(gate=gate):
        e.save_checkpoint(str(tmp_path))          # async, held by chaos
        threading.Timer(0.3, gate.set).start()
        run_steps(e, [chaos.nan_batch(batches[2])])
    assert e._rollback_mgr.rollbacks_used == 1
    assert e.global_steps == 2
    assert ckpt.read_latest(str(tmp_path)) == "global_step2"


def test_rollback_rejects_corrupt_checkpoint(cpu_devices, tmp_path):
    """Bit-rot in the only checkpoint: verify_on_load refuses it and the
    rollback escalates to a loud abort instead of restoring garbage."""
    e = make_engine(res_config(policy="rollback", divergence_patience=1),
                    cpu_devices)
    batches = random_batches(2, 16, HIDDEN, seed=8)
    run_steps(e, batches[:1])
    e.save_checkpoint(str(tmp_path), sync=True)
    chaos = ChaosMonkey(seed=1)
    chaos.corrupt_checkpoint(str(tmp_path / "global_step1"))
    with pytest.raises(TrainingDivergedError, match="no loadable"):
        run_steps(e, [chaos.nan_batch(batches[1])])


def test_chaos_torn_tmp_dir_is_harmless_and_swept(cpu_devices, tmp_path):
    e = make_engine(res_config(), cpu_devices)
    run_steps(e, random_batches(1, 16, HIDDEN, seed=9))
    e.save_checkpoint(str(tmp_path), sync=True)
    chaos = ChaosMonkey()
    torn = chaos.torn_tmp_dir(str(tmp_path), "global_step9")
    # the torn dir never loads nor shadows `latest`
    assert ckpt.verify_checkpoint(torn)[0] == "bad"
    path, _ = e.load_checkpoint(str(tmp_path))
    assert path is not None and path.endswith("global_step1")
    # the next committed save sweeps the wreckage
    run_steps(e, random_batches(1, 16, HIDDEN, seed=10))
    e.save_checkpoint(str(tmp_path), sync=True)
    import os

    assert not os.path.exists(torn)


def test_chaos_crash_mid_save_keeps_previous(cpu_devices, tmp_path):
    e = make_engine(res_config(checkpoint={"save_retries": 0,
                                           "retry_backoff_secs": 0.0}),
                    cpu_devices)
    batches = random_batches(2, 16, HIDDEN, seed=11)
    run_steps(e, batches[:1])
    e.save_checkpoint(str(tmp_path), sync=True)
    chaos = ChaosMonkey()
    run_steps(e, batches[1:])
    with chaos.crash_mid_save():
        with pytest.raises(ckpt.CheckpointError):
            e.save_checkpoint(str(tmp_path), sync=True)
    assert ckpt.read_latest(str(tmp_path)) == "global_step1"
    assert chaos.log[-1][1] == "crash_mid_save"


def test_chaos_sigterm_takes_preemption_save(cpu_devices, tmp_path):
    """Synthetic preemption mid-epoch: the SIGTERM drain commits a final
    synchronous checkpoint at the current step before shutdown."""
    import signal

    from deepspeed_tpu.checkpoint import manager as mgr_mod

    old = signal.signal(signal.SIGTERM, signal.SIG_IGN)
    cbs_before = list(mgr_mod._PREEMPT_CALLBACKS)
    prev_before = dict(mgr_mod._PREEMPT_PREVIOUS)
    try:
        cfg = res_config(checkpoint={"save_on_preemption": True})
        e = make_engine(cfg, cpu_devices)
        batches = random_batches(3, 16, HIDDEN, seed=12)
        run_steps(e, batches[:1])
        e.save_checkpoint(str(tmp_path), sync=True)
        chaos = ChaosMonkey()
        it = chaos.wrap_iter(iter(batches[1:]), sigterm_steps=(1,))
        for _ in range(2):
            e.train_batch(it)
        # the SIGTERM fired before pull 1's step; the preemption handler
        # committed global_step2 synchronously at that point
        assert (0, "sigterm") not in chaos.log
        assert (1, "sigterm") in chaos.log
        assert ckpt.read_latest(str(tmp_path)) == "global_step2"
        assert e.global_steps == 3
    finally:
        mgr_mod._PREEMPT_CALLBACKS[:] = cbs_before
        mgr_mod._PREEMPT_PREVIOUS.clear()
        mgr_mod._PREEMPT_PREVIOUS.update(prev_before)
        signal.signal(signal.SIGTERM, old)


def test_chaos_schedule_is_seed_deterministic():
    a = ChaosMonkey(seed=7).schedule_steps(100, 5)
    b = ChaosMonkey(seed=7).schedule_steps(100, 5)
    c = ChaosMonkey(seed=8).schedule_steps(100, 5)
    assert a == b and len(a) == 5
    assert all(0 <= s < 100 for s in a)
    assert a != c  # different seed, different schedule (overwhelmingly)


# ------------------------------------------------------------ watchdog
def test_watchdog_trips_dumps_and_exits(tmp_path):
    ring = StepLatencyRing(capacity=8)
    for s in (0.1, 0.2, 0.15):
        ring.record(s)
    codes = []
    dump_path = tmp_path / "dump.txt"
    with open(dump_path, "w") as dump:
        wd = StepWatchdog(timeout_secs=0.3, poll_interval=0.05,
                          exit_fn=codes.append, dump_file=dump,
                          latency_ring=ring,
                          describe=lambda: "global_step=7").start()
        try:
            wd.beat()
            # wait on the exit hook, not `fired`: the dump runs between
            # the flag flip and the exit call
            deadline = time.monotonic() + 10
            while not codes and time.monotonic() < deadline:
                time.sleep(0.05)
        finally:
            wd.stop()
    assert codes == [EXIT_STEP_HANG]
    text = dump_path.read_text()
    assert "step watchdog" in text and "global_step=7" in text
    assert "mean=" in text            # latency ring summary
    assert "Current thread" in text or "Thread" in text  # faulthandler


def test_watchdog_arms_only_after_first_beat():
    codes = []
    wd = StepWatchdog(timeout_secs=0.1, poll_interval=0.02,
                      exit_fn=codes.append).start()
    time.sleep(0.4)   # long compile before step 1: must NOT fire
    assert not wd.fired and not codes
    wd.stop()


def test_engine_hung_step_trips_watchdog(cpu_devices, tmp_path):
    """End-to-end through engine config: a chaos-injected step hang
    stalls the heartbeat; the watchdog dumps stacks + step latencies and
    fires the (injected) exit with the respawnable code."""
    e = make_engine(res_config(policy="skip", hang_timeout_secs=0.5),
                    cpu_devices)
    assert e._watchdog is not None
    codes = []
    dump_path = tmp_path / "dump.txt"
    dump = open(dump_path, "w")
    e._watchdog._exit_fn = codes.append   # keep pytest alive
    e._watchdog._dump_file = dump
    try:
        batches = random_batches(3, 16, HIDDEN, seed=13)
        run_steps(e, batches[:1])         # first beat arms the watchdog
        chaos = ChaosMonkey()
        it = chaos.wrap_iter(iter(batches[1:]), hang_steps=(0,),
                             hang_secs=1.5)
        e.train_batch(it)                 # hangs 1.5s > 0.5s timeout
        deadline = time.monotonic() + 10
        while not codes and time.monotonic() < deadline:
            time.sleep(0.05)
        assert codes == [EXIT_STEP_HANG]
        assert (0, "hang") in chaos.log
    finally:
        e._watchdog.stop()
        dump.close()
    text = dump_path.read_text()
    assert "step watchdog" in text and "global_step=1" in text


def test_abort_stops_watchdog_before_raising(cpu_devices):
    """A divergence abort's teardown (final saves, sys.exit with the
    POISON code) must not race the watchdog's RESPAWNABLE os._exit."""
    e = make_engine(res_config(policy="abort", divergence_patience=1,
                               hang_timeout_secs=60), cpu_devices)
    assert e._watchdog is not None
    codes = []
    e._watchdog._exit_fn = codes.append
    batches = random_batches(2, 16, HIDDEN, seed=15)
    run_steps(e, batches[:1])
    chaos = ChaosMonkey()
    with pytest.raises(TrainingDivergedError):
        run_steps(e, [chaos.nan_batch(batches[1])])
    assert e._watchdog._stop.is_set()     # disarmed for the teardown
    assert not codes


# ---------------------------------------------------------- auto_resume
def test_auto_resume_from_latest_pointer(cpu_devices, tmp_path):
    cfg = res_config(policy="rollback",
                     checkpoint_dir=str(tmp_path))
    e = make_engine(cfg, cpu_devices)
    batches = random_batches(4, 16, HIDDEN, seed=14)
    ref_pre = run_steps(e, batches[:2])
    e.save_checkpoint(str(tmp_path), sync=True)
    ref_post = run_steps(e, batches[2:])
    del ref_pre

    mesh = make_mesh({"data": 4}, devices=cpu_devices[:4])
    e2, *_ = deepspeed.initialize(model=SimpleModel(HIDDEN, nlayers=2),
                                  config=cfg, mesh=mesh, auto_resume=True)
    assert e2.global_steps == 2
    np.testing.assert_allclose(run_steps(e2, batches[2:]), ref_post,
                               rtol=1e-6)
    # rollback source defaults to the auto-resume dir: usable immediately
    assert e2._rollback_mgr._load_dir() == str(tmp_path)


def test_auto_resume_fresh_start_when_no_checkpoint(cpu_devices, tmp_path):
    cfg = res_config(checkpoint_dir=str(tmp_path / "empty"))
    mesh = make_mesh({"data": 4}, devices=cpu_devices[:4])
    e, *_ = deepspeed.initialize(model=SimpleModel(HIDDEN, nlayers=2),
                                 config=cfg, mesh=mesh, auto_resume=True)
    assert e.global_steps == 0
    assert np.isfinite(run_steps(e, random_batches(1, 16, HIDDEN))[0])


# ------------------------------------------------- loss-scaler satellite
def test_dynamic_loss_scaler_floor_warning_and_hook():
    from deepspeed_tpu.runtime.fp16.loss_scaler import DynamicLossScaler

    events = []
    s = DynamicLossScaler(init_scale=4, min_scale=1, floor_patience=3,
                          anomaly_hook=events.append)
    for _ in range(6):
        s.update_scale(True)
    # scale path: 4 -> 2 -> 1 (floor) -> three more floor overflows
    assert s.cur_scale == 1
    assert s.floor_stuck
    assert events == [3]           # hook fired once, at patience
    s.update_scale(False)          # one good step resets the detector
    assert s.consecutive_floor_overflows == 0 and not s.floor_stuck


def test_dynamic_loss_scaler_reference_semantics_unchanged():
    """The floor fix must not alter the reference update_scale walk."""
    from deepspeed_tpu.runtime.fp16.loss_scaler import DynamicLossScaler

    s = DynamicLossScaler(init_scale=8, scale_window=2, min_scale=1,
                          delayed_shift=1)
    s.update_scale(True)
    assert s.cur_scale == 4 and s.last_overflow_iter == 0
    s.update_scale(False)
    s.update_scale(False)
    assert s.cur_scale == 8        # window of 2 good iters doubles
    s.update_scale(True)
    s.update_scale(True)
    s.update_scale(True)
    assert s.cur_scale == 1        # floored, silently clamped no more:
    assert s.consecutive_floor_overflows >= 1
