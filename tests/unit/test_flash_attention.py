"""Flash-attention numerics vs the jnp reference (the reference repo's
strategy for kernel tests: compare fused kernel against a layer-by-layer
baseline with tolerances, ``tests/unit/test_cuda_forward.py:23``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.transformer.attention import reference_attention
from deepspeed_tpu.ops.transformer.flash_attention import flash_attention


def rand_qkv(b, s, h, d, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s", [256, 384])
def test_flash_forward_matches_reference(causal, s):
    q, k, v = rand_qkv(2, s, 4, 64)
    out_ref = reference_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal, 128, 128, True)  # interpret mode
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_matches_reference(causal):
    q, k, v = rand_qkv(1, 256, 2, 64, seed=3)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, 128, 128, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")
