"""Flash-attention numerics vs the jnp reference (the reference repo's
strategy for kernel tests: compare fused kernel against a layer-by-layer
baseline with tolerances, ``tests/unit/test_cuda_forward.py:23``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.transformer.attention import reference_attention
from deepspeed_tpu.ops.transformer.flash_attention import flash_attention


def rand_qkv(b, s, h, d, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


def padding_masks(b, s, lengths):
    """(kv_mask [b,s] 1/0, additive [b,1,1,s]) for per-row visible lengths."""
    kvm = np.zeros((b, s), np.float32)
    for i, n in enumerate(lengths):
        kvm[i, :n] = 1.0
    kvm = jnp.asarray(kvm)
    additive = (1.0 - kvm[:, None, None, :]) * -1e9
    return kvm, additive


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s", [256, 384])
def test_flash_forward_matches_reference(causal, s):
    q, k, v = rand_qkv(2, s, 4, 64)
    out_ref = reference_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_matches_reference(causal):
    q, k, v = rand_qkv(1, 256, 2, 64, seed=3)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, block_q=128,
                                       block_k=128, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("causal", [False, True])
def test_flash_key_padding_mask_forward(causal):
    b, s = 2, 256
    q, k, v = rand_qkv(b, s, 4, 64, seed=5)
    kvm, additive = padding_masks(b, s, [200, 131])
    out_ref = reference_attention(q, k, v, mask=additive, causal=causal)
    out = flash_attention(q, k, v, kv_mask=kvm, causal=causal, block_q=128,
                          block_k=128, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_key_padding_mask_backward(causal):
    b, s = 2, 256
    q, k, v = rand_qkv(b, s, 2, 64, seed=7)
    kvm, additive = padding_masks(b, s, [256, 77])

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, kv_mask=kvm, causal=causal,
                                       block_q=128, block_k=128,
                                       interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, mask=additive,
                                           causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")
    # masked keys must receive exactly zero dK/dV
    for g, name in zip(g_flash[1:], "kv"):
        masked_part = np.asarray(g)[1, 77:]
        np.testing.assert_array_equal(masked_part, 0.0,
                                      err_msg=f"d{name} leak into padding")


def test_flash_fully_masked_row_is_zero():
    """A sequence whose every key is padded out must yield zero output and
    zero gradients (not NaN/garbage from an all-NEG_INF softmax)."""
    b, s = 2, 256
    q, k, v = rand_qkv(b, s, 2, 64, seed=9)
    kvm, _ = padding_masks(b, s, [128, 0])
    out = flash_attention(q, k, v, kv_mask=kvm, block_q=128, block_k=128,
                          interpret=True)
    out = np.asarray(out)
    assert np.all(np.isfinite(out))
    np.testing.assert_array_equal(out[1], 0.0)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, kv_mask=kvm, block_q=128,
                                       block_k=128, interpret=True) ** 2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g, name in zip(grads, "qkv"):
        g = np.asarray(g)
        assert np.all(np.isfinite(g)), f"d{name} not finite"
        np.testing.assert_array_equal(g[1], 0.0,
                                      err_msg=f"d{name} on masked batch row")


def test_flash_dropout_zero_rate_identity():
    """rate=0 with a seed present must be the exact no-dropout program."""
    rng = np.random.default_rng(11)
    q, k, v = (jnp.asarray(rng.standard_normal((1, 256, 2, 64)), jnp.float32)
               for _ in range(3))
    base = flash_attention(q, k, v, interpret=True)
    seeded = flash_attention(q, k, v, dropout_seed=jnp.asarray(5, jnp.int32),
                             dropout_rate=0.0, interpret=True)
    assert jnp.array_equal(base, seeded)


@pytest.mark.tpu
def test_flash_dropout_matches_explicit_mask_reference():
    """On-chip: assemble the kernel's regenerable keep masks with a probe
    kernel (same 2-word XOR-fold seeding as ``_keep_mask``), then check
    fwd/dq/dk/dv against a pure-jax attention using that exact mask
    (rel err < 1e-2)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from deepspeed_tpu.ops.transformer.flash_attention import (_auto_blocks,
                                                               _dropout_thresh)

    B, S, H, D, RATE = 2, 512, 4, 64, 0.3
    BQ, BK = _auto_blocks(S, S)
    thresh, inv = _dropout_thresh(RATE)
    rng = np.random.default_rng(0)
    q, k, v, w = (jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
                  for _ in range(4))
    seed = jnp.asarray(123, jnp.int32)

    def tile_kernel(seed_ref, o_ref):
        i, j, kb = pl.program_id(0), pl.program_id(1), pl.program_id(2)
        tile = jnp.int32(j) * jnp.int32(1 << 15) + jnp.int32(kb)
        pltpu.prng_seed(seed_ref[0] ^ jnp.int32(i), seed_ref[1] ^ tile)
        bits = jax.lax.bitcast_convert_type(
            pltpu.prng_random_bits((BQ, BK)), jnp.uint32)
        o_ref[0] = (bits >= jnp.uint32(thresh)).astype(jnp.float32)

    bh = B * H
    M = pl.pallas_call(
        tile_kernel, grid=(bh, S // BQ, S // BK),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((1, BQ, BK), lambda i, j, kb: (i, j, kb)),
        out_shape=jax.ShapeDtypeStruct((bh, S, S), jnp.float32),
    )(jnp.asarray([123, 0], jnp.int32)).reshape(B, H, S, S)

    def ref_with_mask(q_, k_, v_):
        s_ = jnp.einsum("bqhd,bkhd->bhqk", q_, k_) / np.sqrt(D)
        A = M * jax.nn.softmax(s_, axis=-1) * inv
        return jnp.einsum("bhqk,bkhd->bqhd", A, v_)

    def rel(a, b):
        return float(jnp.linalg.norm(a - b) / jnp.linalg.norm(b))

    out_f = flash_attention(q, k, v, dropout_seed=seed, dropout_rate=RATE)
    assert rel(out_f, ref_with_mask(q, k, v)) < 1e-2
    gf = jax.grad(lambda *a: jnp.sum(flash_attention(
        *a, dropout_seed=seed, dropout_rate=RATE) * w), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(ref_with_mask(*a) * w),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert rel(a, b) < 1e-2
    # determinism + seed sensitivity
    again = flash_attention(q, k, v, dropout_seed=seed, dropout_rate=RATE)
    assert jnp.array_equal(out_f, again)
    other = flash_attention(q, k, v, dropout_seed=jnp.asarray(7, jnp.int32),
                            dropout_rate=RATE)
    assert not jnp.array_equal(out_f, other)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_interpret_without_pallas_tpu_package(monkeypatch, causal):
    """CPU-only jax builds (no ``jax.experimental.pallas.tpu``) must still
    serve interpret-mode flash attention — fwd and grads — via the
    scratch-free jnp path, and compiled calls must raise the real reason."""
    from deepspeed_tpu.ops.transformer import flash_attention as fa

    monkeypatch.setattr(fa, "pltpu", None)
    monkeypatch.setattr(fa, "_VMEM", None)
    b, s = 2, 256
    q, k, v = rand_qkv(b, s, 2, 64, seed=11)
    kvm, additive = padding_masks(b, s, [256, 100])
    out = fa.flash_attention(q, k, v, kv_mask=kvm, causal=causal,
                             interpret=True)
    out_ref = reference_attention(q, k, v, mask=additive, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=2e-5, rtol=2e-5)

    def loss(fn):
        return lambda q, k, v: jnp.sum(
            fn(q, k, v, kv_mask=kvm, causal=causal) ** 2)

    g = jax.grad(loss(lambda *a, **kw: fa.flash_attention(
        *a, interpret=True, **kw)), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss(lambda *a, **kw: reference_attention(
        a[0], a[1], a[2], mask=additive, causal=causal)), argnums=(0, 1, 2))(
            q, k, v)
    for gf, gr, name in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")
    with pytest.raises(RuntimeError, match="pallas.tpu"):
        fa.flash_attention(q, k, v, interpret=False)
