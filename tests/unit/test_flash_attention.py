"""Flash-attention numerics vs the jnp reference (the reference repo's
strategy for kernel tests: compare fused kernel against a layer-by-layer
baseline with tolerances, ``tests/unit/test_cuda_forward.py:23``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.transformer.attention import reference_attention
from deepspeed_tpu.ops.transformer.flash_attention import flash_attention


def rand_qkv(b, s, h, d, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


def padding_masks(b, s, lengths):
    """(kv_mask [b,s] 1/0, additive [b,1,1,s]) for per-row visible lengths."""
    kvm = np.zeros((b, s), np.float32)
    for i, n in enumerate(lengths):
        kvm[i, :n] = 1.0
    kvm = jnp.asarray(kvm)
    additive = (1.0 - kvm[:, None, None, :]) * -1e9
    return kvm, additive


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s", [256, 384])
def test_flash_forward_matches_reference(causal, s):
    q, k, v = rand_qkv(2, s, 4, 64)
    out_ref = reference_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_matches_reference(causal):
    q, k, v = rand_qkv(1, 256, 2, 64, seed=3)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, block_q=128,
                                       block_k=128, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("causal", [False, True])
def test_flash_key_padding_mask_forward(causal):
    b, s = 2, 256
    q, k, v = rand_qkv(b, s, 4, 64, seed=5)
    kvm, additive = padding_masks(b, s, [200, 131])
    out_ref = reference_attention(q, k, v, mask=additive, causal=causal)
    out = flash_attention(q, k, v, kv_mask=kvm, causal=causal, block_q=128,
                          block_k=128, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_key_padding_mask_backward(causal):
    b, s = 2, 256
    q, k, v = rand_qkv(b, s, 2, 64, seed=7)
    kvm, additive = padding_masks(b, s, [256, 77])

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, kv_mask=kvm, causal=causal,
                                       block_q=128, block_k=128,
                                       interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, mask=additive,
                                           causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")
    # masked keys must receive exactly zero dK/dV
    for g, name in zip(g_flash[1:], "kv"):
        masked_part = np.asarray(g)[1, 77:]
        np.testing.assert_array_equal(masked_part, 0.0,
                                      err_msg=f"d{name} leak into padding")


def test_flash_fully_masked_row_is_zero():
    """A sequence whose every key is padded out must yield zero output and
    zero gradients (not NaN/garbage from an all-NEG_INF softmax)."""
    b, s = 2, 256
    q, k, v = rand_qkv(b, s, 2, 64, seed=9)
    kvm, _ = padding_masks(b, s, [128, 0])
    out = flash_attention(q, k, v, kv_mask=kvm, block_q=128, block_k=128,
                          interpret=True)
    out = np.asarray(out)
    assert np.all(np.isfinite(out))
    np.testing.assert_array_equal(out[1], 0.0)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, kv_mask=kvm, block_q=128,
                                       block_k=128, interpret=True) ** 2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g, name in zip(grads, "qkv"):
        g = np.asarray(g)
        assert np.all(np.isfinite(g)), f"d{name} not finite"
        np.testing.assert_array_equal(g[1], 0.0,
                                      err_msg=f"d{name} on masked batch row")
