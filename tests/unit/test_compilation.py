"""Compilation subsystem: persistent XLA cache + compile telemetry.

The capacity-scale receipts (a ~35-min gpt2-xl compile becoming a warm
load) are TPU-bound, but every mechanism is backend-agnostic and
CI-checked here: config parsing/validation, the enable policy
("auto" defers to an ambient cache; true overrides; false disables),
the TWO-FRESH-SUBPROCESS warm-start roundtrip, and the
jax.monitoring -> TelemetryManager bridge.
"""

import json
import os
import subprocess
import sys

import jax
import pytest

from deepspeed_tpu.runtime.compilation import (CompileStats,
                                               DeepSpeedCompilationConfig,
                                               configure_persistent_cache,
                                               install_compile_telemetry,
                                               uninstall_compile_telemetry)

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))


@pytest.fixture
def cache_knobs():
    """Snapshot/restore the process-global jax cache config + env (these
    tests deliberately flip them; the rest of the suite must keep the
    conftest-configured warm cache)."""
    old = (jax.config.jax_compilation_cache_dir,
           jax.config.jax_persistent_cache_min_compile_time_secs,
           jax.config.jax_persistent_cache_min_entry_size_bytes,
           os.environ.get("JAX_COMPILATION_CACHE_DIR"))
    yield
    jax.config.update("jax_compilation_cache_dir", old[0])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", old[1])
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", old[2])
    if old[3] is None:
        os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
    else:
        os.environ["JAX_COMPILATION_CACHE_DIR"] = old[3]


# ---------------------------------------------------------------- config
def test_config_defaults_and_validation():
    cfg = DeepSpeedCompilationConfig({})
    assert cfg.cache == "auto" and cfg.cache_dir == ""
    assert cfg.min_entry_size_bytes == 0 and cfg.min_compile_secs == 0.0
    cfg = DeepSpeedCompilationConfig(
        {"compilation": {"cache": True, "cache_dir": "/x",
                         "min_entry_size_bytes": 4096,
                         "min_compile_secs": 1.5}})
    assert cfg.cache is True and cfg.cache_dir == "/x"
    assert cfg.min_entry_size_bytes == 4096 and cfg.min_compile_secs == 1.5
    with pytest.raises(ValueError):
        DeepSpeedCompilationConfig({"compilation": {"cache": "yes"}})
    # 0/1 are rejected, not bool-coerced: 0 == False passes an equality
    # check yet matches neither `is False` nor `== "auto"` downstream —
    # an explicit disable would silently force-ENABLE (reviewed defect)
    with pytest.raises(ValueError):
        DeepSpeedCompilationConfig({"compilation": {"cache": 0}})
    with pytest.raises(ValueError):
        DeepSpeedCompilationConfig({"compilation": {"cache": 1}})
    with pytest.raises(ValueError):
        DeepSpeedCompilationConfig(
            {"compilation": {"min_entry_size_bytes": -1}})
    with pytest.raises(ValueError):
        DeepSpeedCompilationConfig({"compilation": {"min_compile_secs": -1}})


def test_compilation_block_in_dsc4xx_schema():
    """The dslint config-schema extractor knows the new block: a typo'd
    sub-key is flagged with a suggestion (DSC402 machinery)."""
    from deepspeed_tpu.tools.dslint.schema import validate_config_dict

    issues = validate_config_dict(
        {"compilation": {"cache": True, "cach_dir": "/x"}})
    assert len(issues) == 1
    assert issues[0].section == "compilation"
    assert issues[0].suggestion == "cache_dir"
    assert not validate_config_dict(
        {"compilation": {"cache": "auto", "cache_dir": "/x",
                         "min_entry_size_bytes": 0,
                         "min_compile_secs": 0.5}})


# ---------------------------------------------------------------- policy
def test_configure_auto_defers_to_ambient(cache_knobs, tmp_path):
    jax.config.update("jax_compilation_cache_dir", str(tmp_path / "ambient"))
    cfg = DeepSpeedCompilationConfig({})  # auto
    got = configure_persistent_cache(cfg, run_dir=str(tmp_path / "run"))
    assert got == str(tmp_path / "ambient")
    assert jax.config.jax_compilation_cache_dir == str(tmp_path / "ambient")
    assert not (tmp_path / "run").exists()


def test_configure_disabled_touches_nothing(cache_knobs, tmp_path):
    cfg = DeepSpeedCompilationConfig({"compilation": {"cache": False}})
    assert configure_persistent_cache(cfg, run_dir=str(tmp_path)) is None
    assert not (tmp_path / "xla_cache").exists()


def test_configure_forced_overrides_and_exports(cache_knobs, tmp_path):
    jax.config.update("jax_compilation_cache_dir", str(tmp_path / "ambient"))
    cfg = DeepSpeedCompilationConfig(
        {"compilation": {"cache": True, "min_compile_secs": 0.25}})
    got = configure_persistent_cache(cfg, run_dir=str(tmp_path / "run"))
    assert got == str(tmp_path / "run" / "xla_cache")
    assert os.path.isdir(got)
    assert jax.config.jax_compilation_cache_dir == got
    assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.25
    # subprocess inheritance: fresh-process trials read the env var
    assert os.environ["JAX_COMPILATION_CACHE_DIR"] == got


def test_configure_auto_with_explicit_dir_wins(cache_knobs, tmp_path):
    """An explicitly configured cache_dir is intent: under the default
    "auto" it must override an ambient cache (including the env var a
    prior engine in this process exported), not be silently ignored."""
    jax.config.update("jax_compilation_cache_dir", str(tmp_path / "ambient"))
    cfg = DeepSpeedCompilationConfig(
        {"compilation": {"cache_dir": str(tmp_path / "mine")}})
    got = configure_persistent_cache(cfg)
    assert got == str(tmp_path / "mine")
    assert jax.config.jax_compilation_cache_dir == got


def test_configure_auto_enables_when_nothing_ambient(cache_knobs, tmp_path):
    jax.config.update("jax_compilation_cache_dir", None)
    os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
    cfg = DeepSpeedCompilationConfig({})
    got = configure_persistent_cache(cfg, run_dir=str(tmp_path))
    assert got == str(tmp_path / "xla_cache") and os.path.isdir(got)


# ------------------------------------------------- fresh-process roundtrip
_ROUNDTRIP = r"""
import json, os, sys, time
t0 = time.perf_counter()
import numpy as np, jax
import deepspeed_tpu as deepspeed
from deepspeed_tpu.runtime.compilation import CompileStats
from deepspeed_tpu.parallel import make_mesh

stats = CompileStats()


class Stack:
    def init(self, rng):
        import jax.numpy as jnp
        ks = jax.random.split(rng, 4)
        return {f"l{i}": jax.random.normal(ks[i], (64, 64)) * 0.1
                for i in range(4)}

    def apply(self, params, batch, rng=None, train=True, **kw):
        import jax.numpy as jnp
        h, y = batch
        for i in range(4):
            h = jnp.tanh(h @ params[f"l{i}"])
        return jnp.mean((h - y) ** 2)


mesh = make_mesh({"data": 1}, devices=jax.devices("cpu")[:1])
engine, *_ = deepspeed.initialize(
    model=Stack(), mesh=mesh,
    config={"train_batch_size": 8, "steps_per_print": 10 ** 9,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "compilation": {"cache": True, "cache_dir": sys.argv[1],
                            "min_compile_secs": 0.0}})
rng = np.random.default_rng(0)
b = (rng.normal(size=(8, 64)).astype(np.float32),
     rng.normal(size=(8, 64)).astype(np.float32))
loss = engine.train_batch(iter([b]))
assert np.isfinite(float(np.asarray(jax.device_get(loss))))
out = stats.as_dict()
out["wall_secs"] = round(time.perf_counter() - t0, 3)
print("ROUNDTRIP " + json.dumps(out))
"""


def _roundtrip_run(cache_dir):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    proc = subprocess.run(
        [sys.executable, "-c", _ROUNDTRIP, str(cache_dir)],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-3000:]
    for line in proc.stdout.splitlines():
        if line.startswith("ROUNDTRIP "):
            return json.loads(line[len("ROUNDTRIP "):])
    raise AssertionError(f"no ROUNDTRIP line in: {proc.stdout[-2000:]}")


def test_two_fresh_subprocess_cache_roundtrip(tmp_path):
    """THE warm-start receipt, process-boundary honest: a second fresh
    process against the populated cache loads its programs (cache hits,
    near-zero cold-compile wall) instead of recompiling them."""
    cache_dir = tmp_path / "xla_cache"
    cold = _roundtrip_run(cache_dir)
    assert cold["compile_cache_misses"] > 0, cold
    assert cold["compile_seconds_cold"] > 0, cold
    assert os.listdir(cache_dir), "cache dir not populated"
    warm = _roundtrip_run(cache_dir)
    assert warm["compile_cache_hits"] >= cold["compile_cache_misses"], (
        cold, warm)
    assert warm["compile_cache_misses"] == 0, warm
    # measurably faster: the backend-compile wall actually paid must
    # collapse (wall-clock totals are import-dominated on CPU; the
    # compile split is the robust signal — and what PERF.md records)
    assert warm["compile_seconds_cold"] <= cold["compile_seconds_cold"] * 0.2, (
        cold, warm)


# ------------------------------------------------------ telemetry bridge
def test_compile_telemetry_bridge(cache_knobs, tmp_path):
    """A backend compile becomes a ``compile`` event + histogram sample +
    trace span; persistent-cache traffic becomes hit/miss counters.
    Everything is host-side listener work — no engine, no device sync."""
    import jax.numpy as jnp

    from deepspeed_tpu.telemetry.config import DeepSpeedTelemetryConfig
    from deepspeed_tpu.telemetry.manager import TelemetryManager

    jax.config.update("jax_compilation_cache_dir", str(tmp_path / "cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    manager = TelemetryManager(DeepSpeedTelemetryConfig(
        {"telemetry": {"enabled": True, "run_dir": str(tmp_path / "run"),
                       "trace": True}}), rank=0)
    install_compile_telemetry(manager)
    try:
        fn = jax.jit(lambda x: jnp.sin(x) * jnp.float32(41.5))
        fn(jnp.ones((33, 5))).block_until_ready()
        assert manager.registry.counter("compile/cache_miss").value >= 1
        assert manager.registry.counter("compile/programs").value >= 1
        # same lowered program, fresh executable cache -> persistent hit
        jax.clear_caches()
        fn = jax.jit(lambda x: jnp.sin(x) * jnp.float32(41.5))
        fn(jnp.ones((33, 5))).block_until_ready()
        assert manager.registry.counter("compile/cache_hit").value >= 1
    finally:
        uninstall_compile_telemetry(manager)
        manager.close()
    events = [json.loads(l) for l in
              open(tmp_path / "run" / "events-rank0.jsonl")]
    compiles = [e for e in events if e["type"] == "compile"]
    assert compiles and all(
        e["data"]["duration_secs"] > 0 for e in compiles)
    trace = (tmp_path / "run" / "trace-rank0.json").read_text()
    assert '"compile"' in trace

    # unsubscribed: further compiles must not increment this manager
    before = manager.registry.counter("compile/programs").value
    jax.jit(lambda x: x - jnp.float32(17.25))(
        jnp.ones((7, 3))).block_until_ready()
    assert manager.registry.counter("compile/programs").value == before


def test_compile_stats_collector(cache_knobs, tmp_path):
    jax.config.update("jax_compilation_cache_dir", str(tmp_path / "c"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    import jax.numpy as jnp

    stats = CompileStats()
    jax.jit(lambda x: jnp.cos(x) + jnp.float32(3.125))(
        jnp.ones((11, 9))).block_until_ready()
    stats.close()
    d = stats.as_dict()
    assert d["compile_cache_misses"] >= 1
    assert d["compile_seconds_cold"] > 0
    assert d["compile_programs"] >= 1
