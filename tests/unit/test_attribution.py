"""Step-time attribution tests (``profiling/attribution.py`` +
``profiling/doctor.py`` + the DSO705 ratchet + the report/bench
surfaces): the phase model on hand-built summaries, the reconciliation
invariant (phases sum to the measured p50, signed residual), the live
engine receipt + gauges, the offline doctor's per-rank verdict and
straggler explanation on fabricated two-rank artifacts, and the CLI
ratchet tripping on a drifted budget fixture."""

import json
import os

import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.profiling import attribution as attr
from deepspeed_tpu.profiling import doctor as doctor_mod
from deepspeed_tpu.profiling.overlap import (KIND_COLLECTIVE, KIND_HOST,
                                             KIND_P2P)
from deepspeed_tpu.telemetry import report as report_mod
from deepspeed_tpu.tools.dslint import programs as dsp
from deepspeed_tpu.tools.dslint.cli import main as dslint_main

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 16


def _summary(compute=1.0, coll=0.2, host=0.3, p2p=0.0, cp=0.8):
    return {"compute_seconds": compute, "critical_path_seconds": cp,
            "exposed_by_kind": {KIND_COLLECTIVE: coll, KIND_HOST: host,
                                KIND_P2P: p2p}}


# ------------------------------------------------------------ the model
def test_program_budget_phases():
    b = attr.program_budget(_summary(compute=1.0, coll=0.2, host=0.3,
                                     p2p=0.1))
    assert b[attr.PHASE_COMPUTE] == 1.0
    assert b[attr.PHASE_COLLECTIVE] == pytest.approx(0.3)  # coll + p2p
    assert b[attr.PHASE_HOST] == 0.3
    assert b["predicted_seconds"] == pytest.approx(1.6)
    assert attr.program_budget(None) is None


def test_program_budget_falls_back_to_nodes_for_old_summaries():
    """Pre-round-13 recorded summaries carry no exposed_by_kind: the
    per-node list (seconds - hidden_seconds) stands in."""
    legacy = {"compute_seconds": 1.0, "critical_path_seconds": 1.0,
              "nodes": [
                  {"kind": KIND_COLLECTIVE, "seconds": 0.5,
                   "hidden_seconds": 0.1},
                  {"kind": KIND_HOST, "seconds": 0.2,
                   "hidden_seconds": 0.0}]}
    b = attr.program_budget(legacy)
    assert b[attr.PHASE_COLLECTIVE] == pytest.approx(0.4)
    assert b[attr.PHASE_HOST] == pytest.approx(0.2)


def test_step_budget_prefers_fused_and_weights_stepwise():
    fused = {"train_step": {"overlap": _summary(compute=2.0)},
             "fwd_bwd": {"overlap": _summary(compute=1.0)}}
    b = attr.step_budget(fused, grad_accumulation_steps=4,
                         driver_seconds=0.5)
    assert b["program"] == "train_step"
    assert b["phases"][attr.PHASE_COMPUTE] == 2.0
    assert b["phases"][attr.PHASE_DRIVER] == 0.5

    stepwise = {"fwd_bwd": {"overlap": _summary(compute=1.0, coll=0.1,
                                                host=0.0)},
                "accum": {"overlap": _summary(compute=0.5, coll=0.0,
                                              host=0.0)},
                "apply_update": {"overlap": _summary(compute=0.25,
                                                     coll=0.0,
                                                     host=2.0)}}
    b = attr.step_budget(stepwise, grad_accumulation_steps=4)
    assert b["program"] == "stepwise"
    # fwd_bwd x4 + accum x3 + apply x1
    assert b["phases"][attr.PHASE_COMPUTE] == pytest.approx(
        4 * 1.0 + 3 * 0.5 + 0.25)
    assert b["phases"][attr.PHASE_COLLECTIVE] == pytest.approx(0.4)
    assert b["phases"][attr.PHASE_HOST] == pytest.approx(2.0)
    assert attr.step_budget({}, 1) is None


def test_reconcile_phases_sum_to_measured_with_signed_residual():
    budget = attr.step_budget({"train_step": {"overlap": _summary(
        compute=1.0, coll=0.2, host=0.3)}}, driver_seconds=0.1)
    rec = attr.reconcile(budget, 2.0)
    assert rec["measured_step_seconds"] == 2.0
    assert sum(rec["phases"].values()) == pytest.approx(2.0)
    assert rec["phases"][attr.PHASE_UNEXPLAINED] == pytest.approx(0.4)
    assert rec["step_unexplained_fraction"] == pytest.approx(0.2)
    # over-prediction stays SIGNED: the residual goes negative, never
    # silently clamped (that drift is what DSO705 catches)
    over = attr.reconcile(budget, 1.0)
    assert over["phases"][attr.PHASE_UNEXPLAINED] == pytest.approx(-0.6)
    assert over["step_unexplained_fraction"] == pytest.approx(-0.6)
    # no measured side yet: predicted-only record, Nones explicit
    dry = attr.reconcile(budget, None)
    assert dry["measured_step_seconds"] is None
    assert dry["step_unexplained_fraction"] is None
    assert dry["phases"][attr.PHASE_UNEXPLAINED] is None


def test_median_of_window_shrugs_one_outlier():
    assert attr.median_of_window([0.002, 0.0021, 0.0019, 0.002, 30.0]) \
        == pytest.approx(0.002)
    assert attr.median_of_window([0.0, None, 0.0]) is None
    assert attr.median_of_window([1.0, 5.0, 9.0], window=2) == 7.0


def test_straggler_explanation_names_the_phase():
    def rank(measured, driver, unexplained):
        return {"measured_step_seconds": measured,
                "phases": {attr.PHASE_DRIVER: driver,
                           attr.PHASE_UNEXPLAINED: unexplained}}

    # slow rank whose extra time is device-side (unexplained)
    ranks = {"rank0": rank(1.0, 0.1, 0.2), "rank1": rank(1.0, 0.1, 0.2),
             "rank2": rank(3.0, 0.1, 2.2)}
    ex = attr.straggler_explanation(ranks)
    assert ex["slowest_rank"] == "rank2"
    assert ex["attributed_phase"] == attr.PHASE_UNEXPLAINED
    assert ex["extra_seconds"] == pytest.approx(2.0)
    # slow rank whose extra time is a slow input pipeline (driver)
    ranks["rank2"] = rank(3.0, 2.1, 0.2)
    assert attr.straggler_explanation(ranks)["attributed_phase"] \
        == attr.PHASE_DRIVER
    assert attr.straggler_explanation({"rank0": rank(1, 0, 0)}) is None


def test_flops_cross_check_flags_2x_disagreement():
    budget = {"phases": {attr.PHASE_COMPUTE: 1.0}}
    peak = 100.0e12
    ok = attr.flops_cross_check(budget, model_flops=60e12,
                                peak_flops_per_sec=peak)
    assert ok["flops_compute_seconds"] == pytest.approx(0.6)
    assert not ok["disagrees"]
    bad = attr.flops_cross_check(budget, model_flops=10e12,
                                 peak_flops_per_sec=peak)
    assert bad["ratio"] == pytest.approx(10.0)
    assert bad["disagrees"]
    # zero-compute sides must stay strict-JSON (None, never inf): one
    # model at zero = maximal disagreement, both at zero = agreement
    zero = attr.flops_cross_check({"phases": {attr.PHASE_COMPUTE: 0.0}},
                                  model_flops=10e12,
                                  peak_flops_per_sec=peak)
    assert zero["ratio"] is None and zero["disagrees"]
    json.dumps(zero)  # strict-JSON serializable
    both = attr.flops_cross_check({"phases": {attr.PHASE_COMPUTE: 0.0}},
                                  model_flops=0,
                                  peak_flops_per_sec=peak)
    assert both["ratio"] == 1.0 and not both["disagrees"]


# --------------------------------------------------- live engine receipt
def _engine(cpu_devices, run_dir, **profiling):
    cfg = base_config(
        steps_per_print=1,
        telemetry={"enabled": True, "run_dir": str(run_dir)},
        profiling=dict({"comm_ledger": True, "memory_ledger": True},
                       **profiling))
    mesh = make_mesh({"data": 4}, devices=cpu_devices[:4])
    engine, *_ = deepspeed.initialize(model=SimpleModel(HIDDEN, nlayers=2),
                                      config=cfg, mesh=mesh)
    return engine


def test_engine_attribution_receipt_reconciles(cpu_devices, tmp_path):
    engine = _engine(cpu_devices, tmp_path / "run")
    for b in random_batches(4, 16, HIDDEN, seed=0):
        engine.train_batch(iter([b]))
    rec = engine.attribution_receipt()
    assert rec["program"] == "train_step"
    assert rec["measured_step_seconds"] > 0
    assert sum(rec["phases"].values()) == pytest.approx(
        rec["measured_step_seconds"])
    assert rec["phases"][attr.PHASE_DRIVER] > 0  # fused path recorded it
    assert rec["predicted_step_seconds"] == pytest.approx(
        sum(v for p, v in rec["phases"].items()
            if p != attr.PHASE_UNEXPLAINED))
    # bench receipt fields are schema-registered and gate-covered
    from deepspeed_tpu.tools.bench_schema import (threshold_for,
                                                  validate_record)

    row = {"predicted_step_seconds": rec["predicted_step_seconds"],
           "step_unexplained_fraction": rec["step_unexplained_fraction"],
           "leg_zero2_predicted_step_seconds": 0.001,
           "leg_zero2_step_unexplained_fraction": 0.9,
           "offload_gpt2_large_predicted_step_seconds": 0.001,
           "offload_gpt2_large_step_unexplained_fraction": 0.9}
    assert validate_record(row) == []
    assert threshold_for("predicted_step_seconds") == ("lower", 0.25)
    assert threshold_for("leg_zero2_step_unexplained_fraction") \
        == ("zero", 0.25)
    engine.close()


def test_unexplained_fraction_gates_on_magnitude():
    """The fraction is SIGNED with optimum 0: bench_diff's 'zero'
    direction gates |new| vs |old| with an absolute band — moving
    toward 0 is an improvement even across the sign flip, and a worse
    over-prediction regresses despite being 'lower'."""
    from deepspeed_tpu.tools.bench_diff import diff_records

    def status(old, new):
        rows = diff_records({"step_unexplained_fraction": old},
                            {"step_unexplained_fraction": new})
        return rows[0]["status"]

    assert status(-0.10, 0.0) == "ok"        # toward 0: never regressed
    assert status(0.80, 0.30) == "improved"
    assert status(-0.10, -0.50) == "regressed"  # worse over-prediction
    assert status(0.30, 0.80) == "regressed"
    assert status(0.80, 0.85) == "ok"        # within the absolute band


def test_engine_flops_cross_check_rides_the_receipt(cpu_devices,
                                                    tmp_path):
    """The idle flops profiler wired in as the independent compute
    cross-check: once it has profiled, the attribution receipt reports
    both compute estimates and the disagreement verdict."""
    cfg = base_config(
        steps_per_print=1,
        telemetry={"enabled": True, "run_dir": str(tmp_path / "run")},
        profiling={"comm_ledger": True, "memory_ledger": True},
        flops_profiler={"enabled": True, "profile_step": 2})
    mesh = make_mesh({"data": 4}, devices=cpu_devices[:4])
    engine, *_ = deepspeed.initialize(model=SimpleModel(HIDDEN, nlayers=2),
                                      config=cfg, mesh=mesh)
    for b in random_batches(3, 16, HIDDEN, seed=0):
        engine.train_batch(iter([b]))
    rec = engine.attribution_receipt()
    check = rec["flops_check"]
    assert check["model_flops"] == engine.flops_profiler.profile.flops
    assert check["flops_compute_seconds"] > 0
    assert check["roofline_compute_seconds"] == pytest.approx(
        rec["phases"][attr.PHASE_COMPUTE])
    assert check["ratio"] >= 1.0 and isinstance(check["disagrees"], bool)
    engine.close()


# ------------------------------------------------------------ the doctor
def _fabricate_sibling(run_dir, rank, p50, driver):
    """A second rank's event stream: latency snapshots + one
    attribution event carrying its driver phase (what a real sibling
    engine would have written into the shared run dir)."""
    rows = []
    for i in range(3):
        rows.append({"schema_version": 1, "seq": len(rows), "rank": rank,
                     "ts": 1000.0 + i, "type": "comm", "step": i + 1,
                     "data": {"kind": "latency", "n": 3, "steps": 3,
                              "last": p50, "mean": p50, "p50": p50,
                              "p95": p50, "max": p50}})
    rows.append({"schema_version": 1, "seq": len(rows), "rank": rank,
                 "ts": 1003.0, "type": "attribution", "step": 3,
                 "data": {"program": "train_step",
                          "phases": {"compute": 0.0,
                                     "exposed_collective": 0.0,
                                     "host_stream": 0.0,
                                     "driver": driver,
                                     "unexplained": p50 - driver},
                          "predicted_step_seconds": driver,
                          "measured_step_seconds": p50,
                          "step_unexplained_fraction":
                              (p50 - driver) / p50}})
    with open(os.path.join(str(run_dir), f"events-rank{rank}.jsonl"),
              "w") as f:
        f.write("\n".join(json.dumps(r) for r in rows) + "\n")


def test_doctor_verdict_and_straggler_explanation(cpu_devices, tmp_path,
                                                  capsys):
    run_dir = tmp_path / "run"
    engine = _engine(cpu_devices, run_dir, program_dump=True)
    for b in random_batches(4, 16, HIDDEN, seed=0):
        engine.train_batch(iter([b]))
    engine.close()
    # a fabricated slow sibling: device-side stall (driver tiny), so
    # the doctor must attribute its extra time to `unexplained`
    _fabricate_sibling(run_dir, 1, p50=5.0, driver=1e-4)
    verdict = doctor_mod.doctor_run_dir(run_dir)
    assert "train_step" in verdict["programs"]
    ranks = verdict["ranks"]
    assert set(ranks) == {"rank0", "rank1"}
    for rec in ranks.values():
        assert sum(rec["phases"].values()) == pytest.approx(
            rec["measured_step_seconds"])
    straggler = verdict["straggler"]
    assert straggler["slowest_rank"] == "rank1"
    assert straggler["attributed_phase"] == attr.PHASE_UNEXPLAINED
    # CLI: human verdict exit 0, --json parseable, prints the verdict
    assert doctor_mod.main([str(run_dir)]) == 0
    out = capsys.readouterr().out
    assert "straggler: rank rank1" in out
    assert "unexplained" in out
    assert doctor_mod.main([str(run_dir), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["straggler"]["slowest_rank"] == "rank1"
    # report integration: --doctor section + --json doctor key
    assert report_mod.main(["report", str(run_dir), "--doctor"]) == 0
    assert "step-time attribution (doctor):" in capsys.readouterr().out
    assert report_mod.main(["report", str(run_dir), "--json",
                            "--doctor"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["report_schema_version"] == 1
    assert set(doc) >= {"summary", "comm", "elastic", "events", "doctor"}
    assert doc["comm"]["measured_p50_seconds"]["rank1"] == 5.0
    assert doc["doctor"]["straggler"]["attributed_phase"] \
        == attr.PHASE_UNEXPLAINED


def test_doctor_exit_2_without_artifacts(tmp_path, capsys):
    os.makedirs(tmp_path / "empty", exist_ok=True)
    assert doctor_mod.main([str(tmp_path / "empty")]) == 2
    assert "cannot load run artifacts" in capsys.readouterr().err
    # report --doctor degrades to an explicit unavailable line
    from deepspeed_tpu.telemetry import EventLog

    log = EventLog(tmp_path / "empty", rank=0)
    log.emit("run_start", step=0, world_size=1)
    log.close()
    assert report_mod.main(["report", str(tmp_path / "empty"),
                            "--doctor"]) == 0
    assert "unavailable:" in capsys.readouterr().out


# ------------------------------------------------- DSO705 metric ratchet
_HLO = (
    "HloModule fixture, is_scheduled=true\n\n"
    "ENTRY %main.1 (p0: f32[4096,4096]) -> f32[4096,4096] {\n"
    "  %p0 = f32[4096,4096]{1,0} parameter(0)\n"
    "  ROOT %dot.1 = f32[4096,4096]{1,0} dot(f32[4096,4096]{1,0} %p0, "
    "f32[4096,4096]{1,0} %p0), lhs_contracting_dims={1}, "
    "rhs_contracting_dims={0}\n"
    "}\n")


def _fixture_run_dir(tmp_path, declared_bytes):
    progdir = tmp_path / "programs"
    os.makedirs(progdir, exist_ok=True)
    artifact = dsp.ProgramArtifact(
        name="train_step", hlo=_HLO, mesh_axes={"data": 1},
        host_state_wire_bytes=declared_bytes,
        host_stream_schedule={"overlap": False},
        device_kind="TPU v5e")
    (progdir / "train_step.hlo").write_text(_HLO)
    (progdir / "train_step.json").write_text(
        json.dumps(artifact.sidecar()))
    return tmp_path


def _baseline(path, metrics):
    path.write_text(json.dumps({"schema_version": 1, "violations": {},
                                "metrics": metrics}))
    return str(path)


def test_dso705_trips_on_drifted_declared_budget(tmp_path):
    """The acceptance fixture: record the budget, drift the DECLARED
    host stream (the budget's biggest term), and the metrics ratchet
    must fail the baselined run while the faithful run stays exit 0."""
    run = _fixture_run_dir(tmp_path / "run", declared_bytes=140_000_000)
    artifacts = dsp.load_run_artifacts(str(run))
    recorded = dsp.attribution_metrics(artifacts)
    key = dsp.predicted_step_metric_key("train_step")
    assert recorded[key] > 0
    baseline = _baseline(tmp_path / "base.json", recorded)
    # faithful: bare --programs clean AND the ratcheted run exit 0
    assert dslint_main(["--programs", str(run), "--select", "DSO705",
                        "--baseline", baseline]) == 0
    # drift the declaration: 4x the host stream -> predicted step far
    # outside the ±25% band -> DSO705, baseline cannot absolve it
    drifted = _fixture_run_dir(tmp_path / "run2",
                               declared_bytes=560_000_000)
    rc = dslint_main(["--programs", str(drifted), "--select", "DSO705",
                      "--baseline", baseline])
    assert rc == 1
    diags = dsp.check_attribution_ratchet(
        [(str(drifted), dsp.load_run_artifacts(str(drifted)))],
        {k: float(v) for k, v in recorded.items()})
    assert len(diags) == 1 and diags[0].rule_id == "DSO705"
    assert "predicted_step_seconds drifted" in diags[0].message


def test_dso705_unexplained_ceiling_needs_measured_evidence(tmp_path):
    """The measured arm: with latency files in the run dir, a
    reconciled unexplained fraction above the recorded ceiling trips;
    without measured evidence the ceiling is never checked."""
    from deepspeed_tpu.profiling.comm import publish_rank_latency

    run = _fixture_run_dir(tmp_path / "run", declared_bytes=140_000_000)
    artifacts = dsp.load_run_artifacts(str(run))
    predicted = dsp.attribution_metrics(artifacts)[
        dsp.predicted_step_metric_key("train_step")]
    ceiling = {dsp.unexplained_metric_key("train_step"): 0.10}
    # no latency files: ceiling not checkable, no finding
    assert dsp.check_attribution_ratchet(
        [(str(run), artifacts)], ceiling) == []
    # measured p50 = 100x predicted -> fraction ~0.99 >> 0.10 + margin
    publish_rank_latency(str(run), 0, {"n": 3, "steps": 3,
                                       "last": predicted * 100,
                                       "mean": predicted * 100,
                                       "p50": predicted * 100,
                                       "p95": predicted * 100,
                                       "max": predicted * 100}, step=3)
    diags = dsp.check_attribution_ratchet(
        [(str(run), dsp.load_run_artifacts(str(run)))], ceiling)
    assert len(diags) == 1 and diags[0].rule_id == "DSO705"
    assert "step_unexplained_fraction" in diags[0].message
    # recording metrics with measured evidence present captures the
    # fraction key too (what --update-baseline writes)
    recorded = dsp.attribution_metrics(
        dsp.load_run_artifacts(str(run)), run_dir=str(run))
    assert dsp.unexplained_metric_key("train_step") in recorded
    assert recorded[dsp.unexplained_metric_key("train_step")] \
        == pytest.approx(0.99, abs=0.01)
