"""Driver-bench JSON schema (``tools/bench_schema.py``).

The standing ROADMAP rule — every README/PERF headline quotes a driver
artifact — needs the artifact's fields to be stable; this suite pins
the registry against the real round-5 artifact and the round-6 fields
(reduced-precision ``host_state_dtype`` / ``host_state_bytes_per_step``).
"""

import json
import os

from deepspeed_tpu.tools.bench_schema import field_type, validate_record

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def test_round5_artifact_validates():
    path = os.path.join(REPO, "BENCH_r05.json")
    with open(path) as f:
        record = json.load(f)["parsed"]
    assert validate_record(record) == []


def test_round6_reduced_precision_fields():
    """The new offload rows must carry auditable wire-bytes receipts."""
    record = {
        "offload_gpt2_large_ms_per_step": 1292.0,
        "offload_gpt2_large_params_b": 0.77,
        "offload_gpt2_large_host_state_dtype": "fp32",
        "offload_gpt2_large_host_state_bytes_per_step": 18598986752,
        "offload_gpt2_large_bf16_ms_per_step": 880.0,
        "offload_gpt2_large_bf16_params_b": 0.77,
        "offload_gpt2_large_bf16_host_state_dtype": "bf16",
        "offload_gpt2_large_bf16_host_state_bytes_per_step": 9299493376,
        "offload_gpt2_xl_host_groups": 2,
        "sparse_attn_repeats": 3,
    }
    assert validate_record(record) == []
    # the dtype/bytes pattern applies to ANY row name, not a fixed list
    assert field_type("offload_gpt2_27b_host_state_bytes_per_step")
    assert field_type("offload_gpt2_27b_host_state_dtype") is str


def test_round12_overlap_row_validates_and_gates():
    """The overlap-mode record (``bench_offload_capacity.py overlap``):
    the new ``gpt2_large_overlap`` row rides the existing
    ``offload_<row>_<field>`` pattern, so its ms/step and exposed-wire
    receipts are schema-legal AND regression-gated by ``bench_diff``
    with the standard offload thresholds — a future change that slows
    the overlapped row or re-grows its exposure trips CI."""
    from deepspeed_tpu.tools.bench_schema import threshold_for

    record = {
        "metric": "offload_overlap",
        "device": "cpu",
        "offload_gpt2_large_ms_per_step": 660.0,
        "offload_gpt2_large_exposed_wire_seconds": 0.66,
        "offload_gpt2_large_overlap_fraction": 0.0,
        "offload_gpt2_large_overlap_ms_per_step": 480.0,
        "offload_gpt2_large_overlap_exposed_wire_seconds": 0.012,
        "offload_gpt2_large_overlap_overlap_fraction": 0.98,
        "offload_gpt2_large_overlap_host_state_bytes_per_step":
            9299493376,
        "offload_gpt2_large_overlap_note": "dryrun",
    }
    assert validate_record(record) == []
    # the bench_diff gate rows the satellite asked for
    assert threshold_for("offload_gpt2_large_overlap_ms_per_step") == (
        "lower", 0.10)
    assert threshold_for(
        "offload_gpt2_large_overlap_exposed_wire_seconds") == (
        "lower", 0.25)
    assert threshold_for(
        "offload_gpt2_large_overlap_overlap_fraction") == ("higher", 0.10)


def test_round15_integrity_leg_fields_validate_and_gate():
    """The multichip integrity leg's receipts: which rank the
    fingerprint consensus indicted, the verdict, and the resized fleet
    — plus the fleet-wide ``integrity_violations`` pinned at 0 by
    ``bench_diff`` (any seeded fault the consensus misses is a gated
    regression)."""
    from deepspeed_tpu.tools.bench_schema import threshold_for

    record = {
        "metric": "dryrun_multichip",
        "leg_integrity_status": "ok",
        "leg_integrity_evicted_rank": 2,
        "leg_integrity_verdict": "outlier",
        "leg_integrity_resized_to": 2,
        "leg_integrity_resume_step": 1,
        "integrity_violations": 0,
    }
    assert validate_record(record) == []
    assert threshold_for("integrity_violations") == ("lower", 0.0)
    # leg-pattern fields stay informational unless listed; the verdict
    # and rank are identity fields, never gated numerically
    assert field_type("leg_integrity_verdict") is str
    assert validate_record({"leg_integrity_evicted_rank": "two"}) != []


def test_round18_serving_resilience_fields_validate_and_gate():
    """The self-healing serving receipts: the serving_chaos leg's
    exactly-once requeue counts, plus the top-level requeue/shed/
    recovery fields ``bench_serving`` quotes.  An undetected seeded
    fault (``leg_*_integrity_violations``) is a gated regression; the
    raw counters stay informational — they scale with how much chaos
    the bench injects, not with code quality."""
    from deepspeed_tpu.tools.bench_schema import threshold_for

    record = {
        "metric": "dryrun_multichip",
        "leg_serving_chaos_status": "ok",
        "leg_serving_chaos_evicted_rank": 1,
        "leg_serving_chaos_requeued_requests": 3,
        "leg_serving_chaos_completed_requests": 9,
        "leg_serving_chaos_parity_mismatches": 0,
        "leg_serving_chaos_integrity_violations": 0,
        "leg_serving_chaos_recovery_latency_seconds": 0.011,
        "serving_requeued_requests": 3,
        "serving_shed_requests": 2,
        "serving_deadline_expired": 0,
        "serving_recovery_latency_seconds": 0.007,
    }
    assert validate_record(record) == []
    assert threshold_for(
        "leg_serving_chaos_integrity_violations") == ("lower", 0.0)
    assert threshold_for(
        "leg_serving_chaos_parity_mismatches") == ("lower", 0.0)
    # counters are informational: never gated numerically
    assert threshold_for("serving_requeued_requests") == (None, None)
    assert threshold_for(
        "leg_serving_chaos_requeued_requests") == (None, None)
    assert validate_record(
        {"serving_recovery_latency_seconds": "slow"}) != []


def test_unknown_and_mistyped_fields_are_flagged():
    probs = validate_record({
        "offload_gpt2_large_host_state_bytes_per_step": "lots",
        "made_up_field": 1,
        "mfu": True,  # bool smuggled into a metric
    })
    assert len(probs) == 3
    assert any("made_up_field" in p for p in probs)


def test_failure_strings_allowed_per_row():
    assert validate_record({
        "offload_xl_exc": "xl run failed (try 2): ...",
        "seq512_exc": "secondary run failed (try 1): ...",
        "offload_gpt2_large_bf16_error": "non-finite loss nan",
    }) == []
