"""Compile-only stage-3 gather-scale guard at gpt2-xl geometry (round
20, CI-pinned — the ``test_compile_scale_27b.py`` pattern).

The reference ZeRO-3 gathers parameters LAYER BY LAYER with one
collective per module (``stage3.py`` fetch/release per submodule); a
naive port would emit one ``all_gather`` per parameter LEAF — ~770 ops
at gpt2-xl — and the op count (trace time, scheduling freedom, ICI
launch overhead) would grow linearly with depth.  The repo's stage 3
instead gathers **byte-sized groups of consecutive buckets**
(``BucketPlan.ag_groups``, ``allgather_bucket_size`` elements per
group): the collective count is set by parameter BYTES over the group
size, never by layer or leaf count, and backward rematerializes the
same groups.  This file pins that program shape where it can regress —
the lowered step text: the ``all_gather`` op count stays a small
multiple of ``ag_buckets`` (forward + remat'd backward) and far below
the leaf count, the gathers-per-group density is CONSTANT in depth,
and gpt2-xl lowers in seconds.  Abstract avals only (``aot_plan``
plan mode) — no xl-sized buffer ever materializes, so CI boxes run it.
"""

import re
import time

import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.profiling.capacity import GPT2_PRESETS, gpt2_param_count

SEQ = 256
DP = 4
# small groups so gpt2-xl yields a two-digit group count (the density
# statistics below need G well above 1 and well below the leaf count)
REDUCE_BUCKET = 50_000_000
ALLGATHER_BUCKET = 100_000_000


def _lower_step(num_layers, cpu_devices):
    """Lower (never compile) the fused stage-3 train step for a gpt2-xl
    width model of ``num_layers`` layers; returns (ag_groups, leaf_count,
    all_gather op count, text length, lower seconds)."""
    from deepspeed_tpu.models import GPT2Config, GPT2LMHeadTPU

    xl = GPT2_PRESETS["gpt2-xl"]
    cfg = GPT2Config(hidden_size=xl["hidden_size"], num_layers=num_layers,
                     num_heads=xl["num_heads"], max_position_embeddings=SEQ,
                     embd_dropout=0.0, attn_dropout=0.0, resid_dropout=0.0,
                     remat=True, loss_chunk=SEQ)
    config = {
        "train_batch_size": DP,
        "steps_per_print": int(1e9),
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "zero_optimization": {
            "stage": 3,
            "overlap_comm": "auto",
            "reduce_bucket_size": REDUCE_BUCKET,
            "allgather_bucket_size": ALLGATHER_BUCKET,
        },
    }
    mesh = make_mesh({"data": DP}, devices=cpu_devices[:DP])
    engine, *_ = deepspeed.initialize(model=GPT2LMHeadTPU(cfg),
                                      config=config, mesh=mesh,
                                      aot_plan=True)
    try:
        sched = engine.collective_schedule()
        assert sched["param_gathers"] and sched["overlap"]
        batch = {"input_ids": np.zeros((DP, SEQ), np.int32)}
        t0 = time.perf_counter()
        lowered = engine.aot_lower_train_step(batch)
        secs = time.perf_counter() - t0
        text = lowered.as_text()
        gathers = len(re.findall(r'"?stablehlo\.all_gather"?', text))
        leaves = len(engine.flat.bucket_plan.sizes)
        return sched["ag_buckets"], leaves, gathers, len(text), secs
    finally:
        engine.close()


def test_xl_step_gathers_are_o_groups_not_o_leaves(cpu_devices):
    xl_layers = GPT2_PRESETS["gpt2-xl"]["num_layers"]
    groups, leaves, gathers, _, secs = _lower_step(xl_layers, cpu_devices)
    params = gpt2_param_count(GPT2_PRESETS["gpt2-xl"]["hidden_size"],
                              xl_layers, max_position_embeddings=SEQ)
    # the real xl geometry, not a toy: 1.5B+ params, a ~770-leaf tree,
    # and a two-digit byte-determined group count
    assert params > 1_500_000_000
    assert leaves > 500
    assert 10 <= groups < leaves // 10
    # THE claim: collective count tracks GROUPS (forward gather + the
    # remat'd backward re-gather ≈ 2 per group, small constant slack for
    # epilogue all-gathers of the updated master), never LEAVES — the
    # per-leaf reference emission would put ~770+ here
    assert groups <= gathers <= 4 * groups + 8, (
        f"stage-3 step lowered {gathers} all_gather ops for {groups} "
        f"gather groups ({leaves} leaves) — the bucketed O(bytes) "
        "gather structure regressed toward per-leaf collectives")
    assert gathers < leaves // 4
    # compile-wall guard: lowering the unrolled 48-layer step is
    # seconds, not minutes
    assert secs < 120, f"gpt2-xl stage-3 lowering took {secs:.1f}s"


def test_gathers_per_group_constant_in_depth(cpu_devices):
    """Depth scaling: 4x the layers means ~4x the bytes, hence ~4x the
    groups — but the gathers-PER-GROUP density must stay constant (the
    O(1)-in-layers property; a per-layer emission would scale density
    with depth)."""
    g_s, _, ag_s, text_s, _ = _lower_step(12, cpu_devices)
    g_d, _, ag_d, text_d, _ = _lower_step(
        GPT2_PRESETS["gpt2-xl"]["num_layers"], cpu_devices)
    assert g_d >= 3 * g_s >= 3
    dens_s, dens_d = ag_s / g_s, ag_d / g_d
    assert dens_d <= dens_s + 1.0, (
        f"gather density grew with depth: {dens_s:.2f} ops/group at 12 "
        f"layers vs {dens_d:.2f} at 48 — gather emission is no longer "
        "O(1) in layers")
    # program text itself is O(layers) here (the model body is an
    # unrolled python loop) — sanity-bound it to linear, not quadratic
    assert text_d <= 6 * text_s
