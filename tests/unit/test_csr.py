"""CSR row-sparse gradients (reference ``tests/unit/test_csr.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.runtime.csr_tensor import (CSRTensor, csr_allreduce,
                                              csr_allreduce_reference)

from .simple_model import SimpleModel, base_config


def _sparse_dense(rows=64, cols=8, touched=(3, 17, 42), seed=0):
    rng = np.random.default_rng(seed)
    d = np.zeros((rows, cols), np.float32)
    for r in touched:
        d[r] = rng.normal(size=cols)
    return d


def test_roundtrip():
    d = _sparse_dense()
    csr = CSRTensor.from_dense(jnp.asarray(d), max_rows=8)
    assert csr.nnz == 8
    np.testing.assert_allclose(np.asarray(csr.to_dense()), d, rtol=1e-6)
    assert csr.sparsity() == 1.0 - 8 / 64


def test_roundtrip_full_budget():
    d = _sparse_dense()
    csr = CSRTensor.from_dense(jnp.asarray(d))
    np.testing.assert_allclose(np.asarray(csr.to_dense()), d, rtol=1e-6)


def test_duplicate_indices_add():
    vals = jnp.ones((2, 4))
    csr = CSRTensor(indices=jnp.asarray([5, 5], jnp.int32), values=vals,
                    dense_shape=(8, 4))
    dense = np.asarray(csr.to_dense())
    np.testing.assert_allclose(dense[5], 2.0 * np.ones(4))


def test_csr_allreduce_matches_dense(cpu_devices):
    """Padded all_gather exchange inside shard_map == dense sum (the
    reference's csr_allreduce contract, engine.py:1203-1241)."""
    world = 8
    mesh = make_mesh({"data": world}, devices=cpu_devices[:world])
    csrs = []
    host = []
    for r in range(world):
        d = _sparse_dense(touched=(r, 2 * r + 1, 50), seed=r)
        host.append(CSRTensor.from_dense(jnp.asarray(d), max_rows=4))
        csrs.append(d)
    idx = jnp.stack([c.indices for c in host])
    val = jnp.stack([c.values for c in host])

    def body(i, v):
        csr = CSRTensor(indices=i[0], values=v[0], dense_shape=(64, 8))
        return csr_allreduce(csr, "data")[None]

    out = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=P("data"), axis_names={"data"}, check_vma=False))(idx, val)
    ref = csr_allreduce_reference(host)
    for r in range(world):
        np.testing.assert_allclose(np.asarray(out[r]), ref, rtol=1e-5)


def test_engine_sparse_gradients_wiring(cpu_devices):
    mesh = make_mesh({"data": 8}, devices=cpu_devices[:8])
    config = base_config(sparse_gradients=True)
    engine, *_ = deepspeed.initialize(model=SimpleModel(16, nlayers=2),
                                      config=config, mesh=mesh)
    assert engine.sparse_gradients_enabled()

    config2 = base_config(sparse_gradients=True,
                          zero_optimization={"stage": 2})
    with pytest.raises(AssertionError, match="not supported with ZeRO"):
        deepspeed.initialize(model=SimpleModel(16, nlayers=2),
                             config=config2, mesh=mesh)


def test_model_declares_sparse_paths():
    from deepspeed_tpu.models import (BertConfig, BertForPreTrainingTPU,
                                      GPT2Config, GPT2LMHeadTPU)

    bert = BertForPreTrainingTPU(BertConfig(vocab_size=64, hidden_size=16,
                                            num_hidden_layers=1,
                                            num_attention_heads=2,
                                            intermediate_size=32,
                                            max_position_embeddings=16))
    assert "bert/embeddings/word" in bert.sparse_gradient_paths()
    gpt = GPT2LMHeadTPU(GPT2Config(vocab_size=64, hidden_size=16,
                                   num_layers=1, num_heads=2,
                                   max_position_embeddings=16))
    assert "wte" in gpt.sparse_gradient_paths()
