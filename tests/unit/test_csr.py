"""CSR row-sparse gradients (reference ``tests/unit/test_csr.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.runtime.csr_tensor import (CSRTensor, csr_allreduce,
                                              csr_allreduce_reference)

from .simple_model import SimpleModel, base_config


def _sparse_dense(rows=64, cols=8, touched=(3, 17, 42), seed=0):
    rng = np.random.default_rng(seed)
    d = np.zeros((rows, cols), np.float32)
    for r in touched:
        d[r] = rng.normal(size=cols)
    return d


def test_roundtrip():
    d = _sparse_dense()
    csr = CSRTensor.from_dense(jnp.asarray(d), max_rows=8)
    assert csr.nnz == 8
    np.testing.assert_allclose(np.asarray(csr.to_dense()), d, rtol=1e-6)
    assert csr.sparsity() == 1.0 - 8 / 64


def test_roundtrip_full_budget():
    d = _sparse_dense()
    csr = CSRTensor.from_dense(jnp.asarray(d))
    np.testing.assert_allclose(np.asarray(csr.to_dense()), d, rtol=1e-6)


def test_duplicate_indices_add():
    vals = jnp.ones((2, 4))
    csr = CSRTensor(indices=jnp.asarray([5, 5], jnp.int32), values=vals,
                    dense_shape=(8, 4))
    dense = np.asarray(csr.to_dense())
    np.testing.assert_allclose(dense[5], 2.0 * np.ones(4))


def test_csr_allreduce_matches_dense(cpu_devices):
    """Padded all_gather exchange inside shard_map == dense sum (the
    reference's csr_allreduce contract, engine.py:1203-1241)."""
    world = 8
    mesh = make_mesh({"data": world}, devices=cpu_devices[:world])
    csrs = []
    host = []
    for r in range(world):
        d = _sparse_dense(touched=(r, 2 * r + 1, 50), seed=r)
        host.append(CSRTensor.from_dense(jnp.asarray(d), max_rows=4))
        csrs.append(d)
    idx = jnp.stack([c.indices for c in host])
    val = jnp.stack([c.values for c in host])

    def body(i, v):
        csr = CSRTensor(indices=i[0], values=v[0], dense_shape=(64, 8))
        return csr_allreduce(csr, "data")[None]

    out = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=P("data"), axis_names={"data"}, check_vma=False))(idx, val)
    ref = csr_allreduce_reference(host)
    for r in range(world):
        np.testing.assert_allclose(np.asarray(out[r]), ref, rtol=1e-5)


def test_engine_sparse_gradients_wiring(cpu_devices):
    mesh = make_mesh({"data": 8}, devices=cpu_devices[:8])
    config = base_config(sparse_gradients=True)
    engine, *_ = deepspeed.initialize(model=SimpleModel(16, nlayers=2),
                                      config=config, mesh=mesh)
    assert engine.sparse_gradients_enabled()

    config2 = base_config(sparse_gradients=True,
                          zero_optimization={"stage": 2})
    with pytest.raises(ValueError, match=r"sparse_gradients: true requires ZeRO stage 0"):
        deepspeed.initialize(model=SimpleModel(16, nlayers=2),
                             config=config2, mesh=mesh)


def test_model_declares_sparse_paths():
    """Tied-head leaves must NOT be declared row-sparse: the vocab
    projection's backward puts gradient mass on every row, so a CSR
    exchange would drop most of it.  Only genuinely lookup-only embeddings
    qualify."""
    from deepspeed_tpu.models import (BertConfig, BertForPreTrainingTPU,
                                      GPT2Config, GPT2LMHeadTPU)
    from deepspeed_tpu.models.bert import (BertForQuestionAnsweringTPU,
                                           BertForSequenceClassificationTPU)

    cfg = BertConfig(vocab_size=64, hidden_size=16, num_hidden_layers=1,
                     num_attention_heads=2, intermediate_size=32,
                     max_position_embeddings=16)
    # pretraining head ties decoder → word embedding grad is dense
    assert "bert/embeddings/word" not in BertForPreTrainingTPU(
        cfg).sparse_gradient_paths()
    # untied heads: the word embedding really is row-sparse
    assert "bert/embeddings/word" in BertForQuestionAnsweringTPU(
        cfg).sparse_gradient_paths()
    assert "bert/embeddings/word" in BertForSequenceClassificationTPU(
        cfg).sparse_gradient_paths()
    gpt = GPT2LMHeadTPU(GPT2Config(vocab_size=64, hidden_size=16,
                                   num_layers=1, num_heads=2,
                                   max_position_embeddings=16))
    assert "wte" not in gpt.sparse_gradient_paths()  # tied LM head


def test_from_dense_overflow_detection():
    """A budget smaller than the true support must be detectable: the
    dropped-row count comes back alongside the compressed tensor."""
    d = _sparse_dense(touched=(1, 5, 9, 13, 21))  # support = 5 rows
    csr, dropped = CSRTensor.from_dense(jnp.asarray(d), max_rows=3,
                                        return_dropped=True)
    assert int(dropped) == 2
    csr, dropped = CSRTensor.from_dense(jnp.asarray(d), max_rows=8,
                                        return_dropped=True)
    assert int(dropped) == 0
    np.testing.assert_allclose(np.asarray(csr.to_dense()), d, rtol=1e-6)


class TinyEmbModel:
    """Embedding + linear readout: the smallest model whose word-embedding
    gradient is genuinely row-sparse (only touched token rows are nonzero)."""

    VOCAB, HID, SEQ = 64, 8, 4

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        return {"emb": jax.random.normal(k1, (self.VOCAB, self.HID)) * 0.1,
                "w": jax.random.normal(k2, (self.HID,)) * 0.1}

    def sparse_gradient_paths(self):
        return ("emb",)

    def apply(self, params, batch, rng=None, train=True, **kw):
        x = jnp.take(params["emb"], batch["input_ids"], axis=0)  # [B,s,h]
        pred = x @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2)


def _emb_batches(n, b):
    rng = np.random.default_rng(0)
    out = []
    for _ in range(n):
        out.append({
            "input_ids": rng.integers(
                0, TinyEmbModel.VOCAB,
                size=(b, TinyEmbModel.SEQ)).astype(np.int32),
            "y": rng.normal(size=(b, TinyEmbModel.SEQ)).astype(np.float32),
        })
    return out


def _train_emb(cpu_devices, sparse, steps=4, dp=4):
    mesh = make_mesh({"data": dp}, devices=cpu_devices[:dp])
    config = base_config(sparse_gradients=sparse)
    engine, *_ = deepspeed.initialize(model=TinyEmbModel(), config=config,
                                      mesh=mesh)
    losses = []
    for batch in _emb_batches(steps, 8):
        losses.append(float(np.asarray(engine.train_batch(iter([batch])))))
    return losses, np.asarray(engine.state["master"])


def test_sparse_gradients_numerics_match_dense(cpu_devices, monkeypatch):
    """sparse_gradients=True must change the PROGRAM (declared embedding
    grads ride csr_allreduce with a tokens-sized nnz, not a vocab-sized
    dense exchange) while matching the dense path's numerics."""
    from deepspeed_tpu.runtime import csr_tensor

    calls = []
    real = csr_tensor.csr_allreduce

    def spy(csr, axis_name):
        calls.append((csr.nnz, csr.dense_shape))
        return real(csr, axis_name)

    monkeypatch.setattr(csr_tensor, "csr_allreduce", spy)

    losses_dense, master_dense = _train_emb(cpu_devices, sparse=False)
    assert not calls, "dense path must not touch the sparse exchange"
    losses_sparse, master_sparse = _train_emb(cpu_devices, sparse=True)

    # the traced program contained the sparse exchange, with the wire
    # budget bounded by tokens-per-local-batch (8/4 rows * 4 tokens = 8),
    # far under the 64-row dense exchange
    assert calls, "sparse path never traced csr_allreduce"
    nnz, shape = calls[0]
    assert shape == (TinyEmbModel.VOCAB, TinyEmbModel.HID)
    assert nnz == 8 < TinyEmbModel.VOCAB

    np.testing.assert_allclose(losses_sparse, losses_dense, rtol=1e-5)
    np.testing.assert_allclose(master_sparse, master_dense, rtol=1e-4,
                               atol=1e-6)


class TinyTiedModel(TinyEmbModel):
    """Readout TIES to the embedding — its grad is dense over all rows, so
    declaring it sparse is a model bug the engine must surface loudly."""

    def apply(self, params, batch, rng=None, train=True, **kw):
        x = jnp.take(params["emb"], batch["input_ids"], axis=0)  # [B,s,h]
        logits = x @ params["emb"].T  # tied head: dense grad on emb
        return jnp.mean(logits ** 2)


def test_sparse_gradients_tied_head_fails_loud(cpu_devices):
    """A declared-sparse leaf whose gradient overflows the token budget
    must poison the step with NaN (loud) instead of silently training on
    truncated gradients."""
    mesh = make_mesh({"data": 4}, devices=cpu_devices[:4])
    engine, *_ = deepspeed.initialize(model=TinyTiedModel(),
                                      config=base_config(sparse_gradients=True),
                                      mesh=mesh)
    batch = _emb_batches(1, 8)[0]
    engine.train_batch(iter([batch]))
    master = np.asarray(engine.state["master"])
    assert np.isnan(master).any(), (
        "tied-head overflow was silently dropped instead of poisoning")
