"""1-bit Adam: compressed allreduce vs host reference, warmup/compression
phases, engine integration (modeled on reference
``tests/onebitadam/test_com_reduce_host.py`` but CI-friendly — virtual
8-device mesh instead of hardcoded MPI hosts)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu as deepspeed
from deepspeed_tpu.comm.compression import (compressed_allreduce,
                                            compressed_allreduce_reference)
from deepspeed_tpu.parallel import make_mesh

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 16


def test_compressed_allreduce_vs_host_reference(cpu_devices):
    """Distinct per-rank buffers through the shard_map collective must match
    the numpy simulation bit-for-bit in structure (scales, signs, errors)."""
    world, n = 8, 8 * 64
    rng = np.random.default_rng(0)
    bufs = rng.normal(size=(world, n)).astype(np.float32)
    werrs = rng.normal(size=(world, n)).astype(np.float32) * 0.1
    serrs = rng.normal(size=(world, n // world)).astype(np.float32) * 0.1

    mesh = make_mesh({"data": world}, devices=cpu_devices[:world])

    def body(b, we, se):
        out, nwe, nse = compressed_allreduce(b[0], we[0], se[0], "data")
        return out[None], nwe[None], nse[None]

    from deepspeed_tpu.utils.compat import shard_map

    out, nwe, nse = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("data"), P("data"), P("data")),
        out_specs=(P("data"), P("data"), P("data")),
        axis_names={"data"}, check_vma=False))(bufs, werrs, serrs)

    ref_out, ref_werrs, ref_serrs = compressed_allreduce_reference(
        list(bufs), list(werrs), list(serrs))

    # every rank sees the same allreduced output
    for r in range(world):
        np.testing.assert_allclose(np.asarray(out[r]), ref_out, rtol=1e-5,
                                   atol=1e-6)
    np.testing.assert_allclose(np.asarray(nwe), np.stack(ref_werrs), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(nse), np.stack(ref_serrs), rtol=1e-4,
                               atol=1e-5)


def test_compressed_phase_matches_host_reference(cpu_devices):
    """The optimizer's actual compressed momentum sync — distinct per-rank
    local gradients through the engine's compressed program — tracks the
    numpy simulation of the same algorithm (uncompressed-mean target)."""
    config = base_config(optimizer={
        "type": "OneBitAdam", "params": {"lr": 0.0, "freeze_step": 0,
                                         "betas": (0.0, 0.999)}})
    mesh = make_mesh({"data": 8}, devices=cpu_devices[:8])
    engine, *_ = deepspeed.initialize(model=SimpleModel(HIDDEN, nlayers=2),
                                      config=config, mesh=mesh)
    batch = random_batches(1, engine.train_micro_batch_size_per_gpu() * 8,
                           HIDDEN, seed=3)[0]
    engine.train_batch(iter([batch]))
    # beta1=0 => stored momentum is the compressed consensus of the raw
    # per-rank local gradients; with zero error history the consensus is a
    # sign/scale quantization of the true mean — correlation must be high
    m = np.asarray(jax.device_get(engine.state["opt"].exp_avg)).ravel()
    # dense mean gradient via a plain Adam engine on the same batch
    config2 = base_config(optimizer={"type": "Adam", "params": {"lr": 0.0}})
    engine2, *_ = deepspeed.initialize(model=SimpleModel(HIDDEN, nlayers=2),
                                       config=config2, mesh=mesh)
    engine2.forward(batch)
    g = np.asarray(jax.device_get(engine2._pending_grads)).ravel()
    mask = g != 0
    corr = np.corrcoef(m[mask], g[mask])[0, 1]
    assert corr > 0.5, f"compressed consensus uncorrelated with mean grad ({corr})"


def _train(config, cpu_devices, steps, dp=8, seed=0):
    """Overfit one fixed batch: a monotone-ish loss signal that keeps the
    compression noise visible but not dominant on the tiny model."""
    mesh = make_mesh({"data": dp}, devices=cpu_devices[:dp])
    engine, *_ = deepspeed.initialize(model=SimpleModel(HIDDEN, nlayers=2),
                                      config=config, mesh=mesh)
    batch = random_batches(1, engine.train_micro_batch_size_per_gpu() * dp,
                           HIDDEN, seed=seed)[0]
    return [float(np.asarray(engine.train_batch(iter([batch]))))
            for _ in range(steps)]


def test_onebit_adam_trains(cpu_devices):
    """OneBitAdam config (the round-1 crash path) trains through both the
    warmup and the compressed phase on an 8-device mesh."""
    config = base_config(optimizer={
        "type": "OneBitAdam",
        "params": {"lr": 1e-2, "freeze_step": 3}})
    losses = _train(config, cpu_devices, steps=10)
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"


def test_onebit_adam_loss_parity_with_dense(cpu_devices):
    """Post-freeze compressed training must track dense (never-frozen)
    1-bit Adam closely — the error-feedback guarantee (reference blog
    claim: same convergence, ``onebit-adam-blog-post.md``)."""
    dense = _train(base_config(optimizer={
        "type": "OneBitAdam",
        "params": {"lr": 1e-2, "freeze_step": 10 ** 9}}), cpu_devices, steps=16)
    comp = _train(base_config(optimizer={
        "type": "OneBitAdam",
        "params": {"lr": 1e-2, "freeze_step": 2}}), cpu_devices, steps=16)
    # warmup steps are bit-identical (compression not yet selected in)
    np.testing.assert_allclose(comp[:2], dense[:2], rtol=1e-6)
    # compressed phase tracks the dense trajectory (small lag from
    # quantization noise is expected on a 2-layer toy model)
    assert comp[-1] < 0.55 * comp[0], f"compressed did not converge: {comp}"
    # toy-model caveat: with only ~900 parameters the sign-quantization
    # noise floor is coarse; at real scale the gap closes (reference
    # convergence claim) — here we bound the divergence loosely
    assert abs(comp[-1] - dense[-1]) < 0.3 * abs(dense[0]), (
        f"compressed {comp} diverged from dense {dense}")


def _collective_f32_sizes(hlo_text):
    """Element counts of every f32 all-reduce / reduce-scatter in an HLO
    dump (the dense-gradient-sync footprint)."""
    import re

    sizes = []
    for line in hlo_text.splitlines():
        if re.search(r"(all-reduce|reduce-scatter|all-gather|all-to-all)",
                     line) and "f32[" in line:
            m = re.search(r"=\s*\(?f32\[([0-9,]*)\]", line)
            if m:
                dims = [int(d) for d in m.group(1).split(",") if d]
                sizes.append(int(np.prod(dims)) if dims else 1)
    return sizes


def test_onebit_compressed_program_has_no_dense_allreduce(cpu_devices):
    """The compressed phase must not emit any large-fp32 cross-replica
    reduction — its only data-axis traffic is packed uint8 signs + small
    scale gathers (the reference's 5x comm-volume claim,
    onebit-adam-blog-post.md:85).  The warmup program, by contrast, must
    contain the dense gradient sync (detector sanity check)."""
    from deepspeed_tpu.runtime.engine import _pack_batches

    config = base_config(optimizer={
        "type": "OneBitAdam", "params": {"lr": 1e-2, "freeze_step": 1}})
    mesh = make_mesh({"data": 8}, devices=cpu_devices[:8])
    engine, *_ = deepspeed.initialize(model=SimpleModel(HIDDEN, nlayers=2),
                                      config=config, mesh=mesh)
    batch = random_batches(1, engine.train_micro_batch_size_per_gpu() * 8,
                           HIDDEN, seed=0)[0]
    packed, spec = _pack_batches([batch])
    args = (engine.state["master"], engine.state["opt"], engine.state["scale"],
            engine.state["skipped"], engine.state["ustep"],
            engine._module_params, packed, spec,
            engine._device_hyperparams(), engine._segment_ids, {})
    n_params = int(np.prod(engine.segments.shape))

    comp_hlo = engine._train_step_compressed_fn.lower(*args).compile().as_text()
    # detector sanity: the packed-sign transport must be visible
    assert "all-to-all" in comp_hlo or "all-gather" in comp_hlo, (
        "no collectives found — HLO introspection broke, test is vacuous")
    comp_sizes = _collective_f32_sizes(comp_hlo)
    assert all(s < max(n_params // 8, 64) for s in comp_sizes), (
        f"compressed program still has dense f32 collectives: {comp_sizes} "
        f"(n_params={n_params})")


def test_onebit_adam_rejects_zero(cpu_devices):
    config = base_config(optimizer={"type": "OneBitAdam",
                                    "params": {"lr": 1e-2}},
                         zero_optimization={"stage": 2})
    mesh = make_mesh({"data": 8}, devices=cpu_devices[:8])
    with pytest.raises(AssertionError, match="incompatible with ZeRO"):
        deepspeed.initialize(model=SimpleModel(HIDDEN), config=config,
                             mesh=mesh)
