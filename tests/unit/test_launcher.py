"""Launcher: hostfile parsing, include/exclude filtering, world-info
round-trip, rank resolution, and a REAL 2-process CPU smoke launch through
the CLI (reference strategy: "multi-node" exercised as multi-process on one
host, SURVEY §4 / ``tests/unit/test_run.py``)."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from deepspeed_tpu.launcher.launch import resolve_node_rank
from deepspeed_tpu.launcher.runner import (decode_world_info,
                                           encode_world_info, fetch_hostfile,
                                           filter_resources)

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")


def test_fetch_hostfile(tmp_path):
    hf = tmp_path / "hostfile"
    hf.write_text("""
# comment
worker-0 slots=4
worker-1 slots=2  # trailing comment
""")
    assert fetch_hostfile(str(hf)) == {"worker-0": 4, "worker-1": 2}
    assert fetch_hostfile(str(tmp_path / "missing")) == {}


def test_fetch_hostfile_rejects_malformed(tmp_path):
    hf = tmp_path / "hostfile"
    hf.write_text("worker-0 gpus=4\n")
    with pytest.raises(ValueError):
        fetch_hostfile(str(hf))
    hf.write_text("worker-0 slots=4\nworker-0 slots=2\n")
    with pytest.raises(ValueError):
        fetch_hostfile(str(hf))


def test_filter_include():
    pool = {"w0": 4, "w1": 4, "w2": 2}
    assert filter_resources(pool, include="w0@w1:0,2") == {
        "w0": [0, 1, 2, 3], "w1": [0, 2]}
    with pytest.raises(AssertionError):
        filter_resources(pool, include="w9")
    with pytest.raises(AssertionError):
        filter_resources(pool, include="w2:5")


def test_filter_exclude():
    pool = {"w0": 4, "w1": 4}
    assert filter_resources(pool, exclude="w1") == {"w0": [0, 1, 2, 3]}
    assert filter_resources(pool, exclude="w0:1,3") == {
        "w0": [0, 2], "w1": [0, 1, 2, 3]}
    with pytest.raises(AssertionError):
        filter_resources(pool, include="w0", exclude="w1")


def test_world_info_roundtrip():
    active = {"a": [0, 1], "b": [0]}
    assert decode_world_info(encode_world_info(active)) == active


def test_resolve_node_rank():
    world = {"nodeA": [0], "nodeB": [0]}
    assert resolve_node_rank("1", world) == 1
    host = socket.gethostname()
    world2 = {"other": [0], host: [0]}
    assert resolve_node_rank("auto", world2) == 1
    with pytest.raises(RuntimeError):
        resolve_node_rank("auto", {"nope": [0]})


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_dataloader_process_slicing():
    """Each process sees its contiguous slice of every global batch, in a
    deterministic shared order (multi-host data contract)."""
    from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader

    data = [np.full((2,), i, np.float32) for i in range(16)]
    full = list(DeepSpeedDataLoader(data, batch_size=8, shuffle=True, seed=7))
    r0 = list(DeepSpeedDataLoader(data, batch_size=8, shuffle=True, seed=7,
                                  data_parallel_world_size=2,
                                  data_parallel_rank=0))
    r1 = list(DeepSpeedDataLoader(data, batch_size=8, shuffle=True, seed=7,
                                  data_parallel_world_size=2,
                                  data_parallel_rank=1))
    assert len(full) == len(r0) == len(r1) == 2
    for fb, a, b in zip(full, r0, r1):
        np.testing.assert_array_equal(np.concatenate([a, b]), fb)


@pytest.mark.slow
def test_two_process_cli_launch(tmp_path):
    """End-to-end: CLI -> spawner -> 2 processes -> jax.distributed
    rendezvous -> sliced dataloader -> 3 engine steps on a global mesh."""
    hostfile = tmp_path / "hostfile"
    hostfile.write_text(f"{socket.gethostname()} slots=2\n")
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "launcher_smoke_script.py")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("JAX_PLATFORMS", None)
    cmd = [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
           "--hostfile", str(hostfile),
           "--master_addr", "127.0.0.1",
           "--master_port", str(_free_port()),
           script, str(tmp_path)]
    proc = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=280)
    assert proc.returncode == 0, (
        f"launcher failed\nstdout:\n{proc.stdout[-3000:]}\n"
        f"stderr:\n{proc.stderr[-3000:]}")
    for rank in (0, 1):
        ok = tmp_path / f"rank{rank}.ok"
        assert ok.exists(), f"rank {rank} did not finish"
    l0 = (tmp_path / "rank0.ok").read_text()
    l1 = (tmp_path / "rank1.ok").read_text()
    assert l0 == l1, f"ranks diverged: {l0} vs {l1}"


def _mpi_args(hostfile, launcher, include=""):
    from deepspeed_tpu.launcher.runner import parse_args

    argv = ["-H", str(hostfile), "--launcher", launcher]
    if include:
        argv += ["--include", include]
    argv += ["train.py", "--lr", "0.1"]
    return parse_args(argv)


def test_openmpi_runner_command(tmp_path):
    """--launcher=openmpi builds one mpirun line that starts every RANK
    directly (no per-node spawner) and exports the DS_* rendezvous env
    (reference multinode_runner.py:77-107)."""
    from deepspeed_tpu.launcher.runner import OpenMPIRunner

    hostfile = tmp_path / "hostfile"
    hostfile.write_text("worker-0 slots=2\nworker-1 slots=2\n")
    args = _mpi_args(hostfile, "openmpi")
    # the DERIVED resource set (worker-1 trimmed to 1 slot) must reach
    # mpirun, not the raw user hostfile
    active = {"worker-0": [0, 1], "worker-1": [0]}
    (cmd,) = OpenMPIRunner(args, active, "worker-0").commands()
    assert cmd[:3] == ["mpirun", "-n", "3"]
    derived = cmd[cmd.index("-hostfile") + 1]
    assert derived != str(hostfile)
    with open(derived) as f:
        assert f.read().splitlines() == ["worker-0 slots=2",
                                         "worker-1 slots=1"]
    joined = " ".join(cmd)
    assert "-x DS_COORDINATOR=worker-0:29500" in joined
    assert "-x DS_NUM_PROCESSES=3" in joined
    # ranks run the user script directly under python -u
    assert cmd[-3:] == ["train.py", "--lr", "0.1"]
    assert "deepspeed_tpu.launcher.launch" not in joined
    os.unlink(derived)


def test_mvapich_runner_command(tmp_path):
    from deepspeed_tpu.launcher.runner import MVAPICHRunner

    hostfile = tmp_path / "hostfile"
    hostfile.write_text("a slots=2\nb slots=2\n")
    args = _mpi_args(hostfile, "mvapich")
    (cmd,) = MVAPICHRunner(args, {"a": [0, 1], "b": [0, 1]},
                           "a").commands()
    assert cmd[:5] == ["mpirun", "-np", "4", "-ppn", "2"]
    derived = cmd[cmd.index("--hostfile") + 1]
    with open(derived) as f:
        assert f.read().split() == ["a", "b"]
    # Hydra's -env takes name and value as SEPARATE tokens
    env_pairs = {cmd[i + 1]: cmd[i + 2]
                 for i, tok in enumerate(cmd) if tok == "-env"}
    assert env_pairs["DS_COORDINATOR"] == "a:29500"
    os.unlink(derived)


def test_mpi_runner_rejects_include(tmp_path):
    from deepspeed_tpu.launcher.runner import OpenMPIRunner

    hostfile = tmp_path / "hostfile"
    hostfile.write_text("a slots=2\n")
    args = _mpi_args(hostfile, "openmpi", include="a:0")
    with pytest.raises(AssertionError, match="include"):
        OpenMPIRunner(args, {"a": [0]}, "a")


def test_init_distributed_mpi_env_fallback(monkeypatch):
    """mpirun-scheduled ranks have no DS_PROCESS_ID; rank/size must come
    from the MPI library env (the reference's mpi4py discovery analog)."""
    from deepspeed_tpu.utils.distributed import _resolve_env

    for var in ("DS_NUM_PROCESSES", "DS_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("DS_COORDINATOR", "host0:29500")
    monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "3")
    monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "8")
    assert _resolve_env() == ("host0:29500", 8, 3)
    # DS_* takes precedence over MPI env when both are set
    monkeypatch.setenv("DS_NUM_PROCESSES", "4")
    monkeypatch.setenv("DS_PROCESS_ID", "1")
    assert _resolve_env() == ("host0:29500", 4, 1)
    # auto_mpi_discovery=False ignores the MPI env entirely
    monkeypatch.delenv("DS_NUM_PROCESSES")
    monkeypatch.delenv("DS_PROCESS_ID")
    assert _resolve_env(mpi=False) == ("host0:29500", 0, None)


def test_collect_exports(tmp_path):
    """Prefix-matched env + .deepspeed_env files travel to workers
    (reference runner.py:27-29, 341-356); file entries need no prefix and
    override inherited env; later files override earlier ones."""
    from deepspeed_tpu.launcher.runner import collect_exports

    environ = {"LIBTPU_INIT_ARGS": "--mega", "JAX_PLATFORMS": "tpu",
               "DS_FLASH_ATTENTION": "1", "HOME": "/root", "PATH": "/bin"}
    assert collect_exports(environ, paths=()) == {
        "LIBTPU_INIT_ARGS": "--mega", "JAX_PLATFORMS": "tpu",
        "DS_FLASH_ATTENTION": "1"}
    d1, d2 = tmp_path / "a", tmp_path / "b"
    d1.mkdir(), d2.mkdir()
    (d1 / ".deepspeed_env").write_text(
        "# comment\nMY_CUSTOM_FLAG=from_file\nJAX_PLATFORMS=cpu\n")
    (d2 / ".deepspeed_env").write_text("MY_CUSTOM_FLAG=second_wins\n")
    out = collect_exports(environ, paths=(str(d1), str(d2)))
    assert out["MY_CUSTOM_FLAG"] == "second_wins"
    assert out["JAX_PLATFORMS"] == "cpu"  # file overrides inherited env
    assert out["LIBTPU_INIT_ARGS"] == "--mega"


def test_remote_commands_carry_exports(tmp_path):
    """pdsh/ssh remote shells get an 'export K=V;' prelude; MPI backends
    put the same vars on the rank env (reference multinode_runner.py)."""
    from deepspeed_tpu.launcher.runner import (OpenMPIRunner, PDSHRunner,
                                               SSHRunner)

    hostfile = tmp_path / "hostfile"
    hostfile.write_text("a slots=1\nb slots=1\n")
    args = _mpi_args(hostfile, "pdsh")
    active = {"a": [0], "b": [0]}
    exports = {"LIBTPU_INIT_ARGS": "--x=1 --y", "DS_MARK": "7"}
    (pdsh_cmd,) = PDSHRunner(args, active, "a", exports).commands()
    assert "export LIBTPU_INIT_ARGS='--x=1 --y'; " in pdsh_cmd[-1]
    assert "export DS_MARK=7; " in pdsh_cmd[-1]
    ssh_cmds = SSHRunner(args, active, "a", exports).commands()
    assert all("export DS_MARK=7; " in c[-1] for c in ssh_cmds)
    args = _mpi_args(hostfile, "openmpi")
    (mpi_cmd,) = OpenMPIRunner(args, active, "a", exports).commands()
    assert "-x DS_MARK=7" in " ".join(mpi_cmd)
    os.unlink(mpi_cmd[mpi_cmd.index("-hostfile") + 1])


def test_env_reaches_spawned_process(tmp_path):
    """End-to-end: a prefix-matched parent env var AND a .deepspeed_env
    entry both reach the worker process through the single-node path."""
    hostfile = tmp_path / "hostfile"
    hostfile.write_text(f"{socket.gethostname()} slots=1\n")
    (tmp_path / ".deepspeed_env").write_text("MY_CUSTOM_FLAG=from_file\n")
    script = tmp_path / "probe.py"
    script.write_text(
        "import os, sys\n"
        "open(sys.argv[1], 'w').write(\n"
        "    os.environ.get('LIBTPU_INIT_ARGS', '?') + '|' +\n"
        "    os.environ.get('MY_CUSTOM_FLAG', '?'))\n")
    out = tmp_path / "probe.out"
    env = dict(os.environ)
    env["LIBTPU_INIT_ARGS"] = "--marker=42"
    env["HOME"] = str(tmp_path)  # hermetic: ignore any real ~/.deepspeed_env
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
         "--hostfile", str(hostfile), str(script), str(out)],
        cwd=tmp_path, env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert out.read_text() == "--marker=42|from_file"


def _order_guard_loader():
    from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader

    data = [np.zeros((2,), np.float32)] * 8
    return DeepSpeedDataLoader(data, batch_size=4,
                               data_parallel_world_size=2,
                               data_parallel_rank=0)


def test_verify_shared_order_raises_on_divergence(monkeypatch):
    """Mismatched cross-host sample order must raise the RuntimeError
    (silent shard duplication otherwise); matching order must not."""
    import jax
    from jax.experimental import multihost_utils

    loader = _order_guard_loader()
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    # two processes reporting DIFFERENT fingerprints
    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        lambda fp: np.stack([np.asarray(fp), np.asarray(fp) + 1]))
    with pytest.raises(RuntimeError, match="order drift"):
        loader._verify_shared_order(np.arange(8))
    # identical fingerprints: no raise
    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        lambda fp: np.stack([np.asarray(fp), np.asarray(fp)]))
    loader._verify_shared_order(np.arange(8))


def test_verify_shared_order_env_and_epoch_gating(monkeypatch):
    import jax
    from jax.experimental import multihost_utils

    loader = _order_guard_loader()
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        lambda fp: np.stack([np.asarray(fp), np.asarray(fp) + 1]))
    # DS_VERIFY_DATA_ORDER=never skips the collective entirely
    monkeypatch.setenv("DS_VERIFY_DATA_ORDER", "never")
    loader._verify_shared_order(np.arange(8))
    # default epoch0 mode skips past the first epoch (no sync point a
    # dead process could strand the others in)
    monkeypatch.delenv("DS_VERIFY_DATA_ORDER", raising=False)
    loader.epoch = 3
    loader._verify_shared_order(np.arange(8))
    loader.epoch = 1
    with pytest.raises(RuntimeError, match="order drift"):
        loader._verify_shared_order(np.arange(8))
    # world-1 loaders never dial the collective, whatever the env says
    from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader

    solo = DeepSpeedDataLoader([np.zeros((2,), np.float32)] * 8,
                               batch_size=4)
    monkeypatch.setenv("DS_VERIFY_DATA_ORDER", "always")
    solo._verify_shared_order(np.arange(8))


# ---------------------------------------------------------------------------
# resilience exit-code contract (launch.py)
# ---------------------------------------------------------------------------

def _launch_main(tmp_path, script_body, script_args=(), max_restarts=0,
                 extra_argv=()):
    """Drive launch.main() inline with one local child slot; returns the
    SystemExit code."""
    from deepspeed_tpu.launcher import launch
    from deepspeed_tpu.launcher.runner import encode_world_info
    import signal

    script = tmp_path / "child.py"
    script.write_text(script_body)
    wi = encode_world_info({socket.gethostname(): [0]})
    argv = ["--world_info", wi, "--node_rank", "0",
            "--master_addr", "127.0.0.1", "--master_port", "29999",
            "--max-restarts", str(max_restarts), *extra_argv,
            str(script), *script_args]
    old_int = signal.getsignal(signal.SIGINT)
    old_term = signal.getsignal(signal.SIGTERM)
    try:
        with pytest.raises(SystemExit) as exc:
            launch.main(argv)
        return exc.value.code
    finally:
        signal.signal(signal.SIGINT, old_int)
        signal.signal(signal.SIGTERM, old_term)


def test_launch_exports_compile_cache_dir(tmp_path, monkeypatch):
    """--compile-cache-dir reaches children as JAX_COMPILATION_CACHE_DIR
    (absolute), so respawned processes warm-start their compiles — and
    the launcher side stays jax-free (the child env var is jax's native
    knob; nothing is imported to set it)."""
    monkeypatch.setenv("DS_MONITOR_POLL_SECS", "0.1")
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    out = tmp_path / "env.out"
    code = _launch_main(
        tmp_path,
        "import os, sys\n"
        "open(sys.argv[1], 'w').write(\n"
        "    os.environ.get('JAX_COMPILATION_CACHE_DIR', '?'))\n",
        script_args=(str(out),),
        extra_argv=("--compile-cache-dir", str(tmp_path / "xla_cache")))
    assert code == 0
    assert out.read_text() == os.path.abspath(str(tmp_path / "xla_cache"))


def test_map_exit_code_signal_names():
    import signal

    from deepspeed_tpu.launcher.launch import map_exit_code

    assert map_exit_code(0) == (0, None)
    assert map_exit_code(7) == (7, None)
    assert map_exit_code(-signal.SIGKILL) == (137, "SIGKILL")
    assert map_exit_code(-signal.SIGSEGV) == (139, "SIGSEGV")


def test_launch_maps_child_signal_death(tmp_path, monkeypatch):
    """A child killed by a signal must exit the launcher with 128+signum
    (launch.py used to sys.exit the raw negative poll() value)."""
    monkeypatch.setenv("DS_MONITOR_POLL_SECS", "0.1")
    code = _launch_main(
        tmp_path,
        "import os, signal\nos.kill(os.getpid(), signal.SIGKILL)\n")
    assert code == 137


def test_launch_max_restarts_recovers_flaky_child(tmp_path, monkeypatch):
    """--max-restarts respawns a failed child with backoff; a child that
    succeeds on its second life exits the node cleanly."""
    monkeypatch.setenv("DS_MONITOR_POLL_SECS", "0.1")
    monkeypatch.setenv("DS_RESTART_BACKOFF_SECS", "0.05")
    marker = tmp_path / "ran_once"
    code = _launch_main(
        tmp_path,
        "import os, sys\n"
        "marker = sys.argv[1]\n"
        "if os.path.exists(marker):\n"
        "    sys.exit(0)\n"
        "open(marker, 'w').write('x')\n"
        "sys.exit(1)\n",
        script_args=(str(marker),), max_restarts=1)
    assert code == 0
    assert marker.exists()


def test_launch_poison_exit_code_never_respawns(tmp_path, monkeypatch):
    """A divergence abort must tear the node down immediately even with
    restart budget left — respawning replays the same divergence."""
    from deepspeed_tpu.resilience import EXIT_DIVERGENCE_ABORT

    monkeypatch.setenv("DS_MONITOR_POLL_SECS", "0.1")
    monkeypatch.setenv("DS_RESTART_BACKOFF_SECS", "0.05")
    counter = tmp_path / "runs"
    code = _launch_main(
        tmp_path,
        "import sys\n"
        "with open(sys.argv[1], 'a') as f:\n"
        "    f.write('x')\n"
        f"sys.exit({EXIT_DIVERGENCE_ABORT})\n",
        script_args=(str(counter),), max_restarts=3)
    assert code == EXIT_DIVERGENCE_ABORT
    assert counter.read_text() == "x"   # ran exactly once


def test_dataloader_order_fingerprint():
    """The multi-host order-drift guard's fingerprint: deterministic,
    order-sensitive, and cheap (weak spot: silent shard duplication when
    processes iterate in different orders)."""
    from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader

    a = DeepSpeedDataLoader.order_fingerprint(np.arange(64))
    b = DeepSpeedDataLoader.order_fingerprint(np.arange(64))
    assert a == b
    shuffled = np.arange(64)[::-1].copy()
    assert DeepSpeedDataLoader.order_fingerprint(shuffled) != a
    # single-process: the verify hook is a no-op (no collective dialed)
    loader = DeepSpeedDataLoader(
        [np.zeros((2,), np.float32)] * 8, batch_size=4, shuffle=True, seed=1)
    assert len(list(loader)) == 2
