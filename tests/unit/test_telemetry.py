"""Telemetry subsystem tests (``deepspeed_tpu/telemetry``): metrics
registry, structured event stream (golden schema), Chrome-trace spans,
config block validation, engine wiring (zero added host syncs, flush on
shutdown/preemption), launcher events, and the chaos acceptance test —
the report CLI reconstructing the anomaly→rollback→resume timeline from
run-dir artifacts alone."""

import json
import os
import threading

import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.telemetry import (EVENT_TYPES, SCHEMA_VERSION, EventLog,
                                     MetricsRegistry, StepTracer,
                                     read_events, validate_event)
from deepspeed_tpu.telemetry import events as ev
from deepspeed_tpu.telemetry import report as report_mod
from deepspeed_tpu.telemetry.config import DeepSpeedTelemetryConfig
from deepspeed_tpu.telemetry.manager import TelemetryManager

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 16


def tel_config(run_dir, trace=False, **overrides):
    cfg = base_config(steps_per_print=1,
                      telemetry={"enabled": True, "run_dir": str(run_dir),
                                 "trace": trace})
    cfg.update(overrides)
    return cfg


def make_engine(config, cpu_devices, dp=4):
    mesh = make_mesh({"data": dp}, devices=cpu_devices[:dp])
    engine, *_ = deepspeed.initialize(model=SimpleModel(HIDDEN, nlayers=2),
                                      config=config, mesh=mesh)
    return engine


def run_steps(engine, batches):
    return [float(np.asarray(engine.train_batch(iter([b]))))
            for b in batches]


# ------------------------------------------------------------- registry
def test_registry_instruments():
    reg = MetricsRegistry()
    reg.counter("a").inc()
    reg.counter("a").inc(2)
    reg.gauge("b").set(7.5)
    h = reg.histogram("c")
    for v in range(100):
        h.observe(float(v))
    snap = reg.snapshot()
    assert snap["a"] == {"kind": "counter", "value": 3.0}
    assert snap["b"]["value"] == 7.5
    assert snap["c"]["count"] == 100 and snap["c"]["max"] == 99.0
    assert 40.0 <= snap["c"]["p50"] <= 60.0
    # same name, different kind = programming error, loud
    with pytest.raises(TypeError):
        reg.gauge("a")


def test_registry_thread_safety():
    """Writer threads (step loop + checkpoint writers) and a reader
    thread (watchdog) run concurrently; final counts are exact."""
    reg = MetricsRegistry()
    n_threads, n_iters = 8, 2000
    stop = threading.Event()
    snaps = []

    def writer():
        c = reg.counter("steps")
        h = reg.histogram("lat")
        g = reg.gauge("depth")
        for i in range(n_iters):
            c.inc()
            h.observe(i * 0.001)
            g.set(i)

    def watchdog():
        while not stop.is_set():
            snaps.append(reg.snapshot())

    threads = [threading.Thread(target=writer) for _ in range(n_threads)]
    wd = threading.Thread(target=watchdog)
    wd.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    wd.join()
    snap = reg.snapshot()
    assert snap["steps"]["value"] == n_threads * n_iters
    assert snap["lat"]["count"] == n_threads * n_iters
    assert snaps, "watchdog reader never snapshotted"


def test_registry_prometheus_text(tmp_path):
    reg = MetricsRegistry()
    reg.counter("train/steps").inc(5)
    reg.histogram("step/secs").observe(0.25)
    text = reg.to_prometheus_text()
    assert "# TYPE deepspeed_tpu_train_steps_total counter" in text
    assert "deepspeed_tpu_train_steps_total 5.0" in text
    assert "deepspeed_tpu_step_secs_count 1" in text
    # dump/reload round-trip feeds the report CLI
    snap = reg.dump(tmp_path / "m.json")
    assert json.load(open(tmp_path / "m.json")) == snap


# --------------------------------------------------------------- events
def _sample_data(event_type):
    """Minimal valid data payload for each known event type."""
    samples = {
        "world_size": 4, "checkpoint": "/ckpt/global_step2",
        "reason": "close", "scalars": {"loss": 1.0}, "kind": "loss_spike",
        "detail": "z=9.1", "consecutive": 2, "from_step": 7,
        "restored_path": "/ckpt/global_step2", "stalled_secs": 12.5,
        "timeout_secs": 10.0, "scale": 1024.0, "prev_scale": 2048.0,
        "tag": "global_step7", "queue_depth": 1, "latency_secs": 0.2,
        "bytes": 4096, "retries": 1, "error": "disk full", "signum": 15,
        "proc_rank": 0, "pid": 4242, "code": 85, "restart": 1,
        "backoff_secs": 2.0, "duration_secs": 12.75, "phase": "plan",
        "program": "train_step",
        "phases": {"compute": 0.2, "exposed_collective": 0.05,
                   "host_stream": 0.1, "driver": 0.02,
                   "unexplained": 0.13},
        "predicted_step_seconds": 0.37, "measured_step_seconds": 0.5,
        "step_unexplained_fraction": 0.26,
        "verdict": "outlier", "suspects": [2],
    }
    return {k: samples[k] for k in EVENT_TYPES[event_type]}


def test_event_stream_golden_schema(tmp_path):
    """EVERY known event type round-trips through the JSONL stream and
    carries schema_version / rank / seq / ts / step."""
    log = EventLog(tmp_path, rank=3)
    for i, event_type in enumerate(sorted(EVENT_TYPES)):
        rec = log.emit(event_type, step=i, **_sample_data(event_type))
        assert rec is not None
    log.close()
    records = read_events(tmp_path, strict=True)
    assert len(records) == len(EVENT_TYPES)
    assert [r["seq"] for r in records] == list(range(len(EVENT_TYPES)))
    for rec in records:
        assert validate_event(rec) == [], rec
        assert rec["schema_version"] == SCHEMA_VERSION
        assert rec["rank"] == 3
        assert isinstance(rec["ts"], float) and rec["step"] is not None
    assert sorted(r["type"] for r in records) == sorted(EVENT_TYPES)


def test_event_schema_catches_missing_keys():
    assert validate_event({"schema_version": 1, "seq": 0, "rank": 0,
                           "ts": 0.0, "type": "rollback", "step": 1,
                           "data": {"reason": "x"}})  # missing keys
    assert validate_event({"type": "rollback"})       # missing envelope


def test_event_merge_across_ranks(tmp_path):
    for rank in (0, 1):
        log = EventLog(tmp_path, rank=rank)
        log.emit(ev.EVENT_RUN_START, step=0, world_size=2)
        log.emit(ev.EVENT_RUN_END, reason="close")
        log.close()
    merged = read_events(tmp_path)
    assert len(merged) == 4
    assert {r["rank"] for r in merged} == {0, 1}
    # per-rank seq order survives the merge
    for rank in (0, 1):
        seqs = [r["seq"] for r in merged if r["rank"] == rank]
        assert seqs == sorted(seqs)


def test_event_reader_skips_torn_tail_line(tmp_path):
    log = EventLog(tmp_path, rank=0)
    log.emit(ev.EVENT_RUN_START, step=0, world_size=1)
    log.close()
    with open(log.path, "a") as f:
        f.write('{"schema_version": 1, "seq": 1, "tru')  # torn write
    assert len(read_events(tmp_path)) == 1
    with pytest.raises(ValueError):
        read_events(tmp_path, strict=True)


# ---------------------------------------------------------------- trace
def test_step_tracer_writes_chrome_trace(tmp_path):
    tracer = StepTracer(tmp_path, rank=0, max_events=100)
    with tracer.span("dispatch", step=1):
        pass
    tracer.instant("anomaly", step=2)
    tracer.close()
    events = json.load(open(tracer.path))       # strict JSON after close
    complete = [e for e in events if e.get("ph") == "X"]
    assert {e["name"] for e in complete} == {"dispatch", "anomaly"}
    for e in complete:
        assert {"ts", "dur", "pid", "tid"} <= set(e)
    assert any(e.get("ph") == "M" for e in events)  # process_name meta


def test_prometheus_dump_survives_corrupt_metrics_file(tmp_path):
    """A torn metrics-*.json (rank killed mid-dump) must not crash the
    --prometheus export for the surviving ranks."""
    MetricsRegistry().dump(tmp_path / "metrics-rank1.json")
    reg = MetricsRegistry()
    reg.counter("ok").inc()
    reg.dump(tmp_path / "metrics-rank0.json")
    (tmp_path / "metrics-rank2.json").write_text("not json{")
    prom = report_mod.prometheus_dump(tmp_path)
    assert "deepspeed_tpu_ok_total" in prom


def test_device_trace_trigger_stat_is_throttled(tmp_path, monkeypatch):
    """The trigger-file stat runs only every check_every-th poll (run
    dirs live on network filesystems; no per-step I/O), but a pending
    trigger is still picked up on the throttle boundary."""
    from deepspeed_tpu.telemetry.trace import DeviceTraceTrigger

    trig = DeviceTraceTrigger(tmp_path, max_secs=1.0, check_every=5)
    stats = {"n": 0}
    real_exists = os.path.exists

    def counting_exists(p):
        stats["n"] += 1
        return real_exists(p)

    monkeypatch.setattr(os.path, "exists", counting_exists)
    for step in range(20):
        trig.poll(step)
    assert stats["n"] == 4                       # 20 polls / 5
    monkeypatch.undo()
    started = []
    monkeypatch.setattr(trig, "_start", lambda step: started.append(step))
    open(trig.trigger_path, "w").close()
    for step in range(5):
        trig.poll(step)
    assert started, "trigger file never picked up within check_every"
    assert not os.path.exists(trig.trigger_path)  # consumed


def test_ckpt_queue_depth_gauge_drains(cpu_devices, tmp_path):
    """The queue-depth gauge must return to 0 after writers drain, not
    stick at the last enqueue's depth."""
    run_dir = tmp_path / "tel"
    engine = make_engine(tel_config(run_dir), cpu_devices)
    run_steps(engine, random_batches(1, 16, HIDDEN, seed=9))
    engine.save_checkpoint(str(tmp_path / "ckpt"))          # async
    engine.wait_checkpoint()
    assert engine.telemetry.registry.gauge("ckpt/queue_depth").value == 0
    engine.close()


def test_step_tracer_bounds_events(tmp_path):
    tracer = StepTracer(tmp_path, rank=0, max_events=3)
    for i in range(10):
        tracer.instant("e", i=i)
    tracer.close()
    events = [e for e in json.load(open(tracer.path)) if e.get("ph") == "X"]
    assert len(events) == 3                      # capped, not unbounded


# --------------------------------------------------------------- config
def test_telemetry_config_defaults_and_parse():
    cfg = DeepSpeedTelemetryConfig({})
    assert not cfg.enabled and cfg.events and not cfg.trace
    assert cfg.run_dir == os.path.join("runs", "telemetry")
    cfg = DeepSpeedTelemetryConfig({"telemetry": {
        "enabled": True, "run_dir": "/tmp/t", "trace": True,
        "trace_max_events": 10, "device_trace_secs": 3.5,
        "device_trace_trigger": "/tmp/go"}})
    assert cfg.enabled and cfg.trace and cfg.run_dir == "/tmp/t"
    assert cfg.trace_max_events == 10 and cfg.device_trace_secs == 3.5
    assert cfg.device_trace_trigger == "/tmp/go"
    with pytest.raises(AssertionError, match="device_trace_secs"):
        DeepSpeedTelemetryConfig({"telemetry": {"device_trace_secs": 0}})


def test_telemetry_block_in_config_schema():
    """The block rides the DSC4xx schema: misspelled sub-keys get a
    'did you mean' instead of being silently ignored."""
    from deepspeed_tpu.tools.dslint import validate_config_dict

    issues = validate_config_dict({"telemetry": {"evnts": True}})
    assert len(issues) == 1 and issues[0].suggestion == "events"
    assert not validate_config_dict(
        {"telemetry": {"enabled": True, "run_dir": "/x", "trace": True,
                       "trace_max_events": 1000, "device_trace_secs": 5,
                       "device_trace_trigger": ""}})


def test_disabled_manager_is_cheap_noop(tmp_path):
    tel = TelemetryManager(DeepSpeedTelemetryConfig({}), rank=0)
    assert not tel.enabled
    tel.emit("anything", step=1, x=1)
    tel.counter("c").inc()
    tel.gauge("g").set(1)
    tel.histogram("h").observe(1)
    with tel.span("s"):
        pass
    tel.step_metrics(1, 16, {"loss": 1.0})
    tel.flush()
    tel.close()
    assert not os.listdir(tmp_path)   # nothing written anywhere


# -------------------------------------------------------- engine wiring
def test_engine_zero_added_host_syncs(cpu_devices, tmp_path, monkeypatch):
    """The acceptance guarantee: telemetry adds ZERO host syncs — the
    jax.device_get call count per step is identical with telemetry
    enabled (trace + events on) and disabled."""
    import jax

    batches = random_batches(4, 16, HIDDEN, seed=0)

    def count_gets(config, after=None):
        engine = make_engine(config, cpu_devices)
        counts = {"n": 0}
        real_get = jax.device_get

        def counting_get(x):
            counts["n"] += 1
            return real_get(x)

        monkeypatch.setattr(jax, "device_get", counting_get)
        try:
            run_steps(engine, batches)
            if after is not None:
                after(engine)
        finally:
            monkeypatch.setattr(jax, "device_get", real_get)
        engine.close()
        return counts["n"]

    resilience = {"enabled": True, "policy": "skip"}
    base = count_gets(base_config(steps_per_print=1,
                                  resilience=resilience))
    tel = count_gets(tel_config(tmp_path / "t", trace=True,
                                resilience=resilience))
    assert tel == base, (f"telemetry added host syncs: {tel} device_get "
                         f"calls vs {base} baseline")
    assert base > 0
    # memory observability on top (ledger + per-print watermark
    # sampling): memory_analysis happens at compile time and
    # memory_stats is a host runtime query — still ZERO added
    # device_get calls over the same run
    mem = count_gets(tel_config(
        tmp_path / "m", trace=True, resilience=resilience,
        profiling={"memory_ledger": True, "memory_watermarks": True}))
    assert mem == base, (f"memory observability added host syncs: {mem} "
                         f"device_get calls vs {base} baseline")
    # comm observability on top, on the multi-device (virtual CPU) mesh
    # this test already runs: the collective ledger walks HLO text at
    # compile time and the per-rank latency/skew export is host floats
    # + run-dir file I/O at the steps_per_print cadence — still ZERO
    # added device_get calls, even with the straggler hook armed
    comm = count_gets(tel_config(
        tmp_path / "c", trace=True,
        resilience=dict(resilience, straggler_factor=2.0),
        profiling={"memory_ledger": True, "memory_watermarks": True,
                   "comm_ledger": True}))
    assert comm == base, (f"comm observability added host syncs: {comm} "
                          f"device_get calls vs {base} baseline")
    # fleet integrity plane on top (PR 15): the in-jit state fingerprint
    # is a dispatched device scalar that joins the SAME batched
    # steps_per_print transfer, and the consensus vote is host
    # arithmetic + run-dir file I/O — still ZERO added device_get calls
    # with the plane armed
    # fleet identity >= 2 so the consensus arms (a single process can
    # never reach quorum; the engine refuses the wasted checksum)
    monkeypatch.setenv("DS_NUM_PROCESSES", "2")
    integ = count_gets(tel_config(
        tmp_path / "i", trace=True,
        resilience=dict(resilience, integrity=True)))
    assert integ == base, (f"integrity plane added host syncs: {integ} "
                           f"device_get calls vs {base} baseline")
    # ...and the plane really voted inside the counted window: one
    # fingerprint-kind EVENT_INTEGRITY per print, with this rank's
    # canonical fingerprint attached
    integ_events = [r for r in read_events(tmp_path / "i")
                    if r["type"] == "integrity"]
    assert integ_events, "no integrity events at the print cadence"
    for rec in integ_events:
        assert validate_event(rec) == []
        assert rec["data"]["kind"] == "fingerprint"
        assert rec["data"]["fingerprint"]

    # program verification on top (DSP6xx + the DSO7xx overlap
    # analysis, profiling/verify + profiling/overlap): the artifact
    # dump happens at the ledger's one compile-time recording and
    # verify_programs() re-reads compile-time artifacts — running it
    # INSIDE the counted window, overlap verdict included, must still
    # add ZERO device_get calls
    def verify(engine):
        report = engine.verify_programs()
        assert report is not None and report["violations"] == 0, (
            [d.format() for d in report["diagnostics"]])
        # the overlap verdict rode the same compile-time artifacts: a
        # real claim (not None), computed with no device work
        assert report["overlap"] is not None
        assert report["overlap"]["programs"] >= 1
        assert engine.overlap_receipt() is not None
        # the attribution receipt reconciles the same compile-time
        # budget against the latency ring's already-recorded floats —
        # a REAL verdict (measured side present), still no device work
        receipt = engine.attribution_receipt()
        assert receipt is not None
        assert receipt["measured_step_seconds"] is not None
        assert receipt["step_unexplained_fraction"] is not None

    ver = count_gets(tel_config(
        tmp_path / "v", trace=True,
        resilience=resilience,
        profiling={"memory_ledger": True, "memory_watermarks": True,
                   "comm_ledger": True, "program_dump": True}),
        after=verify)
    assert ver == base, (f"program verification added host syncs: {ver} "
                         f"device_get calls vs {base} baseline")
    # ...and the attribution surface really fired inside that counted
    # window: per-print EVENT_ATTRIBUTION records + attribution/*
    # gauges landed in the run artifacts with ZERO added device_gets
    att_events = [r for r in read_events(tmp_path / "v")
                  if r["type"] == "attribution"]
    assert att_events, "no attribution events at the print cadence"
    for rec in att_events:
        assert validate_event(rec) == []
        assert rec["data"]["phases"]["unexplained"] is not None
    snap = json.load(open(tmp_path / "v" / "metrics-rank0.json"))
    assert "attribution/predicted_step_seconds" in snap
    assert "attribution/unexplained_fraction" in snap


def test_engine_zero_added_host_syncs_overlap_comm(cpu_devices, tmp_path,
                                                   monkeypatch):
    """Round 14: the bucketed overlap_comm exchange adds ZERO per-step
    host syncs — the shard_map region, the declared collective
    schedule, and the overlap/verify receipts are all compile-time or
    host-float work.  Same counting harness as the main test, on a
    ZeRO-2 dp=4 run with the buckets engaged."""
    import jax

    zero = {"stage": 2, "overlap_comm": True,
            "reduce_bucket_size": 400, "allgather_bucket_size": 800}
    batches = random_batches(4, 16, HIDDEN, seed=0)

    def count_gets(config, after=None):
        engine = make_engine(config, cpu_devices)
        assert engine.comm_overlap_enabled()
        assert engine.collective_schedule()["rs_buckets"] > 1
        counts = {"n": 0}
        real_get = jax.device_get

        def counting_get(x):
            counts["n"] += 1
            return real_get(x)

        monkeypatch.setattr(jax, "device_get", counting_get)
        try:
            run_steps(engine, batches)
            if after is not None:
                after(engine)
        finally:
            monkeypatch.setattr(jax, "device_get", real_get)
        engine.close()
        return counts["n"]

    base = count_gets(base_config(steps_per_print=1,
                                  zero_optimization=zero))

    def verify(engine):
        report = engine.verify_programs()
        assert report is not None and report["violations"] == 0, (
            [d.format() for d in report["diagnostics"]])
        receipt = engine.overlap_receipt()
        assert receipt is not None
        assert receipt["exposed_wire_seconds"] < receipt["wire_seconds"]

    full = count_gets(tel_config(
        tmp_path / "oc", trace=True, zero_optimization=zero,
        profiling={"memory_ledger": True, "comm_ledger": True,
                   "program_dump": True}), after=verify)
    assert full == base, (f"overlap_comm observability added host "
                          f"syncs: {full} device_get calls vs {base} "
                          f"baseline")
    assert base > 0


def test_engine_step_metrics_and_monitor_preserved(cpu_devices, tmp_path):
    """Scalars flow through the event stream AND the TrainingMonitor's
    JSONL/TB output (thin-consumer contract: TB behavior unchanged)."""
    run_dir = tmp_path / "tel"
    cfg = tel_config(run_dir,
                     tensorboard={"enabled": True,
                                  "output_path": str(tmp_path / "tb"),
                                  "job_name": "unit"})
    engine = make_engine(cfg, cpu_devices)
    run_steps(engine, random_batches(3, 16, HIDDEN, seed=1))
    engine.close()
    # monitor output (pre-telemetry format) intact
    lines = [json.loads(l) for l in
             open(tmp_path / "tb" / "unit" / "events.jsonl")]
    assert len(lines) == 3
    assert all("Train/Samples/train_loss" in l for l in lines)
    # event stream carries the same scalars, schema-tagged
    records = read_events(run_dir)
    metrics = [r for r in records if r["type"] == "step_metrics"]
    assert [m["step"] for m in metrics] == [1, 2, 3]
    for m in metrics:
        assert validate_event(m) == []
        assert "Train/Samples/train_loss" in m["data"]["scalars"]
        assert m["data"]["skipped"] == 0
    assert records[0]["type"] == "run_start"
    assert records[-1]["type"] == "run_end"
    # metrics snapshot dumped on close
    snap = json.load(open(run_dir / "metrics-rank0.json"))
    assert snap["train/steps"]["value"] == 3


def test_engine_close_is_idempotent_and_flushes(cpu_devices, tmp_path):
    run_dir = tmp_path / "tel"
    engine = make_engine(tel_config(run_dir), cpu_devices)
    run_steps(engine, random_batches(1, 16, HIDDEN, seed=2))
    engine.close()
    engine.close()   # second close: no error, no duplicate run_end
    records = read_events(run_dir)
    assert [r["type"] for r in records].count("run_end") == 1


def test_preemption_path_flushes_tail_events(cpu_devices, tmp_path):
    """The SIGTERM-drain path must leave the tail events on disk even
    though the process would die without atexit."""
    run_dir = tmp_path / "tel"
    engine = make_engine(tel_config(run_dir), cpu_devices)
    run_steps(engine, random_batches(1, 16, HIDDEN, seed=3))
    engine._preemption_save()        # no ckpt dir yet: save skipped,
    records = read_events(run_dir)   # telemetry still flushed
    types = [r["type"] for r in records]
    assert "preemption" in types
    assert os.path.isfile(run_dir / "metrics-rank0.json")
    engine.close()


def test_loss_scale_change_event_rides_batched_fetch(cpu_devices,
                                                     tmp_path):
    """fp16 + NaN batch: the scale halving shows up as a loss_scale event
    sourced from the scalars the engine already fetched."""
    from deepspeed_tpu.resilience import ChaosMonkey

    run_dir = tmp_path / "tel"
    cfg = tel_config(run_dir,
                     fp16={"enabled": True, "initial_scale_power": 4,
                           "loss_scale_window": 1000, "hysteresis": 1},
                     resilience={"enabled": True, "policy": "skip"})
    engine = make_engine(cfg, cpu_devices)
    batches = random_batches(3, 16, HIDDEN, seed=4)
    run_steps(engine, batches[:1])
    chaos = ChaosMonkey()
    run_steps(engine, [chaos.nan_batch(batches[1])])   # overflow: halve
    run_steps(engine, batches[2:])
    engine.close()
    scale_events = [r for r in read_events(run_dir)
                    if r["type"] == "loss_scale"]
    assert scale_events, "no loss_scale event for the overflow halving"
    assert scale_events[0]["data"]["scale"] \
        < scale_events[0]["data"]["prev_scale"]


# ------------------------------------------------- chaos report (accept)
def test_chaos_run_report_reconstructs_timeline(cpu_devices, tmp_path):
    """THE acceptance test: a chaos run (NaN burst → rollback → resume,
    plus a checkpoint commit) is fully reconstructable by the report CLI
    from run-dir artifacts alone — each event named with step and rank."""
    from deepspeed_tpu.resilience import ChaosMonkey

    run_dir = tmp_path / "tel"
    cfg = tel_config(run_dir, trace=True,
                     resilience={"enabled": True, "policy": "rollback",
                                 "divergence_patience": 2,
                                 "max_rollbacks": 1})
    engine = make_engine(cfg, cpu_devices)
    clean = random_batches(6, 16, HIDDEN, seed=5)
    run_steps(engine, clean[:2])
    engine.save_checkpoint(str(tmp_path / "ckpt"), sync=True)
    chaos = ChaosMonkey(seed=0)
    it = chaos.wrap_iter(iter([clean[2], clean[3]] + clean[2:]),
                         nan_steps=(0, 1))
    for _ in range(2):
        engine.train_batch(it)       # NaN x2 -> rollback to step 2
    assert engine._rollback_mgr.rollbacks_used == 1
    for _ in range(4):
        engine.train_batch(it)       # resumed run to completion
    assert engine.global_steps == 6
    engine.close()

    # ---- artifacts only from here: fresh read of run_dir ----
    text, records = report_mod.generate_report(str(run_dir))
    by_type = {}
    for r in records:
        by_type.setdefault(r["type"], []).append(r)
    # checkpoint commit, with step + latency + bytes
    commit = by_type["ckpt_commit"][0]
    assert commit["step"] == 2 and commit["data"]["bytes"] > 0
    # two anomalies at the diverging steps
    anomalies = by_type["anomaly"]
    assert [a["step"] for a in anomalies] == [3, 4]
    assert all(a["data"]["kind"] == "nonfinite_grads" for a in anomalies)
    # rollback names both timelines' steps
    rb = by_type["rollback"][0]
    assert rb["data"]["from_step"] == 4 and rb["step"] == 2
    # the resume (load_checkpoint inside the rollback)
    assert by_type["run_resume"][0]["step"] == 2
    # every timeline event is step- and rank-tagged in the text report
    for needle in ("anomaly", "rollback", "run_resume", "ckpt_commit",
                   "rank=0", "step=2", "step=4"):
        assert needle in text, f"report missing {needle}:\n{text}"
    # schema-clean artifacts
    for r in records:
        assert validate_event(r) == [], r
    # CLI entry point agrees (exit 0) and the prometheus dump exposes the
    # rollback counter from the metrics snapshot
    assert report_mod.main(["report", str(run_dir)]) == 0
    prom = report_mod.prometheus_dump(str(run_dir))
    assert "deepspeed_tpu_resilience_rollbacks_total" in prom


# ------------------------------------------------------------- launcher
def test_launcher_emits_lifecycle_events(tmp_path, monkeypatch):
    """Launcher restarts/exit codes land in events-launcher.jsonl (merged
    by the report CLI with the ranks' streams)."""
    import socket

    from deepspeed_tpu.launcher import launch
    from deepspeed_tpu.launcher.runner import encode_world_info

    monkeypatch.setenv("DS_MONITOR_POLL_SECS", "0.1")
    monkeypatch.setenv("DS_RESTART_BACKOFF_SECS", "0.05")
    tel_dir = tmp_path / "tel"
    script = tmp_path / "child.py"
    marker = tmp_path / "ran_once"
    script.write_text(
        "import os, sys\n"
        f"marker = {str(marker)!r}\n"
        "if os.path.exists(marker):\n"
        "    sys.exit(0)\n"
        "open(marker, 'w').write('x')\n"
        "sys.exit(1)\n")
    wi = encode_world_info({socket.gethostname(): [0]})
    argv = ["--world_info", wi, "--node_rank", "0",
            "--master_addr", "127.0.0.1", "--master_port", "29999",
            "--max-restarts", "1", "--telemetry-dir", str(tel_dir),
            str(script)]
    import signal
    old = (signal.getsignal(signal.SIGINT), signal.getsignal(signal.SIGTERM))
    try:
        with pytest.raises(SystemExit) as exc:
            launch.main(argv)
    finally:
        signal.signal(signal.SIGINT, old[0])
        signal.signal(signal.SIGTERM, old[1])
    assert exc.value.code == 0
    records = read_events(tel_dir)
    types = [r["type"] for r in records]
    assert types.count("proc_spawn") == 2        # initial + respawn
    assert "proc_respawn" in types
    assert types.count("proc_exit") == 2         # exit 1, then exit 0
    exits = [r["data"]["code"] for r in records
             if r["type"] == "proc_exit"]
    assert exits == [1, 0]
    assert all(r["rank"] == "launcher" for r in records)
    for r in records:
        assert validate_event(r) == [], r


# ----------------------------------------------------- timer satellites
def test_throughput_timer_avg_before_any_window_is_zero():
    from deepspeed_tpu.utils.timer import ThroughputTimer

    t = ThroughputTimer(batch_size=4, num_workers=1)
    assert t.avg_samples_per_sec() == 0.0        # was float("-inf")
    lines = []
    t2 = ThroughputTimer(batch_size=4, num_workers=1, start_step=0,
                         steps_per_output=1, logging_fn=lines.append)
    t2.start()
    t2.stop()
    assert lines and "-inf" not in lines[0]


def test_wallclock_timer_log_honors_kwargs():
    """log() used to silently ignore ranks= and memory_breakdown=.
    (The framework logger is propagate=False with a stream handler bound
    at import time, so the assertion taps a handler, not caplog/capfd.)"""
    import logging

    from deepspeed_tpu.utils.logging import logger
    from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer

    messages = []
    handler = logging.Handler()
    handler.emit = lambda rec: messages.append(rec.getMessage())
    logger.addHandler(handler)
    try:
        timers = SynchronizedWallClockTimer()
        timers("phase").start(sync=False)
        timers("phase").stop(sync=False)
        timers.log(["phase"], memory_breakdown=True)
        assert any("phase" in m and "mem" in m for m in messages)
        messages.clear()
        # this process is rank 0; ranks=[99] must suppress the line
        timers("phase").start(sync=False)
        timers("phase").stop(sync=False)
        timers.log(["phase"], ranks=[99])
        assert not any("time (ms)" in m for m in messages)
    finally:
        logger.removeHandler(handler)


def test_memory_usage_aggregates_all_local_devices():
    from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer

    out = SynchronizedWallClockTimer.memory_usage()
    assert "mem" in out
    if "across" in out:                 # stats-capable backend
        assert "local device(s)" in out
