"""Collective wrappers over a virtual 8-device mesh.

The reference has no comm-layer unit tests (raw torch.distributed calls were
exercised implicitly); here the comm module is first-class (SURVEY §2.6) and
tested directly under shard_map.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from deepspeed_tpu import comm
from deepspeed_tpu.parallel import make_mesh, DATA_AXIS, MeshGrid


def data_mesh(cpu_devices, n=8):
    return make_mesh({"data": n}, devices=cpu_devices[:n])


def test_make_mesh_infers_data(cpu_devices):
    mesh = make_mesh({"data": -1}, devices=cpu_devices)
    assert dict(zip(mesh.axis_names, mesh.devices.shape))["data"] == 8


def test_psum_and_axis_index(cpu_devices):
    mesh = data_mesh(cpu_devices)
    x = jnp.arange(8, dtype=jnp.float32)

    @jax.jit
    def f(x):
        def inner(xs):
            return comm.psum(xs, DATA_AXIS), comm.axis_index(DATA_AXIS) * jnp.ones_like(xs)

        return shard_map(inner, mesh=mesh, in_specs=P(DATA_AXIS),
                         out_specs=(P(DATA_AXIS), P(DATA_AXIS)))(x)

    total, idx = f(x)
    np.testing.assert_allclose(np.asarray(total), np.full((8,), 28.0))
    np.testing.assert_allclose(np.asarray(idx), np.arange(8, dtype=np.float32))


def test_reduce_scatter_allgather_roundtrip(cpu_devices):
    mesh = data_mesh(cpu_devices)
    # Each shard holds the full vector; psum_scatter leaves each shard with
    # the sum of its slice; all_gather reassembles.
    full = jnp.arange(16, dtype=jnp.float32)
    x = jnp.tile(full, (8, 1))

    @jax.jit
    def f(x):
        def inner(xs):
            local = comm.reduce_scatter(xs[0], DATA_AXIS)
            gathered = comm.all_gather(local, DATA_AXIS)
            return gathered[None]

        return shard_map(inner, mesh=mesh, in_specs=P(DATA_AXIS, None),
                         out_specs=P(DATA_AXIS, None))(x)

    out = f(x)
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(full) * 8)


def test_ppermute_ring(cpu_devices):
    mesh = data_mesh(cpu_devices)
    x = jnp.arange(8, dtype=jnp.float32)

    @jax.jit
    def f(x):
        def inner(xs):
            n = 8
            perm = [(i, (i + 1) % n) for i in range(n)]
            return comm.ppermute(xs, DATA_AXIS, perm)

        return shard_map(inner, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS))(x)

    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), 1))


def test_mesh_grid_mpu_interface(cpu_devices):
    mesh = make_mesh({"pipe": 2, "data": 2, "model": 2}, devices=cpu_devices)
    grid = MeshGrid(mesh)
    assert grid.get_data_parallel_world_size() == 2
    assert grid.get_model_parallel_world_size() == 2
    assert grid.get_pipe_parallel_world_size() == 2
    assert grid.get_data_parallel_group() == "data"
    assert grid.get_model_parallel_group() == "model"
    assert grid.world_size == 8
    assert grid.is_first_stage()
