"""dslint unit tests: every rule id has a triggering fixture AND a clean
twin, plus config-schema extraction/validation round-trips."""

import json

import pytest

from deepspeed_tpu.tools.dslint import (RULES, failing, lint_paths,
                                        validate_config_dict)
from deepspeed_tpu.tools.dslint.cli import main as dslint_main
from deepspeed_tpu.tools.dslint.schema import (dead_key_diagnostics,
                                               extract_schema)


def lint_source(tmp_path, source, name="snippet.py"):
    """Rule ids of unsuppressed diagnostics for one source snippet."""
    path = tmp_path / name
    path.write_text(source)
    return sorted({d.rule_id for d in failing(lint_paths([str(path)]))})


# ---------------------------------------------------------------------------
# hot-path rules (in-jit)
# ---------------------------------------------------------------------------

def test_dsh101_item_in_jit(tmp_path):
    ids = lint_source(tmp_path, """
import jax

@jax.jit
def step(x):
    return x.item()
""")
    assert ids == ["DSH101"]


def test_dsh101_clean_twin(tmp_path):
    ids = lint_source(tmp_path, """
import jax

@jax.jit
def step(x):
    return x + 1

def driver(x):
    return float(jax.device_get(step(x)))
""")
    assert ids == []


def test_dsh101_reaches_through_call_graph(tmp_path):
    # the helper is not decorated; it is hot because a jitted root calls it
    ids = lint_source(tmp_path, """
import jax

def helper(x):
    return x.item()

@jax.jit
def step(x):
    return helper(x)
""")
    assert ids == ["DSH101"]


def test_dsh102_scalar_cast(tmp_path):
    ids = lint_source(tmp_path, """
import jax

@jax.jit
def step(x):
    return float(x) + 1.0
""")
    assert ids == ["DSH102"]


def test_dsh102_shape_arithmetic_is_exempt(tmp_path):
    ids = lint_source(tmp_path, """
import jax

@jax.jit
def step(x):
    scale = float(x.shape[0]) * float(1 << 8) + int(len(x.shape))
    return x * scale
""")
    assert ids == []


def test_dsh103_numpy_materialize(tmp_path):
    ids = lint_source(tmp_path, """
import jax
import numpy as np

@jax.jit
def step(x):
    return np.asarray(x).sum()
""")
    assert ids == ["DSH103"]


def test_dsh103_jnp_is_clean(tmp_path):
    ids = lint_source(tmp_path, """
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    return jnp.asarray(x).sum()
""")
    assert ids == []


def test_dsh104_print_in_jit(tmp_path):
    ids = lint_source(tmp_path, """
import jax

@jax.jit
def step(x):
    print(x)
    return x
""")
    assert ids == ["DSH104"]


def test_dsh104_debug_print_is_clean(tmp_path):
    ids = lint_source(tmp_path, """
import jax

@jax.jit
def step(x):
    jax.debug.print("loss={}", x)
    return x
""")
    assert ids == []


def test_dsh105_wall_clock_in_jit(tmp_path):
    ids = lint_source(tmp_path, """
import time
import jax

@jax.jit
def step(x):
    t0 = time.time()
    return x + t0
""")
    assert ids == ["DSH105"]


def test_dsh105_host_timing_is_clean(tmp_path):
    ids = lint_source(tmp_path, """
import time
import jax

def bench(step, x):
    t0 = time.perf_counter()
    jax.device_get(step(x))
    return time.perf_counter() - t0
""")
    assert ids == []


def test_dsh106_device_loop_in_jit(tmp_path):
    ids = lint_source(tmp_path, """
import jax

@jax.jit
def step(x):
    for d in jax.devices():
        x = x + 1
    return x
""")
    assert ids == ["DSH106"]


def test_dsh106_host_device_loop_is_clean(tmp_path):
    ids = lint_source(tmp_path, """
import jax

def placement_report():
    return [d.platform for d in jax.devices()]
""")
    assert ids == []


def test_shard_map_body_is_hot(tmp_path):
    ids = lint_source(tmp_path, """
import jax
from jax.experimental.shard_map import shard_map

def body(x):
    return x.item()

def build(mesh, spec):
    return shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=spec)
""")
    assert ids == ["DSH101"]


def test_host_callback_bodies_are_exempt(tmp_path):
    # functions handed to pure_callback run on the HOST: numpy there is
    # the whole point, not a violation
    ids = lint_source(tmp_path, """
import jax
import numpy as np

def host_update(p):
    return np.asarray(p) * 2

@jax.jit
def step(p):
    return jax.pure_callback(
        host_update, jax.ShapeDtypeStruct(p.shape, p.dtype), p)
""")
    assert ids == []


# ---------------------------------------------------------------------------
# driver (step-cadence) rules
# ---------------------------------------------------------------------------

def test_dsh201_item_in_driver(tmp_path):
    ids = lint_source(tmp_path, """
class TrainEngine:
    def train_batch(self):
        loss = self._step_fn()
        return loss.item()
""")
    assert ids == ["DSH201"]


def test_dsh202_sync_in_loop(tmp_path):
    ids = lint_source(tmp_path, """
import jax

class TrainEngine:
    def step(self):
        out = []
        for l in self._losses:
            out.append(jax.device_get(l))
        return out
""")
    assert ids == ["DSH202"]


def test_dsh202_batched_fetch_is_clean(tmp_path):
    ids = lint_source(tmp_path, """
import jax

class TrainEngine:
    def step(self):
        return jax.device_get(list(self._losses))
""")
    assert ids == []


def test_dsh203_unbatched_syncs(tmp_path):
    ids = lint_source(tmp_path, """
import jax

class TrainEngine:
    def train_batch(self):
        loss = jax.device_get(self._loss)
        scale = jax.device_get(self._scale)
        return loss, scale
""")
    assert ids == ["DSH203"]


def test_dsh203_single_batched_sync_is_clean(tmp_path):
    ids = lint_source(tmp_path, """
import jax

class TrainEngine:
    def train_batch(self):
        loss, scale = jax.device_get((self._loss, self._scale))
        return loss, scale
""")
    assert ids == []


def test_dsh203_sees_through_sync_properties(tmp_path):
    ids = lint_source(tmp_path, """
import jax

class TrainEngine:
    @property
    def loss_scale(self):
        return float(jax.device_get(self._scale))

    def train_batch(self):
        loss = jax.device_get(self._loss)
        return loss, self.loss_scale
""")
    assert ids == ["DSH203"]


def test_dsh204_memory_stats_in_driver(tmp_path):
    # memory introspection on the per-step path: a host runtime query per
    # device per call — the watermark cadence contract the memory ledger
    # relies on (sample only at steps_per_print, via profiling.memory)
    ids = lint_source(tmp_path, """
import jax

class TrainEngine:
    def train_batch(self):
        stats = jax.local_devices()[0].memory_stats()
        return stats
""")
    assert ids == ["DSH204"]


def test_dsh204_memory_analysis_reached_through_self_call(tmp_path):
    ids = lint_source(tmp_path, """
class TrainEngine:
    def _probe(self):
        return self._compiled.memory_analysis()

    def step(self):
        return self._probe()
""")
    assert ids == ["DSH204"]


def test_dsh204_in_jit_and_clean_twin(tmp_path):
    ids = lint_source(tmp_path, """
import jax

@jax.jit
def step(x, dev):
    dev.memory_stats()
    return x
""")
    assert ids == ["DSH204"]
    # build-time recording (no driver class, not jit-reachable) is clean
    ids = lint_source(tmp_path, """
def record(compiled):
    return compiled.memory_analysis()
""")
    assert ids == []


def test_dsh205_skew_export_on_step_path(tmp_path):
    # latency/skew export called per step, no cadence guard anywhere
    ids = lint_source(tmp_path, """
from profiling.comm import publish_rank_latency

class TrainEngine:
    def train_batch(self, it):
        snap = self._ring.latency_snapshot()
        publish_rank_latency(self._run_dir, 0, snap)
""")
    assert ids == ["DSH205", "DSH205"] or ids == ["DSH205"]


def test_dsh205_guarded_export_is_clean(tmp_path):
    # the contract form: export lexically under the steps_per_print guard
    ids = lint_source(tmp_path, """
from profiling.comm import publish_rank_latency, read_fleet_latencies

class TrainEngine:
    def train_batch(self, it):
        self.global_steps += 1
        if self.global_steps % self.steps_per_print() == 0:
            snap = self._ring.latency_snapshot()
            publish_rank_latency(self._run_dir, 0, snap)
            read_fleet_latencies(self._run_dir)
""")
    assert ids == []


def test_dsh205_export_helper_reached_only_through_guard(tmp_path):
    # the engine shape: a _sample_* helper holding the export calls,
    # reachable ONLY through a steps_per_print-guarded call site
    ids = lint_source(tmp_path, """
from profiling.comm import publish_rank_latency

class TrainEngine:
    def _sample_comm_skew(self):
        snap = self._ring.latency_snapshot()
        publish_rank_latency(self._run_dir, 0, snap)

    def train_batch(self, it):
        if self.global_steps % self.steps_per_print() == 0:
            self._sample_comm_skew()
""")
    assert ids == []


def test_dsh205_helper_also_reachable_unguarded_is_flagged(tmp_path):
    # one unguarded path into the helper poisons it: per-step export
    ids = lint_source(tmp_path, """
from profiling.comm import publish_rank_latency

class TrainEngine:
    def _sample_comm_skew(self):
        publish_rank_latency(self._run_dir, 0, {})

    def train_batch(self, it):
        self._sample_comm_skew()
        if self.global_steps % self.steps_per_print() == 0:
            self._sample_comm_skew()
""")
    assert ids == ["DSH205"]


def test_dsh205_fingerprint_export_on_step_path(tmp_path):
    # PR 15: the integrity plane's fingerprint publish/read/vote APIs
    # carry the same print-cadence-only contract as the skew exchange —
    # per-step calls are flagged
    ids = lint_source(tmp_path, """
from resilience.integrity import (publish_rank_fingerprint,
                                  read_fleet_fingerprints)

class TrainEngine:
    def train_batch(self, it):
        publish_rank_fingerprint(self._run_dir, 0, self._history)
        fleet = read_fleet_fingerprints(self._run_dir)
""")
    assert ids and set(ids) == {"DSH205"}


def test_dsh205_fingerprint_vote_guarded_is_clean(tmp_path):
    # the engine's contract shape: _sample_integrity (note_fingerprint =
    # publish + read + vote) reachable only through the cadence guard;
    # the heartbeat beat() is per-step BY DESIGN and stays unflagged
    ids = lint_source(tmp_path, """
class TrainEngine:
    def _sample_integrity(self, fp):
        self._integrity.note_fingerprint(self.global_steps, fp)

    def train_batch(self, it):
        self._heartbeat.beat(self.global_steps + 1)
        if self.global_steps % self.steps_per_print() == 0:
            self._sample_integrity(0)
""")
    assert ids == []


def test_dsh205_fingerprint_vote_unguarded_helper_is_flagged(tmp_path):
    ids = lint_source(tmp_path, """
class TrainEngine:
    def _sample_integrity(self, fp):
        self._integrity.note_fingerprint(self.global_steps, fp)

    def train_batch(self, it):
        self._sample_integrity(0)
""")
    assert ids == ["DSH205"]


def test_dsh205_serving_fingerprint_unguarded_is_flagged(tmp_path):
    # PR 18: the serving plane's weight-fingerprint twin
    # (inference/resilience.py) carries the same cadence-only contract
    # — publish/read/vote per decode iteration is a host round-trip
    # multiplier on the token hot path
    ids = lint_source(tmp_path, """
from inference.resilience import (publish_weight_fingerprint,
                                  read_fleet_weight_fingerprints)

class InferenceEngine:
    def step(self):
        publish_weight_fingerprint(self._run_dir, 0, self._fp)
        fleet = read_fleet_weight_fingerprints(self._run_dir, 4)
""")
    assert ids and set(ids) == {"DSH205"}


def test_dsh205_serving_fingerprint_guarded_is_clean(tmp_path):
    # the engine's real shape: note_weight_fingerprint reachable only
    # through the steps_per_print cadence guard; the per-iteration
    # health beat stays unflagged (heartbeats are per-step by design)
    ids = lint_source(tmp_path, """
class InferenceEngine:
    def _sample_integrity(self):
        self._health.note_weight_fingerprint(self._pending)

    def step(self):
        self._health.beat(self.decode_iterations)
        if self.decode_iterations % self.steps_per_print() == 0:
            self._sample_integrity()
""")
    assert ids == []


def test_dsh205_serving_window_export_unguarded_is_flagged(tmp_path):
    # PR 19: the serving observability plane's window-close exporter
    # (occupancy/goodput/SLO gauges) carries the cadence-only contract
    # — per decode iteration it multiplies gauge writes onto the token
    # hot path.  The front-end fleet-gauge exporter is the same class
    # of call, and ServingFrontend is a driver root (Frontend marker).
    ids = lint_source(tmp_path, """
class InferenceEngine:
    def step(self):
        self.observability.export_serving_window()

class ServingFrontend:
    def step(self):
        self.export_serving_gauges()
""")
    assert ids == ["DSH205"]


def test_dsh205_serving_window_export_guarded_is_clean(tmp_path):
    # the shipped shape: the window close lives in the engine's
    # _sample_telemetry (reached only through the cadence guard), and
    # the front-end guards its gauge export lexically
    ids = lint_source(tmp_path, """
class InferenceEngine:
    def _sample_telemetry(self):
        self.observability.export_serving_window()

    def step(self):
        if self.decode_iterations % self.steps_per_print() == 0:
            self._sample_telemetry()

class ServingFrontend:
    def step(self):
        self._steps += 1
        if self._steps % self.steps_per_print == 0:
            self.export_serving_gauges()
""")
    assert ids == []


def test_non_engine_class_is_not_driver_scope(tmp_path):
    # benchmarks/profilers sync deliberately; only Engine/Scaler/
    # Frontend classes carry step-cadence semantics
    ids = lint_source(tmp_path, """
import jax

class Prober:
    def step(self):
        a = jax.device_get(self._a)
        b = jax.device_get(self._b)
        return a, b
""")
    assert ids == []


# ---------------------------------------------------------------------------
# retrace rules
# ---------------------------------------------------------------------------

def test_dsr301_mutable_default(tmp_path):
    ids = lint_source(tmp_path, """
import jax

@jax.jit
def step(x, extra={}):
    return x
""")
    assert ids == ["DSR301"]


def test_dsr301_none_default_is_clean(tmp_path):
    ids = lint_source(tmp_path, """
import jax

@jax.jit
def step(x, extra=None):
    return x
""")
    assert ids == []


def test_dsr302_static_argnums_out_of_range(tmp_path):
    ids = lint_source(tmp_path, """
import jax

def step(x, spec):
    return x

step_fn = jax.jit(step, static_argnums=(5,))
""")
    assert ids == ["DSR302"]


def test_dsr302_unhashable_static_default(tmp_path):
    ids = lint_source(tmp_path, """
import jax

def step(x, spec=[1, 2]):
    return x

step_fn = jax.jit(step, static_argnums=(1,))
""")
    assert "DSR302" in ids


def test_dsr302_static_argnames_unknown(tmp_path):
    ids = lint_source(tmp_path, """
import jax

def step(x, spec):
    return x

step_fn = jax.jit(step, static_argnames=("sepc",))
""")
    assert ids == ["DSR302"]


def test_dsr302_hashable_static_is_clean(tmp_path):
    ids = lint_source(tmp_path, """
import jax

def step(x, spec):
    return x

step_fn = jax.jit(step, static_argnums=(1,))
""")
    assert ids == []


def test_dsr303_global_and_module_rng(tmp_path):
    ids = lint_source(tmp_path, """
import jax
import numpy as np

@jax.jit
def step(x):
    global COUNT
    COUNT = 1
    return x + np.random.rand()
""")
    assert ids == ["DSR303"]


def test_dsr303_self_mutation_in_trace(tmp_path):
    ids = lint_source(tmp_path, """
import jax

class Model:
    @jax.jit
    def step(self, x):
        self.cache = x
        return x
""")
    assert ids == ["DSR303"]


def test_dsr303_threaded_state_is_clean(tmp_path):
    ids = lint_source(tmp_path, """
import jax

@jax.jit
def step(x, rng):
    noise = jax.random.normal(rng, x.shape)
    return x + noise, jax.random.split(rng)[0]
""")
    assert ids == []


def test_dsr304_branch_on_traced_arg(tmp_path):
    ids = lint_source(tmp_path, """
import jax

@jax.jit
def step(x):
    if x:
        return x + 1
    return x
""")
    assert ids == ["DSR304"]


def test_dsr304_jnp_where_is_clean(tmp_path):
    ids = lint_source(tmp_path, """
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    return jnp.where(x > 0, x + 1, x)
""")
    assert ids == []


def test_dsr305_unbucketed_decode_loop(tmp_path):
    # the decode-loop bug: the per-request context grows every
    # iteration and reaches the jitted step as a fresh-shaped array, so
    # the serve retraces once per token
    ids = lint_source(tmp_path, """
import jax
import jax.numpy as jnp

def decode(params, ids):
    return ids.sum()

step = jax.jit(decode)

def serve(params, prompt, n):
    ids = list(prompt)
    for _ in range(n):
        nxt = step(params, jnp.asarray(ids))
        ids.append(int(nxt))
    return ids
""")
    assert ids == ["DSR305"]


def test_dsr305_bucketed_twin_is_clean(tmp_path):
    # identical loop, but the length is normalized to a declared bucket
    # before the boundary — the fix the rule's autofix hint names
    ids = lint_source(tmp_path, """
import jax
import jax.numpy as jnp

def decode(params, ids):
    return ids.sum()

step = jax.jit(decode)

def pad_to_bucket(ids, bucket=64):
    return ids + [0] * (bucket - len(ids))

def serve(params, prompt, n):
    ids = list(prompt)
    for _ in range(n):
        nxt = step(params, jnp.asarray(pad_to_bucket(ids)))
        ids.append(int(nxt))
    return ids
""")
    assert ids == []


def test_dsr305_tainted_name_fires(tmp_path):
    # two-step form: the unbucketed array lands in a local first
    ids = lint_source(tmp_path, """
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    return x.sum()

def serve(prompt, n):
    ids = list(prompt)
    for _ in range(n):
        batch = jnp.asarray(ids)
        nxt = step(batch)
        ids.append(int(nxt))
    return ids
""")
    assert ids == ["DSR305"]


def test_dsr305_non_jit_callee_is_clean(tmp_path):
    # the naive reference loop is allowed: model.logits is not an
    # in-module jit boundary, so growing the context only costs the
    # reference (which exists to be slow), not a compiled program
    ids = lint_source(tmp_path, """
import jax.numpy as jnp

def serve(model, params, prompt, n):
    ids = list(prompt)
    for _ in range(n):
        logits = model.logits(params, jnp.asarray([ids]))
        ids.append(int(logits.argmax()))
    return ids
""")
    assert ids == []


def test_dsr305_loop_invariant_array_is_clean(tmp_path):
    # arrays built in the loop from loop-INVARIANT data keep one shape
    ids = lint_source(tmp_path, """
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    return x.sum()

def serve(prompt, n):
    out = []
    for _ in range(n):
        out.append(float(step(jnp.asarray(prompt))))
    return out
""")
    assert ids == []


# ---------------------------------------------------------------------------
# pragmas
# ---------------------------------------------------------------------------

def test_inline_pragma_suppresses(tmp_path):
    path = tmp_path / "snippet.py"
    path.write_text("""
import jax

@jax.jit
def step(x):
    return x.item()  # dslint: disable=DSH101 -- fixture
""")
    diags = lint_paths([str(path)])
    assert failing(diags) == []
    assert [d.rule_id for d in diags if d.suppressed] == ["DSH101"]


def test_standalone_pragma_covers_next_line(tmp_path):
    path = tmp_path / "snippet.py"
    path.write_text("""
import jax

@jax.jit
def step(x):
    # dslint: disable=DSH101
    return x.item()
""")
    assert failing(lint_paths([str(path)])) == []


def test_pragma_for_other_rule_does_not_suppress(tmp_path):
    ids = lint_source(tmp_path, """
import jax

@jax.jit
def step(x):
    return x.item()  # dslint: disable=DSH104
""")
    assert ids == ["DSH101"]


# ---------------------------------------------------------------------------
# config schema: extraction, validation, dead keys
# ---------------------------------------------------------------------------

def test_schema_is_nonempty_and_typed():
    schema = extract_schema()
    assert len(schema.all_keys()) > 60
    top = schema.top_level
    assert "train_batch_size" in top
    assert "gradient_accumulation_steps" in top
    zero = schema.sections["zero_optimization"]
    assert "stage" in zero and "cpu_offload" in zero
    assert zero["stage"].has_default and zero["stage"].default == 0
    fp16 = schema.sections["fp16"]
    assert fp16["loss_scale_window"].default == 1000
    assert "keep_last_n" in schema.sections["checkpoint"]
    assert "partition_activations" in schema.sections[
        "activation_checkpointing"]
    assert "enabled" in schema.sections["flops_profiler"]
    assert "micro_batch_sizes" in schema.sections["elasticity"]


def test_validator_did_you_mean():
    issues = validate_config_dict(
        {"train_batch_size": 8, "gradient_acumulation_steps": 2})
    assert len(issues) == 1
    assert issues[0].suggestion == "gradient_accumulation_steps"
    assert "did you mean 'gradient_accumulation_steps'" in issues[0].message


def test_validator_section_typo():
    issues = validate_config_dict(
        {"zero_optimization": {"stage": 2, "cpu_offlaod": True}})
    assert [i.section for i in issues] == ["zero_optimization"]
    assert issues[0].suggestion == "cpu_offload"


def test_validator_round_trips_known_good_configs():
    # the configs exercised by tests/unit/test_config.py (and the README
    # quick start) must validate clean
    good_configs = [
        {"train_batch_size": 32, "train_micro_batch_size_per_gpu": 16,
         "gradient_accumulation_steps": 1},
        {"train_batch_size": 8, "bf16": {"enabled": True},
         "zero_optimization": {"stage": 2, "cpu_offload": True}},
        {"train_batch_size": 8, "fp16": {
            "enabled": True, "initial_scale_power": 16,
            "loss_scale_window": 500, "hysteresis": 4,
            "min_loss_scale": 0.5}},
        {"train_batch_size": 8,
         "optimizer": {"type": "Adam", "params": {"lr": 0.001}},
         "scheduler": {"type": "WarmupLR",
                       "params": {"warmup_num_steps": 10}}},
        {"train_batch_size": 8, "sparse_attention": {
            "mode": "fixed", "block": 32, "num_local_blocks": 8}},
        {"train_batch_size": 2, "steps_per_print": 10 ** 9, "seed": 1,
         "mesh": {"data": 1}, "pipeline": {"stages": 2},
         "checkpoint": {"async_save": True, "keep_last_n": 3},
         "zero_allow_untested_optimizer": True, "strict_config": True},
    ]
    for cfg in good_configs:
        assert validate_config_dict(cfg) == [], cfg


def test_validator_nested_sub_block_typo():
    """One-level-nested sub-blocks (zero_optimization.offload_state_dtype)
    are schema'd from their ZERO_OFFLOAD_STATE_DTYPE_* constants and
    validated one level deeper than plain sections."""
    schema = extract_schema()
    assert ("zero_optimization", "offload_state_dtype") in schema.nested
    nested = schema.nested[("zero_optimization", "offload_state_dtype")]
    assert {"master", "momentum", "variance", "error_feedback",
            "rounding", "seed"} <= set(nested)

    issues = validate_config_dict(
        {"zero_optimization": {"stage": 2, "cpu_offload": True,
                               "offload_state_dtype": {
                                   "momentum": "bf16",
                                   "varience": "bf16"}}})
    assert len(issues) == 1
    assert issues[0].section == "zero_optimization.offload_state_dtype"
    assert issues[0].suggestion == "variance"


def test_validator_nested_sub_block_accepts_good_forms():
    # dict form, shorthand string form, and absence all validate clean
    for zo in ({"stage": 2, "cpu_offload": True,
                "offload_state_dtype": {"momentum": "bf16",
                                        "variance": "bf16",
                                        "master": "bf16",
                                        "error_feedback": True,
                                        "rounding": "stochastic",
                                        "seed": 7}},
               {"stage": 2, "cpu_offload": True,
                "offload_state_dtype": "bf16"},
               {"stage": 2}):
        assert validate_config_dict({"zero_optimization": zo}) == [], zo


def test_validator_skips_freeform_params():
    issues = validate_config_dict({
        "optimizer": {"type": "Adam",
                      "params": {"lr": 1e-3, "exotic_knob": 7}}})
    assert issues == []


def test_dead_key_detection(tmp_path):
    pkg = tmp_path / "runtime"
    pkg.mkdir()
    (pkg / "constants.py").write_text(
        'USED = "used_key"\nUSED_DEFAULT = 1\n'
        'DEAD = "dead_key"\nDEAD_DEFAULT = 2\n'
        'SUPPRESSED = "ok"  # dslint: disable=DSC401\n')
    (pkg / "config.py").write_text(
        "from . import constants as C\nx = C.USED\n")
    diags = lint_paths([str(tmp_path)])
    dead = [d for d in diags if d.rule_id == "DSC401"]
    assert [("DEAD" in d.message, d.suppressed) for d in dead] == [
        (True, False), (False, True)]
    assert len(failing(diags)) == 1


def test_strict_config_raises_in_deepspeed_config():
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)

    with pytest.raises(DeepSpeedConfigError,
                       match="gradient_accumulation_steps"):
        DeepSpeedConfig({"train_batch_size": 8,
                         "gradient_acumulation_steps": 2,
                         "strict_config": True}, world_size=1)
    # warn-by-default: same typo parses (and silently defaults, which is
    # exactly what the warning reports)
    cfg = DeepSpeedConfig({"train_batch_size": 8,
                           "gradient_acumulation_steps": 2}, world_size=1)
    assert cfg.gradient_accumulation_steps == 1


def test_amp_key_is_now_wired():
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)

    with pytest.raises(DeepSpeedConfigError, match="amp"):
        DeepSpeedConfig({"train_batch_size": 8,
                         "amp": {"enabled": True}}, world_size=1)
    cfg = DeepSpeedConfig({"train_batch_size": 8,
                           "ring_attention": {"enabled": True}},
                          world_size=1)
    assert cfg.ring_attention_enabled
    assert cfg.allgather_size == 500000000


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_exit_codes_and_json_report(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n")
    clean = tmp_path / "clean.py"
    clean.write_text("import jax\n\n@jax.jit\ndef f(x):\n    return x + 1\n")
    report = tmp_path / "report.json"

    assert dslint_main([str(clean)]) == 0
    assert dslint_main([str(bad), "--json", str(report)]) == 1
    data = json.loads(report.read_text())
    assert data["violations"] == 1
    assert data["diagnostics"][0]["rule"] == "DSH101"
    assert data["diagnostics"][0]["line"] == 5
    assert data["schema_keys"] > 60
    assert dslint_main([str(bad), "--ignore", "DSH101"]) == 0
    assert dslint_main(["--list-rules"]) == 0


def test_cli_validates_config_files(tmp_path):
    bad_cfg = tmp_path / "ds_config.json"
    bad_cfg.write_text(json.dumps(
        {"train_batch_size": 8, "gradient_acumulation_steps": 2}))
    good_cfg = tmp_path / "good.json"
    good_cfg.write_text(json.dumps(
        {"train_batch_size": 8, "bf16": {"enabled": True}}))
    assert dslint_main(["--config", str(good_cfg)]) == 0
    assert dslint_main(["--config", str(bad_cfg)]) == 1


def test_every_rule_id_is_documented():
    for rule in RULES.values():
        assert rule.summary and rule.rationale, rule.id
        assert rule.id[:3] in ("DSH", "DSR", "DSC", "DSE", "DSP", "DSO", "DSS")


# ---------------------------------------------------------------------------
# robustness rules (DSE5xx: swallowed failures)
# ---------------------------------------------------------------------------

def test_dse501_bare_except(tmp_path):
    ids = lint_source(tmp_path, """
def load(path):
    try:
        return open(path).read()
    except:
        return None
""")
    assert ids == ["DSE501"]


def test_dse501_clean_twin_named_type(tmp_path):
    ids = lint_source(tmp_path, """
def load(path):
    try:
        return open(path).read()
    except OSError:
        return None
""")
    assert ids == []


def test_dse502_except_exception_pass(tmp_path):
    ids = lint_source(tmp_path, """
def probe():
    try:
        risky()
    except Exception:
        pass
""")
    assert ids == ["DSE502"]


def test_dse502_bare_except_pass_flags_both(tmp_path):
    ids = lint_source(tmp_path, """
def probe():
    try:
        risky()
    except:
        ...
""")
    assert ids == ["DSE501", "DSE502"]


def test_dse502_tuple_type_and_baseexception(tmp_path):
    ids = lint_source(tmp_path, """
def probe():
    try:
        risky()
    except (ValueError, Exception):
        pass

def probe2():
    try:
        risky()
    except BaseException:
        pass
""")
    assert ids == ["DSE502"]


def test_dse502_clean_twins(tmp_path):
    # logging, re-raising, returning a sentinel, or narrowing the type
    # are all legitimate handler bodies
    ids = lint_source(tmp_path, """
import logging

def handled():
    try:
        risky()
    except Exception as e:
        logging.warning("probe failed: %s", e)

def reraised():
    try:
        risky()
    except Exception:
        raise RuntimeError("context")

def sentinel():
    try:
        return risky()
    except Exception:
        return None

def narrow():
    try:
        risky()
    except KeyError:
        pass
""")
    assert ids == []


def test_dse502_pragma_suppression(tmp_path):
    from deepspeed_tpu.tools.dslint import lint_paths as lp

    path = tmp_path / "snippet.py"
    path.write_text("""
def probe():
    try:
        risky()
    except Exception:  # dslint: disable=DSE502 -- optional backend probe
        pass
""")
    diags = lp([str(path)])
    assert not failing(diags)
    assert any(d.suppressed and d.rule_id == "DSE502" for d in diags)


# ---------------------------------------------------------------------------
# CLI: --json schema_version, exit codes, baseline ratchet
# ---------------------------------------------------------------------------

_VIOLATION_SRC = """
import jax

@jax.jit
def step(x):
    return x.item()
"""


def test_json_report_has_stable_schema_version(tmp_path):
    path = tmp_path / "clean.py"
    path.write_text("x = 1\n")
    out = tmp_path / "report.json"
    assert dslint_main([str(path), "--json", str(out)]) == 0
    report = json.loads(out.read_text())
    from deepspeed_tpu.tools.dslint.cli import JSON_SCHEMA_VERSION

    assert report["schema_version"] == JSON_SCHEMA_VERSION == 1
    assert report["violations"] == 0
    assert report["violations_by_family"] == {}
    assert report["suppressed_by_family"] == {}
    assert report["baselined"] == 0


def test_json_report_per_family_counts(tmp_path):
    (tmp_path / "bad.py").write_text(_VIOLATION_SRC)
    (tmp_path / "sup.py").write_text("""
def probe():
    try:
        risky()
    except Exception:  # dslint: disable=DSE502 -- optional probe
        pass
""")
    out = tmp_path / "report.json"
    assert dslint_main([str(tmp_path), "--json", str(out)]) == 1
    report = json.loads(out.read_text())
    assert report["violations_by_family"] == {"DSH1": 1}
    assert report["suppressed_by_family"] == {"DSE5": 1}


def test_cli_exit_2_on_non_utf8_source(tmp_path, capsys):
    """An unreadable/non-UTF8 source file is a usage error (exit 2),
    never a traceback."""
    bad = tmp_path / "latin.py"
    bad.write_bytes(b"# caf\xe9\nx = 1\n")       # invalid UTF-8
    assert dslint_main([str(bad)]) == 2
    err = capsys.readouterr().err
    assert "cannot read" in err and "latin.py" in err
    # the API surface raises the typed error rather than crashing
    from deepspeed_tpu.tools.dslint import SourceReadError, lint_paths as lp

    with pytest.raises(SourceReadError):
        lp([str(bad)])


def test_cli_exit_2_on_unreadable_file(tmp_path, capsys):
    import os
    import stat

    locked = tmp_path / "locked.py"
    locked.write_text("x = 1\n")
    locked.chmod(0)
    if os.access(str(locked), os.R_OK):      # running as root: chmod 0
        locked.chmod(stat.S_IWUSR)           # is a no-op; skip gracefully
        if os.access(str(locked), os.R_OK):
            pytest.skip("cannot make file unreadable (running as root)")
    try:
        assert dslint_main([str(locked)]) == 2
        assert "cannot read" in capsys.readouterr().err
    finally:
        locked.chmod(stat.S_IRUSR | stat.S_IWUSR)


def test_baseline_ratchet_fails_only_new_violations(tmp_path, capsys):
    """The satellite contract: known violations recorded in the
    checked-in baseline stop failing CI; only NEW ones do."""
    src = tmp_path / "legacy.py"
    src.write_text(_VIOLATION_SRC)
    baseline = tmp_path / "baseline.json"

    # record the current state: exit 0, violations captured
    assert dslint_main([str(src), "--baseline", str(baseline),
                        "--update-baseline"]) == 0
    data = json.loads(baseline.read_text())
    assert data["schema_version"] == 1
    assert len(data["violations"]) == 1
    assert all(v == 1 for v in data["violations"].values())

    # unchanged tree: baselined, exit 0
    assert dslint_main([str(src), "--baseline", str(baseline)]) == 0
    assert "1 baselined" in capsys.readouterr().out

    # a NEW violation (even of an already-baselined rule) fails
    src.write_text(_VIOLATION_SRC + """

@jax.jit
def second(x):
    return x.tolist()
""")
    assert dslint_main([str(src), "--baseline", str(baseline)]) == 1
    out = capsys.readouterr().out
    assert "1 violation(s), 0 suppressed, 1 baselined" in out

    # fixing the legacy violation keeps passing (stale baseline entries
    # are inert, not errors)
    src.write_text("x = 1\n")
    assert dslint_main([str(src), "--baseline", str(baseline)]) == 0


def test_baseline_missing_file_exits_2(tmp_path, capsys):
    src = tmp_path / "a.py"
    src.write_text("x = 1\n")
    assert dslint_main([str(src), "--baseline",
                        str(tmp_path / "nope.json")]) == 2
    assert "baseline" in capsys.readouterr().err
    # --update-baseline without --baseline is a usage error too
    assert dslint_main([str(src), "--update-baseline"]) == 2


def test_baseline_counts_are_multisets(tmp_path):
    """Two identical-message violations at different lines: baselining
    one occurrence must not absolve the second."""
    from deepspeed_tpu.tools.dslint.cli import (apply_baseline,
                                                baseline_key,
                                                load_baseline,
                                                write_baseline)
    from deepspeed_tpu.tools.dslint import lint_paths as lp

    src = tmp_path / "dup.py"
    src.write_text(_VIOLATION_SRC)
    one = failing(lp([str(src)]))
    assert len(one) == 1
    path = tmp_path / "b.json"
    write_baseline(path, one)
    base = load_baseline(path)
    new, baselined = apply_baseline(one + one, base)   # second instance
    assert baselined == 1 and len(new) == 1
    assert baseline_key(new[0]) == baseline_key(one[0])


def test_baseline_malformed_file_exits_2(tmp_path, capsys):
    src = tmp_path / "a.py"
    src.write_text("x = 1\n")
    bad = tmp_path / "b.json"
    bad.write_text('{"schema_version": 1, "violations": [1, 2]}')
    assert dslint_main([str(src), "--baseline", str(bad)]) == 2
    assert "must be an object" in capsys.readouterr().err
    bad.write_text('{"violations": {"k": null}}')
    assert dslint_main([str(src), "--baseline", str(bad)]) == 2
    assert "integers" in capsys.readouterr().err
