"""Fast CPU-tier tests for the async fault-tolerant checkpoint subsystem
(``deepspeed_tpu/checkpoint``): atomic commit protocol, crash-mid-save
recovery, retention, retry, async-vs-sync bit-identity, native-dtype model
states, and the elastic DP-degree restore through the manager path."""

import json
import os
import threading
import time

import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu import checkpoint as ckpt
from deepspeed_tpu.checkpoint import writer as ckpt_writer
from deepspeed_tpu.checkpoint.config import DeepSpeedCheckpointConfig
from deepspeed_tpu.checkpoint.manager import CheckpointManager
from deepspeed_tpu.checkpoint.snapshot import CheckpointSnapshot
from deepspeed_tpu.parallel import make_mesh

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 16


# ---------------------------------------------------------------- helpers
def fake_snapshot(step, payload=None, tag=None, save_latest=True):
    """Engine-free snapshot for writer/manager-level tests."""
    arr = np.full((4, 4), float(step), np.float32)
    return CheckpointSnapshot(
        tag=tag or f"global_step{step}",
        model_states={"w": payload if payload is not None else arr},
        model_dtypes={},
        optim_states={"master": arr.reshape(-1)},
        meta={"global_steps": step},
        save_latest=save_latest)


def manager(**overrides):
    cfg = DeepSpeedCheckpointConfig(
        {"checkpoint": dict({"save_retries": 0, "retry_backoff_secs": 0.0},
                            **overrides)})
    return CheckpointManager(cfg)


def make_engine(config, cpu_devices, dp=4, seed=0):
    mesh = make_mesh({"data": dp}, devices=cpu_devices[:dp])
    model = SimpleModel(HIDDEN, nlayers=2)
    engine, *_ = deepspeed.initialize(model=model, config=config, mesh=mesh)
    return engine


def run_steps(engine, batches):
    return [float(np.asarray(engine.train_batch(iter([b]))))
            for b in batches]


@pytest.fixture
def no_hook():
    yield
    ckpt_writer._file_written_hook = None


# ------------------------------------------------------- commit protocol
def test_atomic_commit_layout_and_verify(tmp_path):
    m = manager()
    assert m.save(fake_snapshot(1), str(tmp_path), async_save=False)
    tag_dir = tmp_path / "global_step1"
    assert sorted(os.listdir(tag_dir)) == [
        "manifest.json", "meta.json", "model_states.npz",
        "zero_optim_states.npz"]
    assert ckpt.read_latest(str(tmp_path)) == "global_step1"
    status, problems = ckpt.verify_checkpoint(str(tag_dir))
    assert status == "ok" and not problems
    manifest = ckpt.read_manifest(str(tag_dir))
    assert manifest["global_steps"] == 1
    for entry in manifest["files"].values():
        assert entry["bytes"] > 0 and "checksum" in entry


def test_verify_flags_corruption(tmp_path):
    m = manager()
    m.save(fake_snapshot(1), str(tmp_path), async_save=False)
    victim = tmp_path / "global_step1" / "model_states.npz"
    data = bytearray(victim.read_bytes())
    data[-1] ^= 0xFF
    victim.write_bytes(bytes(data))
    status, problems = ckpt.verify_checkpoint(str(tmp_path / "global_step1"))
    assert status == "bad" and any("checksum" in p for p in problems)


def test_crash_mid_save_preserves_previous(tmp_path, no_hook):
    """Kill the writer between npz files: `latest` must still resolve to
    the previous complete checkpoint and the torn tmp dir must be flagged
    by verification, never loadable."""
    m = manager()
    assert m.save(fake_snapshot(1), str(tmp_path), async_save=False)

    def die_after_first_file(tmp_dir, name):
        if name == ckpt.MODEL_STATES_NPZ:
            raise OSError("simulated crash mid-save")

    ckpt_writer._file_written_hook = die_after_first_file
    assert not m.save(fake_snapshot(2), str(tmp_path), async_save=False)
    ckpt_writer._file_written_hook = None

    assert ckpt.read_latest(str(tmp_path)) == "global_step1"
    torn = tmp_path / "global_step2.tmp"
    assert torn.is_dir()  # half-written, never committed
    status, _ = ckpt.verify_checkpoint(str(torn))
    assert status == "bad"
    assert not (tmp_path / "global_step2").exists()
    # the next successful save sweeps the torn leftovers
    assert m.save(fake_snapshot(3), str(tmp_path), async_save=False)
    assert not torn.exists()
    assert ckpt.read_latest(str(tmp_path)) == "global_step3"


def test_interrupted_resave_recovers_from_old_dir(tmp_path, cpu_devices):
    """A crash between the two renames of a same-tag re-save leaves only
    <tag>.old; the loader heals it, and retention sweeps superseded .old
    dirs instead of counting them as checkpoints."""
    m = manager()
    assert m.save(fake_snapshot(1), str(tmp_path), async_save=False)
    # simulate the crash window: final dir parked aside, new commit lost
    os.replace(str(tmp_path / "global_step1"),
               str(tmp_path / "global_step1.old"))

    assert ckpt.recover_tag(str(tmp_path), "global_step1")
    assert ckpt.verify_checkpoint(str(tmp_path / "global_step1"))[0] == "ok"
    assert not (tmp_path / "global_step1.old").exists()

    # engine loader does the same healing implicitly
    e = make_engine(base_config(), cpu_devices)
    run_steps(e, random_batches(1, 16, HIDDEN, seed=3))
    e.save_checkpoint(str(tmp_path), sync=True)
    os.replace(str(tmp_path / "global_step1"),
               str(tmp_path / "global_step1.old"))
    path, _ = e.load_checkpoint(str(tmp_path), tag="global_step1")
    assert path is not None and path.endswith("global_step1")

    # a superseded .old (final dir still present) is swept by retention,
    # never listed as a committed checkpoint
    import shutil

    shutil.copytree(str(tmp_path / "global_step1"),
                    str(tmp_path / "global_step1.old"))
    m2 = manager(keep_last_n=1)
    assert m2.save(fake_snapshot(2), str(tmp_path), async_save=False)
    assert not (tmp_path / "global_step1.old").exists()


def test_save_retry_with_backoff(tmp_path, no_hook):
    fails = {"left": 2}

    def flaky(tmp_dir, name):
        if name == ckpt.META_JSON and fails["left"] > 0:
            fails["left"] -= 1
            raise OSError("transient I/O error")

    ckpt_writer._file_written_hook = flaky
    m = manager(save_retries=2, retry_backoff_secs=0.0)
    assert m.save(fake_snapshot(5), str(tmp_path), async_save=False)
    assert fails["left"] == 0
    assert ckpt.verify_checkpoint(str(tmp_path / "global_step5"))[0] == "ok"


def test_retention_keep_last_n_and_every_n(tmp_path):
    m = manager(keep_last_n=2, keep_every_n_steps=4)
    for step in range(1, 7):
        assert m.save(fake_snapshot(step), str(tmp_path), async_save=False)
    kept = sorted(p for p in os.listdir(tmp_path)
                  if (tmp_path / p).is_dir())
    # last 2 (steps 5, 6) + every multiple of 4 (step 4)
    assert kept == ["global_step4", "global_step5", "global_step6"]
    assert ckpt.read_latest(str(tmp_path)) == "global_step6"


def test_retention_never_prunes_foreign_dirs(tmp_path):
    (tmp_path / "not_a_checkpoint").mkdir()
    (tmp_path / "not_a_checkpoint" / "data.txt").write_text("keep me")
    m = manager(keep_last_n=1)
    for step in (1, 2):
        m.save(fake_snapshot(step), str(tmp_path), async_save=False)
    assert (tmp_path / "not_a_checkpoint" / "data.txt").exists()
    assert not (tmp_path / "global_step1").exists()


def test_save_latest_false_does_not_pin_pointer(tmp_path):
    """An archival save_latest=False commit at a high step must not pin
    the monotonic guard: later lower-step saves that DO want `latest`
    moved still move it."""
    m = manager()
    assert m.save(fake_snapshot(10), str(tmp_path), async_save=False)
    assert m.save(fake_snapshot(100, tag="archive100", save_latest=False),
                  str(tmp_path), async_save=False)
    assert ckpt.read_latest(str(tmp_path)) == "global_step10"
    assert m.save(fake_snapshot(11), str(tmp_path), async_save=False)
    assert ckpt.read_latest(str(tmp_path)) == "global_step11"


def test_verify_uses_manifest_checksum_algorithm(tmp_path):
    """A crc32 manifest must verify with crc32 even on a host whose
    preferred local algorithm is crc32c (cross-host portability)."""
    m = manager()
    m.save(fake_snapshot(1), str(tmp_path), async_save=False)
    tag_dir = tmp_path / "global_step1"
    manifest = ckpt.read_manifest(str(tag_dir))
    algo = manifest["checksum_algorithm"]
    for name, entry in manifest["files"].items():
        assert entry["checksum"] == ckpt_writer.file_checksum(
            str(tag_dir / name), algorithm=algo)
    # an algorithm we don't have degrades to sizes-only, still "ok"
    manifest["checksum_algorithm"] = "xxh3"
    (tag_dir / ckpt.MANIFEST_JSON).write_text(json.dumps(manifest))
    status, problems = ckpt.verify_checkpoint(str(tag_dir))
    assert status == "ok" and not problems


def test_legacy_dir_without_manifest_is_loadable(tmp_path):
    """Pre-manifest checkpoints (meta.json only) verify as 'legacy'."""
    legacy = tmp_path / "global_step9"
    legacy.mkdir()
    (legacy / "meta.json").write_text(json.dumps({"global_steps": 9}))
    status, problems = ckpt.verify_checkpoint(str(legacy))
    assert status == "legacy" and not problems


# ------------------------------------------------------------ engine level
def test_async_save_matches_sync_bit_identical(cpu_devices, tmp_path):
    """A committed async checkpoint restores bit-identically to a
    synchronous save of the same step."""
    config = base_config(zero_optimization={"stage": 2})
    e = make_engine(config, cpu_devices)
    run_steps(e, random_batches(3, 16, HIDDEN, seed=5))

    sync_dir, async_dir = str(tmp_path / "sync"), str(tmp_path / "async")
    e.save_checkpoint(sync_dir, sync=True)
    e.save_checkpoint(async_dir)          # async by default
    e.wait_checkpoint(async_dir)

    for name in (ckpt.MODEL_STATES_NPZ, ckpt.OPTIM_STATES_NPZ):
        a = np.load(os.path.join(sync_dir, "global_step3", name))
        b = np.load(os.path.join(async_dir, "global_step3", name))
        assert sorted(a.files) == sorted(b.files)
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k], err_msg=f"{name}:{k}")


def test_train_batch_overlaps_inflight_save(cpu_devices, tmp_path, no_hook):
    """The acceptance gate: train_batch completes a full update while a
    checkpoint write is still in flight."""
    gate = threading.Event()
    blocked = threading.Event()

    def block_writer(tmp_dir, name):
        if name == ckpt.OPTIM_STATES_NPZ:
            blocked.set()
            assert gate.wait(timeout=60), "test deadlock"

    config = base_config(zero_optimization={"stage": 1})
    e = make_engine(config, cpu_devices)
    batches = random_batches(4, 16, HIDDEN, seed=9)
    ref = run_steps(e, batches[:2])

    ckpt_writer._file_written_hook = block_writer
    try:
        e.save_checkpoint(str(tmp_path))
        assert blocked.wait(timeout=60), "writer thread never started"
        # writer is parked mid-checkpoint; a full optimizer update runs
        loss = run_steps(e, batches[2:3])[0]
        assert np.isfinite(loss)
        assert e.global_steps == 3
        assert ckpt.read_latest(str(tmp_path)) is None  # not committed yet
    finally:
        gate.set()
        ckpt_writer._file_written_hook = None
    e.wait_checkpoint(str(tmp_path))
    assert ckpt.read_latest(str(tmp_path)) == "global_step2"

    # the in-flight snapshot was immutable: restoring it replays step 3
    e2 = make_engine(config, cpu_devices)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None and e2.global_steps == 2
    np.testing.assert_allclose(run_steps(e2, batches[2:3])[0], loss,
                               rtol=1e-6)
    del ref


def test_load_waits_for_inflight_save_same_process(cpu_devices, tmp_path,
                                                   no_hook):
    """A different engine in the same process loading the same dir drains
    the in-flight save instead of racing it."""
    gate = threading.Event()

    def slow_writer(tmp_dir, name):
        if name == ckpt.META_JSON:
            gate.wait(timeout=60)

    config = base_config()
    e = make_engine(config, cpu_devices)
    run_steps(e, random_batches(2, 16, HIDDEN, seed=2))
    ckpt_writer._file_written_hook = slow_writer
    try:
        e.save_checkpoint(str(tmp_path))
        threading.Timer(0.2, gate.set).start()
        e2 = make_engine(config, cpu_devices)
        path, _ = e2.load_checkpoint(str(tmp_path))  # drains, then loads
        assert path is not None and e2.global_steps == 2
    finally:
        gate.set()
        ckpt_writer._file_written_hook = None


def test_strict_load_raises(cpu_devices, tmp_path):
    e = make_engine(base_config(), cpu_devices)
    with pytest.raises(ckpt.CheckpointError, match="latest"):
        e.load_checkpoint(str(tmp_path), strict=True)
    # non-strict keeps the reference warn-and-continue contract
    assert e.load_checkpoint(str(tmp_path)) == (None, None)


def test_missing_meta_rejected_not_raised(cpu_devices, tmp_path):
    """A tag dir without meta.json must be rejected up front, not blow up
    mid-restore with FileNotFoundError."""
    (tmp_path / "sometag").mkdir()
    e = make_engine(base_config(), cpu_devices)
    assert e.load_checkpoint(str(tmp_path), tag="sometag") == (None, None)
    with pytest.raises(ckpt.CheckpointError, match="meta.json"):
        e.load_checkpoint(str(tmp_path), tag="sometag", strict=True)


def test_verify_on_load_rejects_corrupt_checkpoint(cpu_devices, tmp_path):
    config = base_config()
    e = make_engine(config, cpu_devices)
    run_steps(e, random_batches(2, 16, HIDDEN, seed=1))
    e.save_checkpoint(str(tmp_path), sync=True)
    victim = tmp_path / "global_step2" / ckpt.OPTIM_STATES_NPZ
    data = bytearray(victim.read_bytes())
    data[len(data) // 2] ^= 0xFF
    victim.write_bytes(bytes(data))

    e2 = make_engine(config, cpu_devices)
    assert e2.load_checkpoint(str(tmp_path)) == (None, None)
    # corruption raises the dedicated subclass so callers can distinguish
    # "corrupt, fail hard" from "missing, start fresh"
    with pytest.raises(ckpt.CheckpointCorruptionError, match="integrity"):
        e2.load_checkpoint(str(tmp_path), strict=True)


def test_native_dtype_model_states(cpu_devices, tmp_path):
    """bf16 runs save bf16 model states (half the bytes of the old forced
    fp32) with the dtype recorded; the typed loader restores them."""
    config = base_config(zero_optimization={"stage": 1},
                         bf16={"enabled": True})
    e = make_engine(config, cpu_devices)
    run_steps(e, random_batches(2, 16, HIDDEN, seed=4))
    e.save_checkpoint(str(tmp_path), sync=True)

    tag_dir = str(tmp_path / "global_step2")
    with open(os.path.join(tag_dir, ckpt.META_JSON)) as f:
        meta = json.load(f)
    assert meta["model_dtypes"], "bf16 params must be recorded in the map"
    assert all(v == "bfloat16" for v in meta["model_dtypes"].values())
    states = ckpt.load_model_states(tag_dir)
    import ml_dtypes

    for key in meta["model_dtypes"]:
        assert states[key].dtype == np.dtype(ml_dtypes.bfloat16)
    # and a bf16-saved checkpoint restores exactly (load path uses the
    # fp32 master, so precision is untouched)
    e2 = make_engine(config, cpu_devices)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None
    np.testing.assert_array_equal(np.asarray(e2.get_master_params()),
                                  np.asarray(e.get_master_params()))


def test_fp32_checkpoint_loads_into_bf16_run(cpu_devices, tmp_path):
    """Old-style fp32 model states (no dtype map) pass through the typed
    loader unchanged — fp32 checkpoints restore into any compute dtype."""
    fp32_cfg = base_config()
    e = make_engine(fp32_cfg, cpu_devices)
    run_steps(e, random_batches(2, 16, HIDDEN, seed=6))
    e.save_checkpoint(str(tmp_path), sync=True)
    tag_dir = str(tmp_path / "global_step2")
    states = ckpt.load_model_states(tag_dir)
    assert all(a.dtype == np.float32 for a in states.values())

    bf16_cfg = base_config(bf16={"enabled": True})
    e2 = make_engine(bf16_cfg, cpu_devices)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None
    np.testing.assert_array_equal(np.asarray(e2.get_master_params()),
                                  np.asarray(e.get_master_params()))


def test_async_retention_roundtrip(cpu_devices, tmp_path):
    """Async saves + retention: several saves in flight, only the window
    survives, and the survivor restores correctly."""
    config = base_config(checkpoint={"keep_last_n": 2})
    e = make_engine(config, cpu_devices)
    batches = random_batches(6, 16, HIDDEN, seed=8)
    for i in range(4):
        run_steps(e, batches[i:i + 1])
        e.save_checkpoint(str(tmp_path))
    e.wait_checkpoint(str(tmp_path))

    tags = sorted(p for p in os.listdir(tmp_path)
                  if (tmp_path / p).is_dir())
    assert tags == ["global_step3", "global_step4"]
    assert ckpt.read_latest(str(tmp_path)) == "global_step4"
    ref = run_steps(e, batches[4:])

    e2 = make_engine(config, cpu_devices)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path.endswith("global_step4")
    np.testing.assert_allclose(run_steps(e2, batches[4:]), ref, rtol=1e-5)


def test_elastic_dp_change_through_manager(cpu_devices, tmp_path):
    """DP-degree-change restore through the new manager path: async save
    at dp=8, resume at dp=4 (elastic ZeRO restore, reference
    ``stage2.py:1714-1841``)."""
    batches = random_batches(8, 16, HIDDEN, seed=7)
    cfg8 = base_config(zero_optimization={"stage": 2})
    e1 = make_engine(cfg8, cpu_devices, dp=8)
    run_steps(e1, batches[:4])
    e1.save_checkpoint(str(tmp_path))     # async path
    ref_losses = run_steps(e1, batches[4:])
    e1.wait_checkpoint(str(tmp_path))

    cfg4 = base_config(zero_optimization={"stage": 2})
    cfg4["train_batch_size"] = 16  # same global batch, dp=4 -> micro 4
    e2 = make_engine(cfg4, cpu_devices, dp=4)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None
    np.testing.assert_allclose(run_steps(e2, batches[4:]), ref_losses,
                               rtol=1e-5)


def test_checkpoint_config_defaults_and_parse():
    cfg = DeepSpeedCheckpointConfig({})
    assert cfg.async_save and cfg.verify_on_load
    assert cfg.keep_last_n == 0 and cfg.keep_every_n_steps == 0
    cfg = DeepSpeedCheckpointConfig(
        {"checkpoint": {"async_save": False, "keep_last_n": 3,
                        "keep_every_n_steps": 100, "verify_on_load": False,
                        "save_on_preemption": True}})
    assert not cfg.async_save and cfg.keep_last_n == 3
    assert cfg.keep_every_n_steps == 100
    assert not cfg.verify_on_load and cfg.save_on_preemption
    with pytest.raises(AssertionError):
        DeepSpeedCheckpointConfig({"checkpoint": {"keep_last_n": -1}})


def test_wait_errors_are_per_directory(tmp_path, no_hook):
    """A failed commit to one dir must still raise from wait() after a
    later successful commit to a different dir."""
    dir_a, dir_b = tmp_path / "a", tmp_path / "b"

    def fail_in_a(tmp_dir, name):
        if os.path.dirname(tmp_dir) == str(dir_a):
            raise OSError("disk full")

    ckpt_writer._file_written_hook = fail_in_a
    m = manager()
    assert not m.save(fake_snapshot(1), str(dir_a), async_save=False)
    ckpt_writer._file_written_hook = None
    assert m.save(fake_snapshot(1), str(dir_b), async_save=False)

    m.wait(str(dir_b))  # b is clean
    with pytest.raises(ckpt.CheckpointError, match="disk full"):
        m.wait(str(dir_a))
    with pytest.raises(ckpt.CheckpointError):
        m.wait()  # no dir: any tracked failure raises
    # a later successful re-save to a clears its error
    assert m.save(fake_snapshot(2), str(dir_a), async_save=False)
    m.wait(str(dir_a))
    m.wait()


def test_drain_inflight_timeout_path(tmp_path, no_hook):
    """drain_inflight with a timeout returns False while a writer is
    stuck (instead of blocking forever) and True once it finishes; the
    checkpoint still commits intact afterwards."""
    from deepspeed_tpu.checkpoint.manager import drain_inflight

    gate = threading.Event()
    started = threading.Event()

    def block(tmp_dir, name):
        if name == ckpt.OPTIM_STATES_NPZ:
            started.set()
            assert gate.wait(timeout=60), "test deadlock"

    ckpt_writer._file_written_hook = block
    m = manager()
    try:
        assert m.save(fake_snapshot(1), str(tmp_path), async_save=True)
        assert started.wait(timeout=60), "writer never started"
        t0 = time.monotonic()
        assert not drain_inflight(str(tmp_path), timeout=0.2)
        assert time.monotonic() - t0 < 5  # timed out, didn't hang
        # a zero timeout is a pure poll
        assert not drain_inflight(str(tmp_path), timeout=0.0)
    finally:
        gate.set()
        ckpt_writer._file_written_hook = None
    assert drain_inflight(str(tmp_path), timeout=60)
    m.wait(str(tmp_path))
    assert ckpt.read_latest(str(tmp_path)) == "global_step1"


def test_preemption_handler_chained_not_self_chained(tmp_path):
    """Installing handlers from several managers must chain the ORIGINAL
    disposition exactly once — never the preemption handler over itself
    (which would re-run every callback recursively on delivery)."""
    import signal

    from deepspeed_tpu.checkpoint import manager as mgr_mod

    chained = []
    old = signal.signal(signal.SIGTERM, lambda s, f: chained.append(s))
    cbs_before = list(mgr_mod._PREEMPT_CALLBACKS)
    prev_before = dict(mgr_mod._PREEMPT_PREVIOUS)
    try:
        calls = []
        m1, m2 = manager(), manager()
        assert m1.install_preemption_handler(lambda: calls.append(1))
        assert m2.install_preemption_handler(lambda: calls.append(2))
        # the second install saw our handler already in place and must
        # NOT have recorded it as the disposition to chain to
        assert (signal.getsignal(signal.SIGTERM)
                is mgr_mod._preemption_handler)
        assert (mgr_mod._PREEMPT_PREVIOUS[signal.SIGTERM]
                is not mgr_mod._preemption_handler)
        signal.raise_signal(signal.SIGTERM)
        assert sorted(calls) == [1, 2]       # every callback ran once
        assert chained == [signal.SIGTERM]   # original handler ran ONCE
    finally:
        mgr_mod._PREEMPT_CALLBACKS[:] = cbs_before
        mgr_mod._PREEMPT_PREVIOUS.clear()
        mgr_mod._PREEMPT_PREVIOUS.update(prev_before)
        signal.signal(signal.SIGTERM, old)


def test_preemption_handler_refused_off_main_thread():
    """Signal handlers can only be installed from the main thread; a
    worker-thread install must refuse (False) without touching the
    process disposition."""
    import signal

    before = signal.getsignal(signal.SIGTERM)
    results = []
    m = manager()
    t = threading.Thread(target=lambda: results.append(
        m.install_preemption_handler(lambda: None)))
    t.start()
    t.join()
    assert results == [False]
    assert signal.getsignal(signal.SIGTERM) is before


def test_preemption_callbacks_drop_dead_engines(tmp_path):
    """Bound-method callbacks are weak: a discarded registrant neither
    leaks nor fires on SIGTERM; live ones still do."""
    import signal

    from deepspeed_tpu.checkpoint import manager as mgr_mod

    class Registrant:
        def __init__(self):
            self.fired = 0

        def final_save(self):
            self.fired += 1

    old = signal.signal(signal.SIGTERM, signal.SIG_IGN)
    cbs_before = list(mgr_mod._PREEMPT_CALLBACKS)
    try:
        m = manager()
        dead, live = Registrant(), Registrant()
        m.install_preemption_handler(dead.final_save)
        m.install_preemption_handler(live.final_save)
        del dead  # weakref target gone
        signal.raise_signal(signal.SIGTERM)
        assert live.fired == 1
        # the dead registrant's callback was pruned from the registry
        assert all(r() is not None for r in mgr_mod._PREEMPT_CALLBACKS)
    finally:
        mgr_mod._PREEMPT_CALLBACKS[:] = cbs_before
        signal.signal(signal.SIGTERM, old)


def test_preemption_handler_drains_final_save(tmp_path):
    """SIGTERM runs one final synchronous save before the previous
    disposition fires (manager-level; the engine wires save_checkpoint
    in as final_save_fn when checkpoint.save_on_preemption is set)."""
    import signal

    from deepspeed_tpu.checkpoint import manager as mgr_mod

    chained = []
    old = signal.signal(signal.SIGTERM, lambda s, f: chained.append(s))
    cbs_before = list(mgr_mod._PREEMPT_CALLBACKS)
    try:
        m = manager()
        calls = []
        m.install_preemption_handler(
            lambda: calls.append(
                m.save(fake_snapshot(7), str(tmp_path), async_save=False)))
        signal.raise_signal(signal.SIGTERM)  # delivered synchronously
        assert calls == [True]
        assert ckpt.read_latest(str(tmp_path)) == "global_step7"
        # the previous handler still fires, so shutdown proceeds
        assert chained == [signal.SIGTERM]
    finally:
        mgr_mod._PREEMPT_CALLBACKS[:] = cbs_before
        signal.signal(signal.SIGTERM, old)
