"""Flash-attention runtime block autotuner (reference analog:
``csrc/includes/gemm_test.h``'s cached algorithm search)."""

import json

import numpy as np
import pytest

from deepspeed_tpu.ops.transformer import kernel_tuner as kt


def test_anchored_shapes_keep_heuristic():
    # the measured calibration set never re-tunes by default
    assert kt.anchored(512, 512, 64, False)
    assert kt.anchored(1024, 1024, 64, True)   # single-tile causal anchor
    assert kt.anchored(2048, 2048, 64, False)
    assert not kt.anchored(1536, 1536, 64, False)   # off-grid length
    assert not kt.anchored(512, 512, 128, False)    # un-measured head_dim
    assert not kt.anchored(512, 1024, 64, False)    # cross-attention


def test_candidates_respect_constraints():
    for s, kv, d, causal in [(1536, 1536, 64, False), (512, 512, 128, True),
                             (768, 768, 96, False)]:
        cands = kt.candidates(s, kv, d, causal)
        assert cands and len(cands) <= 6
        for bq, bk in cands:
            assert s % bq == 0 and kv % bk == 0
            assert bk * d <= 128 * 1024  # VMEM cap (mirrors _auto_blocks)
            if causal:
                assert bk <= bq  # no diagonal-straddling k blocks


def test_tune_returns_heuristic_off_tpu(monkeypatch, tmp_path):
    """On non-TPU backends (this CI tier) tune() must fall back to the
    heuristic without touching the kernel."""
    monkeypatch.setattr(kt, "_CACHE_PATH", str(tmp_path / "cache.json"))
    monkeypatch.setattr(kt, "_memory_cache", {})
    monkeypatch.setattr(kt, "_disk_loaded", False)

    def boom(*a, **k):  # noqa: ANN001
        raise AssertionError("kernel must not run on CPU tier")

    got = kt.tune(1536, 1536, 64, False, 0.0, boom, (512, 512))
    assert got == (512, 512)


class _FakeTpu:
    platform = "tpu"
    device_kind = "faketpu v0"


def test_cache_roundtrip(monkeypatch, tmp_path):
    """A cached winner short-circuits the search in a fresh 'process';
    cache keys carry the device kind (a v5e winner must not be reused on
    a different TPU generation)."""
    cache = tmp_path / "cache.json"
    key = kt._key(1536, 1536, 64, False, 0.0, _FakeTpu.device_kind)
    assert "faketpu_v0" in key
    monkeypatch.setattr(kt, "_CACHE_PATH", str(cache))
    monkeypatch.setattr(kt, "_memory_cache", {key: [256, 512]})
    monkeypatch.setattr(kt, "_disk_loaded", True)
    monkeypatch.setattr(kt.jax, "devices", lambda *a: [_FakeTpu()])
    kt._save_disk()
    assert json.loads(cache.read_text())

    # fresh in-memory state: disk cache must be honored before any search
    monkeypatch.setattr(kt, "_memory_cache", {})
    monkeypatch.setattr(kt, "_disk_loaded", False)

    def boom(*a, **k):  # noqa: ANN001
        raise AssertionError("cached shape must not re-tune")

    got = kt.tune(1536, 1536, 64, False, 0.0, boom, (512, 512))
    assert got == (256, 512)

    # a DIFFERENT device kind must not see that cache entry (falls back
    # to the heuristic rather than searching, since boom cannot compile)
    class OtherTpu(_FakeTpu):
        device_kind = "faketpu v1"

    monkeypatch.setattr(kt.jax, "devices", lambda *a: [OtherTpu()])

    def heuristic_only(*a, **k):  # noqa: ANN001
        raise RuntimeError("no kernels on this backend")

    got2 = kt.tune(1536, 1536, 64, False, 0.0, heuristic_only, (512, 512))
    assert got2 == (512, 512)


def test_tuner_version_bump_invalidates_cache(monkeypatch, tmp_path):
    """VERDICT r5 item 6: winners persist to disk indefinitely, so a
    ranking produced by an older tuner must not survive a tuner upgrade
    — the cache key carries TUNER_VERSION, and a bump forces re-tune."""
    assert f"v{kt.TUNER_VERSION}|" in kt._key(
        1536, 1536, 64, False, 0.0, _FakeTpu.device_kind)

    cache = tmp_path / "cache.json"
    monkeypatch.setattr(kt, "_CACHE_PATH", str(cache))
    monkeypatch.setattr(kt.jax, "devices", lambda *a: [_FakeTpu()])
    # a winner cached by the CURRENT tuner version...
    key = kt._key(1536, 1536, 64, False, 0.0, _FakeTpu.device_kind)
    monkeypatch.setattr(kt, "_memory_cache", {key: [256, 512]})
    monkeypatch.setattr(kt, "_disk_loaded", True)
    kt._save_disk()

    def boom(*a, **k):  # noqa: ANN001
        raise AssertionError("same-version cached shape must not re-tune")

    monkeypatch.setattr(kt, "_memory_cache", {})
    monkeypatch.setattr(kt, "_disk_loaded", False)
    assert kt.tune(1536, 1536, 64, False, 0.0, boom, (512, 512)) == (256, 512)

    # ...is INVISIBLE to a bumped tuner: the stale entry is ignored and
    # the search runs again (falls back to the heuristic here, since no
    # candidate can compile on this fake backend)
    monkeypatch.setattr(kt, "TUNER_VERSION", kt.TUNER_VERSION + 1)
    monkeypatch.setattr(kt, "_memory_cache", {})
    monkeypatch.setattr(kt, "_disk_loaded", False)

    def no_compile(*a, **k):  # noqa: ANN001
        raise RuntimeError("no kernels on this backend")

    got = kt.tune(1536, 1536, 64, False, 0.0, no_compile, (512, 512))
    assert got == (512, 512)  # re-tuned (heuristic fallback), not [256, 512]


@pytest.mark.tpu
def test_tune_searches_on_chip(monkeypatch, tmp_path):
    """First-use micro-search on the real chip for an un-anchored shape:
    returns a legal candidate, caches it, and the tuned geometry is not
    slower than ~5% vs the heuristic would require a perf harness — here
    the gate is that the search completes, returns a valid divisor pair,
    and a second call is a cache hit (no recompiles)."""
    from deepspeed_tpu.ops.transformer.flash_attention import flash_attention

    monkeypatch.setattr(kt, "_CACHE_PATH", str(tmp_path / "cache.json"))
    monkeypatch.setattr(kt, "_memory_cache", {})
    monkeypatch.setattr(kt, "_disk_loaded", False)

    s = 1536  # off the anchored grid → triggers the search
    got = kt.tune(s, s, 64, False, 0.0, flash_attention, (512, 512), bh=4)
    assert s % got[0] == 0 and s % got[1] == 0

    def boom(*a, **k):  # noqa: ANN001
        raise AssertionError("second call must hit the cache")

    again = kt.tune(s, s, 64, False, 0.0, boom, (512, 512))
    assert tuple(again) == tuple(got)
    data = json.loads((tmp_path / "cache.json").read_text())
    assert list(data.values())[0] == list(got)
