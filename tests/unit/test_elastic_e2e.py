"""Elastic preemptible-fleet training, proven end-to-end.

The tentpole chaos harness: a REAL launcher (``launch.main``) supervises
a training child on the 8-device CPU mesh; the seeded chaos injector
SIGKILLs it mid-stream at step k; the elastic supervisor charges the
lost capacity, re-plans 8 -> 4 via the HCN planner, and respawns the
fleet at the new world size; the child elastic-restores the latest
committed checkpoint onto the dp=4 mesh (loader cursor included) and
trains to completion.  Loss continuity is asserted against an
UNINTERRUPTED reference run consuming the same global batches, and the
telemetry report must show the plan -> resize -> restore timeline.

Cheaper companions: launcher-level resize/poison/jitter semantics with
stdlib children, dataloader cursor unit tests, and chaos rank-targeting
unit tests.
"""

import json
import os
import signal
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")

ELASTIC_BLOCK = {"enabled": True, "max_train_batch_size": 16,
                 "micro_batch_sizes": [2, 4], "min_gpus": 1,
                 "max_gpus": 8, "version": 0.1}


# ---------------------------------------------------------------------------
# launcher-level elastic semantics (stdlib children: no jax in the kids)
# ---------------------------------------------------------------------------

def _launch_main(tmp_path, script_body=None, script_args=(), max_restarts=0,
                 extra_argv=(), script_path=None):
    from deepspeed_tpu.launcher import launch
    from deepspeed_tpu.launcher.runner import encode_world_info

    if script_path is None:
        script_path = tmp_path / "child.py"
        script_path.write_text(script_body)
    wi = encode_world_info({socket.gethostname(): [0]})
    argv = ["--world_info", wi, "--node_rank", "0",
            "--master_addr", "127.0.0.1", "--master_port", "29999",
            "--max-restarts", str(max_restarts), *extra_argv,
            str(script_path), *script_args]
    old_int = signal.getsignal(signal.SIGINT)
    old_term = signal.getsignal(signal.SIGTERM)
    try:
        with pytest.raises(SystemExit) as exc:
            launch.main(argv)
        return exc.value.code
    finally:
        signal.signal(signal.SIGINT, old_int)
        signal.signal(signal.SIGTERM, old_term)


def _elastic_argv(tmp_path, devices=8, telemetry=True):
    cfg = tmp_path / "elastic.json"
    cfg.write_text(json.dumps({"elasticity": ELASTIC_BLOCK}))
    argv = ["--elastic-config", str(cfg), "--elastic-devices", str(devices)]
    if telemetry:
        argv += ["--telemetry-dir", str(tmp_path / "tel")]
    return argv


def _launcher_events(tmp_path, event_type=None):
    path = tmp_path / "tel" / "events-launcher.jsonl"
    if not path.exists():
        return []
    recs = [json.loads(line) for line in path.read_text().splitlines()]
    if event_type is not None:
        recs = [r for r in recs if r["type"] == event_type]
    return recs


def test_launcher_resize_replans_and_reexports_world(tmp_path, monkeypatch):
    """A respawnable signal death with the supervisor armed respawns the
    fleet at the PLANNED smaller world size: the child's second life
    sees DS_ELASTIC_TARGET_WORLD_SIZE=4 + the normalized schedule, and
    the launcher stream carries plan + resize events plus a respawn
    event naming the planned world size."""
    monkeypatch.setenv("DS_MONITOR_POLL_SECS", "0.05")
    monkeypatch.setenv("DS_RESTART_BACKOFF_SECS", "0.05")
    monkeypatch.setenv("DS_ELASTIC_DEVICES_PER_FAILURE", "4")
    out = tmp_path / "lives.jsonl"
    code = _launch_main(
        tmp_path,
        "import json, os, sys\n"
        "out = sys.argv[1]\n"
        "rec = {'world': os.environ.get('DS_ELASTIC_TARGET_WORLD_SIZE'),\n"
        "       'sched': os.environ.get('DEEPSPEED_ELASTICITY_CONFIG')}\n"
        "open(out, 'a').write(json.dumps(rec) + '\\n')\n"
        "if len(open(out).readlines()) == 1:\n"
        "    os.kill(os.getpid(), 9)\n",
        script_args=(str(out),), max_restarts=2,
        extra_argv=_elastic_argv(tmp_path))
    assert code == 0
    lives = [json.loads(line) for line in out.read_text().splitlines()]
    assert [l["world"] for l in lives] == ["8", "4"]
    sched = json.loads(lives[1]["sched"])
    assert sched["max_train_batch_size"] == 16
    plans = _launcher_events(tmp_path, "elastic")
    assert [p["data"]["phase"] for p in plans] == ["plan", "resize"]
    assert plans[0]["data"]["prev_world_size"] == 8
    assert plans[0]["data"]["planned_world_size"] == 4
    assert plans[0]["data"]["global_batch"] == 16
    (respawn,) = _launcher_events(tmp_path, "proc_respawn")
    assert respawn["data"]["planned_world_size"] == 4


def test_launcher_poison_exit_is_never_resized_around(tmp_path,
                                                      monkeypatch):
    """Exit 86 (divergence abort) must tear the node down even with an
    armed elastic supervisor and restart budget left: resizing around a
    divergence replays the same data into the same divergence with less
    capacity."""
    from deepspeed_tpu.resilience import EXIT_DIVERGENCE_ABORT

    monkeypatch.setenv("DS_MONITOR_POLL_SECS", "0.05")
    monkeypatch.setenv("DS_RESTART_BACKOFF_SECS", "0.05")
    counter = tmp_path / "runs"
    code = _launch_main(
        tmp_path,
        "import sys\n"
        "with open(sys.argv[1], 'a') as f:\n"
        "    f.write('x')\n"
        f"sys.exit({EXIT_DIVERGENCE_ABORT})\n",
        script_args=(str(counter),), max_restarts=3,
        extra_argv=_elastic_argv(tmp_path))
    assert code == EXIT_DIVERGENCE_ABORT
    assert counter.read_text() == "x"          # ran exactly once
    assert _launcher_events(tmp_path, "elastic") == []


def test_launcher_tears_down_below_schedule_floor(tmp_path, monkeypatch):
    """When the surviving budget admits NO valid world size the resize
    is terminal: the launcher reports the original failure instead of
    thrashing respawns that can never train."""
    monkeypatch.setenv("DS_MONITOR_POLL_SECS", "0.05")
    monkeypatch.setenv("DS_RESTART_BACKOFF_SECS", "0.05")
    monkeypatch.setenv("DS_ELASTIC_DEVICES_PER_FAILURE", "8")
    counter = tmp_path / "runs"
    code = _launch_main(
        tmp_path,
        "import os, sys\n"
        "with open(sys.argv[1], 'a') as f:\n"
        "    f.write('x')\n"
        "os.kill(os.getpid(), 9)\n",
        script_args=(str(counter),), max_restarts=3,
        extra_argv=_elastic_argv(tmp_path))
    assert code == 137
    assert counter.read_text() == "x"
    phases = [p["data"]["phase"]
              for p in _launcher_events(tmp_path, "elastic")]
    assert phases == []    # the failed plan never emits a resize


def test_launcher_resize_budget_bounds_total_restarts(tmp_path,
                                                      monkeypatch):
    """--max-restarts bounds RESIZES when the supervisor is armed: a
    child that keeps dying gets exactly that many resized lives, never a
    same-size per-child respawn on top (which would double the budget
    behind the supervisor's back)."""
    monkeypatch.setenv("DS_MONITOR_POLL_SECS", "0.05")
    monkeypatch.setenv("DS_RESTART_BACKOFF_SECS", "0.05")
    monkeypatch.setenv("DS_ELASTIC_DEVICES_PER_FAILURE", "2")
    counter = tmp_path / "runs"
    code = _launch_main(
        tmp_path,
        "import os, sys\n"
        "with open(sys.argv[1], 'a') as f:\n"
        "    f.write('x')\n"
        "os.kill(os.getpid(), 9)\n",
        script_args=(str(counter),), max_restarts=1,
        extra_argv=_elastic_argv(tmp_path))
    assert code == 137
    assert counter.read_text() == "xx"      # first life + ONE resize
    phases = [p["data"]["phase"]
              for p in _launcher_events(tmp_path, "elastic")]
    assert phases == ["plan", "resize"]


def test_respawn_backoff_is_jittered_within_bounds(tmp_path, monkeypatch):
    """Non-elastic respawns keep exponential backoff but gain a bounded
    multiplicative jitter: base*2^(r-1) <= delay <= that * (1+jitter)."""
    monkeypatch.setenv("DS_MONITOR_POLL_SECS", "0.05")
    monkeypatch.setenv("DS_RESTART_BACKOFF_SECS", "0.05")
    monkeypatch.setenv("DS_RESTART_BACKOFF_JITTER", "0.5")
    marker = tmp_path / "count"
    code = _launch_main(
        tmp_path,
        "import os, sys\n"
        "with open(sys.argv[1], 'a') as f:\n"
        "    f.write('x')\n"
        "sys.exit(0 if len(open(sys.argv[1]).read()) >= 3 else 1)\n",
        script_args=(str(marker),), max_restarts=2,
        extra_argv=["--telemetry-dir", str(tmp_path / "tel")])
    assert code == 0
    respawns = _launcher_events(tmp_path, "proc_respawn")
    assert len(respawns) == 2
    for rec in respawns:
        r = rec["data"]["restart"]
        base = 0.05 * (2 ** (r - 1))
        assert base <= rec["data"]["backoff_secs"] <= base * 1.5 + 1e-9


# ---------------------------------------------------------------------------
# dataloader cursor: no replay, no skip — across geometry changes
# ---------------------------------------------------------------------------

def _loader(batch_size, seed=5):
    from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader

    data = [np.full((2,), i, np.float32) for i in range(64)]
    return DeepSpeedDataLoader(data, batch_size=batch_size, shuffle=True,
                               seed=seed)


def test_loader_state_roundtrip_resumes_exact_stream():
    a = _loader(8)
    it = iter(a)
    consumed = [next(it) for _ in range(3)]        # 24 samples into epoch 1
    state = a.state_dict()
    assert state == {"epoch": 1, "samples_yielded": 24}

    b = _loader(8)
    b.load_state_dict(state)
    resumed = list(iter(b))
    rest = [next(it) for _ in range(5)]            # the uninterrupted tail
    assert len(resumed) == len(rest) == 5
    for x, y in zip(resumed, rest):
        np.testing.assert_array_equal(x, y)
    del consumed


def test_loader_state_survives_geometry_change():
    """An elastic resume changes micro x dp (the per-pull batch size)
    while the optimizer-boundary cursor is a multiple of the fixed
    global batch: the resumed loader must continue the SAME sample
    stream in its new chunking."""
    a = _loader(16)                    # old geometry: 16-sample pulls
    it = iter(a)
    for _ in range(2):                 # 32 samples consumed
        next(it)
    state = a.state_dict()

    b = _loader(8)                     # new geometry: 8-sample pulls
    b.load_state_dict(state)
    resumed = np.concatenate([x.reshape(-1) for x in iter(b)])
    want = np.concatenate([x.reshape(-1) for x in it])
    np.testing.assert_array_equal(resumed, want)


def test_loader_state_next_epoch_rolls_fresh():
    """A cursor at the exact epoch end yields nothing more from that
    epoch; the next __iter__ (RepeatingLoader's restart) begins the
    following epoch with a fresh cursor."""
    a = _loader(16)
    list(iter(a))                      # consume epoch 1 fully (4 batches)
    state = a.state_dict()
    assert state == {"epoch": 1, "samples_yielded": 64}
    b = _loader(16)
    b.load_state_dict(state)
    assert list(iter(b)) == []         # epoch 1 exhausted — no replay
    nxt = list(iter(b))                # epoch 2, fresh order
    assert len(nxt) == 4 and b.epoch == 2


# ---------------------------------------------------------------------------
# chaos rank targeting
# ---------------------------------------------------------------------------

def test_chaos_kill_and_sigterm_target_a_specific_rank():
    from deepspeed_tpu.resilience.chaos import ChaosMonkey

    # non-victim ranks iterate straight through the same seeded schedule
    monkey = ChaosMonkey(seed=3)
    it = monkey.wrap_iter(iter(range(6)), kill_steps=[2],
                          sigterm_steps=[4], rank=1, target_rank=0)
    assert list(it) == list(range(6))
    assert monkey.log == []

    # the victim rank injects; prove it with the survivable fault
    fired = []
    old = signal.signal(signal.SIGTERM, lambda s, f: fired.append(s))
    try:
        monkey2 = ChaosMonkey(seed=3)
        it2 = monkey2.wrap_iter(iter(range(6)), sigterm_steps=[4],
                                rank=0, target_rank=0)
        assert list(it2) == list(range(6))
    finally:
        signal.signal(signal.SIGTERM, old)
    assert fired == [signal.SIGTERM]
    assert monkey2.log == [(4, "sigterm")]


def test_chaos_kill_dies_like_a_preempted_host():
    """kill_steps delivers an unhandleable SIGKILL to the process —
    proven in a subprocess, the same shape the launcher supervises."""
    code = (
        "import sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from deepspeed_tpu.resilience.chaos import ChaosMonkey\n"
        "it = ChaosMonkey(0).wrap_iter(iter(range(4)), kill_steps=[1])\n"
        "for _ in it: pass\n"
        "print('survived')\n")
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == -signal.SIGKILL
    assert "survived" not in proc.stdout


# ---------------------------------------------------------------------------
# THE chaos e2e: kill at step k -> re-plan 8->4 -> elastic restore ->
# loss continuity vs an uninterrupted same-batch reference
# ---------------------------------------------------------------------------

def _read_final(out_dir):
    with open(os.path.join(out_dir, "final.json")) as f:
        return json.load(f)


def _run_reference(tmp_path, env):
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "elastic_train_script.py")
    ref_env = dict(env)
    ref_env.pop("DS_CHAOS_KILL_STEP", None)
    ref_env["DS_ELASTIC_TARGET_WORLD_SIZE"] = "8"
    ref_env["DS_TELEMETRY_DIR"] = str(tmp_path / "tel-ref")
    proc = subprocess.run(
        [sys.executable, script, str(tmp_path / "ckpt-ref"),
         str(tmp_path / "out-ref")],
        cwd=REPO, env=ref_env, capture_output=True, text=True, timeout=280)
    assert proc.returncode == 0, (
        f"reference run failed\nstdout:\n{proc.stdout[-3000:]}\n"
        f"stderr:\n{proc.stderr[-3000:]}")
    return _read_final(tmp_path / "out-ref")


def test_chaos_elastic_resize_end_to_end(tmp_path, monkeypatch):
    from deepspeed_tpu.resilience.chaos import ChaosMonkey
    from deepspeed_tpu.telemetry.report import generate_report

    # seeded kill step in [3, 6]: late enough that committed checkpoints
    # exist, early enough that the resized fleet trains several steps
    kill_step = 3 + ChaosMonkey(seed=11).schedule_steps(4, 1)[0]

    monkeypatch.setenv("DS_MONITOR_POLL_SECS", "0.05")
    monkeypatch.setenv("DS_RESTART_BACKOFF_SECS", "0.05")
    monkeypatch.setenv("DS_ELASTIC_DEVICES_PER_FAILURE", "4")
    monkeypatch.setenv("DS_CHAOS_KILL_STEP", str(kill_step))
    monkeypatch.setenv("DS_CHAOS_SEED", "11")
    # children force their own 8-device CPU topology
    monkeypatch.setenv("PYTHONPATH",
                       REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "elastic_train_script.py")
    run_dir = tmp_path / "tel"
    code = _launch_main(
        tmp_path, script_path=script,
        script_args=(str(tmp_path / "ckpt"), str(tmp_path / "out")),
        max_restarts=2,
        extra_argv=_elastic_argv(tmp_path) + [
            "--compile-cache-dir", str(tmp_path / "xla-cache")])
    assert code == 0

    # the interrupted run finished all 10 steps at the resized world
    final = _read_final(tmp_path / "out")
    assert final["steps"] == 10 and final["world"] == 4
    assert final["samples"] == 10 * 16

    # step accounting: each optimizer step 1..10 appears EXACTLY once
    # across the two lives (no replay, no skip), world 8 before the kill
    # and 4 after
    steps = {}
    out_dir = tmp_path / "out"
    for name in os.listdir(out_dir):
        if not name.startswith("steps-"):
            continue
        for line in open(out_dir / name):
            rec = json.loads(line)
            assert rec["step"] not in steps, f"step {rec['step']} replayed"
            steps[rec["step"]] = rec
    assert sorted(steps) == list(range(1, 11))
    for s, rec in steps.items():
        assert rec["world"] == (8 if s <= kill_step else 4), (s, rec)
        assert rec["samples"] == s * 16

    # loss continuity vs the uninterrupted same-batch reference run
    ref = _run_reference(tmp_path, dict(os.environ))
    assert ref["steps"] == 10 and ref["world"] == 8
    np.testing.assert_allclose(final["final_loss"], ref["final_loss"],
                               rtol=1e-3)
    ref_steps = {}
    for name in os.listdir(tmp_path / "out-ref"):
        if name.startswith("steps-"):
            for line in open(tmp_path / "out-ref" / name):
                rec = json.loads(line)
                ref_steps[rec["step"]] = rec["loss"]
    for s in range(1, 11):
        np.testing.assert_allclose(
            steps[s]["loss"], ref_steps[s], rtol=1e-3,
            err_msg=f"loss diverged from uninterrupted reference at "
                    f"step {s} (kill was at {kill_step})")

    # telemetry: the merged report shows the plan -> resize -> restore
    # resize timeline
    text, records = generate_report(str(run_dir))
    assert "elastic resize timeline:" in text
    phases = [r["data"]["phase"] for r in records
              if r["type"] == "elastic"]
    assert phases.count("plan") == 1 and phases.count("resize") == 1
    assert "restore" in phases, "engine never emitted the elastic restore"
    restore = next(r for r in records
                   if r["type"] == "elastic"
                   and r["data"]["phase"] == "restore")
    assert restore["data"]["from_dp"] == 8
    assert restore["data"]["to_dp"] == 4
    assert "world 8->4" in text
