"""Serving observability plane (inference/observability.py): the P²
streaming-quantile estimator, the schema-versioned request-lifecycle
traces (joined across a requeue), the occupancy/goodput/SLO receipts,
and the doctor's tail-request phase decomposition.

The zero-added-syncs side of the contract is pinned dynamically by
``test_inference.py::test_zero_added_host_syncs`` (device_get counting
with the full plane + SLO armed) and statically by the DSH205 cases in
``test_dslint.py``.
"""

import numpy as np
import pytest

from deepspeed_tpu.inference import (InferenceEngine, ServingFrontend,
                                     SERVING_PHASE_KEYS,
                                     SERVING_TRACE_SCHEMA_VERSION)
from deepspeed_tpu.telemetry import events as TEL
from deepspeed_tpu.telemetry.registry import (MetricsRegistry, P2Quantile,
                                              StreamingQuantiles)

from .test_inference import (seeded_prompts, serve_config, tiny_model,
                             model_and_params)  # noqa: F401 — fixture


# ---------------------------------------------------------------------------
# P² streaming quantiles: convergence + merge safety
# ---------------------------------------------------------------------------

class TestP2Quantile:
    @pytest.mark.parametrize("p,tol", [(0.5, 0.05), (0.9, 0.05),
                                       (0.99, 0.10)])
    def test_converges_on_heavy_tail(self, p, tol):
        # lognormal: the shape of a latency stream (long right tail) —
        # the estimator must track the sorted ground truth within a
        # few percent relative error at 20k observations
        rng = np.random.default_rng(7)
        samples = rng.lognormal(mean=-7.0, sigma=1.0, size=20000)
        est = P2Quantile(p)
        for s in samples:
            est.observe(float(s))
        truth = float(np.quantile(samples, p))
        assert est.count == len(samples)
        assert est.value == pytest.approx(truth, rel=tol)

    def test_exact_until_five_observations(self):
        est = P2Quantile(0.5)
        for v in (3.0, 1.0, 2.0):
            est.observe(v)
        assert est.value == 2.0  # exact small-sample median

    def test_merge_across_windows_matches_concatenated_stream(self):
        # three per-window estimators over disjoint slices must merge
        # to (approximately) the quantile of the concatenated stream —
        # the property that makes window-scoped estimators safe to
        # aggregate without any window re-seeing another's samples
        rng = np.random.default_rng(11)
        samples = rng.lognormal(mean=-6.0, sigma=0.8, size=9000)
        windows = [P2Quantile(0.9) for _ in range(3)]
        for i, s in enumerate(samples):
            windows[i % 3].observe(float(s))
        merged = P2Quantile.merged_estimate(0.9, windows)
        truth = float(np.quantile(samples, 0.9))
        assert merged == pytest.approx(truth, rel=0.10)

    def test_merge_weights_unequal_windows(self):
        # a tiny window must not drag the merged estimate: weights are
        # count-proportional.  9900 samples near 1.0, 100 near 100.0 —
        # the merged p50 stays near 1.0
        big, small = P2Quantile(0.5), P2Quantile(0.5)
        rng = np.random.default_rng(3)
        for _ in range(9900):
            big.observe(1.0 + rng.normal() * 0.01)
        for _ in range(100):
            small.observe(100.0 + rng.normal())
        merged = P2Quantile.merged_estimate(0.5, [big, small])
        assert merged == pytest.approx(1.0, abs=0.1)

    def test_empty_estimators_merge_to_zero(self):
        assert P2Quantile.merged_estimate(0.5, [P2Quantile(0.5)]) == 0.0


class TestStreamingQuantilesInstrument:
    def test_snapshot_shape_matches_histogram_family(self):
        reg = MetricsRegistry()
        q = reg.quantiles("serving/per_token_seconds")
        assert isinstance(q, StreamingQuantiles)
        for v in (0.001, 0.002, 0.004):
            q.observe(v)
        snap = q.snapshot()
        assert snap["kind"] == "quantiles"
        assert snap["count"] == 3
        assert snap["sum"] == pytest.approx(0.007)
        assert snap["min"] == 0.001 and snap["max"] == 0.004
        for key in ("mean", "p50", "p90", "p99"):
            assert key in snap
        # registered: a second fetch is the same instrument
        assert reg.quantiles("serving/per_token_seconds") is q


# ---------------------------------------------------------------------------
# golden schema: lifecycle phase records, trace joined across a requeue
# ---------------------------------------------------------------------------

def _serving_events(run_dir):
    """EVENT_SERVING payloads in stream order (the lifecycle fields —
    kind/trace/schema/t_mono — ride the record's ``data`` dict)."""
    return [dict(r.get("data") or {})
            for r in TEL.read_events(str(run_dir))
            if r.get("type") == TEL.EVENT_SERVING]


class TestLifecycleTraceSchema:
    @pytest.fixture()
    def requeue_run(self, model_and_params, tmp_path):  # noqa: F811
        """2-replica front-end serve with one replica death mid-decode:
        the canonical joined-trace fixture."""
        model, params = model_and_params
        config = serve_config(slo={"ttft_ms": 2000, "per_token_ms": 500})
        config["steps_per_print"] = 2
        config["telemetry"] = {"enabled": True, "run_dir": str(tmp_path)}
        replicas = [InferenceEngine(model, params, config=config)
                    for _ in range(2)]
        frontend = ServingFrontend(replicas)
        for i, p in enumerate(seeded_prompts(4, seed=21)):
            frontend.submit(p, max_new_tokens=4, request_id=f"r{i}")
        for _ in range(2):
            frontend.step()
        frontend.mark_dead(0)
        results = frontend.run()
        for engine in replicas:
            engine.close()
        return results, _serving_events(tmp_path)

    def test_every_phase_record_validates_against_the_table(
            self, requeue_run):
        results, events = requeue_run
        lifecycle = [r for r in events
                     if r.get("kind") in SERVING_PHASE_KEYS]
        assert lifecycle, "no lifecycle phase records emitted"
        for rec in lifecycle:
            required = SERVING_PHASE_KEYS[rec["kind"]]
            missing = [k for k in required if k not in rec]
            assert not missing, (
                f"{rec['kind']} record missing {missing}: {rec}")
            assert rec["schema"] == SERVING_TRACE_SCHEMA_VERSION
            assert rec["t_mono"] > 0

    def test_requeued_request_is_one_joined_trace(self, requeue_run):
        results, events = requeue_run
        assert len(results) == 4
        by_trace = {}
        for rec in events:
            if "trace" in rec:
                by_trace.setdefault(rec["trace"], []).append(rec)
        requeued = [kinds for kinds in
                    ([r["kind"] for r in recs]
                     for recs in by_trace.values())
                    if "requeue" in kinds]
        assert requeued, "no requeued trace in the fixture run"
        for kinds in requeued:
            # one submit, then TWO lives (admit/first_token before and
            # after the requeue), one terminal finish — all one trace
            assert kinds.count("submit") == 1
            assert kinds.count("admit") == 2
            assert kinds.count("first_token") == 2
            assert kinds[-1] == "finish"
            assert kinds.index("requeue") > kinds.index("admit")
        # untouched traces keep the single-life shape
        for kinds in ([r["kind"] for r in recs]
                      for recs in by_trace.values()):
            if "requeue" in kinds:
                continue
            assert kinds.count("admit") == kinds.count("first_token") == 1

    def test_trace_ids_land_in_results(self, requeue_run):
        results, events = requeue_run
        traces = {rec["trace"] for rec in events if "trace" in rec}
        for rid, result in results.items():
            assert result["trace_id"] in traces
            assert result["admission_wait_seconds"] >= 0

    def test_monotonic_ordering_within_each_trace(self, requeue_run):
        _, events = requeue_run
        by_trace = {}
        for rec in events:
            if "trace" in rec:
                by_trace.setdefault(rec["trace"], []).append(rec)
        for recs in by_trace.values():
            stamps = [r["t_mono"] for r in recs]
            assert stamps == sorted(stamps)

    def test_decode_window_and_slo_records_at_cadence(self, requeue_run):
        _, events = requeue_run
        windows = [r for r in events if r.get("kind") == "decode_window"]
        slos = [r for r in events if r.get("kind") == "slo"]
        assert windows and slos
        for w in windows:
            assert 0 < w["batch_occupancy"] <= 1.0
            assert 0 <= w["token_budget_utilization"] <= 1.0
            assert w["kv_used_peak"] >= w["kv_used_blocks"] >= 0
        for s in slos:
            assert 0 <= s["slo_attainment"] <= 1.0
            assert s["goodput_tokens"] <= s["window_tokens"]


# ---------------------------------------------------------------------------
# occupancy / goodput receipt
# ---------------------------------------------------------------------------

class TestServingReceipt:
    def test_receipt_fields_sane_without_slo(self, model_and_params,
                                             tmp_path):
        model, params = model_and_params
        config = serve_config()
        config["telemetry"] = {"enabled": True, "run_dir": str(tmp_path)}
        engine = InferenceEngine(model, params, config=config)
        for i, p in enumerate(seeded_prompts(4, seed=33)):
            engine.submit(p, max_new_tokens=4, request_id=f"r{i}")
        engine.run()
        receipt = engine.serving_receipt()
        engine.close()
        assert 0 < receipt["batch_occupancy_mean"] <= 1.0
        assert 0 < receipt["token_budget_utilization"] <= 1.0
        assert 0 < receipt["kv_block_occupancy_peak"] <= 1.0
        assert 0 <= receipt["padding_waste_fraction"] < 1.0
        # no SLO block: every token is good, goodput == raw throughput
        assert not receipt["slo_enabled"]
        assert receipt["slo_attainment"] == 1.0
        assert receipt["goodput_tokens"] == receipt["generated_tokens"]
        assert receipt["goodput_tokens_per_second"] == pytest.approx(
            receipt["tokens_per_second_per_chip"], rel=0.2)

    def test_impossible_slo_zeroes_goodput(self, model_and_params,
                                           tmp_path):
        # sub-microsecond targets: nothing conforms, attainment ~ 0,
        # goodput collapses while raw throughput stays positive
        model, params = model_and_params
        config = serve_config(slo={"ttft_ms": 0.0001,
                                   "per_token_ms": 0.0001})
        config["telemetry"] = {"enabled": True, "run_dir": str(tmp_path)}
        engine = InferenceEngine(model, params, config=config)
        for i, p in enumerate(seeded_prompts(3, seed=34)):
            engine.submit(p, max_new_tokens=4, request_id=f"r{i}")
        engine.run()
        receipt = engine.serving_receipt()
        engine.close()
        assert receipt["slo_enabled"]
        assert receipt["slo_attainment"] == 0.0
        assert receipt["goodput_tokens"] == 0
        assert receipt["tokens_per_second_per_chip"] > 0

    def test_kv_allocator_peak_tracks_high_water(self):
        from deepspeed_tpu.inference import BlockAllocator

        alloc = BlockAllocator(16)
        first = alloc.allocate(6)
        assert alloc.used_peak == 6
        alloc.release(first)
        assert alloc.used_blocks == 0
        assert alloc.used_peak == 6      # high water survives release
        alloc.allocate(4)
        assert alloc.used_peak == 6      # lower second wave: unchanged
        assert alloc.capacity == 15      # null block excluded


# ---------------------------------------------------------------------------
# front-end fleet gauges (satellite: queue_depth / live_replicas)
# ---------------------------------------------------------------------------

class TestFrontendGauges:
    def test_gauges_exported_at_print_cadence(self, model_and_params,
                                              tmp_path):
        model, params = model_and_params
        config = serve_config()
        config["steps_per_print"] = 2
        config["telemetry"] = {"enabled": True, "run_dir": str(tmp_path)}
        replicas = [InferenceEngine(model, params, config=config)
                    for _ in range(2)]
        frontend = ServingFrontend(replicas)
        for i, p in enumerate(seeded_prompts(3, seed=40)):
            frontend.submit(p, max_new_tokens=4, request_id=f"r{i}")
        frontend.step()
        registry = replicas[0].telemetry.registry
        frontend.step()  # second step crosses the cadence: export fires
        assert registry.gauge("serving/live_replicas").value == 2.0
        frontend.mark_dead(0)
        results = frontend.run()
        assert len(results) == 3
        assert registry.gauge("serving/live_replicas").value == 1.0
        assert registry.gauge("serving/queue_depth").value == 0.0
        for engine in replicas:
            engine.close()


# ---------------------------------------------------------------------------
# Request.result caching (satellite: latency_summary computed once)
# ---------------------------------------------------------------------------

class TestResultCaching:
    def test_finished_result_computed_once_and_stable(
            self, model_and_params, tmp_path):
        model, params = model_and_params
        engine = InferenceEngine(model, params, config=serve_config())
        engine.submit(seeded_prompts(1, seed=50)[0], max_new_tokens=4,
                      request_id="r0")
        results = engine.run()
        request = engine.request("r0")
        first = request.result()
        assert first is request.result()    # cached dict, not recomputed
        assert first == results["r0"]
        engine.close()


# ---------------------------------------------------------------------------
# doctor: tail-request phase decomposition
# ---------------------------------------------------------------------------

class TestDoctorServingTail:
    def test_queue_starved_tail_dominated_by_queue_wait(
            self, model_and_params, tmp_path):
        """One decode slot, four requests: the last-admitted request's
        latency is (deterministically) dominated by queue wait, and the
        doctor names it."""
        from deepspeed_tpu.profiling.doctor import (
            SERVING_TAIL_PHASES, doctor_run_dir, format_serving_tail,
            serving_tail_decomposition)

        model, params = model_and_params
        config = serve_config(max_batch_slots=1, token_budget=64,
                              slo={"ttft_ms": 1, "per_token_ms": 1})
        config["telemetry"] = {"enabled": True, "run_dir": str(tmp_path)}
        config["profiling"] = {"comm_ledger": True}
        engine = InferenceEngine(model, params, config=config)
        for i, p in enumerate(seeded_prompts(4, seed=60)):
            engine.submit(p, max_new_tokens=8, request_id=f"r{i}")
        engine.run()
        engine.close()

        tail = serving_tail_decomposition(str(tmp_path))
        assert tail is not None
        assert tail["finished_traces"] == 4
        assert set(tail["phases"]) == set(SERVING_TAIL_PHASES)
        assert tail["dominant_phase"] == "queue_wait"
        # the decomposition covers the measured latency: no negative
        # phases, unexplained is the bounded remainder
        assert all(v >= 0 for v in tail["phases"].values())
        assert sum(tail["phases"].values()) == pytest.approx(
            tail["latency_seconds"], rel=0.01)
        # the rendered verdict names the phase
        lines = format_serving_tail(tail)
        assert any("dominant phase: queue-wait" in ln for ln in lines)
        # and the full doctor verdict carries the serving section
        verdict = doctor_run_dir(str(tmp_path))
        assert verdict["serving"]["dominant_phase"] == "queue_wait"

    def test_no_serving_events_yields_none(self, tmp_path):
        from deepspeed_tpu.profiling.doctor import (
            serving_tail_decomposition)

        assert serving_tail_decomposition(str(tmp_path)) is None


# ---------------------------------------------------------------------------
# telemetry report --serving
# ---------------------------------------------------------------------------

class TestServingReport:
    def test_report_renders_serving_section(self, model_and_params,
                                            tmp_path, capsys):
        from deepspeed_tpu.telemetry.report import main as report_main

        model, params = model_and_params
        config = serve_config(slo={"ttft_ms": 2000, "per_token_ms": 500})
        config["steps_per_print"] = 2
        config["telemetry"] = {"enabled": True, "run_dir": str(tmp_path)}
        engine = InferenceEngine(model, params, config=config)
        for i, p in enumerate(seeded_prompts(3, seed=70)):
            engine.submit(p, max_new_tokens=4, request_id=f"r{i}")
        engine.run()
        engine.close()
        assert report_main(["report", str(tmp_path), "--serving"]) == 0
        out = capsys.readouterr().out
        assert "serving (request traces / occupancy / SLO):" in out
        assert "occupancy" in out
        assert "SLO:" in out
