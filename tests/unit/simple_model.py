"""Tiny model fixtures (analog of reference ``tests/unit/simple_model.py``)."""

import jax
import jax.numpy as jnp
import numpy as np


class SimpleModel:
    """Linear stack with MSE loss; conforms to the engine's model contract."""

    def __init__(self, hidden_dim, nlayers=1):
        self.hidden_dim = hidden_dim
        self.nlayers = nlayers

    def init(self, rng):
        params = {}
        for i in range(self.nlayers):
            k1, k2, rng = jax.random.split(rng, 3)
            params[f"layer_{i}"] = {
                "w": jax.random.normal(k1, (self.hidden_dim, self.hidden_dim),
                                       jnp.float32) * 0.1,
                "b": jnp.zeros((self.hidden_dim,), jnp.float32),
            }
        return params

    def apply(self, params, batch, rng=None, train=True, **kwargs):
        x, y = batch
        h = x
        for i in range(self.nlayers):
            p = params[f"layer_{i}"]
            h = jnp.tanh(h @ p["w"] + p["b"])
        loss = jnp.mean((h - y) ** 2)
        return loss


class SimpleMLPWithLogits(SimpleModel):
    """Variant returning logits when train=False (eval-path testing)."""

    def apply(self, params, batch, rng=None, train=True, **kwargs):
        x = batch[0] if isinstance(batch, (tuple, list)) else batch
        h = x
        for i in range(self.nlayers):
            p = params[f"layer_{i}"]
            h = jnp.tanh(h @ p["w"] + p["b"])
        if not train:
            return h
        y = batch[1]
        return jnp.mean((h - y) ** 2)


def random_dataset(total_samples, hidden_dim, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(total_samples, hidden_dim)).astype(np.float32)
    y = rng.normal(size=(total_samples, hidden_dim)).astype(np.float32)
    return [(x[i], y[i]) for i in range(total_samples)]


def random_batches(num_batches, batch_size, hidden_dim, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(num_batches):
        x = rng.normal(size=(batch_size, hidden_dim)).astype(np.float32)
        y = rng.normal(size=(batch_size, hidden_dim)).astype(np.float32)
        out.append((x, y))
    return out


def base_config(**overrides):
    cfg = {
        "train_batch_size": 16,
        "steps_per_print": 100,
        "optimizer": {"type": "Adam", "params": {"lr": 0.01}},
    }
    cfg.update(overrides)
    return cfg
