"""Bucketed gradient-collective overlap (``overlap_comm``) tests.

Covers the round-14 tentpole end to end: the :class:`BucketPlan`
sub-partition layout (zero/buckets.py), the engine's per-bucket
``psum_scatter`` exchange + per-group master all-gathers, numerical
parity of the bucketed schedule against the serialized (GSPMD fused)
control, canonical-checkpoint compatibility across layouts and dp
degrees, the declared collective schedule, and the config surface.

Parity note (the documented tolerance): the bucketed exchange sums the
same per-rank gradients as GSPMD's fused reduction but in a different
association (per-bucket psum_scatter ring vs the fused all-reduce), so
masters drift by single ulps per step — measured ≤ 1.2e-7 absolute
after 22 steps on the fixture below.  The update math itself is
elementwise and layout-agnostic (bit-identical given identical
gradients); only the reduction order differs.
"""

import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.runtime.zero.buckets import BucketPlan

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 64
# 4 layers x (w 64x64 + b 64): 8 leaves, 16640 elements
NLAYERS = 4


def _cfg(overlap, clip=1.0, acc=1, **over):
    cfg = base_config(
        steps_per_print=10 ** 9,
        zero_optimization={"stage": 2, "overlap_comm": overlap,
                           # small buckets: several per model, multi-leaf
                           "reduce_bucket_size": 3 * HIDDEN * HIDDEN // 2,
                           "allgather_bucket_size": 3 * HIDDEN * HIDDEN},
        gradient_clipping=clip,
        telemetry={"enabled": False})
    if acc > 1:
        cfg["train_batch_size"] = 16 * acc
        cfg["gradient_accumulation_steps"] = acc
    cfg.update(over)
    return cfg


def _engine(cpu_devices, overlap, dp=4, **kw):
    mesh = make_mesh({"data": dp}, devices=cpu_devices[:dp])
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(HIDDEN, nlayers=NLAYERS),
        config=_cfg(overlap, **kw), mesh=mesh)
    return engine


def _canonical_state(engine):
    """Canonical (layout-independent) host copies of master + flat opt
    leaves — the checkpoint format."""
    return {
        "master": engine.flat.gather_master_unpadded(
            engine.state["master"]),
        "exp_avg": engine.flat.gather_master_unpadded(
            engine.state["opt"].exp_avg),
        "exp_avg_sq": engine.flat.gather_master_unpadded(
            engine.state["opt"].exp_avg_sq),
    }


# ---------------------------------------------------------------- plan
def test_bucket_plan_layout_and_roundtrips():
    sizes = [1024 * 3 + 5, 2048, 100, 4096 * 2, 7, 1024]
    plan = BucketPlan(sizes, dp=4, reduce_bucket_size=5000,
                      allgather_bucket_size=9000, lanes=1024)
    # leaf-aligned, >= 1 leaf per bucket, oversized leaf alone
    assert [(b.leaf_lo, b.leaf_hi) for b in plan.buckets] == [
        (0, 1), (1, 3), (3, 4), (4, 6)]
    for b in plan.buckets:
        assert b.rows % 4 == 0 and b.piece_rows == b.rows // 4
    assert plan.rows == sum(b.rows for b in plan.buckets)
    assert plan.piece_rows * 4 == plan.rows
    # ag groups: consecutive buckets bounded by allgather_bucket_size
    assert plan.ag_groups == ((0, 2), (2, 3), (3, 4))

    arr = np.random.default_rng(0).normal(
        size=sum(sizes)).astype(np.float32)
    storage = plan.scatter_unpadded(arr)
    assert storage.shape == plan.shape
    assert np.array_equal(plan.gather_unpadded(storage), arr)
    # permutation is an exact involution pair
    canon = plan.canonical_from_storage(storage)
    assert np.array_equal(plan.storage_from_canonical(canon), storage)
    # shard-major property: rank r's contiguous shard holds exactly its
    # piece of every bucket
    S = plan.piece_rows
    for b in plan.buckets:
        block = canon[b.start_row:b.start_row + b.rows].reshape(
            4, b.piece_rows, 1024)
        for r in range(4):
            piece = storage[r * S + b.piece_start:
                            r * S + b.piece_start + b.piece_rows]
            assert np.array_equal(piece, block[r])


def test_bucket_plan_single_oversized_leaf_and_empty():
    plan = BucketPlan([10 ** 6], dp=8, reduce_bucket_size=10,
                      allgather_bucket_size=10)
    assert plan.n_buckets == 1 and plan.buckets[0].elements == 10 ** 6
    empty = BucketPlan([], dp=4, reduce_bucket_size=10,
                       allgather_bucket_size=10)
    assert empty.rows % 4 == 0
    assert empty.gather_unpadded(
        np.zeros(empty.shape, np.float32)).size == 0


# ------------------------------------------------------------- parity
def test_bucketed_parity_vs_serialized_20_steps(cpu_devices):
    """The acceptance criterion: masters/opt state of the bucketed
    schedule track the unbucketed step over >= 20 steps.  Not
    bit-identical — the documented reduction-order tolerance (module
    docstring): the per-bucket psum_scatter and GSPMD's fused exchange
    associate the same per-rank sums differently, a few ulps/step."""
    steps = 22
    batches = random_batches(steps, 16, HIDDEN, seed=0)

    def run(overlap):
        engine = _engine(cpu_devices, overlap)
        assert engine.comm_overlap_enabled() == overlap
        losses = [float(np.asarray(engine.train_batch(iter([b]))))
                  for b in batches]
        state = _canonical_state(engine)
        engine.close()
        return losses, state

    l_on, s_on = run(True)
    l_off, s_off = run(False)
    np.testing.assert_allclose(l_on, l_off, rtol=1e-5)
    for key in ("master", "exp_avg", "exp_avg_sq"):
        np.testing.assert_allclose(s_on[key], s_off[key], atol=5e-6,
                                   err_msg=f"{key} diverged")
    # and the drift really is ulp-scale, not silently at the tolerance
    assert np.abs(s_on["master"] - s_off["master"]).max() < 1e-6


def test_bucketed_parity_with_grad_accumulation(cpu_devices):
    """acc=2: the per-micro-batch bucketed exchange accumulates in the
    scan carry exactly like the fused GSPMD exchange."""
    batches = random_batches(6, 32, HIDDEN, seed=1)

    def halves(batch):
        x, y = batch
        return iter([(x[:16], y[:16]), (x[16:], y[16:])])

    def run(overlap):
        engine = _engine(cpu_devices, overlap, acc=2)
        losses = [float(np.asarray(engine.train_batch(halves(b))))
                  for b in batches]
        state = _canonical_state(engine)
        engine.close()
        return losses, state

    l_on, s_on = run(True)
    l_off, s_off = run(False)
    np.testing.assert_allclose(l_on, l_off, rtol=1e-5)
    np.testing.assert_allclose(s_on["master"], s_off["master"],
                               atol=5e-6)


def test_bucketed_dp1_global_parity(cpu_devices):
    """dp=4 bucketed vs dp=1 single-chip on the SAME global batches:
    the exchange must compute the global mean gradient (a psum-for-
    pmean bug scales it by dp — far outside this band)."""
    batches = random_batches(4, 16, HIDDEN, seed=2)
    engine = _engine(cpu_devices, True)
    losses = [float(np.asarray(engine.train_batch(iter([b]))))
              for b in batches]
    engine.close()
    ref = _engine(cpu_devices, "auto", dp=1)
    assert not ref.comm_overlap_enabled()  # dp=1: nothing to bucket
    ref_losses = [float(np.asarray(ref.train_batch(iter([b]))))
                  for b in batches]
    ref.close()
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)


# -------------------------------------------------- checkpoint/layouts
def test_checkpoint_roundtrip_across_layouts_and_dp(cpu_devices,
                                                    tmp_path):
    """Checkpoints are canonical: bucketed (shard-major) saves restore
    bit-exactly into (a) the same geometry, (b) the serialized layout,
    and (c) a DIFFERENT dp degree's bucketed layout (bucket padding
    depends on dp, the canonical bytes do not)."""
    engine = _engine(cpu_devices, True)
    for b in random_batches(3, 16, HIDDEN, seed=3):
        engine.train_batch(iter([b]))
    want = _canonical_state(engine)
    engine.save_checkpoint(str(tmp_path), tag="ov")
    engine.wait_checkpoint()
    engine.close()

    for name, kwargs in (("same", dict(overlap=True)),
                         ("serialized", dict(overlap=False)),
                         ("dp2", dict(overlap=True, dp=2))):
        other = _engine(cpu_devices, **kwargs)
        path, _ = other.load_checkpoint(str(tmp_path), tag="ov")
        assert path is not None, name
        got = _canonical_state(other)
        for key in want:
            assert np.array_equal(want[key], got[key]), (name, key)
        # restored state trains (donation-safe re-homing)
        other.train_batch(iter([random_batches(1, 16, HIDDEN,
                                               seed=9)[0]]))
        other.close()


# ---------------------------------------------------- schedule/receipts
def test_schedule_declared_and_hlo_bucket_counts(cpu_devices, tmp_path):
    """The declared schedule matches the compiled HLO: exactly
    rs_buckets reduce-scatters and ag_buckets all-gathers in the fused
    step (the tiny loss pmean stays an all-reduce), and the sidecar
    round-trips the schedule for the offline verifier."""
    cfg = _cfg(True, telemetry={"enabled": True,
                                "run_dir": str(tmp_path / "run")},
               profiling={"comm_ledger": True, "memory_ledger": True})
    mesh = make_mesh({"data": 4}, devices=cpu_devices[:4])
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(HIDDEN, nlayers=NLAYERS), config=cfg,
        mesh=mesh)
    engine.train_batch(iter([random_batches(1, 16, HIDDEN, seed=0)[0]]))
    sched = engine.collective_schedule()
    assert sched["overlap"] is True
    assert sched["rs_buckets"] > 1 and sched["ag_buckets"] > 1
    plan = engine.flat.bucket_plan
    assert sched["rs_buckets"] == plan.n_buckets
    entry = engine.comm_ledger.entry("train_step")
    assert entry["ops"]["reduce-scatter"]["count"] == plan.n_buckets
    assert entry["ops"]["all-gather"]["count"] == len(plan.ag_groups)
    # reduce-scatter payload = the full fp32 flat buffer, once
    assert entry["ops"]["reduce-scatter"]["payload_bytes"] == (
        plan.rows * 1024 * 4)
    receipt = engine.overlap_receipt()
    assert 0 < receipt["exposed_wire_seconds"] < receipt["wire_seconds"]
    assert 0 < receipt["overlap_fraction"] < 1.0
    engine.close()

    from deepspeed_tpu.tools.dslint import programs as dsp

    arts = {a.name: a for a in dsp.load_run_artifacts(
        str(tmp_path / "run"))}
    assert arts["train_step"].collective_schedule == sched
    assert arts["cast_params"].collective_schedule == sched


def test_zero_stage2_control_unchanged(cpu_devices):
    """overlap_comm: false keeps the pre-round-14 layout: flat buffers
    at the canonical segments shape, no bucket plan, GSPMD exchange."""
    engine = _engine(cpu_devices, False)
    assert engine.flat.bucket_plan is None
    assert engine.flat.flat_shape == engine.segments.shape
    sched = engine.collective_schedule()
    assert sched is not None and sched["overlap"] is False
    engine.close()


# ------------------------------------------------------------- config
def test_overlap_comm_true_raises_on_unsupported(cpu_devices):
    mesh4 = make_mesh({"data": 4}, devices=cpu_devices[:4])
    model = SimpleModel(HIDDEN, nlayers=2)

    def init(zero, mesh=mesh4, **over):
        cfg = base_config(steps_per_print=10 ** 9,
                          zero_optimization=zero, **over)
        return deepspeed.initialize(model=model, config=cfg, mesh=mesh)

    with pytest.raises(ValueError, match="stage 2"):
        init({"stage": 1, "overlap_comm": True})
    with pytest.raises(ValueError, match="dp > 1"):
        init({"stage": 2, "overlap_comm": True},
             mesh=make_mesh({"data": 1}, devices=cpu_devices[:1]))
    with pytest.raises(ValueError, match="pure data-parallel"):
        init({"stage": 2, "overlap_comm": True},
             mesh=make_mesh({"data": 2, "model": 2},
                            devices=cpu_devices[:4]))
    with pytest.raises(ValueError, match="cpu_offload"):
        init({"stage": 2, "overlap_comm": True, "cpu_offload": True})
    with pytest.raises(ValueError, match="Adam"):
        init({"stage": 2, "overlap_comm": True},
             optimizer={"type": "Lamb", "params": {"lr": 1e-3}})


def test_stage3_unmet_requirements_raise_loudly(cpu_devices):
    """Round-20 contract: stage 3 never silently degrades — an
    unsupported composition raises a ValueError NAMING the unmet
    requirement (no 'stage 3 not supported' stubs remain)."""
    mesh4 = make_mesh({"data": 4}, devices=cpu_devices[:4])
    model = SimpleModel(HIDDEN, nlayers=2)

    def init(zero, mesh=mesh4, **over):
        cfg = base_config(steps_per_print=10 ** 9,
                          zero_optimization=zero, **over)
        return deepspeed.initialize(model=model, config=cfg, mesh=mesh)

    # sparse row-sparse exchange cannot ride the ÷dp-sharded parameter
    # space — the error says exactly that (and the fix)
    with pytest.raises(ValueError, match=r"sparse_gradients: true "
                                         r"requires ZeRO stage 0"):
        init({"stage": 3}, sparse_gradients=True)
    # explicit overlap_comm: true under stage 3 names the blocking
    # requirement, same contract as the stage-2 arm above
    with pytest.raises(ValueError, match="dp > 1"):
        init({"stage": 3, "overlap_comm": True},
             mesh=make_mesh({"data": 1}, devices=cpu_devices[:1]))
    with pytest.raises(ValueError, match="cpu_offload"):
        init({"stage": 3, "overlap_comm": True, "cpu_offload": True})
    with pytest.raises(ValueError, match="pure data-parallel"):
        init({"stage": 3, "overlap_comm": True},
             mesh=make_mesh({"data": 2, "model": 2},
                            devices=cpu_devices[:4]))
    with pytest.raises(ValueError, match="Adam"):
        init({"stage": 3, "overlap_comm": True},
             optimizer={"type": "Lamb", "params": {"lr": 1e-3}})


def test_overlap_comm_config_validation():
    from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig

    with pytest.raises(ValueError, match="overlap_comm"):
        DeepSpeedZeroConfig({"zero_optimization": {
            "stage": 2, "overlap_comm": "yes"}})
    with pytest.raises(ValueError, match="reduce_bucket_size"):
        DeepSpeedZeroConfig({"zero_optimization": {
            "stage": 2, "reduce_bucket_size": 0}})
    with pytest.raises(ValueError, match="allgather_bucket_size"):
        DeepSpeedZeroConfig({"zero_optimization": {
            "stage": 2, "allgather_bucket_size": True}})
    cfg = DeepSpeedZeroConfig({"zero_optimization": {"stage": 2}})
    assert cfg.overlap_comm == "auto"  # round-14 default
    # JSON scientific notation (the documented default idiom) parses as
    # an integral float — coerced, not rejected
    cfg = DeepSpeedZeroConfig({"zero_optimization": {
        "stage": 2, "reduce_bucket_size": 5e8,
        "allgather_bucket_size": 2.5e8}})
    assert cfg.reduce_bucket_size == 500000000
    assert isinstance(cfg.reduce_bucket_size, int)
    assert cfg.allgather_bucket_size == 250000000
    with pytest.raises(ValueError, match="reduce_bucket_size"):
        DeepSpeedZeroConfig({"zero_optimization": {
            "stage": 2, "reduce_bucket_size": 1.5}})


def test_auto_disables_on_unsupported_meshes(cpu_devices):
    """auto never raises: multi-axis meshes / stage 1 / dp=1 silently
    keep the GSPMD exchange (and declare no schedule)."""
    mesh = make_mesh({"data": 2, "model": 2}, devices=cpu_devices[:4])
    cfg = base_config(steps_per_print=10 ** 9,
                      zero_optimization={"stage": 2})
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(HIDDEN, nlayers=2), config=cfg, mesh=mesh)
    assert not engine.comm_overlap_enabled()
    assert engine.collective_schedule() is None
    engine.close()


# -------------------------------------------- compression padding unit
def test_compressed_allreduce_internal_padding_vs_reference(cpu_devices):
    """The satellite: ``compressed_allreduce`` pads unaligned sizes to
    8*world internally and trims on return — parity against the numpy
    reference running on the explicitly padded buffers."""
    import jax
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.comm.compression import (
        compressed_allreduce, compressed_allreduce_reference,
        padded_size)
    from deepspeed_tpu.utils.compat import shard_map

    world, n = 4, 100  # 100 % (8*4) != 0
    n_pad = padded_size(n, world)
    assert n_pad == 128 and padded_size(n_pad, world) == n_pad
    rng = np.random.default_rng(0)
    bufs = rng.normal(size=(world, n)).astype(np.float32)
    werrs = (rng.normal(size=(world, n_pad)) * 0.1).astype(np.float32)
    serrs = (rng.normal(size=(world, n_pad // world)) * 0.1).astype(
        np.float32)

    mesh = make_mesh({"data": world}, devices=cpu_devices[:world])

    def body(b, we, se):
        out, nwe, nse = compressed_allreduce(b[0], we[0], se[0], "data")
        return out[None], nwe[None], nse[None]

    out, nwe, nse = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("data"), P("data"), P("data")),
        out_specs=(P("data"), P("data"), P("data")),
        axis_names={"data"}, check_vma=False))(bufs, werrs, serrs)
    assert out.shape == (world, n)  # trimmed
    assert nwe.shape == (world, n_pad)  # errors stay padded

    padded_bufs = np.zeros((world, n_pad), np.float32)
    padded_bufs[:, :n] = bufs
    ref_out, ref_werrs, ref_serrs = compressed_allreduce_reference(
        list(padded_bufs), list(werrs), list(serrs))
    for r in range(world):
        np.testing.assert_allclose(np.asarray(out[r]), ref_out[:n],
                                   rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(nwe), np.stack(ref_werrs),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(nse), np.stack(ref_serrs),
                               rtol=1e-4, atol=1e-5)


def test_compressed_allreduce_rejects_wrong_error_sizes(cpu_devices):
    import jax
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.comm.compression import compressed_allreduce
    from deepspeed_tpu.utils.compat import shard_map

    mesh = make_mesh({"data": 4}, devices=cpu_devices[:4])

    def body(b, we, se):
        out, nwe, nse = compressed_allreduce(b[0], we[0], se[0], "data")
        return out[None], nwe[None], nse[None]

    bufs = np.zeros((4, 100), np.float32)
    bad_werrs = np.zeros((4, 100), np.float32)  # must be 128
    serrs = np.zeros((4, 32), np.float32)
    with pytest.raises(AssertionError, match="padded_size"):
        jax.jit(shard_map(
            body, mesh=mesh,
            in_specs=(P("data"), P("data"), P("data")),
            out_specs=(P("data"), P("data"), P("data")),
            axis_names={"data"}, check_vma=False))(bufs, bad_werrs,
                                                   serrs)
