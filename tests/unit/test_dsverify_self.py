"""Tier-1 CI self-verify: HEAD's REAL compiled step programs carry zero
DSP6xx program-verifier violations.

The dsverify analog of ``test_dslint_self.py``'s self-lint: the zero2
(dp×tp mesh), pipeline, and offload-in-jit (``DS_OFFLOAD_FORCE_INJIT``,
streamed update + bf16 error-feedback qres donation) step programs are
compiled on the virtual CPU mesh — warm under the suite's persistent
compile cache — then verified through BOTH surfaces: the live
``engine.verify_programs()`` hook and the offline
``dslint --programs <run_dir>`` CLI over the dumped artifacts.  Any
unsuppressed DSP6xx finding fails the suite with the diagnostics in the
assertion message.  (DSP602 downgraded verdicts are allowed: the warm
compile cache legitimately deserializes executables that report
alias=0 — the caveat the rule exists to make explicit.)

Since round 12 the offload-injit leg asserts the overlap analyzer's
verdict (DSO7xx) for the OVERLAPPED world: the double-buffered chunk
pipeline is the default (``offload_overlap: auto``), so the streamed
step program verifies overlap-CLEAN — no DSO702, bare ``--programs``
exits 0 — and the checked-in baseline records its exposed-wire metric
as the DSO704 ratchet.  The serialized control (``offload_overlap:
false``) must still trip DSO702 with STRICTLY MORE exposed wire, and
the (empty-violations) baseline must NOT absolve it: a change that
re-serializes the stream fails CI through exactly that path.
"""

import os

import numpy as np
import pytest

import deepspeed_tpu as deepspeed
import deepspeed_tpu.runtime.zero.coordinator as coord
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.tools.dslint.cli import main as dslint_main

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 64

CHECKED_IN_BASELINE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tools", "dslint_baseline.json")


def _assert_clean(engine, run_dir=None):
    report = engine.verify_programs()
    assert report is not None and report["programs_checked"] >= 1
    listing = "\n".join(d.format() for d in report["diagnostics"]
                        if not d.suppressed)
    assert report["violations"] == 0, (
        f"DSP6xx program-verifier violations in HEAD's compiled "
        f"programs:\n{listing}")
    if run_dir is not None:
        assert dslint_main(["--programs", str(run_dir)]) == 0
    return report


def _cfg(tmp_path, **overrides):
    cfg = base_config(
        steps_per_print=10 ** 9,
        telemetry={"enabled": True, "run_dir": str(tmp_path / "run")},
        profiling={"comm_ledger": True, "memory_ledger": True})
    cfg.update(overrides)
    return cfg


def test_zero2_dp_tp_step_programs_verify_clean(cpu_devices, tmp_path):
    """The flatten-×tp bug's home turf: a dp×tp mesh with ZeRO-2.  The
    fixed flatten plus the fused step must produce zero DSP6xx
    findings — the all-reduces stay on the data axis, the donation
    aliases materialize."""
    cfg = _cfg(tmp_path, zero_optimization={"stage": 2},
               gradient_clipping=1.0)
    mesh = make_mesh({"data": 2, "model": 2}, devices=cpu_devices[:4])
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(HIDDEN, nlayers=2), config=cfg, mesh=mesh)
    engine.train_batch(iter([random_batches(1, 16, HIDDEN, seed=0)[0]]))
    assert engine.flat.master_provenance == "jit_copy"
    report = _assert_clean(engine, run_dir=tmp_path / "run")
    # round 17: the sharding auditor ran (declared spec reconciled
    # against the compiled layout) and priced the step's residency
    sh = report["sharding"]["train_step"]
    assert sh["param_bytes_per_device"] > 0
    assert sh["param_shard_divisor"] >= 1
    engine.close()


def test_pipe_step_programs_verify_clean(cpu_devices, tmp_path):
    """The pipeline (step-wise) path compiles separate fwd_bwd / accum /
    apply_update / cast_params programs — all ride the same ledger hook
    and must verify clean."""
    from deepspeed_tpu.runtime.pipe import LayerSpec, PipelineModule

    class Linear:
        def __init__(self, in_dim, out_dim):
            self.in_dim, self.out_dim = in_dim, out_dim

        def init(self, rng):
            import jax

            k = jax.random.normal(rng, (self.in_dim, self.out_dim))
            return {"w": k * 0.1}

        def apply(self, params, x):
            import jax.numpy as jnp

            return jnp.tanh(x @ params["w"])

    def mse(outputs, labels):
        import jax.numpy as jnp

        return jnp.mean((outputs - labels) ** 2)

    cfg = _cfg(tmp_path)
    cfg["train_micro_batch_size_per_gpu"] = 4
    cfg["gradient_accumulation_steps"] = 4
    cfg.pop("train_batch_size", None)
    mesh = make_mesh({"pipe": 2, "data": 2}, devices=cpu_devices[:4])
    module = PipelineModule([LayerSpec(Linear, HIDDEN, HIDDEN)
                             for _ in range(4)], loss_fn=mse)
    engine, *_ = deepspeed.initialize(model=module, config=cfg, mesh=mesh)
    rng = np.random.default_rng(0)
    data = [(rng.normal(size=(8, HIDDEN)).astype(np.float32),
             rng.normal(size=(8, HIDDEN)).astype(np.float32))
            for _ in range(4)]
    engine.train_batch(iter(data))
    _assert_clean(engine, run_dir=tmp_path / "run")
    engine.close()


def _offload_engine(cpu_devices, tmp_path, run_name, overlap="auto"):
    cfg = _cfg(
        tmp_path,
        zero_optimization={
            "stage": 2, "cpu_offload": True, "offload_chunk_mb": 1,
            "offload_uniform_chunks": True,
            "offload_overlap": overlap,
            "offload_state_dtype": {"master": "bf16", "momentum": "bf16",
                                    "variance": "bf16",
                                    "error_feedback": True}})
    cfg["telemetry"]["run_dir"] = str(tmp_path / run_name)
    mesh = make_mesh({"data": 1}, devices=cpu_devices[:1])
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(256, nlayers=8), config=cfg, mesh=mesh)
    engine.train_batch(iter([random_batches(
        1, engine.train_micro_batch_size_per_gpu(), 256, seed=0)[0]]))
    return engine


def test_offload_injit_step_programs_verify_clean(cpu_devices, tmp_path,
                                                  monkeypatch):
    """The streamed-offload program (uniform-chunk lax.scan update,
    bf16 host state with error-feedback residuals): master/opt/qres
    buffers are donated through the fused step and the grouped
    pinned-host layout — the heaviest donation surface in the repo —
    and must verify clean under DS_OFFLOAD_FORCE_INJIT on CPU.  Since
    round 12 "clean" includes the overlap verdict: the double-buffered
    pipeline is the default, so NO DSO702 fires and the bare
    ``--programs`` run exits 0 — the baseline no longer needs to
    absolve anything, it records the exposed-wire ratchet metric."""
    monkeypatch.setenv("DS_OFFLOAD_FORCE_INJIT", "1")
    monkeypatch.setattr(coord, "HOST_GROUP_BYTES", 2 << 20)
    engine = _offload_engine(cpu_devices, tmp_path, "run")
    assert engine.flat.master_provenance == "host_staging_device_put"
    assert engine.state.get("qres"), "error-feedback residuals expected"
    assert engine._donation_specs["train_step"][-1] == 12  # qres donated
    sched = engine.host_stream_schedule()
    assert sched["overlap"] is True and sched["form"] == "scan"
    assert sched["prefetch_depth"] >= 2 and sched["chunks"] > 1
    report = engine.verify_programs()
    assert report is not None and report["violations"] == 0, [
        d.format() for d in report["diagnostics"] if not d.suppressed]
    assert report["overlap"] is not None
    assert report["overlap"]["serialized_host_transfers"] == 0
    declared = engine.host_state_bytes_per_step()
    assert declared and declared > 0
    receipt = engine.overlap_receipt()
    assert receipt["program"] == "train_step"
    # the pipeline fill/drain stays exposed (the model never claims a
    # free lunch), but some wire now hides behind the update compute
    assert 0 < receipt["exposed_wire_seconds"] < receipt["wire_seconds"]
    assert 0 < receipt["overlap_fraction"] < 1.0
    engine.close()
    # offline CLI: clean bare (exit 0) AND under the checked-in
    # baseline (exit 0 — the recorded exposed-wire metric holds)
    assert dslint_main(["--programs", str(tmp_path / "run")]) == 0
    assert dslint_main(["--programs", str(tmp_path / "run"),
                        "--baseline", CHECKED_IN_BASELINE]) == 0


def _zero2_overlap_engine(cpu_devices, tmp_path, run_name,
                          overlap=True):
    """The round-14 bucketed-exchange fixture: pure-dp ZeRO-2 with
    overlap_comm on (the overlapped schedule) or off (the serialized
    GSPMD control).  Deterministic geometry — the checked-in baseline
    records this fixture's collective exposure as the DSO704 ratchet
    (comm_exposed_wire_seconds keys, next to the offload fixture's
    host-stream keys)."""
    cfg = _cfg(
        tmp_path,
        zero_optimization={"stage": 2, "overlap_comm": overlap,
                           # 8 x 65792-element layers: 4 reduce
                           # buckets, 2 all-gather groups
                           "reduce_bucket_size": 140000,
                           "allgather_bucket_size": 280000},
        gradient_clipping=1.0)
    cfg["telemetry"]["run_dir"] = str(tmp_path / run_name)
    mesh = make_mesh({"data": 4}, devices=cpu_devices[:4])
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(256, nlayers=8), config=cfg, mesh=mesh)
    engine.train_batch(iter([random_batches(
        1, engine.train_micro_batch_size_per_gpu() * 4, 256,
        seed=0)[0]]))
    return engine


def test_zero2_overlap_step_programs_verify_clean(cpu_devices,
                                                  tmp_path):
    """The round-14 acceptance criterion, overlap side: the bucketed
    zero-2 step verifies CLEAN — per-bucket reduce-scatters + per-group
    all-gathers re-priced by the declared schedule, DSO701 quiet, bare
    ``--programs`` exit 0, and the checked-in baseline's
    comm-exposure metrics hold (DSO704)."""
    engine = _zero2_overlap_engine(cpu_devices, tmp_path, "run")
    assert engine.comm_overlap_enabled()
    sched = engine.collective_schedule()
    assert sched["overlap"] is True and sched["rs_buckets"] == 4, sched
    assert sched["ag_buckets"] == 2, sched
    report = _assert_clean(engine)
    assert report["overlap"] is not None
    # on this CPU toy the compute budget cannot hide every bucket
    # (some stay classified serialized — honestly: there is nothing to
    # hide behind), but real wire DID move behind compute
    agg = report["overlap"]
    assert agg["exposed_wire_seconds"] < agg["wire_seconds"]
    receipt = engine.overlap_receipt()
    assert receipt["program"] == "train_step"
    # fill/drain stays exposed (no free lunch); steady state hides
    assert 0 < receipt["exposed_wire_seconds"] < receipt["wire_seconds"]
    assert 0 < receipt["overlap_fraction"] < 1.0
    engine.close()
    assert dslint_main(["--programs", str(tmp_path / "run")]) == 0
    assert dslint_main(["--programs", str(tmp_path / "run"),
                        "--baseline", CHECKED_IN_BASELINE]) == 0


def test_zero2_serialized_control_trips_dso701_and_ratchet(
        cpu_devices, tmp_path):
    """``overlap_comm: false`` — the serialized GSPMD control.  DSO701
    must fire on the fused step with a NONZERO independent-compute
    window (the declared potential the bucketed schedule would free),
    its exposed wire must be STRICTLY higher than the overlapped
    schedule's, and the checked-in baseline must NOT absolve it."""
    eng_on = _zero2_overlap_engine(cpu_devices, tmp_path, "run_on")
    on = eng_on.overlap_receipt()
    eng_on.close()
    eng_off = _zero2_overlap_engine(cpu_devices, tmp_path, "run_off",
                                    overlap=False)
    assert not eng_off.comm_overlap_enabled()
    assert eng_off.collective_schedule()["overlap"] is False
    report = eng_off.verify_programs()
    dso701 = [d for d in report["diagnostics"]
              if d.rule_id == "DSO701"]
    assert dso701 and any("[train_step]" in d.message
                          for d in dso701), [
        d.format() for d in report["diagnostics"]]
    msg = next(d.message for d in dso701 if "[train_step]" in d.message)
    # a NONZERO independent-compute window is quoted in the finding
    import re as _re

    m = _re.search(r"up to ([0-9.]+) ms of independent compute", msg)
    assert m and float(m.group(1)) > 0, msg
    off = eng_off.overlap_receipt()
    eng_off.close()
    assert on["exposed_wire_seconds"] < off["exposed_wire_seconds"]
    assert on["overlap_fraction"] > off["overlap_fraction"]
    assert dslint_main(["--programs", str(tmp_path / "run_off")]) == 1
    assert dslint_main(["--programs", str(tmp_path / "run_off"),
                        "--baseline", CHECKED_IN_BASELINE]) == 1


def test_offload_serialized_control_trips_dso702_and_ratchet(
        cpu_devices, tmp_path, monkeypatch):
    """``offload_overlap: false`` — the serialized control schedule.
    Its exposed wire must be STRICTLY higher than the overlapped
    schedule's (the round-12 acceptance criterion), DSO702 must fire on
    the fused step, and the checked-in baseline must NOT absolve it:
    any future change that re-serializes the stream fails CI through
    this exact path (empty violations baseline + DSO704 metric
    ratchet)."""
    monkeypatch.setenv("DS_OFFLOAD_FORCE_INJIT", "1")
    monkeypatch.setattr(coord, "HOST_GROUP_BYTES", 2 << 20)
    eng_on = _offload_engine(cpu_devices, tmp_path, "run_on")
    on = eng_on.overlap_receipt()
    eng_on.close()
    eng_off = _offload_engine(cpu_devices, tmp_path, "run_off",
                              overlap=False)
    assert eng_off.host_stream_schedule()["overlap"] is False
    assert eng_off._offload_prefetch_depth == 1
    report = eng_off.verify_programs()
    dso702 = [d for d in report["diagnostics"] if d.rule_id == "DSO702"]
    assert len(dso702) == 1 and "[train_step]" in dso702[0].message, [
        d.format() for d in report["diagnostics"]]
    off = eng_off.overlap_receipt()
    eng_off.close()
    # the acceptance criterion: exposed-wire fraction strictly lower
    # with offload_overlap: on than off, same model/geometry
    assert on["exposed_wire_seconds"] < off["exposed_wire_seconds"]
    assert on["overlap_fraction"] > off["overlap_fraction"]
    # the serialized control fails a bare --programs run AND the
    # checked-in (empty-violations) baseline run: re-serialization is
    # CI-fatal through the fresh DSO702 (the DSO704 metric ratchet
    # guards the subtler partial regressions — test_overlap.py)
    assert dslint_main(["--programs", str(tmp_path / "run_off")]) == 1
    assert dslint_main(["--programs", str(tmp_path / "run_off"),
                        "--baseline", CHECKED_IN_BASELINE]) == 1


def _zero3_engine(cpu_devices, tmp_path, run_name, overlap=True):
    """The round-20 stage-3 fixture: the SAME geometry/buckets as
    ``_zero2_overlap_engine`` but with sharded parameters — the flat
    fp32 master is the only persistent parameter surface (÷dp
    resident), and the step program issues the JIT per-group
    all-gathers inline.  ``overlap=False`` is the serialized GSPMD
    control (a single full-tensor gather schedule the analyzer must
    flag)."""
    cfg = _cfg(
        tmp_path,
        zero_optimization={"stage": 3, "overlap_comm": overlap,
                           "reduce_bucket_size": 140000,
                           "allgather_bucket_size": 280000},
        gradient_clipping=1.0)
    cfg["telemetry"]["run_dir"] = str(tmp_path / run_name)
    mesh = make_mesh({"data": 4}, devices=cpu_devices[:4])
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(256, nlayers=8), config=cfg, mesh=mesh)
    engine.train_batch(iter([random_batches(
        1, engine.train_micro_batch_size_per_gpu() * 4, 256,
        seed=0)[0]]))
    return engine


def test_zero3_step_programs_verify_clean(cpu_devices, tmp_path):
    """Round-20 acceptance criterion, overlap+sharding side: the
    stage-3 step — JIT per-group parameter all-gathers in forward
    order, rematerialized on backward, gradients arriving reduced AND
    sharded through the all-gather transpose — verifies CLEAN.  DSO701
    quiet, DSS801 clean with the ÷dp residency receipt
    (param_shard_divisor == dp), bare ``--programs`` exit 0, and the
    checked-in baseline's tag-qualified pins hold."""
    engine = _zero3_engine(cpu_devices, tmp_path, "run")
    assert engine.comm_overlap_enabled()
    sched = engine.collective_schedule()
    assert sched["overlap"] is True and sched["param_gathers"] is True
    assert sched["rs_buckets"] == 4 and sched["ag_buckets"] == 2, sched
    assert sched["gather_bytes"] > 0
    report = _assert_clean(engine)
    assert report["overlap"] is not None
    agg = report["overlap"]
    assert agg["exposed_wire_seconds"] < agg["wire_seconds"]
    sh = report["sharding"]["train_step"]
    assert sh["param_shard_divisor"] == 4
    # the ÷dp receipt: 528 padded rows × 1024 lanes × 4 B over dp=4
    assert sh["param_bytes_per_device"] == 528 * 1024 * 4 // 4
    receipt = engine.overlap_receipt()
    assert receipt["program"] == "train_step"
    assert 0 < receipt["exposed_wire_seconds"] < receipt["wire_seconds"]
    assert 0 < receipt["overlap_fraction"] < 1.0
    engine.close()
    assert dslint_main(["--programs", str(tmp_path / "run")]) == 0
    assert dslint_main(["--programs", str(tmp_path / "run"),
                        "--baseline", CHECKED_IN_BASELINE]) == 0


def test_zero3_serialized_control_trips_dso701_and_ratchet(
        cpu_devices, tmp_path):
    """``overlap_comm: false`` under stage 3 — the serialized control:
    parameters still shard ÷dp but the gathers ride the un-bucketed
    GSPMD schedule.  DSO701 must fire on the fused step with a NONZERO
    independent-compute window, its exposed wire must be STRICTLY
    higher than the overlapped schedule's, and the checked-in baseline
    must NOT absolve it."""
    eng_on = _zero3_engine(cpu_devices, tmp_path, "run_on")
    on = eng_on.overlap_receipt()
    eng_on.close()
    eng_off = _zero3_engine(cpu_devices, tmp_path, "run_off",
                            overlap=False)
    assert not eng_off.comm_overlap_enabled()
    report = eng_off.verify_programs()
    dso701 = [d for d in report["diagnostics"]
              if d.rule_id == "DSO701"]
    assert dso701 and any("[train_step]" in d.message
                          for d in dso701), [
        d.format() for d in report["diagnostics"]]
    msg = next(d.message for d in dso701 if "[train_step]" in d.message)
    import re as _re

    m = _re.search(r"up to ([0-9.]+) ms of independent compute", msg)
    assert m and float(m.group(1)) > 0, msg
    off = eng_off.overlap_receipt()
    eng_off.close()
    assert on["exposed_wire_seconds"] < off["exposed_wire_seconds"]
    assert on["overlap_fraction"] > off["overlap_fraction"]
    assert dslint_main(["--programs", str(tmp_path / "run_off")]) == 1
    assert dslint_main(["--programs", str(tmp_path / "run_off"),
                        "--baseline", CHECKED_IN_BASELINE]) == 1


def test_serving_decode_programs_verify_clean(cpu_devices, tmp_path):
    """Round-17 serving leg of the self-verify suite: the paged-KV
    decode/prefill programs carry a declared spec (``serve|data1`` —
    replicated serve weights + KV cache) and verify clean on BOTH
    surfaces, with the decode program's residency receipt priced (the
    ``serving_param_bytes_per_device`` field bench_serving quotes)."""
    import json

    import jax

    from deepspeed_tpu.inference import InferenceEngine
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadTPU
    from deepspeed_tpu.tools.dslint import programs as dsp

    model = GPT2LMHeadTPU(GPT2Config(
        vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
        max_position_embeddings=64, embd_dropout=0.0, attn_dropout=0.0,
        resid_dropout=0.0))
    params = model.init(jax.random.PRNGKey(0))
    cfg = {
        "inference": {"kv_block_size": 8, "kv_blocks": 64,
                      "max_batch_slots": 2, "max_seq_len": 64,
                      "prefill_buckets": [16], "token_budget": 256,
                      "max_new_tokens": 4},
        "steps_per_print": 10 ** 9,
        "telemetry": {"enabled": True, "run_dir": str(tmp_path / "run")},
        "profiling": {"comm_ledger": True, "memory_ledger": True},
    }
    engine = InferenceEngine(model, params, config=cfg)
    rng = np.random.default_rng(0)
    for i in range(3):
        engine.submit([int(t) for t in rng.integers(0, 256, size=8)],
                      request_id=f"r{i}")
    engine.run()
    report = engine.verify_programs()
    assert report is not None and report["violations"] == 0, [
        d.format() for d in report["diagnostics"] if not d.suppressed]
    sh = report["sharding"]["serve_decode"]
    assert sh["param_bytes_per_device"] > 0
    assert sh["param_shard_divisor"] == 1        # single-chip serve
    engine.close()
    # the sidecar carries the serve-tagged declaration; offline load
    # agrees and the bare --programs CLI stays clean
    side = json.loads((tmp_path / "run" / "programs" /
                       "serve_decode.json").read_text())
    decl = side["declared_sharding"]
    assert decl["tag"] == "serve|data1"
    assert set(decl["families"]) == {"params", "kv_cache"}
    assert decl["families"]["kv_cache"]["total_bytes"] > 0
    arts = {a.name: a
            for a in dsp.load_run_artifacts(str(tmp_path / "run"))}
    assert arts["serve_decode"].declared_sharding == decl
    assert dslint_main(["--programs", str(tmp_path / "run")]) == 0
