"""The examples/ scripts actually run (CPU-scale smoke)."""

import os
import subprocess
import sys
import pytest

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")


def _run(script, *args):
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *args],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=280)
    assert proc.returncode == 0, (
        f"{script} failed\nstdout:{proc.stdout[-2000:]}\n"
        f"stderr:{proc.stderr[-2000:]}")
    assert "final loss:" in proc.stdout
    return proc.stdout


@pytest.mark.slow
def test_bert_example():
    _run("bert_pretraining.py", "--steps", "3", "--batch", "8",
         "--seq", "32", "--model", "tiny", "--zero", "2",
         "--data_parallel", "4")


@pytest.mark.slow
def test_gpt2_pipeline_example():
    _run("gpt2_pipeline.py", "--steps", "2", "--pipe", "2", "--data", "2",
         "--layers", "4", "--micro_batch", "2", "--grad_acc", "2",
         "--seq", "32", "--vocab", "256")


@pytest.mark.slow
def test_bench_serving_example():
    import json

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples",
                                      "bench_serving.py"), "8", "0"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=280)
    assert proc.returncode == 0, (
        f"bench_serving.py failed\nstdout:{proc.stdout[-2000:]}\n"
        f"stderr:{proc.stderr[-2000:]}")
    assert "bench-serving-schema" not in proc.stderr
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    assert record["serving_requests"] == 8
    assert record["serving_dsp_violations"] == 0
    assert record["serving_programs_compiled"] <= 3
    assert record["serving_per_token_p50_seconds"] > 0
