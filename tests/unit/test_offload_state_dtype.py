"""Reduced-precision host optimizer state (``offload_state_dtype``).

Runs the real in-jit streamed paths on CPU via ``DS_OFFLOAD_FORCE_INJIT``
(same lever as ``test_offload_stream.py``).  The contract under test:

- fp32 default: NO quantization plan — programs and trajectories are
  identical to a config without the block at all;
- bf16 storage + fp32 math + a write-back mechanism (stochastic
  rounding or error feedback) tracks the fp32 loss curve over ≥200
  steps within tolerance, in BOTH streamed forms (scan and unrolled);
- the mechanism is load-bearing: plain nearest rounding demonstrably
  drifts where SR/EF track;
- wire bytes: the all-bf16 SR layout moves exactly HALF the fp32 state
  bytes per step (the headline the driver bench asserts);
- error-feedback residuals persist across checkpoint save/restore
  bit-exactly, and checkpoints load across state-dtype layouts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as deepspeed
import deepspeed_tpu.runtime.zero.coordinator as coord
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.runtime.zero import qstate

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 64
NLAYERS = 2

BF16_SR = "bf16"
BF16_EF = {"momentum": "bf16", "variance": "bf16", "master": "bf16",
           "error_feedback": True}
BF16_NEAREST = {"momentum": "bf16", "variance": "bf16", "master": "bf16",
                "rounding": "nearest"}


@pytest.fixture
def force_injit(monkeypatch):
    """CPU backend executes the in-jit streamed program structure, with
    row-grouping forced at toy scale and the host-buffer COUNT cap
    lifted (the residual families would otherwise collapse toy state
    back into one group)."""
    monkeypatch.setenv("DS_OFFLOAD_FORCE_INJIT", "1")
    monkeypatch.setattr(coord, "HOST_GROUP_BYTES", 1 << 20)
    monkeypatch.setattr(coord, "MAX_HOST_BUFFERS", 64)


def _engine(cpu_devices, state_dtype=None, uniform=True, hidden=HIDDEN,
            nlayers=NLAYERS, **cfg_kw):
    mesh = make_mesh({"data": 1}, devices=cpu_devices[:1])
    zo = {"stage": 2, "cpu_offload": True, "offload_chunk_mb": 1,
          "offload_uniform_chunks": uniform}
    if state_dtype is not None:
        zo["offload_state_dtype"] = state_dtype
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(hidden, nlayers=nlayers),
        config=base_config(zero_optimization=zo, **cfg_kw), mesh=mesh)
    return engine


def _losses(engine, steps, hidden=HIDDEN, seed=0):
    batch = random_batches(1, engine.train_micro_batch_size_per_gpu(),
                           hidden, seed=seed)[0]
    return np.array([float(np.asarray(engine.train_batch(iter([batch]))))
                     for _ in range(steps)])


# ------------------------------------------------------------- config
def test_config_validation():
    from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig

    def zc(sub, cpu_offload=True):
        return DeepSpeedZeroConfig({"zero_optimization": {
            "stage": 2, "cpu_offload": cpu_offload,
            "offload_state_dtype": sub}})

    with pytest.raises(ValueError, match="must be one of"):
        zc({"momentum": "int8"})
    with pytest.raises(ValueError, match="master does not support fp16"):
        zc({"master": "fp16"})
    with pytest.raises(ValueError, match="rounding"):
        zc({"momentum": "bf16", "rounding": "sideways"})
    with pytest.raises(ValueError, match="error_feedback must be a bool"):
        zc({"momentum": "bf16", "error_feedback": "yes"})
    with pytest.raises(ValueError, match="requires\\s+cpu_offload"):
        zc("bf16", cpu_offload=False)

    # shorthand: one dtype name for the whole block; fp16 keeps the
    # master at the range-safe bf16
    c = zc("bf16")
    assert c.offload_state_dtype["master"] == "bf16"
    assert c.offload_state_dtype["momentum"] == "bf16"
    assert c.offload_state_dtype["variance"] == "bf16"
    assert c.offload_state_reduced
    c16 = zc("fp16")
    assert c16.offload_state_dtype["master"] == "bf16"
    assert c16.offload_state_dtype["momentum"] == "fp16"
    # residual-family accounting drives the host-buffer-count cap
    assert zc(BF16_EF).offload_state_residual_count == 3
    assert zc("bf16").offload_state_residual_count == 0


def test_default_fp32_is_inert(force_injit, cpu_devices):
    """An explicit all-fp32 block is the SAME configuration as no block:
    no quantization plan, no residual state, bit-identical trajectory
    (the byte-identical default-path contract)."""
    eng_none = _engine(cpu_devices)
    eng_fp32 = _engine(cpu_devices, state_dtype={"master": "fp32"})
    assert eng_none._state_quant is None
    assert eng_fp32._state_quant is None
    assert eng_fp32.state["qres"] is None
    np.testing.assert_array_equal(_losses(eng_fp32, 4), _losses(eng_none, 4))


# ------------------------------------------------------------- parity
@pytest.mark.parametrize("uniform", [True, False],
                         ids=["scan", "unrolled"])
def test_bf16_sr_parity_200_steps(force_injit, cpu_devices, uniform):
    """bf16 storage + stochastic rounding tracks the fp32 loss curve
    over 200+ steps in both streamed layouts."""
    fp32 = _losses(_engine(cpu_devices, uniform=uniform), 200)
    eng = _engine(cpu_devices, state_dtype=BF16_SR, uniform=uniform)
    assert eng._offload_uniform == uniform
    assert eng._state_quant is not None and eng.state["qres"] is None
    # storage really is bf16, in pinned-host layout
    masters = (eng.state["master"] if type(eng.state["master"]) is tuple
               else (eng.state["master"],))
    assert all(m.dtype == jnp.bfloat16 for m in masters)
    for leaf in jax.tree_util.tree_leaves(eng.state["opt"]):
        if getattr(leaf, "ndim", 0) == 2:
            assert leaf.dtype == jnp.bfloat16
    bf16 = _losses(eng, 200)
    np.testing.assert_allclose(bf16, fp32, rtol=2e-2, atol=2e-3)
    assert bf16[-1] < bf16[0]


def test_bf16_ef_parity_200_steps(force_injit, cpu_devices):
    """Error feedback (deterministic residual carry) tracks fp32 at
    least as tightly, and the residual buffers actually accumulate."""
    fp32 = _losses(_engine(cpu_devices), 200)
    eng = _engine(cpu_devices, state_dtype=BF16_EF)
    assert set(eng.state["qres"]) == {"master", "exp_avg", "exp_avg_sq"}
    ef = _losses(eng, 200)
    np.testing.assert_allclose(ef, fp32, rtol=2e-2, atol=2e-3)
    for name, buf in eng.state["qres"].items():
        groups = buf if type(buf) is tuple else (buf,)
        assert all(g.dtype == jnp.bfloat16 for g in groups)
        total = sum(float(jnp.sum(jnp.abs(g.astype(jnp.float32))))
                    for g in groups)
        assert total > 0.0, f"residual {name} never accumulated"


@pytest.mark.parametrize("uniform", [True, False],
                         ids=["scan", "unrolled"])
def test_bf16_composes_with_offload_gradients(force_injit, cpu_devices,
                                              uniform):
    """The host-gradient leg (reverse-order spill + per-chunk coef
    fold) composes with reduced state in both streamed forms."""
    def eng(state_dtype):
        mesh = make_mesh({"data": 1}, devices=cpu_devices[:1])
        zo = {"stage": 2, "cpu_offload": True, "offload_chunk_mb": 1,
              "offload_uniform_chunks": uniform,
              "offload_gradients": True}
        if state_dtype:
            zo["offload_state_dtype"] = state_dtype
        e, *_ = deepspeed.initialize(
            model=SimpleModel(HIDDEN, nlayers=NLAYERS),
            config=base_config(zero_optimization=zo,
                               gradient_clipping=1.0), mesh=mesh)
        return e

    fp32 = _losses(eng(None), 30)
    e_b = eng(BF16_EF)
    assert e_b._offload_grads
    bf16 = _losses(e_b, 30)
    np.testing.assert_allclose(bf16, fp32, rtol=2e-2, atol=2e-3)


def test_mechanism_is_load_bearing(force_injit, cpu_devices):
    """The ISSUE's control: with BOTH mechanisms off (nearest rounding,
    no residuals) sub-ulp updates are dropped and the loss curve drifts
    measurably away from fp32, while SR and EF stay locked on — the
    mechanism, not the dtype, carries the accuracy."""
    steps = 220
    fp32 = _losses(_engine(cpu_devices), steps)
    sr = _losses(_engine(cpu_devices, state_dtype=BF16_SR), steps)
    ef = _losses(_engine(cpu_devices, state_dtype=BF16_EF), steps)
    nr = _losses(_engine(cpu_devices, state_dtype=BF16_NEAREST), steps)

    def tail_dev(x):
        d = np.abs(x - fp32) / np.maximum(np.abs(fp32), 1e-8)
        return float(d[-50:].mean())

    dev_sr, dev_ef, dev_nr = tail_dev(sr), tail_dev(ef), tail_dev(nr)
    # measured margins on this toy: nr ~2.7e-3 vs sr ~1.1e-4 / ef ~5e-5
    assert dev_nr > 5e-4, (dev_nr, "control failed to drift")
    assert dev_nr > 3 * dev_sr, (dev_nr, dev_sr)
    assert dev_nr > 3 * dev_ef, (dev_nr, dev_ef)


# -------------------------------------------------------- wire bytes
def test_wire_bytes_halved(force_injit, cpu_devices):
    """The headline claim, asserted at the accounting level the bench
    JSON quotes: all-bf16 SR state moves exactly half the fp32 wire
    bytes; all-bf16 EF moves the same as fp32 (residuals ride the
    wire too — why SR is the default)."""
    from deepspeed_tpu.ops.op_common import LANES

    e_fp32 = _engine(cpu_devices)
    e_sr = _engine(cpu_devices, state_dtype=BF16_SR)
    e_ef = _engine(cpu_devices, state_dtype=BF16_EF)
    b_fp32 = e_fp32.host_state_bytes_per_step()
    assert b_fp32 == 2 * e_fp32.segments.rows * LANES * 4 * 3
    assert e_sr.host_state_bytes_per_step() * 2 == b_fp32
    assert e_ef.host_state_bytes_per_step() == b_fp32
    assert e_sr.host_state_dtype() == "bf16"
    assert e_fp32.host_state_dtype() == "fp32"
    # the pure accounting helper agrees with the engine
    assert qstate.host_state_bytes_per_step(
        e_sr.segments.rows, LANES, e_sr._state_quant) == \
        e_sr.host_state_bytes_per_step()


# -------------------------------------------------------- checkpoints
def test_ef_residual_checkpoint_persistence(force_injit, cpu_devices,
                                            tmp_path):
    """Residuals are training state: a same-layout save/restore is
    bit-exact (buffers AND the next step's loss)."""
    eng = _engine(cpu_devices, state_dtype=BF16_EF)
    _losses(eng, 3)
    eng.save_checkpoint(str(tmp_path))

    eng2 = _engine(cpu_devices, state_dtype=BF16_EF)
    eng2.load_checkpoint(str(tmp_path))
    for name in eng.state["qres"]:
        a = eng.state["qres"][name]
        b = eng2.state["qres"][name]
        for ga, gb in zip(a if type(a) is tuple else (a,),
                          b if type(b) is tuple else (b,)):
            np.testing.assert_array_equal(np.asarray(ga), np.asarray(gb))
    batch = random_batches(1, eng.train_micro_batch_size_per_gpu(),
                           HIDDEN, seed=0)[0]
    l_ref = float(np.asarray(eng.train_batch(iter([batch]))))
    l_res = float(np.asarray(eng2.train_batch(iter([batch]))))
    assert l_ref == l_res, (l_ref, l_res)


@pytest.mark.parametrize("src,dst", [
    (BF16_EF, None), (None, BF16_EF), (BF16_SR, None), (None, BF16_SR),
], ids=["ef-to-fp32", "fp32-to-ef", "sr-to-fp32", "fp32-to-sr"])
def test_cross_dtype_checkpoint_load(force_injit, cpu_devices, tmp_path,
                                     src, dst):
    """Checkpoints stay canonical fp32 and load across state-dtype
    layouts: residuals fold into the values on the way out of an EF
    layout, and re-derive from the exact rounding error on the way in."""
    eng = _engine(cpu_devices, state_dtype=src)
    losses = _losses(eng, 3)
    eng.save_checkpoint(str(tmp_path))

    eng2 = _engine(cpu_devices, state_dtype=dst)
    eng2.load_checkpoint(str(tmp_path))
    batch = random_batches(1, eng2.train_micro_batch_size_per_gpu(),
                           HIDDEN, seed=0)[0]
    l_resumed = float(np.asarray(eng2.train_batch(iter([batch]))))
    l_ref = float(np.asarray(eng.train_batch(iter([batch]))))
    np.testing.assert_allclose(l_resumed, l_ref, rtol=5e-3, atol=5e-4)
    assert losses[-1] < losses[0]
    if dst is BF16_EF:
        # an fp32 checkpoint's master is NOT bf16-representable: the
        # load must capture the rounding error into the residual, not
        # silently discard it
        total = sum(
            float(jnp.sum(jnp.abs(g.astype(jnp.float32))))
            for buf in eng2.state["qres"].values()
            for g in (buf if type(buf) is tuple else (buf,)))
        assert total > 0.0


# ------------------------------------------------------------ qstate
def test_stochastic_round_unbiased_and_neighbor_valued():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4096,)).astype(np.float32) * 0.37)
    lo = x.astype(jnp.bfloat16)  # nearest — a valid neighbor either way
    draws = []
    for i in range(64):
        q = qstate.stochastic_round(x, jnp.bfloat16,
                                    jax.random.PRNGKey(i))
        q32 = np.asarray(q, np.float32)
        # every output is one of the two bracketing bf16 neighbors
        ulp = np.abs(np.asarray(lo, np.float32)) * 2.0 ** -7 + 1e-45
        assert np.all(np.abs(q32 - np.asarray(x)) <= ulp)
        draws.append(q32)
    mean = np.mean(draws, axis=0)
    err_sr = np.abs(mean - np.asarray(x))
    err_nearest = np.abs(np.asarray(lo, np.float32) - np.asarray(x))
    # unbiased: averaging 64 draws beats nearest's deterministic error
    assert err_sr.mean() < err_nearest.mean()

    special = jnp.asarray([np.inf, -np.inf, np.nan, 0.0, -0.0],
                          jnp.float32)
    qs = np.asarray(qstate.stochastic_round(special, jnp.bfloat16,
                                            jax.random.PRNGKey(0)),
                    np.float32)
    assert qs[0] == np.inf and qs[1] == -np.inf and np.isnan(qs[2])
    assert qs[3] == 0.0 and qs[4] == 0.0


def test_ef_store_roundtrip_precision():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(1024,)).astype(np.float32))
    q, r = qstate.ef_store(x, jnp.bfloat16)
    assert q.dtype == jnp.bfloat16 and r.dtype == jnp.bfloat16
    recon = np.asarray(q, np.float32) + np.asarray(r, np.float32)
    # q + r carries ~16 mantissa bits: worst case well under bf16's ulp
    rel = np.abs(recon - np.asarray(x)) / np.maximum(
        np.abs(np.asarray(x)), 1e-30)
    assert rel.max() < 2.0 ** -14


def test_scan_core_overflow_skip_bit_exact_reduced():
    """The fp16/guard skip contract survives quantization: on overflow
    every chunk keeps its stored bf16 values AND residuals bit-exactly."""
    from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
    from deepspeed_tpu.ops.op_common import LANES
    from deepspeed_tpu.runtime.zero import stream

    opt = FusedAdam()
    quant = qstate.build_state_quant(
        {"master": "bf16", "momentum": "bf16", "variance": "bf16",
         "error_feedback": True},
        jax.eval_shape(opt.init_state,
                       jax.ShapeDtypeStruct((32, LANES), jnp.float32)))
    rng = np.random.default_rng(2)
    rows, chunk_rows = 32, 8
    master = jnp.asarray(rng.normal(size=(rows, LANES)), jnp.bfloat16)
    res_m = jnp.asarray(rng.normal(size=(rows, LANES)) * 1e-3,
                        jnp.bfloat16)
    st = opt.init_state(jnp.zeros((rows, LANES), jnp.float32))
    leaves, treedef = jax.tree_util.tree_flatten(st)
    is_flat = [getattr(l, "ndim", 0) == 2 for l in leaves]
    leaves = [jnp.zeros((rows, LANES), jnp.bfloat16) if f else l
              for l, f in zip(leaves, is_flat)]
    res_f = [jnp.zeros((rows, LANES), jnp.bfloat16) for _ in range(2)]
    out = stream.uniform_scan_update(
        masters=[master], group_leaves=[list(leaves)], is_flat=is_flat,
        opt_treedef=treedef, update_fn=opt.update, hp=opt.hyperparams(),
        overflow=jnp.asarray(True), skip_bad=True,
        jobs=stream.uniform_chunk_jobs(((0, rows),), chunk_rows),
        chunk_rows=chunk_rows, lanes=LANES,
        g=jnp.asarray(rng.normal(size=(rows, LANES)), jnp.float32),
        quant=quant, res_masters=[res_m], res_group_leaves=[res_f])
    new_m, new_gl, new_scalars, new_resm, new_resf = out
    np.testing.assert_array_equal(np.asarray(new_m[0]),
                                  np.asarray(master))
    np.testing.assert_array_equal(np.asarray(new_resm[0]),
                                  np.asarray(res_m))
    np.testing.assert_array_equal(np.asarray(new_gl[0][0]),
                                  np.asarray(leaves[0]))
    assert int(np.asarray(new_scalars[0])) == 0


def test_reduced_requires_adam_and_injit(cpu_devices, monkeypatch):
    """Reduced dtypes must fail LOUDLY off the streamed-Adam path."""
    monkeypatch.setenv("DS_OFFLOAD_FORCE_INJIT", "1")
    mesh = make_mesh({"data": 1}, devices=cpu_devices[:1])
    with pytest.raises(ValueError, match="Adam"):
        deepspeed.initialize(
            model=SimpleModel(HIDDEN, nlayers=1),
            config=base_config(
                optimizer={"type": "Lamb", "params": {"lr": 0.01}},
                zero_optimization={"stage": 2, "cpu_offload": True,
                                   "offload_state_dtype": "bf16"}),
            mesh=mesh)
    monkeypatch.delenv("DS_OFFLOAD_FORCE_INJIT")
    with pytest.raises(ValueError, match="in-jit host placement"):
        deepspeed.initialize(
            model=SimpleModel(HIDDEN, nlayers=1),
            config=base_config(
                zero_optimization={"stage": 2, "cpu_offload": True,
                                   "offload_state_dtype": "bf16"}),
            mesh=mesh)
