"""Fleet-integrity recovery, proven end-to-end on the real launcher.

THE chaos e2e pair (PR 15 acceptance):

- **bitflip**: a seeded SDC on one rank of a 4-process fleet → the
  fingerprint consensus names that rank → every rank exits 87 → the
  supervisor evicts the suspect's slot, rolls the fleet back to the
  latest committed checkpoint, and resizes WITHOUT the suspect → the
  remaining steps match an uninterrupted same-batch reference to rtol
  1e-3.
- **hang**: one rank wedges before entering a step → the healthy
  majority's heartbeat quorum convicts it and exits 87 → ONE eviction
  resize completes well inside the local watchdog timeout (wall-clock
  bound asserted): one resize, not N independent watchdog timeouts.

Cheaper companions with stdlib children: the launcher's verdict
consumption (eviction blocklist, fleet-state clearing), repeated
eviction escalating to poison 86, and the preemption drain's hard
deadline (a hung checkpoint writer exits respawnable 85 instead of
pinning the process until SIGKILL)."""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")

ELASTIC_BLOCK = {"enabled": True, "max_train_batch_size": 16,
                 "micro_batch_sizes": [2, 4], "min_gpus": 1,
                 "max_gpus": 8, "version": 0.1}


def _launch_main(tmp_path, script_body=None, script_args=(), max_restarts=0,
                 extra_argv=(), script_path=None, slots=(0,)):
    from deepspeed_tpu.launcher import launch
    from deepspeed_tpu.launcher.runner import encode_world_info

    if script_path is None:
        script_path = tmp_path / "child.py"
        script_path.write_text(script_body)
    wi = encode_world_info({socket.gethostname(): list(slots)})
    argv = ["--world_info", wi, "--node_rank", "0",
            "--master_addr", "127.0.0.1", "--master_port", "29999",
            "--max-restarts", str(max_restarts), *extra_argv,
            str(script_path), *script_args]
    old_int = signal.getsignal(signal.SIGINT)
    old_term = signal.getsignal(signal.SIGTERM)
    try:
        with pytest.raises(SystemExit) as exc:
            launch.main(argv)
        return exc.value.code
    finally:
        signal.signal(signal.SIGINT, old_int)
        signal.signal(signal.SIGTERM, old_term)


def _elastic_argv(tmp_path, devices):
    cfg = tmp_path / "elastic.json"
    cfg.write_text(json.dumps({"elasticity": ELASTIC_BLOCK}))
    return ["--elastic-config", str(cfg), "--elastic-devices",
            str(devices), "--telemetry-dir", str(tmp_path / "tel")]


def _launcher_events(tmp_path, event_type=None):
    path = tmp_path / "tel" / "events-launcher.jsonl"
    if not path.exists():
        return []
    recs = [json.loads(line) for line in path.read_text().splitlines()]
    if event_type is not None:
        recs = [r for r in recs if r["type"] == event_type]
    return recs


# ---------------------------------------------------------------------------
# launcher-level eviction semantics (stdlib children: no jax in the kids)
# ---------------------------------------------------------------------------

# Two-slot fleet; first life's rank 0 commits an integrity verdict
# naming rank 1 and exits 87 while rank 1 idles (it will be drained by
# the resize).  Every life appends its identity to the lives file.
_EVICT_CHILD = f"""
import json, os, sys, time
sys.path.insert(0, {REPO!r})
out, marker = sys.argv[1], sys.argv[2]
rec = {{"rank": os.environ["DS_PROCESS_ID"],
       "nprocs": os.environ["DS_NUM_PROCESSES"],
       "slot": os.environ["DS_LOCAL_RANK"]}}
with open(out, "a") as f:
    f.write(json.dumps(rec) + "\\n")
lives = 0
if os.path.exists(marker):
    lives = len(open(marker).read())
if rec["rank"] != "0":
    time.sleep(120)          # drained by the resize SIGTERM
if lives >= int(sys.argv[3]):
    sys.exit(0)              # recovered life: clean finish
with open(marker, "a") as f:
    f.write("x")
from deepspeed_tpu.resilience import integrity
integrity.write_verdict(os.environ["DS_TELEMETRY_DIR"], "sdc_outlier",
                        (1 + lives) % 2, f"seeded verdict {{lives}}",
                        rank=0, step=3)
sys.exit(87)
"""


def test_launcher_eviction_resize_blocklists_suspect_slot(tmp_path,
                                                          monkeypatch):
    """Exit 87 with a verdict naming rank 1: the supervisor charges the
    suspect's device, blocklists its slot, clears the fleet state, and
    respawns ONLY from the surviving slot — evict → plan → resize in
    the launcher stream."""
    monkeypatch.setenv("DS_MONITOR_POLL_SECS", "0.05")
    monkeypatch.setenv("DS_RESTART_BACKOFF_SECS", "0.05")
    monkeypatch.setenv("DS_TERM_GRACE_SECS", "2")
    monkeypatch.delenv("DS_INTEGRITY_MAX_EVICTIONS", raising=False)
    out, marker = tmp_path / "lives.jsonl", tmp_path / "marker"
    code = _launch_main(
        tmp_path, _EVICT_CHILD, slots=(0, 1),
        script_args=(str(out), str(marker), "1"), max_restarts=2,
        extra_argv=_elastic_argv(tmp_path, devices=2))
    assert code == 0
    lives = [json.loads(line) for line in out.read_text().splitlines()]
    # first life: ranks 0+1 over 2 procs; recovered life: ONE proc on
    # the non-evicted slot 0
    assert sorted((r["rank"], r["nprocs"], r["slot"]) for r in lives) == [
        ("0", "1", "0"), ("0", "2", "0"), ("1", "2", "1")]
    phases = [(p["data"]["phase"], p["data"])
              for p in _launcher_events(tmp_path, "elastic")]
    assert [p for p, _ in phases] == ["evict", "plan", "resize"]
    evict = phases[0][1]
    assert evict["suspect"] == 1 and evict["slot"] == 1
    assert evict["kind"] == "sdc_outlier" and evict["eviction"] == 1
    assert phases[1][1]["trigger"].startswith("integrity eviction")
    assert phases[2][1]["evicted_slots"] == [1]
    assert phases[2][1]["world_size"] == 1
    # the consumed verdict and fleet state were cleared for the new
    # life — but the verdict was RENAMED to the consumed marker, not
    # deleted: a sibling node's launcher sharing the run dir still
    # needs to read it to aim its own resize (each launcher dedups by
    # the verdict ts, so the lingering marker is inert here)
    assert not (tmp_path / "tel" / "integrity-verdict.json").exists()
    from deepspeed_tpu.resilience import integrity
    marker_file = tmp_path / "tel" / integrity.VERDICT_CONSUMED_FILE
    assert marker_file.exists()
    sibling_view = integrity.read_verdict(str(tmp_path / "tel"),
                                          include_consumed=True)
    assert sibling_view is not None and sibling_view["suspect"] == 1


def test_launcher_repeated_eviction_poisons(tmp_path, monkeypatch):
    """A second integrity verdict after an eviction already resized
    around a suspect is unrecoverable: the launcher escalates to poison
    86 and never respawns, restart budget notwithstanding."""
    from deepspeed_tpu.resilience import EXIT_DIVERGENCE_ABORT

    monkeypatch.setenv("DS_MONITOR_POLL_SECS", "0.05")
    monkeypatch.setenv("DS_RESTART_BACKOFF_SECS", "0.05")
    monkeypatch.setenv("DS_TERM_GRACE_SECS", "2")
    monkeypatch.delenv("DS_INTEGRITY_MAX_EVICTIONS", raising=False)
    out, marker = tmp_path / "lives.jsonl", tmp_path / "marker"
    code = _launch_main(
        tmp_path, _EVICT_CHILD, slots=(0, 1),
        script_args=(str(out), str(marker), "99"), max_restarts=3,
        extra_argv=_elastic_argv(tmp_path, devices=2))
    assert code == EXIT_DIVERGENCE_ABORT
    lives = [json.loads(line) for line in out.read_text().splitlines()]
    assert len(lives) == 3          # 2 first-life ranks + ONE resized life
    phases = [p["data"]["phase"]
              for p in _launcher_events(tmp_path, "elastic")]
    # second evict is recorded, then the run poisons: no second resize
    assert phases == ["evict", "plan", "resize", "evict"]


# First life publishes its heartbeat then crashes with an ordinary
# (non-87) code; second life proves the launcher cleared ITS stale beat
# (the quorum would otherwise falsely convict the new life) while the
# pre-seeded peer's beat survived the targeted clear.
_ORDINARY_RESPAWN_CHILD = f"""
import os, sys
sys.path.insert(0, {REPO!r})
from deepspeed_tpu.resilience import integrity
tel = os.environ["DS_TELEMETRY_DIR"]
marker = sys.argv[1]
if os.path.exists(marker):
    mine = os.path.join(tel, integrity.heartbeat_filename(0))
    peer = os.path.join(tel, integrity.heartbeat_filename(1))
    sys.exit(0 if (not os.path.exists(mine) and os.path.exists(peer))
             else 3)
open(marker, "w").write("x")
# the first life publishes its own beat AND simulates a healthy peer's
# (published here, AFTER the launcher's startup clear, so it must
# survive the targeted respawn clear)
integrity.publish_rank_heartbeat(tel, 0, 5)
integrity.publish_rank_heartbeat(tel, 1, 5)
sys.exit(1)
"""


def test_launcher_ordinary_respawn_clears_own_heartbeat(tmp_path,
                                                        monkeypatch):
    """A rank respawned after an ORDINARY crash (exit 1, no verdict)
    must not leave its previous life's heartbeat behind — through the
    backoff + re-init window that stale beat reads as "step lags the
    head, beat stale" and the hang quorum would falsely evict the new
    life.  The clear is targeted: peers' state survives."""
    from deepspeed_tpu.resilience import integrity

    monkeypatch.setenv("DS_MONITOR_POLL_SECS", "0.05")
    monkeypatch.setenv("DS_RESTART_BACKOFF_SECS", "0.05")
    tel = tmp_path / "tel"
    tel.mkdir()
    # debris from a PREVIOUS run: the launcher's startup clear must
    # scrub it before the first spawn (a stale verdict consumed at this
    # run's first death would blocklist an innocent slot)
    integrity.publish_rank_heartbeat(str(tel), 7, 99)
    integrity.write_verdict(str(tel), integrity.KIND_SDC, 7, "old run")
    marker = tmp_path / "marker"
    code = _launch_main(
        tmp_path, _ORDINARY_RESPAWN_CHILD, script_args=(str(marker),),
        max_restarts=1, extra_argv=("--telemetry-dir", str(tel)))
    assert code == 0
    assert integrity.read_verdict(str(tel)) is None   # startup-cleared


# ---------------------------------------------------------------------------
# preemption drain hard deadline (satellite: hung writer exits respawnable)
# ---------------------------------------------------------------------------

_HUNG_WRITER_CHILD = f"""
import os, signal, sys, time
sys.path.insert(0, {REPO!r})
marker = sys.argv[1]
if os.path.exists(marker):
    sys.exit(0)              # respawned life: the recovery worked
open(marker, "w").write("x")
from deepspeed_tpu.checkpoint.manager import CheckpointManager
mgr = CheckpointManager()
mgr.install_preemption_handler(lambda: time.sleep(600))  # stuck storage
signal.raise_signal(signal.SIGTERM)                      # preemption notice
time.sleep(600)
"""


def test_preemption_drain_hard_deadline_exits_respawnable(tmp_path):
    """A checkpoint writer that hangs during the SIGTERM grace-window
    save must NOT pin the process until the launcher's SIGKILL: the
    drain watchdog exits 85 (respawnable) at the hard deadline."""
    from deepspeed_tpu.resilience import EXIT_STEP_HANG

    script = tmp_path / "hung.py"
    script.write_text(_HUNG_WRITER_CHILD)
    env = dict(os.environ, DS_TERM_GRACE_SECS="30",
               DS_TERM_DRAIN_DEADLINE_SECS="0.5")
    t0 = time.monotonic()
    proc = subprocess.run([sys.executable, str(script),
                           str(tmp_path / "marker")],
                          env=env, capture_output=True, text=True,
                          timeout=60)
    elapsed = time.monotonic() - t0
    assert proc.returncode == EXIT_STEP_HANG, proc.stderr[-2000:]
    assert elapsed < 20, f"drain deadline did not bound the hang: " \
                         f"{elapsed:.1f}s"
    assert "hard deadline" in proc.stdout + proc.stderr


def test_preemption_drain_deadline_respawns_under_launcher(tmp_path,
                                                           monkeypatch):
    """The full loop with the launcher supervising: hung-writer life
    exits 85, the supervisor respawns, the second life finishes clean —
    lost capacity, not a lost run."""
    monkeypatch.setenv("DS_MONITOR_POLL_SECS", "0.05")
    monkeypatch.setenv("DS_RESTART_BACKOFF_SECS", "0.05")
    monkeypatch.setenv("DS_TERM_GRACE_SECS", "30")
    monkeypatch.setenv("DS_TERM_DRAIN_DEADLINE_SECS", "0.5")
    t0 = time.monotonic()
    code = _launch_main(
        tmp_path, _HUNG_WRITER_CHILD,
        script_args=(str(tmp_path / "marker"),), max_restarts=1,
        extra_argv=["--telemetry-dir", str(tmp_path / "tel")])
    assert code == 0
    assert time.monotonic() - t0 < 30   # never served the full grace
    (exit_rec,) = [r for r in _launcher_events(tmp_path, "proc_exit")
                   if r["data"]["code"] != 0]
    assert exit_rec["data"]["code"] == 85


# ---------------------------------------------------------------------------
# THE chaos e2e pair: real launcher, real training fleet, virtual CPU
# ---------------------------------------------------------------------------

SCRIPT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "integrity_train_script.py")
TOTAL_STEPS = 10
_CHAOS_ENV = ("DS_CHAOS_BITFLIP_STEP", "DS_CHAOS_HANG_STEP",
              "DS_CHAOS_TARGET_RANK", "DS_CHAOS_SEED",
              "DS_INTEGRITY_PEER_TIMEOUT", "DS_WATCHDOG_SECS",
              "DS_STEP_SLEEP_SECS")


@pytest.fixture(scope="module")
def reference_run(tmp_path_factory):
    """The uninterrupted single-replica run on the same seeded batch
    stream: per-step losses + the final record (each fleet rank is a
    full replica, so ONE reference serves both chaos legs)."""
    base = tmp_path_factory.mktemp("integrity-ref")
    env = {k: v for k, v in os.environ.items() if k not in _CHAOS_ENV}
    env["DS_TELEMETRY_DIR"] = str(base / "tel")
    env["DS_PROCESS_ID"] = "0"
    proc = subprocess.run(
        [sys.executable, SCRIPT, str(base / "ckpt"), str(base / "out")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=280)
    assert proc.returncode == 0, (
        f"reference run failed\nstdout:\n{proc.stdout[-3000:]}\n"
        f"stderr:\n{proc.stderr[-3000:]}")
    losses = {}
    for name in os.listdir(base / "out"):
        if name.startswith("steps-"):
            for line in open(base / "out" / name):
                rec = json.loads(line)
                losses[rec["step"]] = rec["loss"]
    final = json.load(open(base / "out" / "final.json"))
    assert final["steps"] == TOTAL_STEPS and sorted(losses) == list(
        range(1, TOTAL_STEPS + 1))
    return {"losses": losses, "final": final}


def _rank0_steps(out_dir):
    """{step: loss} across every life of fleet rank 0 — asserting no
    step was ever trained twice (replay) on the logging rank."""
    steps = {}
    for name in sorted(os.listdir(out_dir)):
        if not name.startswith("steps-rank0-"):
            continue
        for line in open(os.path.join(out_dir, name)):
            rec = json.loads(line)
            assert rec["step"] not in steps, f"step {rec['step']} replayed"
            steps[rec["step"]] = rec["loss"]
    return steps


def _chaos_env(monkeypatch, **extra):
    monkeypatch.setenv("DS_MONITOR_POLL_SECS", "0.05")
    monkeypatch.setenv("DS_RESTART_BACKOFF_SECS", "0.05")
    monkeypatch.setenv("DS_TERM_GRACE_SECS", "3")
    monkeypatch.setenv("DS_ELASTIC_DEVICES_PER_FAILURE", "1")
    monkeypatch.delenv("DS_INTEGRITY_MAX_EVICTIONS", raising=False)
    for k in _CHAOS_ENV:
        monkeypatch.delenv(k, raising=False)
    for k, v in extra.items():
        monkeypatch.setenv(k, str(v))


def _merged_events(run_dir, event_type):
    from deepspeed_tpu.telemetry import read_events

    return [r for r in read_events(str(run_dir)) if r["type"] == event_type]


def test_chaos_bitflip_evict_resize_parity_end_to_end(tmp_path,
                                                      monkeypatch,
                                                      reference_run):
    """Seeded SDC on rank 2 of a 4-replica fleet: the fingerprint
    consensus names rank 2, the fleet exits 87, the supervisor evicts
    slot 2 and resizes 4 -> 2, every surviving rank rolls back to the
    latest committed checkpoint, and the re-trained steps match the
    uninterrupted reference to rtol 1e-3."""
    from deepspeed_tpu.resilience.chaos import ChaosMonkey

    # seeded flip step in [3, 5]: committed checkpoints exist, several
    # steps remain after the resize
    flip_step = 3 + ChaosMonkey(seed=13).schedule_steps(3, 1)[0]
    _chaos_env(monkeypatch, DS_CHAOS_BITFLIP_STEP=flip_step,
               DS_CHAOS_TARGET_RANK=2, DS_CHAOS_SEED=13,
               DS_STEP_SLEEP_SECS=0.1)

    code = _launch_main(
        tmp_path, script_path=SCRIPT, slots=(0, 1, 2, 3),
        script_args=(str(tmp_path / "ckpt"), str(tmp_path / "out")),
        max_restarts=2,
        extra_argv=_elastic_argv(tmp_path, devices=4) + [
            "--compile-cache-dir", str(tmp_path / "xla-cache")])
    assert code == 0

    # the fleet finished all 10 steps after the eviction resize
    final = json.load(open(tmp_path / "out" / "final.json"))
    assert final["steps"] == TOTAL_STEPS

    # the launcher stream shows ONE aimed resize: evict names rank 2 /
    # slot 2, the respawn excludes it
    phases = [(p["data"]["phase"], p["data"])
              for p in _launcher_events(tmp_path, "elastic")]
    assert [p for p, _ in phases] == ["evict", "plan", "resize"]
    evict = phases[0][1]
    assert evict["suspect"] == 2 and evict["slot"] == 2
    assert evict["kind"] == "sdc_outlier"
    assert phases[2][1]["evicted_slots"] == [2]
    assert phases[2][1]["world_size"] == 2

    # the engines' merged stream carries the outlier verdict naming 2
    outliers = [r for r in _merged_events(tmp_path / "tel", "integrity")
                if r["data"]["verdict"] == "outlier"]
    assert outliers and all(r["data"]["suspects"] == [2]
                            for r in outliers)
    assert all(r["data"]["kind"] == "fingerprint" for r in outliers)

    # loss continuity: every step rank 0 trained (across both lives, no
    # replay) matches the uninterrupted reference; the flip itself must
    # never leak into the surviving timeline
    steps = _rank0_steps(tmp_path / "out")
    assert TOTAL_STEPS in steps and flip_step in steps
    for s, loss in steps.items():
        np.testing.assert_allclose(
            loss, reference_run["losses"][s], rtol=1e-3,
            err_msg=f"loss diverged from the uninterrupted reference at "
                    f"step {s} (bitflip was at {flip_step})")
    np.testing.assert_allclose(final["final_loss"],
                               reference_run["final"]["final_loss"],
                               rtol=1e-3)


def test_chaos_hang_quorum_one_resize_end_to_end(tmp_path, monkeypatch,
                                                 reference_run):
    """Rank 2 wedges before step 2; the healthy majority's hang quorum
    convicts it and exits 87 — the launcher completes ONE eviction
    resize and the run finishes well inside the local watchdog timeout
    (which is armed 300s loose to prove the quorum, not N watchdogs,
    recovered the fleet)."""
    _chaos_env(monkeypatch, DS_CHAOS_HANG_STEP=2, DS_CHAOS_TARGET_RANK=2,
               DS_INTEGRITY_PEER_TIMEOUT=1.2, DS_WATCHDOG_SECS=300,
               DS_STEP_SLEEP_SECS=0.35)

    t0 = time.monotonic()
    code = _launch_main(
        tmp_path, script_path=SCRIPT, slots=(0, 1, 2, 3),
        script_args=(str(tmp_path / "ckpt"), str(tmp_path / "out")),
        max_restarts=2,
        extra_argv=_elastic_argv(tmp_path, devices=4) + [
            "--compile-cache-dir", str(tmp_path / "xla-cache")])
    elapsed = time.monotonic() - t0
    assert code == 0
    # the wall-clock bound IS the claim: one quorum eviction, not N
    # independent 300s watchdog timeouts (and not even one of them)
    assert elapsed < 240, f"hang recovery took {elapsed:.0f}s"

    final = json.load(open(tmp_path / "out" / "final.json"))
    assert final["steps"] == TOTAL_STEPS

    phases = [(p["data"]["phase"], p["data"])
              for p in _launcher_events(tmp_path, "elastic")]
    assert [p for p, _ in phases] == ["evict", "plan", "resize"]
    evict = phases[0][1]
    assert evict["suspect"] == 2 and evict["slot"] == 2
    assert evict["kind"] == "hang_quorum"
    assert phases[2][1]["evicted_slots"] == [2]

    # at least one healthy rank exited with the eviction code (87) —
    # the detecting accusers, not the victim
    exit_codes = [r["data"]["code"]
                  for r in _launcher_events(tmp_path, "proc_exit")]
    assert 87 in exit_codes

    # the hang-quorum verdict rode the engines' telemetry before the
    # os._exit (flush-on-fire)
    hangs = [r for r in _merged_events(tmp_path / "tel", "integrity")
             if r["data"]["kind"] == "hang_quorum"]
    assert hangs and all(r["data"]["suspects"] == [2] for r in hangs)

    # rollback correctness: the surviving timeline matches the
    # uninterrupted reference
    steps = _rank0_steps(tmp_path / "out")
    for s, loss in steps.items():
        np.testing.assert_allclose(loss, reference_run["losses"][s],
                                   rtol=1e-3)
