"""Mixture-of-Experts: router invariants, dense parity, expert-parallel
training (beyond-reference — SURVEY §2.5 lists EP as absent upstream)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.moe import MoEFFN, _router_dispatch


def test_router_dispatch_invariants():
    rng = np.random.default_rng(0)
    T, E, k = 64, 4, 2
    probs = jax.nn.softmax(jnp.asarray(rng.standard_normal((T, E)),
                                       jnp.float32), axis=-1)
    C = T  # ample capacity: nothing drops
    dispatch, combine, aux = _router_dispatch(probs, k, C)
    d = np.asarray(dispatch)
    c = np.asarray(combine)
    # every token lands in exactly k distinct (expert, slot) cells
    assert (d.reshape(T, -1).sum(-1) == k).all()
    # no slot holds two tokens
    assert (d.sum(0) <= 1).all()
    # combine weights renormalize to ~1 per token
    np.testing.assert_allclose(c.reshape(T, -1).sum(-1), 1.0, atol=1e-5)
    # aux loss near 1 for a roughly balanced router (Switch normalization)
    assert 0.5 < float(aux) < 2.0


def test_router_capacity_drops_overflow():
    T, E = 32, 2
    # all tokens prefer expert 0 -> only C fit, rest drop (residual path)
    probs = jnp.tile(jnp.asarray([[0.99, 0.01]], jnp.float32), (T, 1))
    dispatch, combine, _ = _router_dispatch(probs, 1, 8)
    assert int(np.asarray(dispatch)[:, 0].sum()) == 8
    assert float(np.asarray(combine)[9:].sum()) == 0.0


def test_moe_e1_matches_dense_ffn():
    """One expert, top-1: MoE reduces exactly to the dense FFN."""
    from deepspeed_tpu.models.layers import gelu

    moe = MoEFFN(hidden_size=16, intermediate_size=32, num_experts=1, k=1,
                 capacity_factor=4.0)
    params = moe.init(jax.random.PRNGKey(0))
    x = jnp.asarray(np.random.default_rng(1).standard_normal((2, 8, 16)),
                    jnp.float32)
    y, aux = moe.apply(params, x)
    ref = gelu(x @ params["fc1"]["kernel"][0] + params["fc1"]["bias"][0]) \
        @ params["fc2"]["kernel"][0] + params["fc2"]["bias"][0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-4)
    np.testing.assert_allclose(float(aux), 1.0, atol=1e-5)


@pytest.mark.parametrize("mesh_shape", [{"data": 2, "expert": 4},
                                        {"data": 1, "expert": 2, "model": 2}])
@pytest.mark.slow
def test_gpt2_moe_trains_expert_parallel(mesh_shape, cpu_devices):
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import GPT2Config, GPT2LMHeadTPU
    from deepspeed_tpu.parallel import make_mesh

    n = int(np.prod(list(mesh_shape.values())))
    mesh = make_mesh(mesh_shape, devices=cpu_devices[:n])
    dp = mesh_shape.get("data", 1)
    cfg = GPT2Config(vocab_size=128, hidden_size=32, num_layers=4,
                     num_heads=2, max_position_embeddings=32,
                     moe_experts=4, embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    model = GPT2LMHeadTPU(cfg)
    config = {"train_batch_size": 4 * dp, "steps_per_print": 10 ** 9,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
              "zero_optimization": {"stage": 1}}
    engine, *_ = deepspeed.initialize(model=model, config=config, mesh=mesh)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 128, (4 * dp, 16)).astype(np.int32)}
    losses = [float(jax.device_get(engine.train_batch(iter([batch]))))
              for _ in range(6)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
    # expert leaves really are sharded over the expert axis
    params = engine.get_master_params()
    spec = model.partition_specs(mesh)["blocks"]["layer_1"]["moe"]["fc1"]["kernel"]
    assert spec[0] == "expert"


@pytest.mark.slow
def test_gpt2_moe_honors_attn_impl_and_remat(cpu_devices):
    """MoE blocks share TransformerLayer's attention core (sparse/ring
    configs apply) and participate in config-driven remat."""
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import GPT2Config, GPT2LMHeadTPU
    from deepspeed_tpu.ops.sparse_attention import FixedSparsityConfig
    from deepspeed_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 1, "expert": 2}, devices=cpu_devices[:2])
    cfg = GPT2Config(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                     max_position_embeddings=64, moe_experts=2, remat=True,
                     attn_impl="sparse",
                     sparsity_config=FixedSparsityConfig(
                         num_heads=2, block=8, num_local_blocks=2),
                     embd_dropout=0.0, attn_dropout=0.0, resid_dropout=0.0)
    model = GPT2LMHeadTPU(cfg)
    assert model.moe_layer.attn.attn_impl == "sparse"
    config = {"train_batch_size": 2, "steps_per_print": 10 ** 9,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
    engine, *_ = deepspeed.initialize(model=model, config=config, mesh=mesh)
    batch = {"input_ids": np.zeros((2, 32), np.int32)}
    loss = engine.train_batch(iter([batch]))
    assert np.isfinite(float(jax.device_get(loss)))


def test_moe_aux_loss_train_only():
    from deepspeed_tpu.models import GPT2Config, GPT2LMHeadTPU

    cfg = GPT2Config(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                     max_position_embeddings=32, moe_experts=2,
                     embd_dropout=0.0, attn_dropout=0.0, resid_dropout=0.0)
    model = GPT2LMHeadTPU(cfg)
    params = model.init(jax.random.PRNGKey(0))
    ids = np.arange(32, dtype=np.int32).reshape(2, 16) % 64
    batch = {"input_ids": ids, "labels": ids}
    train_loss = float(model.apply(params, batch, train=True))
    eval_loss = float(model.apply(params, batch, train=False))
    # train objective carries the aux regularizer; eval is pure CE
    assert train_loss > eval_loss
    assert abs(train_loss - eval_loss - cfg.moe_aux_coef *
               float(model._last_moe_aux)) < 1e-5
