"""Model-scale convergence gates (CI tier).

The reference gates releases on model-level runs: Megatron-GPT2
functional tests compare DS-config loss curves against a baseline run
(``tests/model/Megatron_GPT2/run_func_test.py``), and BingBertSquad
asserts EM/F1 after a fine-tune (``test_e2e_squad.py``).  The full-size
analog lives in ``tests/model/run_func_test.py`` (standalone; minutes on
the real chip).  These tests run the same harness at CI scale:

- slow tier (CPU): real-WIDTH BERT-base (h768 L12 i3072 — the config is
  what's being gated; seq/steps shrink to fit one CPU core) with the loss
  curve pinned under ``tests/unit/baselines/model_scale.json``
  (regenerate with ``DS_UPDATE_BASELINES=1``), plus the QA EM/F1 gate.
- tpu tier (``DS_TEST_TPU=1 pytest -m tpu``): the full few-hundred-step
  BERT-base seq128 matrix + QA gate, on-chip.
"""

import os

import numpy as np
import pytest

from ..model import func_harness as H

BASELINES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "baselines", "model_scale.json")


@pytest.mark.slow
def test_bert_base_mlm_curve_pinned(cpu_devices):
    """Real-width BERT-base MLM loss curve on fixed data, pinned."""
    from deepspeed_tpu.models.bert import BertForPreTrainingTPU

    steps, batch, seq = 40, 8, 32
    data = H.mlm_batches(seed=17, n_batches=4, batch=batch, seq=seq)
    model = BertForPreTrainingTPU(H.bert_base_config(seq, dropout=0.0))
    engine = H.make_engine(
        model, {"train_batch_size": batch, "steps_per_print": 10 ** 9,
                "optimizer": {"type": "Adam", "params": {"lr": 3e-4}}})
    curve = H.train_curve(engine, data, steps, sample_every=8)
    assert curve[-1] < curve[0], f"no convergence: {curve}"
    pinned = H.load_or_update_baseline(BASELINES, "bert_base_mlm_seq32",
                                       curve)
    np.testing.assert_allclose(curve, pinned, rtol=2e-2,
                               err_msg="curve drifted from pinned baseline")


@pytest.mark.slow
def test_qa_gate_real_data():
    """Extractive-QA EM/F1 gate on the vendored REAL dataset (qa_mini,
    SQuAD v1.1 format — reference BingBertSquad/test_e2e_squad.py).
    Calibrated: healthy run EM ~0.94 / F1 ~0.95 vs gates 0.75/0.85."""
    from ..model import run_func_test as R

    R.run_qa_gate(steps=250, batch=32, seq=128, em_min=0.75, f1_min=0.85)


@pytest.mark.slow
def test_qa_gate_fails_under_broken_mask():
    """Falsifiability: the same gate must FAIL when the attention mask is
    deliberately broken (question hidden from the encoder at eval).  Each
    passage carries three questions with different answers and the
    question slot is fixed-width, so a model that cannot attend the
    question caps near EM 1/3 (measured: EM 0.15 / F1 0.27) — if this
    test ever fails, the gate has stopped measuring attention."""
    from ..model import run_func_test as R

    R.run_qa_gate(steps=250, batch=32, seq=128, em_min=0.75, f1_min=0.85,
                  corrupt_mask=True, _expect_fail=True)


@pytest.mark.slow
def test_checkpoint_resume_continuity_matrix():
    """Train -> save -> resume-in-a-fresh-process -> the resumed loss
    curve must match the uninterrupted run step-for-step (reference
    ``tests/model/Megatron_GPT2/run_checkpoint_test.py``).  The
    large-model checkpoint roundtrips all live in this slow tier; CPU
    tier runs the cheapest legs plus the async checkpoint-subsystem leg,
    and the full 7-config matrix (incl. pipeline and the elastic
    DP-degree change) is the standalone driver
    ``tests/model/run_checkpoint_test.py``."""
    import tempfile

    from ..model import run_checkpoint_test as R

    with tempfile.TemporaryDirectory() as tmp:
        for name in ("baseline", "zero2", "zero2_async", "elastic_dp"):
            R.run_config(name, steps=8, out_dir=tmp, force_cpu=True)


@pytest.mark.tpu
def test_checkpoint_resume_continuity_on_chip():
    """One continuity leg on the real chip (single-device configs only:
    the tier has one TPU)."""
    import tempfile

    from ..model import run_checkpoint_test as R

    with tempfile.TemporaryDirectory() as tmp:
        R.run_config("zero2_offload", steps=8, out_dir=tmp, force_cpu=False)


@pytest.mark.tpu
def test_bert_base_full_matrix_on_chip():
    """The full model-scale flow on the real chip: config-matrix loss
    parity at BERT-base seq128 + the QA EM/F1 gate (reference
    run_func_test.py + test_e2e_squad.py, end to end)."""
    import tempfile

    from ..model import run_func_test as R

    with tempfile.TemporaryDirectory() as tmp:
        curves = R.run_matrix(steps=120, batch=32, seq=128, out_dir=tmp)
    R.check_matrix(curves, rtol=0.05)
    R.run_qa_gate(steps=250, batch=32, seq=128, em_min=0.75, f1_min=0.85)
