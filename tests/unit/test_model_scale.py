"""Model-scale convergence gates (CI tier).

The reference gates releases on model-level runs: Megatron-GPT2
functional tests compare DS-config loss curves against a baseline run
(``tests/model/Megatron_GPT2/run_func_test.py``), and BingBertSquad
asserts EM/F1 after a fine-tune (``test_e2e_squad.py``).  The full-size
analog lives in ``tests/model/run_func_test.py`` (standalone; minutes on
the real chip).  These tests run the same harness at CI scale:

- slow tier (CPU): real-WIDTH BERT-base (h768 L12 i3072 — the config is
  what's being gated; seq/steps shrink to fit one CPU core) with the loss
  curve pinned under ``tests/unit/baselines/model_scale.json``
  (regenerate with ``DS_UPDATE_BASELINES=1``), plus the QA EM/F1 gate.
- tpu tier (``DS_TEST_TPU=1 pytest -m tpu``): the full few-hundred-step
  BERT-base seq128 matrix + QA gate, on-chip.
"""

import os

import numpy as np
import pytest

from ..model import func_harness as H

BASELINES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "baselines", "model_scale.json")


@pytest.mark.slow
def test_bert_base_mlm_curve_pinned(cpu_devices):
    """Real-width BERT-base MLM loss curve on fixed data, pinned."""
    from deepspeed_tpu.models.bert import BertForPreTrainingTPU

    steps, batch, seq = 40, 8, 32
    data = H.mlm_batches(seed=17, n_batches=4, batch=batch, seq=seq)
    model = BertForPreTrainingTPU(H.bert_base_config(seq, dropout=0.0))
    engine = H.make_engine(
        model, {"train_batch_size": batch, "steps_per_print": 10 ** 9,
                "optimizer": {"type": "Adam", "params": {"lr": 3e-4}}})
    curve = H.train_curve(engine, data, steps, sample_every=8)
    assert curve[-1] < curve[0], f"no convergence: {curve}"
    pinned = H.load_or_update_baseline(BASELINES, "bert_base_mlm_seq32",
                                       curve)
    np.testing.assert_allclose(curve, pinned, rtol=2e-2,
                               err_msg="curve drifted from pinned baseline")


# The QA EM/F1 gate runs on the TPU tier + the standalone driver only
# (mirroring the reference, whose BingBertSquad e2e lives in tests/model,
# not unit CI): from-scratch 12-layer post-LN BERT needs warmup and a few
# hundred steps to move off the uniform plateau — calibrated on-chip,
# infeasible on the 1-core CPU tier (measured: 60 steps at lr 1e-3 stays
# at ln(seq) exactly).


@pytest.mark.tpu
def test_bert_base_full_matrix_on_chip():
    """The full model-scale flow on the real chip: config-matrix loss
    parity at BERT-base seq128 + the QA EM/F1 gate (reference
    run_func_test.py + test_e2e_squad.py, end to end)."""
    import tempfile

    from ..model import run_func_test as R

    with tempfile.TemporaryDirectory() as tmp:
        curves = R.run_matrix(steps=120, batch=32, seq=128, out_dir=tmp)
    R.check_matrix(curves, rtol=0.05)
    R.run_qa_gate(steps=150, batch=32, seq=128, em_min=0.75, f1_min=0.85)
