"""Training script run by the launcher smoke test: 2 processes x 1 CPU
device, global data mesh, real multi-host rendezvous + sliced dataloader."""

import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np  # noqa: E402

import deepspeed_tpu as deepspeed  # noqa: E402
from deepspeed_tpu.parallel import make_mesh  # noqa: E402
from deepspeed_tpu.utils.distributed import init_distributed  # noqa: E402
from unit.simple_model import SimpleModel, base_config  # noqa: E402

HIDDEN = 16


def main():
    out_dir = sys.argv[1]
    init_distributed()
    assert jax.process_count() == 2, f"expected 2 processes, got {jax.process_count()}"
    devices = jax.devices()
    assert len(devices) == 2, f"expected 2 global devices, got {devices}"

    mesh = make_mesh({"data": 2}, devices=devices)
    rng = np.random.default_rng(0)
    n, bs = 32, 8
    data = [(rng.normal(size=(HIDDEN,)).astype(np.float32),
             rng.normal(size=(HIDDEN,)).astype(np.float32)) for _ in range(n)]
    config = base_config(train_batch_size=bs)
    engine, _, loader, _ = deepspeed.initialize(
        model=SimpleModel(HIDDEN, nlayers=2), config=config, mesh=mesh,
        training_data=data)
    assert loader.local_batch == bs // 2, loader.local_batch

    losses = [float(np.asarray(jax.device_get(engine.train_batch())))
              for _ in range(3)]
    assert all(np.isfinite(losses)), losses

    with open(os.path.join(out_dir, f"rank{jax.process_index()}.ok"), "w") as f:
        f.write(repr(losses))
    print(f"rank {jax.process_index()} done: {losses}")


if __name__ == "__main__":
    main()
