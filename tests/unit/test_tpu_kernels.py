"""Compiled (non-interpret) kernel numerics on a real TPU chip.

The CI suite runs the same numerics in interpret mode on CPU; a Mosaic
lowering/layout regression would surface there only as a bench failure.
This module is the cheap on-chip gate: ``DS_TEST_TPU=1 python -m pytest
-m tpu`` runs every kernel compiled on the real chip in a couple of
minutes (PERF.md methodology).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.transformer.attention import reference_attention
from deepspeed_tpu.ops.transformer.flash_attention import flash_attention

pytestmark = pytest.mark.tpu


@pytest.fixture(autouse=True)
def _full_matmul_precision():
    """fp32 operands otherwise run the MXU at reduced (bf16-passes)
    precision on TPU, drowning kernel-vs-reference comparisons in matmul
    noise that has nothing to do with the kernels."""
    with jax.default_matmul_precision("float32"):
        yield


def rand_qkv(b, s, h, d, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, s, h, d), dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_compiled_flash_forward(causal):
    q, k, v = rand_qkv(2, 512, 4, 64)
    out = flash_attention(q, k, v, causal=causal)
    out_ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=2e-4, rtol=2e-4)


def test_compiled_flash_backward():
    q, k, v = rand_qkv(1, 512, 2, 64, seed=3)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss(reference_attention), argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=2e-3, rtol=2e-3,
                                   err_msg=f"d{name} mismatch")


def test_compiled_flash_key_padding_mask():
    b, s = 2, 512
    q, k, v = rand_qkv(b, s, 2, 64, seed=5)
    kvm = np.zeros((b, s), np.float32)
    kvm[0, :400] = 1.0
    kvm[1, :137] = 1.0
    kvm = jnp.asarray(kvm)
    additive = (1.0 - kvm[:, None, None, :]) * -1e9
    out = flash_attention(q, k, v, kv_mask=kvm)
    out_ref = reference_attention(q, k, v, mask=additive)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=2e-4, rtol=2e-4)


def test_compiled_flash_streamed_kv():
    """Multi-k-block (streamed VMEM scratch) path: kv 2048 with 512 blocks."""
    q, k, v = rand_qkv(1, 2048, 2, 64, seed=7)
    out = flash_attention(q, k, v, block_q=512, block_k=512)
    out_ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=2e-4, rtol=2e-4)


def test_compiled_flash_dropout_deterministic_and_unbiased():
    """In-kernel hardware-PRNG dropout compiles, regenerates bit-identical
    masks across calls, varies with the seed, and keeps the output mean
    near the no-dropout mean (inverse-keep scaling)."""
    q, k, v = rand_qkv(2, 512, 4, 64, seed=9)
    seed = jnp.asarray([42, 7], jnp.int32)
    a = flash_attention(q, k, v, dropout_seed=seed, dropout_rate=0.25)
    b = flash_attention(q, k, v, dropout_seed=seed, dropout_rate=0.25)
    assert jnp.array_equal(a, b)
    c = flash_attention(q, k, v, dropout_seed=jnp.asarray([43, 7], jnp.int32),
                        dropout_rate=0.25)
    assert not jnp.array_equal(a, c)
    base = flash_attention(q, k, v)
    # dropout is unbiased in expectation; at this tile count the mean of
    # |out| stays within a few percent
    ratio = float(jnp.mean(jnp.abs(a)) / jnp.mean(jnp.abs(base)))
    assert 0.85 < ratio < 1.25, ratio


def test_compiled_block_sparse_kernel():
    """LUT-driven block-sparse flash kernel compiled on-chip vs the
    gather-based reference implementation."""
    from deepspeed_tpu.ops.sparse_attention import (
        BigBirdSparsityConfig, block_sparse_attention,
        flash_block_sparse_attention)

    b, s, h, d = 1, 1024, 4, 64
    cfg = BigBirdSparsityConfig(num_heads=h, block=128,
                                num_random_blocks=1,
                                num_sliding_window_blocks=3,
                                num_global_blocks=1)
    layout = cfg.make_layout(s)
    q, k, v = rand_qkv(b, s, h, d, seed=11)
    out = flash_block_sparse_attention(q, k, v, layout)
    out_ref = block_sparse_attention(q, k, v, layout)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=2e-4, rtol=2e-4)

    def loss_k(q, k, v):
        return jnp.sum(flash_block_sparse_attention(q, k, v, layout) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(block_sparse_attention(q, k, v, layout) ** 2)

    g = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for gk, gr, name in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gk), np.asarray(gr),
                                   atol=2e-3, rtol=2e-3,
                                   err_msg=f"d{name} mismatch")


def test_compiled_flash_exp2_matches_exp(monkeypatch):
    """Base-2 softmax (DS_FLASH_EXP2) is numerically interchangeable with
    the natural-base kernel: exp2(x*log2e) == exp(x) up to fp rounding,
    forward and grads."""
    import deepspeed_tpu.ops.transformer.flash_attention as fa

    q, k, v = rand_qkv(1, 1024, 2, 64, seed=7)

    def loss(q_, k_, v_):
        return jnp.sum(fa.flash_attention(q_, k_, v_, causal=True) ** 2)

    monkeypatch.setattr(fa, "EXP2", False)
    out_e = fa.flash_attention(q, k, v, causal=True)
    g_e = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setattr(fa, "EXP2", True)
    out_2 = fa.flash_attention(q, k, v, causal=True)
    g_2 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    np.testing.assert_allclose(np.asarray(out_2), np.asarray(out_e),
                               atol=2e-5, rtol=2e-5)
    for a, b, name in zip(g_2, g_e, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4,
                                   err_msg=f"d{name} mismatch")
