"""Donated host buffers must be XLA-owned, never numpy-owned.

The shipped bug this pins (round 12, found while landing overlapped
streaming; the same family as the PR 8 donated-staging finding): on
single-memory-space backends a ``jax.device_put`` of a numpy staging
buffer can ALIAS the numpy arena, and the step programs donate every
offloaded/flat state buffer — donating the alias lets XLA free (and
reuse) memory the numpy allocator still owns.  One live engine usually
got away with it; the second didn't: glibc ``corrupted size vs.
prev_size`` / ``corrupted double-linked list`` aborts, reproduced with
(a) two live offload engines in one process and (b) a checkpoint
restore followed by building another engine — exactly the 8-device
``dryrun_multichip`` crash after the elastic leg (flagged pre-existing
in PR 11).  The fix routes every numpy-staged donated buffer through
``FlatParamCoordinator.home_host`` / ``home_host_like`` (a jitted copy
re-homes it in the XLA allocator on single-space backends; TPU
pinned-host puts are real cross-space copies and stay direct).

These tests are the in-tier-1 reproducers: before the fix each aborted
the interpreter (uncatchable), so them RUNNING TO COMPLETION is the
assertion that matters; the numeric checks just keep them honest.
"""

import numpy as np
import pytest

import deepspeed_tpu as deepspeed
import deepspeed_tpu.runtime.zero.coordinator as coord
from deepspeed_tpu.parallel import make_mesh

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 64


def _zero2_engine(cpu_devices, dp):
    mesh = make_mesh({"data": dp}, devices=cpu_devices[:dp])
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(HIDDEN, nlayers=2),
        config=base_config(zero_optimization={"stage": 2}), mesh=mesh)
    return engine


def _steps(engine, n=2):
    batch = random_batches(1, engine.train_micro_batch_size_per_gpu(),
                           HIDDEN, seed=0)[0]
    return [float(np.asarray(engine.train_batch(iter([batch]))))
            for _ in range(n)]


def test_restore_then_third_engine_does_not_corrupt_heap(
        cpu_devices, tmp_path):
    """The 8-device dryrun crash shape, minimized: train → checkpoint →
    restore into a second engine (different dp) → run → build a THIRD
    engine and run.  Before the home_host_like fix the restored opt
    state was a donated alias of checkpoint numpy arrays and the third
    engine's allocations hit the corrupted arena (glibc abort after
    the elastic leg, before the record printed)."""
    e1 = _zero2_engine(cpu_devices, 2)
    _steps(e1)
    e1.save_checkpoint(str(tmp_path), tag="t", sync=True)
    e2 = _zero2_engine(cpu_devices, 1)
    path, _ = e2.load_checkpoint(str(tmp_path), tag="t")
    assert path is not None
    losses2 = _steps(e2, 3)
    e3 = _zero2_engine(cpu_devices, 2)
    losses3 = _steps(e3, 3)
    assert np.all(np.isfinite(losses2)) and np.all(np.isfinite(losses3))


def test_two_live_offload_engines_coexist(cpu_devices, monkeypatch):
    """Two live streamed-offload engines (the other pre-fix abort):
    each trains independently with finite losses and identical
    trajectories — no cross-engine host-buffer corruption.  (The
    overlap parity suite builds engine pairs too; this is the minimal
    named reproducer.)"""
    monkeypatch.setenv("DS_OFFLOAD_FORCE_INJIT", "1")
    monkeypatch.setattr(coord, "HOST_GROUP_BYTES", 2 << 20)

    def make():
        mesh = make_mesh({"data": 1}, devices=cpu_devices[:1])
        engine, *_ = deepspeed.initialize(
            model=SimpleModel(256, nlayers=8),
            config=base_config(zero_optimization={
                "stage": 2, "cpu_offload": True, "offload_chunk_mb": 1,
                "offload_uniform_chunks": False,
                "offload_state_dtype": "bf16"}), mesh=mesh)
        return engine

    e1, e2 = make(), make()
    batch = random_batches(1, e1.train_micro_batch_size_per_gpu(),
                           256, seed=0)[0]
    l1 = [float(np.asarray(e1.train_batch(iter([batch]))))
          for _ in range(4)]
    l2 = [float(np.asarray(e2.train_batch(iter([batch]))))
          for _ in range(4)]
    assert l1 == l2 and np.all(np.isfinite(l1))


def test_home_host_rehomes_numpy_staging(cpu_devices):
    """The mechanism itself: on a single-memory-space backend the
    homed buffer is a fresh XLA allocation — mutating (or freeing) the
    numpy staging array afterwards cannot change it."""
    mesh = make_mesh({"data": 1}, devices=cpu_devices[:1])
    flat = coord.FlatParamCoordinator(
        mesh, {"w": np.zeros((64, 64), np.float32)}, stage=2, dp_size=1)
    staging = np.full((4, 1024), 7.0, np.float32)
    homed = flat.home_host(staging)
    homed.block_until_ready()
    staging.fill(-1.0)
    assert float(np.asarray(homed)[0, 0]) == 7.0
    del staging
    np.testing.assert_array_equal(np.asarray(homed), 7.0)
