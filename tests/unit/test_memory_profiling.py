"""Memory observability suite (``deepspeed_tpu/profiling/memory`` +
``capacity`` + ``tools/bench_diff``): the compiled-program HBM ledger
(records every engine jit entry point's ``memory_analysis`` with zero
step-path cost and bit-identical training), live watermark events at the
steps_per_print cadence, the offload host-buffer registry, the AOT
capacity planner's fit/no-fit verdict on CPU (fail-soft when capacity is
unknowable), and the bench regression gate over the checked-in
``BENCH_r0*.json`` history."""

import glob
import json
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.profiling import capacity
from deepspeed_tpu.profiling import memory as mem
from deepspeed_tpu.telemetry import read_events, validate_event
from deepspeed_tpu.tools import bench_diff
from deepspeed_tpu.tools.bench_schema import (field_type, threshold_for,
                                              validate_record)

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 16
REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def tel_config(run_dir, **overrides):
    cfg = base_config(steps_per_print=1,
                      telemetry={"enabled": True, "run_dir": str(run_dir)},
                      profiling={"memory_ledger": True,
                                 "memory_watermarks": True})
    cfg.update(overrides)
    return cfg


def make_engine(config, cpu_devices, dp=4):
    mesh = make_mesh({"data": dp}, devices=cpu_devices[:dp])
    engine, *_ = deepspeed.initialize(model=SimpleModel(HIDDEN, nlayers=2),
                                      config=config, mesh=mesh)
    return engine


def run_steps(engine, batches):
    return [float(np.asarray(engine.train_batch(iter([b]))))
            for b in batches]


# ------------------------------------------------------------- the ledger
def test_ledger_records_engine_programs(cpu_devices, tmp_path):
    """Every dispatched jit entry point lands in the ledger with its
    memory_analysis bytes, one schema-clean ``memory`` event per program
    and per-program gauges — all recorded at compile time."""
    run_dir = tmp_path / "tel"
    engine = make_engine(tel_config(run_dir), cpu_devices)
    run_steps(engine, random_batches(2, 16, HIDDEN, seed=0))
    entries = engine.memory_ledger.entries()
    assert "train_step" in entries and "cast_params" in entries
    ts = entries["train_step"]
    assert ts["argument_size_in_bytes"] > 0
    assert ts["alias_size_in_bytes"] > 0          # donated buffers
    assert engine.memory_ledger.predicted_peak_bytes("train_step") > 0
    snap = engine.telemetry.registry.snapshot()
    assert snap["memory/program/train_step/argument_size_in_bytes"][
        "value"] > 0
    engine.close()
    events = [r for r in read_events(run_dir) if r["type"] == "memory"]
    programs = {e["data"]["program"] for e in events
                if e["data"]["kind"] == "program"}
    assert {"train_step", "cast_params"} <= programs
    for e in events:
        assert validate_event(e) == [], e


def test_ledger_training_parity(cpu_devices, tmp_path):
    """The ledger's compiled-executable path must train identically to
    the plain jit path (same programs, donation intact)."""
    batches = random_batches(4, 16, HIDDEN, seed=3)
    plain = run_steps(make_engine(base_config(), cpu_devices), batches)
    ledgered = run_steps(
        make_engine(base_config(profiling={"memory_ledger": True}),
                    cpu_devices), batches)
    assert plain == ledgered


def test_ledgered_jit_falls_back_on_shape_change():
    """A wrapped program keeps answering correctly when callers change
    shapes (falls back to jit retrace) — and records exactly once."""
    ledger = mem.MemoryLedger(enabled=True)
    calls = []

    @jax.jit
    def double(x):
        calls.append(None)  # traced per compile
        return x * 2

    wrapped = ledger.wrap("double", double)
    a = wrapped(jnp.arange(4.0))
    b = wrapped(jnp.arange(4.0))          # compiled path
    c = wrapped(jnp.arange(8.0))          # shape change -> jit fallback
    assert list(np.asarray(a)) == [0, 2, 4, 6]
    assert list(np.asarray(b)) == [0, 2, 4, 6]
    assert list(np.asarray(c))[:3] == [0, 2, 4]
    assert ledger.entry("double") is not None
    assert len(ledger.entries()) == 1


def test_ledgered_jit_static_argnums_and_tracers():
    ledger = mem.MemoryLedger(enabled=True)
    wrapped = ledger.wrap("ws", jax.jit(
        lambda x, spec: x * len(spec), static_argnums=(1,)),
        static_argnums=(1,))
    a = wrapped(jnp.ones(4), ("i", "j"))
    assert float(np.asarray(a)[0]) == 2.0
    # a DIFFERENT static value must not reuse the baked executable
    b = wrapped(jnp.ones(4), ("i", "j", "k"))
    assert float(np.asarray(b)[0]) == 3.0
    # tracer args (an outer trace over the wrapper) delegate cleanly
    g = jax.jit(lambda x: wrapped(x, ("i", "j")))(jnp.ones(4))
    assert float(np.asarray(g)[0]) == 2.0


def test_disabled_ledger_returns_raw_fn():
    ledger = mem.MemoryLedger(enabled=False)
    fn = jax.jit(lambda x: x)
    assert ledger.wrap("f", fn) is fn
    assert ledger.entries() == {}


# --------------------------------------------------- watermarks + buffers
def test_watermark_events_at_print_cadence(cpu_devices, tmp_path):
    """One ``memory``/watermark event per steps_per_print boundary,
    honest about backend capability (CPU reports no stats ->
    reporting=0, sums stay 0 rather than fabricated)."""
    run_dir = tmp_path / "tel"
    engine = make_engine(tel_config(run_dir), cpu_devices)
    run_steps(engine, random_batches(3, 16, HIDDEN, seed=1))
    engine.close()
    marks = [r for r in read_events(run_dir)
             if r["type"] == "memory" and r["data"]["kind"] == "watermark"]
    assert [m["step"] for m in marks] == [1, 2, 3]
    for m in marks:
        data = m["data"]
        assert {"bytes_in_use", "peak_bytes_in_use", "devices",
                "reporting", "host_buffer_bytes"} <= set(data)
        if data["reporting"] == 0:
            assert data["bytes_in_use"] == 0


def test_host_buffer_registry_under_offload(cpu_devices, tmp_path,
                                            monkeypatch):
    """The offload coordinator feeds the pinned-buffer registry: one
    family per host buffer (master + flat optimizer leaves), group
    counts matching the coordinator layout, and one host_buffers event
    carrying the per-step wire bytes."""
    from deepspeed_tpu.runtime.zero import coordinator as coord

    monkeypatch.setenv("DS_OFFLOAD_FORCE_INJIT", "1")
    monkeypatch.setattr(coord, "HOST_GROUP_BYTES", 2 << 20)
    run_dir = tmp_path / "tel"
    mesh = make_mesh({"data": 1}, devices=cpu_devices[:1])
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(256, nlayers=3),
        config=tel_config(run_dir,
                          zero_optimization={"stage": 2,
                                             "cpu_offload": True,
                                             "offload_chunk_mb": 1}),
        mesh=mesh)
    registry = engine.memory_ledger.host_buffers
    families = {e["family"]: e for e in registry.entries()}
    assert "master" in families
    assert any(f.startswith("opt/") for f in families)
    bounds, per_family = engine.flat.host_buffer_layout()
    assert families["master"]["count"] == len(bounds) == per_family
    assert registry.total_bytes() > 0
    run_steps(engine, random_batches(1, 16, 256, seed=2))
    engine.close()
    buf_events = [r for r in read_events(run_dir)
                  if r["type"] == "memory"
                  and r["data"]["kind"] == "host_buffers"]
    assert buf_events
    data = buf_events[0]["data"]
    assert data["bytes"] == registry.total_bytes()
    assert data["buffers"] == registry.total_count()
    assert data.get("state_wire_bytes_per_step", 0) > 0


# ------------------------------------------------- shared memory summary
class _FakeDev:
    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        return self._stats


def test_device_memory_summary_sums_across_devices():
    devs = [_FakeDev({"bytes_in_use": 10, "peak_bytes_in_use": 20,
                      "bytes_limit": 100}),
            _FakeDev({"bytes_in_use": 1, "peak_bytes_in_use": 2,
                      "bytes_limit": 100}),
            _FakeDev(None)]
    s = mem.device_memory_summary(devs)
    assert s == {"bytes_in_use": 11, "peak_bytes_in_use": 22,
                 "bytes_limit": 200, "devices": 3, "reporting": 2}


def test_see_memory_usage_routes_through_shared_helper(monkeypatch):
    """Both historical call sites (runtime.utils + the engine's
    memory_breakdown) resolve to the one cross-device implementation —
    the device-0-only reader is gone."""
    from deepspeed_tpu.runtime.utils import see_memory_usage
    from deepspeed_tpu.utils.logging import logger

    fake = {"bytes_in_use": 3 << 30, "peak_bytes_in_use": 5 << 30,
            "bytes_limit": 32 << 30, "devices": 2, "reporting": 2}
    monkeypatch.setattr(mem, "device_memory_summary", lambda devices=None:
                        dict(fake))
    messages = []
    handler = logging.Handler()
    handler.emit = lambda rec: messages.append(rec.getMessage())
    logger.addHandler(handler)
    try:
        see_memory_usage("after step", force=True)
        see_memory_usage("quiet")          # force=False: no output
    finally:
        logger.removeHandler(handler)
    assert len(messages) == 1
    assert "after step" in messages[0]
    assert "5.0000 GB" in messages[0] and "2/2 local device(s)" \
        in messages[0]
    # the timer's breakdown string comes from the same summary
    from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer

    assert "2/2 local device(s)" in SynchronizedWallClockTimer.memory_usage()


# ---------------------------------------------------- capacity planner
def _planner_config(tmp_path):
    path = tmp_path / "plan_config.json"
    path.write_text(json.dumps({
        "train_batch_size": 2,
        "steps_per_print": 10 ** 9,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
    }))
    return str(path)


def _run_planner(tmp_path, capsys, *extra):
    rc = capacity.main([
        "--config", _planner_config(tmp_path),
        "--hidden", "32", "--layers", "1", "--heads", "2",
        "--seq", "64", "--batch", "2", "--json", *extra])
    out = capsys.readouterr().out.strip().splitlines()
    return rc, json.loads(out[-1])


def test_capacity_planner_fit_verdict(tmp_path, capsys):
    """CPU acceptance: compile-only plan, real memory_analysis numbers,
    FIT against an ample capacity — exit 0, no step ever runs."""
    rc, result = _run_planner(tmp_path, capsys, "--capacity-gb", "64")
    assert rc == 0 and result["fit"] is True
    assert result["analysis_available"]
    assert result["predicted_peak_hbm_bytes"] > 0
    assert result["predicted_temp_bytes"] >= 0
    assert result["params_b"] > 0


def test_capacity_planner_no_fit_verdict(tmp_path, capsys):
    rc, result = _run_planner(tmp_path, capsys, "--capacity-gb", "0.0001")
    assert rc == 1 and result["fit"] is False


def test_capacity_planner_fail_soft_without_capacity(tmp_path, capsys):
    """CPU reports no bytes_limit: verdict must degrade to UNKNOWN
    (exit 3), never crash — the fail-soft contract."""
    rc, result = _run_planner(tmp_path, capsys)
    assert rc == 3 and result["fit"] is None
    assert result["predicted_peak_hbm_bytes"] > 0   # analysis still real


def test_capacity_planner_usage_errors_exit_2(tmp_path, capsys):
    """Exit-code contract: 1 is reserved for NO-FIT — a typo'd model or
    a partial --hidden/--layers/--heads spec must exit 2, not plan the
    preset default."""
    cfg = _planner_config(tmp_path)
    assert capacity.main(["--config", cfg, "--model", "gpt2-typo"]) == 2
    assert capacity.main(["--config", cfg, "--hidden", "2048",
                          "--layers", "24"]) == 2  # --heads forgotten
    assert capacity.main(["--config", str(tmp_path / "absent.json")]) == 2
    err = capsys.readouterr().err
    assert "gpt2-typo" in err or "--model" in err
    assert "must all be given together" in err


def _stage_planner_config(tmp_path):
    """One config for the stage-2 vs stage-3 planner arms: the stage is
    the ONLY thing --zero-stage varies, so the verdicts compare exactly
    the ÷dp sharding.  Small collective groups keep the gathered-buffer
    liveness (and the CPU compile) bounded."""
    path = tmp_path / "plan_stage_config.json"
    path.write_text(json.dumps({
        "train_batch_size": 4,
        "steps_per_print": 10 ** 9,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 2, "overlap_comm": "auto",
                              "reduce_bucket_size": 12500000,
                              "allgather_bucket_size": 25000000},
    }))
    return str(path)


def _run_stage_planner(cfg, capsys, stage, *extra):
    rc = capacity.main([
        "--config", cfg, "--hidden", "32", "--layers", "1",
        "--heads", "2", "--seq", "64", "--batch", "4", "--dp", "4",
        "--zero-stage", str(stage), "--json", *extra])
    out = capsys.readouterr().out.strip().splitlines()
    return rc, json.loads(out[-1])


def test_capacity_planner_stage3_divdp_receipt(tmp_path, capsys):
    """``--zero-stage 3 --dp 4``: the plan's residency receipt quotes
    the flat fp32 master ÷dp (param_shard_divisor == dp) where the
    stage-2 plan at the SAME geometry quotes the replicated figure ÷1
    — the planner-verified ÷dp receipt of ROADMAP item 2."""
    cfg = _stage_planner_config(tmp_path)
    rc3, r3 = _run_stage_planner(cfg, capsys, 3, "--capacity-gb", "64")
    assert rc3 == 0 and r3["fit"] is True
    assert r3["zero_stage"] == 3 and r3["dp"] == 4
    assert r3["param_shard_divisor"] == 4
    assert r3["param_bytes_per_device"] * 4 == r3["param_bytes_global"]
    rc2, r2 = _run_stage_planner(cfg, capsys, 2, "--capacity-gb", "64")
    assert rc2 == 0 and r2["zero_stage"] == 2
    assert r2["param_shard_divisor"] == 1
    assert r2["param_bytes_per_device"] == r2["param_bytes_global"]
    # same model: the stage-3 per-device claim is a quarter of the
    # replicated one (modulo the flat layout's row/bucket padding)
    assert r3["param_bytes_per_device"] < r2["param_bytes_per_device"] / 3


def test_capacity_planner_stage3_report_prints_shard_line(tmp_path,
                                                          capsys):
    """The human report carries the ÷shard line verbatim."""
    cfg = _stage_planner_config(tmp_path)
    rc = capacity.main([
        "--config", cfg, "--hidden", "32", "--layers", "1",
        "--heads", "2", "--seq", "64", "--batch", "4", "--dp", "4",
        "--zero-stage", "3", "--capacity-gb", "64"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "zero-stage=3 dp=4" in out
    assert "÷4 shard" in out


@pytest.mark.slow
def test_capacity_planner_stage3_fits_what_stage2_rejects(tmp_path,
                                                          capsys):
    """The round-20 capacity acceptance arms: a gpt2-xl-or-larger
    (1.82B params — hidden 4096 over 8 wide layers, more than gpt2-xl's
    1.56B) DEVICE-RESIDENT plan at dp=4 that stage 3 fits (exit 0) and
    stage 2 rejects (exit 1) at the same geometry and capacity.  The
    capacity is derived from the measured peaks rather than hardcoded:
    alias accounting differs between cold and cache-deserialized
    executables (DSP602), so the measure arms run AFTER a warm-up pass
    and the verdict arms re-plan under the same cache state."""
    cfg = _stage_planner_config(tmp_path)
    geom = ("--hidden", "4096", "--layers", "8", "--heads", "32",
            "--seq", "256", "--batch", "4", "--dp", "4")

    def arm(stage, *extra):
        rc = capacity.main(["--config", cfg, *geom, "--zero-stage",
                            str(stage), "--json", *extra])
        out = capsys.readouterr().out.strip().splitlines()
        return rc, json.loads(out[-1])

    arm(3)
    arm(2)                         # warm-up: pin the alias accounting
    rc3, r3 = arm(3)
    rc2, r2 = arm(2)
    assert rc3 == 3 and rc2 == 3   # fail-soft: no capacity known on CPU
    # gpt2-xl or larger (the xl preset's analytic count at its own
    # 1024-position table)
    xl_b = round(capacity.gpt2_param_count(1600, 48) / 1e9, 3)
    assert r3["params_b"] >= xl_b
    assert r3["param_shard_divisor"] == 4
    assert r3["param_bytes_per_device"] * 4 == r3["param_bytes_global"]
    assert r2["param_shard_divisor"] == 1
    p3 = r3["predicted_peak_hbm_bytes"]
    p2 = r2["predicted_peak_hbm_bytes"]
    assert p3 < p2, (p3, p2)
    # verdict arms: capacity strictly between the two measured peaks
    cap_gb = (p3 * 1.02) / capacity.DEFAULT_HEADROOM / (1 << 30)
    assert p2 > p3 * 1.02, (p3, p2)
    rc3, r3 = arm(3, "--capacity-gb", f"{cap_gb:.6f}")
    assert rc3 == 0 and r3["fit"] is True
    rc2, r2 = arm(2, "--capacity-gb", f"{cap_gb:.6f}")
    assert rc2 == 1 and r2["fit"] is False


def test_predicted_peak_accounting():
    entry = {"argument_size_in_bytes": 100, "output_size_in_bytes": 90,
             "alias_size_in_bytes": 80, "temp_size_in_bytes": 50,
             "generated_code_size_in_bytes": 7,
             "host_argument_size_in_bytes": 30,
             "host_output_size_in_bytes": 30,
             "host_alias_size_in_bytes": 30, "host_temp_size_in_bytes": 5}
    assert mem.predicted_peak_bytes(entry) == 100 + 90 - 80 + 50 + 7
    assert mem.predicted_host_bytes(entry) == 30 + 30 - 30 + 5
    assert mem.predicted_peak_bytes(None) is None


# ------------------------------------------------------ bench regression
def test_bench_diff_classification():
    old = {"value": 100.0, "offload_gpt2_large_ms_per_step": 1000.0,
           "loss": 8.0, "device": "TPU v5 lite", "mfu": 0.5}
    new = {"value": 80.0, "offload_gpt2_large_ms_per_step": 850.0,
           "loss": 9.5, "device": "TPU v5 lite", "mfu": 0.51,
           "peak_hbm_bytes": 7}
    by_field = {d["field"]: d for d in bench_diff.diff_records(old, new)}
    assert by_field["value"]["status"] == "regressed"          # -20% tput
    assert by_field["offload_gpt2_large_ms_per_step"]["status"] \
        == "improved"                                          # -15% time
    assert by_field["loss"]["status"] == "info"                # no gate
    assert by_field["device"]["status"] == "ok"
    assert by_field["mfu"]["status"] == "ok"                   # +2% < tol
    assert by_field["peak_hbm_bytes"]["status"] == "added"
    assert len(bench_diff.regressions(by_field.values())) == 1


def test_bench_diff_cli_gate(tmp_path, capsys):
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps({"value": 100.0}))
    b.write_text(json.dumps({"parsed": {"value": 50.0}}))  # driver wrapper
    assert bench_diff.main([str(a), str(b)]) == 1          # gate trips
    assert "REGRESSED" in capsys.readouterr().out
    assert bench_diff.main([str(a), str(b), "--no-fail"]) == 0
    assert bench_diff.main([str(b), str(a)]) == 0          # improvement


def test_bench_diff_self_check_over_checked_in_history(capsys):
    """CI mode over the real BENCH_r0*.json sequence: violations are
    REPORTED, historical rows never hard-fail (exit 0 by contract)."""
    artifacts = sorted(glob.glob(os.path.join(REPO, "BENCH_r0*.json")))
    assert len(artifacts) >= 2, "checked-in bench history missing"
    rc = bench_diff.main(["--self-check", *artifacts])
    out = capsys.readouterr().out
    assert rc == 0
    assert out.count("bench diff:") == len(artifacts) - 1
    assert "field(s) compared" in out


def test_report_cli_diff_mode(tmp_path, capsys):
    from deepspeed_tpu.telemetry import report as report_mod

    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps({"value": 100.0}))
    b.write_text(json.dumps({"value": 101.0}))
    assert report_mod.main(["report", "--diff", str(a), str(b)]) == 0
    assert "bench diff" in capsys.readouterr().out
    # without --diff, run_dir stays required
    assert report_mod.main(["report"]) == 2
    # the regression gate survives the combined run_dir + --diff form
    from deepspeed_tpu.telemetry import EventLog

    run_dir = tmp_path / "run"
    log = EventLog(run_dir, rank=0)
    log.emit("run_start", step=0, world_size=1)
    log.close()
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"value": 50.0}))
    assert report_mod.main(["report", str(run_dir),
                            "--diff", str(a), str(bad)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSED" in out and "timeline" in out
    # --json + --diff emits ONE JSON document (the diff), gate intact
    assert report_mod.main(["report", str(run_dir), "--json",
                            "--diff", str(a), str(bad)]) == 1
    json.loads(capsys.readouterr().out)  # parseable as a single doc


def test_bench_schema_memory_receipt_fields():
    record = {
        "peak_hbm_bytes": 12884901888,
        "predicted_temp_bytes": 7516192768,
        "offload_gpt2_xl_peak_hbm_bytes": 15032385536,
        "offload_gpt2_xl_predicted_temp_bytes": 9663676416,
        "offload_gpt2_xl_host_buffer_bytes": 18677760000,
    }
    assert validate_record(record) == []
    assert field_type("offload_gpt2_27b_host_buffer_bytes")
    assert threshold_for("value") == ("higher", 0.05)
    assert threshold_for("offload_gpt2_xl_ms_per_step") == ("lower", 0.10)
    assert threshold_for("loss") == (None, None)
    assert threshold_for("offload_gpt2_xl_host_groups") == (None, None)


# ------------------------------------------------------------ env report
def test_env_report_prints_hbm_capacity(capsys):
    from deepspeed_tpu import env_report

    env_report.main()
    out = capsys.readouterr().out
    assert "hbm capacity" in out
