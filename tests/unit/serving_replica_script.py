"""Serving-fleet child script for the serving chaos e2e tests.

Driven by ``deepspeed_tpu.launcher.launch`` with the elastic supervisor
armed.  Every process is one serving replica: a full copy of the tiny
GPT-2 on one virtual CPU device behind an :class:`InferenceEngine` with
the resilience plane armed (``ServingHealth`` heartbeats + weight-
fingerprint consensus into the launcher's shared ``DS_TELEMETRY_DIR``,
``arm_serving_preemption`` for the SIGTERM drain).

The fleet serves ONE shared, seeded request set with an exactly-once
ledger protocol:

- every life appends finished results to its own ``results-<pid>.jsonl``
  (O_APPEND, one flushed JSON line per request) in the shared out dir;
- at life start a replica unions every ledger into the done-set, sorts
  the remaining request ids, and serves the slice ``remaining[rank ::
  world]`` — disjoint within a life, re-planned each life, so a resized
  fleet picks up exactly the dead replicas' unfinished work;
- a replica whose slice is drained PARKS: it keeps beating (a clean
  early finisher must never read as hung to the quorum) and keeps
  voting the fingerprint consensus at a throttled cadence, exiting 0
  only once the union covers every request;
- a replica convicted of SDC by the consensus deletes its OWN current
  life's ledger before exiting 87: every token it served since the flip
  is suspect, and deleting the ledger re-queues them onto healthy
  replicas (re-served greedily => bit-identical to the reference).

Chaos (first life per slot only, seeded, one target rank), selected by
``DS_SERVE_CHAOS_KIND``:

- ``kill``  — the target SIGKILLs itself mid-decode at engine iteration
  ``DS_SERVE_CHAOS_STEP``: the supervisor sees the signal death and
  resizes; survivors drain under SIGTERM and the next life re-serves
  the dead replica's remainder.
- ``hang``  — the target wedges before that iteration (beats stop); the
  PARKED/serving majority's freshness quorum convicts it, exits 87
  with a verdict, and the supervisor aims the resize at its slot.
- ``bitflip`` — one seeded bit of the target's weights flips; the next
  fingerprint cadence names it, the fleet exits 87, the target deletes
  its suspect ledger, and the resized fleet re-serves its requests.

argv: <out_dir>   (telemetry/run dir rides DS_TELEMETRY_DIR)
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402,F401 — fail fast before engine construction

from deepspeed_tpu.inference import (InferenceEngine,  # noqa: E402
                                     ServingHealth,
                                     arm_serving_preemption)
from deepspeed_tpu.inference.resilience import (  # noqa: E402
    read_fleet_weight_fingerprints)
from deepspeed_tpu.resilience.chaos import ChaosMonkey  # noqa: E402
from deepspeed_tpu.resilience.constants import (  # noqa: E402
    FleetIntegrityError, TrainingDivergedError)
from deepspeed_tpu.resilience import integrity as integ  # noqa: E402

from test_inference import (seeded_prompts, serve_config,  # noqa: E402
                            tiny_model)

STEPS_PER_PRINT = 2          # fingerprint-vote cadence (decode iters)


def _env_int(name, default=0):
    return int(os.environ.get(name, "") or default)


def _env_float(name, default=0.0):
    return float(os.environ.get(name, "") or default)


def request_set():
    """The fleet-wide request set: (rid -> prompt), rid-sorted ids.
    Seed/count/cap come from the env so the TEST builds the identical
    set for its uninterrupted reference."""
    n = _env_int("DS_SERVE_REQUESTS", 9)
    seed = _env_int("DS_SERVE_SEED", 71)
    prompts = seeded_prompts(n, seed=seed)
    return {f"req-{i:03d}": p for i, p in enumerate(prompts)}


def read_done(out_dir):
    """Union of every life's ledger: rid -> record.  Torn trailing
    lines (a writer died mid-append) parse as garbage and are skipped —
    an unparsable record is NOT done and gets re-served."""
    done = {}
    for name in sorted(os.listdir(out_dir)):
        if not name.startswith("results-"):
            continue
        with open(os.path.join(out_dir, name)) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                    done[rec["rid"]] = rec
                except (ValueError, KeyError):
                    continue
    return done


def main():
    out_dir = sys.argv[1]
    os.makedirs(out_dir, exist_ok=True)
    rank = _env_int("DS_PROCESS_ID", 0)
    world = _env_int("DS_NUM_PROCESSES", 1)
    slot = _env_int("DS_LOCAL_RANK", 0)
    tel_dir = os.environ["DS_TELEMETRY_DIR"]
    max_new = _env_int("DS_SERVE_MAX_NEW", 4)

    # first-life-per-slot marker: chaos is a one-shot fault injection,
    # respawned lives on the same slot must serve clean
    marker = os.path.join(out_dir, f"chaos-armed-slot{slot}")
    fresh = not os.path.exists(marker)
    with open(marker, "a"):
        pass

    config = serve_config(max_new_tokens=max_new)
    config["steps_per_print"] = STEPS_PER_PRINT
    config["telemetry"] = {"enabled": True, "run_dir": tel_dir}
    model = tiny_model()
    engine = InferenceEngine(model, model.init(jax.random.PRNGKey(0)),
                             config=config)

    # warm up EVERY prefill bucket + the decode program BEFORE arming
    # chaos or health: a lazy bucket compile mid-serving stalls the
    # main thread for seconds — longer than a tight peer timeout — and
    # the freshness quorum would convict a healthy compiling replica
    # instead of the wedged one.  Before the first beat this rank is
    # unpublished and CANNOT be convicted, so compiling here is safe.
    warm = [f"warmup-{os.getpid()}-{i}" for i in range(3)]
    for rid, plen in zip(warm, (4, 12, 24)):     # buckets 8 / 16 / 32
        engine.submit([1] * plen, max_new_tokens=1, request_id=rid)
    engine.run()
    for rid in warm:
        engine.forget(rid)

    kind = os.environ.get("DS_SERVE_CHAOS_KIND", "")
    target = _env_int("DS_SERVE_CHAOS_TARGET", -1)
    step = _env_int("DS_SERVE_CHAOS_STEP", 3)
    if fresh and kind:
        monkey = ChaosMonkey(seed=_env_int("DS_SERVE_CHAOS_SEED", 19))
        monkey.wrap_engine_step(
            engine,
            kill_steps=[step] if kind == "kill" else (),
            hang_steps=[step] if kind == "hang" else (),
            hang_secs=600.0,
            bitflip_steps=[step] if kind == "bitflip" else (),
            rank=rank, target_rank=target)

    health = ServingHealth(
        engine, tel_dir, rank, world,
        peer_timeout_secs=_env_float("DS_SERVE_PEER_TIMEOUT", 30.0))
    engine.attach_health(health)

    # startup fingerprint barrier: publish THIS replica's (healthy)
    # fingerprint and wait until the whole fleet has published.  All
    # values are equal here, so the vote is OK/PENDING — but a later
    # post-flip vote is then guaranteed a full voter set: with only 2
    # of 3 voters on disk, a corrupt-vs-healthy tie would read as
    # NO_MAJORITY and POISON the fleet instead of evicting the suspect
    health.sample()
    barrier_deadline = time.time() + 60
    while (len(read_fleet_weight_fingerprints(tel_dir, world)) < world
           and time.time() < barrier_deadline):
        time.sleep(0.05)

    ledger_path = os.path.join(out_dir, f"results-{os.getpid()}.jsonl")
    written = set()

    def flush_finished(f):
        """Append every finished-but-unwritten result: one flushed line
        per request, so a death at any instant loses at most one torn
        (=> skipped, => re-served) record."""
        for rid in list(mine):
            if rid in written:
                continue
            req = engine.request(rid)
            if req is None or req.state != "finished":
                continue
            rec = req.result()
            f.write(json.dumps({
                "rid": rid, "tokens": rec["tokens"],
                "finish_reason": rec["finish_reason"],
                "rank": rank, "life": os.getpid()}) + "\n")
            f.flush()
            written.add(rid)

    all_requests = request_set()
    done = read_done(out_dir)
    remaining = sorted(r for r in all_requests if r not in done)
    mine = remaining[rank::world]

    def drain_exit(code):
        # SIGTERM drain (resize/preemption): arm_serving_preemption
        # already ran engine.close() — persist whatever the drain
        # finished, then die respawnable
        try:
            with open(ledger_path, "a") as f:
                flush_finished(f)
        finally:
            os._exit(code)

    arm_serving_preemption(engine, exit_fn=drain_exit)

    try:
        with open(ledger_path, "a") as f:
            for rid in mine:
                engine.submit(all_requests[rid], max_new_tokens=max_new,
                              request_id=rid)
            while not engine.scheduler.idle():
                engine.step()
                flush_finished(f)
            flush_finished(f)
            # PARK: slice drained, fleet still serving.  Keep beating
            # (a clean finisher must stay "fresh" to the hang quorum)
            # and keep voting the consensus at a throttled cadence; a
            # flip landing after our last decode is still convicted.
            it = engine.decode_iterations
            while set(read_done(out_dir)) < set(all_requests):
                it += 1
                health.beat(it)
                if it % 20 == 0:
                    health.sample()
                time.sleep(0.05)
    except (FleetIntegrityError, TrainingDivergedError) as e:
        suspect = getattr(e, "suspect", None)
        if (getattr(e, "kind", None) == integ.KIND_SDC
                and suspect is not None and int(suspect) == rank):
            # every token this life served since the flip is suspect:
            # withdraw the whole life's ledger so healthy replicas
            # re-serve it (greedy decode => bit-identical re-serve)
            try:
                os.remove(ledger_path)
            except OSError:
                pass
        sys.exit(e.exit_code)

    engine.close()
    sys.exit(0)


if __name__ == "__main__":
    main()
