"""End-to-end engine tests over a virtual 8-device data mesh (modeled on
reference ``tests/unit/test_fp16.py`` / ``test_zero.py`` coverage)."""

import jax
import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 16


def make_engine(config, cpu_devices, dp=8, nlayers=2):
    mesh = make_mesh({"data": dp}, devices=cpu_devices[:dp])
    model = SimpleModel(HIDDEN, nlayers=nlayers)
    engine, opt, loader, sched = deepspeed.initialize(
        model=model, config=config, mesh=mesh)
    return engine


def train_losses(engine, steps=5, seed=0):
    gas = engine.gradient_accumulation_steps()
    batches = random_batches(steps * gas,
                             engine.train_micro_batch_size_per_gpu() * engine.dp_world_size,
                             HIDDEN, seed=seed)
    it = iter(batches)
    losses = []
    for _ in range(steps):
        loss = engine.train_batch(it)
        losses.append(float(np.asarray(loss)))
    return losses


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_zero_stages_train(stage, cpu_devices):
    config = base_config(zero_optimization={"stage": stage},
                         bf16={"enabled": stage > 0})
    engine = make_engine(config, cpu_devices)
    losses = train_losses(engine, steps=6)
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"
    assert engine.global_steps == 6


def test_zero_stage_parity(cpu_devices):
    """All ZeRO stages must produce identical training trajectories (the
    reference asserts ZeRO correctness against unsharded training,
    ``test_zero.py:32``)."""
    trajs = {}
    for stage in [0, 1, 2, 3]:
        config = base_config(zero_optimization={"stage": stage})
        engine = make_engine(config, cpu_devices)
        trajs[stage] = train_losses(engine, steps=4)
    for stage in [1, 2, 3]:
        np.testing.assert_allclose(trajs[stage], trajs[0], rtol=2e-5,
                                   err_msg=f"stage {stage} diverged from stage 0")


def test_gradient_accumulation(cpu_devices):
    """grad_acc=2 with half micro-batch must match grad_acc=1 trajectories."""
    cfg1 = base_config(train_batch_size=16, gradient_accumulation_steps=1)
    cfg2 = base_config(train_batch_size=16, gradient_accumulation_steps=2)
    e1 = make_engine(cfg1, cpu_devices)
    e2 = make_engine(cfg2, cpu_devices)

    batches = random_batches(8, 16, HIDDEN, seed=3)
    l1 = []
    for i in range(4):
        l1.append(float(np.asarray(e1.train_batch(iter([batches[2 * i]])))))
        # feed same data twice? no: grad-acc engine consumes two half batches
    # Build half micro-batches for e2: split each full batch into two halves
    # along batch dim scaled so the accumulated gradient matches.
    l2 = []
    for i in range(4):
        x, y = batches[2 * i]
        halves = [(x[:8], y[:8]), (x[8:], y[8:])]
        l2.append(float(np.asarray(e2.train_batch(iter(halves)))))
    # identical data split across micro batches: mean loss equal, updates equal
    np.testing.assert_allclose(l2, l1, rtol=2e-5)


def test_dataloader_and_train(cpu_devices):
    from .simple_model import random_dataset

    config = base_config()
    mesh = make_mesh({"data": 8}, devices=cpu_devices)
    model = SimpleModel(HIDDEN, nlayers=1)
    engine, _, loader, _ = deepspeed.initialize(
        model=model, config=config, mesh=mesh,
        training_data=random_dataset(64, HIDDEN))
    assert loader is not None
    assert len(loader) == 4
    loss = engine.train_batch()
    assert np.isfinite(float(np.asarray(loss)))


def test_fp16_dynamic_loss_scale_skips(cpu_devices):
    """Overflow must skip the update, halve the scale, and count the skip
    (reference ``test_dynamic_loss_scale.py`` semantics)."""
    config = base_config(
        fp16={"enabled": True, "initial_scale_power": 4, "loss_scale_window": 2,
              "hysteresis": 1, "min_loss_scale": 0.25})
    engine = make_engine(config, cpu_devices, nlayers=1)
    assert engine.loss_scale == 2 ** 4

    batches = random_batches(4, 16, HIDDEN, seed=1)
    master_before = np.asarray(engine.get_master_params())

    # Poison one batch to force inf grads.
    x, y = batches[0]
    x_bad = x.copy()
    x_bad[0, 0] = np.float32(np.inf)
    engine.train_batch(iter([(x_bad, y)]))
    assert engine.skipped_steps == 1
    assert engine.loss_scale == 2 ** 3
    master_after = np.asarray(engine.get_master_params())
    np.testing.assert_array_equal(master_before, master_after)

    # A clean step applies normally.
    engine.train_batch(iter([batches[1]]))
    assert engine.skipped_steps == 1
    assert not np.array_equal(np.asarray(engine.get_master_params()), master_before)


def test_scale_window_growth(cpu_devices):
    config = base_config(
        fp16={"enabled": True, "initial_scale_power": 4, "loss_scale_window": 2,
              "hysteresis": 1})
    engine = make_engine(config, cpu_devices, nlayers=1)
    batches = random_batches(4, 16, HIDDEN, seed=2)
    for b in batches:
        engine.train_batch(iter([b]))
    # 4 good steps with window 2 → scale doubled twice
    assert engine.loss_scale == 2 ** 6


def test_lamb_optimizer(cpu_devices):
    config = base_config(optimizer={"type": "Lamb", "params": {"lr": 0.01}},
                         zero_optimization={"stage": 2}, bf16={"enabled": True})
    engine = make_engine(config, cpu_devices)
    losses = train_losses(engine, steps=5)
    assert losses[-1] < losses[0]


def test_warmup_lr_schedule(cpu_devices):
    config = base_config(
        scheduler={"type": "WarmupLR",
                   "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 0.01,
                              "warmup_num_steps": 10}})
    engine = make_engine(config, cpu_devices)
    lrs = []
    batches = random_batches(5, 16, HIDDEN)
    for b in batches:
        engine.train_batch(iter([b]))
        lrs.append(engine.get_lr()[0])
    assert lrs == sorted(lrs)
    # log-warmup: first step lands at gamma=log(1)=0 → min_lr (reference
    # WarmupLR._get_gamma, lr_schedules.py:745-748)
    assert lrs[0] == 0.0
    assert lrs[1] > 0.0
    assert lrs[-1] < 0.01


def test_scheduler_restore_reapplies_hyperparams():
    """load_state_dict must re-apply the restored-iteration lr (and
    OneCycle's betas) to the optimizer immediately: the first post-resume
    update fires BEFORE the next scheduler.step() (caught by the
    checkpoint-continuity gate).  A pre-first-step checkpoint
    (iteration -1) must leave the construction state untouched."""
    from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
    from deepspeed_tpu.runtime.lr_schedules import OneCycle, WarmupLR

    opt = FusedAdam(lr=5e-4)
    sched = WarmupLR(opt, warmup_min_lr=0.0, warmup_max_lr=1e-2,
                     warmup_num_steps=10)
    for _ in range(5):
        sched.step()
    sd = sched.state_dict()
    lr_at_5 = opt.param_groups[0]["lr"]

    opt2 = FusedAdam(lr=5e-4)
    sched2 = WarmupLR(opt2, warmup_min_lr=0.0, warmup_max_lr=1e-2,
                      warmup_num_steps=10)
    sched2.load_state_dict(sd)
    assert opt2.param_groups[0]["lr"] == lr_at_5

    # pre-first-step checkpoint: construction lr preserved (get_lr's -1
    # sentinel must not clobber it)
    opt3 = FusedAdam(lr=5e-4)
    sched3 = WarmupLR(opt3, warmup_min_lr=0.0, warmup_max_lr=1e-2,
                      warmup_num_steps=10)
    sched3.load_state_dict({"last_batch_iteration": -1})
    assert opt3.param_groups[0]["lr"] == 5e-4

    # OneCycle schedules betas too — restore must re-apply both
    opt4 = FusedAdam(lr=5e-4)
    c1 = OneCycle(opt4, cycle_min_lr=1e-4, cycle_max_lr=1e-2,
                  cycle_first_step_size=10)
    for _ in range(7):
        c1.step()
    sd4 = c1.state_dict()
    lr4, betas4 = opt4.param_groups[0]["lr"], opt4.param_groups[0]["betas"]
    opt5 = FusedAdam(lr=5e-4)
    c2 = OneCycle(opt5, cycle_min_lr=1e-4, cycle_max_lr=1e-2,
                  cycle_first_step_size=10)
    c2.load_state_dict(sd4)
    assert opt5.param_groups[0]["lr"] == lr4
    assert opt5.param_groups[0]["betas"] == betas4


def test_eval_batch(cpu_devices):
    from .simple_model import SimpleMLPWithLogits

    config = base_config()
    mesh = make_mesh({"data": 8}, devices=cpu_devices)
    model = SimpleMLPWithLogits(HIDDEN, nlayers=1)
    engine, _, _, _ = deepspeed.initialize(model=model, config=config, mesh=mesh)
    x = np.random.default_rng(0).normal(size=(16, HIDDEN)).astype(np.float32)
    out = engine.eval_batch((x, x))
    assert out.shape == (16, HIDDEN)
    # iterator form (the reference eval_batch contract, pipe/engine.py:320)
    out_it = engine.eval_batch(iter([(x, x)]))
    np.testing.assert_allclose(np.asarray(out_it), np.asarray(out))


def test_eval_batch_iterator_aggregates_micro_batches(cpu_devices):
    """Iterator form draws gradient_accumulation_steps micro-batches and
    returns their mean — the reference pipe-engine contract
    (pipe/engine.py:320)."""
    from .simple_model import SimpleMLPWithLogits

    config = dict(base_config())
    config["train_batch_size"] = 32
    config["train_micro_batch_size_per_gpu"] = 2
    config["gradient_accumulation_steps"] = 2
    mesh = make_mesh({"data": 8}, devices=cpu_devices)
    model = SimpleMLPWithLogits(HIDDEN, nlayers=1)
    engine, _, _, _ = deepspeed.initialize(model=model, config=config, mesh=mesh)
    rng = np.random.default_rng(0)
    b1 = rng.normal(size=(16, HIDDEN)).astype(np.float32)
    b2 = rng.normal(size=(16, HIDDEN)).astype(np.float32)
    out1 = engine.eval_batch((b1, b1))
    out2 = engine.eval_batch((b2, b2))
    it = iter([(b1, b1), (b2, b2), (b1, b1)])
    agg = engine.eval_batch(it)
    np.testing.assert_allclose(
        np.asarray(agg), (np.asarray(out1) + np.asarray(out2)) / 2,
        rtol=1e-6)
    # exactly micro_batches entries consumed
    assert next(it)[0] is b1


@pytest.mark.slow
def test_zero3_shards_resident_state_compile_time():
    """ZeRO-3's memory claim, checked at compile time: the train step's
    persistent buffers (master + optimizer state, no resident params) are
    sharded over ``data``, so per-step argument size shrinks ~dp-fold vs
    stage 0, and the in-step gather materializes only compute-dtype
    parameters as temporaries (VERDICT r1 weak #7: no replicated fp32
    master copy)."""
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import GPT2Config, GPT2LMHeadTPU
    from deepspeed_tpu.parallel import make_mesh

    def arg_bytes(stage):
        mesh = make_mesh({"data": 8}, devices=jax.devices("cpu")[:8])
        config = {"train_batch_size": 8, "steps_per_print": 10 ** 9,
                  "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                  "zero_optimization": {"stage": stage}}
        model = GPT2LMHeadTPU(GPT2Config(
            vocab_size=1024, hidden_size=256, num_layers=3, num_heads=4,
            max_position_embeddings=64, embd_dropout=0.0, attn_dropout=0.0,
            resid_dropout=0.0))
        engine, *_ = deepspeed.initialize(model=model, config=config,
                                          mesh=mesh)
        captured = {}
        orig = engine._train_step_fn
        engine._train_step_fn = lambda *a, **kw: (
            captured.__setitem__("args", a) or orig(*a, **kw))
        engine.train_batch(iter([{
            "input_ids": np.zeros((8, 64), np.int32)}]))
        ma = orig.lower(*captured["args"]).compile().memory_analysis()
        return ma.argument_size_in_bytes, ma.temp_size_in_bytes

    args0, _ = arg_bytes(0)
    args3, temp3 = arg_bytes(3)
    # persistent state sharded 8 ways (params not resident at all)
    assert args3 < args0 / 4, (args0, args3)
    # the gather is per-leaf in compute dtype: temps must stay well under a
    # replicated fp32 master copy per device (= args0 fp32 master+opt)
    assert temp3 < args0, (args0, temp3)


def test_segment_norm_rows_matches_scatter():
    """The row-aligned segment-norm fast path must equal the generic
    scatter implementation on a real flat layout (incl. padding rows)."""
    import jax.numpy as jnp

    from deepspeed_tpu.ops.op_common import (LANES, build_segments,
                                             segment_l2_norms,
                                             segment_l2_norms_rows)

    sizes = [7, LANES, 3 * LANES + 5, 1]
    segs = build_segments(sizes, pad_to=4)
    rng = np.random.default_rng(0)
    flat = np.zeros(segs.shape, np.float32)
    ids = segs.segment_ids()
    # fill only real elements; padding stays zero (the layout contract)
    flat[ids < segs.num_segments] = rng.normal(
        size=int((ids < segs.num_segments).sum())).astype(np.float32)
    flat = jnp.asarray(flat)
    a = segment_l2_norms(flat, jnp.asarray(ids), segs.num_segments)
    b = segment_l2_norms_rows(flat, segs)
    np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=1e-6)
