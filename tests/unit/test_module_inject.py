"""Module injection: HF Flax BERT layer → fused TransformerLayer weight
surgery with output parity, and exact revert (reference strategy:
``tests/unit/test_cuda_forward.py`` asserts the injected kernel matches the
HF layer it replaced)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.layers import TransformerLayer
from deepspeed_tpu.module_inject import (cast_weights, ingest_gpt2_model,
                                         inject_gpt2_layer, replace_module,
                                         replace_transformer_layer,
                                         inject_bert_layer,
                                         replace_gpt2_transformer_layer,
                                         revert_bert_layer,
                                         revert_gpt2_layer)

H, HEADS, INTER = 64, 4, 128


def _hf_layer_and_params(seed=0):
    transformers = pytest.importorskip("transformers")
    from transformers.models.bert.modeling_flax_bert import FlaxBertLayer

    cfg = transformers.BertConfig(
        hidden_size=H, num_attention_heads=HEADS, intermediate_size=INTER,
        vocab_size=128, num_hidden_layers=1, hidden_act="gelu_new",
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    layer = FlaxBertLayer(cfg, dtype=jnp.float32)
    x = jnp.ones((2, 8, H))
    params = layer.init(jax.random.PRNGKey(seed), x, None, None)["params"]
    return layer, params


@pytest.mark.slow
def test_injected_layer_matches_hf():
    hf_layer, hf_params = _hf_layer_and_params()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 16, H)).astype(np.float32))

    hf_out = hf_layer.apply({"params": hf_params}, x, None, None,
                            deterministic=True)[0]

    ours = TransformerLayer(H, HEADS, intermediate_size=INTER,
                            attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0,
                            pre_layer_norm=False)
    our_params = inject_bert_layer(hf_params)
    our_out = ours.apply(our_params, x, deterministic=True)
    np.testing.assert_allclose(np.asarray(our_out), np.asarray(hf_out),
                               rtol=2e-4, atol=2e-4)


def test_revert_roundtrip_exact():
    _, hf_params = _hf_layer_and_params(seed=3)
    ours = inject_bert_layer(hf_params)
    back = revert_bert_layer(ours, hidden_size=H)
    flat1, _ = jax.tree_util.tree_flatten_with_path(hf_params)
    flat2 = dict(jax.tree_util.tree_flatten_with_path(back)[0])
    flat2 = {jax.tree_util.keystr(k): v
             for k, v in jax.tree_util.tree_flatten_with_path(back)[0]}
    for path, leaf in flat1:
        key = jax.tree_util.keystr(path)
        np.testing.assert_array_equal(np.asarray(leaf),
                                      np.asarray(flat2[key]), err_msg=key)


def test_replace_transformer_layer_walks_encoder():
    _, hf_params = _hf_layer_and_params()
    encoder = {"layer": {"0": hf_params, "1": hf_params}}
    ours = replace_transformer_layer(encoder)
    assert set(ours) == {"layer_0", "layer_1"}
    assert ours["layer_0"]["qkv"]["kernel"].shape == (H, 3 * H)
    back = replace_transformer_layer(ours, revert=True, hidden_size=H)
    assert set(back) == {"0", "1"}
    np.testing.assert_array_equal(
        np.asarray(back["0"]["attention"]["self"]["query"]["kernel"]),
        np.asarray(hf_params["attention"]["self"]["query"]["kernel"]))


def test_replace_module_generic_walker():
    tree = {"a": {"hit": {"x": 1}}, "b": {"x": 2}}
    out = replace_module(tree,
                         policy=lambda sub: {"x": sub["x"] * 10},
                         match=lambda path, sub: path.endswith("hit"))
    assert out == {"a": {"hit": {"x": 10}}, "b": {"x": 2}}


# ------------------------------------------------------------- GPT-2
def _gpt2_block_params(seed=0):
    """Synthetic HF FlaxGPT2Block param tree (no transformers needed:
    the layout is fixed — c_attn already holds the fused [h, 3h] qkv)."""
    rng = np.random.default_rng(seed)

    def w(*shape):
        return jnp.asarray(rng.normal(size=shape).astype(np.float32))

    return {
        "ln_1": {"scale": w(H), "bias": w(H)},
        "attn": {"c_attn": {"kernel": w(H, 3 * H), "bias": w(3 * H)},
                 "c_proj": {"kernel": w(H, H), "bias": w(H)}},
        "ln_2": {"scale": w(H), "bias": w(H)},
        "mlp": {"c_fc": {"kernel": w(H, INTER), "bias": w(INTER)},
                "c_proj": {"kernel": w(INTER, H), "bias": w(H)}},
    }


def test_gpt2_revert_roundtrip_exact():
    hf = _gpt2_block_params(seed=3)
    ours = inject_gpt2_layer(hf)
    assert set(ours) == {"qkv", "attn_out", "fc1", "fc2", "ln_attn",
                         "ln_mlp"}
    back = revert_gpt2_layer(ours)
    flat1 = jax.tree_util.tree_flatten_with_path(hf)[0]
    flat2 = {jax.tree_util.keystr(k): v
             for k, v in jax.tree_util.tree_flatten_with_path(back)[0]}
    assert len(flat1) == len(flat2)
    for path, leaf in flat1:
        key = jax.tree_util.keystr(path)
        np.testing.assert_array_equal(np.asarray(leaf),
                                      np.asarray(flat2[key]), err_msg=key)


def test_replace_gpt2_transformer_layer_walks_blocks():
    hf = _gpt2_block_params()
    ours = replace_gpt2_transformer_layer({"h": {"0": hf, "1": hf}})
    assert set(ours) == {"layer_0", "layer_1"}
    assert ours["layer_0"]["qkv"]["kernel"].shape == (H, 3 * H)
    back = replace_gpt2_transformer_layer(ours, revert=True)
    assert set(back) == {"0", "1"}
    np.testing.assert_array_equal(
        np.asarray(back["0"]["attn"]["c_attn"]["kernel"]),
        np.asarray(hf["attn"]["c_attn"]["kernel"]))


def test_ingest_gpt2_model_maps_embeddings_and_blocks():
    rng = np.random.default_rng(1)
    hf = {"transformer": {
        "wte": {"embedding": jnp.asarray(
            rng.normal(size=(128, H)).astype(np.float32))},
        "wpe": {"embedding": jnp.asarray(
            rng.normal(size=(32, H)).astype(np.float32))},
        "h": {"0": _gpt2_block_params(seed=4)},
        "ln_f": {"scale": jnp.ones(H), "bias": jnp.zeros(H)},
    }}
    params = ingest_gpt2_model(hf)
    assert set(params) == {"wte", "wpe", "blocks", "ln_f"}
    assert params["wte"].shape == (128, H)
    assert set(params["blocks"]) == {"layer_0"}
    # the ingested tree is directly consumable by GPT2LMHeadTPU
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadTPU

    model = GPT2LMHeadTPU(GPT2Config(
        vocab_size=128, hidden_size=H, num_layers=1, num_heads=HEADS,
        max_position_embeddings=32, embd_dropout=0.0, attn_dropout=0.0,
        resid_dropout=0.0))
    logits = model.logits(params, jnp.asarray([[1, 2, 3]], jnp.int32))
    assert logits.shape == (1, 3, 128)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_cast_weights_bf16_skips_integer_leaves():
    tree = {"w": jnp.ones((2, 2), jnp.float32),
            "ids": jnp.asarray([1, 2], jnp.int32)}
    out = cast_weights(tree, jnp.bfloat16)
    assert out["w"].dtype == jnp.bfloat16
    assert out["ids"].dtype == jnp.int32
