"""Activation checkpointing: config-driven remat with identical loss and a
measurable memory delta; policy knobs (number_checkpoints, offload policy,
partitioned saves); reference-API parity
(ref ``tests/unit/test_activation_checkpointing.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.models import BertConfig, BertForPreTrainingTPU
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.runtime.activation_checkpointing import checkpointing as ck
from deepspeed_tpu.runtime.activation_checkpointing.config import (
    DeepSpeedActivationCheckpointingConfig)


@pytest.fixture(autouse=True)
def reset_module_config():
    yield
    ck.configure(act_config=DeepSpeedActivationCheckpointingConfig({}))


def _bert_engine(cpu_devices, ds_extra=None, **bert_kw):
    cfg = BertConfig(vocab_size=128, hidden_size=32, num_hidden_layers=4,
                     num_attention_heads=4, intermediate_size=64,
                     max_position_embeddings=64,
                     hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
                     **bert_kw)
    model = BertForPreTrainingTPU(cfg, compute_dtype=None)
    config = {"train_batch_size": 8, "steps_per_print": 10 ** 9,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
    config.update(ds_extra or {})
    mesh = make_mesh({"data": 1}, devices=cpu_devices[:1])
    engine, *_ = deepspeed.initialize(model=model, config=config, mesh=mesh)
    return engine, model


def _batch(bs=8, seq=32, vocab=128, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab, size=(bs, seq)).astype(np.int32)
    return {"input_ids": ids,
            "attention_mask": np.ones((bs, seq), np.int32),
            "token_type_ids": np.zeros((bs, seq), np.int32),
            "masked_lm_labels": np.where(rng.random((bs, seq)) < 0.15, ids,
                                         -100).astype(np.int32),
            "next_sentence_labels": rng.integers(0, 2, (bs,)).astype(np.int32)}


def test_config_enables_remat_with_identical_loss(cpu_devices):
    """activation_checkpointing config turns remat on; losses match the
    non-remat run exactly and the compiled step's temp memory shrinks."""
    e_plain, m_plain = _bert_engine(cpu_devices)
    assert m_plain.config.remat is False
    e_ck, m_ck = _bert_engine(cpu_devices,
                              ds_extra={"activation_checkpointing": {}})
    assert m_ck.config.remat is True, "config did not enable remat"

    b = _batch()
    l_plain = [float(np.asarray(e_plain.train_batch(iter([b])))) for _ in range(3)]
    l_ck = [float(np.asarray(e_ck.train_batch(iter([b])))) for _ in range(3)]
    np.testing.assert_allclose(l_ck, l_plain, rtol=1e-6)


def test_config_drives_remat_program_structure():
    """The remat flag materially changes the traced program: one remat
    equation per layer, gone when disabled.  (The capacity win — e.g.
    BERT-large batch 256 OOMs on a 16 GB chip without remat and trains
    with it — only shows at scale, so CI asserts program structure; temp
    memory on toy sizes is fused away by XLA either way.)"""
    cfg = BertConfig(vocab_size=256, hidden_size=64, num_hidden_layers=6,
                     num_attention_heads=4, intermediate_size=256,
                     max_position_embeddings=128,
                     hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    model = BertForPreTrainingTPU(cfg, compute_dtype=None)
    params = model.init(jax.random.PRNGKey(0))
    b = jax.tree_util.tree_map(jnp.asarray, _batch(bs=4, seq=32, vocab=256))

    def remat_count():
        jx = jax.make_jaxpr(lambda p: jax.grad(
            lambda q: model.apply(q, b, rng=None, train=True))(p))(params)
        return str(jx).count("remat2")

    cfg.remat = False
    assert remat_count() == 0
    cfg.remat = True
    assert remat_count() >= cfg.num_hidden_layers


def test_remat_visible_in_jaxpr(cpu_devices):
    e_ck, _ = _bert_engine(cpu_devices,
                           ds_extra={"activation_checkpointing": {}})
    b = _batch()
    jx = jax.make_jaxpr(
        lambda p, bb: e_ck._loss_fn(p, bb, rng=None, train=True))(
        e_ck._module_params, jax.tree_util.tree_map(jnp.asarray, b))
    text = str(jx)
    assert "remat" in text, "no remat primitive in traced program"


def test_number_checkpoints_spacing():
    cfg = DeepSpeedActivationCheckpointingConfig(
        {"activation_checkpointing": {"number_checkpoints": 2}})
    flags = [ck.should_checkpoint_layer(i, 8, cfg) for i in range(8)]
    assert sum(flags) == 2 and flags[0] and flags[4], flags
    cfg_all = DeepSpeedActivationCheckpointingConfig({})
    assert all(ck.should_checkpoint_layer(i, 8, cfg_all) for i in range(8))


def test_offload_policy_selection():
    cfg = DeepSpeedActivationCheckpointingConfig(
        {"activation_checkpointing": {"cpu_checkpointing": True}})
    assert ck.make_remat_policy(cfg) is not None
    cfg2 = DeepSpeedActivationCheckpointingConfig({})
    assert ck.make_remat_policy(cfg2) is None


def test_reference_api_checkpoint():
    """deepspeed.checkpointing.checkpoint(fn, *args) works and matches."""
    w = jnp.asarray(np.random.default_rng(0).normal(size=(8, 8)),
                    jnp.float32)

    def layer(x):
        return jnp.tanh(x @ w)

    x = jnp.ones((4, 8))
    out = deepspeed.checkpointing.checkpoint(layer, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(layer(x)),
                               rtol=1e-6)
    g1 = jax.grad(lambda x: deepspeed.checkpointing.checkpoint(layer, x).sum())(x)
    g2 = jax.grad(lambda x: layer(x).sum())(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-6)


def test_partition_activations_constraint(cpu_devices):
    """partition_activations shards saved residuals over the model axis —
    verified by running a TP mesh with the constraint active (it must not
    change numerics)."""
    from deepspeed_tpu.parallel.mesh import set_current_mesh

    mesh = make_mesh({"data": 2, "model": 2}, devices=cpu_devices[:4])
    cfg = DeepSpeedActivationCheckpointingConfig(
        {"activation_checkpointing": {"partition_activations": True}})
    ck.configure(act_config=cfg)
    w = jnp.asarray(np.random.default_rng(0).normal(size=(8, 8)), jnp.float32)

    def layer(x):
        return jnp.tanh(x @ w)

    x = jnp.ones((4, 8, 8))
    with mesh:
        set_current_mesh(mesh)
        wrapped = ck.checkpoint_wrapper(layer, cfg)
        out = jax.jit(jax.grad(lambda x: wrapped(x).sum()))(x)
    ref = jax.grad(lambda x: layer(x).sum())(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)
