"""Partitioning + pytree utility tests (modeled on reference
``tests/unit/test_partition_balanced.py`` and flatten-op usage)."""

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime import utils as ds_utils


def check_partition(weights, num_parts, target_diff):
    result = ds_utils.partition_balanced(weights=weights, num_parts=num_parts)
    parts_sum = []
    for b, e in zip(result[:-1], result[1:]):
        parts_sum.append(sum(weights[b:e]))
    assert max(parts_sum) - min(parts_sum) == target_diff, (
        f"ds_utils.partition_balanced(weights={weights}, num_parts={num_parts}) "
        f"return {result}")


def test_partition_balanced():
    check_partition([1, 2, 1], 4, target_diff=2)
    check_partition([1, 1, 1, 1], 4, target_diff=0)
    check_partition([1, 1, 1, 1, 1], 4, target_diff=1)
    check_partition([1, 1, 1, 1, 0, 1], 4, target_diff=1)


def test_partition_uniform():
    parts = ds_utils.partition_uniform(10, 2)
    assert parts == [0, 5, 10]
    parts = ds_utils.partition_uniform(3, 5)
    assert parts[-1] == 3
    assert len(parts) == 6


def test_flatten_roundtrip():
    tree = {"a": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
            "b": [jnp.ones((4,), jnp.float32), jnp.zeros((2, 2), jnp.float32)]}
    flat = ds_utils.flatten_tree(tree)
    assert flat.shape == (14,)
    back = ds_utils.unflatten_like(flat, tree)
    np.testing.assert_array_equal(np.asarray(back["a"]), np.asarray(tree["a"]))
    np.testing.assert_array_equal(np.asarray(back["b"][1]), np.asarray(tree["b"][1]))


def test_global_norm_and_clip():
    tree = {"w": jnp.array([3.0, 4.0])}
    norm = ds_utils.global_norm(tree)
    assert abs(float(norm) - 5.0) < 1e-6
    clipped, _ = ds_utils.clip_grads_by_global_norm(tree, 1.0)
    cn = ds_utils.global_norm(clipped)
    assert float(cn) <= 1.0 + 1e-5


def test_has_overflow():
    ok = {"w": jnp.ones((3,))}
    bad = {"w": jnp.array([1.0, float("inf")])}
    assert not bool(ds_utils.has_overflow(ok))
    assert bool(ds_utils.has_overflow(bad))
