"""Partitioning + pytree utility tests (modeled on reference
``tests/unit/test_partition_balanced.py`` and flatten-op usage)."""

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime import utils as ds_utils


def check_partition(weights, num_parts, target_diff):
    result = ds_utils.partition_balanced(weights=weights, num_parts=num_parts)
    parts_sum = []
    for b, e in zip(result[:-1], result[1:]):
        parts_sum.append(sum(weights[b:e]))
    assert max(parts_sum) - min(parts_sum) == target_diff, (
        f"ds_utils.partition_balanced(weights={weights}, num_parts={num_parts}) "
        f"return {result}")


def test_partition_balanced():
    check_partition([1, 2, 1], 4, target_diff=2)
    check_partition([1, 1, 1, 1], 4, target_diff=0)
    check_partition([1, 1, 1, 1, 1], 4, target_diff=1)
    check_partition([1, 1, 1, 1, 0, 1], 4, target_diff=1)


def test_partition_uniform():
    parts = ds_utils.partition_uniform(10, 2)
    assert parts == [0, 5, 10]
    parts = ds_utils.partition_uniform(3, 5)
    assert parts[-1] == 3
    assert len(parts) == 6


def test_flatten_roundtrip():
    tree = {"a": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
            "b": [jnp.ones((4,), jnp.float32), jnp.zeros((2, 2), jnp.float32)]}
    flat = ds_utils.flatten_tree(tree)
    assert flat.shape == (14,)
    back = ds_utils.unflatten_like(flat, tree)
    np.testing.assert_array_equal(np.asarray(back["a"]), np.asarray(tree["a"]))
    np.testing.assert_array_equal(np.asarray(back["b"][1]), np.asarray(tree["b"][1]))


def test_global_norm_and_clip():
    tree = {"w": jnp.array([3.0, 4.0])}
    norm = ds_utils.global_norm(tree)
    assert abs(float(norm) - 5.0) < 1e-6
    clipped, _ = ds_utils.clip_grads_by_global_norm(tree, 1.0)
    cn = ds_utils.global_norm(clipped)
    assert float(cn) <= 1.0 + 1e-5


def test_has_overflow():
    ok = {"w": jnp.ones((3,))}
    bad = {"w": jnp.array([1.0, float("inf")])}
    assert not bool(ds_utils.has_overflow(ok))
    assert bool(ds_utils.has_overflow(bad))


def test_random_keep_mask_statistics():
    """Byte-mask dropout: keep rate matches the 1/256-quantized request and
    the scale makes it exactly unbiased (E[keep * scale] == 1)."""
    import jax

    from deepspeed_tpu.ops.op_common import random_keep

    rng = jax.random.PRNGKey(7)
    for rate in (0.1, 0.5, 0.015625):
        keep, scale = random_keep(rng, (1 << 16,), rate)
        thresh = round(rate * 256.0)
        expect_keep = (256 - thresh) / 256.0
        assert abs(scale * expect_keep - 1.0) < 1e-9
        got = float(jnp.mean(keep.astype(jnp.float32)))
        assert abs(got - expect_keep) < 0.01, (rate, got, expect_keep)
    # degenerate rates clamp instead of crashing
    for rate in (1e-4, 0.9999):
        keep, scale = random_keep(rng, (128,), rate)
        assert np.isfinite(scale)


def test_dropout_passthrough_and_scaling():
    import jax

    from deepspeed_tpu.models.layers import dropout

    rng = jax.random.PRNGKey(0)
    x = jnp.ones((4096,), jnp.float32)
    assert dropout(rng, x, 0.5, deterministic=True) is x
    assert dropout(rng, x, 0.0, deterministic=False) is x
    assert dropout(None, x, 0.5, deterministic=False) is x
    y = dropout(rng, x, 0.5, deterministic=False)
    kept = np.asarray(y) > 0
    # inverted dropout: survivors scaled by 1/keep_prob (=2.0 at rate 0.5)
    assert np.allclose(np.asarray(y)[kept], 2.0)
    assert abs(kept.mean() - 0.5) < 0.05


def test_engine_prng_impl_config():
    """prng_impl=auto resolves per-backend; explicit values are honored."""
    import jax

    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import BertConfig, BertForPreTrainingTPU
    from deepspeed_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 1}, devices=jax.devices("cpu")[:1])
    config = {"train_batch_size": 2, "steps_per_print": 10 ** 9,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
              "prng_impl": "rbg"}
    model = BertForPreTrainingTPU(BertConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=2, max_position_embeddings=32))
    engine, *_ = deepspeed.initialize(model=model, config=config, mesh=mesh)
    assert "rbg" in str(jax.random.key_impl(engine._rng))
    batch = {"input_ids": np.zeros((2, 16), np.int32),
             "attention_mask": np.ones((2, 16), np.int32),
             "masked_lm_labels": np.zeros((2, 16), np.int32)}
    loss = engine.train_batch(iter([batch]))
    assert np.isfinite(float(jax.device_get(loss)))
