"""Serving subsystem tests: paged KV cache, continuous-batching
scheduler, and the InferenceEngine's acceptance guarantees —

- greedy decode through the cache is TOKEN-IDENTICAL to the naive
  one-request-at-a-time full-forward reference over staggered requests;
- the KV-cache donation materializes as ``input_output_alias`` on the
  decode program (``verify_programs()`` clean);
- the whole serve compiles at most ``len(prefill_buckets) + 1``
  programs (the bounded-retrace contract);
- serving telemetry + ledgers add ZERO host syncs over the serve loop's
  own next-token fetches.
"""

import json
import os

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference import (NULL_BLOCK, BlockAllocator,
                                     ContinuousBatchScheduler,
                                     DeepSpeedInferenceConfig,
                                     InferenceEngine, Request,
                                     reference_generate)
from deepspeed_tpu.inference.scheduler import REASON_EOS, REASON_LENGTH
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadTPU

VOCAB = 256


def tiny_model():
    cfg = GPT2Config(vocab_size=VOCAB, hidden_size=64, num_layers=2,
                     num_heads=4, max_position_embeddings=64,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    return GPT2LMHeadTPU(cfg)


def serve_config(**inference_overrides):
    inf = {"kv_block_size": 8, "kv_blocks": 64, "max_batch_slots": 4,
           "max_seq_len": 64, "prefill_buckets": [8, 16, 32],
           "token_budget": 256, "max_new_tokens": 8}
    inf.update(inference_overrides)
    return {"inference": inf, "steps_per_print": 4}


def seeded_prompts(n, seed=42, lo=3, hi=30):
    rng = np.random.RandomState(seed)
    return [list(int(t) for t in rng.randint(0, VOCAB,
                                             size=rng.randint(lo, hi)))
            for _ in range(n)]


@pytest.fixture(scope="module")
def model_and_params():
    model = tiny_model()
    return model, model.init(jax.random.PRNGKey(0))


# ---------------------------------------------------------------- config
class TestInferenceConfig:
    def test_defaults(self):
        icfg = DeepSpeedInferenceConfig({})
        assert icfg.kv_block_size == 16
        assert icfg.max_seq_len % icfg.kv_block_size == 0
        assert icfg.prefill_buckets == tuple(sorted(icfg.prefill_buckets))
        assert icfg.max_blocks_per_seq \
            == icfg.max_seq_len // icfg.kv_block_size

    def test_bucket_for(self):
        icfg = DeepSpeedInferenceConfig(serve_config())
        assert icfg.bucket_for(1) == 8
        assert icfg.bucket_for(8) == 8
        assert icfg.bucket_for(9) == 16
        assert icfg.bucket_for(32) == 32
        with pytest.raises(ValueError):
            icfg.bucket_for(33)

    @pytest.mark.parametrize("bad", [
        {"max_seq_len": 60},               # not a multiple of block size
        {"prefill_buckets": [12]},         # bucket not block-aligned
        {"prefill_buckets": [128]},        # bucket beyond max_seq_len
        {"kv_blocks": 1},                  # only the null block
        {"weights_dtype": "float16"},      # unsupported serve dtype
    ])
    def test_invalid_rejected(self, bad):
        with pytest.raises((AssertionError, ValueError)):
            DeepSpeedInferenceConfig(serve_config(**bad))


# ------------------------------------------------------------- kv blocks
class TestBlockAllocator:
    def test_never_hands_out_null_block(self):
        alloc = BlockAllocator(8)
        got = alloc.allocate(7)
        assert got is not None and NULL_BLOCK not in got
        assert alloc.free_blocks == 0

    def test_no_partial_grant(self):
        alloc = BlockAllocator(4)
        assert alloc.allocate(5) is None
        assert alloc.free_blocks == 3  # nothing leaked by the refusal

    def test_release_recycles(self):
        alloc = BlockAllocator(4)
        got = alloc.allocate(3)
        alloc.release(got)
        assert alloc.free_blocks == 3
        assert alloc.allocate(3) is not None


# ------------------------------------------------------------- scheduler
class TestScheduler:
    def make(self, **overrides):
        icfg = DeepSpeedInferenceConfig(serve_config(**overrides))
        alloc = BlockAllocator(icfg.kv_blocks)
        return ContinuousBatchScheduler(icfg, alloc), alloc

    def test_submit_rejects_overflow(self):
        sched, _ = self.make()
        with pytest.raises(ValueError):
            # worst case exceeds max_seq_len
            sched.submit(Request("r", list(range(32)), 64))
        with pytest.raises(ValueError):
            # prompt exceeds the largest prefill bucket
            sched.submit(Request("r", list(range(40)), 2))

    def test_submit_rejects_budget_overflow_at_submit_time(self):
        """A request whose worst case exceeds the TOKEN BUDGET (not
        just max_seq_len) can never be admitted: FIFO admission would
        park it at the queue head and starve everything behind it
        forever.  Loud ValueError at submit, not a silent hang."""
        sched, _ = self.make(token_budget=32)
        with pytest.raises(ValueError, match="token_budget"):
            sched.submit(Request("r", [1] * 16, 32))   # worst 48 > 32
        assert sched.queue_depth == 0                  # nothing parked
        # exactly at the budget: queues and admits normally
        sched.submit(Request("ok", [1] * 16, 16))      # worst 32 == 32
        ok = sched.try_admit()
        assert ok is not None and ok.request_id == "ok"

    def test_fifo_admission_and_token_budget(self):
        sched, _ = self.make(token_budget=24)
        sched.submit(Request("a", [1] * 10, 8))   # worst case 18
        sched.submit(Request("b", [1] * 10, 8))   # would push to 36 > 24
        a = sched.try_admit()
        assert a is not None and a.request_id == "a"
        assert sched.try_admit() is None           # budget defers b
        assert sched.queue_depth == 1
        sched.finish(a, REASON_LENGTH)             # debt released...
        b = sched.try_admit()
        assert b is not None and b.request_id == "b"  # ...b admits

    def test_slot_recycling_mid_batch(self):
        sched, alloc = self.make()
        reqs = [Request(f"r{i}", [1] * 8, 4) for i in range(4)]
        for r in reqs:
            sched.submit(r)
        admitted = [sched.try_admit() for _ in range(4)]
        assert all(admitted) and sched.active_count == 4
        free_before = alloc.free_blocks
        sched.finish(admitted[1], REASON_EOS)      # middle slot finishes
        assert sched.active_count == 3
        assert alloc.free_blocks > free_before     # blocks came back
        assert sched.slots[admitted[1].slot] is None
        late = Request("late", [1] * 8, 4)
        sched.submit(late)
        again = sched.try_admit()                  # recycled slot reused
        assert again is late and again.slot == admitted[1].slot

    def test_block_table_row_padded_with_null(self):
        sched, _ = self.make()
        sched.submit(Request("r", [1] * 8, 4))
        r = sched.try_admit()
        row = sched.block_table_row(r)
        assert len(row) == sched.icfg.max_blocks_per_seq
        assert row[:len(r.blocks)] == r.blocks
        assert all(b == NULL_BLOCK for b in row[len(r.blocks):])

    def test_allocation_covers_worst_case(self):
        # bucket 16 but prompt+max_new = 10+20=30 -> 4 blocks of 8
        sched, _ = self.make()
        sched.submit(Request("r", [1] * 10, 20))
        r = sched.try_admit()
        assert len(r.blocks) == 4


# ---------------------------------------------------------------- engine
class TestInferenceEngine:
    def test_continuous_batching_token_parity(self, model_and_params):
        """THE acceptance test: 8 staggered seeded requests through the
        continuous batch are token-identical to the naive
        one-request-at-a-time full-forward reference."""
        model, params = model_and_params
        engine = InferenceEngine(model, params, config=serve_config())
        prompts = seeded_prompts(8)
        # stagger: half up front, the rest submitted mid-serve so they
        # join a batch whose siblings are mid-generation
        for i, p in enumerate(prompts[:4]):
            engine.submit(p, max_new_tokens=8, request_id=f"r{i}")
        for _ in range(3):
            engine.step()
        for i, p in enumerate(prompts[4:], start=4):
            engine.submit(p, max_new_tokens=8, request_id=f"r{i}")
        results = engine.run()
        for i, p in enumerate(prompts):
            ref = reference_generate(model, params, p, 8)
            got = results[f"r{i}"]["tokens"]
            assert got == ref, (f"request r{i} (prompt len {len(p)}): "
                                f"cached decode {got} != reference {ref}")
            assert results[f"r{i}"]["finish_reason"] == REASON_LENGTH
        engine.close()

    def test_eos_stops_generation(self, model_and_params):
        model, params = model_and_params
        prompt = seeded_prompts(1, seed=7)[0]
        ref = reference_generate(model, params, prompt, 8)
        eos = ref[2]  # force an EOS hit mid-generation
        engine = InferenceEngine(model, params,
                                 config=serve_config(eos_token_id=eos))
        rid = engine.submit(prompt, max_new_tokens=8)
        out = engine.run()[rid]
        assert out["tokens"] == reference_generate(model, params, prompt,
                                                   8, eos_token_id=eos)
        assert out["finish_reason"] == REASON_EOS
        assert len(out["tokens"]) < 8
        engine.close()

    def test_kv_cache_donation_materializes(self, model_and_params,
                                            tmp_path):
        """DSP601/DSP603: the decode program's donated cache args must
        materialize as input_output_alias entries — a silently-copied
        KV cache is the bug this gate exists for."""
        model, params = model_and_params
        config = serve_config()
        config["telemetry"] = {"enabled": True, "run_dir": str(tmp_path)}
        engine = InferenceEngine(model, params, config=config)
        for i, p in enumerate(seeded_prompts(4, seed=3)):
            engine.submit(p, max_new_tokens=4, request_id=f"r{i}")
        engine.run()
        report = engine.verify_programs()
        assert report is not None
        assert report["programs_checked"] >= 2  # decode + >=1 prefill
        assert report["errors"] == 0, report["diagnostics"]
        assert report["violations"] == 0, report["diagnostics"]
        # and explicitly: the alias is in the decode HLO header
        compiled = engine.memory_ledger.compiled_programs()["serve_decode"]
        assert "input_output_alias" in compiled.as_text().split("\n", 1)[0]
        # the dumper landed offline-verifiable sidecars for every program
        dumped = sorted(os.listdir(tmp_path / "programs"))
        assert "serve_decode.hlo" in dumped
        assert any(f.startswith("serve_prefill_") and f.endswith(".json")
                   for f in dumped)
        engine.close()

    def test_bounded_retraces_compile_counter(self, model_and_params):
        """The whole serve compiles at most len(prefill_buckets) + 1
        programs, however many requests and lengths flow through — and a
        SECOND wave of new lengths adds zero."""
        model, params = model_and_params
        config = serve_config()
        config["profiling"] = {"memory_ledger": True}
        engine = InferenceEngine(model, params, config=config)
        limit = len(engine.inference_config.prefill_buckets) + 1
        # first wave deliberately covers every declared bucket
        lens = [5, 12, 30, 7, 14, 25]
        rng = np.random.RandomState(11)
        for i, n in enumerate(lens):
            prompt = [int(t) for t in rng.randint(0, VOCAB, size=n)]
            engine.submit(prompt, max_new_tokens=4, request_id=f"a{i}")
        engine.run()
        first_wave = set(engine.memory_ledger.entries())
        assert 0 < len(first_wave) <= limit, first_wave
        for i, p in enumerate(seeded_prompts(6, seed=12, lo=3, hi=31)):
            engine.submit(p, max_new_tokens=4, request_id=f"b{i}")
        engine.run()
        assert set(engine.memory_ledger.entries()) == first_wave
        engine.close()

    def test_zero_added_host_syncs(self, model_and_params, tmp_path,
                                   monkeypatch):
        """Serving observability (telemetry + both ledgers + program
        dumper, print cadence every iteration) rides the serve loop's
        own next-token fetches: the jax.device_get count is IDENTICAL
        with it all on and all off."""
        model, params = model_and_params
        prompts = seeded_prompts(4, seed=5)

        def count_gets(config):
            engine = InferenceEngine(model, params, config=config)
            counts = {"n": 0}
            real_get = jax.device_get

            def counting_get(x):
                counts["n"] += 1
                return real_get(x)

            monkeypatch.setattr(jax, "device_get", counting_get)
            try:
                for i, p in enumerate(prompts):
                    engine.submit(p, max_new_tokens=4,
                                  request_id=f"r{i}")
                engine.run()
            finally:
                monkeypatch.setattr(jax, "device_get", real_get)
            engine.close()
            return counts["n"]

        base_cfg = serve_config()
        base_cfg["steps_per_print"] = 1
        base = count_gets(base_cfg)
        # the FULL observability plane armed: lifecycle tracing +
        # occupancy/goodput windows ride automatically with telemetry,
        # and the slo block arms the per-token conformance legs — all
        # of it host arithmetic over values the loop already fetched
        tel_cfg = serve_config(slo={"ttft_ms": 100, "per_token_ms": 50})
        tel_cfg["steps_per_print"] = 1
        tel_cfg["telemetry"] = {"enabled": True,
                                "run_dir": str(tmp_path / "t")}
        tel = count_gets(tel_cfg)
        assert base > 0
        assert tel == base, (f"serving observability added host syncs: "
                             f"{tel} device_get calls vs {base} baseline")

    def test_serving_events_and_receipt(self, model_and_params, tmp_path):
        model, params = model_and_params
        config = serve_config()
        config["steps_per_print"] = 2
        config["telemetry"] = {"enabled": True, "run_dir": str(tmp_path)}
        engine = InferenceEngine(model, params, config=config)
        for i, p in enumerate(seeded_prompts(4, seed=9)):
            engine.submit(p, max_new_tokens=6, request_id=f"r{i}")
        engine.run()
        receipt = engine.serving_receipt()
        assert receipt["requests"] == 4
        assert receipt["generated_tokens"] == 4 * 6
        assert receipt["per_token_p50_seconds"] > 0
        assert receipt["per_token_p99_seconds"] \
            >= receipt["per_token_p50_seconds"]
        assert receipt["ttft_p50_seconds"] > 0
        assert receipt["tokens_per_second_per_chip"] > 0
        # the decode comm/attribution receipts resolve to serve_decode
        assert engine.comm_receipt()["program"] == "serve_decode"
        attribution = engine.attribution_receipt()
        assert attribution["program"] == "serve_decode"
        assert attribution["measured_step_seconds"] > 0
        assert set(attribution["phases"]) == {
            "compute", "exposed_collective", "host_stream", "driver",
            "unexplained"}
        engine.close()
        events = [json.loads(line) for line in
                  open(tmp_path / "events-rank0.jsonl")]
        kinds = {e["data"].get("kind") for e in events
                 if e["type"] == "serving"}
        assert {"admit", "finish", "queue"} <= kinds
        assert any(e["type"] == "attribution" for e in events)
        # the offline doctor reconstructs the SAME phase table from the
        # run dir alone: serve_decode priced as the step program, with a
        # measured side from the comm/latency snapshots
        from deepspeed_tpu.profiling.doctor import doctor_run_dir

        verdict = doctor_run_dir(str(tmp_path))
        assert verdict["budget"]["program"] == "serve_decode"
        assert verdict["ranks"], "doctor found no measured latency"
        rank0 = verdict["ranks"]["rank0"]
        assert rank0["measured_step_seconds"] > 0
        assert set(rank0["phases"]) == {
            "compute", "exposed_collective", "host_stream", "driver",
            "unexplained"}

    def test_bf16_weight_ingestion(self, model_and_params):
        import jax.numpy as jnp

        model, params = model_and_params
        engine = InferenceEngine(
            model, params, config=serve_config(weights_dtype="bfloat16"))
        leaves = jax.tree_util.tree_leaves(engine.params)
        assert all(l.dtype == jnp.bfloat16 for l in leaves)
        assert engine._k_cache.dtype == jnp.bfloat16
        rid = engine.submit(seeded_prompts(1, seed=2)[0],
                            max_new_tokens=4)
        out = engine.run()[rid]
        assert len(out["tokens"]) == 4
        assert all(0 <= t < VOCAB for t in out["tokens"])
        engine.close()

    def test_strict_config_rejects_unknown_keys(self, model_and_params):
        model, params = model_and_params
        config = serve_config()
        config["inference"]["kv_block_sise"] = 8  # typo
        config["strict_config"] = True
        with pytest.raises(ValueError, match="kv_block_sise"):
            InferenceEngine(model, params, config=config)
