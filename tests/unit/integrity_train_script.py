"""Replicated-fleet child script for the integrity chaos e2e tests.

Driven by ``deepspeed_tpu.launcher.launch`` with the elastic supervisor
armed.  Every process is one fleet rank holding a FULL replica: a dp=1
mesh on one virtual CPU device, consuming the complete global batch
stream — so all ranks' (master, optimizer) states are bit-identical
step for step without cross-process collectives, which is exactly the
pure-dp invariant the fingerprint consensus votes on.  The integrity
plane is armed (telemetry run dir = the launcher's shared
``DS_TELEMETRY_DIR``); rank 0 commits a synchronous checkpoint per
step; every life ``auto_resume``s.

Chaos (first life only, seeded, one target rank):

- ``DS_CHAOS_BITFLIP_STEP`` — the target rank's master state takes a
  single seeded bitflip right before that optimizer step: silent SDC.
  The consensus names the rank, every healthy rank exits 87, the
  supervisor evicts the slot and resizes; respawned lives roll back to
  the last committed checkpoint and re-train to completion.
- ``DS_CHAOS_HANG_STEP`` — the target rank wedges in the batch fetch
  before entering that step (never beats it).  The healthy majority's
  hang quorum convicts it after ``DS_INTEGRITY_PEER_TIMEOUT`` seconds
  and exits 87 — ONE eviction resize instead of N local watchdog
  timeouts (the local watchdog is armed far looser to prove which
  mechanism recovered).

argv: <ckpt_dir> <out_dir>   (telemetry dir rides DS_TELEMETRY_DIR)
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import deepspeed_tpu as deepspeed  # noqa: E402
from deepspeed_tpu.parallel import make_mesh  # noqa: E402
from deepspeed_tpu.resilience.chaos import ChaosMonkey  # noqa: E402
from deepspeed_tpu.resilience.constants import (  # noqa: E402
    FleetIntegrityError, TrainingDivergedError)
from deepspeed_tpu.runtime.dataloader import RepeatingLoader  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from simple_model import SimpleModel, random_dataset  # noqa: E402

HIDDEN = 16
GLOBAL_BATCH = 16
TOTAL_STEPS = 10
DATASET_SAMPLES = 80


def _env_int(name, default=0):
    return int(os.environ.get(name, "") or default)


def _env_float(name, default=0.0):
    return float(os.environ.get(name, "") or default)


def main():
    ckpt_dir, out_dir = sys.argv[1], sys.argv[2]
    rank = _env_int("DS_PROCESS_ID", 0)
    # full-replica fleet: every rank computes the complete global batch
    # independently (bit-identical states without cross-process
    # collectives), so the jax multi-controller rendezvous must NOT
    # engage — the DS_PROCESS_ID/DS_NUM_PROCESSES fleet identity still
    # reaches the integrity plane
    os.environ.pop("DS_COORDINATOR", None)
    mesh = make_mesh({"data": 1}, devices=jax.devices("cpu")[:1])

    config = {
        "train_batch_size": GLOBAL_BATCH,
        "steps_per_print": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "resilience": {
            "enabled": True,
            "checkpoint_dir": ckpt_dir,
            "integrity": True,
            "integrity_peer_timeout_secs":
                _env_float("DS_INTEGRITY_PEER_TIMEOUT"),
            "hang_timeout_secs": _env_float("DS_WATCHDOG_SECS"),
        },
        "telemetry": {"enabled": True},
    }
    dataset = random_dataset(DATASET_SAMPLES, HIDDEN, seed=7)
    engine, _, loader, _ = deepspeed.initialize(
        model=SimpleModel(HIDDEN, nlayers=1), config=config, mesh=mesh,
        training_data=dataset, auto_resume=True)
    fresh = engine.global_steps == 0

    target = _env_int("DS_CHAOS_TARGET_RANK", -1)
    flip_step = _env_int("DS_CHAOS_BITFLIP_STEP")
    hang_step = _env_int("DS_CHAOS_HANG_STEP")
    step_sleep = _env_float("DS_STEP_SLEEP_SECS")
    monkey = ChaosMonkey(seed=_env_int("DS_CHAOS_SEED"))
    acc = engine.gradient_accumulation_steps()
    # pull index of the FIRST micro-batch of optimizer step k: the fault
    # lands before step k runs, on the first life only
    it = monkey.wrap_iter(
        iter(RepeatingLoader(loader)),
        bitflip_steps=[(flip_step - 1) * acc] if (flip_step and fresh)
        else [],
        bitflip_engine=engine,
        hang_steps=[(hang_step - 1) * acc] if (hang_step and fresh)
        else [],
        hang_secs=600.0,
        rank=rank, target_rank=target)

    os.makedirs(out_dir, exist_ok=True)
    life = "fresh" if fresh else f"resumed@{engine.global_steps}"
    log_path = os.path.join(out_dir, f"steps-rank{rank}-{life}.jsonl")
    loss = None          # a resumed-complete life never enters the loop
    try:
        with open(log_path, "a") as f:
            while engine.global_steps < TOTAL_STEPS:
                loss = engine.train_batch(it)
                if rank == 0:
                    engine.save_checkpoint(ckpt_dir, sync=True)
                f.write(json.dumps({
                    "step": engine.global_steps,
                    "loss": float(jax.device_get(loss)),
                    "samples": engine.global_samples}) + "\n")
                f.flush()
                if step_sleep:
                    time.sleep(step_sleep)
    except (FleetIntegrityError, TrainingDivergedError) as e:
        # the launcher's supervisor owns recovery: 87 = evict + resize,
        # 86 = poison (never respawned)
        sys.exit(e.exit_code)

    if rank == 0:
        if loss is not None:
            final_loss = float(jax.device_get(loss))
        else:
            # this life resumed already-complete (the previous life
            # died between its final commit and final.json): recover
            # the last trained loss from the step logs
            recs = []
            for name in os.listdir(out_dir):
                if name.startswith(f"steps-rank{rank}-"):
                    with open(os.path.join(out_dir, name)) as g:
                        recs += [json.loads(line) for line in g]
            final_loss = max(recs, key=lambda r: r["step"])["loss"]
        with open(os.path.join(out_dir, "final.json"), "w") as f:
            json.dump({"final_loss": final_loss,
                       "steps": engine.global_steps,
                       "samples": engine.global_samples}, f)
    engine.close()


if __name__ == "__main__":
    main()
