"""Model-level convergence (the reference's ``tests/model`` strategy,
scaled to CI: real model, real optimizer, loss driven close to zero by
overfitting — much stronger than 'loss decreased')."""

import jax
import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.models import GPT2Config, GPT2LMHeadTPU
from deepspeed_tpu.parallel import make_mesh


@pytest.mark.parametrize("zero_stage", [0, 2])
@pytest.mark.slow
def test_gpt2_overfits(zero_stage, cpu_devices):
    mesh = make_mesh({"data": 4}, devices=cpu_devices[:4])
    model = GPT2LMHeadTPU(GPT2Config(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        max_position_embeddings=32, embd_dropout=0.0, attn_dropout=0.0,
        resid_dropout=0.0))
    config = {
        "train_batch_size": 8,
        "steps_per_print": 10 ** 9,
        "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
        "zero_optimization": {"stage": zero_stage},
        "gradient_clipping": 1.0,
    }
    engine, *_ = deepspeed.initialize(model=model, config=config, mesh=mesh)

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 128, size=(8, 32)).astype(np.int32)}
    losses = [float(np.asarray(jax.device_get(
        engine.train_batch(iter([batch]))))) for _ in range(60)]
    assert losses[0] > 3.0, f"sanity: initial loss {losses[0]}"
    assert losses[-1] < 0.3, (
        f"GPT-2 failed to overfit one batch: {losses[0]:.3f} -> "
        f"{losses[-1]:.3f} (stage {zero_stage})")
