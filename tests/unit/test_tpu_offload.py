"""Streamed ZeRO-Offload on the real chip (``DS_TEST_TPU=1 pytest -m tpu``).

The in-jit offload path (chunk-streamed update, row-grouped host state,
DUS write-back) is TPU-only — memory-kind placement inside jit does not
exist on the CPU backend, so the CI suite can exercise only the eager
offload mode.  This module is the compiled-path gate: numerics parity of
the streamed update against device-resident training, with grouping and
chunking both forced on at toy scale.
"""

import numpy as np
import pytest

import jax

pytestmark = pytest.mark.tpu

HIDDEN = 256
LAYERS = 2


def _losses(cpu_offload, steps=4, chunk_mb=1, offload_gradients=False,
            clip=0.0, uniform="auto", state_dtype=None):
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import GPT2Config, GPT2LMHeadTPU
    from deepspeed_tpu.parallel import make_mesh

    cfg = GPT2Config(hidden_size=HIDDEN, num_layers=LAYERS, num_heads=4,
                     vocab_size=1024, max_position_embeddings=128,
                     embd_dropout=0.0, attn_dropout=0.0, resid_dropout=0.0)
    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    model = GPT2LMHeadTPU(cfg)
    zero = {"stage": 2, "cpu_offload": cpu_offload,
            "offload_chunk_mb": chunk_mb,
            "offload_uniform_chunks": uniform,
            "offload_gradients": offload_gradients and cpu_offload}
    if state_dtype is not None:
        zero["offload_state_dtype"] = state_dtype
    engine, *_ = deepspeed.initialize(
        model=model, mesh=mesh,
        config={"train_batch_size": 4, "steps_per_print": 10 ** 9,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "gradient_clipping": clip,
                "zero_optimization": zero,
                "bf16": {"enabled": True}})
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 1024, size=(4, 128)).astype(np.int32)}
    out = []
    for _ in range(steps):
        loss = engine.train_batch(iter([batch]))
        out.append(float(np.asarray(jax.device_get(loss))))
    return out, engine


def test_streamed_offload_matches_device_training(monkeypatch):
    """Chunked+grouped streaming is a memory-placement choice, not a
    numerics change: loss trajectories match device-resident training."""
    import deepspeed_tpu.runtime.zero.coordinator as coord

    base, _ = _losses(cpu_offload=False)
    # force row-grouping at toy scale (a few hundred KB per group) so the
    # group loop, per-group chunking, AND the DUS write-back all engage
    monkeypatch.setattr(coord, "HOST_GROUP_BYTES", 1 << 20)
    streamed, engine = _losses(cpu_offload=True, chunk_mb=1)
    assert engine.flat.host_group_bounds is not None, (
        "test setup failed: grouping did not engage")
    assert len(engine.flat.host_group_bounds) >= 2
    np.testing.assert_allclose(streamed, base, rtol=2e-4, atol=2e-4)
    # state stayed host-resident through the steps
    for g in engine.state["master"]:
        assert g.sharding.memory_kind == "pinned_host"


def test_streamed_offload_checkpoint_roundtrip(tmp_path, monkeypatch):
    """Grouped state saves in the portable (ungrouped) checkpoint format
    and restores into groups with loss continuity."""
    import deepspeed_tpu.runtime.zero.coordinator as coord

    monkeypatch.setattr(coord, "HOST_GROUP_BYTES", 1 << 20)
    losses, engine = _losses(cpu_offload=True, chunk_mb=1)
    engine.save_checkpoint(str(tmp_path))

    _, engine2 = _losses(cpu_offload=True, chunk_mb=1, steps=1)
    engine2.load_checkpoint(str(tmp_path))
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 1024, size=(4, 128)).astype(np.int32)}
    l_resumed = float(np.asarray(jax.device_get(
        engine2.train_batch(iter([batch])))))
    l_ref = float(np.asarray(jax.device_get(
        engine.train_batch(iter([batch])))))
    np.testing.assert_allclose(l_resumed, l_ref, rtol=2e-4, atol=2e-4)


def test_offload_gradients_matches_device_training(monkeypatch):
    """offload_gradients (host-resident flat gradient + streamed read-back
    with folded unscale/clip) is numerics-identical to device training at
    the same clip setting, with grouping forced on so the reverse-order
    chunked gradient write-out crosses group bounds."""
    import deepspeed_tpu.runtime.zero.coordinator as coord

    base, _ = _losses(cpu_offload=False, clip=1.0)
    monkeypatch.setattr(coord, "HOST_GROUP_BYTES", 1 << 20)
    streamed, engine = _losses(cpu_offload=True, chunk_mb=1,
                               offload_gradients=True, clip=1.0)
    assert engine._offload_grads
    assert engine.state["hostgrad"] is not None
    hg = engine.state["hostgrad"]
    for g in (hg if type(hg) is tuple else (hg,)):
        assert g.sharding.memory_kind == "pinned_host"
    np.testing.assert_allclose(streamed, base, rtol=2e-4, atol=2e-4)


def test_uniform_scan_offload_matches_device_training(monkeypatch):
    """The O(1)-compile uniform-chunk scan update ON THE REAL CHIP: the
    pinned_host<->device placements live INSIDE a lax.scan body here
    (the one thing the CPU-forced suite cannot exercise), with grouping
    and the host-gradient leg both on.  Parity vs device-resident
    training, and state stays host-resident."""
    import deepspeed_tpu.runtime.zero.coordinator as coord

    base, _ = _losses(cpu_offload=False, clip=1.0)
    monkeypatch.setattr(coord, "HOST_GROUP_BYTES", 1 << 20)
    streamed, engine = _losses(cpu_offload=True, chunk_mb=1, clip=1.0,
                               offload_gradients=True, uniform=True)
    assert engine._offload_uniform, "scan path did not engage"
    assert engine.flat.host_group_bounds is not None
    np.testing.assert_allclose(streamed, base, rtol=2e-4, atol=2e-4)
    for g in engine.state["master"]:
        assert g.sharding.memory_kind == "pinned_host"


def test_reduced_state_bf16_matches_device_training(monkeypatch):
    """Reduced-precision host state ON THE REAL CHIP: bf16 pinned-host
    buffers with stochastic-rounding write-back track device-resident
    fp32 training, with grouping forced and the scan layout engaged
    (the pinned_host<->device placements around the quantize/dequantize
    are the one thing the CPU-forced suite cannot exercise)."""
    import deepspeed_tpu.runtime.zero.coordinator as coord

    base, _ = _losses(cpu_offload=False, steps=8)
    monkeypatch.setattr(coord, "HOST_GROUP_BYTES", 1 << 20)
    reduced, engine = _losses(cpu_offload=True, chunk_mb=1, steps=8,
                              uniform=True, state_dtype="bf16")
    assert engine._state_quant is not None
    assert engine.host_state_bytes_per_step() * 2 == \
        8 * engine.segments.rows * engine.state["master"][0].shape[1] * 3
    for g in engine.state["master"]:
        assert g.sharding.memory_kind == "pinned_host"
        assert str(g.dtype) == "bfloat16"
    np.testing.assert_allclose(reduced, base, rtol=2e-2, atol=2e-3)


def test_streamed_offload_grouped_with_chunking_disabled(monkeypatch):
    """offload_chunk_mb=0 disables sub-group chunking, but row-grouped
    state must STILL stream (one chunk per group) — the one-shot update
    cannot consume tuple-of-group buffers."""
    import deepspeed_tpu.runtime.zero.coordinator as coord

    monkeypatch.setattr(coord, "HOST_GROUP_BYTES", 1 << 20)
    losses, engine = _losses(cpu_offload=True, chunk_mb=0)
    assert engine.flat.host_group_bounds is not None
    assert losses[-1] < losses[0], losses
