"""Compile-only 2.7B lowering guard (PR 5/6 claim, CI-pinned).

The round-5 capacity blocker at gpt2-2.7B was COMPILE WALL TIME: the
unrolled streamed-update program grew linearly with chunk count and the
fused step stopped compiling inside 30 minutes.  Rounds 5/6 fixed it by
program shape (the uniform-chunk ``lax.scan`` update traced once), and
PERF.md claims "the 2.7B program now lowers at gpt2-large's size".
This file makes that claim a regression test instead of prose: the
streamed update core LOWERS (trace + StableHLO emission — no buffers
materialize, so a 32 GB state fits a CI box) at the REAL 2.7B offload
geometry — the coordinator's own group/chunk layout, the bench config's
512 MB chunks — in seconds, with program text within a small factor of
the gpt2-large lowering despite >3× the chunks.  Keeping this green
keeps ROADMAP item 2's measured capacity ladder (2.7B → 4B → 6B on the
bench attachment) unblocked from the compile side.
"""

import time

import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
from deepspeed_tpu.ops.op_common import LANES
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.runtime.zero import coordinator as coord
from deepspeed_tpu.runtime.zero import stream

# analytic GPT-2 parameter counts (capacity.GPT2_PRESETS geometry)
GPT2_LARGE_PARAMS = 774_030_080
GPT2_27B_PARAMS = 2_649_000_000
CHUNK_ROWS = (512 << 20) // (LANES * 4)  # the bench row's 512 MB chunks


def _lower_update_core(params, cpu_devices):
    """Lower the uniform-chunk scan update at the real offload layout
    for ``params`` parameters; returns (jobs, groups, text_len,
    lower_seconds).  Abstract avals only — nothing state-sized exists.
    """
    tmpl = {"w": jax.ShapeDtypeStruct((params,), jnp.float32)}
    mesh = make_mesh({"data": 1}, devices=cpu_devices[:1])
    flat = coord.FlatParamCoordinator(
        mesh, tmpl, stage=2, dp_size=1, cpu_offload=True,
        uniform_chunk_rows=CHUNK_ROWS, uniform_min_chunks=1)
    gb = flat.host_group_bounds or ((0, flat.segments.rows),)
    jobs = stream.uniform_chunk_jobs(gb, CHUNK_ROWS)
    opt = FusedAdam()
    st = jax.eval_shape(
        opt.init_state,
        jax.ShapeDtypeStruct((gb[0][1], LANES), jnp.float32))
    leaves, treedef = jax.tree_util.tree_flatten(st)
    is_flat = [getattr(l, "ndim", 0) == 2 for l in leaves]

    def mk(rc):
        return jax.ShapeDtypeStruct((rc, LANES), jnp.float32)

    masters = [mk(rc) for _, rc in gb]
    gls = [[mk(rc) if f else jax.ShapeDtypeStruct(l.shape, l.dtype)
            for f, l in zip(is_flat, leaves)] for _, rc in gb]
    g = jax.ShapeDtypeStruct((flat.segments.rows, LANES), jnp.float32)

    def run(ms, gl, gg):
        m, l, _ = stream.uniform_scan_update(
            masters=ms, group_leaves=gl, is_flat=is_flat,
            opt_treedef=treedef, update_fn=opt.update,
            hp=opt.hyperparams(), overflow=jnp.asarray(False),
            skip_bad=False, jobs=jobs, chunk_rows=CHUNK_ROWS,
            lanes=LANES, g=gg, prefetch_depth=2)
        return m, l

    t0 = time.perf_counter()
    lowered = jax.jit(run).lower(masters, gls, g)
    return (len(jobs), len(gb), len(lowered.as_text()),
            time.perf_counter() - t0)


@pytest.fixture
def injit(monkeypatch):
    # in-jit placement: the real grouped pinned-host layout on CPU
    monkeypatch.setenv("DS_OFFLOAD_FORCE_INJIT", "1")


def test_27b_update_lowers_at_gpt2_large_size(injit, cpu_devices):
    jobs_l, groups_l, text_l, secs_l = _lower_update_core(
        GPT2_LARGE_PARAMS, cpu_devices)
    jobs_x, groups_x, text_x, secs_x = _lower_update_core(
        GPT2_27B_PARAMS, cpu_devices)
    # the real geometries, not toys: 2.7B has >3x the chunks and a
    # multi-group pinned-host layout (the buffer-count-capped 3584 MB
    # groups)
    assert jobs_x >= 3 * jobs_l
    assert groups_x > groups_l >= 1
    # THE claim: program size is O(groups) with a tiny constant, NOT
    # O(chunks) — 2.7B's lowering stays within 2x of gpt2-large's text
    # (measured ~1.2x; the margin covers group-switch branches)
    assert text_x <= 2 * text_l, (
        f"2.7B streamed-update lowering grew to {text_x} chars vs "
        f"{text_l} at gpt2-large — the O(1)-compile scan property "
        "regressed (the round-5 >30-min-compile blocker is back)")
    # lowering is seconds, not minutes — the compile-wall guard
    assert secs_x < 60, f"2.7B lowering took {secs_x:.1f}s"


def test_27b_geometry_streams_grouped(injit, cpu_devices):
    """The 2.7B layout exercises the multi-group switch (the program
    shape the bench attachment will compile), and every group tiles
    exactly into uniform chunks."""
    _, groups, _, _ = _lower_update_core(GPT2_27B_PARAMS, cpu_devices)
    assert groups >= 2
