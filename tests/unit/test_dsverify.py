"""DSP6xx program-verifier tests (``tools/dslint/programs.py`` +
``profiling/verify.py``): the alias-header parser and donation verdicts
(incl. the warm-cache alias=0 downgrade), regression fixtures replaying
BOTH PR 8 bugs statically (the psum-over-dp×tp flatten and the donated
live numpy staging buffer), psum-for-pmean detection, comm-ledger drift,
the run-dir artifact dump + ``dslint --programs`` CLI, and the engine
hook at AOT-plan time."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.runtime.zero.coordinator import FlatParamCoordinator
from deepspeed_tpu.tools.dslint import failing
from deepspeed_tpu.tools.dslint import programs as dsp
from deepspeed_tpu.tools.dslint.cli import main as dslint_main
from deepspeed_tpu.tools.dslint.core import ParsedFile
from deepspeed_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 64


def rule_ids(diags):
    return sorted(d.rule_id for d in diags)


# ------------------------------------------------------ alias parsing
def test_parse_input_output_aliases():
    hdr = ("HloModule jit_x, is_scheduled=true, input_output_alias="
           "{ {1}: (0, {}, may-alias), {2}: (1, {}, must-alias) }, "
           "entry_computation_layout={...}\n  %body...")
    assert dsp.parse_input_output_aliases(hdr) == [("1", 0), ("2", 1)]
    assert dsp.parse_input_output_aliases("HloModule jit_x\n %b") == []


def test_donation_verdicts_601_602_and_clean():
    hlo_aliased = ("HloModule m, input_output_alias={ {0}: (0, {}, "
                   "may-alias) }, entry_computation_layout={...}\n")
    hlo_bare = "HloModule m, entry_computation_layout={...}\n"
    # declared donation, no aliases materialized -> hard error
    art = dsp.ProgramArtifact(name="p", hlo=hlo_bare,
                              donate_argnums=(0, 1))
    assert rule_ids(dsp.verify_program(art)) == ["DSP601"]
    # aliases in text + nonzero alias bytes -> fully verified
    art = dsp.ProgramArtifact(name="p", hlo=hlo_aliased,
                              donate_argnums=(0,),
                              alias_size_in_bytes=4096)
    assert dsp.verify_program(art) == []
    # aliases in text, memory_analysis says 0 -> the documented
    # warm-cache deserialization caveat: downgraded verdict, NOT silence
    art = dsp.ProgramArtifact(name="p", hlo=hlo_aliased,
                              donate_argnums=(0,),
                              alias_size_in_bytes=0)
    diags = dsp.verify_program(art)
    assert rule_ids(diags) == ["DSP602"]
    assert "cache-deserialized" in diags[0].message
    # DSP602 is a downgraded verdict: visible but never CI-failing
    assert failing(diags) == []
    # no donation declared -> nothing to verify
    art = dsp.ProgramArtifact(name="p", hlo=hlo_bare, donate_argnums=())
    assert dsp.verify_program(art) == []


def test_donation_verified_on_real_compiled_program():
    f = jax.jit(lambda x, y: (x + y, y * 2), donate_argnums=(0,))
    compiled = f.lower(jnp.zeros((256, 128), jnp.float32),
                       jnp.ones((256, 128), jnp.float32)).compile()
    art = dsp.ProgramArtifact(
        name="donating", hlo=compiled.as_text(), donate_argnums=(0,),
        alias_size_in_bytes=int(
            compiled.memory_analysis().alias_size_in_bytes))
    # cold compile: alias in text; warm (persistent test cache) may
    # report alias=0 -> DSP602.  Either way: zero hard violations
    assert not any(d.rule_id == "DSP601"
                   for d in dsp.verify_program(art))
    assert dsp.parse_input_output_aliases(art.hlo)


# ------------------------------------- PR 8 bug replay 1: flatten x tp
def _coordinator(cpu_devices, axes):
    mesh = make_mesh(axes, devices=cpu_devices[:int(np.prod(
        list(axes.values())))])
    params = {"w": np.zeros((100, 64), np.float32),
              "b": np.zeros((64,), np.float32)}
    return mesh, params, FlatParamCoordinator(
        mesh, params, stage=2, dp_size=axes.get("data", 1))


def test_rebroken_flatten_psum_over_tp_trips_dsp611(cpu_devices):
    """THE regression fixture: re-break ``flatten_to_master`` into its
    pre-PR 8 form (the jitted whole-tree flatten on a dp×tp mesh) and
    the verifier must catch the parameter sum STATICALLY — no runtime
    parity assert needed anymore."""
    mesh, params, coord = _coordinator(cpu_devices,
                                       {"data": 2, "model": 2})
    with mesh:
        compiled = jax.jit(
            coord._flatten_traced,
            out_shardings=coord.master_device_sharding).lower(
                params).compile()
    art = dsp.ProgramArtifact(
        name="flatten_to_master", hlo=compiled.as_text(),
        mesh_axes={"data": 2, "model": 2},
        param_bytes=int(np.prod(coord.segments.shape)) * 4)
    diags = dsp.verify_program(art)
    assert "DSP611" in rule_ids(diags), rule_ids(diags)
    msg = [d for d in diags if d.rule_id == "DSP611"][0].message
    assert "×2" in msg and "data axis is only 2" in msg


def test_fixed_flatten_paths_verify_clean(cpu_devices):
    # dp-only mesh: the jitted flatten is still the shipped path and
    # must verify clean (its all-reduce groups == the data axis)
    mesh, params, coord = _coordinator(cpu_devices, {"data": 4})
    with mesh:
        compiled = jax.jit(
            coord._flatten_traced,
            out_shardings=coord.master_device_sharding).lower(
                params).compile()
    art = dsp.ProgramArtifact(
        name="flatten_to_master", hlo=compiled.as_text(),
        mesh_axes={"data": 4},
        param_bytes=int(np.prod(coord.segments.shape)) * 4)
    assert dsp.verify_program(art) == []
    # ... and the fixed multi-axis path records its laundering
    # provenance for the verification artifacts
    mesh2, params2, coord2 = _coordinator(cpu_devices,
                                          {"data": 2, "model": 2})
    coord2.flatten_to_master(params2)
    assert coord2.master_provenance == "jit_copy"


# ------------------------------------------------ DSP612 psum-for-pmean
def _shard_scalar_program(cpu_devices, fn):
    mesh = make_mesh({"data": 4}, devices=cpu_devices[:4])
    with mesh:
        return jax.jit(shard_map(
            fn, mesh=mesh, in_specs=P("data"), out_specs=P(),
            axis_names={"data"}, check_vma=False)).lower(
                jnp.zeros((8, 16))).compile()


def test_psum_for_pmean_suspect_trips_and_pmean_clean(cpu_devices):
    psum_c = _shard_scalar_program(
        cpu_devices, lambda x: jax.lax.psum(jnp.sum(x), "data"))
    pmean_c = _shard_scalar_program(
        cpu_devices, lambda x: jax.lax.pmean(jnp.sum(x), "data"))
    bad = dsp.ProgramArtifact(name="psum", hlo=psum_c.as_text(),
                              mesh_axes={"data": 4})
    good = dsp.ProgramArtifact(name="pmean", hlo=pmean_c.as_text(),
                               mesh_axes={"data": 4})
    assert rule_ids(dsp.verify_program(bad)) == ["DSP612"]
    assert dsp.verify_program(good) == []


def test_mean_scaling_evidence_accepts_global_batch_normalization():
    # a loss normalized by the global element count (1/(g*k)) is mean
    # evidence too — the engine's fused step carries 1/1024-style
    # constants, not 1/dp
    hlo = "  %c = f32[] constant(0.0009765625)\n"     # 1/1024
    assert dsp.has_mean_scaling_evidence(hlo, 4)
    assert not dsp.has_mean_scaling_evidence(hlo, 3)  # 3 !| 1024
    assert dsp.has_mean_scaling_evidence("constant(0.25)", 4)
    assert not dsp.has_mean_scaling_evidence("constant(0.3)", 4)
    assert dsp.has_mean_scaling_evidence("", 1)       # no group, no sum


# ------------------------------------------------- DSP613 ledger drift
def test_comm_ledger_drift_trips_on_tampered_entry(cpu_devices):
    compiled = _shard_scalar_program(
        cpu_devices, lambda x: jax.lax.pmean(jnp.sum(x), "data"))
    from deepspeed_tpu.profiling.comm import (collective_summary,
                                              parse_hlo_collectives)

    hlo = compiled.as_text()
    fresh = collective_summary(parse_hlo_collectives(
        hlo, all_participants=4))
    ok = dsp.ProgramArtifact(name="p", hlo=hlo, mesh_axes={"data": 4},
                             comm=fresh)
    assert dsp.verify_program(ok) == []
    tampered = dict(fresh, wire_bytes=fresh["wire_bytes"] * 10 + 64,
                    collectives=fresh["collectives"] + 1)
    bad = dsp.ProgramArtifact(name="p", hlo=hlo, mesh_axes={"data": 4},
                              comm=tampered)
    assert rule_ids(dsp.verify_program(bad)) == ["DSP613"]


# --------------------- PR 8 bug replay 2: donated live staging buffer
_STAGED_DONATION = '''
import jax
import numpy as np

step = jax.jit(lambda m, g: m + g, donate_argnums=(0,))

def driver(sharding, g):
    buf = np.zeros((1024, 1024), np.float32)
    master = jax.device_put(buf, sharding)
    out = step(master, g)
    buf[0, 0] = 1.0
    return out
'''


def lint_src(tmp_path, source, name="fixture.py"):
    path = tmp_path / name
    path.write_text(source)
    pf = ParsedFile.parse(str(path), source)
    return dsp.check_use_after_donation(pf)


def test_donated_numpy_staging_read_after_trips_dsp603(tmp_path):
    """THE second regression fixture: the PR 8 heap-corruption shape —
    a device_put of a live numpy staging buffer donated into a jit,
    the staging buffer touched afterwards — caught at the AST level,
    no flaky glibc abort required."""
    diags = lint_src(tmp_path, _STAGED_DONATION)
    assert rule_ids(diags) == ["DSP603"]
    assert "STAGING" in diags[0].message
    assert "heap corruption" in diags[0].message


def test_plain_name_read_after_donation_trips(tmp_path):
    diags = lint_src(tmp_path, '''
import jax

apply_fn = jax.jit(lambda m, g: m + g, donate_argnums=(0,))

def driver(master, g):
    new_master = apply_fn(master, g)
    return master.sum() + new_master.sum()
''')
    assert rule_ids(diags) == ["DSP603"]


def test_dsp603_clean_twins(tmp_path):
    # (a) rebinding the donated name to the call result kills the watch
    assert lint_src(tmp_path, '''
import jax

accum_fn = jax.jit(lambda a, g: a + g, donate_argnums=(0,))

def driver(acc, grads):
    for g in grads:
        acc = accum_fn(acc, g)
    return acc
''') == []
    # (b) the fixed PR 8 shape: staging deleted, buffer re-homed
    # through a jitted copy before the donating call
    assert lint_src(tmp_path, '''
import jax
import jax.numpy as jnp
import numpy as np

step = jax.jit(lambda m, g: m + g, donate_argnums=(0,))

def driver(sharding, g):
    buf = np.zeros((4, 4), np.float32)
    staged = jax.device_put(buf, sharding)
    del buf
    master = jax.jit(lambda m: m + jnp.zeros((), m.dtype))(staged)
    out = step(master, g)
    return out
''') == []
    # (c) engine-style pytree-slot calls are the sanctioned pattern:
    # self.state[...] arguments are rebound by the outputs, not names
    assert lint_src(tmp_path, '''
import jax

class Engine:
    def __init__(self):
        self._apply_fn = jax.jit(lambda m, g: m + g, donate_argnums=(0,))

    def step(self, g):
        self.state["master"] = self._apply_fn(self.state["master"], g)
        return self.state["master"]
''') == []
    # (d) the non-donated argument stays readable
    assert lint_src(tmp_path, '''
import jax

step = jax.jit(lambda m, g: m + g, donate_argnums=(0,))

def driver(master, g):
    out = step(master, g)
    return g.sum() + out.sum()
''') == []


def test_dsp603_computed_argnums_only_flags_staged_numpy(tmp_path):
    # engine-style computed donate tuples: positions unknown -> only
    # the high-confidence staged-numpy shape is flagged
    src = '''
import jax
import numpy as np

donate = (0,) + (1,)
step = jax.jit(lambda m, g: m + g, donate_argnums=donate)

def staged(sharding, g):
    buf = np.zeros((4, 4), np.float32)
    out = step(jax.device_put(buf, sharding), g)
    return buf.sum() + out.sum()

def plain(master, g):
    out = step(master, g)
    return master.sum() + out.sum()
'''
    diags = lint_src(tmp_path, src)
    assert rule_ids(diags) == ["DSP603"]
    assert diags[0].line == 11            # the buf read in staged()


# ------------------------------------ artifacts: dump + CLI --programs
def _program_engine(cpu_devices, tmp_path, **profiling):
    cfg = base_config(
        steps_per_print=10 ** 9,
        telemetry={"enabled": True, "run_dir": str(tmp_path / "run")},
        profiling=dict({"comm_ledger": True}, **profiling))
    cfg["zero_optimization"] = {"stage": 2}
    mesh = make_mesh({"data": 4}, devices=cpu_devices[:4])
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(HIDDEN, nlayers=2), config=cfg, mesh=mesh)
    return engine


def test_program_dump_and_cli_roundtrip(cpu_devices, tmp_path, capsys):
    engine = _program_engine(cpu_devices, tmp_path)
    engine.train_batch(iter([random_batches(1, 16, HIDDEN, seed=0)[0]]))
    engine.close()
    progdir = tmp_path / "run" / "programs"
    names = sorted(os.listdir(progdir))
    assert "train_step.hlo" in names and "train_step.json" in names
    side = json.loads((progdir / "train_step.json").read_text())
    assert side["artifact_schema_version"] == dsp.ARTIFACT_SCHEMA_VERSION
    assert side["donate_argnums"] == [0, 1, 5]
    assert side["mesh_axes"] == {"data": 4}
    assert side["param_bytes"] > 0
    assert side["comm"]["collectives"] > 0
    # offline load agrees with the sidecars
    arts = {a.name: a for a in dsp.load_run_artifacts(str(tmp_path / "run"))}
    assert arts["train_step"].donate_argnums == (0, 1, 5)
    assert "input_output_alias" in arts["train_step"].hlo
    # library-side offline verification returns the engine-report
    # shape and agrees with the CLI
    from deepspeed_tpu.profiling.verify import verify_run_dir
    offline = verify_run_dir(tmp_path / "run")
    assert offline["violations"] == 0 and offline["errors"] == 0
    assert offline["programs_checked"] >= 2
    # the CLI self-verify invocation: zero DSP violations at HEAD
    assert dslint_main(["--programs", str(tmp_path / "run")]) == 0
    out = capsys.readouterr().out
    assert "0 violation(s)" in out
    # a tampered artifact fails through the same CLI path
    side["comm"]["wire_bytes"] = side["comm"]["wire_bytes"] * 10 + 64
    side["comm"]["collectives"] += 3
    (progdir / "train_step.json").write_text(json.dumps(side))
    assert dslint_main(["--programs", str(tmp_path / "run")]) == 1
    assert "DSP613" in capsys.readouterr().out


def test_cli_programs_missing_dir_exits_2(tmp_path, capsys):
    assert dslint_main(["--programs", str(tmp_path)]) == 2
    assert "program artifacts" in capsys.readouterr().err


def test_program_dump_off_without_run_dir(cpu_devices):
    cfg = base_config(steps_per_print=10 ** 9,
                      profiling={"comm_ledger": True})
    mesh = make_mesh({"data": 2}, devices=cpu_devices[:2])
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(HIDDEN, nlayers=2), config=cfg, mesh=mesh)
    assert engine.memory_ledger.dumper is None   # no telemetry run dir
    # ... but the in-memory hook still verifies
    engine.train_batch(iter([random_batches(1, 16, HIDDEN, seed=1)[0]]))
    report = engine.verify_programs()
    assert report["violations"] == 0
    assert report["programs_checked"] >= 1


# --------------------------------------------- engine hook at plan time
def test_verify_programs_at_aot_plan_time(cpu_devices, tmp_path):
    """The capacity-planner integration shape: plan mode compiles the
    step without running it, and verify_programs() renders a verdict
    from the same ledger hook."""
    cfg = base_config(steps_per_print=10 ** 9,
                      profiling={"comm_ledger": True})
    cfg["zero_optimization"] = {"stage": 2}
    mesh = make_mesh({"data": 2}, devices=cpu_devices[:2])
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(HIDDEN, nlayers=2), config=cfg, mesh=mesh,
        aot_plan=True)
    batch = random_batches(1, 16, HIDDEN, seed=2)[0]
    engine.aot_compile_train_step(batch)
    report = engine.verify_programs()
    assert report is not None and report["programs_checked"] >= 1
    assert report["violations"] == 0, [
        d.format() for d in report["diagnostics"]]
    engine.close()


def test_verify_report_shape_and_downgrade_count():
    from deepspeed_tpu.profiling.verify import _report

    hlo = ("HloModule m, input_output_alias={ {0}: (0, {}, may-alias) }"
           ", entry\n")
    diags = dsp.verify_program(dsp.ProgramArtifact(
        name="p", hlo=hlo, donate_argnums=(0,), alias_size_in_bytes=0))
    report = _report(diags, 1)
    # "overlap" is None here: a header-only artifact has no scheduled
    # computation to analyze, and the report must say "no claim"
    # rather than a silent fully-overlapped 0
    assert report == {"programs_checked": 1, "violations": 0,
                      "errors": 0, "downgraded": 1, "overlap": None,
                      "sharding": None, "diagnostics": diags}


# ------------------------------------------------ receipts + schema
def test_dsp_violation_fields_are_schema_registered():
    from deepspeed_tpu.tools.bench_schema import (threshold_for,
                                                  validate_record)

    rec = {"dsp_violations": 0, "dsp_downgraded": 2,
           "leg_zero2_dsp_violations": 0,
           "offload_gpt2_xl_dsp_violations": 0}
    assert validate_record(rec) == []
    # zero tolerance: any increase is a gated regression
    assert threshold_for("dsp_violations") == ("lower", 0.0)
    assert threshold_for("leg_zero2_dsp_violations") == ("lower", 0.0)
    assert threshold_for(
        "offload_gpt2_xl_dsp_violations") == ("lower", 0.0)
    assert validate_record({"dsp_violations": True})   # bool smuggled
    assert validate_record({"dsp_violations": 1.5})    # non-integral


def test_multichip_r07_artifact_carries_dsp_receipt():
    import glob

    from deepspeed_tpu.tools.bench_diff import load_bench_record

    newest = sorted(glob.glob(os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "MULTICHIP_r*.json")))[-1]
    rec = load_bench_record(newest)
    if "dsp_violations" not in rec:
        pytest.skip("driver artifact predates the dsp receipt")
    assert rec["dsp_violations"] == 0
    leg_fields = [k for k in rec if k.endswith("_dsp_violations")]
    assert leg_fields and all(rec[k] == 0 for k in leg_fields)


# ------------------------------------------- review-hardening paths
def test_cli_programs_foreign_json_only_exits_2(tmp_path, capsys):
    """A telemetry run dir that never dumped programs still holds
    latency-rank*.json etc. — that must be exit 2 ('no artifacts'),
    never a silent 0-violations pass."""
    (tmp_path / "latency-rank0.json").write_text('{"p50": 0.01}')
    assert dslint_main(["--programs", str(tmp_path)]) == 2
    assert "program_dump" in capsys.readouterr().err


def test_missing_hlo_text_is_a_violation_not_clean(tmp_path, capsys):
    """A sidecar whose .hlo file is missing/empty must fail (DSP613),
    not neutralize every HLO-side rule."""
    progdir = tmp_path / "programs"
    progdir.mkdir()
    (progdir / "train_step.json").write_text(json.dumps(
        {"artifact_schema_version": 1, "program": "train_step",
         "donate_argnums": [0, 1], "mesh_axes": {"data": 4}}))
    # no train_step.hlo on disk
    assert dslint_main(["--programs", str(tmp_path)]) == 1
    assert "DSP613" in capsys.readouterr().out
    art = dsp.ProgramArtifact(name="p", hlo="", donate_argnums=(0,))
    diags = dsp.verify_program(art)
    assert rule_ids(diags) == ["DSP613"]
    assert "missing or empty" in diags[0].message


def test_absent_alias_byte_data_downgrades_not_silent():
    """alias_size None (backend/sidecar without memory_analysis) is as
    unverifiable as the ==0 warm-cache case: explicit DSP602, never
    the silent-verified verdict."""
    hlo = ("HloModule m, input_output_alias={ {0}: (0, {}, may-alias) }"
           ", entry\n")
    diags = dsp.verify_program(dsp.ProgramArtifact(
        name="p", hlo=hlo, donate_argnums=(0,),
        alias_size_in_bytes=None))
    assert rule_ids(diags) == ["DSP602"]
    assert "no memory_analysis byte data" in diags[0].message
    assert failing(diags) == []


def test_baseline_key_stable_for_program_findings(tmp_path):
    """Program findings ratchet by (rule, program), not by the run-dir
    path or the byte counts in the message — a baselined intentional
    psum keeps matching after a re-dump or a model resize."""
    from deepspeed_tpu.tools.dslint.cli import baseline_key
    from deepspeed_tpu.tools.dslint.core import Diagnostic

    a = Diagnostic(path="/run1/programs/train_step.hlo", line=1, col=1,
                   rule_id="DSP612",
                   message="[train_step] scalar all-reduce over 4 "
                           "replicas with no 1/k scaling constant ...")
    b = Diagnostic(path="/tmp/other_run/programs/train_step.hlo",
                   line=1, col=1, rule_id="DSP612",
                   message="[train_step] scalar all-reduce over 8 "
                           "replicas with no 1/k scaling constant ...")
    assert baseline_key(a) == baseline_key(b) \
        == "<programs>|DSP612|train_step"
    # AST diagnostics keep the path+message identity
    c = Diagnostic(path="x.py", line=3, col=1, rule_id="DSH101",
                   message=".item() in jit")
    assert baseline_key(c) == "x.py|DSH101|.item() in jit"


def test_capacity_exit_code_fails_on_dsp_violations(monkeypatch):
    """'fails the PLAN, not the 2-AM run': a fitting plan with a DSP
    violation must exit nonzero."""
    from deepspeed_tpu.profiling import capacity

    def fake_plan(config, model, batch, mesh=None, capacity_bytes=None,
                  headroom=capacity.DEFAULT_HEADROOM):
        return {"analysis_available": True, "dsp_violations": 1,
                "dsp_errors": 1, "dsp_downgraded": 0,
                "dsp_findings": ["<train_step>:1:1: DSP601 ..."],
                "predicted_peak_hbm_bytes": 1, "predicted_temp_bytes": 1,
                "argument_bytes": 1, "output_bytes": 1, "alias_bytes": 1,
                "generated_code_bytes": 0, "predicted_host_bytes": 0,
                "host_buffer_bytes": 0, "host_buffer_count": 0,
                "host_state_wire_bytes_per_step": None,
                "capacity_bytes": capacity_bytes, "headroom": headroom,
                "plan_seconds": 0.0, "fit": True}

    monkeypatch.setattr(capacity, "plan", fake_plan)
    import json as _json
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        f.write(_json.dumps({"train_batch_size": 4}))
        cfg = f.name
    rc = capacity.main(["--config", cfg, "--model", "gpt2-medium",
                        "--capacity-gb", "16", "--json"])
    assert rc == 1          # fit=True but the program failed to verify
    os.unlink(cfg)


def test_foreign_non_dict_json_is_skipped_not_traceback(tmp_path,
                                                        capsys):
    """A run dir whose only json is a bare value (metrics.json holding
    a number) is 'no artifacts' (exit 2), never a TypeError traceback."""
    (tmp_path / "metrics.json").write_text("42")
    assert dslint_main(["--programs", str(tmp_path)]) == 2
    assert "program artifacts" in capsys.readouterr().err
    # ... and a non-dict json sitting NEXT to real sidecars is skipped
    progdir = tmp_path / "programs"
    progdir.mkdir()
    (progdir / "junk.json").write_text('"just a string"')
    (progdir / "p.json").write_text(json.dumps(
        {"artifact_schema_version": 1, "program": "p"}))
    (progdir / "p.hlo").write_text("HloModule m, entry\n")
    arts = dsp.load_run_artifacts(str(tmp_path))
    assert [a.name for a in arts] == ["p"]


def test_unavailable_collective_parser_is_loud_dsp614(monkeypatch):
    """If profiling.comm cannot import, the collective checks must
    report DSP614 ('UNVERIFIED'), not silently verify clean — even on
    the marquee flatten-×tp artifact."""
    monkeypatch.setattr(dsp, "_parse_collectives",
                        lambda hlo, n: None)
    art = dsp.ProgramArtifact(
        name="flatten_to_master",
        hlo="  %ar = f32[16384]{0} all-reduce(f32[16384]{0} %x), "
            "replica_groups={{0,1,2,3}}, to_apply=%add\n",
        mesh_axes={"data": 2, "model": 2}, param_bytes=65536)
    diags = dsp.verify_program(art)
    assert rule_ids(diags) == ["DSP614"]
    assert "UNVERIFIED" in diags[0].message


def test_partial_donation_drop_lower_bound():
    """Fewer distinct aliased parameters than declared donated argnums
    proves a whole donated argument aliased nothing: explicit DSP602,
    not silent-verified."""
    hlo = ("HloModule m, input_output_alias={ {0}: (0, {}, may-alias), "
           "{1}: (0, {}, may-alias) }, entry\n")   # 1 distinct param
    diags = dsp.verify_program(dsp.ProgramArtifact(
        name="p", hlo=hlo, donate_argnums=(0, 1, 4),
        alias_size_in_bytes=4096))
    assert rule_ids(diags) == ["DSP602"]
    assert "at least one donated argument" in diags[0].message
    # enough distinct params for every declared argnum -> verified
    hlo_ok = ("HloModule m, input_output_alias={ {0}: (0, {}, "
              "may-alias), {1}: (1, {}, may-alias), {2}: (4, {}, "
              "may-alias) }, entry\n")
    assert dsp.verify_program(dsp.ProgramArtifact(
        name="p", hlo=hlo_ok, donate_argnums=(0, 1, 4),
        alias_size_in_bytes=4096)) == []


def test_malformed_sidecar_types_exit_2_not_traceback(tmp_path, capsys):
    progdir = tmp_path / "programs"
    progdir.mkdir()
    (progdir / "p.json").write_text(json.dumps(
        {"artifact_schema_version": 1, "program": "p",
         "donate_argnums": 5}))           # int, not a list
    (progdir / "p.hlo").write_text("HloModule m, entry\n")
    assert dslint_main(["--programs", str(tmp_path)]) == 2
    assert "malformed program sidecar" in capsys.readouterr().err
    (progdir / "p.json").write_text(json.dumps(
        {"artifact_schema_version": 1, "program": "p",
         "mesh_axes": [4]}))              # list, not a dict
    assert dslint_main(["--programs", str(tmp_path)]) == 2


def test_program_dump_true_forces_the_hook_with_ledgers_off(
        cpu_devices, tmp_path):
    """Explicit program_dump=true must dump even when memory_ledger and
    comm_ledger are BOTH explicitly false (the knob's 'true forces the
    dump' contract) — the shared AOT hook goes live for the dumper."""
    cfg = base_config(
        steps_per_print=10 ** 9,
        telemetry={"enabled": True, "run_dir": str(tmp_path / "run")},
        profiling={"memory_ledger": False, "comm_ledger": False,
                   "program_dump": True})
    mesh = make_mesh({"data": 2}, devices=cpu_devices[:2])
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(HIDDEN, nlayers=2), config=cfg, mesh=mesh)
    assert engine.memory_ledger.enabled
    assert engine.memory_ledger.dumper is not None
    engine.train_batch(iter([random_batches(1, 16, HIDDEN, seed=3)[0]]))
    engine.close()
    names = os.listdir(tmp_path / "run" / "programs")
    assert "train_step.hlo" in names and "train_step.json" in names
    assert dslint_main(["--programs", str(tmp_path / "run")]) == 0


def test_null_hlo_file_sidecar_exits_2_not_traceback(tmp_path, capsys):
    progdir = tmp_path / "programs"
    progdir.mkdir()
    (progdir / "p.json").write_text(json.dumps(
        {"artifact_schema_version": 1, "program": "p",
         "hlo_file": None}))          # null: falls back to p.hlo
    (progdir / "p.hlo").write_text("HloModule m, entry\n")
    assert dslint_main(["--programs", str(tmp_path)]) == 0
    (progdir / "p.json").write_text(json.dumps(
        {"artifact_schema_version": 1, "program": "p",
         "hlo_file": 42}))            # non-string: malformed
    assert dslint_main(["--programs", str(tmp_path)]) == 2
    assert "hlo_file" in capsys.readouterr().err


def test_capacity_warnings_report_but_do_not_gate(monkeypatch):
    """Heuristic DSP warnings (psum-for-pmean suspect, ledger drift)
    have no ratchet on the planner surface, so they print in the
    report but must not turn a fitting plan into exit 1 — only
    error-severity findings gate."""
    from deepspeed_tpu.profiling import capacity

    def fake_plan(config, model, batch, mesh=None, capacity_bytes=None,
                  headroom=capacity.DEFAULT_HEADROOM):
        return {"analysis_available": True, "dsp_violations": 1,
                "dsp_errors": 0,          # the one finding is a warning
                "dsp_downgraded": 0,
                "dsp_findings": ["<p>:1:1: DSP612 [warning] ..."],
                "predicted_peak_hbm_bytes": 1, "predicted_temp_bytes": 1,
                "argument_bytes": 1, "output_bytes": 1, "alias_bytes": 1,
                "generated_code_bytes": 0, "predicted_host_bytes": 0,
                "host_buffer_bytes": 0, "host_buffer_count": 0,
                "host_state_wire_bytes_per_step": None,
                "capacity_bytes": capacity_bytes, "headroom": headroom,
                "plan_seconds": 0.0, "fit": True}

    monkeypatch.setattr(capacity, "plan", fake_plan)
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        f.write('{"train_batch_size": 4}')
        cfg = f.name
    assert capacity.main(["--config", cfg, "--model", "gpt2-medium",
                          "--capacity-gb", "16", "--json"]) == 0
    os.unlink(cfg)


def test_dsp603_message_carries_no_line_number(tmp_path):
    """baseline keys embed messages verbatim; a line number in the
    DSP603 message would break the ratchet on any unrelated edit."""
    diags = lint_src(tmp_path, _STAGED_DONATION)
    assert rule_ids(diags) == ["DSP603"]
    import re as _re

    assert not _re.search(r"line \d+", diags[0].message)
    assert diags[0].line > 0          # the location IS the read site


def test_verify_withholds_verdict_when_no_hlo_available(cpu_devices,
                                                        monkeypatch):
    """If no compiled program yields HLO text, verify_programs() must
    return None ('could not verify'), never a 0-violation report —
    receipts then omit the field instead of claiming clean."""
    cfg = base_config(steps_per_print=10 ** 9,
                      profiling={"comm_ledger": True})
    mesh = make_mesh({"data": 2}, devices=cpu_devices[:2])
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(HIDDEN, nlayers=2), config=cfg, mesh=mesh)
    engine.train_batch(iter([random_batches(1, 16, HIDDEN, seed=5)[0]]))
    from deepspeed_tpu.profiling import verify as pv

    monkeypatch.setattr(pv, "build_engine_artifact",
                        lambda engine, name, compiled: None)
    assert engine.verify_programs() is None


def test_dsp_warnings_field_registered_and_ungated():
    from deepspeed_tpu.tools.bench_schema import (threshold_for,
                                                  validate_record)

    assert validate_record({"dsp_warnings": 2}) == []
    assert threshold_for("dsp_warnings") == (None, None)


# ------------------------------------------- DSS8xx sharding auditor
def _declared(tag, mesh_axes, **families):
    """An engine-shaped declared_sharding dict from
    ``family=[(global_bytes, axes, divisor), ...]`` kwargs."""
    from deepspeed_tpu.profiling import sharding as sharding_prof

    return {"tag": tag, "mesh_axes": dict(mesh_axes),
            "families": {fam: sharding_prof.build_declared_family(leaves)
                         for fam, leaves in families.items()}}


def _jit_param_program(mesh, x, spec):
    from jax.sharding import NamedSharding

    sh = NamedSharding(mesh, spec)
    with mesh:
        return jax.jit(lambda p: p * 2.0, in_shardings=sh,
                       out_shardings=sh).lower(x).compile()


def test_rebroken_replicated_params_trip_dss801(cpu_devices):
    """THE round-17 regression fixture: parameters DECLARED ÷dp that
    compile fully replicated on the dp mesh — numerically identical,
    loss finite, every device silently paying ×dp resident bytes —
    must fail statically, with the fold priced in the message."""
    mesh = make_mesh({"data": 4}, devices=cpu_devices[:4])
    x = jnp.zeros((512, 1024), jnp.float32)       # 2 MiB ≥ audit floor
    nb = x.size * 4
    decl = _declared("zero3|data4", {"data": 4},
                     params=[(nb, ["data"], 4)])
    compiled = _jit_param_program(mesh, x, P())   # the re-broken layout
    art = dsp.ProgramArtifact(
        name="train_step", hlo=compiled.as_text(),
        mesh_axes={"data": 4}, declared_sharding=decl)
    diags = dsp.verify_program(art)
    assert "DSS801" in rule_ids(diags), rule_ids(diags)
    bad = [d for d in diags if d.rule_id == "DSS801"][0]
    assert bad.severity == "error" and failing([bad])
    assert "×4" in bad.message and "replicated" in bad.message
    assert f"{nb // 4} declared -> {nb} actual" in bad.message
    # ... and the summary prices the fold: per-device == global
    summary = dsp.program_sharding(art)
    assert summary["param_bytes_per_device"] == nb
    assert summary["param_shard_divisor"] == 1
    # the FIXED layout (the same declaration actually materialized)
    # verifies clean and halves^2 the receipt
    compiled_ok = _jit_param_program(mesh, x, P("data"))
    ok = dsp.ProgramArtifact(
        name="train_step", hlo=compiled_ok.as_text(),
        mesh_axes={"data": 4}, declared_sharding=decl)
    assert dsp.verify_program(ok) == []
    summary_ok = dsp.program_sharding(ok)
    assert summary_ok["param_bytes_per_device"] == nb // 4
    assert summary_ok["param_shard_divisor"] == 4


def test_sub_mib_fold_stays_quiet(cpu_devices):
    """DSS801 has a 1 MiB floor: a small declared-sharded tensor that
    materializes replicated is noise, not a capacity regression."""
    mesh = make_mesh({"data": 4}, devices=cpu_devices[:4])
    x = jnp.zeros((64, 64), jnp.float32)          # 16 KiB
    decl = _declared("zero3|data4", {"data": 4},
                     params=[(x.size * 4, ["data"], 4)])
    compiled = _jit_param_program(mesh, x, P())
    art = dsp.ProgramArtifact(
        name="train_step", hlo=compiled.as_text(),
        mesh_axes={"data": 4}, declared_sharding=decl)
    assert dsp.verify_program(art) == []
    # the mismatch is still RECORDED (receipts see it) — only the
    # diagnostic is floored
    summary = dsp.program_sharding(art)
    assert summary["families"]["params"]["mismatches"]


def test_cross_program_layout_divergence_trips_dss802(cpu_devices):
    """The same declared family materializing ÷4 in one program and
    replicated in another pays an unpriced reshard at the boundary:
    DSS802 on the divergent program, naming both layouts."""
    mesh = make_mesh({"data": 4}, devices=cpu_devices[:4])
    x = jnp.zeros((512, 1024), jnp.float32)
    nb = x.size * 4
    # declared replicated in BOTH sidecars so DSS801 stays out of the
    # frame: DSS802 compares what MATERIALIZED, not what was declared
    decl = _declared("zero2|data4", {"data": 4},
                     params=[(nb, [], 1)])
    art_sharded = dsp.ProgramArtifact(
        name="z_step", hlo=_jit_param_program(mesh, x, P("data")).as_text(),
        mesh_axes={"data": 4}, declared_sharding=decl)
    art_replicated = dsp.ProgramArtifact(
        name="a_step", hlo=_jit_param_program(mesh, x, P()).as_text(),
        mesh_axes={"data": 4}, declared_sharding=decl)
    diags = dsp.check_sharding_consistency([art_sharded, art_replicated])
    assert rule_ids(diags) == ["DSS802"]
    msg = diags[0].message
    assert "family 'params'" in msg
    assert "÷1" in msg and "÷4" in msg and "[z_step]" in msg
    # same artifacts through the CLI-facing batch entry point
    assert "DSS802" in rule_ids(
        dsp.verify_artifacts([art_sharded, art_replicated]))
    # agreeing layouts: silent
    art_sharded2 = dsp.ProgramArtifact(
        name="b_step", hlo=art_sharded.hlo,
        mesh_axes={"data": 4}, declared_sharding=decl)
    assert dsp.check_sharding_consistency(
        [art_sharded, art_sharded2]) == []


def test_param_bytes_ratchet_trips_dss803(cpu_devices):
    """A re-replication that the declaration ALSO weakened (so DSS801
    cannot fire) still trips the baseline ratchet: the recorded
    per-device figure is the contract."""
    mesh = make_mesh({"data": 4}, devices=cpu_devices[:4])
    x = jnp.zeros((512, 1024), jnp.float32)
    nb = x.size * 4
    hlo_rep = _jit_param_program(mesh, x, P()).as_text()
    # the declaration says replicated (weakened), matching the compile
    decl = _declared("zero2|data4", {"data": 4}, params=[(nb, [], 1)])
    art = dsp.ProgramArtifact(
        name="train_step", hlo=hlo_rep,
        mesh_axes={"data": 4}, declared_sharding=decl)
    assert dsp.verify_program(art) == []          # DSS801 blind here
    key = dsp.sharding_metric_key("zero2|data4", "train_step")
    # baseline recorded the ÷4 era: ×4 growth far exceeds tolerance
    diags = dsp.check_sharding_ratchet([art], {key: nb / 4})
    assert rule_ids(diags) == ["DSS803"]
    assert f"grew {nb // 4} -> {nb}" in diags[0].message
    # within tolerance (same figure): silent; no recorded key: silent
    assert dsp.check_sharding_ratchet([art], {key: float(nb)}) == []
    assert dsp.check_sharding_ratchet([art], {}) == []
    # ... and sharding_metrics records exactly this key
    assert dsp.sharding_metrics([art]) == {key: float(nb)}


def test_unavailable_sharding_parser_is_loud_dss804(monkeypatch):
    """If profiling.sharding cannot import, a program WITH a declared
    spec must report DSS804 ('UNVERIFIED'), not silently verify clean
    — the DSP614 contract applied to residency."""
    monkeypatch.setattr(dsp, "_load_sharding", lambda: None)
    art = dsp.ProgramArtifact(
        name="train_step", hlo="HloModule m, entry\n",
        mesh_axes={"data": 4},
        declared_sharding=_declared("zero2|data4", {"data": 4},
                                    params=[(1 << 21, ["data"], 4)]))
    diags = dsp.verify_program(art)
    assert rule_ids(diags) == ["DSS804"]
    assert "UNVERIFIED" in diags[0].message
    # warning severity: the planner's error-count gate ignores it, but
    # the CLI still fails fresh (only --baseline can absolve it) — the
    # same contract as DSP614
    assert diags[0].severity == "warning"
    # no declaration -> nothing to verify, no noise
    bare = dsp.ProgramArtifact(name="p", hlo="HloModule m, entry\n")
    assert dsp.verify_program(bare) == []


def test_declared_sharding_sidecar_roundtrip(cpu_devices, tmp_path):
    """The engine's declared spec survives ProgramDumper → sidecar →
    offline load byte-identically, and the offline report carries the
    per-device residency receipt."""
    engine = _program_engine(cpu_devices, tmp_path)
    engine.train_batch(iter([random_batches(1, 16, HIDDEN, seed=7)[0]]))
    engine.close()
    progdir = tmp_path / "run" / "programs"
    side = json.loads((progdir / "train_step.json").read_text())
    decl = side["declared_sharding"]
    assert decl["tag"] == "zero2|data4"
    assert set(decl["families"]) >= {"params", "master", "optimizer"}
    for fam in ("params", "master", "optimizer"):
        assert decl["families"][fam]["total_bytes"] > 0
        assert decl["families"][fam]["leaves"]
    # offline load agrees byte-for-byte with the sidecar
    arts = {a.name: a
            for a in dsp.load_run_artifacts(str(tmp_path / "run"))}
    assert arts["train_step"].declared_sharding == decl
    # the offline report prices residency from the same artifacts
    from deepspeed_tpu.profiling.verify import verify_run_dir
    offline = verify_run_dir(tmp_path / "run")
    assert offline["violations"] == 0
    sh = offline["sharding"]["train_step"]
    assert sh["param_bytes_per_device"] > 0
    assert sh["param_shard_divisor"] >= 1
    # ... and the CLI path stays clean over the same run dir
    assert dslint_main(["--programs", str(tmp_path / "run")]) == 0


def test_malformed_declared_sharding_sidecar_exits_2(tmp_path, capsys):
    """A type-tampered declared_sharding must fail the CLI loudly
    (exit 2), never quietly disable the DSS8xx reconciliation."""
    progdir = tmp_path / "programs"
    progdir.mkdir()
    (progdir / "p.hlo").write_text("HloModule m, entry\n")
    (progdir / "p.json").write_text(json.dumps(
        {"artifact_schema_version": 1, "program": "p",
         "declared_sharding": "zero2|data4"}))     # string, not object
    assert dslint_main(["--programs", str(tmp_path)]) == 2
    assert "malformed program sidecar" in capsys.readouterr().err
    (progdir / "p.json").write_text(json.dumps(
        {"artifact_schema_version": 1, "program": "p",
         "declared_sharding": {"tag": "t", "families": 3}}))
    assert dslint_main(["--programs", str(tmp_path)]) == 2
    (progdir / "p.json").write_text(json.dumps(
        {"artifact_schema_version": 1, "program": "p",
         "declared_sharding": {"tag": "t",
                               "families": {"params": {"leaves": 5}}}}))
    assert dslint_main(["--programs", str(tmp_path)]) == 2
    # absent field (a pre-DSS8 sidecar): loads and verifies clean
    (progdir / "p.json").write_text(json.dumps(
        {"artifact_schema_version": 1, "program": "p"}))
    assert dslint_main(["--programs", str(tmp_path)]) == 0
