"""Tier-1 CI guard: the shipped tree must be dslint-clean.

This is the "wired into CI" part of the static-analysis pass: it rides
the existing pytest tier-1 command, so any PR that introduces an
unsuppressed hot-path sync, retrace hazard, or dead config key fails the
suite with the exact file:line diagnostics in the assertion message.
"""

import collections
import os

import deepspeed_tpu
from deepspeed_tpu.tools.dslint import failing, lint_paths, rule_family

PKG_DIR = os.path.dirname(os.path.abspath(deepspeed_tpu.__file__))

# Every suppression in the tree is an explicit, reasoned pragma; the
# per-family budgets keep "add a pragma" from becoming the path of
# least resistance.  Raise one only with a `-- reason` on the new
# pragma line.  Single-sourced in tools/dslint/core.py since round 11
# (the CLI reports the same table via --json family_budgets and
# --list-rules); program families (DSP6, DSO7) are 0 by construction —
# the --baseline ratchet is their only suppression mechanism.
# Current usage: DSC4 1, DSH1 2, DSH2 3, DSE5 7 = 13.
from deepspeed_tpu.tools.dslint.core import FAMILY_BUDGETS

MAX_SUPPRESSIONS = sum(FAMILY_BUDGETS.values())
ALLOWED_SUPPRESSED_RULES = {"DSC401", "DSH102", "DSH202", "DSH203",
                            "DSE502"}


def _diags():
    return lint_paths([PKG_DIR])


def test_package_is_dslint_clean():
    bad = failing(_diags())
    listing = "\n".join(d.format() for d in bad)
    assert not bad, (
        f"dslint found {len(bad)} unsuppressed violation(s) in the "
        f"shipped tree — fix them or add a reasoned "
        f"'# dslint: disable=<id> -- why' pragma:\n{listing}")


def test_suppression_budget():
    suppressed = [d for d in _diags() if d.suppressed]
    listing = "\n".join(d.format() for d in suppressed)
    by_family = collections.Counter(rule_family(d.rule_id)
                                    for d in suppressed)
    for family, count in sorted(by_family.items()):
        budget = FAMILY_BUDGETS.get(family, 0)
        assert count <= budget, (
            f"suppression budget for {family}xx exceeded ({count} > "
            f"{budget}):\n{listing}")
    assert len(suppressed) <= MAX_SUPPRESSIONS, (
        f"total suppression budget exceeded ({len(suppressed)} > "
        f"{MAX_SUPPRESSIONS}):\n{listing}")
    stray = {d.rule_id for d in suppressed} - ALLOWED_SUPPRESSED_RULES
    assert not stray, (
        f"new suppressed rule famil{'ies' if len(stray) > 1 else 'y'} "
        f"{sorted(stray)} — extend ALLOWED_SUPPRESSED_RULES only with a "
        f"review of:\n{listing}")


def test_cli_exit_zero_on_shipped_tree():
    from deepspeed_tpu.tools.dslint.cli import main

    assert main([PKG_DIR]) == 0


def test_checked_in_baseline_is_empty_of_violations():
    """Round 12 (overlapped chunk streaming) EMPTIED the ratchet file:
    the offload stream's DSO702 finding is gone because the stream is
    double-buffered now, so the shipped baseline records ZERO absolved
    violations — any serialized stream (or any other program finding)
    fails CI fresh.  What the baseline DOES record is the exposed-wire
    METRIC of the CI offload leg's fused step (the DSO704 ratchet): a
    change that quietly grows exposure past tolerance trips CI even if
    every node still classifies as partially overlapped."""
    import json

    from deepspeed_tpu.tools.dslint.cli import main
    from deepspeed_tpu.tools.dslint.programs import (
        comm_exposure_metric_key, exposure_metric_key,
        predicted_step_metric_key, sharding_metric_key)

    baseline = os.path.join(os.path.dirname(PKG_DIR), "tools",
                            "dslint_baseline.json")
    assert os.path.isfile(baseline)
    data = json.load(open(baseline, encoding="utf-8"))
    assert data["schema_version"] == 1
    assert data["violations"] == {}, (
        "the checked-in dslint baseline must stay EMPTY of absolved "
        "violations: fix or pragma findings instead of baselining them")
    metrics = data.get("metrics") or {}
    # round 13 added the attribution budget pin (DSO705) next to the
    # exposed-wire ratchet (DSO704) — both for the CI offload step —
    # and round 14 the bucketed zero-2 exchange's collective-exposure
    # pins (its OWN metric name: the two fixtures share the
    # "train_step" program name), all re-derived deterministically
    # from the dumped HLO
    # round 17 added the DSS803 per-device parameter-bytes pins — TAG-
    # qualified (the two CI fixtures share the "train_step" program
    # name AND model geometry, so each needs its own ratchet key),
    # recorded from the checked-in tools/dslint_fixtures/ sidecars by
    # tools/regen_dslint_fixtures.py
    # round 19 added the serving sidecar (tiny-GPT-2 inference engine):
    # its two serve programs pin their serve|data1 residency the same
    # way — no exposure/attribution keys (no host stream, no
    # overlapped collective schedule on the serve programs)
    # round 20 added the stage-3 fixture (same geometry/buckets as
    # zero2_overlap) and TAG-qualified the comm-exposure keys: two
    # overlapped train_step programs now coexist, and a name-only key
    # would be last-write-wins across the recorded run dirs
    keys = {exposure_metric_key("train_step"),
            predicted_step_metric_key("train_step"),
            comm_exposure_metric_key("train_step", "zero2|data4"),
            comm_exposure_metric_key("cast_params", "zero2|data4"),
            comm_exposure_metric_key("train_step", "zero3|data4"),
            sharding_metric_key("zero2-offload|data1", "train_step"),
            sharding_metric_key("zero2|data4", "train_step"),
            sharding_metric_key("zero3|data4", "train_step"),
            sharding_metric_key("serve|data1", "serve_decode"),
            sharding_metric_key("serve|data1", "serve_prefill_16")}
    assert set(metrics) == keys, (
        "the baseline records exactly the offload-step exposed-wire + "
        "attribution ratchet metrics, the overlap fixtures' "
        "collective-exposure metrics, and the fixtures' DSS803 "
        f"param-bytes pins ({sorted(keys)}); anything else needs "
        "review")
    for key in keys:
        assert metrics[key] > 0
    # the zero2/offload fixtures share SimpleModel(256, nlayers=8)
    # with replicated params: both pins state the same full byte count
    pb = metrics[sharding_metric_key("zero2|data4", "train_step")]
    assert pb == 8 * (256 * 256 + 256) * 4
    # the stage-3 pin is the SAME model's flat master ÷dp: 520 leaf
    # rows pad to 528 over 4 buckets × dp=4 (132 rows each), so the
    # per-device claim is 528 × 1024 lanes × 4 B / 4 — the replicated
    # 2105344-byte figure shrunk to a quarter (modulo dp padding), the
    # ÷dp receipt of ROADMAP item 2 as a checked-in ratchet
    pb3 = metrics[sharding_metric_key("zero3|data4", "train_step")]
    assert pb3 == 528 * 1024 * 4 // 4
    assert pb3 < pb / 3
    assert main([PKG_DIR, "--baseline", baseline]) == 0


def test_family_budgets_cover_every_registered_family():
    """Every registered rule family has an explicit budget entry (new
    families must opt into a budget, not inherit silence), and the
    program families carry none."""
    from deepspeed_tpu.tools.dslint.core import RULES

    families = {rule_family(rid) for rid in RULES}
    assert families <= set(FAMILY_BUDGETS), (
        f"families without a budget entry: "
        f"{sorted(families - set(FAMILY_BUDGETS))}")
    assert FAMILY_BUDGETS["DSP6"] == 0
    assert FAMILY_BUDGETS["DSO7"] == 0
    assert FAMILY_BUDGETS["DSS8"] == 0


def test_list_rules_and_json_report_include_dso7_family(tmp_path):
    """`--list-rules` prints the DSO7xx overlap rules and the budget
    table; `--json` carries the same budgets (family_budgets) so CI
    dashboards read one source of truth."""
    import contextlib
    import io
    import json

    from deepspeed_tpu.tools.dslint.cli import main

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert main(["--list-rules"]) == 0
    catalog = buf.getvalue()
    for rule_id in ("DSO701", "DSO702", "DSO703",
                    "DSS801", "DSS802", "DSS803", "DSS804"):
        assert rule_id in catalog
    assert "suppression budgets" in catalog
    assert "DSO7xx=0" in catalog
    assert "DSS8xx=0" in catalog

    out = tmp_path / "r.json"
    assert main([os.path.join(PKG_DIR, "tools", "dslint", "core.py"),
                 "--json", str(out)]) == 0
    report = json.load(open(out, encoding="utf-8"))
    assert report["family_budgets"] == FAMILY_BUDGETS
    assert "DSO701" in report["rules"]
    assert "DSS801" in report["rules"]


def test_dslint_all_composite_gate():
    """Satellite of the round-17 sharding auditor: ``dslint --all`` is
    the ONE CI invocation combining the source self-lint, the
    checked-in baseline ratchet (incl. the DSS803 param-bytes pins),
    and program verification over the checked-in fixture sidecars
    (tools/dslint_fixtures/) — wired as a tier-1 test so the three
    gates cannot drift apart."""
    from deepspeed_tpu.tools.dslint.cli import main

    assert main(["--all"]) == 0


def test_telemetry_package_is_hotpath_clean():
    """The telemetry subsystem's zero-added-host-syncs contract, pinned
    statically: no DSH1xx/DSH2xx diagnostics over deepspeed_tpu/telemetry/
    or the instrumented engine driver paths — not even suppressed ones.
    (test_engine_zero_added_host_syncs asserts the same thing dynamically
    by counting device_get calls per step.)"""
    diags = lint_paths([os.path.join(PKG_DIR, "telemetry"),
                        os.path.join(PKG_DIR, "runtime", "engine.py"),
                        os.path.join(PKG_DIR, "checkpoint", "manager.py")])
    hot = [d for d in diags if d.rule_id.startswith(("DSH1", "DSH2"))
           and not d.suppressed]
    listing = "\n".join(d.format() for d in hot)
    assert not hot, f"telemetry hot-path violations:\n{listing}"
    # the only suppressed hot-path syncs in these files are the two
    # documented print-cadence DSH203 pragmas that predate telemetry
    sup = sorted(d.rule_id for d in diags if d.suppressed
                 and d.rule_id.startswith("DSH"))
    assert sup == ["DSH203", "DSH203"], sup
