"""Instruction-stream schedule tests (model: reference
``tests/unit/test_pipe_schedule.py``)."""

import pytest

from deepspeed_tpu.runtime import pipe as schedule


def _count(cmds, cls):
    return sum(1 for c in cmds if isinstance(c, cls))


def test_pipe_inference_schedule_singlestage():
    sched = schedule.InferenceSchedule(micro_batches=4, stages=1, stage_id=0)
    assert sched.num_micro_batches == 4
    full = list(iter(sched))
    for idx, cmds in enumerate(full):
        assert len(cmds) == 2
        assert isinstance(cmds[0], schedule.LoadMicroBatch)
        assert isinstance(cmds[1], schedule.ForwardPass)
        assert cmds[0].buffer_id == cmds[1].buffer_id
    assert len(full) == sched.num_micro_batches


def test_pipe_train_schedule_singlestage():
    sched = schedule.TrainSchedule(micro_batches=4, stages=1, stage_id=0)
    full = list(iter(sched))
    # forward and backward ticks alternate on one stage
    for idx, cmds in enumerate(full):
        if (idx % 2) != 0:
            assert len(cmds) == 1 or (idx == len(full) - 1 and len(cmds) == 4)
            assert isinstance(cmds[0], schedule.BackwardPass)
        else:
            assert len(cmds) == 2
            assert isinstance(cmds[0], schedule.LoadMicroBatch)
            assert isinstance(cmds[1], schedule.ForwardPass)
    assert len(full) == 2 * sched.num_micro_batches


@pytest.mark.parametrize("micro_batches", [1, 3, 8, 10])
def test_pipe_inference_schedule_firststage(micro_batches, stages=3):
    sched = schedule.InferenceSchedule(micro_batches=micro_batches,
                                       stages=stages, stage_id=0)
    full = list(iter(sched))
    for idx, cmds in enumerate(full):
        if idx < sched.num_micro_batches:
            assert _count(cmds, schedule.LoadMicroBatch) == 1
            assert _count(cmds, schedule.ForwardPass) == 1
        else:
            # draining: no compute on first stage
            assert _count(cmds, schedule.ForwardPass) == 0
        # first stage never receives
        assert _count(cmds, schedule.RecvActivation) == 0
    assert len(full) == micro_batches + stages - 1


@pytest.mark.parametrize("micro_batches", [1, 3, 8, 10])
def test_pipe_inference_schedule_laststage(micro_batches, stages=3):
    sched = schedule.InferenceSchedule(micro_batches=micro_batches,
                                       stages=stages, stage_id=stages - 1)
    full = list(iter(sched))
    for idx, cmds in enumerate(full):
        if idx < sched.stage_id:  # still filling
            assert _count(cmds, schedule.ForwardPass) == 0
        else:
            assert _count(cmds, schedule.LoadMicroBatch) == 1
            assert _count(cmds, schedule.RecvActivation) == 1
            assert _count(cmds, schedule.ForwardPass) == 1
        assert _count(cmds, schedule.SendActivation) == 0
    assert len(full) == micro_batches + stages - 1


def test_pipe_schedule_firststage_train():
    sched = schedule.TrainSchedule(micro_batches=8, stages=3, stage_id=0)
    total_fwd = total_bwd = 0
    for cmds in sched:
        total_fwd += _count(cmds, schedule.ForwardPass)
        total_bwd += _count(cmds, schedule.BackwardPass)
        # first stage never exchanges with a previous stage
        assert _count(cmds, schedule.RecvActivation) == 0
        assert _count(cmds, schedule.SendGrad) == 0
    assert total_fwd == 8
    assert total_bwd == 8


@pytest.mark.parametrize("stages", [2, 3, 4])
@pytest.mark.parametrize("micro_batches", [2, 4, 8])
def test_pipe_train_schedule_all_stages_balanced(micro_batches, stages):
    """Every stage forwards and backwards each micro-batch exactly once, and
    the final tick carries the reduce + step instructions."""
    for stage_id in range(stages):
        sched = schedule.TrainSchedule(micro_batches=micro_batches,
                                       stages=stages, stage_id=stage_id)
        full = list(iter(sched))
        assert len(full) == 2 * (micro_batches + stages - 1)
        fwd = sum(_count(c, schedule.ForwardPass) for c in full)
        bwd = sum(_count(c, schedule.BackwardPass) for c in full)
        assert fwd == micro_batches
        assert bwd == micro_batches
        last = full[-1]
        assert _count(last, schedule.ReduceTiedGrads) == 1
        assert _count(last, schedule.ReduceGrads) == 1
        assert _count(last, schedule.OptimizerStep) == 1
        # sends/recvs pair across all stages
        if stage_id > 0:
            assert sum(_count(c, schedule.RecvActivation) for c in full) == micro_batches
        if stage_id < stages - 1:
            assert sum(_count(c, schedule.SendActivation) for c in full) == micro_batches


def test_pipe_train_schedule_buffers():
    # steady-state buffer count shrinks toward the last stage
    sched0 = schedule.TrainSchedule(micro_batches=8, stages=4, stage_id=0)
    sched3 = schedule.TrainSchedule(micro_batches=8, stages=4, stage_id=3)
    assert sched0.num_pipe_buffers() >= sched3.num_pipe_buffers()
    assert sched3.num_pipe_buffers() == 2


def test_send_recv_pairing():
    """Stage s's SendActivation ticks must match stage s+1's RecvActivation
    ticks (barrier-atomicity of steps)."""
    stages, micro_batches = 3, 4
    per_stage = [list(iter(schedule.TrainSchedule(micro_batches=micro_batches,
                                                  stages=stages, stage_id=s)))
                 for s in range(stages)]
    for s in range(stages - 1):
        sends = [i for i, cmds in enumerate(per_stage[s])
                 if _count(cmds, schedule.SendActivation)]
        recvs = [i for i, cmds in enumerate(per_stage[s + 1])
                 if _count(cmds, schedule.RecvActivation)]
        assert len(sends) == len(recvs) == micro_batches
        # every send happens no later than the paired recv
        for snd, rcv in zip(sends, recvs):
            assert snd <= rcv


def test_dataparallel_schedule():
    sched = schedule.DataParallelSchedule(micro_batches=3, stages=1, stage_id=0)
    full = list(iter(sched))
    assert len(full) == 3
    assert _count(full[-1], schedule.ReduceGrads) == 1
    assert _count(full[-1], schedule.OptimizerStep) == 1
    assert sched.num_pipe_buffers() == 1
