"""Monitoring (tensorboard/JSONL scalars) + env report."""

import json
import os

import numpy as np

import deepspeed_tpu as deepspeed
from deepspeed_tpu.env_report import op_report
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.utils.monitor import TrainingMonitor

from .simple_model import SimpleModel, base_config, random_batches


def test_monitor_writes_scalars(tmp_path):
    mon = TrainingMonitor(True, str(tmp_path), "job")
    mon.write_scalars(10, {"Train/loss": 1.5, "Train/lr": 0.01})
    mon.write_scalars(20, {"Train/loss": 1.2, "Train/lr": 0.01})
    mon.close()
    lines = [json.loads(l) for l in
             open(tmp_path / "job" / "events.jsonl")]
    assert [l["step"] for l in lines] == [10, 20]
    assert lines[1]["Train/loss"] == 1.2
    # tensorboard event file exists when the writer is available
    tb_files = [f for f in os.listdir(tmp_path / "job")
                if f.startswith("events.out.tfevents")]
    assert tb_files, "no tensorboard event file written"


def test_monitor_disabled_is_noop(tmp_path):
    mon = TrainingMonitor(False, str(tmp_path), "job")
    mon.write_scalars(1, {"x": 1.0})
    mon.close()
    assert not (tmp_path / "job").exists()


def test_engine_tensorboard_wiring(tmp_path, cpu_devices):
    mesh = make_mesh({"data": 8}, devices=cpu_devices[:8])
    # monitor scalars follow the steps_per_print cadence (host-sync cost)
    config = base_config(steps_per_print=1,
                         tensorboard={"enabled": True,
                                      "output_path": str(tmp_path),
                                      "job_name": "unit"})
    engine, *_ = deepspeed.initialize(model=SimpleModel(16, nlayers=2),
                                      config=config, mesh=mesh)
    batch = random_batches(1, engine.train_micro_batch_size_per_gpu() * 8,
                           16, seed=0)[0]
    for _ in range(3):
        engine.train_batch(iter([batch]))
    lines = [json.loads(l) for l in
             open(tmp_path / "unit" / "events.jsonl")]
    assert len(lines) == 3
    assert all("Train/Samples/train_loss" in l for l in lines)
    assert all(np.isfinite(l["Train/Samples/train_loss"]) for l in lines)


def test_op_report_shape():
    rows = op_report()
    names = [r[0] for r in rows]
    assert "fused_adam" in names and "flash_attention" in names
    for name, ok, detail in rows:
        assert isinstance(ok, (bool, np.bool_)) and isinstance(detail, str)


def test_op_builder_registry():
    """Every registered op loads its entry point, and compatibility checks
    run without error (reference ALL_OPS / OpBuilder.load contract)."""
    from deepspeed_tpu.ops.op_builder import ALL_OPS, get_op_builder

    assert {"fused_adam", "flash_attention", "cpu_adam",
            "onebit_adam"} <= set(ALL_OPS)
    for name, builder in ALL_OPS.items():
        ok, detail = builder.compatibility()
        assert isinstance(detail, str)
        entry = builder.load()
        assert entry is not None, name
    assert get_op_builder("fused_adam").load().__name__ == "FusedAdam"
