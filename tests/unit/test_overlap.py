"""DSO7xx overlap-analyzer tests (``profiling/overlap.py`` +
``tools/dslint/programs.py`` rules + CLI surfaces).

Hand-written scheduled-HLO fixtures pin every layer: the instruction
/ computation parser, the roofline cost model and critical path, the
host/p2p transfer parser (the CommLedger satellite), the per-node
overlap classification (sync = serialized, async pair hidden by the
schedule window between ``-start`` and ``-done``), the DSO701/702/703
rules, the ``--sarif`` CLI output round-tripped against ``--json``,
and the bench-schema registration of the exposure receipts.

All figures below assume the v5e table in ``profiling/utilization.py``
(peak 197 TF/s, HBM 819 GB/s, ICI 45 GB/s, host 14 GB/s): an
f32[8192,8192] dot costs ~5.6 ms (flops-bound), the f32[1024,8192]
group-4 all-reduce moves 2·(3/4)·32 MiB ≈ 50 MiB of wire ≈ 1.1 ms.
"""

import io
import json
import os
from contextlib import redirect_stdout

from deepspeed_tpu.profiling import overlap as ov
from deepspeed_tpu.profiling.utilization import chip_specs
from deepspeed_tpu.tools.dslint import programs as dsp
from deepspeed_tpu.tools.dslint.cli import main as dslint_main

V5E = chip_specs("TPU v5e")

_HEADER = "HloModule fixture, is_scheduled=true\n\n"

_BIG_DOT = ("  %dot.big = f32[8192,8192]{1,0} dot(f32[8192,8192]{1,0} "
            "%p1, f32[8192,8192]{1,0} %p1), lhs_contracting_dims={1}, "
            "rhs_contracting_dims={0}\n")

# sync all-reduce next to an independent flops-bound dot: fully
# serialized by construction, with a >1 ms window available -> DSO701
SERIAL_AR = _HEADER + (
    "ENTRY %main.1 (p0: f32[1024,8192], p1: f32[8192,8192]) -> "
    "(f32[1024,8192], f32[8192,8192]) {\n"
    "  %p0 = f32[1024,8192]{1,0} parameter(0)\n"
    "  %p1 = f32[8192,8192]{1,0} parameter(1)\n"
    + _BIG_DOT +
    "  %all-reduce.1 = f32[1024,8192]{1,0} all-reduce("
    "f32[1024,8192]{1,0} %p0), replica_groups={{0,1,2,3}}\n"
    "  ROOT %tuple.1 = (f32[1024,8192]{1,0}, f32[8192,8192]{1,0}) "
    "tuple(%all-reduce.1, %dot.big)\n"
    "}\n")

# the same collective as an async pair with the dot scheduled inside
# the start/done window: hidden compute >= wire -> overlapped, clean
OVERLAPPED_AR = _HEADER + (
    "ENTRY %main.1 (p0: f32[1024,8192], p1: f32[8192,8192]) -> "
    "(f32[1024,8192], f32[8192,8192]) {\n"
    "  %p0 = f32[1024,8192]{1,0} parameter(0)\n"
    "  %p1 = f32[8192,8192]{1,0} parameter(1)\n"
    "  %all-reduce-start.1 = (f32[1024,8192]{1,0}, f32[1024,8192]{1,0})"
    " all-reduce-start(f32[1024,8192]{1,0} %p0), "
    "replica_groups={{0,1,2,3}}\n"
    + _BIG_DOT +
    "  %all-reduce-done.1 = f32[1024,8192]{1,0} all-reduce-done("
    "(f32[1024,8192]{1,0}, f32[1024,8192]{1,0}) %all-reduce-start.1)\n"
    "  ROOT %tuple.1 = (f32[1024,8192]{1,0}, f32[8192,8192]{1,0}) "
    "tuple(%all-reduce-done.1, %dot.big)\n"
    "}\n")

# async pair hiding only a smaller dot: 0 < hidden < wire -> partial
PARTIAL_AR = _HEADER + (
    "ENTRY %main.1 (p0: f32[1024,8192], p1: f32[4096,4096]) -> "
    "(f32[1024,8192], f32[4096,4096]) {\n"
    "  %p0 = f32[1024,8192]{1,0} parameter(0)\n"
    "  %p1 = f32[4096,4096]{1,0} parameter(1)\n"
    "  %all-reduce-start.1 = (f32[1024,8192]{1,0}, f32[1024,8192]{1,0})"
    " all-reduce-start(f32[1024,8192]{1,0} %p0), "
    "replica_groups={{0,1,2,3}}\n"
    "  %dot.small = f32[4096,4096]{1,0} dot(f32[4096,4096]{1,0} %p1, "
    "f32[4096,4096]{1,0} %p1), lhs_contracting_dims={1}, "
    "rhs_contracting_dims={0}\n"
    "  %all-reduce-done.1 = f32[1024,8192]{1,0} all-reduce-done("
    "(f32[1024,8192]{1,0}, f32[1024,8192]{1,0}) %all-reduce-start.1)\n"
    "  ROOT %tuple.1 = (f32[1024,8192]{1,0}, f32[4096,4096]{1,0}) "
    "tuple(%all-reduce-done.1, %dot.small)\n"
    "}\n")

# a host copy pair the scheduler left back-to-back, next to an
# independent dot -> DSO702 (the offload tax, HLO-visible form)
SERIAL_HOST_COPY = _HEADER + (
    "ENTRY %main.1 (p0: f32[8388608], p1: f32[8192,8192]) -> "
    "(f32[8388608], f32[8192,8192]) {\n"
    "  %p0 = f32[8388608]{0} parameter(0)\n"
    "  %p1 = f32[8192,8192]{1,0} parameter(1)\n"
    "  %copy-start.1 = (f32[8388608]{0:S(5)}, f32[8388608]{0}, u32[]) "
    "copy-start(f32[8388608]{0} %p0)\n"
    "  %copy-done.1 = f32[8388608]{0:S(5)} copy-done("
    "(f32[8388608]{0:S(5)}, f32[8388608]{0}, u32[]) %copy-start.1)\n"
    + _BIG_DOT +
    "  ROOT %tuple.1 = (f32[8388608]{0:S(5)}, f32[8192,8192]{1,0}) "
    "tuple(%copy-done.1, %dot.big)\n"
    "}\n")

# pure-compute module for critical-path / declared-stream tests
COMPUTE_ONLY = _HEADER + (
    "ENTRY %main.1 (p0: f32[4096,4096]) -> f32[4096,4096] {\n"
    "  %p0 = f32[4096,4096]{1,0} parameter(0)\n"
    "  %dot.1 = f32[4096,4096]{1,0} dot(f32[4096,4096]{1,0} %p0, "
    "f32[4096,4096]{1,0} %p0), lhs_contracting_dims={1}, "
    "rhs_contracting_dims={0}\n"
    "  %dot.2 = f32[4096,4096]{1,0} dot(f32[4096,4096]{1,0} %dot.1, "
    "f32[4096,4096]{1,0} %p0), lhs_contracting_dims={1}, "
    "rhs_contracting_dims={0}\n"
    "  %dot.3 = f32[4096,4096]{1,0} dot(f32[4096,4096]{1,0} %p0, "
    "f32[4096,4096]{1,0} %p0), lhs_contracting_dims={1}, "
    "rhs_contracting_dims={0}\n"
    "  ROOT %tuple.1 = (f32[4096,4096]{1,0}, f32[4096,4096]{1,0}) "
    "tuple(%dot.2, %dot.3)\n"
    "}\n")


def _ar_wire_seconds():
    # f32[1024,8192] = 32 MiB; ring all-reduce over group 4 moves
    # 2*(3/4) of it; ICI 45 GB/s
    payload = 1024 * 8192 * 4
    return 2 * payload * 3 // 4 / (V5E["ici_gbps"] * 1e9)


def _dot_seconds(n):
    return 2 * n ** 3 / (V5E["peak_tflops"] * 1e12)


# ------------------------------------------------------------ parsing
def test_parse_computations_and_instructions():
    comps, entry, scheduled = ov.parse_hlo_computations(SERIAL_AR)
    assert scheduled and entry == "main.1"
    main = comps["main.1"]
    assert [i.op for i in main.instructions] == [
        "parameter", "parameter", "dot", "all-reduce", "tuple"]
    ar = main.by_name["all-reduce.1"]
    assert "%p0" in ar.operands and "replica_groups" in ar.attrs


def test_parse_hlo_transfers_and_summary():
    hlo = (
        "  %copy-start.1 = (f32[1024]{0:S(5)}, f32[1024]{0}, u32[]) "
        "copy-start(f32[1024]{0} %a)\n"
        "  %copy-done.1 = f32[1024]{0:S(5)} copy-done(%copy-start.1)\n"
        "  %copy-start.2 = (f32[256]{0}, f32[256]{0}, u32[]) "
        "copy-start(f32[256]{0} %b)\n"
        "  %send.1 = (f32[512]{0}, u32[], token[]) send(f32[512]{0} "
        "%c, token[] %tok), channel_id=1, is_host_transfer=true\n"
        "  %send-done.1 = token[] send-done(%send.1), channel_id=1\n"
        "  %recv.1 = (f32[2048]{0}, u32[], token[]) recv(token[] "
        "%tok2), channel_id=2\n"
        "  %recv-done.1 = (f32[2048]{0}, token[]) recv-done(%recv.1)\n")
    recs = ov.parse_hlo_transfers(hlo)
    # -done halves never double-count; the async result tuple takes its
    # LARGEST element, not the sum
    assert [(r["op"], r["bytes"], r["host"]) for r in recs] == [
        ("copy-start", 4096, True),    # S(5): a host DMA
        ("copy-start", 1024, False),   # device-local async copy
        ("send", 2048, True),          # is_host_transfer=true
        ("recv", 8192, False),         # device point-to-point
    ]
    assert ov.transfer_summary(recs) == {
        "host_transfers": 2, "host_transfer_bytes": 4096 + 2048,
        "p2p_transfers": 1, "p2p_transfer_bytes": 8192}


def test_critical_path_vs_total_compute():
    s = ov.analyze_hlo(COMPUTE_ONLY, device_kind="TPU v5e")
    d = _dot_seconds(4096)
    # three equal dots, two chained: cp = 2 dots, compute total = 3
    assert abs(s["compute_seconds"] - 3 * d) / d < 0.1
    assert abs(s["critical_path_seconds"] - 2 * d) / d < 0.1
    assert s["wire_seconds"] == 0 and s["overlap_fraction"] == 1.0


def test_called_computations_are_not_double_counted():
    """A fusion body's cost is charged at the call site (whose roofline
    folds the body flops in) — summing the body computation again would
    report ~2x compute for fully-fused programs."""
    hlo = _HEADER + (
        "%fused_computation (param_0: f32[4096,4096]) -> "
        "f32[4096,4096] {\n"
        "  %param_0 = f32[4096,4096]{1,0} parameter(0)\n"
        "  ROOT %dot.f = f32[4096,4096]{1,0} dot(f32[4096,4096]{1,0} "
        "%param_0, f32[4096,4096]{1,0} %param_0), "
        "lhs_contracting_dims={1}, rhs_contracting_dims={0}\n"
        "}\n\n"
        "ENTRY %main.1 (p0: f32[4096,4096]) -> f32[4096,4096] {\n"
        "  %p0 = f32[4096,4096]{1,0} parameter(0)\n"
        "  ROOT %fusion.1 = f32[4096,4096]{1,0} fusion("
        "f32[4096,4096]{1,0} %p0), kind=kLoop, "
        "calls=%fused_computation\n"
        "}\n")
    s = ov.analyze_hlo(hlo, device_kind="TPU v5e")
    d = _dot_seconds(4096)
    assert abs(s["compute_seconds"] - d) / d < 0.1
    assert abs(s["critical_path_seconds"] - d) / d < 0.1


# --------------------------------------------------- classification
def test_sync_collective_is_serialized_with_window():
    s = ov.analyze_hlo(SERIAL_AR, total_devices=4, device_kind="TPU v5e")
    assert s["collectives"] == {"total": 1, "overlapped": 0,
                                "partially_exposed": 0, "serialized": 1}
    (node,) = s["nodes"]
    assert node["classification"] == ov.SERIALIZED
    assert abs(node["seconds"] - _ar_wire_seconds()) < 1e-6
    # the big dot is independent of the all-reduce: its ~5.6 ms is the
    # available window
    assert node["window_seconds"] > ov.DSO701_MIN_WINDOW_SECONDS
    assert s["exposed_wire_seconds"] == s["wire_seconds"] > 0
    assert s["overlap_fraction"] == 0.0


def test_async_pair_fully_hidden_is_overlapped():
    s = ov.analyze_hlo(OVERLAPPED_AR, total_devices=4,
                       device_kind="TPU v5e")
    assert s["collectives"]["overlapped"] == 1
    assert s["exposed_wire_seconds"] == 0.0
    assert s["overlap_fraction"] == 1.0
    # the hidden wire must not stretch the critical path beyond the
    # compute that hides it (start issues at t~0, dot covers the wire)
    assert s["critical_path_seconds"] < _ar_wire_seconds() + \
        _dot_seconds(8192)


def test_async_pair_partially_hidden():
    s = ov.analyze_hlo(PARTIAL_AR, total_devices=4, device_kind="TPU v5e")
    assert s["collectives"]["partially_exposed"] == 1
    (node,) = s["nodes"]
    hidden = _dot_seconds(4096)
    assert abs(node["hidden_seconds"] - hidden) / hidden < 0.1
    assert 0 < s["exposed_wire_seconds"] < s["wire_seconds"]
    assert 0.0 < s["overlap_fraction"] < 1.0


def test_serialized_host_copy_and_declared_stream():
    s = ov.analyze_hlo(SERIAL_HOST_COPY, device_kind="TPU v5e")
    assert s["host_transfers"]["serialized"] == 1
    (node,) = s["nodes"]
    assert node["kind"] == ov.KIND_HOST and node["source"] == "hlo"
    assert node["window_seconds"] > 0  # the dot could have hidden it
    # a DECLARED stream (engine host_state_bytes_per_step) larger than
    # what the HLO accounts for adds the residual as one serialized
    # node whose window is the whole program's compute
    declared = 8388608 * 4 + (32 << 20)
    s2 = ov.analyze_hlo(SERIAL_HOST_COPY, device_kind="TPU v5e",
                        declared_host_wire_bytes=declared)
    extra = [n for n in s2["nodes"] if n["source"] == "declared"]
    assert len(extra) == 1 and extra[0]["wire_bytes"] == 32 << 20
    assert extra[0]["window_seconds"] == s2["compute_seconds"]
    # and a declared stream already covered by HLO transfers adds none
    s3 = ov.analyze_hlo(SERIAL_HOST_COPY, device_kind="TPU v5e",
                        declared_host_wire_bytes=1024)
    assert not [n for n in s3["nodes"] if n["source"] == "declared"]


def test_analysis_is_deterministic():
    a = ov.analyze_hlo(SERIAL_HOST_COPY, device_kind="TPU v5e",
                       declared_host_wire_bytes=123456)
    b = ov.analyze_hlo(SERIAL_HOST_COPY, device_kind="TPU v5e",
                       declared_host_wire_bytes=123456)
    assert a == b


# ------------------------------------------------------- DSO7x rules
def _artifact(hlo, name="fix", **kw):
    kw.setdefault("mesh_axes", {"data": 4})
    kw.setdefault("device_kind", "TPU v5e")
    return dsp.ProgramArtifact(name=name, hlo=hlo, **kw)


def rule_ids(diags):
    return sorted(d.rule_id for d in diags)


def test_dso701_serialized_collective_with_window():
    diags = dsp.verify_program(_artifact(SERIAL_AR))
    assert rule_ids(diags) == ["DSO701"]
    assert "independent compute" in diags[0].message


def test_overlapped_program_is_clean():
    assert dsp.verify_program(_artifact(OVERLAPPED_AR)) == []
    # partial exposure is not flagged either (DSO701 is about FULLY
    # serialized collectives; the exposure metric rides the receipts)
    assert dsp.verify_program(_artifact(PARTIAL_AR)) == []


def test_dso702_serialized_host_transfer():
    diags = dsp.verify_program(_artifact(SERIAL_HOST_COPY))
    assert rule_ids(diags) == ["DSO702"]
    assert "exposed_wire_seconds=" in diags[0].message
    # declared-stream form (no HLO transfer ops at all, offload tax
    # known from the engine's wire accounting)
    diags = dsp.verify_program(_artifact(
        COMPUTE_ONLY, host_state_wire_bytes=64 << 20))
    assert rule_ids(diags) == ["DSO702"]
    assert "declared" in diags[0].message


def test_dso703_overlap_model_drift():
    fresh = dsp.program_overlap(_artifact(SERIAL_AR))
    ok = _artifact(SERIAL_AR, comm={"overlap": {
        "wire_seconds": fresh["wire_seconds"],
        "exposed_wire_seconds": fresh["exposed_wire_seconds"],
        "collectives": {"total": 1}, "host_transfers": {"total": 0}}})
    assert "DSO703" not in rule_ids(dsp.verify_program(ok))
    drifted = _artifact(SERIAL_AR, comm={"overlap": {
        "wire_seconds": fresh["wire_seconds"] * 3,
        "exposed_wire_seconds": fresh["exposed_wire_seconds"],
        "collectives": {"total": 2}, "host_transfers": {"total": 0}}})
    diags = dsp.verify_program(drifted)
    assert "DSO703" in rule_ids(diags)
    msg = next(d.message for d in diags if d.rule_id == "DSO703")
    assert "wire_seconds" in msg and "collectives 2 -> 1" in msg


def test_header_only_artifact_has_no_overlap_claim():
    art = _artifact("HloModule m, entry_computation_layout={...}\n")
    assert dsp.program_overlap(art) is None
    assert dsp.verify_program(art) == []


def test_rule_checks_see_past_the_telemetry_node_cap():
    """The telemetry event caps the node list at 32, but the rule
    checks must see EVERY node: a program with > 32 serialized
    collectives plus a declared host stream (appended LAST) still
    fires DSO702."""
    body = ["  %p0 = f32[1024,8192]{1,0} parameter(0)",
            "  %p1 = f32[8192,8192]{1,0} parameter(1)", _BIG_DOT.rstrip()]
    for i in range(40):
        body.append(
            f"  %all-reduce.{i} = f32[1024,8192]{{1,0}} all-reduce("
            f"f32[1024,8192]{{1,0}} %p0), replica_groups={{{{0,1,2,3}}}}")
    body.append("  ROOT %tuple.1 = (f32[1024,8192]{1,0}) "
                "tuple(%all-reduce.0)")
    hlo = _HEADER + ("ENTRY %main.1 (p0: f32[1024,8192], "
                     "p1: f32[8192,8192]) -> (f32[1024,8192]) {\n"
                     + "\n".join(body) + "\n}\n")
    art = _artifact(hlo, name="train_step",
                    host_state_wire_bytes=64 << 20)
    summary = dsp.program_overlap(art)
    assert summary["collectives"]["total"] == 40
    assert summary["nodes_truncated"] == 0  # untruncated for the rules
    assert len(summary["nodes"]) == 41
    ids = rule_ids(dsp.verify_program(art))
    assert "DSO702" in ids and "DSO701" in ids
    # the telemetry-facing default DOES truncate (event size bound)
    capped = ov.analyze_hlo(hlo, total_devices=4, device_kind="TPU v5e",
                            declared_host_wire_bytes=64 << 20)
    assert len(capped["nodes"]) == 32 and capped["nodes_truncated"] == 9
    assert capped["collectives"]["total"] == 40  # buckets never truncate


# ----------------------------------------------- CLI: sarif + ratchet
def _write_run_dir(tmp_path, hlo, name="fix", **side_extra):
    progdir = tmp_path / "programs"
    progdir.mkdir(parents=True, exist_ok=True)
    (progdir / f"{name}.hlo").write_text(hlo)
    side = {"artifact_schema_version": 1, "program": name,
            "hlo_file": f"{name}.hlo", "mesh_axes": {"data": 4},
            "device_kind": "TPU v5e"}
    side.update(side_extra)
    (progdir / f"{name}.json").write_text(json.dumps(side))
    return tmp_path


def test_sarif_round_trips_against_json(tmp_path):
    run_dir = _write_run_dir(tmp_path / "run", SERIAL_AR)
    # a second program whose donation verdict downgrades (aliases in
    # the header, alias bytes 0): an INFO-severity DSP602 — must emit
    # as a note-level SARIF result and never count as active
    _write_run_dir(
        tmp_path / "run",
        "HloModule m, input_output_alias={ {0}: (0, {}, may-alias) }, "
        "entry_computation_layout={...}\n",
        name="downgraded", donate_argnums=[0], alias_size_in_bytes=0)
    jout, sout = tmp_path / "r.json", tmp_path / "r.sarif"
    src = tmp_path / "clean.py"
    src.write_text("x = 1\n")
    with redirect_stdout(io.StringIO()):
        rc = dslint_main([str(src), "--programs", str(run_dir),
                          "--json", str(jout), "--sarif", str(sout)])
    assert rc == 1  # the DSO701 warning
    jrep = json.loads(jout.read_text())
    sarif = json.loads(sout.read_text())
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "dslint"
    rules = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"DSO701", "DSO702", "DSO703", "DSP601"} <= rules
    # the round-trip invariant: unsuppressed error/warning results ==
    # --json violations; info results ride along as notes
    active = [r for r in run["results"]
              if not r.get("suppressions")
              and r["level"] in ("error", "warning")]
    assert len(active) == jrep["violations"] == 1
    (res,) = active
    assert res["ruleId"] == "DSO701" and res["level"] == "warning"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("fix.hlo")
    assert loc["region"]["startLine"] == 1
    notes = [r for r in run["results"] if r["level"] == "note"]
    assert [r["ruleId"] for r in notes] == ["DSP602"]
    assert not notes[0].get("suppressions")


def test_sarif_marks_baselined_findings_external(tmp_path):
    run_dir = _write_run_dir(tmp_path / "run", SERIAL_AR)
    baseline = tmp_path / "baseline.json"
    with redirect_stdout(io.StringIO()):
        assert dslint_main(["--programs", str(run_dir), "--baseline",
                            str(baseline), "--update-baseline"]) == 0
        sout = tmp_path / "r.sarif"
        rc = dslint_main(["--programs", str(run_dir), "--baseline",
                          str(baseline), "--sarif", str(sout)])
    assert rc == 0
    results = json.loads(sout.read_text())["runs"][0]["results"]
    assert len(results) == 1
    assert results[0]["suppressions"] == [{"kind": "external"}]


def test_program_baseline_key_covers_dso7(tmp_path):
    from deepspeed_tpu.tools.dslint.cli import baseline_key
    diags = dsp.verify_program(_artifact(SERIAL_AR, name="train_step"))
    assert baseline_key(diags[0]) == "<programs>|DSO701|train_step"


# --------------------------------------------------- receipts/schema
def test_overlap_fields_are_schema_registered():
    from deepspeed_tpu.tools.bench_schema import (threshold_for,
                                                  validate_record)

    rec = {"exposed_wire_seconds": 0.0012, "overlap_fraction": 0.0,
           "leg_zero2_exposed_wire_seconds": 0.0,
           "leg_zero2_overlap_fraction": 1.0,
           "offload_gpt2_large_exposed_wire_seconds": 0.08,
           "offload_gpt2_large_overlap_fraction": 0.1}
    assert validate_record(rec) == []
    assert threshold_for("exposed_wire_seconds") == ("lower", 0.25)
    assert threshold_for("overlap_fraction") == ("higher", 0.10)
    assert threshold_for("leg_pipe_exposed_wire_seconds") == \
        ("lower", 0.25)
    assert threshold_for("offload_gpt2_xl_overlap_fraction") == \
        ("higher", 0.10)


class _FakeCompiled:
    def __init__(self, hlo):
        self._hlo = hlo

    def as_text(self):
        return self._hlo

    def memory_analysis(self):
        return None


def test_window_cap_degrade_is_loud_not_clean(monkeypatch):
    """Past MAX_WINDOW_INSTRUCTIONS the independence bitsets degrade to
    unknown windows — the window-gated rules then CANNOT run, and that
    must surface as a DSP614 'unverified' warning, never as clean."""
    monkeypatch.setattr(ov, "MAX_WINDOW_INSTRUCTIONS", 3)
    art = _artifact(SERIAL_AR)
    ids = rule_ids(dsp.verify_program(art))
    assert "DSP614" in ids and "DSO701" not in ids
    msg = next(d.message for d in dsp.verify_program(_artifact(SERIAL_AR))
               if d.rule_id == "DSP614")
    assert "UNVERIFIED" in msg and "window" in msg
    # the declared stream carries its own window and stays flagged
    # even on over-cap programs
    ids2 = rule_ids(dsp.verify_program(_artifact(
        SERIAL_AR, name="train_step", host_state_wire_bytes=64 << 20)))
    assert "DSO702" in ids2 and "DSP614" in ids2


def test_ledger_transfer_fields_come_from_the_analysis_nodes():
    """One classification: the entry's host_transfer_bytes must equal
    the byte total of the overlap analysis' own KIND_HOST hlo-source
    nodes (the set the declared-residual subtraction uses)."""
    from deepspeed_tpu.profiling.comm import CommLedger

    ledger = CommLedger(enabled=True, mesh_axes={"data": 4})
    entry = ledger.record("fwd_bwd", _FakeCompiled(SERIAL_HOST_COPY))
    ovl = entry["overlap"]
    hlo_hosts = [n for n in ovl["nodes"]
                 if n["kind"] == ov.KIND_HOST and n["source"] == "hlo"]
    assert entry["host_transfers"] == len(hlo_hosts) == 1
    assert entry["host_transfer_bytes"] == \
        sum(n["wire_bytes"] for n in hlo_hosts) == 32 << 20
    assert ovl["hlo_transfer_summary"]["host_transfer_bytes"] == 32 << 20


def test_comm_ledger_records_transfers_and_overlap():
    from deepspeed_tpu.profiling.comm import CommLedger

    ledger = CommLedger(enabled=True, mesh_axes={"data": 4})
    ledger.overlap_context_fn = lambda: {
        "host_state_wire_bytes": 48 << 20, "device_kind": "TPU v5e"}
    entry = ledger.record("train_step", _FakeCompiled(SERIAL_HOST_COPY))
    # the S(5) copy-start is a host DMA: 8388608 f32 = 32 MiB
    assert entry["host_transfers"] == 1
    assert entry["host_transfer_bytes"] == 32 << 20
    assert entry["p2p_transfers"] == 0
    ovl = entry["overlap"]
    # declared 48 MiB minus the 32 MiB the HLO accounts for: one extra
    # 16 MiB declared-stream node (train_step IS an update program)
    declared = [n for n in ovl["nodes"] if n["source"] == "declared"]
    assert len(declared) == 1 and declared[0]["wire_bytes"] == 16 << 20
    assert ovl["exposed_wire_seconds"] > 0
    # a NON-update program never carries the declared stream
    entry2 = ledger.record("fwd_bwd", _FakeCompiled(SERIAL_HOST_COPY))
    assert not [n for n in entry2["overlap"]["nodes"]
                if n["source"] == "declared"]


def test_step_overlap_stepwise_aggregation():
    from deepspeed_tpu.profiling.comm import CommLedger

    ledger = CommLedger(enabled=True, mesh_axes={"data": 4})
    ledger.record("fwd_bwd", _FakeCompiled(SERIAL_AR))
    ledger.record("apply_update", _FakeCompiled(COMPUTE_ONLY))
    step = ledger.step_overlap(grad_accumulation_steps=2)
    single = ledger.entry("fwd_bwd")["overlap"]
    assert step["program"] == "stepwise"
    assert abs(step["wire_seconds"] - 2 * single["wire_seconds"]) < 1e-9
    assert step["exposed_wire_seconds"] == step["wire_seconds"]
    assert step["overlap_fraction"] == 0.0


# --------------------------------- round 12: pipelined declared stream
def test_declared_stream_pipelined_schedule_lowers_exposure():
    """The declared-schedule model: the same declared bytes classify as
    one fully serialized node without a schedule, and as a
    fill/drain-exposed PARTIAL node under the double-buffered schedule
    — exposure strictly lower, wire identical (the pipeline moves the
    same one sweep each way)."""
    declared = 64 << 20
    base = ov.analyze_hlo(COMPUTE_ONLY, device_kind="TPU v5e",
                          declared_host_wire_bytes=declared)
    piped = ov.analyze_hlo(
        COMPUTE_ONLY, device_kind="TPU v5e",
        declared_host_wire_bytes=declared,
        declared_host_stream={"overlap": True, "chunks": 16,
                              "prefetch_depth": 2, "form": "scan"})
    assert piped["wire_seconds"] == base["wire_seconds"]
    assert piped["exposed_wire_seconds"] < base["exposed_wire_seconds"]
    assert piped["overlap_fraction"] > base["overlap_fraction"]
    (node,) = [n for n in piped["nodes"] if n["source"] == "declared"]
    # fill/drain (one chunk's round trip) is always exposed — the model
    # never claims a free lunch
    secs = declared / (V5E["host_gbps"] * 1e9)
    assert node["seconds"] - node["hidden_seconds"] >= secs / 16 - 1e-12
    assert node["classification"] == ov.PARTIAL
    # overlap: false (or a single chunk) keeps the serialized verdict
    ser = ov.analyze_hlo(
        COMPUTE_ONLY, device_kind="TPU v5e",
        declared_host_wire_bytes=declared,
        declared_host_stream={"overlap": False, "chunks": 16})
    (snode,) = [n for n in ser["nodes"] if n["source"] == "declared"]
    assert snode["classification"] == ov.SERIALIZED


def test_declared_stream_hiding_is_budgeted_by_compute():
    """Components share ONE compute budget: a declared stream whose
    steady state exceeds the program's compute stays mostly exposed —
    the model can never hide more wire than the program holds."""
    huge = 8 << 30  # ~0.57 s of host wire vs ~17 ms of compute
    s = ov.analyze_hlo(
        COMPUTE_ONLY, device_kind="TPU v5e",
        declared_host_wire_bytes=huge,
        declared_host_stream={"overlap": True, "chunks": 64,
                              "prefetch_depth": 2})
    (node,) = [n for n in s["nodes"] if n["source"] == "declared"]
    assert node["hidden_seconds"] <= s["compute_seconds"] + 1e-12
    assert node["hidden_seconds"] > 0


def test_declared_grad_stream_rides_the_schedule():
    """offload_gradients declares its spill+reload wire as a second
    component; it draws hiding budget AFTER the state stream and the
    two components never hide more than the program's compute."""
    sched = {"overlap": True, "chunks": 16, "prefetch_depth": 2,
             "grad_wire_bytes": 32 << 20}
    s = ov.analyze_hlo(COMPUTE_ONLY, device_kind="TPU v5e",
                       declared_host_wire_bytes=64 << 20,
                       declared_host_stream=sched)
    declared = [n for n in s["nodes"] if n["source"] == "declared"]
    assert sorted(n["op"] for n in declared) == ["grad-stream",
                                                 "host-stream"]
    hidden = sum(n["hidden_seconds"] for n in declared)
    assert 0 < hidden <= s["compute_seconds"] + 1e-12
    # without a schedule the grad stream is not declared at all (the
    # engine only emits grad_wire_bytes inside a schedule)
    s2 = ov.analyze_hlo(COMPUTE_ONLY, device_kind="TPU v5e",
                        declared_host_wire_bytes=64 << 20)
    assert [n["op"] for n in s2["nodes"]
            if n["source"] == "declared"] == ["host-stream"]


def test_dso702_not_fired_for_pipelined_declared_stream():
    """The pipelined schedule's declared node is PARTIAL, so DSO702
    (fully serialized host transfers) stays quiet — re-serializing
    (schedule overlap False) brings it back."""
    piped = _artifact(COMPUTE_ONLY, name="train_step",
                      host_state_wire_bytes=64 << 20,
                      host_stream_schedule={"overlap": True, "chunks": 8,
                                            "prefetch_depth": 2})
    assert "DSO702" not in rule_ids(dsp.verify_program(piped))
    ser = _artifact(COMPUTE_ONLY, name="train_step",
                    host_state_wire_bytes=64 << 20,
                    host_stream_schedule={"overlap": False, "chunks": 8})
    assert "DSO702" in rule_ids(dsp.verify_program(ser))


def test_schedule_survives_the_sidecar_round_trip(tmp_path):
    """The sidecar carries host_stream_schedule, so the offline
    ``--programs`` re-analysis prices the SAME schedule the live hook
    recorded (the DSO703 like-for-like contract)."""
    sched = {"overlap": True, "chunks": 8, "prefetch_depth": 2,
             "form": "scan", "groups": 2}
    art = _artifact(COMPUTE_ONLY, name="train_step",
                    host_state_wire_bytes=64 << 20,
                    host_stream_schedule=sched)
    side = art.sidecar()
    assert side["host_stream_schedule"] == sched
    run_dir = _write_run_dir(tmp_path / "run", COMPUTE_ONLY,
                             name="train_step",
                             host_state_wire_bytes=64 << 20,
                             host_stream_schedule=sched)
    (loaded,) = dsp.load_run_artifacts(str(run_dir))
    assert loaded.host_stream_schedule == sched
    assert (dsp.program_overlap(loaded)["exposed_wire_seconds"]
            == dsp.program_overlap(art)["exposed_wire_seconds"])


# ------------------------------------------- DSO704: exposure ratchet
def test_dso704_exposure_ratchet():
    """check_exposure_ratchet: growth past the recorded metric's
    tolerance fires; within-tolerance and unrecorded programs stay
    quiet."""
    art = _artifact(COMPUTE_ONLY, name="train_step",
                    host_state_wire_bytes=64 << 20,
                    host_stream_schedule={"overlap": True, "chunks": 8,
                                          "prefetch_depth": 2})
    metrics = dsp.exposure_metrics([art])
    key = dsp.exposure_metric_key("train_step")
    assert list(metrics) == [key] and metrics[key] > 0
    # within tolerance: quiet
    assert dsp.check_exposure_ratchet([art], metrics) == []
    # recorded figure far below current: DSO704 fires
    tight = {key: metrics[key] / 10.0}
    diags = dsp.check_exposure_ratchet([art], tight)
    assert rule_ids(diags) == ["DSO704"]
    assert "re-serializing" in diags[0].message
    # unrecorded program: the ratchet only tightens what was recorded
    assert dsp.check_exposure_ratchet(
        [_artifact(COMPUTE_ONLY, name="other",
                   host_state_wire_bytes=64 << 20)], metrics) == []


def test_cli_baseline_metrics_ratchet(tmp_path):
    """End-to-end: --update-baseline records the exposed-wire metric;
    a later run whose exposure grew past tolerance exits 1 with a
    DSO704 finding the violations baseline cannot absolve."""
    sched_on = {"overlap": True, "chunks": 8, "prefetch_depth": 2}
    run_on = _write_run_dir(tmp_path / "on", COMPUTE_ONLY,
                            name="train_step",
                            host_state_wire_bytes=64 << 20,
                            host_stream_schedule=sched_on)
    baseline = tmp_path / "baseline.json"
    with redirect_stdout(io.StringIO()):
        assert dslint_main(["--programs", str(run_on), "--baseline",
                            str(baseline), "--update-baseline"]) == 0
        assert dslint_main(["--programs", str(run_on), "--baseline",
                            str(baseline)]) == 0
    data = json.loads(baseline.read_text())
    key = dsp.exposure_metric_key("train_step")
    assert data["violations"] == {} and key in data["metrics"]
    # the regression: the same program re-dumped with a serialized
    # schedule — exposure grows ~8x past the 25% tolerance
    run_off = _write_run_dir(tmp_path / "off", COMPUTE_ONLY,
                             name="train_step",
                             host_state_wire_bytes=64 << 20,
                             host_stream_schedule={"overlap": False,
                                                   "chunks": 8})
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = dslint_main(["--programs", str(run_off), "--baseline",
                          str(baseline)])
    assert rc == 1
    out = buf.getvalue()
    assert "DSO704" in out and "re-serializing" in out


def test_declared_grad_stream_reduced_by_hlo_excess():
    """TPU lowerings can materialize the grad spill as real HLO host
    transfers; HLO-accounted bytes beyond the state declaration reduce
    the declared grad component so nothing is double-counted."""
    sched = {"overlap": True, "chunks": 8, "prefetch_depth": 2,
             "grad_wire_bytes": 32 << 20}
    # SERIAL_HOST_COPY carries one 32 MiB HLO host transfer; declare
    # 16 MiB of state -> 16 MiB of HLO excess absorbs half the grads
    s = ov.analyze_hlo(SERIAL_HOST_COPY, device_kind="TPU v5e",
                       declared_host_wire_bytes=16 << 20,
                       declared_host_stream=sched)
    grad = [n for n in s["nodes"] if n["op"] == "grad-stream"]
    assert len(grad) == 1 and grad[0]["wire_bytes"] == 16 << 20
    # no HLO transfers at all (CPU form): the full grad declaration
    s2 = ov.analyze_hlo(COMPUTE_ONLY, device_kind="TPU v5e",
                        declared_host_wire_bytes=16 << 20,
                        declared_host_stream=sched)
    (grad2,) = [n for n in s2["nodes"] if n["op"] == "grad-stream"]
    assert grad2["wire_bytes"] == 32 << 20
    # HLO excess >= grad declaration: the grad node disappears
    s3 = ov.analyze_hlo(SERIAL_HOST_COPY, device_kind="TPU v5e",
                        declared_host_wire_bytes=0,
                        declared_host_stream={**sched,
                                              "grad_wire_bytes": 1 << 20})
    assert not [n for n in s3["nodes"] if n["op"] == "grad-stream"]


def test_dso704_ratchet_has_an_absolute_floor():
    """A recorded metric of 0.0 must not turn cost-model epsilons into
    CI failures: the ceiling carries an absolute 10 µs floor."""
    art = _artifact(COMPUTE_ONLY, name="train_step",
                    host_state_wire_bytes=1 << 10,
                    host_stream_schedule={"overlap": True, "chunks": 64,
                                          "prefetch_depth": 2})
    cur = dsp.program_overlap(art)["exposed_wire_seconds"]
    assert 0 < cur < dsp.EXPOSED_WIRE_RATCHET_EPS
    key = dsp.exposure_metric_key("train_step")
    assert dsp.check_exposure_ratchet([art], {key: 0.0}) == []


# ---------------------------------- declared collective schedule (r14)
# a bucketed zero-2 exchange as CPU HLO shows it: sync reduce-scatters
# (one per bucket) + a sync all-gather, next to an independent
# flops-bound dot (the "rest of the backward")
BUCKETED_EXCHANGE = _HEADER + (
    "ENTRY %main.1 (p0: f32[1024,8192], p1: f32[8192,8192]) -> "
    "(f32[256,8192], f32[256,8192], f32[1024,8192], f32[8192,8192]) {\n"
    "  %p0 = f32[1024,8192]{1,0} parameter(0)\n"
    "  %p1 = f32[8192,8192]{1,0} parameter(1)\n"
    + _BIG_DOT +
    "  %reduce-scatter.1 = f32[256,8192]{1,0} reduce-scatter("
    "f32[1024,8192]{1,0} %p0), replica_groups={{0,1,2,3}}, "
    "dimensions={0}\n"
    "  %reduce-scatter.2 = f32[256,8192]{1,0} reduce-scatter("
    "f32[1024,8192]{1,0} %p0), replica_groups={{0,1,2,3}}, "
    "dimensions={0}\n"
    "  %all-gather.1 = f32[1024,8192]{1,0} all-gather("
    "f32[256,8192]{1,0} %reduce-scatter.1), "
    "replica_groups={{0,1,2,3}}, dimensions={0}\n"
    "  ROOT %tuple.1 = (f32[256,8192]{1,0}, f32[256,8192]{1,0}, "
    "f32[1024,8192]{1,0}, f32[8192,8192]{1,0}) tuple("
    "%reduce-scatter.1, %reduce-scatter.2, %all-gather.1, %dot.big)\n"
    "}\n")

_SCHED_ON = {"overlap": True, "rs_buckets": 2, "ag_buckets": 1}
_SCHED_OFF = {"overlap": False, "rs_buckets": 2, "ag_buckets": 1}


def test_declared_collective_schedule_pipelined_pricing():
    """overlap on: steady-state buckets hide up to the shared compute
    budget, fill/drain (one bucket's wire) stays exposed, nodes are
    re-sourced ``hlo+declared``; all-reduces / no-schedule runs are
    untouched."""
    base = ov.analyze_hlo(BUCKETED_EXCHANGE, total_devices=4,
                          device_kind="TPU v5e", max_nodes=None)
    on = ov.analyze_hlo(BUCKETED_EXCHANGE, total_devices=4,
                        device_kind="TPU v5e", max_nodes=None,
                        declared_collective_schedule=_SCHED_ON)
    assert on["exposed_wire_seconds"] < base["exposed_wire_seconds"]
    matching = [n for n in on["nodes"]
                if n["op"] in ("reduce-scatter", "all-gather")]
    assert matching and all(n["source"] == "hlo+declared"
                            for n in matching)
    # fill/drain floor: at least one bucket's wire stays exposed
    total = sum(n["seconds"] for n in matching)
    exposed = sum(n["seconds"] - n["hidden_seconds"] for n in matching)
    assert exposed >= total / len(matching) * (1 - 1e-9)
    # the hiding never exceeds the program's compute
    hidden = sum(n["hidden_seconds"] for n in matching)
    assert hidden <= on["compute_seconds"] + 1e-12
    # no node fully serialized any more -> DSO701 stays quiet
    assert dsp.verify_program(_artifact(
        BUCKETED_EXCHANGE, collective_schedule=_SCHED_ON)) == []


def test_declared_collective_schedule_serialized_control():
    """overlap off: exposure unchanged (everything stays serialized)
    but the POTENTIAL window is recorded and DSO701 fires — the
    engine declared a bucketed schedule could hide this exchange."""
    base = ov.analyze_hlo(BUCKETED_EXCHANGE, total_devices=4,
                          device_kind="TPU v5e", max_nodes=None)
    off = ov.analyze_hlo(BUCKETED_EXCHANGE, total_devices=4,
                         device_kind="TPU v5e", max_nodes=None,
                         declared_collective_schedule=_SCHED_OFF)
    assert off["exposed_wire_seconds"] == base["exposed_wire_seconds"]
    matching = [n for n in off["nodes"]
                if n["op"] in ("reduce-scatter", "all-gather")]
    potential = off["compute_seconds"] * 2 / 3  # (B-1)/B over 3 buckets
    for n in matching:
        assert n["source"] == "hlo+declared"
        assert n["classification"] == ov.SERIALIZED
        assert n["window_seconds"] >= potential * (1 - 1e-9)
    diags = dsp.verify_program(_artifact(
        BUCKETED_EXCHANGE, collective_schedule=_SCHED_OFF))
    assert rule_ids(diags) == ["DSO701"]
    assert "overlap_comm would bucket" in diags[0].message


def test_declared_collective_schedule_ignores_other_collectives():
    """The schedule re-prices only reduce-scatter/all-gather: a sync
    all-reduce (loss pmean) keeps its HLO classification, window rules
    and all."""
    on = ov.analyze_hlo(SERIAL_AR, total_devices=4,
                        device_kind="TPU v5e", max_nodes=None,
                        declared_collective_schedule=_SCHED_ON)
    ar = [n for n in on["nodes"] if n["op"] == "all-reduce"]
    assert ar and ar[0]["source"] == "hlo" and (
        ar[0]["classification"] == ov.SERIALIZED)


def test_collective_schedule_sidecar_roundtrip(tmp_path):
    art = _artifact(BUCKETED_EXCHANGE, name="train_step",
                    collective_schedule=_SCHED_ON)
    progdir = tmp_path / "programs"
    progdir.mkdir()
    (progdir / "train_step.hlo").write_text(BUCKETED_EXCHANGE)
    (progdir / "train_step.json").write_text(
        json.dumps(art.sidecar()))
    loaded = dsp.load_run_artifacts(str(tmp_path))
    assert loaded[0].collective_schedule == _SCHED_ON
    # and the offline re-analysis agrees with the live one (DSO703's
    # like-with-like contract)
    assert dsp.program_overlap(loaded[0])["exposed_wire_seconds"] == (
        ov.analyze_hlo(BUCKETED_EXCHANGE, total_devices=4,
                       device_kind="TPU v5e", max_nodes=None,
                       declared_collective_schedule=_SCHED_ON)[
            "exposed_wire_seconds"])


def test_comm_exposure_metric_keys_and_ratchet():
    """The baseline records the collective exposure under its OWN key
    (comm_exposed_wire_seconds — the offload host-stream metric for a
    same-named program must not collide), only for OVERLAPPED
    schedules; the DSO704 ratchet reads it back."""
    on = _artifact(BUCKETED_EXCHANGE, name="train_step",
                   collective_schedule=_SCHED_ON)
    off = _artifact(BUCKETED_EXCHANGE, name="train_step",
                    collective_schedule=_SCHED_OFF)
    metrics = dsp.exposure_metrics([on])
    key = dsp.comm_exposure_metric_key("train_step")
    assert set(metrics) == {key}
    assert key != dsp.exposure_metric_key("train_step")
    # the serialized control records nothing (it exists to be worse)
    assert dsp.exposure_metrics([off]) == {}
    # ratchet: growth past tolerance trips DSO704 through the new key
    tight = {key: metrics[key] / 2.0}
    diags = dsp.check_exposure_ratchet([on], tight)
    assert rule_ids(diags) == ["DSO704"]
    assert not dsp.check_exposure_ratchet([on], metrics)
