"""Overlapped chunk streaming (round 12): the double-buffered pipeline
is a SCHEDULE change, not a numerics change.

The load-bearing contract: ``offload_overlap: on`` reorders when the
host↔device transfers are ISSUED (prefetch chunk k+1 while chunk k
updates, write-back overlapping the next fetch) but every chunk still
consumes the same host values with the same canonical stochastic-
rounding tags, so the overlapped and serialized schedules produce
BIT-IDENTICAL masters, optimizer state, and error-feedback residuals —
asserted here exactly (``assert_array_equal``, no tolerance) over ≥20
steps on the CPU-forced streamed path (``DS_OFFLOAD_FORCE_INJIT``), for
both reduced host-state forms:

- bf16 + stochastic rounding (the default wire-halving layout), and
- fp16 (m, v) + error feedback (the residual-carrying layout).

Also pinned: the canonical SR tags make the UNROLLED form's job order
issue-invariant (round-robin vs the sequential order the gpt2-xl scale
pathology guard switches to — PERF.md capacity ladder), and the engine
declares the schedule it actually built.
"""

import numpy as np
import pytest

import deepspeed_tpu as deepspeed
import deepspeed_tpu.runtime.zero.coordinator as coord
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.runtime.zero import stream

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 256
NLAYERS = 8
PARITY_STEPS = 20

BF16_SR = {"master": "bf16", "momentum": "bf16", "variance": "bf16"}
FP16_EF = {"momentum": "fp16", "variance": "fp16",
           "error_feedback": True}


@pytest.fixture
def force_injit(monkeypatch):
    monkeypatch.setenv("DS_OFFLOAD_FORCE_INJIT", "1")
    monkeypatch.setattr(coord, "HOST_GROUP_BYTES", 2 << 20)


def _engine(cpu_devices, overlap, uniform=True, state_dtype=None,
            prefetch_depth=None, offload_gradients=False):
    zero = {"stage": 2, "cpu_offload": True, "offload_chunk_mb": 1,
            "offload_uniform_chunks": uniform,
            "offload_overlap": overlap,
            "offload_gradients": offload_gradients}
    if state_dtype is not None:
        zero["offload_state_dtype"] = dict(state_dtype)
    if prefetch_depth is not None:
        zero["offload_prefetch_depth"] = prefetch_depth
    mesh = make_mesh({"data": 1}, devices=cpu_devices[:1])
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(HIDDEN, nlayers=NLAYERS),
        config=base_config(zero_optimization=zero), mesh=mesh)
    return engine


def _run_steps(engine, steps=PARITY_STEPS):
    batches = random_batches(steps, engine.train_micro_batch_size_per_gpu(),
                             HIDDEN, seed=0)
    return [float(np.asarray(engine.train_batch(iter([b]))))
            for b in batches]


def _state_snapshot(engine):
    """Every persistent training buffer, bit-for-bit: master (exact
    fp32 upcast of the storage dtype), flat optimizer leaves, scalars,
    and error-feedback residuals."""
    import jax

    snap = {"master": engine.flat.gather_master_unpadded(
        engine.state["master"])}
    for li, leaf in enumerate(jax.tree_util.tree_leaves(
            engine.state["opt"])):
        if type(leaf) is tuple:
            for gi, part in enumerate(leaf):
                snap[f"opt{li}g{gi}"] = np.asarray(jax.device_get(part))
        else:
            snap[f"opt{li}"] = np.asarray(jax.device_get(leaf))
    for name, buf in (engine.state.get("qres") or {}).items():
        parts = buf if type(buf) is tuple else (buf,)
        for gi, part in enumerate(parts):
            snap[f"qres.{name}.g{gi}"] = np.asarray(jax.device_get(part))
    return snap


def _assert_bit_identical(a, b):
    assert a.keys() == b.keys()
    for key in a:
        np.testing.assert_array_equal(a[key], b[key], err_msg=key)


@pytest.mark.parametrize("state_dtype", [BF16_SR, FP16_EF],
                         ids=["bf16_sr", "fp16_ef"])
def test_overlap_bit_identical_scan_form(force_injit, cpu_devices,
                                         state_dtype):
    """THE round-12 contract: 20 steps of the pipelined scan equal 20
    steps of the serialized scan bit-for-bit — masters, moments, step
    counters, and (fp16+EF) residuals, not just losses."""
    eng_on = _engine(cpu_devices, overlap=True, state_dtype=state_dtype)
    eng_off = _engine(cpu_devices, overlap=False, state_dtype=state_dtype)
    assert eng_on._offload_overlap and not eng_off._offload_overlap
    assert eng_on._offload_prefetch_depth >= 2
    assert eng_off._offload_prefetch_depth == 1
    losses_on = _run_steps(eng_on)
    losses_off = _run_steps(eng_off)
    assert losses_on == losses_off  # exact, not allclose
    _assert_bit_identical(_state_snapshot(eng_on),
                          _state_snapshot(eng_off))
    # fresh random batch per step (stronger parity coverage than one
    # repeated batch; training PROGRESS is test_offload_stream's job)
    assert np.all(np.isfinite(losses_on))


def test_overlap_bit_identical_deeper_prefetch(force_injit, cpu_devices):
    """Depth is a scheduling knob too: a 4-deep prefetch queue equals
    the serialized schedule bit-for-bit."""
    eng_d4 = _engine(cpu_devices, overlap=True, state_dtype=BF16_SR,
                     prefetch_depth=4)
    eng_off = _engine(cpu_devices, overlap=False, state_dtype=BF16_SR)
    assert eng_d4._offload_prefetch_depth == 4
    assert _run_steps(eng_d4, 6) == _run_steps(eng_off, 6)
    _assert_bit_identical(_state_snapshot(eng_d4),
                          _state_snapshot(eng_off))


def test_overlap_bit_identical_unrolled_form(force_injit, cpu_devices):
    """The unrolled (round-robin) form: overlap off serializes the
    token chain and issue order, and still matches bit-for-bit — the
    canonical SR tags are issue-order invariant."""
    eng_on = _engine(cpu_devices, overlap=True, uniform=False,
                     state_dtype=BF16_SR)
    eng_off = _engine(cpu_devices, overlap=False, uniform=False,
                      state_dtype=BF16_SR)
    assert not eng_on._offload_uniform
    assert _run_steps(eng_on, 6) == _run_steps(eng_off, 6)
    _assert_bit_identical(_state_snapshot(eng_on),
                          _state_snapshot(eng_off))


def test_overlap_composes_with_offload_gradients(force_injit,
                                                 cpu_devices):
    """The gradient spill's per-group token chains (the hot-start hook
    at the grad-flatten point) change scheduling only: parity with the
    serialized spill, and with fp32 state the schedules are exactly
    equal by construction."""
    eng_on = _engine(cpu_devices, overlap=True, offload_gradients=True)
    eng_off = _engine(cpu_devices, overlap=False, offload_gradients=True)
    assert eng_on._offload_grads and eng_off._offload_grads
    assert _run_steps(eng_on, 6) == _run_steps(eng_off, 6)
    _assert_bit_identical(_state_snapshot(eng_on),
                          _state_snapshot(eng_off))


def test_round_robin_auto_disables_past_breakpoint(force_injit,
                                                   cpu_devices,
                                                   monkeypatch):
    """The gpt2-xl scale pathology guard (PERF.md: 19.5 s/step
    round-robin vs 5.16 sequential at 37 chunks): past
    ROUND_ROBIN_MAX_CHUNKS the unrolled form issues group-sequentially.
    The order switch is observable (the one-shot log latch) and — the
    point of canonical SR tags — bit-identical to the interleaved
    order below the breakpoint."""
    eng_rr = _engine(cpu_devices, overlap=True, uniform=False,
                     state_dtype=BF16_SR)
    assert not getattr(eng_rr, "_rr_disabled_logged", False)
    monkeypatch.setattr(stream, "ROUND_ROBIN_MAX_CHUNKS", 1)
    eng_seq = _engine(cpu_devices, overlap=True, uniform=False,
                      state_dtype=BF16_SR)
    losses_seq = _run_steps(eng_seq, 6)
    assert eng_seq._rr_disabled_logged
    losses_rr = _run_steps(eng_rr, 6)
    assert losses_rr == losses_seq
    _assert_bit_identical(_state_snapshot(eng_rr),
                          _state_snapshot(eng_seq))


def test_engine_declares_the_schedule_it_built(force_injit, cpu_devices):
    """The DSO7xx receipt chain starts at the engine's declaration —
    it must describe the program actually traced."""
    eng = _engine(cpu_devices, overlap="auto", state_dtype=BF16_SR)
    sched = eng.host_stream_schedule()
    assert sched["overlap"] is True
    assert sched["form"] == "scan" and sched["prefetch_depth"] == 2
    assert sched["chunks"] >= 2 and sched["groups"] >= 1
    assert "grad_wire_bytes" not in sched  # no offload_gradients here
    ctx = eng.program_verify_context()
    assert ctx["host_stream_schedule"] == sched
    eng_g = _engine(cpu_devices, overlap=True, offload_gradients=True)
    sched_g = eng_g.host_stream_schedule()
    assert sched_g["grad_wire_bytes"] == (
        2 * eng_g.segments.rows * 1024 * 4)


def test_prefetch_depth_one_is_the_serialized_schedule(force_injit,
                                                       cpu_devices):
    """The documented knob contract: an explicit depth of 1 under
    "auto" selects the serialized control exactly like
    offload_overlap: false — it must not be silently clamped to 2."""
    eng = _engine(cpu_devices, overlap="auto", state_dtype=BF16_SR,
                  prefetch_depth=1)
    assert not eng._offload_overlap
    assert eng._offload_prefetch_depth == 1
    assert eng.host_stream_schedule()["overlap"] is False
    # and the contradiction (overlap FORCED true at depth 1) is loud
    with pytest.raises(ValueError, match="contradicts"):
        _engine(cpu_devices, overlap=True, state_dtype=BF16_SR,
                prefetch_depth=1)


def test_forced_overlap_without_streaming_raises(cpu_devices):
    """offload_overlap: true on a non-streaming (one-shot) offload
    config is a contradiction the engine must refuse loudly, not
    silently ignore."""
    mesh = make_mesh({"data": 1}, devices=cpu_devices[:1])
    cfg = base_config(zero_optimization={
        "stage": 2, "cpu_offload": True, "offload_overlap": True})
    with pytest.raises(ValueError, match="does not stream"):
        deepspeed.initialize(model=SimpleModel(32, nlayers=1),
                             config=cfg, mesh=mesh)


def test_config_rejects_bad_overlap_keys():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    with pytest.raises(ValueError, match="offload_overlap"):
        DeepSpeedConfig({"train_batch_size": 1, "zero_optimization": {
            "stage": 2, "cpu_offload": True, "offload_overlap": 1}})
    with pytest.raises(ValueError, match="offload_prefetch_depth"):
        DeepSpeedConfig({"train_batch_size": 1, "zero_optimization": {
            "stage": 2, "cpu_offload": True,
            "offload_prefetch_depth": 0}})
    with pytest.raises(ValueError, match="requires cpu_offload"):
        DeepSpeedConfig({"train_batch_size": 1, "zero_optimization": {
            "stage": 2, "offload_overlap": True}})
