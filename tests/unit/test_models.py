"""Model-family tests: tiny BERT/GPT-2 train end-to-end on the engine,
including tensor-parallel (data×model) meshes — the reference exercises
this with Megatron GPT-2 runs (``tests/model/Megatron_GPT2``)."""

import jax
import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.models import BertConfig, BertForPreTrainingTPU, GPT2Config, GPT2LMHeadTPU
from deepspeed_tpu.parallel import make_mesh

VOCAB = 128
SEQ = 32


def tiny_bert(remat=False):
    return BertForPreTrainingTPU(BertConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=SEQ,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0, remat=remat))


def tiny_gpt2(remat=False):
    return GPT2LMHeadTPU(GPT2Config(
        vocab_size=VOCAB, hidden_size=32, num_layers=2, num_heads=4,
        max_position_embeddings=SEQ, embd_dropout=0.0, attn_dropout=0.0,
        resid_dropout=0.0, remat=remat))


def bert_batch(rng, n):
    ids = rng.integers(0, VOCAB, size=(n, SEQ)).astype(np.int32)
    labels = np.where(rng.random((n, SEQ)) < 0.15, ids, -100).astype(np.int32)
    return {
        "input_ids": ids,
        "attention_mask": np.ones((n, SEQ), np.int32),
        "token_type_ids": np.zeros((n, SEQ), np.int32),
        "masked_lm_labels": labels,
        "next_sentence_labels": rng.integers(0, 2, size=(n,)).astype(np.int32),
    }


def gpt2_batch(rng, n):
    # learnable structure: consecutive token runs (next-token = current+1)
    starts = rng.integers(0, VOCAB, size=(n, 1))
    ids = (starts + np.arange(SEQ)[None, :]) % VOCAB
    return {"input_ids": ids.astype(np.int32)}


def run_engine(model, config, mesh, batch_fn, steps=4, seed=0):
    engine, *_ = deepspeed.initialize(model=model, config=config, mesh=mesh)
    rng = np.random.default_rng(seed)
    losses = []
    for _ in range(steps):
        b = batch_fn(rng, engine.train_micro_batch_size_per_gpu()
                     * engine.dp_world_size)
        losses.append(float(np.asarray(engine.train_batch(iter([b])))))
    return losses


@pytest.mark.parametrize("remat", [False, True])
@pytest.mark.slow
def test_bert_trains(cpu_devices, remat):
    mesh = make_mesh({"data": 4}, devices=cpu_devices[:4])
    config = {"train_batch_size": 8,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
              "zero_optimization": {"stage": 2}, "bf16": {"enabled": False}}
    losses = run_engine(tiny_bert(remat), config, mesh, bert_batch, steps=5)
    assert losses[-1] < losses[0]


def test_gpt2_trains(cpu_devices):
    mesh = make_mesh({"data": 4}, devices=cpu_devices[:4])
    config = {"train_batch_size": 8,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
    losses = run_engine(tiny_gpt2(), config, mesh, gpt2_batch, steps=5)
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_gpt2_tensor_parallel_parity(cpu_devices):
    """data×model mesh must match the data-only trajectory (Megatron-style
    TP correctness; reference relies on the external mpu for this)."""
    config = {"train_batch_size": 8,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
    mesh_dp = make_mesh({"data": 2}, devices=cpu_devices[:2])
    mesh_tp = make_mesh({"data": 2, "model": 2}, devices=cpu_devices[:4])
    l_dp = run_engine(tiny_gpt2(), config, mesh_dp, gpt2_batch, steps=3)
    l_tp = run_engine(tiny_gpt2(), config, mesh_tp, gpt2_batch, steps=3)
    np.testing.assert_allclose(l_tp, l_dp, rtol=2e-4)


@pytest.mark.slow
def test_bert_pld(cpu_devices):
    """Progressive layer drop wiring (engine injects pld_theta)."""
    mesh = make_mesh({"data": 2}, devices=cpu_devices[:2])
    config = {"train_batch_size": 4,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
              "progressive_layer_drop": {"enabled": True, "theta": 0.5,
                                         "gamma": 0.01}}
    model = BertForPreTrainingTPU(BertConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=SEQ,
        hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1))
    losses = run_engine(model, config, mesh, bert_batch, steps=3)
    assert all(np.isfinite(l) for l in losses)


def test_gpt2_eval_logits(cpu_devices):
    mesh = make_mesh({"data": 2}, devices=cpu_devices[:2])
    config = {"train_batch_size": 4,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
    engine, *_ = deepspeed.initialize(model=tiny_gpt2(), config=config, mesh=mesh)
    rng = np.random.default_rng(0)
    logits = engine.eval_batch(gpt2_batch(rng, 4))
    assert logits.shape == (4, SEQ, VOCAB)


def test_transformer_memory_knobs():
    """DeepSpeedTransformerConfig memory knobs (reference
    transformer.py:109-137): each adds a remat region without changing
    numerics."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.models.layers import TransformerLayer

    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 16, 32)),
                    jnp.float32)

    def run(**knobs):
        layer = TransformerLayer(32, 4, attn_dropout_ratio=0.0,
                                 hidden_dropout_ratio=0.0, **knobs)
        params = layer.init(jax.random.PRNGKey(0))
        out = layer.apply(params, x, deterministic=True)
        jx = jax.make_jaxpr(jax.grad(
            lambda p: layer.apply(p, x, deterministic=True)
            .astype(jnp.float32).sum()))(params)
        return np.asarray(out), str(jx).count("remat2")

    base_out, base_remats = run()
    assert base_remats == 0
    for knob in ("gelu_checkpoint", "attn_dropout_checkpoint",
                 "normalize_invertible"):
        out, remats = run(**{knob: True})
        assert remats > 0, knob
        np.testing.assert_allclose(out, base_out, rtol=1e-6, err_msg=knob)


@pytest.mark.slow
def test_bert_qa_head_trains():
    """SQuAD-style span head (reference BingBertSquad parity): loss is
    finite, decreases, and logits mode returns [b, s] pairs."""
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import BertConfig, BertForQuestionAnsweringTPU
    from deepspeed_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 1}, devices=jax.devices("cpu")[:1])
    cfg = BertConfig(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64)
    model = BertForQuestionAnsweringTPU(cfg)
    config = {"train_batch_size": 4, "steps_per_print": 10 ** 9,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
    engine, *_ = deepspeed.initialize(model=model, config=config, mesh=mesh)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 128, (4, 32)).astype(np.int32),
             "attention_mask": np.ones((4, 32), np.int32),
             "start_positions": rng.integers(0, 32, (4,)).astype(np.int32),
             "end_positions": rng.integers(0, 32, (4,)).astype(np.int32)}
    losses = [float(jax.device_get(engine.train_batch(iter([batch]))))
              for _ in range(8)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    logits = model.apply(engine.get_params(),
                         {k: batch[k] for k in ("input_ids", "attention_mask")},
                         train=False)
    assert logits[0].shape == (4, 32) and logits[1].shape == (4, 32)


@pytest.mark.slow
def test_bert_classifier_head_trains():
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import (BertConfig,
                                      BertForSequenceClassificationTPU)
    from deepspeed_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 1}, devices=jax.devices("cpu")[:1])
    cfg = BertConfig(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64)
    model = BertForSequenceClassificationTPU(cfg, num_labels=3)
    config = {"train_batch_size": 4, "steps_per_print": 10 ** 9,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
    engine, *_ = deepspeed.initialize(model=model, config=config, mesh=mesh)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 128, (4, 32)).astype(np.int32),
             "attention_mask": np.ones((4, 32), np.int32),
             "labels": rng.integers(0, 3, (4,)).astype(np.int32)}
    losses = [float(jax.device_get(engine.train_batch(iter([batch]))))
              for _ in range(8)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    logits = model.apply(engine.get_params(),
                         {k: batch[k] for k in ("input_ids", "attention_mask")},
                         train=False)
    assert logits.shape == (4, 3)


@pytest.mark.slow
def test_memory_knobs_preserve_loss():
    """gelu_checkpoint/attn_dropout_checkpoint/normalize_invertible change
    what is stored for backward, never the math (reference kernel knobs,
    ops/transformer/transformer.py:109-137)."""
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.parallel import make_mesh

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, VOCAB, (4, SEQ)).astype(np.int32),
             "attention_mask": np.ones((4, SEQ), np.int32),
             "masked_lm_labels": rng.integers(0, VOCAB, (4, SEQ)).astype(np.int32)}

    def losses(**knobs):
        mesh = make_mesh({"data": 1}, devices=jax.devices("cpu")[:1])
        cfg = BertConfig(vocab_size=VOCAB, hidden_size=64, num_hidden_layers=2,
                         num_attention_heads=4, max_position_embeddings=SEQ,
                         hidden_dropout_prob=0.0,
                         attention_probs_dropout_prob=0.0, **knobs)
        engine, *_ = deepspeed.initialize(
            model=BertForPreTrainingTPU(cfg),
            config={"train_batch_size": 4, "steps_per_print": 10 ** 9,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}},
            mesh=mesh)
        return [float(jax.device_get(engine.train_batch(iter([batch]))))
                for _ in range(3)]

    base = losses()
    knobbed = losses(gelu_checkpoint=True, attn_dropout_checkpoint=True,
                     normalize_invertible=True)
    np.testing.assert_allclose(base, knobbed, rtol=2e-5)


def test_bert_mlm_gather_head_loss_parity():
    """`max_predictions_per_seq` gathers labeled positions before the vocab
    projection (a pure-FLOPs saving); when every row's label count fits the
    budget, the loss must be bit-comparable to the full-head computation."""
    import jax.numpy as jnp

    cfg_kw = dict(vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
                  num_attention_heads=4, max_position_embeddings=SEQ,
                  hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    full = BertForPreTrainingTPU(BertConfig(**cfg_kw))
    gathered = BertForPreTrainingTPU(
        BertConfig(max_predictions_per_seq=8, **cfg_kw))
    params = full.init(jax.random.PRNGKey(0))

    rng = np.random.default_rng(3)
    b = bert_batch(rng, 4)
    # exactly 5 labeled positions per row (within the 8-position budget)
    ids = b["input_ids"]
    labels = np.full_like(ids, -100)
    for r in range(ids.shape[0]):
        pos = rng.permutation(SEQ)[:5]
        labels[r, pos] = ids[r, pos]
    b["masked_lm_labels"] = labels

    loss_full = full.apply(params, b, train=True)
    loss_gather = gathered.apply(params, b, train=True)
    np.testing.assert_allclose(np.asarray(loss_gather),
                               np.asarray(loss_full), rtol=1e-6)

    # rows with MORE labels than the budget keep the first n_pred (stable
    # top_k) — the loss stays finite and close, never NaN
    over = BertForPreTrainingTPU(BertConfig(max_predictions_per_seq=4,
                                            **cfg_kw))
    loss_over = over.apply(params, b, train=True)
    assert np.isfinite(np.asarray(loss_over))

    # inference without labels still returns full-sequence logits
    b_nolabel = {k: v for k, v in b.items() if k != "masked_lm_labels"}
    logits = gathered.apply(params, b_nolabel, train=False)
    assert logits.shape == (4, SEQ, VOCAB)


def test_bert_mlm_gather_composes_with_sparse():
    """max_predictions_per_seq must not crash non-dense attention cores:
    the final-layer query gather requires attn_impl='auto', so the sparse
    config (and by the same code path, ring) falls back to the
    post-encode head gather."""
    import jax.numpy as jnp

    from deepspeed_tpu.ops.sparse_attention import FixedSparsityConfig

    rng = np.random.default_rng(5)
    b = bert_batch(rng, 2)
    for impl, extra in (("sparse", dict(sparsity_config=FixedSparsityConfig(
            num_heads=4, block=8))), ):
        model = BertForPreTrainingTPU(BertConfig(
            vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=SEQ,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
            attn_impl=impl, max_predictions_per_seq=8, **extra))
        params = model.init(jax.random.PRNGKey(0))
        loss = model.apply(params, b, train=True)
        assert np.isfinite(np.asarray(loss))


def test_gpt2_chunked_lm_loss_matches_full():
    """loss_chunk computes exactly the full-logits loss without ever
    materializing [b, s, vocab]."""
    model_full = tiny_gpt2()
    cfg = GPT2Config(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                     num_heads=4, max_position_embeddings=SEQ,
                     embd_dropout=0.0, attn_dropout=0.0, resid_dropout=0.0,
                     loss_chunk=8)
    model_chunk = GPT2LMHeadTPU(cfg)
    params = model_full.init(jax.random.PRNGKey(2))
    rng = np.random.default_rng(7)
    ids = rng.integers(0, VOCAB, size=(2, SEQ)).astype(np.int32)
    labels = np.where(rng.random((2, SEQ)) < 0.8, ids, -100).astype(np.int32)
    batch = {"input_ids": ids, "labels": labels}
    loss_full = model_full.apply(params, batch, train=True)
    loss_chunk = model_chunk.apply(params, batch, train=True)
    np.testing.assert_allclose(np.asarray(loss_chunk), np.asarray(loss_full),
                               rtol=1e-6)
    # grads must match too (the chunked head has its own backward)
    g_full = jax.grad(lambda p: model_full.apply(p, batch, train=True))(params)
    g_chunk = jax.grad(lambda p: model_chunk.apply(p, batch, train=True))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g_full),
                    jax.tree_util.tree_leaves(g_chunk)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-5, atol=1e-7)
