"""Checkpoint save/resume tests (modeled on reference
``tests/unit/test_checkpointing.py`` — round-trips per wrapper and elastic
DP-degree changes, e.g. ``test_checkpoint_zero_optimizer:295``)."""

import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 16


def make_engine(config, cpu_devices, dp=8, seed=0):
    mesh = make_mesh({"data": dp}, devices=cpu_devices[:dp])
    model = SimpleModel(HIDDEN, nlayers=2)
    engine, *_ = deepspeed.initialize(model=model, config=config, mesh=mesh)
    return engine


def run_steps(engine, batches):
    losses = []
    for b in batches:
        losses.append(float(np.asarray(engine.train_batch(iter([b])))))
    return losses


@pytest.mark.parametrize("stage", [0, 2])
def test_checkpoint_roundtrip_loss_continuity(stage, cpu_devices, tmp_path):
    config = base_config(zero_optimization={"stage": stage})
    batches = random_batches(8, 16, HIDDEN, seed=11)

    e1 = make_engine(config, cpu_devices)
    run_steps(e1, batches[:4])
    e1.save_checkpoint(str(tmp_path), client_state={"note": "hello", "arr": [1, 2]})
    ref_losses = run_steps(e1, batches[4:])

    e2 = make_engine(config, cpu_devices)
    path, client = e2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert client["note"] == "hello"
    assert e2.global_steps == 4
    new_losses = run_steps(e2, batches[4:])
    np.testing.assert_allclose(new_losses, ref_losses, rtol=1e-5)


def test_elastic_dp_degree_change(cpu_devices, tmp_path):
    """Save under dp=8, resume under dp=4 (elastic ZeRO restore, reference
    ``stage2.py:1714-1841``)."""
    batches = random_batches(8, 16, HIDDEN, seed=7)
    cfg8 = base_config(zero_optimization={"stage": 2})
    e1 = make_engine(cfg8, cpu_devices, dp=8)
    run_steps(e1, batches[:4])
    e1.save_checkpoint(str(tmp_path))
    ref_losses = run_steps(e1, batches[4:])

    cfg4 = base_config(zero_optimization={"stage": 2})
    cfg4["train_batch_size"] = 16  # same global batch, dp=4 → micro 4
    e2 = make_engine(cfg4, cpu_devices, dp=4)
    e2.load_checkpoint(str(tmp_path))
    new_losses = run_steps(e2, batches[4:])
    np.testing.assert_allclose(new_losses, ref_losses, rtol=1e-5)


def test_load_without_optimizer_states(cpu_devices, tmp_path):
    config = base_config(zero_optimization={"stage": 1}, bf16={"enabled": True})
    e1 = make_engine(config, cpu_devices)
    run_steps(e1, random_batches(2, 16, HIDDEN))
    e1.save_checkpoint(str(tmp_path), tag="mytag")

    e2 = make_engine(config, cpu_devices)
    path, _ = e2.load_checkpoint(str(tmp_path), tag="mytag",
                                 load_optimizer_states=False)
    assert path is not None
    # weights restored even without optimizer state
    np.testing.assert_allclose(np.asarray(e2.get_master_params()),
                               np.asarray(e1.get_master_params()), rtol=1e-6)


def test_missing_checkpoint_returns_none(cpu_devices, tmp_path):
    e = make_engine(base_config(), cpu_devices)
    path, client = e.load_checkpoint(str(tmp_path))
    assert path is None and client is None
