"""Checkpoint save/resume tests (modeled on reference
``tests/unit/test_checkpointing.py`` — round-trips per wrapper and elastic
DP-degree changes, e.g. ``test_checkpoint_zero_optimizer:295``)."""

import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 16


def make_engine(config, cpu_devices, dp=8, seed=0):
    mesh = make_mesh({"data": dp}, devices=cpu_devices[:dp])
    model = SimpleModel(HIDDEN, nlayers=2)
    engine, *_ = deepspeed.initialize(model=model, config=config, mesh=mesh)
    return engine


def run_steps(engine, batches):
    losses = []
    for b in batches:
        losses.append(float(np.asarray(engine.train_batch(iter([b])))))
    return losses


@pytest.mark.parametrize("stage", [0, 2])
def test_checkpoint_roundtrip_loss_continuity(stage, cpu_devices, tmp_path):
    config = base_config(zero_optimization={"stage": stage})
    batches = random_batches(8, 16, HIDDEN, seed=11)

    e1 = make_engine(config, cpu_devices)
    run_steps(e1, batches[:4])
    e1.save_checkpoint(str(tmp_path), client_state={"note": "hello", "arr": [1, 2]})
    ref_losses = run_steps(e1, batches[4:])

    e2 = make_engine(config, cpu_devices)
    path, client = e2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert client["note"] == "hello"
    assert e2.global_steps == 4
    new_losses = run_steps(e2, batches[4:])
    np.testing.assert_allclose(new_losses, ref_losses, rtol=1e-5)


def test_elastic_dp_degree_change(cpu_devices, tmp_path):
    """Save under dp=8, resume under dp=4 (elastic ZeRO restore, reference
    ``stage2.py:1714-1841``)."""
    batches = random_batches(8, 16, HIDDEN, seed=7)
    cfg8 = base_config(zero_optimization={"stage": 2})
    e1 = make_engine(cfg8, cpu_devices, dp=8)
    run_steps(e1, batches[:4])
    e1.save_checkpoint(str(tmp_path))
    ref_losses = run_steps(e1, batches[4:])

    cfg4 = base_config(zero_optimization={"stage": 2})
    cfg4["train_batch_size"] = 16  # same global batch, dp=4 → micro 4
    e2 = make_engine(cfg4, cpu_devices, dp=4)
    e2.load_checkpoint(str(tmp_path))
    new_losses = run_steps(e2, batches[4:])
    np.testing.assert_allclose(new_losses, ref_losses, rtol=1e-5)


@pytest.mark.parametrize("offload", ["none", "save", "load"])
@pytest.mark.parametrize("load_stage", [2, 3])
@pytest.mark.parametrize("save_stage", [2, 3])
def test_cross_stage_checkpoint_matrix(save_stage, load_stage, offload,
                                       cpu_devices, tmp_path):
    """Round-20 cross-stage matrix: checkpoints are canonical unpadded
    fp32 (PR 14 pattern), so stage-2 and stage-3 engines restore each
    other BIT-exactly in both directions — across dp widths (save dp=4,
    load dp=2: elastic) and across the offload layout (the pinned-host
    flat master gathers/scatters through the same canonical form).
    Loss continuity after restore rides the same data."""
    def cfg(stage, off):
        zo = {"stage": stage, "overlap_comm": "auto"}
        if off:
            zo["cpu_offload"] = True
        return base_config(zero_optimization=zo)

    def canonical_master(engine):
        # the canonical unpadded fp32 vector — the checkpoint format,
        # independent of dp padding, bucket layout, or host grouping
        return np.asarray(engine.flat.gather_master_unpadded(
            engine.state["master"]))

    batches = random_batches(5, 16, HIDDEN, seed=3)
    e1 = make_engine(cfg(save_stage, offload == "save"), cpu_devices,
                     dp=1 if offload == "save" else 4)
    run_steps(e1, batches[:3])
    saved_master = canonical_master(e1)
    e1.save_checkpoint(str(tmp_path))
    ref = run_steps(e1, batches[3:])

    e2 = make_engine(cfg(load_stage, offload == "load"), cpu_devices,
                     dp=1 if offload == "load" else 2)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None
    np.testing.assert_array_equal(canonical_master(e2), saved_master)
    new = run_steps(e2, batches[3:])
    np.testing.assert_allclose(new, ref, rtol=2e-5)


def test_load_without_optimizer_states(cpu_devices, tmp_path):
    config = base_config(zero_optimization={"stage": 1}, bf16={"enabled": True})
    e1 = make_engine(config, cpu_devices)
    run_steps(e1, random_batches(2, 16, HIDDEN))
    e1.save_checkpoint(str(tmp_path), tag="mytag")

    e2 = make_engine(config, cpu_devices)
    path, _ = e2.load_checkpoint(str(tmp_path), tag="mytag",
                                 load_optimizer_states=False)
    assert path is not None
    # weights restored even without optimizer state
    np.testing.assert_allclose(np.asarray(e2.get_master_params()),
                               np.asarray(e1.get_master_params()), rtol=1e-6)


def test_missing_checkpoint_returns_none(cpu_devices, tmp_path):
    e = make_engine(base_config(), cpu_devices)
    path, client = e.load_checkpoint(str(tmp_path))
    assert path is None and client is None


def test_grouped_master_gather_scatter_roundtrip(cpu_devices):
    """Row-grouped offload state (tuple-of-host-buffers) gathers to the
    SAME unpadded checkpoint format as the single-buffer layout and
    scatters back into groups — the host-side half of the on-chip
    streamed-offload tests, runnable in the CI tier."""
    import jax.numpy as jnp

    from deepspeed_tpu.parallel import make_mesh
    from deepspeed_tpu.runtime.zero.coordinator import (FlatParamCoordinator,
                                                        split_rows)

    mesh = make_mesh({"data": 1}, devices=cpu_devices[:1])
    template = {"a": np.zeros((3, 1000), np.float32),
                "b": np.zeros((2048,), np.float32),
                "c": np.zeros((7,), np.float32)}
    flat = FlatParamCoordinator(mesh=mesh, params_template=template,
                                stage=2, dp_size=1)
    rng = np.random.default_rng(0)
    vals = {k: rng.normal(size=v.shape).astype(np.float32)
            for k, v in template.items()}
    master = flat.flatten_to_master(vals)
    unpadded_single = flat.gather_master_unpadded(master)

    # simulate the grouped layout (injit/TPU-only in production): split
    # the same buffer into row groups and run the tuple paths
    bounds = split_rows(flat.segments.rows, max(1, flat.segments.rows // 2))
    assert len(bounds) >= 2
    flat.host_group_bounds = bounds
    grouped = tuple(jnp.asarray(np.asarray(master)[r0:r0 + rc])
                    for r0, rc in bounds)
    unpadded_grouped = flat.gather_master_unpadded(grouped)
    np.testing.assert_array_equal(unpadded_grouped, unpadded_single)

    back = flat.scatter_master_from_unpadded(unpadded_grouped)
    assert isinstance(back, tuple) and len(back) == len(bounds)
    np.testing.assert_array_equal(
        np.concatenate([np.asarray(g) for g in back], axis=0),
        np.asarray(master))
