"""DP-elastic restore matrix: checkpoints written at dp in {2, 4, 8}
must restore onto dp in {1, 2, 4} — a DIFFERENT mesh shape — across the
offload layouts:

- ``plain``         ZeRO-2, state on device (flat rows padded per dp);
- ``offload``       in-jit streamed ZeRO-Offload with the host-buffer
                    GROUP layout forced (several row groups), so the
                    load path re-derives the pinned-host layout under
                    the new dp;
- ``offload_bf16``  reduced-precision host state with persistent
                    error-feedback residuals (``qres``) riding the
                    checkpoint.

Parity contract (``offload-state-dtype`` rules, docs/config.md): a
SAME-layout restore is bit-exact — master, flat optimizer leaves, and
residuals — regardless of the dp transition, because checkpoints store
the flat space unpadded in canonical fp32.  A cross-layout load (bf16+EF
checkpoint into an fp32 engine) folds residuals into the values; that
documented fold is asserted separately.
"""

import os

import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.checkpoint.snapshot import capture_engine_snapshot
from deepspeed_tpu.parallel import make_mesh

from .simple_model import SimpleModel, random_batches

HIDDEN = 128
NLAYERS = 4          # ~66k params -> 68 content rows -> padded 128: the
                     # forced small group size below yields MULTIPLE host
                     # row groups, the layout re-derivation under test
GLOBAL_BATCH = 16

MODES = {
    "plain": {"stage": 2},
    "offload": {"stage": 2, "cpu_offload": True},
    "offload_bf16": {"stage": 2, "cpu_offload": True,
                     "offload_state_dtype": {"master": "bf16",
                                             "momentum": "bf16",
                                             "variance": "bf16",
                                             "error_feedback": True}},
}

SAVE_DPS = (2, 4, 8)
LOAD_DPS = (1, 2, 4)


@pytest.fixture
def force_injit(monkeypatch):
    """Run the REAL in-jit streamed offload paths on CPU, with the host
    group size shrunk so this tiny model still splits into several row
    groups (the grouped-layout re-derivation is the point)."""
    from deepspeed_tpu.runtime.zero import coordinator as coord

    monkeypatch.setenv("DS_OFFLOAD_FORCE_INJIT", "1")
    monkeypatch.setattr(coord, "HOST_GROUP_BYTES", 1 << 18)
    monkeypatch.setattr(coord, "MAX_HOST_BUFFERS", 64)


def _build_engine(cpu_devices, dp, mode, steps=0, seed=0):
    mesh = make_mesh({"data": dp}, devices=cpu_devices[:dp])
    config = {
        "train_batch_size": GLOBAL_BATCH,
        "steps_per_print": 10 ** 9,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": dict(MODES[mode]),
    }
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(HIDDEN, nlayers=NLAYERS), config=config,
        mesh=mesh)
    for i, batch in enumerate(
            random_batches(steps, GLOBAL_BATCH, HIDDEN, seed=seed)):
        engine.train_batch(iter([batch]))
    return engine


def _host_states(engine):
    """Everything the checkpoint persists, gathered host-side in
    canonical form: {name: fp32 unpadded array} + the meta block."""
    snap = capture_engine_snapshot(engine, tag="probe")
    return snap.optim_states, snap.meta


def _grouped(engine):
    bounds, per_family = engine.flat.host_buffer_layout()
    return per_family


@pytest.mark.parametrize("mode", sorted(MODES))
def test_dp_elastic_restore_matrix(cpu_devices, tmp_path, mode,
                                   force_injit, request):
    """save at dp in {2,4,8} -> load at dp in {1,2,4}: every persisted
    state buffer restores BIT-EXACTLY onto the new mesh shape."""
    if mode == "plain":
        # plain mode must not depend on the offload test lever
        request.getfixturevalue("monkeypatch").delenv(
            "DS_OFFLOAD_FORCE_INJIT", raising=False)

    saved = {}
    for dp in SAVE_DPS:
        engine = _build_engine(cpu_devices, dp, mode, steps=1, seed=dp)
        save_dir = tmp_path / f"{mode}-dp{dp}"
        engine.save_checkpoint(str(save_dir), tag="m", sync=True)
        states, meta = _host_states(engine)
        if mode != "plain":
            assert _grouped(engine) > 1, (
                "grouped host layout did not engage; the matrix must "
                "exercise group re-derivation")
        saved[dp] = (str(save_dir), states, meta)
        engine.close()

    for load_dp in LOAD_DPS:
        engine = _build_engine(cpu_devices, load_dp, mode, steps=0)
        for save_dp in SAVE_DPS:
            save_dir, want_states, want_meta = saved[save_dp]
            path, _ = engine.load_checkpoint(save_dir, tag="m")
            assert path is not None, (mode, save_dp, load_dp)
            got_states, got_meta = _host_states(engine)
            assert set(got_states) == set(want_states)
            for name in sorted(want_states):
                np.testing.assert_array_equal(
                    got_states[name], want_states[name],
                    err_msg=f"{mode}: {name} not bit-exact across "
                            f"dp{save_dp}->dp{load_dp}")
            assert got_meta["global_steps"] == want_meta["global_steps"]
            assert got_meta["scale_state"] == want_meta["scale_state"]
            if mode == "offload_bf16":
                assert any(n.startswith("qres/") for n in got_states), (
                    "bf16+error_feedback checkpoint must carry residuals")
        engine.close()


def test_cross_layout_load_folds_residuals(cpu_devices, tmp_path,
                                           force_injit):
    """The documented non-bit-exact leg: a bf16+error-feedback
    checkpoint loaded into a PLAIN fp32 engine at a different dp folds
    each residual into its value (value = stored + qres, exact fp32
    add), so the fp32 engine resumes from the checkpoint's TRUE state,
    not its rounded storage."""
    engine = _build_engine(cpu_devices, 4, "offload_bf16", steps=2, seed=3)
    save_dir = tmp_path / "xlayout"
    engine.save_checkpoint(str(save_dir), tag="m", sync=True)
    states, _ = _host_states(engine)
    engine.close()

    engine2 = _build_engine(cpu_devices, 2, "plain", steps=0)
    path, _ = engine2.load_checkpoint(str(save_dir), tag="m")
    assert path is not None
    got, _ = _host_states(engine2)
    for name in ("master", "opt/.exp_avg", "opt/.exp_avg_sq"):
        res = states.get("qres/" + name.split("/")[-1].lstrip("."))
        want = states[name].astype(np.float32)
        if res is not None:
            want = want + res.astype(np.float32)
        np.testing.assert_array_equal(
            got[name], want,
            err_msg=f"cross-layout fold drifted for {name}")
    engine2.close()
