"""Uniform-chunk (O(1)-compile) streamed offload update.

These tests run the REAL in-jit chunk-streamed paths on the CPU backend
via ``DS_OFFLOAD_FORCE_INJIT=1`` (zero/coordinator.py): the program
structure — chunk slicing, group switch, scan carry, DUS write-back —
is identical to the TPU form; only the memory-space placements compile
as no-ops.  Numerics parity of the scan rewrite against both the
round-5 unrolled form and device-resident training is therefore CI-
checked, not TPU-only; ``tests/unit/test_tpu_offload.py`` remains the
real-chip gate for the pinned-host placement itself.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as deepspeed
import deepspeed_tpu.runtime.zero.coordinator as coord
from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
from deepspeed_tpu.ops.op_common import LANES
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.runtime.zero import stream

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 256
NLAYERS = 8


@pytest.fixture
def force_injit(monkeypatch):
    """CPU backend executes the in-jit streamed program structure, with
    row-grouping forced at toy scale (2 MB per host group)."""
    monkeypatch.setenv("DS_OFFLOAD_FORCE_INJIT", "1")
    monkeypatch.setattr(coord, "HOST_GROUP_BYTES", 2 << 20)


def _engine(cpu_devices, uniform, cpu_offload=True, offload_gradients=False,
            clip=0.0, chunk_mb=1):
    mesh = make_mesh({"data": 1}, devices=cpu_devices[:1])
    cfg = base_config(
        gradient_clipping=clip,
        zero_optimization={"stage": 2, "cpu_offload": cpu_offload,
                           "offload_chunk_mb": chunk_mb,
                           "offload_gradients": (offload_gradients
                                                 and cpu_offload),
                           "offload_uniform_chunks": uniform})
    engine, *_ = deepspeed.initialize(
        model=SimpleModel(HIDDEN, nlayers=NLAYERS), config=cfg, mesh=mesh)
    return engine


def _losses(engine, steps=4):
    batch = random_batches(1, engine.train_micro_batch_size_per_gpu(),
                           HIDDEN, seed=0)[0]
    return [float(np.asarray(engine.train_batch(iter([batch]))))
            for _ in range(steps)]


def test_uniform_matches_unrolled(force_injit, cpu_devices):
    """The scan rewrite is a compile-cost change, not a numerics change:
    same chunk bounds, same per-chunk math, same loss trajectory as the
    round-5 unrolled round-robin form."""
    eng_u = _engine(cpu_devices, uniform=True)
    eng_r = _engine(cpu_devices, uniform=False)
    assert eng_u._offload_uniform and not eng_r._offload_uniform
    # real multi-group, multi-chunk geometry, or the test proves nothing
    assert eng_u.flat.host_group_bounds is not None
    assert len(eng_u.flat.host_group_bounds) >= 2
    np.testing.assert_allclose(_losses(eng_u), _losses(eng_r), rtol=1e-6)


def test_uniform_matches_device_resident(force_injit, cpu_devices):
    """...and the same trajectory as plain device-resident training."""
    streamed = _losses(_engine(cpu_devices, uniform=True))
    base = _losses(_engine(cpu_devices, uniform=False, cpu_offload=False))
    np.testing.assert_allclose(streamed, base, rtol=2e-4, atol=2e-4)
    assert streamed[-1] < streamed[0]


def test_uniform_offload_gradients_parity(force_injit, cpu_devices):
    """The host-gradient leg (reverse-order spill + per-chunk coef fold)
    composes with the scan update: parity vs the unrolled form at the
    same clip setting."""
    eng_u = _engine(cpu_devices, uniform=True, offload_gradients=True,
                    clip=1.0)
    eng_r = _engine(cpu_devices, uniform=False, offload_gradients=True,
                    clip=1.0)
    assert eng_u._offload_grads and eng_u._offload_uniform
    np.testing.assert_allclose(_losses(eng_u), _losses(eng_r), rtol=1e-6)


def test_uniform_layout_alignment(force_injit, cpu_devices):
    """The coordinator pads total rows AND every group bound to whole
    chunks, so each chunk of each group has the one scanned shape."""
    engine = _engine(cpu_devices, uniform=True)
    chunk_rows = engine.flat.uniform_chunk_rows
    assert chunk_rows == (1 << 20) // (LANES * 4)
    assert engine.segments.rows % chunk_rows == 0
    for _, grc in engine.flat.host_group_bounds:
        assert grc % chunk_rows == 0
    jobs = stream.uniform_chunk_jobs(engine.flat.host_group_bounds,
                                     chunk_rows)
    assert len(jobs) == engine.segments.rows // chunk_rows
    assert len({gi for gi, _, _ in jobs}) == len(
        engine.flat.host_group_bounds)


def test_uniform_falls_back_on_ragged_geometry(force_injit, cpu_devices):
    """offload_chunk_mb: 0 (one ragged chunk per group) cannot scan;
    the engine must warn and keep the unrolled path, still training."""
    engine = _engine(cpu_devices, uniform=True, chunk_mb=0)
    assert not engine._offload_uniform
    losses = _losses(engine)
    assert losses[-1] < losses[0], losses


def test_uniform_auto_threshold(force_injit, cpu_devices):
    """"auto" keeps the measured-faster unrolled round-robin form below
    UNIFORM_MIN_CHUNKS and switches to the scan past it."""
    few = _engine(cpu_devices, uniform="auto")
    assert not few._offload_uniform  # toy model: far under the threshold
    assert stream.UNIFORM_MIN_CHUNKS > 1
    forced = _engine(cpu_devices, uniform=True)
    assert forced._offload_uniform


def test_checkpoint_roundtrip_across_forms(force_injit, cpu_devices,
                                           tmp_path):
    """Uniform-chunk padding changes the padded row layout, not the
    portable checkpoint format: a checkpoint written by the scan form
    restores into the unrolled form (and vice versa) with loss
    continuity — layout elasticity, like DP-degree elasticity."""
    eng_u = _engine(cpu_devices, uniform=True)
    losses = _losses(eng_u, steps=2)
    eng_u.save_checkpoint(str(tmp_path))
    eng_r = _engine(cpu_devices, uniform=False)
    eng_r.load_checkpoint(str(tmp_path))
    batch = random_batches(1, eng_r.train_micro_batch_size_per_gpu(),
                           HIDDEN, seed=0)[0]
    l_resumed = float(np.asarray(eng_r.train_batch(iter([batch]))))
    l_ref = float(np.asarray(eng_u.train_batch(iter([batch]))))
    np.testing.assert_allclose(l_resumed, l_ref, rtol=2e-4, atol=2e-4)
    assert losses[-1] < losses[0]


# ------------------------------------------------------- group layout
def test_derive_group_bytes_caps_buffer_count():
    """ROADMAP item 1 refactor: the host-group layout is auto-derived by
    capping total buffer COUNT (the observed AOT-crash mode), so the
    gpt2-xl bench row runs with an EMPTY offload_group_mb override.
    The round-5 receipt: 4 families x 4 groups (1792 MB) crashed the
    AOT helper; 4 x 2 (3584 MB) compiled."""
    gb = coord.derive_group_bytes
    xl_bytes = int(1.56e9) * 4  # gpt2-xl fp32 rows
    # 4 families (p, m, v, g): cap 8 buffers -> 2 groups of <= 3584 MB
    got = gb(xl_bytes, 4)
    assert got <= coord.HOST_GROUP_BYTES_MAX
    n_groups = -(-xl_bytes // got)
    assert n_groups * 4 <= coord.MAX_HOST_BUFFERS
    # small states keep the >=2-group round-robin calibration size
    assert gb(100 << 20, 3) == coord.HOST_GROUP_BYTES
    # state too big for the count cap under the per-buffer bound: the
    # per-buffer bound wins (loud warning), never a SIGABRT-sized buffer
    assert gb(int(30e9), 7) == coord.HOST_GROUP_BYTES_MAX


def test_engine_uses_derived_group_layout(force_injit, cpu_devices,
                                          monkeypatch):
    """With no offload_group_mb override, the engine's layout respects
    the buffer-count cap at toy scale: families x groups <= the cap."""
    monkeypatch.setattr(coord, "HOST_GROUP_BYTES", 256 << 10)
    monkeypatch.setattr(coord, "MAX_HOST_BUFFERS", 8)
    engine = _engine(cpu_devices, uniform=False, offload_gradients=True)
    bounds = engine.flat.host_group_bounds or ((0, engine.segments.rows),)
    assert len(bounds) * engine.flat.host_families <= 8
    assert engine.flat.host_families == 4  # p, m, v + host gradients
    losses = _losses(engine)
    assert losses[-1] < losses[0], losses


# ----------------------------------------------------------------- core
def _core_jaxpr(n_chunks, n_groups=2, chunk_rows=8):
    """jaxpr of the scan core at a given chunk count (state size grows,
    geometry otherwise fixed)."""
    opt = FusedAdam()
    rows_total = n_chunks * chunk_rows
    per = rows_total // n_groups
    assert per % chunk_rows == 0
    bounds = tuple((g * per, per) for g in range(n_groups))
    hp = opt.hyperparams()

    masters = [jnp.zeros((per, LANES), jnp.float32) for _ in range(n_groups)]
    st = opt.init_state(jnp.zeros((per, LANES), jnp.float32))
    leaves, treedef = jax.tree_util.tree_flatten(st)
    is_flat = [getattr(l, "ndim", 0) == 2 for l in leaves]
    group_leaves = [list(leaves) for _ in range(n_groups)]
    g = jnp.zeros((rows_total, LANES), jnp.float32)

    def run(ms, gls, gg):
        new_m, new_gl, _ = stream.uniform_scan_update(
            masters=ms, group_leaves=gls, is_flat=is_flat,
            opt_treedef=treedef, update_fn=opt.update, hp=hp,
            overflow=jnp.asarray(False), skip_bad=True,
            jobs=stream.uniform_chunk_jobs(bounds, chunk_rows),
            chunk_rows=chunk_rows, lanes=LANES, g=gg)
        return new_m, new_gl

    return jax.make_jaxpr(run)(masters, group_leaves, g)


def test_program_size_constant_in_chunk_count():
    """THE tentpole property: the scanned update's program size does not
    grow with chunk count (the unrolled form grew linearly — 361 ->
    5641 HLO lines from 8 -> 128 chunks, examples/
    bench_compile_scaling.py), so compile wall time stops scaling with
    model size and the >30-min remote compiles that blocked gpt2-2.7B
    cannot return."""
    small = _core_jaxpr(n_chunks=4)
    big = _core_jaxpr(n_chunks=64)
    count = lambda jx: sum(1 for _ in jx.jaxpr.eqns)
    assert count(big) == count(small), (
        f"scan update grew with chunk count: {count(small)} eqns at 4 "
        f"chunks vs {count(big)} at 64")


def test_core_update_matches_whole_buffer_adam():
    """The scan core applied chunk-by-chunk equals one whole-buffer Adam
    update (same master, same moments, same step counter)."""
    opt = FusedAdam()
    chunk_rows, n_groups = 8, 2
    rows = 4 * chunk_rows * n_groups
    per = rows // n_groups
    rng = np.random.default_rng(0)
    master = rng.normal(size=(rows, LANES)).astype(np.float32)
    g = rng.normal(size=(rows, LANES)).astype(np.float32)
    hp = opt.hyperparams()

    ref_p, ref_st = opt.update(
        opt.init_state(jnp.asarray(master)), jnp.asarray(master),
        jnp.asarray(g), hp)

    bounds = tuple((gi * per, per) for gi in range(n_groups))
    masters = [jnp.asarray(master[r0:r0 + rc]) for r0, rc in bounds]
    st0 = opt.init_state(jnp.zeros((per, LANES), jnp.float32))
    leaves, treedef = jax.tree_util.tree_flatten(st0)
    is_flat = [getattr(l, "ndim", 0) == 2 for l in leaves]
    group_leaves = [list(jax.tree_util.tree_leaves(st0))
                    for _ in range(n_groups)]
    new_m, new_gl, new_scalars = stream.uniform_scan_update(
        masters=masters, group_leaves=group_leaves, is_flat=is_flat,
        opt_treedef=treedef, update_fn=opt.update, hp=hp,
        overflow=jnp.asarray(False), skip_bad=False,
        jobs=stream.uniform_chunk_jobs(bounds, chunk_rows),
        chunk_rows=chunk_rows, lanes=LANES, g=jnp.asarray(g))
    got_p = np.concatenate([np.asarray(m) for m in new_m])
    np.testing.assert_allclose(got_p, np.asarray(ref_p), rtol=5e-6)
    got_m = np.concatenate([np.asarray(gl[0]) for gl in new_gl])
    np.testing.assert_allclose(got_m, np.asarray(ref_st.exp_avg),
                               rtol=1e-6)
    assert int(np.asarray(new_scalars[0])) == int(np.asarray(ref_st.step))


def test_core_overflow_skips_every_chunk():
    """skip_bad + overflow keeps master and moments bit-identical and
    the step counter un-advanced, chunk-for-chunk (the fp16/guard
    contract the unrolled path implements per chunk)."""
    opt = FusedAdam()
    chunk_rows = 8
    rows = 4 * chunk_rows
    rng = np.random.default_rng(1)
    master = jnp.asarray(rng.normal(size=(rows, LANES)).astype(np.float32))
    g = jnp.asarray(rng.normal(size=(rows, LANES)).astype(np.float32))
    st0 = opt.init_state(master)
    leaves, treedef = jax.tree_util.tree_flatten(st0)
    is_flat = [getattr(l, "ndim", 0) == 2 for l in leaves]
    new_m, new_gl, new_scalars = stream.uniform_scan_update(
        masters=[master], group_leaves=[list(leaves)], is_flat=is_flat,
        opt_treedef=treedef, update_fn=opt.update, hp=opt.hyperparams(),
        overflow=jnp.asarray(True), skip_bad=True,
        jobs=stream.uniform_chunk_jobs(((0, rows),), chunk_rows),
        chunk_rows=chunk_rows, lanes=LANES, g=g)
    np.testing.assert_array_equal(np.asarray(new_m[0]), np.asarray(master))
    np.testing.assert_array_equal(np.asarray(new_gl[0][0]),
                                  np.asarray(st0.exp_avg))
    assert int(np.asarray(new_scalars[0])) == 0
