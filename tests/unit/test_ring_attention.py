"""Ring attention (sequence parallelism) tests: exactness vs dense
attention, forward and backward, on the virtual multi-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.ops.transformer.attention import reference_attention
from deepspeed_tpu.ops.transformer.ring_attention import ring_attention
from deepspeed_tpu.parallel import make_mesh


def _qkv(b=2, s=64, h=4, d=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True], ids=["bidir", "causal"])
@pytest.mark.parametrize("seq_shards", [4, 8])
def test_ring_attention_matches_dense(causal, seq_shards, cpu_devices):
    mesh = make_mesh({"seq": seq_shards}, devices=cpu_devices[:seq_shards])
    q, k, v = _qkv()
    sharding = NamedSharding(mesh, P(None, "seq"))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))

    with mesh:
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, mesh=mesh, causal=causal))(qs, ks, vs)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_grads_match_dense(cpu_devices):
    mesh = make_mesh({"seq": 4}, devices=cpu_devices[:4])
    q, k, v = _qkv(b=1, s=32, h=2, d=8, seed=1)
    sharding = NamedSharding(mesh, P(None, "seq"))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))

    def ring_loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh, causal=True) ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    with mesh:
        g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(qs, ks, vs)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-5)


def test_ring_attention_mixed_axes(cpu_devices):
    """seq parallelism composes with data parallelism (batch stays sharded
    over 'data' in GSPMD-auto mode)."""
    mesh = make_mesh({"data": 2, "seq": 4}, devices=cpu_devices[:8])
    q, k, v = _qkv(b=4, s=32, h=2, d=8, seed=2)
    sharding = NamedSharding(mesh, P("data", "seq"))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    with mesh:
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, mesh=mesh, causal=False))(qs, ks, vs)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_single_shard_fallback(cpu_devices):
    mesh = make_mesh({"data": 1}, devices=cpu_devices[:1])
    q, k, v = _qkv(b=1, s=16, h=2, d=8)
    out = ring_attention(q, k, v, mesh=mesh, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.slow
def test_gpt2_engine_with_ring_attention(cpu_devices):
    """Full engine train step with sequence-parallel attention on a
    data×seq mesh (long-context path end-to-end)."""
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import GPT2Config, GPT2LMHeadTPU

    mesh = make_mesh({"data": 2, "seq": 4}, devices=cpu_devices[:8])
    cfg = GPT2Config(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
                     max_position_embeddings=64, embd_dropout=0.0,
                     attn_dropout=0.0, resid_dropout=0.0, attn_impl="ring")
    config = {"train_batch_size": 4, "steps_per_print": 10 ** 9,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
    engine, *_ = deepspeed.initialize(model=GPT2LMHeadTPU(cfg), config=config,
                                      mesh=mesh)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 128, size=(4, 64)).astype(np.int32)}
    l0 = float(np.asarray(jax.device_get(engine.train_batch(iter([batch])))))
    l1 = float(np.asarray(jax.device_get(engine.train_batch(iter([batch])))))
    assert np.isfinite([l0, l1]).all() and l1 < l0

    # parity: same model with dense attention on dp-only mesh
    mesh1 = make_mesh({"data": 1}, devices=cpu_devices[:1])
    cfg_d = GPT2Config(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
                       max_position_embeddings=64, embd_dropout=0.0,
                       attn_dropout=0.0, resid_dropout=0.0)
    config1 = {"train_batch_size": 4, "steps_per_print": 10 ** 9,
               "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
    e1, *_ = deepspeed.initialize(model=GPT2LMHeadTPU(cfg_d), config=config1,
                                  mesh=mesh1)
    d0 = float(np.asarray(jax.device_get(e1.train_batch(iter([batch])))))
    d1 = float(np.asarray(jax.device_get(e1.train_batch(iter([batch])))))
    np.testing.assert_allclose([l0, l1], [d0, d1], rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_gpt2_engine_with_sparse_attention(cpu_devices):
    """Full engine train step with block-sparse attention."""
    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import GPT2Config, GPT2LMHeadTPU
    from deepspeed_tpu.ops.sparse_attention import FixedSparsityConfig

    mesh = make_mesh({"data": 1}, devices=cpu_devices[:1])
    sc = FixedSparsityConfig(num_heads=4, block=16, num_local_blocks=2,
                             attention="unidirectional")
    cfg = GPT2Config(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
                     max_position_embeddings=64, embd_dropout=0.0,
                     attn_dropout=0.0, resid_dropout=0.0,
                     attn_impl="sparse", sparsity_config=sc)
    config = {"train_batch_size": 2, "steps_per_print": 10 ** 9,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
    engine, *_ = deepspeed.initialize(model=GPT2LMHeadTPU(cfg), config=config,
                                      mesh=mesh)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 128, size=(2, 64)).astype(np.int32)}
    l0 = float(np.asarray(jax.device_get(engine.train_batch(iter([batch])))))
    l1 = float(np.asarray(jax.device_get(engine.train_batch(iter([batch])))))
    assert np.isfinite([l0, l1]).all() and l1 < l0


def test_ring_attention_key_padding_mask(cpu_devices):
    mesh = make_mesh({"seq": 4}, devices=cpu_devices[:4])
    q, k, v = _qkv(b=2, s=32, h=2, d=8, seed=3)
    kpm = np.zeros((2, 32), np.float32)
    kpm[:, 24:] = -1e9  # mask final chunk's keys
    sharding = NamedSharding(mesh, P(None, "seq"))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    with mesh:
        out = jax.jit(lambda q, k, v, m: ring_attention(
            q, k, v, mesh=mesh, key_padding_mask=m))(qs, ks, vs, jnp.asarray(kpm))
    ref = reference_attention(q, k, v, mask=jnp.asarray(kpm)[:, None, None, :])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_dense_fallback_respects_custom_scale(cpu_devices):
    """The single-shard/old-jax dense fallback must honor a caller scale
    (reference_attention hard-codes 1/sqrt(d); the fallback pre-scales q)."""
    mesh = make_mesh({"seq": 1}, devices=cpu_devices[:1])
    q, k, v = _qkv(b=1, s=16, h=2, d=8, seed=5)
    scale = 0.05
    out = ring_attention(q, k, v, mesh=mesh, scale=scale)

    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    ref = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # and the default-scale output differs, i.e. scale isn't dropped
    out_default = ring_attention(q, k, v, mesh=mesh)
    assert not np.allclose(np.asarray(out), np.asarray(out_default))
