"""Flops profiler: exact counts on known-FLOPs modules, control-flow
handling, per-scope attribution, engine integration (reference
``tests/unit/test_flops_profiler.py``)."""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.profiling.flops_profiler import (count_fn_flops,
                                                    get_model_profile,
                                                    params_count)

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 16


def test_matmul_exact_count():
    B, K, N = 8, 32, 64
    x = jnp.ones((B, K))
    w = jnp.ones((K, N))
    flops, _ = count_fn_flops(lambda a, b: a @ b, x, w)
    assert flops == 2 * B * K * N


def test_grad_counts_backward_too():
    """Training FLOPs come from the traced backward, not a 3x heuristic:
    d(xW) needs two more matmuls (dx = gW^T, dW = x^T g)."""
    B, K, N = 4, 8, 16
    x = jnp.ones((B, K))
    w = jnp.ones((K, N))

    def loss(w):
        return jnp.sum(x @ w)

    fwd, _ = count_fn_flops(loss, w)
    bwd, _ = count_fn_flops(jax.grad(loss), w)
    assert bwd >= fwd + 2 * B * K * N - 2 * B * N  # two extra matmuls


def test_scan_multiplies_by_length():
    K = 16
    w = jnp.ones((K, K))

    def scanned(x):
        def body(c, _):
            return c @ w, None
        out, _ = jax.lax.scan(body, x, None, length=10)
        return out

    one, _ = count_fn_flops(lambda x: x @ w, jnp.ones((2, K)))
    ten, _ = count_fn_flops(scanned, jnp.ones((2, K)))
    assert ten == 10 * one


def test_named_scope_attribution():
    K = 32
    w1 = jnp.ones((K, K))
    w2 = jnp.ones((K, 2 * K))

    def fn(x):
        with jax.named_scope("small"):
            a = x @ w1
        with jax.named_scope("big"):
            b = a @ w2
        return jnp.sum(b)

    flops, by_scope = count_fn_flops(fn, jnp.ones((4, K)))
    small = sum(v for k, v in by_scope.items() if "small" in k)
    big = sum(v for k, v in by_scope.items() if "big" in k)
    assert small == 2 * 4 * K * K
    assert big == 2 * 4 * K * 2 * K


def test_get_model_profile_simple_model():
    model = SimpleModel(HIDDEN, nlayers=2)
    batch = random_batches(1, 8, HIDDEN, seed=0)[0]
    params = model.init(jax.random.PRNGKey(0))
    flops, macs, n_params = get_model_profile(model=model, batch=batch,
                                              params=params,
                                              print_profile=False)
    assert n_params == params_count(params)
    assert flops > 0 and macs == flops // 2
    ftrain, _, _ = get_model_profile(model=model, batch=batch, params=params,
                                     train=True, print_profile=False)
    assert ftrain > flops  # backward included


def test_engine_profiler_wiring(cpu_devices):
    config = base_config(flops_profiler={"enabled": True, "profile_step": 2})
    mesh = make_mesh({"data": 8}, devices=cpu_devices[:8])
    engine, *_ = deepspeed.initialize(model=SimpleModel(HIDDEN, nlayers=2),
                                      config=config, mesh=mesh)
    assert engine.flops_profiler is not None
    batch = random_batches(1, engine.train_micro_batch_size_per_gpu() * 8,
                           HIDDEN, seed=0)[0]
    for _ in range(3):
        engine.train_batch(iter([batch]))
    prof = engine.flops_profiler.profile
    assert prof is not None, "profiler did not run at profile_step"
    assert prof.flops > 0
    assert prof.params == params_count(engine._param_template)
