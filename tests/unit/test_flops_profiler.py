"""Flops profiler: exact counts on known-FLOPs modules, control-flow
handling, per-scope attribution, engine integration (reference
``tests/unit/test_flops_profiler.py``)."""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.profiling.flops_profiler import (count_fn_flops,
                                                    get_model_profile,
                                                    params_count)

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 16


def test_matmul_exact_count():
    B, K, N = 8, 32, 64
    x = jnp.ones((B, K))
    w = jnp.ones((K, N))
    flops, _ = count_fn_flops(lambda a, b: a @ b, x, w)
    assert flops == 2 * B * K * N


def test_grad_counts_backward_too():
    """Training FLOPs come from the traced backward, not a 3x heuristic:
    d(xW) needs two more matmuls (dx = gW^T, dW = x^T g)."""
    B, K, N = 4, 8, 16
    x = jnp.ones((B, K))
    w = jnp.ones((K, N))

    def loss(w):
        return jnp.sum(x @ w)

    fwd, _ = count_fn_flops(loss, w)
    bwd, _ = count_fn_flops(jax.grad(loss), w)
    assert bwd >= fwd + 2 * B * K * N - 2 * B * N  # two extra matmuls


def test_scan_multiplies_by_length():
    K = 16
    w = jnp.ones((K, K))

    def scanned(x):
        def body(c, _):
            return c @ w, None
        out, _ = jax.lax.scan(body, x, None, length=10)
        return out

    one, _ = count_fn_flops(lambda x: x @ w, jnp.ones((2, K)))
    ten, _ = count_fn_flops(scanned, jnp.ones((2, K)))
    assert ten == 10 * one


def test_named_scope_attribution():
    K = 32
    w1 = jnp.ones((K, K))
    w2 = jnp.ones((K, 2 * K))

    def fn(x):
        with jax.named_scope("small"):
            a = x @ w1
        with jax.named_scope("big"):
            b = a @ w2
        return jnp.sum(b)

    flops, by_scope = count_fn_flops(fn, jnp.ones((4, K)))
    small = sum(v for k, v in by_scope.items() if "small" in k)
    big = sum(v for k, v in by_scope.items() if "big" in k)
    assert small == 2 * 4 * K * K
    assert big == 2 * 4 * K * 2 * K


def test_get_model_profile_simple_model():
    model = SimpleModel(HIDDEN, nlayers=2)
    batch = random_batches(1, 8, HIDDEN, seed=0)[0]
    params = model.init(jax.random.PRNGKey(0))
    flops, macs, n_params = get_model_profile(model=model, batch=batch,
                                              params=params,
                                              print_profile=False)
    assert n_params == params_count(params)
    assert flops > 0 and macs == flops // 2
    ftrain, _, _ = get_model_profile(model=model, batch=batch, params=params,
                                     train=True, print_profile=False)
    assert ftrain > flops  # backward included


def test_engine_profiler_wiring(cpu_devices):
    config = base_config(flops_profiler={"enabled": True, "profile_step": 2})
    mesh = make_mesh({"data": 8}, devices=cpu_devices[:8])
    engine, *_ = deepspeed.initialize(model=SimpleModel(HIDDEN, nlayers=2),
                                      config=config, mesh=mesh)
    assert engine.flops_profiler is not None
    batch = random_batches(1, engine.train_micro_batch_size_per_gpu() * 8,
                           HIDDEN, seed=0)[0]
    for _ in range(3):
        engine.train_batch(iter([batch]))
    prof = engine.flops_profiler.profile
    assert prof is not None, "profiler did not run at profile_step"
    assert prof.flops > 0
    assert prof.params == params_count(engine._param_template)


def test_conv_flops_exact_count():
    import jax.lax as lax

    B, C, H, W, O, K = 2, 3, 8, 8, 4, 3
    x = jnp.ones((B, C, H, W))
    w = jnp.ones((O, C, K, K))

    def conv(x, w):
        return lax.conv_general_dilated(
            x, w, window_strides=(1, 1), padding="SAME",
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    flops, _ = count_fn_flops(conv, x, w)
    # 2 * output elements * kernel taps per output channel
    assert flops == 2 * (B * O * H * W) * (C * K * K)


def test_while_loop_counts_one_iteration():
    """Data-dependent trip counts are invisible to the jaxpr walk: one
    iteration is counted (the documented reference-parity caveat)."""
    K = 16
    w = jnp.ones((K, K))

    def looped(x):
        def cond(c):
            return jnp.sum(c[0]) < 1e9

        def body(c):
            return (c[0] @ w, c[1] + 1)

        out, _ = jax.lax.while_loop(cond, body, (x, 0))
        return out

    one, _ = count_fn_flops(lambda x: x @ w, jnp.ones((2, K)))
    loop, _ = count_fn_flops(looped, jnp.ones((2, K)))
    assert one <= loop < 2 * one + K * K  # body once, not N times


def test_cond_counts_hot_branch():
    K = 32
    w_small = jnp.ones((K, K))
    w_big = jnp.ones((K, 4 * K))

    def f(x, pred):
        return jax.lax.cond(pred,
                            lambda a: jnp.sum(a @ w_big),
                            lambda a: jnp.sum(a @ w_small), x)

    big, _ = count_fn_flops(lambda x: jnp.sum(x @ w_big),
                            jnp.ones((4, K)))
    both, _ = count_fn_flops(f, jnp.ones((4, K)), True)
    assert both >= big  # the hot (max-flops) branch is what counts


def test_backend_cost_analysis_returns_dict():
    from deepspeed_tpu.profiling.flops_profiler import profiler as prof_mod

    fn = jax.jit(lambda a, b: a @ b)
    cost = prof_mod.backend_cost_analysis(fn, jnp.ones((8, 8)),
                                          jnp.ones((8, 8)))
    assert isinstance(cost, dict)  # {} when the backend offers none


def test_flops_profile_wall_and_mfu():
    from deepspeed_tpu.profiling.flops_profiler.profiler import FlopsProfile
    from deepspeed_tpu.profiling.utilization import chip_peak_tflops

    prof = FlopsProfile(flops=2 * 10 ** 12, macs=10 ** 12, params=1000,
                        wall_ms=100.0)
    assert prof.achieved_tflops() == 20.0
    dev = jax.devices()[0]
    assert prof.mfu(dev) == 20.0 / chip_peak_tflops(dev)
    assert FlopsProfile(1, 0, 1).achieved_tflops() is None
