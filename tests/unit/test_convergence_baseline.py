"""Baseline-pinned convergence suite.

Port of the reference's model-test idea (``tests/model/Megatron_GPT2/
run_func_test.py:20-130``, ``BingBertSquad/test_e2e_squad.py``): train a
fixed tiny transformer on a fixed synthetic corpus for a few hundred steps
under every major engine configuration, and compare the LOSS CURVE against
a stored baseline within tolerance — so a silent numerics regression in
any stage/offload/pipe/onebit path shows up as a curve drift, not just a
"loss went down" smoke signal.

One command reproduces and diffs every curve:

    python -m pytest tests/unit/test_convergence_baseline.py -m slow

Regenerate the stored baselines after an INTENTIONAL numerics change:

    DS_UPDATE_BASELINES=1 python -m pytest \
        tests/unit/test_convergence_baseline.py -m slow
"""

import json
import os

import jax
import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.models import BertConfig, BertForPreTrainingTPU
from deepspeed_tpu.parallel import make_mesh

pytestmark = pytest.mark.slow

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baselines",
                             "convergence.json")
VOCAB, SEQ, BATCH = 128, 32, 16
STEPS, RECORD_EVERY = 200, 10
# bf16 paths accumulate rounding differently across program structures;
# the pin is about curve SHAPE regressions, not bit equality
RTOL, ATOL = 5e-2, 5e-2


def _corpus(n_batches):
    """Fixed synthetic MLM corpus — a small vocab with learnable structure
    (token i is followed by token (i*7+3) % VOCAB) so the loss genuinely
    converges rather than memorizing noise."""
    rng = np.random.default_rng(1234)
    batches = []
    for _ in range(n_batches):
        start = rng.integers(0, VOCAB, size=(BATCH, 1))
        seqs = [start]
        for _ in range(SEQ - 1):
            seqs.append((seqs[-1] * 7 + 3) % VOCAB)
        ids = np.concatenate(seqs, axis=1).astype(np.int32)
        labels = np.full_like(ids, -100)
        for r in range(BATCH):
            pos = rng.permutation(SEQ)[:5]
            labels[r, pos] = ids[r, pos]
        batches.append({
            "input_ids": ids,
            "attention_mask": np.ones((BATCH, SEQ), np.int32),
            "token_type_ids": np.zeros((BATCH, SEQ), np.int32),
            "masked_lm_labels": labels,
            "next_sentence_labels": rng.integers(
                0, 2, size=(BATCH,)).astype(np.int32),
        })
    return batches


def _model():
    return BertForPreTrainingTPU(BertConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=SEQ,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0))


def _run_curve(config, mesh_axes, cpu_devices, steps=STEPS):
    n_dev = int(np.prod(list(mesh_axes.values())))
    mesh = make_mesh(mesh_axes, devices=cpu_devices[:n_dev])
    engine, *_ = deepspeed.initialize(model=_model(), config=config,
                                      mesh=mesh)
    corpus = _corpus(8)
    gas = engine.gradient_accumulation_steps()
    curve = []
    for step in range(steps):
        b = corpus[step % len(corpus)]
        # one optimizer step consumes `gas` micro-batches
        micros = [{k: v[i * (BATCH // gas):(i + 1) * (BATCH // gas)]
                   for k, v in b.items()} for i in range(gas)]
        loss = engine.train_batch(iter(micros))
        if step % RECORD_EVERY == 0:
            curve.append(round(float(np.asarray(loss)), 4))
    return curve


def _base_config(**over):
    cfg = {
        "train_batch_size": BATCH,
        "steps_per_print": 10 ** 9,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }
    cfg.update(over)
    return cfg


CONFIGS = {
    "zero0_fp32": (_base_config(), {"data": 4}),
    "zero1_bf16": (_base_config(zero_optimization={"stage": 1},
                                bf16={"enabled": True}), {"data": 4}),
    "zero2_bf16": (_base_config(zero_optimization={"stage": 2},
                                bf16={"enabled": True}), {"data": 4}),
    "zero3_bf16": (_base_config(zero_optimization={"stage": 3},
                                bf16={"enabled": True}), {"data": 4}),
    "zero2_offload": (_base_config(
        zero_optimization={"stage": 2, "cpu_offload": True},
        bf16={"enabled": True}), {"data": 4}),
    # freeze only after v has ~saturated (1 − β2^freeze ≈ 0.95): like the
    # reference, neither phase bias-corrects, so freezing early leaves a
    # tiny frozen v and the compressed updates run hot and diverge —
    # reference deployments freeze after ~23k steps for the same reason
    "onebit_post_freeze": (_base_config(
        optimizer={"type": "OneBitAdam",
                   "params": {"lr": 1e-3, "freeze_step": 100,
                              "betas": (0.9, 0.97)}}), {"data": 4}),
    "dp_x2_grad_acc": (_base_config(
        train_batch_size=BATCH, gradient_accumulation_steps=2,
        train_micro_batch_size_per_gpu=BATCH // 4), {"data": 2}),
}


def _load_baselines():
    if not os.path.exists(BASELINE_PATH):
        return {}
    with open(BASELINE_PATH) as f:
        return json.load(f)


def _store_baseline(name, curve):
    os.makedirs(os.path.dirname(BASELINE_PATH), exist_ok=True)
    baselines = _load_baselines()
    baselines[name] = curve
    with open(BASELINE_PATH, "w") as f:
        json.dump(baselines, f, indent=1, sort_keys=True)


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_convergence_curve_matches_baseline(name, cpu_devices):
    config, mesh_axes = CONFIGS[name]
    curve = _run_curve(dict(config), mesh_axes, cpu_devices)
    if name == "onebit_post_freeze":
        # At this toy scale (~60k params) the 1-bit sign compression noise
        # floor dominates once near convergence (verified: swapping the
        # collective for an exact pmean converges smoothly, and the
        # collective itself matches its float64 host reference) — the
        # reference algorithm replaces the momentum with a sign·scale
        # vector each step, identical behavior.  Require the warmup to
        # converge and the compressed phase to stay bounded; the pinned
        # curve is the regression guard.
        assert min(curve) < curve[0] * 0.7, f"warmup did not converge: {curve}"
        assert curve[-1] < curve[0] * 1.2, f"compressed phase diverged: {curve}"
    else:
        # the curve must actually converge, baseline or not
        assert curve[-1] < curve[0] * 0.8, f"{name} did not converge: {curve}"
    if os.environ.get("DS_UPDATE_BASELINES") == "1":
        _store_baseline(name, curve)
        pytest.skip(f"baseline for {name} regenerated")
    baselines = _load_baselines()
    assert name in baselines, (
        f"no stored baseline for {name}; run DS_UPDATE_BASELINES=1 pytest "
        f"{__file__} -m slow once and commit {BASELINE_PATH}")
    np.testing.assert_allclose(
        curve, baselines[name], rtol=RTOL, atol=ATOL,
        err_msg=f"{name} loss curve drifted from pinned baseline")


def test_pipeline_convergence_matches_dense(cpu_devices):
    """Pipeline (2 stages × dp 2, interleave 2) over the same corpus: the
    curve must track the plain data-parallel curve — pipe is an execution
    strategy, not a numerics change."""
    from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule

    class EmbedL:
        def init(self, rng):
            return {"emb": jax.random.normal(rng, (VOCAB, 32)) * 0.1}

        def apply(self, p, ids):
            return jnp_take(p["emb"], ids)

    import jax.numpy as jnp

    def jnp_take(emb, ids):
        return jnp.take(emb, ids, axis=0)

    class Block:
        def init(self, rng):
            k1, k2 = jax.random.split(rng)
            return {"w1": jax.random.normal(k1, (32, 64)) * 0.1,
                    "w2": jax.random.normal(k2, (64, 32)) * 0.1}

        def apply(self, p, x):
            return x + jnp.tanh(x @ p["w1"]) @ p["w2"]

    class Head:
        def init(self, rng):
            return {"out": jax.random.normal(rng, (32, VOCAB)) * 0.1}

        def apply(self, p, x):
            return x @ p["out"]

    def xent(logits, labels):
        from deepspeed_tpu.models.layers import cross_entropy_with_logits

        return cross_entropy_with_logits(logits, labels, ignore_index=-100)

    corpus = _corpus(8)
    steps = 120

    def data_iter(step):
        b = corpus[step % len(corpus)]
        # pipeline batches are (inputs, labels) micro-batch tuples
        ids = b["input_ids"].reshape(2, BATCH // 2, SEQ)
        lab = b["masked_lm_labels"].reshape(2, BATCH // 2, SEQ)
        return iter([(ids[0], lab[0]), (ids[1], lab[1])])

    def run(interleave):
        module = PipelineModule(
            [LayerSpec(EmbedL)] + [LayerSpec(Block) for _ in range(4)]
            + [LayerSpec(Head)],
            loss_fn=xent, partition_method="uniform", interleave=interleave)
        mesh = make_mesh({"pipe": 2, "data": 2}, devices=cpu_devices[:4])
        engine, *_ = deepspeed.initialize(
            model=module, mesh=mesh,
            config={"train_micro_batch_size_per_gpu": BATCH // 4,
                    "gradient_accumulation_steps": 2,
                    "steps_per_print": 10 ** 9,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
        curve = []
        for step in range(steps):
            loss = engine.train_batch(data_iter(step))
            if step % RECORD_EVERY == 0:
                curve.append(float(np.asarray(loss)))
        return curve

    plain = run(1)
    inter = run(2)
    assert plain[-1] < plain[0] * 0.8, f"pipe did not converge: {plain}"
    np.testing.assert_allclose(inter, plain, rtol=1e-4, atol=1e-5)
