"""Elastic-fleet child script for the chaos e2e test.

Driven by ``deepspeed_tpu.launcher.launch`` with the elastic supervisor
armed: every life reads its planned world size from
``DS_ELASTIC_TARGET_WORLD_SIZE``, builds a data mesh of that many
virtual CPU devices (out of 8), and trains a tiny model on the elastic
schedule (global batch fixed at 16) with per-step synchronous
checkpoints and ``auto_resume``.

Chaos: when ``DS_CHAOS_KILL_STEP`` is set and this life started FRESH
(no checkpoint to resume — i.e. the first life), the seeded chaos
injector SIGKILLs the process mid-stream at that optimizer step, exactly
like a preempted host.  The respawned life resumes from the last
committed checkpoint onto the resized mesh and continues the same
sample stream (loader state rides the checkpoint: no replay, no skip).

argv: <ckpt_dir> <out_dir>   (telemetry dir rides DS_TELEMETRY_DIR)
"""

import json
import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import deepspeed_tpu as deepspeed  # noqa: E402
from deepspeed_tpu.elasticity import elastic_world_size  # noqa: E402
from deepspeed_tpu.parallel import make_mesh  # noqa: E402
from deepspeed_tpu.resilience.chaos import ChaosMonkey  # noqa: E402
from deepspeed_tpu.runtime.dataloader import RepeatingLoader  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from simple_model import SimpleModel, random_dataset  # noqa: E402

HIDDEN = 16
GLOBAL_BATCH = 16
TOTAL_STEPS = 10
DATASET_SAMPLES = 80          # 5 optimizer steps per epoch: step 6
                              # crosses an epoch boundary, so the resume
                              # cursor proves (epoch, offset) carriage

ELASTIC = {"enabled": True, "max_train_batch_size": GLOBAL_BATCH,
           "micro_batch_sizes": [2, 4], "min_gpus": 1, "max_gpus": 8,
           "version": 0.1}


def main():
    ckpt_dir, out_dir = sys.argv[1], sys.argv[2]
    world = elastic_world_size(default=8)
    devices = jax.devices("cpu")
    assert len(devices) >= world, (len(devices), world)
    mesh = make_mesh({"data": world}, devices=devices[:world])

    config = {
        "elasticity": dict(ELASTIC),
        "steps_per_print": 4,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2},
        "resilience": {"enabled": True, "checkpoint_dir": ckpt_dir},
        "telemetry": {"enabled": True},
    }
    dataset = random_dataset(DATASET_SAMPLES, HIDDEN, seed=7)
    engine, _, loader, _ = deepspeed.initialize(
        model=SimpleModel(HIDDEN, nlayers=1), config=config, mesh=mesh,
        training_data=dataset, auto_resume=True)
    fresh = engine.global_steps == 0

    kill_step = int(os.environ.get("DS_CHAOS_KILL_STEP", "0") or 0)
    monkey = ChaosMonkey(seed=int(os.environ.get("DS_CHAOS_SEED", "0")))
    acc = engine.gradient_accumulation_steps()
    # pull index -> optimizer step: the kill lands on the FIRST pull of
    # step kill_step+1, i.e. strictly after step kill_step committed
    kill_pulls = [kill_step * acc] if (kill_step and fresh) else []
    it = monkey.wrap_iter(iter(RepeatingLoader(loader)),
                          kill_steps=kill_pulls,
                          rank=int(os.environ.get("DS_PROCESS_ID", "0")),
                          target_rank=0)

    os.makedirs(out_dir, exist_ok=True)
    life = "fresh" if fresh else f"resumed@{engine.global_steps}"
    log_path = os.path.join(out_dir, f"steps-world{world}-{life}.jsonl")
    with open(log_path, "a") as f:
        while engine.global_steps < TOTAL_STEPS:
            loss = engine.train_batch(it)
            engine.save_checkpoint(ckpt_dir, sync=True)
            f.write(json.dumps({
                "step": engine.global_steps,
                "loss": float(jax.device_get(loss)),
                "world": world,
                "samples": engine.global_samples}) + "\n")
            f.flush()

    with open(os.path.join(out_dir, "final.json"), "w") as f:
        json.dump({"final_loss": float(jax.device_get(loss)),
                   "steps": engine.global_steps,
                   "samples": engine.global_samples,
                   "world": world}, f)
    engine.close()


if __name__ == "__main__":
    main()
