"""Multi-replica serving front-end (inference/frontend.py) and the
request-level robustness satellites:

- per-request deadlines: expiry mid-batch recycles the slot and block
  grant, partial tokens come back with ``reason="deadline"``;
- bounded admission: load shedding at ``max_queue_depth`` (typed
  :class:`ServingOverloadError`) and graceful degradation past
  ``degrade_queue_depth``;
- dead-replica requeue: in-flight requests reset and re-served on a
  survivor with BIT-IDENTICAL tokens (greedy determinism), exactly
  once — pinned by the kill-at-every-step-k sweep;
- the blocks-conserved invariant: after every scheduler exercise —
  including admission paths that RAISE — aborting everything returns
  the allocator to its initial free count.  A leaked grant is a
  permanently shrunk KV pool.
"""

import pytest

from deepspeed_tpu.inference import (BlockAllocator,
                                     ContinuousBatchScheduler,
                                     DeepSpeedInferenceConfig,
                                     InferenceEngine, Request,
                                     ServingFrontend,
                                     ServingOverloadError,
                                     reference_generate)
from deepspeed_tpu.inference.scheduler import (ACTIVE, FINISHED, QUEUED,
                                               REASON_DEADLINE,
                                               REASON_LENGTH)

from .test_inference import (seeded_prompts, serve_config, tiny_model,
                             model_and_params)  # noqa: F401 — fixture


def _drain_and_check_conserved(sched, alloc, initial_free):
    """The blocks-conserved invariant: abort every request the
    scheduler still tracks and the allocator must be exactly back at
    its initial free count — any shortfall is a leaked grant."""
    for request in list(sched.slots):
        if request is not None:
            sched.abort(request)
    for request in list(sched.waiting):
        sched.abort(request)
    assert alloc.free_blocks == initial_free, (
        f"block leak: {initial_free - alloc.free_blocks} block(s) never "
        "returned to the pool")


# ---------------------------------------------------------------------------
# scheduler satellites: deadlines + exception-safe admission
# ---------------------------------------------------------------------------

class TestSchedulerDeadlines:
    @pytest.fixture(autouse=True)
    def conserved(self):
        """Every test in this class ends with the invariant check."""
        self._made = []
        yield
        for sched, alloc, initial in self._made:
            _drain_and_check_conserved(sched, alloc, initial)

    def make(self, **overrides):
        icfg = DeepSpeedInferenceConfig(serve_config(**overrides))
        alloc = BlockAllocator(icfg.kv_blocks)
        sched = ContinuousBatchScheduler(icfg, alloc)
        self._made.append((sched, alloc, alloc.free_blocks))
        return sched, alloc

    def test_active_deadline_recycles_slot_and_blocks(self):
        sched, alloc = self.make()
        r = Request("r", [1] * 8, 8, deadline_at=100.0)
        sched.submit(r)
        assert sched.try_admit() is r
        r.generated = [5, 6]                      # two tokens in
        free_mid = alloc.free_blocks
        done = sched.sweep_deadlines(now=99.0)    # not yet
        assert done == [] and r.state == ACTIVE
        done = sched.sweep_deadlines(now=100.0)   # expired
        assert done == [r]
        assert r.state == FINISHED
        assert r.finish_reason == REASON_DEADLINE
        assert r.generated == [5, 6]              # partial tokens kept
        assert alloc.free_blocks > free_mid       # grant recycled
        assert sched.slots == [None] * len(sched.slots)

    def test_slot_reuse_after_deadline(self):
        # the freed slot must seat the queue head the very next pass
        sched, _ = self.make(max_batch_slots=1)
        doomed = Request("doomed", [1] * 8, 8, deadline_at=10.0)
        waiting = Request("waiting", [1] * 8, 4)
        sched.submit(doomed)
        sched.submit(waiting)
        assert sched.try_admit() is doomed
        assert sched.try_admit() is None          # the only slot is busy
        sched.sweep_deadlines(now=10.0)
        again = sched.try_admit()
        assert again is waiting and again.slot == 0

    def test_queued_request_expires_without_ever_running(self):
        sched, _ = self.make(max_batch_slots=1)
        hog = Request("hog", [1] * 8, 8)
        late = Request("late", [1] * 8, 4, deadline_at=5.0)
        sched.submit(hog)
        sched.submit(late)
        assert sched.try_admit() is hog
        done = sched.sweep_deadlines(now=6.0)
        assert done == [late]
        assert late.state == FINISHED
        assert late.finish_reason == REASON_DEADLINE
        assert late.generated == [] and late.blocks == []
        assert sched.queue_depth == 0

    def test_no_deadline_never_expires(self):
        sched, _ = self.make()
        r = Request("r", [1] * 8, 4)              # deadline_at=None
        sched.submit(r)
        sched.try_admit()
        assert sched.sweep_deadlines(now=1e12) == []

    def test_try_admit_exception_returns_the_grant(self):
        """A raise during post-allocate bookkeeping must release the
        fresh grant — the allocator has no owner to reclaim from."""
        sched, alloc = self.make()
        r = Request("r", [1] * 8, 4)
        sched.submit(r)
        free_before = alloc.free_blocks

        class Detonating(list):
            def __setitem__(self, i, v):
                raise RuntimeError("chaos: bookkeeping blew up")

        sched.slots = Detonating(sched.slots)
        with pytest.raises(RuntimeError, match="bookkeeping"):
            sched.try_admit()
        sched.slots = [None] * sched.icfg.max_batch_slots
        assert alloc.free_blocks == free_before   # grant came back
        assert r.blocks == [] and r.slot is None
        assert r.state == QUEUED

    def test_abort_releases_active_and_queued(self):
        sched, alloc = self.make()
        a = Request("a", [1] * 8, 4)
        b = Request("b", [1] * 8, 4)
        sched.submit(a)
        sched.submit(b)
        sched.try_admit()
        free_mid = alloc.free_blocks
        sched.abort(a)                            # active: slot + blocks
        assert alloc.free_blocks > free_mid
        assert sched.slots[0] is None
        sched.abort(b)                            # queued: just dequeued
        assert sched.queue_depth == 0
        assert a.state == QUEUED and b.state == QUEUED

    def test_submit_rejects_stale_grant(self):
        sched, _ = self.make()
        r = Request("r", [1] * 8, 4)
        sched.submit(r)
        sched.try_admit()
        with pytest.raises(AssertionError, match="reset_for_requeue"):
            sched.submit(r)                       # still holds blocks

    def test_reset_for_requeue_refuses_finished(self):
        sched, _ = self.make()
        r = Request("r", [1] * 8, 4)
        sched.submit(r)
        sched.try_admit()
        r.generated = [1, 2, 3, 4]
        sched.finish(r, REASON_LENGTH)
        with pytest.raises(AssertionError, match="exactly-once"):
            r.reset_for_requeue()

    def test_reset_for_requeue_clears_but_never_releases(self):
        # the grant belonged to the DEAD replica's allocator: the block
        # list is cleared, not released into this pool
        sched, alloc = self.make()
        r = Request("r", [1] * 8, 4)
        sched.submit(r)
        sched.try_admit()
        r.generated = [9]
        foreign = list(r.blocks)
        sched.abort(r)                            # the dead engine's abort
        r.reset_for_requeue()
        assert r.blocks == [] and r.generated == []
        assert r.requeues == 1
        assert r.state == QUEUED
        assert foreign                            # (the ids existed)


# ---------------------------------------------------------------------------
# engine-level deadline + prefill-abort
# ---------------------------------------------------------------------------

class TestEngineDeadlines:
    def test_deadline_result_carries_partial_tokens(self,
                                                    model_and_params):
        model, params = model_and_params
        engine = InferenceEngine(model, params, config=serve_config())
        prompt = seeded_prompts(1, seed=41)[0]
        fast = engine.submit(prompt, max_new_tokens=8, request_id="fast")
        doomed = engine.submit(prompt, max_new_tokens=8,
                               request_id="doomed", deadline_ms=1)
        engine.step()                             # admit both, decode once
        import time as _t

        _t.sleep(0.01)                            # let the deadline lapse
        results = engine.run()
        assert results["doomed"]["finish_reason"] == REASON_DEADLINE
        assert len(results["doomed"]["tokens"]) < 8      # partial
        assert results["fast"]["finish_reason"] == REASON_LENGTH
        assert results["fast"]["tokens"] == reference_generate(
            model, params, prompt, 8)
        assert engine.allocator.free_blocks \
            == engine.inference_config.kv_blocks - 1
        engine.close()
        assert fast and doomed

    def test_config_deadline_applies_to_every_request(self,
                                                      model_and_params):
        model, params = model_and_params
        engine = InferenceEngine(
            model, params, config=serve_config(request_deadline_ms=1))
        rid = engine.submit(seeded_prompts(1, seed=42)[0],
                            max_new_tokens=8)
        import time as _t

        engine.step()
        _t.sleep(0.01)
        out = engine.run()[rid]
        assert out["finish_reason"] == REASON_DEADLINE
        engine.close()

    def test_prefill_raise_aborts_cleanly(self, model_and_params):
        model, params = model_and_params
        engine = InferenceEngine(model, params, config=serve_config())
        initial_free = engine.allocator.free_blocks
        engine.submit(seeded_prompts(1, seed=43)[0], max_new_tokens=4,
                      request_id="r")

        def exploding_prefill(*a, **k):
            raise RuntimeError("chaos: prefill died")

        real = dict(engine._prefills)
        engine._prefills = {b: exploding_prefill for b in real}
        with pytest.raises(RuntimeError, match="prefill died"):
            engine.step()
        assert engine.allocator.free_blocks == initial_free
        assert engine.scheduler.slots \
            == [None] * engine.inference_config.max_batch_slots
        # the engine recovers once the fault clears: the aborted request
        # is gone from the queue (the router owns the retry), new ones run
        engine._prefills = real
        rid = engine.submit(seeded_prompts(1, seed=44)[0],
                            max_new_tokens=4)
        assert len(engine.run()[rid]["tokens"]) == 4
        engine.close()


# ---------------------------------------------------------------------------
# front-end: shedding, degradation, requeue, exactly-once
# ---------------------------------------------------------------------------

def _fleet(model_and_params, n=2, **cfg_overrides):
    model, params = model_and_params
    return [InferenceEngine(model, params,
                            config=serve_config(**cfg_overrides))
            for _ in range(n)]


class TestServingFrontend:
    def test_round_robin_completion_and_parity(self, model_and_params):
        model, params = model_and_params
        replicas = _fleet(model_and_params)
        fe = ServingFrontend(replicas)
        prompts = seeded_prompts(6, seed=51)
        rids = [fe.submit(p, max_new_tokens=4) for p in prompts]
        results = fe.run()
        assert set(results) == set(rids)
        for rid, p in zip(rids, prompts):
            assert results[rid]["tokens"] == reference_generate(
                model, params, p, 4)
        # both replicas actually served
        assert all(e.generated_tokens > 0 for e in replicas)
        for e in replicas:
            e.close()

    def test_shed_at_max_queue_depth(self, model_and_params):
        replicas = _fleet(model_and_params, n=1, max_queue_depth=2)
        fe = ServingFrontend(replicas)
        prompts = seeded_prompts(3, seed=52)
        fe.submit(prompts[0], max_new_tokens=2)
        fe.submit(prompts[1], max_new_tokens=2)
        with pytest.raises(ServingOverloadError) as err:
            fe.submit(prompts[2], max_new_tokens=2)
        assert err.value.queue_depth == 2
        assert err.value.max_queue_depth == 2
        assert fe.shed_total == 1
        results = fe.run()                 # the admitted two still finish
        assert len(results) == 2
        assert fe.resilience_receipt()["shed_requests"] == 1
        replicas[0].close()

    def test_degrade_caps_generation_under_pressure(self,
                                                    model_and_params):
        replicas = _fleet(model_and_params, n=1, max_queue_depth=8,
                          degrade_queue_depth=1,
                          degraded_max_new_tokens=2)
        fe = ServingFrontend(replicas)
        prompts = seeded_prompts(3, seed=53)
        a = fe.submit(prompts[0], max_new_tokens=6)   # depth 0: full cap
        b = fe.submit(prompts[1], max_new_tokens=6)   # depth 1: capped
        c = fe.submit(prompts[2], max_new_tokens=1)   # already under cap
        assert fe.degraded_total == 1
        results = fe.run()
        assert len(results[a]["tokens"]) == 6
        assert len(results[b]["tokens"]) == 2
        assert len(results[c]["tokens"]) == 1
        replicas[0].close()

    def test_dead_replica_requeues_with_parity(self, model_and_params):
        model, params = model_and_params
        replicas = _fleet(model_and_params)
        fe = ServingFrontend(replicas)
        prompts = seeded_prompts(6, seed=54)
        rids = [fe.submit(p, max_new_tokens=6) for p in prompts]
        for _ in range(2):
            fe.step()                      # both replicas mid-decode
        moved = fe.mark_dead(0)
        assert moved, "replica 0 should have owned in-flight work"
        results = fe.run()
        assert set(results) == set(rids)   # nothing lost, nothing doubled
        for rid, p in zip(rids, prompts):
            assert results[rid]["tokens"] == reference_generate(
                model, params, p, 6), (
                f"requeued request {rid} lost greedy determinism")
        receipt = fe.resilience_receipt()
        assert receipt["requeued_requests"] == len(moved)
        assert receipt["dead_replicas"] == 1
        assert receipt["recovery_latency_seconds"] is not None
        # the dead replica's allocator stayed conserved: its aborts
        # released every grant back to ITS pool
        assert replicas[0].allocator.free_blocks \
            == replicas[0].inference_config.kv_blocks - 1
        for e in replicas:
            e.close()

    def test_replica_that_raises_mid_step_is_evicted(self,
                                                     model_and_params):
        model, params = model_and_params
        replicas = _fleet(model_and_params)
        fe = ServingFrontend(replicas)
        prompts = seeded_prompts(4, seed=55)
        rids = [fe.submit(p, max_new_tokens=4) for p in prompts]
        fe.step()

        def explode():
            raise RuntimeError("chaos: replica wedged")

        replicas[0].step = explode
        results = fe.run()
        assert set(results) == set(rids)
        assert fe.live_replicas() == [1]
        for rid, p in zip(rids, prompts):
            assert results[rid]["tokens"] == reference_generate(
                model, params, p, 4)
        for e in replicas:
            e.close()

    def test_finished_results_survive_the_death_unrecomputed(
            self, model_and_params):
        # a result the dead replica already materialized is DELIVERED,
        # never re-served (exactly-once)
        replicas = _fleet(model_and_params)
        fe = ServingFrontend(replicas)
        prompts = seeded_prompts(2, seed=56)
        rids = [fe.submit(p, max_new_tokens=2) for p in prompts]
        while not all(fe.replicas[fe._owner[r]].request(r).state
                      == FINISHED for r in rids if r in fe._owner):
            fe.step()
            if not fe._owner:
                break
        dead_tokens = {rid: list(fe.results().get(rid, {}).get("tokens",
                                                               []))
                       for rid in rids}
        fe.mark_dead(0)
        assert fe.requeued_total == 0      # nothing was in flight
        results = fe.run() if (fe._owner or fe._backlog) else fe.results()
        assert set(results) == set(rids)
        for rid in rids:
            if dead_tokens[rid]:
                assert results[rid]["tokens"] == dead_tokens[rid]
        for e in replicas:
            e.close()

    def test_no_live_replicas_is_loud(self, model_and_params):
        replicas = _fleet(model_and_params, n=1)
        fe = ServingFrontend(replicas)
        fe.mark_dead(0)
        with pytest.raises(RuntimeError, match="no live replicas"):
            fe.submit(seeded_prompts(1, seed=57)[0], max_new_tokens=2)
        replicas[0].close()

    def test_deadline_counted_in_receipt(self, model_and_params):
        replicas = _fleet(model_and_params, n=1)
        fe = ServingFrontend(replicas)
        import time as _t

        fe.submit(seeded_prompts(1, seed=58)[0], max_new_tokens=8,
                  deadline_ms=1)
        fe.step()
        _t.sleep(0.01)
        fe.run()
        assert fe.resilience_receipt()["deadline_expired"] == 1
        replicas[0].close()


# ---------------------------------------------------------------------------
# the kill-at-every-step-k determinism sweep (satellite 4)
# ---------------------------------------------------------------------------

def test_kill_at_every_step_k_is_token_identical(model_and_params):
    """For EVERY step index k, killing replica 0 after k front-end
    iterations and requeuing its in-flight work onto the survivor
    yields the complete result set with tokens BIT-IDENTICAL to the
    uninterrupted reference — the greedy-determinism property the whole
    requeue design rests on."""
    model, params = model_and_params
    prompts = seeded_prompts(4, seed=61)
    reference = {i: reference_generate(model, params, p, 4)
                 for i, p in enumerate(prompts)}
    # enough iterations that the sweep crosses admission, prefill, and
    # every request's full decode on the victim
    for k in range(6):
        replicas = _fleet(model_and_params)
        fe = ServingFrontend(replicas)
        rids = [fe.submit(p, max_new_tokens=4, request_id=f"k{k}-r{i}")
                for i, p in enumerate(prompts)]
        for _ in range(k):
            fe.step()
        fe.mark_dead(0)
        results = fe.run()
        assert set(results) == set(rids), f"k={k}: lost/duplicated work"
        for i, rid in enumerate(rids):
            assert results[rid]["tokens"] == reference[i], (
                f"k={k}: request {rid} diverged after requeue")
        for e in replicas:
            e.close()
