"""Communication observability tests (``deepspeed_tpu/profiling/comm``):
the HLO collective parser and wire-bytes model, the CommLedger riding the
MemoryLedger AOT hook on a real ZeRO-2 multi-device program (exactness
against the analytic formulas), per-rank latency/skew export + the
straggler resilience hook, the report CLI's ``--comm`` section and
cross-rank clock alignment, the structured MULTICHIP record path through
``bench_diff``, and the multichip dp=1 loss-parity assert tripping on a
deliberately broken psum-for-pmean."""

import glob
import json
import os
import sys

import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.profiling import comm as cp
from deepspeed_tpu.profiling.step_profiler import StepLatencyRing
from deepspeed_tpu.telemetry import read_events, validate_event
from deepspeed_tpu.telemetry import report as report_mod

from .simple_model import SimpleModel, base_config, random_batches

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
HIDDEN = 64
LANES = 1024


# ------------------------------------------------------------ HLO parser
_HLO_SAMPLE = """\
HloModule jit_train_step, entry_computation_layout={...}
  %all-reduce.2 = f32[64,64]{1,0} all-reduce(f32[64,64]{1,0} %dot.3), channel_id=3, replica_groups=[1,4]<=[4], use_global_device_ids=true, to_apply=%add
  %all-gather = bf16[12,1024]{1,0} all-gather(bf16[3,1024]{1,0} %param.6), channel_id=7, replica_groups={{0,1,2,3},{4,5,6,7}}, dimensions={0}, use_global_device_ids=true
  %reduce-scatter.1 = f32[4,8]{1,0} reduce-scatter(f32[16,8]{1,0} %param), channel_id=2, replica_groups={{0,1,2,3}}, use_global_device_ids=true, dimensions={0}, to_apply=%region_0.4
  %collective-permute.1 = f32[16,8]{1,0} collective-permute(f32[16,8]{1,0} %param), channel_id=1, source_target_pairs={{0,1},{1,2},{2,3},{3,0}}, metadata={op_name="ppermute"}
  %all-to-all.2 = (f32[1,32]{1,0}, f32[1,32]{1,0}, f32[1,32]{1,0}, f32[1,32]{1,0}) all-to-all(f32[1,32]{1,0} %a, f32[1,32]{1,0} %b, f32[1,32]{1,0} %c, f32[1,32]{1,0} %d), channel_id=4, replica_groups={{0,1,2,3}}, dimensions={0}
  %all-gather-start = f32[8,128]{1,0} all-gather-start(f32[2,128]{1,0} %p), channel_id=9, replica_groups=[2,4]<=[8], dimensions={0}
  %all-gather-done = f32[8,128]{1,0} all-gather-done(f32[8,128]{1,0} %all-gather-start)
  %bitcast = f32[64]{0} bitcast(f32[64]{0} %all-reduce.2)
"""


def test_parse_hlo_collectives_ops_and_groups():
    ops = cp.parse_hlo_collectives(_HLO_SAMPLE)
    by_op = {}
    for rec in ops:
        by_op.setdefault(rec["op"], []).append(rec)
    # -done is the async completion of an already-counted -start
    assert [len(by_op[o]) for o in ("all-reduce", "all-gather",
                                    "reduce-scatter", "collective-permute",
                                    "all-to-all")] == [1, 2, 1, 1, 1]
    ar = by_op["all-reduce"][0]
    assert ar["out_bytes"] == 64 * 64 * 4 and ar["group"] == 4
    ag, ag_start = by_op["all-gather"]
    assert ag["out_bytes"] == 12 * 1024 * 2            # bf16
    assert ag["group"] == 4                            # explicit groups
    assert ag_start["group"] == 4                      # iota [2,4]<=[8]
    rs = by_op["reduce-scatter"][0]
    assert rs["out_bytes"] == 4 * 8 * 4 and rs["group"] == 4
    perm = by_op["collective-permute"][0]
    assert perm["out_bytes"] == 16 * 8 * 4 and perm["group"] == 4
    a2a = by_op["all-to-all"][0]
    assert a2a["out_bytes"] == 4 * 1 * 32 * 4          # tuple summed


def test_async_start_tuple_counts_result_not_operand_alias():
    """TPU lowers collectives to async -start/-done pairs whose -start
    result is a bookkeeping tuple (operand alias, result, context) —
    the payload is the LARGEST element, not the tuple sum (which would
    double-count the operand).  Sync variadic tuples still sum."""
    hlo = """\
  %ag = (f32[1,1024]{1,0}, f32[4,1024]{1,0}) all-gather-start(f32[1,1024]{1,0} %p), channel_id=1, replica_groups={{0,1,2,3}}, dimensions={0}
  %cp = (f32[2,8]{1,0}, f32[2,8]{1,0}, u32[], u32[]) collective-permute-start(f32[2,8]{1,0} %q), channel_id=2, source_target_pairs={{0,1},{1,0}}
"""
    ops = {r["op"]: r for r in cp.parse_hlo_collectives(hlo)}
    assert ops["all-gather"]["out_bytes"] == 4 * 1024 * 4   # result only
    assert ops["collective-permute"]["out_bytes"] == 2 * 8 * 4


def test_empty_replica_groups_means_all_participants():
    """``replica_groups={}`` is HLO for "every replica in one group"
    (cross-replica lowerings): it must price at the fleet size, not
    silently at group 1 / zero wire bytes."""
    hlo = ("  %ar = f32[1024]{0} all-reduce(f32[1024]{0} %p), "
           "channel_id=1, replica_groups={}, to_apply=%add\n")
    rec = cp.parse_hlo_collectives(hlo, all_participants=8)[0]
    assert rec["group"] == 8
    assert rec["wire_bytes"] == cp.predicted_wire_bytes(
        "all-reduce", 1024 * 4, 8) > 0
    # bare parse without fleet context degrades to 1 (wire 0), not crash
    assert cp.parse_hlo_collectives(hlo)[0]["group"] == 1


def test_step_entry_prices_stepwise_programs_per_step():
    """Without a fused program (the pipeline path), the step receipt
    must sum the step-wise programs WITH micro-batch multiplicity —
    fwd_bwd alone would undercount the step by ~1/acc."""
    ledger = cp.CommLedger(enabled=True)
    ledger._entries = {
        "fwd_bwd": {"collectives": 2, "payload_bytes": 100,
                    "wire_bytes": 75, "ops": {}},
        "accum": {"collectives": 0, "payload_bytes": 0,
                  "wire_bytes": 0, "ops": {}},
        "apply_update": {"collectives": 1, "payload_bytes": 40,
                         "wire_bytes": 30, "ops": {}},
        "cast_params": {"collectives": 1, "payload_bytes": 20,
                        "wire_bytes": 15, "ops": {}},
    }
    e = ledger.step_entry(grad_accumulation_steps=4)
    assert e["program"] == "stepwise"
    assert e["collectives"] == 2 * 4 + 1 + 1
    assert e["wire_bytes"] == 75 * 4 + 30 + 15
    assert ledger.step_wire_bytes(4) == e["wire_bytes"]
    # a fused entry, once present, takes over — and `prefer` picks the
    # engine's ACTIVE fused program (1-bit Adam past freeze_step)
    ledger._entries["train_step"] = {"collectives": 9,
                                     "payload_bytes": 500,
                                     "wire_bytes": 400, "ops": {}}
    ledger._entries["train_step_compressed"] = {
        "collectives": 3, "payload_bytes": 90, "wire_bytes": 60,
        "ops": {}}
    assert ledger.step_entry(4)["wire_bytes"] == 400
    compressed = ledger.step_entry(4, prefer="train_step_compressed")
    assert compressed["program"] == "train_step_compressed"
    assert compressed["wire_bytes"] == 60


def test_predicted_wire_bytes_ring_model():
    # per participant, group g, payload/result p bytes
    assert cp.predicted_wire_bytes("all-reduce", 1024, 4) == 2 * 1024 * 3 // 4
    assert cp.predicted_wire_bytes("all-gather", 1024, 4) == 1024 * 3 // 4
    assert cp.predicted_wire_bytes("reduce-scatter", 256, 4) == 256 * 3
    assert cp.predicted_wire_bytes("collective-permute", 512, 4) == 512
    assert cp.predicted_wire_bytes("all-to-all", 1024, 4) == 1024 * 3 // 4
    # group 1 = no wire traffic at all
    for op in cp.COLLECTIVE_OPS:
        assert cp.predicted_wire_bytes(op, 4096, 1) == 0


def test_collective_summary_aggregates_and_rs_payload():
    ops = cp.parse_hlo_collectives(_HLO_SAMPLE)
    entry = cp.collective_summary(ops)
    assert entry["collectives"] == 6
    # reduce-scatter's logical payload is its full input (out x group)
    assert entry["ops"]["reduce-scatter"]["payload_bytes"] == 4 * 8 * 4 * 4
    assert entry["ops"]["all-gather"]["count"] == 2
    assert entry["payload_bytes"] == sum(
        b["payload_bytes"] for b in entry["ops"].values())
    assert entry["wire_bytes"] == sum(
        b["wire_bytes"] for b in entry["ops"].values())


# ------------------------------------------- zero2 exactness (tentpole)
def _comm_engine(cpu_devices, tmp_path, dp=4, **overrides):
    cfg = base_config(steps_per_print=1,
                      telemetry={"enabled": True,
                                 "run_dir": str(tmp_path / "run")},
                      profiling={"comm_ledger": True})
    cfg["zero_optimization"] = {"stage": 2}
    cfg.update(overrides)
    mesh = make_mesh({"data": dp}, devices=cpu_devices[:dp])
    engine, *_ = deepspeed.initialize(model=SimpleModel(HIDDEN, nlayers=2),
                                      config=cfg, mesh=mesh)
    return engine


def test_zero2_all_gather_matches_analytic_wire_formula(cpu_devices,
                                                        tmp_path):
    """THE exactness receipt: on a dp=4 ZeRO-2 mesh the fused step
    program's param all-gathers move EXACTLY the flat master buffer, and
    the ledger's predicted wire bytes equal the analytic ring formula
    ``(dp-1)/dp x gathered bytes`` — computed from engine shapes, not
    from the parse."""
    dp = 4
    engine = _comm_engine(cpu_devices, tmp_path, dp=dp)
    batches = random_batches(1, 16, HIDDEN, seed=0)
    engine.train_batch(iter([batches[0]]))

    entry = engine.comm_ledger.entry("train_step")
    assert entry is not None and entry["collectives"] > 0
    flat_bytes = int(np.prod(engine.segments.shape)) * 4       # fp32
    gathers = entry["ops"]["all-gather"]
    # ZeRO-2 re-materializes the updated params from the data-sharded
    # master: every gather output is the full flat buffer
    assert gathers["payload_bytes"] == gathers["count"] * flat_bytes
    assert gathers["max_group"] == dp
    assert gathers["wire_bytes"] == (
        gathers["count"] * flat_bytes * (dp - 1) // dp)
    # the gradient reduction (XLA lowers it as all-reduce or
    # reduce-scatter depending on shape/backend) must at least carry the
    # flat gradient once; whichever form appears obeys the wire formula
    reduce_ops = {op: b for op, b in entry["ops"].items()
                  if op in ("all-reduce", "reduce-scatter")}
    assert sum(b["payload_bytes"] for b in reduce_ops.values()) \
        >= flat_bytes
    # per-op wire == formula applied to its own payload/group — the
    # whole entry is internally consistent with predicted_wire_bytes
    raw = cp.parse_hlo_collectives(
        engine._train_step_fn.compiled.as_text())
    assert entry["wire_bytes"] == sum(r["wire_bytes"] for r in raw)
    for r in raw:
        assert r["wire_bytes"] == cp.predicted_wire_bytes(
            r["op"], r["out_bytes"], r["group"])
    # the engine-level receipt agrees
    receipt = engine.comm_receipt()
    assert receipt["program"] == "train_step"
    assert receipt["wire_bytes"] == entry["wire_bytes"]
    assert engine.comm_wire_bytes_per_step() == entry["wire_bytes"]
    engine.close()


def test_comm_ledger_emits_schema_clean_events(cpu_devices, tmp_path):
    engine = _comm_engine(cpu_devices, tmp_path)
    engine.train_batch(iter(random_batches(1, 16, HIDDEN, seed=1)))
    engine.close()
    records = read_events(tmp_path / "run")
    comm = [r for r in records if r["type"] == "comm"]
    assert any(r["data"]["kind"] == "program" for r in comm)
    for r in comm:
        assert validate_event(r) == [], r
    progs = {r["data"]["program"] for r in comm
             if r["data"]["kind"] == "program"}
    assert "train_step" in progs
    prog = [r for r in comm if r["data"].get("program") == "train_step"][0]
    assert prog["data"]["mesh"] == {"data": 4}
    assert prog["data"]["wire_bytes"] > 0
    # round 11: the program event carries the host-transfer accounting
    # (0 on this CPU lowering — the receipt proves it rather than
    # leaving "no DMA ops" as an assumption) and the overlap summary
    assert prog["data"]["host_transfers"] == 0
    assert prog["data"]["host_transfer_bytes"] == 0
    ovl = prog["data"]["overlap"]
    assert ovl["overlap_schema_version"] == 1
    assert ovl["wire_seconds"] >= ovl["exposed_wire_seconds"] >= 0
    assert 0.0 <= ovl["overlap_fraction"] <= 1.0


def test_comm_ledger_gauges_include_host_transfer_bytes(cpu_devices,
                                                        tmp_path):
    engine = _comm_engine(cpu_devices, tmp_path)
    engine.train_batch(iter(random_batches(1, 16, HIDDEN, seed=1)))
    names = engine.telemetry.registry.names()
    engine.close()
    assert "comm/program/train_step/host_transfer_bytes" in names
    assert "comm/program/train_step/exposed_wire_seconds" in names
    assert "comm/program/train_step/overlap_fraction" in names


def test_comm_ledger_off_by_default_without_telemetry(cpu_devices):
    cfg = base_config(steps_per_print=10 ** 9)
    mesh = make_mesh({"data": 2}, devices=cpu_devices[:2])
    engine, *_ = deepspeed.initialize(model=SimpleModel(HIDDEN, nlayers=2),
                                      config=cfg, mesh=mesh)
    assert not engine.comm_ledger.enabled
    engine.train_batch(iter(random_batches(1, 16, HIDDEN, seed=2)))
    assert engine.comm_receipt() is None
    assert engine.comm_wire_bytes_per_step() is None


# ------------------------------------------------- latency ring + skew
def test_latency_ring_beat_pause_snapshot():
    ring = StepLatencyRing(capacity=8)
    snap = ring.latency_snapshot()
    assert snap["n"] == 0 and snap["p50"] == 0.0
    ring.beat()                       # arms; records nothing yet
    assert ring.latency_snapshot()["n"] == 0
    ring.beat()
    assert ring.latency_snapshot()["n"] == 1
    ring.pause()                      # a long gap must not be recorded
    ring.beat()
    assert ring.latency_snapshot()["n"] == 1
    ring.record(0.25)
    snap = ring.latency_snapshot()
    assert snap["max"] >= 0.25 and snap["last"] == 0.25
    assert snap["steps"] == ring.total_steps


def test_latency_publish_read_roundtrip_and_torn_file(tmp_path):
    snap = {"n": 4, "steps": 4, "last": 0.01, "mean": 0.01, "p50": 0.01,
            "p95": 0.01, "max": 0.02}
    path = cp.publish_rank_latency(tmp_path, 3, snap, step=7)
    assert path and os.path.basename(path) == "latency-rank3.json"
    (tmp_path / "latency-rank5.json").write_text('{"torn')   # crashed rank
    fleet = cp.read_fleet_latencies(tmp_path)
    assert list(fleet) == [3]
    assert fleet[3]["step"] == 7 and fleet[3]["rank"] == 3
    assert fleet[3]["ts"] > 0                                # freshness stamp


def test_read_fleet_latencies_staleness_guards(tmp_path):
    """Dead ranks from a previous or larger run must not pollute skew:
    too-old publishes and ranks outside the current world are dropped."""
    snap = {"n": 4, "steps": 4, "last": 0.01, "mean": 0.01, "p50": 0.01,
            "p95": 0.01, "max": 0.02}
    cp.publish_rank_latency(tmp_path, 0, snap)
    cp.publish_rank_latency(tmp_path, 1, snap)
    cp.publish_rank_latency(tmp_path, 7, snap)      # from a larger run
    stale = dict(snap, rank=2, ts=1.0)              # ancient publish
    (tmp_path / "latency-rank2.json").write_text(json.dumps(stale))
    legacy = dict(snap, rank=3)                     # pre-round-8: no ts
    (tmp_path / "latency-rank3.json").write_text(json.dumps(legacy))

    assert set(cp.read_fleet_latencies(tmp_path)) == {0, 1, 2, 3, 7}
    fresh = cp.read_fleet_latencies(tmp_path, max_age_secs=600.0,
                                    world_size=4)
    # rank 2 is stale, rank 7 outside world; ts-less rank 3 passes
    assert set(fresh) == {0, 1, 3}


def test_fleet_skew_slowest_vs_median():
    assert cp.fleet_skew({}) is None
    one = cp.fleet_skew({0: {"p50": 0.01}})
    assert one["ranks"] == 1 and one["ratio"] == 1.0
    skew = cp.fleet_skew({0: {"p50": 0.010}, 1: {"p50": 0.011},
                          2: {"p50": 0.100}})
    assert skew["slowest_rank"] == 2 and skew["ranks"] == 3
    assert skew["median"] == pytest.approx(0.011)
    assert skew["ratio"] == pytest.approx(0.100 / 0.011)


def test_injected_slow_rank_trips_straggler_and_skew_gauge(cpu_devices,
                                                           tmp_path):
    """Acceptance: an injected slow sibling rank produces a nonzero
    comm/skew gauge AND a ``straggler`` anomaly event via the resilience
    hook — all sampled at the steps_per_print cadence."""
    run_dir = tmp_path / "run"
    engine = _comm_engine(
        cpu_devices, tmp_path,
        resilience={"enabled": True, "policy": "skip",
                    "straggler_factor": 2.0})
    # two published siblings: one healthy (sub-ms, like this rank), one
    # sick — the fleet median stays healthy, the ratio explodes
    fast = {"n": 8, "steps": 8, "last": 1e-3, "mean": 1e-3, "p50": 1e-3,
            "p95": 1e-3, "max": 2e-3}
    slow = dict(fast, last=5.0, mean=5.0, p50=5.0, p95=5.0, max=5.0)
    cp.publish_rank_latency(run_dir, 1, fast, step=1)
    cp.publish_rank_latency(run_dir, 2, slow, step=1)
    for b in random_batches(3, 16, HIDDEN, seed=3):
        engine.train_batch(iter([b]))
    snap = engine.telemetry.registry.snapshot()
    assert snap["comm/skew/slowest_over_median"]["value"] > 2.0
    assert snap["comm/skew/ranks"]["value"] == 3.0
    assert snap["resilience/anomalies"]["value"] >= 1
    engine.close()
    records = read_events(run_dir)
    stragglers = [r for r in records if r["type"] == "anomaly"
                  and r["data"]["kind"] == "straggler"]
    assert stragglers, "no straggler anomaly event"
    assert "rank 2" in stragglers[0]["data"]["detail"]
    kinds = {r["data"]["kind"] for r in records if r["type"] == "comm"}
    assert {"program", "latency", "skew"} <= kinds
    # this rank's own latency file landed for its siblings to read
    assert os.path.isfile(run_dir / "latency-rank0.json")


# ------------------------------------------------------- report --comm
def test_report_comm_section_from_run_artifacts(cpu_devices, tmp_path):
    run_dir = tmp_path / "run"
    engine = _comm_engine(cpu_devices, tmp_path)
    cp.publish_rank_latency(run_dir, 1, {"n": 4, "steps": 4, "last": 1.0,
                                         "mean": 1.0, "p50": 1.0,
                                         "p95": 1.0, "max": 1.0}, step=1)
    for b in random_batches(3, 16, HIDDEN, seed=4):
        engine.train_batch(iter([b]))
    engine.close()
    text, records = report_mod.generate_report(str(run_dir), comm=True)
    assert "comm programs" in text
    assert "train_step" in text
    assert "per-step cross-rank latency" in text
    assert "skew" in text
    assert "predicted step wire" in text
    # CLI flag path agrees
    assert report_mod.main(["report", str(run_dir), "--comm"]) == 0


def test_report_clock_aligns_respawned_rank(tmp_path):
    """The launcher-respawn fixture: rank1's run starts 300s after
    rank0's, but its events must interleave by run-relative time (each
    stream anchored on its own first spawn/step event), not sort after
    rank0's entire run."""
    t0 = 1_700_000_000.0

    def write_stream(rank, start):
        rows = [
            {"schema_version": 1, "seq": 0, "rank": rank, "ts": start,
             "type": "run_start", "step": 0, "data": {"world_size": 2}},
            {"schema_version": 1, "seq": 1, "rank": rank, "ts": start + 1,
             "type": "anomaly", "step": 1,
             "data": {"kind": "loss_spike", "detail": "z=9",
                      "consecutive": 1}},
        ]
        with open(tmp_path / f"events-rank{rank}.jsonl", "w") as f:
            f.write("\n".join(json.dumps(r) for r in rows) + "\n")

    write_stream(0, t0)
    write_stream(1, t0 + 300)          # respawned 300s later

    records = read_events(tmp_path)
    aligned = report_mod.align_records(records)
    # aligned: both run_starts at rel 0.0, both anomalies at rel 1.0 —
    # interleaved, instead of rank1's whole run trailing rank0's
    rels = [(r["rank"], r["type"], round(r["_rel"], 3)) for r in aligned]
    assert rels[0][2] == 0.0 and rels[1][2] == 0.0
    assert {rels[0][0], rels[1][0]} == {0, 1}
    assert rels[2][2] == 1.0 and rels[3][2] == 1.0
    text = "\n".join(report_mod.format_timeline(records))
    assert "t=+    1.000s" in text
    assert "t=+  301.000s" not in text


def test_comm_summary_measured_uses_median_of_last_window(tmp_path):
    """The respawned-rank fixture, latency edition: a resized rank's
    stream holds two lives, and cross-life clock skew can sort the
    dying first life's stale (huge) snapshot LAST.  "Last snapshot
    wins" quoted exactly that outlier as the measured verdict; the
    median over the last window must shrug it off."""
    t0 = 1_700_000_000.0
    rows = [{"schema_version": 1, "seq": 0, "rank": 0, "ts": t0,
             "type": "run_start", "step": 0, "data": {"world_size": 1}}]
    # second life: healthy ~2ms snapshots...
    for i, p50 in enumerate((0.002, 0.0021, 0.0019, 0.002)):
        rows.append({"schema_version": 1, "seq": i + 1, "rank": 0,
                     "ts": t0 + 10 + i, "type": "comm", "step": i + 1,
                     "data": {"kind": "latency", "n": 4, "steps": 4,
                              "last": p50, "mean": p50, "p50": p50,
                              "p95": p50, "max": p50}})
    # ...then the first life's stale 30s snapshot (its clock ran ahead,
    # so it merges AFTER the healthy ones)
    rows.append({"schema_version": 1, "seq": 99, "rank": 0,
                 "ts": t0 + 20, "type": "comm", "step": 1,
                 "data": {"kind": "latency", "n": 1, "steps": 1,
                          "last": 30.0, "mean": 30.0, "p50": 30.0,
                          "p95": 30.0, "max": 30.0}})
    with open(tmp_path / "events-rank0.jsonl", "w") as f:
        f.write("\n".join(json.dumps(r) for r in rows) + "\n")
    records = read_events(tmp_path)
    measured = report_mod.measured_latencies(records)
    # median of the last-5 window [2, 2.1, 1.9, 2, 30000] ms = 2 ms
    assert abs(measured["rank0"] - 0.002) < 1e-9
    lines = "\n".join(report_mod.comm_summary(records))
    assert "2.00ms" in lines and "30000" not in lines


# ------------------------------------- MULTICHIP record + bench_diff CI
def test_load_bench_record_extracts_multichip_tail(tmp_path):
    from deepspeed_tpu.tools.bench_diff import load_bench_record

    rec = {"metric": "dryrun_multichip", "multichip_schema_version": 1,
           "n_devices": 8, "leg_zero2_status": "ok",
           "leg_zero2_loss": 5.54, "leg_zero2_comm_wire_bytes": 3007634,
           "legs_ok": 9, "legs_failed": 0, "legs_skipped": 0,
           "axes": "pipe,data,seq,model,expert"}
    wrapper = {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
               "tail": "log line\n" + json.dumps(rec)
                       + "\nRuntimeError: trailing noise"}
    path = tmp_path / "MULTICHIP_new.json"
    path.write_text(json.dumps(wrapper))
    loaded = load_bench_record(str(path))
    assert loaded["legs_ok"] == 9
    assert loaded["leg_zero2_comm_wire_bytes"] == 3007634

    # legacy blob (rounds <= 7): scalar fields survive, prose dropped
    legacy = tmp_path / "MULTICHIP_old.json"
    legacy.write_text(json.dumps({"n_devices": 8, "rc": 0, "ok": True,
                                  "skipped": False, "tail": "just logs"}))
    loaded = load_bench_record(str(legacy))
    assert loaded == {"n_devices": 8, "rc": 0, "ok": True,
                      "skipped": False}


def test_multichip_record_fields_are_schema_registered():
    from deepspeed_tpu.tools.bench_schema import (threshold_for,
                                                  validate_record)

    rec = {"metric": "dryrun_multichip", "multichip_schema_version": 1,
           "n_devices": 8, "axes": "data,model",
           "legs_ok": 9, "legs_failed": 0, "legs_skipped": 0,
           "leg_pipe_3d_status": "ok", "leg_pipe_3d_loss": 2.2,
           "leg_pipe_3d_loss2": 1.8, "leg_pipe_3d_parity_ref_loss": 2.2,
           "leg_pipe_3d_comm_collectives": 22,
           "leg_pipe_3d_comm_payload_bytes": 68616,
           "leg_pipe_3d_comm_wire_bytes": 74760,
           "leg_moe_status": "skipped", "leg_moe_note": "odd devices",
           "leg_zero3_status": "failed", "leg_zero3_error": "boom",
           "ok": True, "rc": 0, "skipped": False}
    assert validate_record(rec) == []
    assert threshold_for("leg_pipe_3d_comm_wire_bytes") == ("lower", 0.25)
    assert threshold_for("legs_ok") == ("higher", 0.0)
    assert threshold_for("leg_pipe_3d_loss") == (None, None)
    # type drift is caught
    assert validate_record({"leg_pipe_3d_loss": "high"})
    assert validate_record({"legs_ok": True})          # bool smuggled


def test_bench_comm_receipt_fields_registered():
    from deepspeed_tpu.tools.bench_schema import (threshold_for,
                                                  validate_record)

    rec = {"comm_collectives_per_step": 0, "comm_wire_bytes_per_step": 0,
           "offload_gpt2_xl_comm_wire_bytes_per_step": 123,
           "offload_gpt2_xl_comm_collectives_per_step": 9}
    assert validate_record(rec) == []
    assert threshold_for("comm_wire_bytes_per_step") == ("lower", 0.25)
    assert threshold_for(
        "offload_gpt2_xl_comm_wire_bytes_per_step") == ("lower", 0.25)


def test_bench_diff_self_check_covers_multichip_history(capsys):
    """CI satellite: the checked-in MULTICHIP_r0*.json sequence runs
    through the regression gate's --self-check (report-only, exit 0)."""
    from deepspeed_tpu.tools import bench_diff

    artifacts = sorted(glob.glob(os.path.join(REPO, "MULTICHIP_r*.json")))
    assert len(artifacts) >= 2
    assert bench_diff.main(["--self-check", *artifacts]) == 0
    out = capsys.readouterr().out
    assert "regression(s)" in out


# --------------------------------------------- dp=1 loss-parity asserts
def _graft():
    sys.path.insert(0, REPO)
    import __graft_entry__ as g
    return g


def test_loss_parity_assert_catches_dp_scaling():
    g = _graft()
    # reduction-order jitter passes
    g._assert_loss_parity("t", [5.543301, 5.56005], [5.543305, 5.56004])
    # a psum-for-pmean over dp=4 scales the loss by 4: must trip
    with pytest.raises(AssertionError, match="parity"):
        g._assert_loss_parity("t", [4 * 5.5433], [5.5433])
    # ... and a gradient-scale bug that only shows after the update
    with pytest.raises(AssertionError, match="step 2"):
        g._assert_loss_parity("t", [5.5433, 5.61], [5.5433, 5.56])


def test_zero2_leg_parity_trips_on_broken_pmean(cpu_devices, tmp_path,
                                                monkeypatch):
    """The satellite's proof: run the REAL zero2 dryrun leg with its
    loss scaled by the dp degree — exactly the arithmetic a
    psum-where-pmean-belongs over the data axis produces — and the
    leg's dp=1 parity assert must fail loudly (the old finiteness-only
    check passed this, since dp x loss is still finite)."""
    g = _graft()
    real_tiny = g._tiny_gpt2

    class _SumNotMean:
        """Wraps the tiny model: multiplies the loss by dp on the
        multi-device leg engine only (the dp=1 reference and the
        elastic-reload engine see the true loss)."""

        def __init__(self, inner, factor):
            self._inner = inner
            self._factor = factor

        def init(self, rng):
            return self._inner.init(rng)

        def apply(self, params, batch, **kw):
            return self._inner.apply(params, batch, **kw) * self._factor

    calls = {"n": 0}

    def broken_tiny(**kw):
        calls["n"] += 1
        inner = real_tiny(**kw)
        # first construction = the dp x tp leg engine; later ones are
        # the parity reference / elastic engines and stay correct
        return _SumNotMean(inner, 2.0) if calls["n"] == 1 else inner

    monkeypatch.setattr(g, "_tiny_gpt2", broken_tiny)
    with pytest.raises(AssertionError, match="parity"):
        g._dryrun_dp_tp_zero2_elastic_ckpt(cpu_devices[:4], str(tmp_path))
