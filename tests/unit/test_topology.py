"""Topology math tests (modeled on reference ``tests/unit/test_topology.py``)."""

import pytest

from deepspeed_tpu.parallel import (PipeDataParallelTopology,
                                    PipeModelDataParallelTopology,
                                    ProcessTopology)


def test_topology_2d():
    topo = ProcessTopology(axes=["row", "col"], dims=[2, 2])
    assert topo.world_size() == 4
    assert topo.get_rank(row=0, col=0) == 0
    assert topo.get_rank(row=0, col=1) == 1
    assert topo.get_rank(row=1, col=0) == 2
    assert topo.get_rank(row=1, col=1) == 3
    assert topo.get_axis_list(axis="row", idx=0) == [0, 1]
    assert topo.get_axis_list(axis="row", idx=1) == [2, 3]
    assert topo.get_axis_list(axis="col", idx=0) == [0, 2]
    assert topo.get_axis_list(axis="col", idx=1) == [1, 3]


def test_topology_dims():
    topo = ProcessTopology(axes=["a", "b", "c"], dims=[2, 3, 4])
    assert topo.world_size() == 24
    assert topo.get_dim("a") == 2
    assert topo.get_dim("b") == 3
    assert topo.get_dim("c") == 4


def test_topology_match():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=4, num_dp=2)
    print(topo.mapping)
    ranks = topo.filter_match(pipe=0, data=1)
    assert ranks == [4, 5, 6, 7]
    ranks = topo.filter_match(pipe=0, model=1)
    assert ranks == [1, 5]


def test_topology_rank_repr():
    topo = ProcessTopology(axes=["pipe", "data"], dims=[2, 2])
    assert topo.get_rank_repr(rank=0) == ""
    assert topo.get_rank_repr(rank=0, omit_axes=["pipe"]) == "data_00"
    assert topo.get_rank_repr(rank=1, omit_axes=["pipe"]) == "data_01"

    topo = ProcessTopology(axes=["pipe", "data", "model"], dims=[2, 2, 2])
    assert topo.get_rank_repr(rank=0) == "model_00"
    assert topo.get_rank_repr(rank=1) == "model_01"
    assert topo.get_rank_repr(rank=0, omit_axes=["pipe"]) == "data_00-model_00"
    assert topo.get_rank_repr(rank=3, omit_axes=["pipe"]) == "data_01-model_01"


def test_topology_3d():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    # axes order: pipe, data, model (model innermost)
    assert topo.get_rank(pipe=0, data=0, model=0) == 0
    assert topo.get_rank(pipe=0, data=0, model=1) == 1
    assert topo.get_rank(pipe=0, data=1, model=0) == 2
    assert topo.get_rank(pipe=1, data=0, model=0) == 4

    # model-parallel groups vary fastest
    assert topo.get_axis_comm_lists("model") == [[0, 1], [2, 3], [4, 5], [6, 7]]
    assert topo.get_axis_comm_lists("data") == [[0, 2], [1, 3], [4, 6], [5, 7]]
    assert topo.get_axis_comm_lists("pipe") == [[0, 4], [1, 5], [2, 6], [3, 7]]


def test_topology_comm_list():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=2)
    assert topo.get_rank(pipe=0, data=0) == 0
    assert topo.get_rank(pipe=0, data=1) == 1
    assert topo.get_rank(pipe=1, data=0) == 2
    assert topo.get_rank(pipe=1, data=1) == 3

    pipe_list = [[0, 2], [1, 3]]
    assert topo.get_axis_comm_lists("pipe") == pipe_list
    data_list = [[0, 1], [2, 3]]
    assert topo.get_axis_comm_lists("data") == data_list
    assert topo.get_axis_comm_lists("bogus") == []

    for rank in range(4):
        assert rank in pipe_list[0] or rank in pipe_list[1]
        assert rank in data_list[0] or rank in data_list[1]


def test_get_rank_slices():
    topo = ProcessTopology(axes=["a", "b"], dims=[2, 2])
    with pytest.raises(ValueError):
        topo.get_rank(a=0)
