"""Elasticity tests (modeled on reference ``tests/unit/test_elastic.py``)."""

import pytest

import deepspeed_tpu as deepspeed

base_ds_config = {
    "elasticity": {
        "enabled": True,
        "max_train_batch_size": 10000,
        "micro_batch_sizes": [8, 12, 16, 17],
        "min_gpus": 32,
        "max_gpus": 1500,
        "min_time": 20,
        "version": 0.1,
    }
}


def copy_config():
    import copy

    return copy.deepcopy(base_ds_config)


def test_basic_10k():
    ds_config = copy_config()
    final_batch_size, valid_gpus = deepspeed.elasticity.compute_elastic_config(
        ds_config=ds_config, target_deepspeed_version="0")
    for gpu_num in valid_gpus:
        assert final_batch_size % gpu_num == 0
        batch_per_gpu = final_batch_size // gpu_num
        found_valid_mb = any(batch_per_gpu % mb == 0
                             for mb in ds_config["elasticity"]["micro_batch_sizes"])
        assert found_valid_mb, "No valid mb found"
    assert len(valid_gpus) == 23
    assert final_batch_size == 9792


def test_disabled():
    ds_config = copy_config()
    ds_config["elasticity"]["enabled"] = False
    with pytest.raises(deepspeed.elasticity.ElasticityError):
        deepspeed.elasticity.compute_elastic_config(
            ds_config=ds_config, target_deepspeed_version="0")


def test_valid_world_size():
    ds_config = copy_config()
    final_batch_size, valid_gpus, mbsize = deepspeed.elasticity.compute_elastic_config(
        ds_config=ds_config, target_deepspeed_version="0", world_size=64)
    assert mbsize == 17


def test_invalid_world_size():
    ds_config = copy_config()
    with pytest.raises(deepspeed.elasticity.ElasticityIncompatibleWorldSize):
        deepspeed.elasticity.compute_elastic_config(
            ds_config=ds_config, target_deepspeed_version="0", world_size=128)


def test_future_elastic_version():
    ds_config = copy_config()
    ds_config["elasticity"]["version"] = "0.2"
    with pytest.raises(deepspeed.elasticity.ElasticityError):
        deepspeed.elasticity.compute_elastic_config(
            ds_config=ds_config, target_deepspeed_version="0")


def test_missing_max_batch():
    ds_config = copy_config()
    del ds_config["elasticity"]["max_train_batch_size"]
    with pytest.raises(deepspeed.elasticity.ElasticityError):
        deepspeed.elasticity.compute_elastic_config(
            ds_config=ds_config, target_deepspeed_version="0")


def test_missing_micro_batch():
    ds_config = copy_config()
    del ds_config["elasticity"]["micro_batch_sizes"]
    with pytest.raises(deepspeed.elasticity.ElasticityError):
        deepspeed.elasticity.compute_elastic_config(
            ds_config=ds_config, target_deepspeed_version="0")


def test_empty_config():
    ds_config = {"elasticity": {"enabled": True}}
    with pytest.raises(deepspeed.elasticity.ElasticityError):
        deepspeed.elasticity.compute_elastic_config(
            ds_config=ds_config, target_deepspeed_version="0")


def test_config_batch_override():
    """Elasticity overrides the batch triple inside DeepSpeedConfig
    (reference ``runtime/config.py:538-588``)."""
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    ds_config = copy_config()
    cfg = DeepSpeedConfig(ds_config, world_size=64)
    assert cfg.train_batch_size == 9792
    assert cfg.train_micro_batch_size_per_gpu == 17
    assert cfg.gradient_accumulation_steps == 9792 // (17 * 64)


def test_config_batch_conflict_raises():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    ds_config = copy_config()
    ds_config["train_batch_size"] = 4
    with pytest.raises(deepspeed.elasticity.ElasticityError):
        DeepSpeedConfig(ds_config, world_size=64)


def test_config_batch_conflict_ignored():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    ds_config = copy_config()
    ds_config["train_batch_size"] = 4
    ds_config["elasticity"]["ignore_non_elastic_batch_info"] = True
    cfg = DeepSpeedConfig(ds_config, world_size=64)
    assert cfg.train_batch_size == 9792


# ---------------------------------------------------------------------------
# version handling under THIS repo's versioning (satellite: same-config
# respawn must never be rejected)
# ---------------------------------------------------------------------------

def test_parse_version_pads_and_compares():
    from deepspeed_tpu.elasticity.elasticity import parse_version

    assert parse_version("0") == parse_version("0.0.0")
    assert parse_version("0.1") == (0, 1, 0)
    assert parse_version("0.3.11") > parse_version("0.3.9")
    with pytest.raises(deepspeed.elasticity.ElasticityConfigError):
        parse_version("0.3.11rc1")


def test_compute_elastic_config_defaults_to_repo_version():
    # no target version argument: the package's own version is used and
    # satisfies the minimum, so the call behaves exactly as before
    final_batch_size, valid_gpus = deepspeed.elasticity.compute_elastic_config(
        ds_config=copy_config())
    assert final_batch_size == 9792
    assert len(valid_gpus) == 23


def test_elastic_algorithm_version_accepts_dotted_forms():
    """v0.1 spelled 0.1 / "0.1" / "0.1.0" all select the v0.1 algorithm
    (numeric-tuple comparison), and "0.2.0" still raises as future."""
    for version in (0.1, "0.1", "0.1.0"):
        cfg = copy_config()
        cfg["elasticity"]["version"] = version
        final, _ = deepspeed.elasticity.compute_elastic_config(ds_config=cfg)
        assert final == 9792, version
    cfg = copy_config()
    cfg["elasticity"]["version"] = "0.2.0"
    with pytest.raises(deepspeed.elasticity.ElasticityError):
        deepspeed.elasticity.compute_elastic_config(ds_config=cfg)


def test_ensure_immutable_accepts_same_config_respawn(monkeypatch):
    """The launcher re-exports the schedule through json on every
    respawn; value-identical configs with drifted representations
    (float vs str version, list order) must pass the immutability
    check — rejecting them would kill every elastic resume."""
    import json as _json

    from deepspeed_tpu.elasticity import normalized_elastic_config

    block = copy_config()["elasticity"]
    exported = normalized_elastic_config(dict(block, version="0.1"))
    monkeypatch.setenv("DEEPSPEED_ELASTICITY_CONFIG",
                       _json.dumps(exported))
    # runtime sees version as float, env var carried it normalized
    deepspeed.elasticity.ensure_immutable_elastic_config(block)
    # micro-batch order is representation too
    reordered = dict(block,
                     micro_batch_sizes=list(block["micro_batch_sizes"])[::-1])
    deepspeed.elasticity.ensure_immutable_elastic_config(reordered)
    # a REAL schedule drift still fails loudly
    with pytest.raises(deepspeed.elasticity.ElasticityConfigError):
        deepspeed.elasticity.ensure_immutable_elastic_config(
            dict(block, max_train_batch_size=4096))


def test_elasticity_block_validated_by_config_schema():
    """The elasticity block rides DSC4xx key validation like every
    other config section (unknown keys warn with a did-you-mean)."""
    from deepspeed_tpu.tools.dslint.schema import validate_config_dict

    issues = validate_config_dict(
        {"elasticity": dict(copy_config()["elasticity"], bogus_key=1)})
    assert any("bogus_key" in i.message for i in issues)
    assert not validate_config_dict(copy_config())


# ---------------------------------------------------------------------------
# elastic supervisor: the resize-on-failure planner half
# ---------------------------------------------------------------------------

SUPERVISOR_BLOCK = {"enabled": True, "max_train_batch_size": 16,
                    "micro_batch_sizes": [2, 4], "min_gpus": 1,
                    "max_gpus": 8, "version": 0.1}


def test_plan_world_size_picks_largest_fit():
    from deepspeed_tpu.elasticity import plan_world_size

    plan = plan_world_size(SUPERVISOR_BLOCK, 8)
    assert plan.world_size == 8 and plan.global_batch == 16
    assert plan.valid_world_sizes == (1, 2, 4, 8)
    # 7 survivors: largest valid count that fits is 4 — the 8->4 resize
    plan = plan_world_size(SUPERVISOR_BLOCK, 7)
    assert plan.world_size == 4


def test_plan_world_size_keeps_global_batch_on_schedule():
    from deepspeed_tpu.elasticity import plan_world_size

    for budget in (8, 6, 4, 2, 1):
        plan = plan_world_size(SUPERVISOR_BLOCK, budget)
        assert (plan.micro_batch * plan.grad_accum * plan.world_size
                == plan.global_batch == 16)
        assert plan.micro_batch in SUPERVISOR_BLOCK["micro_batch_sizes"]


def test_plan_world_size_raises_below_schedule_floor():
    from deepspeed_tpu.elasticity import plan_world_size

    with pytest.raises(deepspeed.elasticity.ElasticityIncompatibleWorldSize):
        plan_world_size(SUPERVISOR_BLOCK, 0)
    with pytest.raises(deepspeed.elasticity.ElasticityIncompatibleWorldSize):
        plan_world_size(dict(SUPERVISOR_BLOCK, min_gpus=4), 2)


def test_export_plan_env_contract(monkeypatch):
    """export_plan_env writes exactly what a respawned child needs: the
    planned world size (elastic_world_size reads it back) and the
    normalized schedule (ensure_immutable accepts it verbatim)."""
    import json as _json

    from deepspeed_tpu.elasticity import (elastic_world_size,
                                          export_plan_env, plan_world_size)

    plan = plan_world_size(SUPERVISOR_BLOCK, 5)
    env = export_plan_env({}, SUPERVISOR_BLOCK, plan)
    assert env["DS_ELASTIC_TARGET_WORLD_SIZE"] == str(plan.world_size) == "4"
    monkeypatch.setenv("DS_ELASTIC_TARGET_WORLD_SIZE",
                       env["DS_ELASTIC_TARGET_WORLD_SIZE"])
    assert elastic_world_size() == 4
    monkeypatch.setenv("DEEPSPEED_ELASTICITY_CONFIG",
                       env["DEEPSPEED_ELASTICITY_CONFIG"])
    deepspeed.elasticity.ensure_immutable_elastic_config(SUPERVISOR_BLOCK)
    # and the exported json is valid input to the planner again
    reparsed = _json.loads(env["DEEPSPEED_ELASTICITY_CONFIG"])
    assert plan_world_size(reparsed, 5).world_size == 4


def test_elastic_world_size_default(monkeypatch):
    from deepspeed_tpu.elasticity import elastic_world_size

    monkeypatch.delenv("DS_ELASTIC_TARGET_WORLD_SIZE", raising=False)
    assert elastic_world_size() is None
    assert elastic_world_size(default=8) == 8
