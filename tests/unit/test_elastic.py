"""Elasticity tests (modeled on reference ``tests/unit/test_elastic.py``)."""

import pytest

import deepspeed_tpu as deepspeed

base_ds_config = {
    "elasticity": {
        "enabled": True,
        "max_train_batch_size": 10000,
        "micro_batch_sizes": [8, 12, 16, 17],
        "min_gpus": 32,
        "max_gpus": 1500,
        "min_time": 20,
        "version": 0.1,
    }
}


def copy_config():
    import copy

    return copy.deepcopy(base_ds_config)


def test_basic_10k():
    ds_config = copy_config()
    final_batch_size, valid_gpus = deepspeed.elasticity.compute_elastic_config(
        ds_config=ds_config, target_deepspeed_version="0")
    for gpu_num in valid_gpus:
        assert final_batch_size % gpu_num == 0
        batch_per_gpu = final_batch_size // gpu_num
        found_valid_mb = any(batch_per_gpu % mb == 0
                             for mb in ds_config["elasticity"]["micro_batch_sizes"])
        assert found_valid_mb, "No valid mb found"
    assert len(valid_gpus) == 23
    assert final_batch_size == 9792


def test_disabled():
    ds_config = copy_config()
    ds_config["elasticity"]["enabled"] = False
    with pytest.raises(deepspeed.elasticity.ElasticityError):
        deepspeed.elasticity.compute_elastic_config(
            ds_config=ds_config, target_deepspeed_version="0")


def test_valid_world_size():
    ds_config = copy_config()
    final_batch_size, valid_gpus, mbsize = deepspeed.elasticity.compute_elastic_config(
        ds_config=ds_config, target_deepspeed_version="0", world_size=64)
    assert mbsize == 17


def test_invalid_world_size():
    ds_config = copy_config()
    with pytest.raises(deepspeed.elasticity.ElasticityIncompatibleWorldSize):
        deepspeed.elasticity.compute_elastic_config(
            ds_config=ds_config, target_deepspeed_version="0", world_size=128)


def test_future_elastic_version():
    ds_config = copy_config()
    ds_config["elasticity"]["version"] = "0.2"
    with pytest.raises(deepspeed.elasticity.ElasticityError):
        deepspeed.elasticity.compute_elastic_config(
            ds_config=ds_config, target_deepspeed_version="0")


def test_missing_max_batch():
    ds_config = copy_config()
    del ds_config["elasticity"]["max_train_batch_size"]
    with pytest.raises(deepspeed.elasticity.ElasticityError):
        deepspeed.elasticity.compute_elastic_config(
            ds_config=ds_config, target_deepspeed_version="0")


def test_missing_micro_batch():
    ds_config = copy_config()
    del ds_config["elasticity"]["micro_batch_sizes"]
    with pytest.raises(deepspeed.elasticity.ElasticityError):
        deepspeed.elasticity.compute_elastic_config(
            ds_config=ds_config, target_deepspeed_version="0")


def test_empty_config():
    ds_config = {"elasticity": {"enabled": True}}
    with pytest.raises(deepspeed.elasticity.ElasticityError):
        deepspeed.elasticity.compute_elastic_config(
            ds_config=ds_config, target_deepspeed_version="0")


def test_config_batch_override():
    """Elasticity overrides the batch triple inside DeepSpeedConfig
    (reference ``runtime/config.py:538-588``)."""
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    ds_config = copy_config()
    cfg = DeepSpeedConfig(ds_config, world_size=64)
    assert cfg.train_batch_size == 9792
    assert cfg.train_micro_batch_size_per_gpu == 17
    assert cfg.gradient_accumulation_steps == 9792 // (17 * 64)


def test_config_batch_conflict_raises():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    ds_config = copy_config()
    ds_config["train_batch_size"] = 4
    with pytest.raises(deepspeed.elasticity.ElasticityError):
        DeepSpeedConfig(ds_config, world_size=64)


def test_config_batch_conflict_ignored():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    ds_config = copy_config()
    ds_config["train_batch_size"] = 4
    ds_config["elasticity"]["ignore_non_elastic_batch_info"] = True
    cfg = DeepSpeedConfig(ds_config, world_size=64)
    assert cfg.train_batch_size == 9792
