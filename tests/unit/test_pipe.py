"""End-to-end pipeline-parallel training tests (model: reference
``tests/unit/test_pipe.py`` topology sweep + loss checks).

The pipeline program runs on the virtual 8-device CPU mesh; correctness is
checked against the identical model trained without pipelining (same init,
same data): the pipelined schedule is pure re-ordering, so losses must
match to fp tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.runtime.pipe import LayerSpec, PipelineModule, TiedLayerSpec

HIDDEN = 16


class Linear:
    """Tiny layer obeying the pipeline layer contract."""

    def __init__(self, in_dim, out_dim, act=True):
        self.in_dim, self.out_dim, self.act = in_dim, out_dim, act

    def init(self, rng):
        k = jax.random.normal(rng, (self.in_dim, self.out_dim), jnp.float32)
        return {"w": k * 0.1, "b": jnp.zeros((self.out_dim,), jnp.float32)}

    def apply(self, params, x):
        y = x @ params["w"] + params["b"]
        return jnp.tanh(y) if self.act else y


def mse_loss(outputs, labels):
    return jnp.mean((outputs - labels) ** 2)


def _specs(n_layers=8):
    return [LayerSpec(Linear, HIDDEN, HIDDEN) for _ in range(n_layers)]


def _data(micro_batches, mb_size, seed=0):
    rng = np.random.default_rng(seed)
    return [
        (rng.normal(size=(mb_size, HIDDEN)).astype(np.float32),
         rng.normal(size=(mb_size, HIDDEN)).astype(np.float32))
        for _ in range(micro_batches)
    ]


def _config(mb_size, grad_acc, dp):
    return {
        "train_micro_batch_size_per_gpu": mb_size // dp,
        "gradient_accumulation_steps": grad_acc,
        "steps_per_print": 10 ** 9,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    }


def _train(engine, data, steps):
    losses = []
    for _ in range(steps):
        loss = engine.train_batch(iter(data))
        losses.append(float(np.asarray(jax.device_get(loss))))
    return losses


@pytest.mark.parametrize("topo", [dict(pipe=4, data=2), dict(pipe=2, data=2),
                                  dict(pipe=8, data=1)])
def test_pipe_matches_sequential(topo, cpu_devices):
    micro_batches, mb_size, steps = 4, 8, 3
    data = _data(micro_batches, mb_size)

    # baseline: plain engine, same layers applied sequentially
    mesh1 = make_mesh({"data": 1}, devices=cpu_devices[:1])
    base_module = PipelineModule(_specs(), loss_fn=mse_loss)
    base_engine, *_ = deepspeed.initialize(
        model=base_module, config=_config(mb_size, micro_batches, 1), mesh=mesh1)
    base_losses = _train(base_engine, data, steps)

    n = topo["pipe"] * topo["data"]
    mesh = make_mesh(topo, devices=cpu_devices[:n])
    module = PipelineModule(_specs(), loss_fn=mse_loss)
    engine, *_ = deepspeed.initialize(
        model=module, config=_config(mb_size, micro_batches, topo["data"]),
        mesh=mesh)
    pipe_losses = _train(engine, data, steps)

    assert np.allclose(base_losses, pipe_losses, rtol=2e-4, atol=2e-5), (
        f"pipeline {topo} losses {pipe_losses} != sequential {base_losses}")
    assert pipe_losses[-1] < pipe_losses[0], "training did not reduce loss"


def test_pipe_tied_layers(cpu_devices):
    """Tied first/last layers share parameters; their gradient is the sum
    over both use sites (implicit ReduceTiedGrads)."""
    micro_batches, mb_size = 2, 8
    specs = [
        TiedLayerSpec("emb", Linear, HIDDEN, HIDDEN),
        LayerSpec(Linear, HIDDEN, HIDDEN),
        LayerSpec(Linear, HIDDEN, HIDDEN),
        TiedLayerSpec("emb", Linear, HIDDEN, HIDDEN),
    ]
    mesh = make_mesh({"pipe": 4}, devices=cpu_devices[:4])
    module = PipelineModule(specs, loss_fn=mse_loss, partition_method="uniform")
    engine, *_ = deepspeed.initialize(
        model=module, config=_config(mb_size, micro_batches, 1), mesh=mesh)
    assert set(engine.get_params()["tied"].keys()) == {"emb"}

    data = _data(micro_batches, mb_size)
    p_before = np.asarray(jax.device_get(engine.get_params()["tied"]["emb"]["w"]))
    losses = _train(engine, data, 2)
    p_after = np.asarray(jax.device_get(engine.get_params()["tied"]["emb"]["w"]))
    assert not np.allclose(p_before, p_after), "tied weights did not update"
    assert np.isfinite(losses).all()

    # parity vs sequential on the same tied model
    mesh1 = make_mesh({"data": 1}, devices=cpu_devices[:1])
    module1 = PipelineModule(specs, loss_fn=mse_loss, partition_method="uniform")
    engine1, *_ = deepspeed.initialize(
        model=module1, config=_config(mb_size, micro_batches, 1), mesh=mesh1)
    base_losses = _train(engine1, data, 2)
    assert np.allclose(base_losses, losses, rtol=2e-4, atol=2e-5)


def test_pipe_partition_methods():
    specs = [LayerSpec(Linear, HIDDEN, HIDDEN) for _ in range(6)]
    module = PipelineModule(specs, loss_fn=mse_loss, partition_method="uniform")
    parts = module.partition_layers(3)
    assert parts == [0, 2, 4, 6]

    params = module.init(jax.random.PRNGKey(0))
    counts = module.layer_param_counts(params)
    assert all(c == HIDDEN * HIDDEN + HIDDEN for c in counts)
    parts = module.partition_layers(3, param_counts=counts, method="parameters")
    assert parts[0] == 0 and parts[-1] == 6 and len(parts) == 4

    parts = module.partition_layers(2, method="type:linear")
    assert parts == [0, 3, 6]


def test_pipe_module_layer_checkpoint(tmp_path):
    specs = [LayerSpec(Linear, HIDDEN, HIDDEN) for _ in range(3)]
    module = PipelineModule(specs, loss_fn=mse_loss)
    params = module.init(jax.random.PRNGKey(0))
    module.save_state_dict(params, str(tmp_path))

    params2 = module.init(jax.random.PRNGKey(1))
    loaded = module.load_state_dir(params2, str(tmp_path))
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(loaded)):
        assert np.allclose(np.asarray(a), np.asarray(b))


def test_pipe_engine_checkpoint_roundtrip(tmp_path, cpu_devices):
    micro_batches, mb_size = 2, 8
    data = _data(micro_batches, mb_size)
    mesh = make_mesh({"pipe": 2, "data": 2}, devices=cpu_devices[:4])

    module = PipelineModule(_specs(4), loss_fn=mse_loss)
    engine, *_ = deepspeed.initialize(
        model=module, config=_config(mb_size, micro_batches, 2), mesh=mesh)
    _train(engine, data, 2)
    engine.save_checkpoint(str(tmp_path))
    expected = _train(engine, data, 1)

    module2 = PipelineModule(_specs(4), loss_fn=mse_loss)
    engine2, *_ = deepspeed.initialize(
        model=module2, config=_config(mb_size, micro_batches, 2), mesh=mesh)
    engine2.load_checkpoint(str(tmp_path))
    resumed = _train(engine2, data, 1)
    assert np.allclose(expected, resumed, rtol=1e-5, atol=1e-6)


def test_pipe_schedule_trace(cpu_devices):
    mesh = make_mesh({"pipe": 2}, devices=cpu_devices[:2])
    module = PipelineModule(_specs(4), loss_fn=mse_loss)
    engine, *_ = deepspeed.initialize(
        model=module, config=_config(4, 2, 1), mesh=mesh)
    trace = engine.schedule_trace(stage_id=0, kind="train")
    assert len(trace) == 2 * (2 + 2 - 1)
    flat = [c for step in trace for c in step]
    names = {c.name for c in flat}
    assert {"ForwardPass", "BackwardPass", "OptimizerStep"} <= names
