"""End-to-end pipeline-parallel training tests (model: reference
``tests/unit/test_pipe.py`` topology sweep + loss checks).

The pipeline program runs on the virtual 8-device CPU mesh; correctness is
checked against the identical model trained without pipelining (same init,
same data): the pipelined schedule is pure re-ordering, so losses must
match to fp tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.runtime.pipe import LayerSpec, PipelineModule, TiedLayerSpec

HIDDEN = 16


class Linear:
    """Tiny layer obeying the pipeline layer contract."""

    def __init__(self, in_dim, out_dim, act=True):
        self.in_dim, self.out_dim, self.act = in_dim, out_dim, act

    def init(self, rng):
        k = jax.random.normal(rng, (self.in_dim, self.out_dim), jnp.float32)
        return {"w": k * 0.1, "b": jnp.zeros((self.out_dim,), jnp.float32)}

    def apply(self, params, x):
        y = x @ params["w"] + params["b"]
        return jnp.tanh(y) if self.act else y


def mse_loss(outputs, labels):
    return jnp.mean((outputs - labels) ** 2)


def _specs(n_layers=8):
    return [LayerSpec(Linear, HIDDEN, HIDDEN) for _ in range(n_layers)]


def _data(micro_batches, mb_size, seed=0):
    rng = np.random.default_rng(seed)
    return [
        (rng.normal(size=(mb_size, HIDDEN)).astype(np.float32),
         rng.normal(size=(mb_size, HIDDEN)).astype(np.float32))
        for _ in range(micro_batches)
    ]


def _config(mb_size, grad_acc, dp):
    return {
        "train_micro_batch_size_per_gpu": mb_size // dp,
        "gradient_accumulation_steps": grad_acc,
        "steps_per_print": 10 ** 9,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    }


def _train(engine, data, steps):
    losses = []
    for _ in range(steps):
        loss = engine.train_batch(iter(data))
        losses.append(float(np.asarray(jax.device_get(loss))))
    return losses


@pytest.mark.parametrize("topo", [dict(pipe=4, data=2), dict(pipe=2, data=2),
                                  dict(pipe=8, data=1),
                                  dict(pipe=2, model=2, data=2)])
def test_pipe_matches_sequential(topo, cpu_devices):
    micro_batches, mb_size, steps = 4, 8, 3
    data = _data(micro_batches, mb_size)

    # baseline: plain engine, same layers applied sequentially
    mesh1 = make_mesh({"data": 1}, devices=cpu_devices[:1])
    base_module = PipelineModule(_specs(), loss_fn=mse_loss)
    base_engine, *_ = deepspeed.initialize(
        model=base_module, config=_config(mb_size, micro_batches, 1), mesh=mesh1)
    base_losses = _train(base_engine, data, steps)

    n = topo["pipe"] * topo["data"] * topo.get("model", 1)
    mesh = make_mesh(topo, devices=cpu_devices[:n])
    module = PipelineModule(_specs(), loss_fn=mse_loss)
    engine, *_ = deepspeed.initialize(
        model=module, config=_config(mb_size, micro_batches, topo["data"]),
        mesh=mesh)
    pipe_losses = _train(engine, data, steps)

    assert np.allclose(base_losses, pipe_losses, rtol=2e-4, atol=2e-5), (
        f"pipeline {topo} losses {pipe_losses} != sequential {base_losses}")
    assert pipe_losses[-1] < pipe_losses[0], "training did not reduce loss"


class TPBlock:
    """Megatron-style column→row parallel MLP block declaring its own TP
    sharding (the layer-level partition_specs contract)."""

    def __init__(self, hidden, inner):
        self.hidden, self.inner = hidden, inner

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        return {
            "w1": jax.random.normal(k1, (self.hidden, self.inner),
                                    jnp.float32) * 0.1,
            "w2": jax.random.normal(k2, (self.inner, self.hidden),
                                    jnp.float32) * 0.1,
        }

    def apply(self, params, x):
        return x + jnp.tanh(x @ params["w1"]) @ params["w2"]

    @staticmethod
    def partition_specs():
        from jax.sharding import PartitionSpec as P
        return {"w1": P(None, "model"), "w2": P("model", None)}


def test_pipe_3d_tensor_parallel_parity(cpu_devices):
    """True 3D hybrid: pipe×model×data with the layers' declared TP
    sharding actually applied to the params (reference
    PipeModelDataParallelTopology, topology.py:246 + engine.py:527-538).
    Loss trajectory must match the same model trained sequentially."""
    micro_batches, mb_size, steps = 4, 8, 3
    data = _data(micro_batches, mb_size)

    def specs():
        return [LayerSpec(TPBlock, HIDDEN, 4 * HIDDEN) for _ in range(4)]

    mesh1 = make_mesh({"data": 1}, devices=cpu_devices[:1])
    base, *_ = deepspeed.initialize(
        model=PipelineModule(specs(), loss_fn=mse_loss),
        config=_config(mb_size, micro_batches, 1), mesh=mesh1)
    base_losses = _train(base, data, steps)

    mesh3d = make_mesh({"pipe": 2, "model": 2, "data": 2},
                       devices=cpu_devices[:8])
    module = PipelineModule(specs(), loss_fn=mse_loss)
    engine, *_ = deepspeed.initialize(
        model=module, config=_config(mb_size, micro_batches, 2), mesh=mesh3d)
    # the layers' TP rules reached the engine's param shardings
    from jax.sharding import PartitionSpec as P
    eng_specs = engine._param_specs
    assert eng_specs["layers"][0]["w1"] == P(None, "model")
    assert eng_specs["layers"][0]["w2"] == P("model", None)
    pipe_losses = _train(engine, data, steps)

    assert np.allclose(base_losses, pipe_losses, rtol=2e-4, atol=2e-5), (
        f"3D losses {pipe_losses} != sequential {base_losses}")
    assert pipe_losses[-1] < pipe_losses[0]


def test_pipe_tied_layers(cpu_devices):
    """Tied first/last layers share parameters; their gradient is the sum
    over both use sites (implicit ReduceTiedGrads)."""
    micro_batches, mb_size = 2, 8
    specs = [
        TiedLayerSpec("emb", Linear, HIDDEN, HIDDEN),
        LayerSpec(Linear, HIDDEN, HIDDEN),
        LayerSpec(Linear, HIDDEN, HIDDEN),
        TiedLayerSpec("emb", Linear, HIDDEN, HIDDEN),
    ]
    mesh = make_mesh({"pipe": 4}, devices=cpu_devices[:4])
    module = PipelineModule(specs, loss_fn=mse_loss, partition_method="uniform")
    engine, *_ = deepspeed.initialize(
        model=module, config=_config(mb_size, micro_batches, 1), mesh=mesh)
    assert set(engine.get_params()["tied"].keys()) == {"emb"}

    data = _data(micro_batches, mb_size)
    p_before = np.asarray(jax.device_get(engine.get_params()["tied"]["emb"]["w"]))
    losses = _train(engine, data, 2)
    p_after = np.asarray(jax.device_get(engine.get_params()["tied"]["emb"]["w"]))
    assert not np.allclose(p_before, p_after), "tied weights did not update"
    assert np.isfinite(losses).all()

    # parity vs sequential on the same tied model
    mesh1 = make_mesh({"data": 1}, devices=cpu_devices[:1])
    module1 = PipelineModule(specs, loss_fn=mse_loss, partition_method="uniform")
    engine1, *_ = deepspeed.initialize(
        model=module1, config=_config(mb_size, micro_batches, 1), mesh=mesh1)
    base_losses = _train(engine1, data, 2)
    assert np.allclose(base_losses, losses, rtol=2e-4, atol=2e-5)


def test_pipe_partition_methods():
    specs = [LayerSpec(Linear, HIDDEN, HIDDEN) for _ in range(6)]
    module = PipelineModule(specs, loss_fn=mse_loss, partition_method="uniform")
    parts = module.partition_layers(3)
    assert parts == [0, 2, 4, 6]

    params = module.init(jax.random.PRNGKey(0))
    counts = module.layer_param_counts(params)
    assert all(c == HIDDEN * HIDDEN + HIDDEN for c in counts)
    parts = module.partition_layers(3, param_counts=counts, method="parameters")
    assert parts[0] == 0 and parts[-1] == 6 and len(parts) == 4

    parts = module.partition_layers(2, method="type:linear")
    assert parts == [0, 3, 6]


def test_pipe_module_layer_checkpoint(tmp_path):
    specs = [LayerSpec(Linear, HIDDEN, HIDDEN) for _ in range(3)]
    module = PipelineModule(specs, loss_fn=mse_loss)
    params = module.init(jax.random.PRNGKey(0))
    module.save_state_dict(params, str(tmp_path))

    params2 = module.init(jax.random.PRNGKey(1))
    loaded = module.load_state_dir(params2, str(tmp_path))
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(loaded)):
        assert np.allclose(np.asarray(a), np.asarray(b))


def test_pipe_engine_checkpoint_roundtrip(tmp_path, cpu_devices):
    micro_batches, mb_size = 2, 8
    data = _data(micro_batches, mb_size)
    mesh = make_mesh({"pipe": 2, "data": 2}, devices=cpu_devices[:4])

    module = PipelineModule(_specs(4), loss_fn=mse_loss)
    engine, *_ = deepspeed.initialize(
        model=module, config=_config(mb_size, micro_batches, 2), mesh=mesh)
    _train(engine, data, 2)
    engine.save_checkpoint(str(tmp_path))
    expected = _train(engine, data, 1)

    module2 = PipelineModule(_specs(4), loss_fn=mse_loss)
    engine2, *_ = deepspeed.initialize(
        model=module2, config=_config(mb_size, micro_batches, 2), mesh=mesh)
    engine2.load_checkpoint(str(tmp_path))
    resumed = _train(engine2, data, 1)
    assert np.allclose(expected, resumed, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("topo2", [dict(pipe=2, data=2), dict(pipe=1, data=2),
                                   dict(data=2)])
def test_pipe_checkpoint_restores_across_stage_counts(topo2, tmp_path,
                                                      cpu_devices):
    """The reference keeps per-layer checkpoint files precisely so a ckpt
    saved at S stages loads at S' (module.py:526-567, tested at
    tests/unit/test_checkpointing.py:567).  Here the params pytree is
    stage-layout-independent, so the same flat checkpoint must restore into
    pipe=2, pipe=1, and a plain data-parallel engine — with loss continuity
    against the saving engine's own next step."""
    micro_batches, mb_size = 2, 8
    data = _data(micro_batches, mb_size)
    mesh4 = make_mesh({"pipe": 4, "data": 2}, devices=cpu_devices[:8])
    module = PipelineModule(_specs(4), loss_fn=mse_loss)
    engine, *_ = deepspeed.initialize(
        model=module, config=_config(mb_size, micro_batches, 2), mesh=mesh4)
    _train(engine, data, 2)
    engine.save_checkpoint(str(tmp_path))
    expected = _train(engine, data, 2)

    n = topo2.get("pipe", 1) * topo2["data"]
    mesh2 = make_mesh(topo2, devices=cpu_devices[:n])
    module2 = PipelineModule(_specs(4), loss_fn=mse_loss)
    engine2, *_ = deepspeed.initialize(
        model=module2, config=_config(mb_size, micro_batches, topo2["data"]),
        mesh=mesh2)
    engine2.load_checkpoint(str(tmp_path))
    resumed = _train(engine2, data, 2)
    assert np.allclose(expected, resumed, rtol=2e-4, atol=2e-5), (
        f"restore at {topo2} diverged: {resumed} vs {expected}")


def test_pipe_schedule_trace(cpu_devices):
    mesh = make_mesh({"pipe": 2}, devices=cpu_devices[:2])
    module = PipelineModule(_specs(4), loss_fn=mse_loss)
    engine, *_ = deepspeed.initialize(
        model=module, config=_config(4, 2, 1), mesh=mesh)
    trace = engine.schedule_trace(stage_id=0, kind="train")
    assert len(trace) == 2 * (2 + 2 - 1)
    flat = [c for step in trace for c in step]
    names = {c.name for c in flat}
    assert {"ForwardPass", "BackwardPass", "OptimizerStep"} <= names


class Embed:
    """Embedding layer with bias, for subset weight tying (tied 'table',
    per-site 'bias')."""

    def __init__(self, vocab, hidden):
        self.vocab, self.hidden = vocab, hidden

    def init(self, rng):
        return {"table": jax.random.normal(rng, (self.vocab, self.hidden),
                                           jnp.float32) * 0.1,
                "bias": jnp.zeros((self.hidden,), jnp.float32)}

    def apply(self, params, x):
        return jnp.take(params["table"], x, axis=0) + params["bias"]


def _lm_head(params, x):
    # decode with the TIED embedding table (transposed) + this site's bias
    return x @ params["table"].T + params["bias"][:1][0]


def xent_loss(logits, labels):
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - gold)


def _gpt_like_specs(vocab=32, n_blocks=8):
    """Embedding -> transformer-ish stack -> tied LM head: the GPT-2 shape
    at toy size (8 pipeline stages need >= 10 layers)."""
    return ([TiedLayerSpec("emb", Embed, vocab, HIDDEN,
                           tied_weight_attr="table")]
            + [LayerSpec(Linear, HIDDEN, HIDDEN) for _ in range(n_blocks)]
            + [TiedLayerSpec("emb", Embed, vocab, HIDDEN,
                             forward_fn=_lm_head, tied_weight_attr="table")])


def _token_data(micro_batches, mb_size, vocab=32, seed=0):
    rng = np.random.default_rng(seed)
    return [
        (rng.integers(0, vocab, size=(mb_size, 4)).astype(np.int32),
         rng.integers(0, vocab, size=(mb_size, 4)).astype(np.int32))
        for _ in range(micro_batches)
    ]


def test_gpt_like_8stage_tied_subset_matches_sequential(cpu_devices):
    """GPT-2-shaped stack (tied embedding/LM-head via tied_weight_attr,
    per-site bias) on an 8-stage pipeline with per-tick remat: loss parity
    vs the non-pipelined run, and the tied table is stored once."""
    micro_batches, mb_size, steps = 8, 8, 3
    data = _token_data(micro_batches, mb_size)

    mesh1 = make_mesh({"data": 1}, devices=cpu_devices[:1])
    base_module = PipelineModule(_gpt_like_specs(), loss_fn=xent_loss,
                                 seed_layers=True,
                                 partition_method="uniform")
    base_engine, *_ = deepspeed.initialize(
        model=base_module, config=_config(mb_size, micro_batches, 1),
        mesh=mesh1)
    # tied subset: the table lives once under tied/, biases per slot
    p = base_engine.module.module.init(jax.random.PRNGKey(0))
    assert set(p["tied"]) == {"emb"}
    assert "bias" in p["layers"][0] and "table" not in p["layers"][0]
    assert "bias" in p["layers"][-1]
    base_losses = _train(base_engine, data, steps)

    mesh = make_mesh({"pipe": 8, "data": 1}, devices=cpu_devices[:8])
    module = PipelineModule(_gpt_like_specs(), loss_fn=xent_loss,
                            seed_layers=True, partition_method="uniform",
                            activation_checkpoint_interval=1)
    engine, *_ = deepspeed.initialize(
        model=module, config=_config(mb_size, micro_batches, 1), mesh=mesh)
    pipe_losses = _train(engine, data, steps)

    assert np.allclose(base_losses, pipe_losses, rtol=2e-4, atol=2e-5), (
        f"8-stage tied pipeline {pipe_losses} != sequential {base_losses}")
    assert pipe_losses[-1] < pipe_losses[0]


def _find_tick_remat(jaxpr):
    """True iff somewhere a remat2 eqn directly wraps the stage switch
    (cond) — the engine's per-TICK checkpoint, as opposed to apply_range's
    per-layer-chunk remats (which contain no cond)."""
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "remat2":
            inner = eqn.params["jaxpr"]
            inner = getattr(inner, "jaxpr", inner)
            if any(e.primitive.name == "cond" for e in inner.eqns):
                return True
        for key in ("jaxpr", "call_jaxpr", "body_jaxpr"):
            if key in eqn.params:
                inner = eqn.params[key]
                inner = getattr(inner, "jaxpr", inner)
                if _find_tick_remat(inner):
                    return True
        if eqn.primitive.name == "cond":
            if any(_find_tick_remat(b.jaxpr) for b in eqn.params["branches"]):
                return True
    return False


def test_per_tick_remat_in_program(cpu_devices):
    """activation_checkpoint_interval puts ONE remat around every pipeline
    tick (a remat2 region containing the stage switch); apply_range's
    per-chunk remats are disabled inside it (no double recompute)."""
    mesh = make_mesh({"pipe": 4, "data": 1}, devices=cpu_devices[:4])
    for interval, expect in ((0, False), (1, True)):
        module = PipelineModule(_specs(8), loss_fn=mse_loss,
                                activation_checkpoint_interval=interval)
        engine, *_ = deepspeed.initialize(
            model=module, config=_config(8, 2, 1), mesh=mesh)
        data = _data(2, 8)
        batch = engine._stack_micro_batches(iter(data))
        jx = jax.make_jaxpr(
            lambda p, b: jax.grad(lambda q: engine._loss_fn(
                q, b, rng=None, train=True))(p))(
            engine._module_params,
            jax.tree_util.tree_map(jnp.asarray, batch))
        assert _find_tick_remat(jx.jaxpr) == expect, (interval, str(jx)[:500])
        if interval:
            # the tick remat must be the ONLY remat: nested per-chunk
            # remats would recompute the forward twice in backward
            def count_remats(j):
                n = 0
                for e in j.eqns:
                    if e.primitive.name == "remat2":
                        n += 1
                    for key in ("jaxpr", "call_jaxpr", "body_jaxpr"):
                        if key in e.params:
                            inner = e.params[key]
                            n += count_remats(getattr(inner, "jaxpr", inner))
                    if e.primitive.name == "cond":
                        n += sum(count_remats(b.jaxpr)
                                 for b in e.params["branches"])
                return n

            assert count_remats(jx.jaxpr) == 1, "nested remat detected"


class SplitCarry:
    """Layer whose output is a (tuple) pytree boundary."""

    def __init__(self):
        pass

    def init(self, rng):
        return {"w": jnp.eye(HIDDEN)}

    def apply(self, params, x):
        if isinstance(x, tuple):
            a, b = x
            return (jnp.tanh(a @ params["w"]), b + 1.0)
        return (jnp.tanh(x @ params["w"]), jnp.zeros(x.shape[:1]))


class MergeCarry:
    def init(self, rng):
        return {"w": jnp.eye(HIDDEN)}

    def apply(self, params, x):
        a, b = x
        return a @ params["w"] + b[:, None]


def test_pytree_boundary_activations(cpu_devices):
    """Stage boundaries may carry a pytree (here (hidden, counter));
    parity vs sequential."""
    specs = [LayerSpec(SplitCarry), LayerSpec(SplitCarry),
             LayerSpec(SplitCarry), LayerSpec(MergeCarry)]
    data = _data(4, 8)

    mesh1 = make_mesh({"data": 1}, devices=cpu_devices[:1])
    base, *_ = deepspeed.initialize(
        model=PipelineModule(specs, loss_fn=mse_loss, seed_layers=True),
        config=_config(8, 4, 1), mesh=mesh1)
    base_losses = _train(base, data, 2)

    mesh = make_mesh({"pipe": 4, "data": 1}, devices=cpu_devices[:4])
    eng, *_ = deepspeed.initialize(
        model=PipelineModule(specs, loss_fn=mse_loss, seed_layers=True),
        config=_config(8, 4, 1), mesh=mesh)
    pipe_losses = _train(eng, data, 2)
    assert np.allclose(base_losses, pipe_losses, rtol=2e-4, atol=2e-5), (
        f"pytree boundary: {pipe_losses} != {base_losses}")


def test_pipeline_config_section_fills_module_defaults(cpu_devices):
    """json "pipeline" section applies knobs the module ctor left default
    (reference config.py:363-374)."""
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.parallel import make_mesh
    from deepspeed_tpu.runtime.pipe import LayerSpec, PipelineModule

    class Lin:
        def __init__(self, d):
            self.d = d

        def init(self, rng):
            return {"w": jax.random.normal(rng, (self.d, self.d)) * 0.1}

        def apply(self, p, x):
            return jnp.tanh(x @ p["w"])

    mesh = make_mesh({"pipe": 2}, devices=cpu_devices[:2])
    module = PipelineModule([LayerSpec(Lin, 8) for _ in range(4)],
                            loss_fn=lambda o, l: jnp.mean((o - l) ** 2))
    assert module.activation_checkpoint_interval == 0
    config = {"train_micro_batch_size_per_gpu": 2,
              "gradient_accumulation_steps": 2,
              "steps_per_print": 10 ** 9,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
              "pipeline": {"activation_checkpoint_interval": 1}}
    engine, *_ = deepspeed.initialize(model=module, config=config, mesh=mesh)
    assert module.activation_checkpoint_interval == 1
    x = np.random.default_rng(0).normal(size=(2, 8)).astype(np.float32)
    loss = engine.train_batch(iter([(x, x), (x, x)]))
    assert np.isfinite(float(jax.device_get(loss)))


@pytest.mark.parametrize("interleave", [2, 4])
def test_pipe_interleaved_matches_plain(interleave, cpu_devices):
    """Interleaved (virtual-stage) schedule must train identically to the
    plain fill-drain schedule: same layers, same data, same seeds →
    bit-comparable losses over several steps."""
    micro_batches, mb_size, steps = 4, 8, 3
    n_layers = 4 * interleave  # every logical stage must own >= 1 layer
    data = _data(micro_batches, mb_size)
    mesh = make_mesh({"pipe": 4}, devices=cpu_devices[:4])

    module1 = PipelineModule(_specs(n_layers), loss_fn=mse_loss)
    eng1, *_ = deepspeed.initialize(
        model=module1, config=_config(mb_size, micro_batches, 1), mesh=mesh)
    losses1 = _train(eng1, data, steps)

    module2 = PipelineModule(_specs(n_layers), loss_fn=mse_loss,
                             interleave=interleave)
    eng2, *_ = deepspeed.initialize(
        model=module2, config=_config(mb_size, micro_batches, 1), mesh=mesh)
    losses2 = _train(eng2, data, steps)

    np.testing.assert_allclose(losses2, losses1, rtol=1e-5, atol=1e-6)


def test_pipe_interleave_rejects_too_few_layers(cpu_devices):
    mesh = make_mesh({"pipe": 4}, devices=cpu_devices[:4])
    module = PipelineModule(_specs(8), loss_fn=mse_loss, interleave=4)
    engine, *_ = deepspeed.initialize(
        model=module, config=_config(8, 4, 1), mesh=mesh)
    with pytest.raises(AssertionError, match="logical stages"):
        _train(engine, _data(4, 8), 1)


def test_pipe_interleave_config_knob(cpu_devices):
    mesh = make_mesh({"pipe": 2}, devices=cpu_devices[:2])
    module = PipelineModule(_specs(4), loss_fn=mse_loss)
    config = dict(_config(4, 2, 1), pipeline={"interleave": 2})
    engine, *_ = deepspeed.initialize(model=module, config=config, mesh=mesh)
    assert module.interleave == 2
    data = _data(2, 4)
    loss = _train(engine, data, 1)
    assert np.isfinite(loss[0])


def test_pipe_interleave_rejects_ragged_microbatches(cpu_devices):
    mesh = make_mesh({"pipe": 4}, devices=cpu_devices[:4])
    module = PipelineModule(_specs(8), loss_fn=mse_loss, interleave=2)
    engine, *_ = deepspeed.initialize(
        model=module, config=_config(8, 3, 1), mesh=mesh)  # 3 % 4 != 0
    with pytest.raises(AssertionError, match="divisible"):
        _train(engine, _data(3, 8), 1)
