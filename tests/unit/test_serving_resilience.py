"""Serving-replica health plane (inference/resilience.py): the
freshness hang quorum, the weight-fingerprint consensus, SIGTERM drain,
and the zero-added-syncs guarantee with the whole plane armed.

The real-launcher serving chaos e2e (test_serving_chaos_e2e.py) drives
the same machinery across actual processes; these units pin each
verdict path in isolation.
"""

import json
import os
import signal
import time

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference import InferenceEngine
from deepspeed_tpu.inference import resilience as sres
from deepspeed_tpu.resilience import integrity as integ
from deepspeed_tpu.resilience.chaos import ChaosMonkey
from deepspeed_tpu.resilience.constants import (EXIT_INTEGRITY_EVICT,
                                                FleetIntegrityError,
                                                TrainingDivergedError)

from .test_inference import (seeded_prompts, serve_config, tiny_model,
                             model_and_params)  # noqa: F401 — fixture


# ---------------------------------------------------------------------------
# serving_hang_quorum: freshness-majority over incomparable counters
# ---------------------------------------------------------------------------

def _fleet(now, **beats):
    """{rank: {"step", "ts"}} from rank=(step, age_secs) kwargs."""
    return {int(r[1:]): {"step": step, "ts": now - age}
            for r, (step, age) in beats.items()}


class TestServingHangQuorum:
    def test_names_stale_peer_with_fresh_majority(self):
        now = time.time()
        fleet = _fleet(now, r0=(7, 0.0), r1=(3, 9.0), r2=(40, 0.1))
        v = sres.serving_hang_quorum(fleet, 0, 3, 1.0, now=now)
        assert v is not None and v["suspect"] == 1
        assert v["stalled_secs"] == pytest.approx(9.0)
        assert v["leaders"] == 2 and v["fleet"] == 3

    def test_slow_but_fresh_replica_is_never_named(self):
        # rank 1 is far behind in iterations but its beat is FRESH: a
        # busy replica chewing a long batch, not a hang.  The training
        # quorum would see it parked at a low step; the serving quorum
        # must not care about step position at all
        now = time.time()
        fleet = _fleet(now, r0=(500, 0.0), r1=(2, 0.2), r2=(480, 0.1))
        assert sres.serving_hang_quorum(fleet, 0, 3, 1.0, now=now) is None

    def test_stale_self_abstains(self):
        # this rank's own beat is stale — it may be the wedged one, and
        # a wedged rank must never convict a peer
        now = time.time()
        fleet = _fleet(now, r0=(7, 5.0), r1=(3, 9.0), r2=(40, 0.1))
        assert sres.serving_hang_quorum(fleet, 0, 3, 1.0, now=now) is None

    def test_no_fresh_majority_abstains(self):
        # 1 fresh of fleet 3: a partition this small must not evict
        now = time.time()
        fleet = _fleet(now, r0=(7, 0.0), r1=(3, 9.0), r2=(40, 8.0))
        assert sres.serving_hang_quorum(fleet, 0, 3, 1.0, now=now) is None

    def test_unpublished_ranks_count_against_quorum(self):
        # fleet_size 4 but only 2 published: 2 fresh of FLEET 4 is not
        # a strict majority even though every publisher is fresh
        now = time.time()
        fleet = _fleet(now, r0=(7, 0.0), r1=(3, 9.0))
        assert sres.serving_hang_quorum(fleet, 0, 4, 1.0, now=now) is None
        # the same two beats in a fleet of 3... still 1 fresh short?
        # no: 1 fresh of 3 fails, 2 fresh of 3 passes
        fleet2 = _fleet(now, r0=(7, 0.0), r1=(3, 9.0), r2=(9, 0.1))
        assert sres.serving_hang_quorum(fleet2, 0, 3, 1.0,
                                        now=now)["suspect"] == 1

    def test_names_the_stalest_when_several_are_stale(self):
        now = time.time()
        fleet = _fleet(now, r0=(1, 0.0), r1=(1, 3.0), r2=(1, 7.0),
                       r3=(1, 0.1), r4=(1, 0.2))
        v = sres.serving_hang_quorum(fleet, 0, 5, 1.0, now=now)
        assert v["suspect"] == 2

    def test_single_replica_never_fires(self):
        now = time.time()
        assert sres.serving_hang_quorum(_fleet(now, r0=(1, 0.0)), 0, 1,
                                        1.0, now=now) is None


# ---------------------------------------------------------------------------
# weight-fingerprint exchange + consensus
# ---------------------------------------------------------------------------

class TestWeightFingerprintExchange:
    def test_publish_read_roundtrip_under_fixed_step(self, tmp_path):
        for rank, fp in ((0, 0xAB12), (1, 0xAB12), (2, 0xFF00)):
            assert sres.publish_weight_fingerprint(tmp_path, rank, fp)
        fleet = sres.read_fleet_weight_fingerprints(tmp_path, 3)
        assert set(fleet) == {0, 1, 2}
        assert fleet[0] == {sres.SERVING_FINGERPRINT_STEP: "0000ab12"}
        v = integ.fingerprint_consensus(fleet, 3)
        assert v["verdict"] == integ.VERDICT_OUTLIER
        assert v["suspects"] == [2]

    def test_republish_refreshes_timestamp(self, tmp_path):
        sres.publish_weight_fingerprint(tmp_path, 0, 1)
        path = tmp_path / integ.fingerprint_filename(0)
        first = json.loads(path.read_text())["ts"]
        time.sleep(0.02)
        sres.publish_weight_fingerprint(tmp_path, 0, 1)
        assert json.loads(path.read_text())["ts"] > first


def _mk_engine(model_and_params, tmp_path=None, **cfg_overrides):
    model, params = model_and_params
    config = serve_config(**cfg_overrides)
    if tmp_path is not None:
        config["telemetry"] = {"enabled": True, "run_dir": str(tmp_path)}
    return InferenceEngine(model, params, config=config)


class TestServingHealthConsensus:
    def test_fingerprint_is_deterministic_and_flip_sensitive(
            self, model_and_params):
        e1 = _mk_engine(model_and_params)
        e2 = _mk_engine(model_and_params)
        h1 = sres.ServingHealth(e1, "/tmp/unused", 0, 2)
        h2 = sres.ServingHealth(e2, "/tmp/unused", 1, 2)
        fp1 = int(jax.device_get(h1.fingerprint_device()))
        fp2 = int(jax.device_get(h2.fingerprint_device()))
        assert fp1 == fp2, "same weights must fingerprint identically"
        ChaosMonkey(seed=3).bitflip_params(e2)
        fp2b = int(jax.device_get(h2.fingerprint_device()))
        assert fp2b != fp2, "a single flipped bit must change the sum"
        e1.close()
        e2.close()

    def test_outlier_verdict_convicts_and_raises(self, model_and_params,
                                                 tmp_path):
        engine = _mk_engine(model_and_params, tmp_path=tmp_path / "t")
        health = sres.ServingHealth(engine, tmp_path, 0, 3)
        # two healthy peers agree; this replica publishes the odd one out
        integ.publish_rank_fingerprint(
            tmp_path, 1, {sres.SERVING_FINGERPRINT_STEP: "00000aaa"})
        integ.publish_rank_fingerprint(
            tmp_path, 2, {sres.SERVING_FINGERPRINT_STEP: "00000aaa"})
        with pytest.raises(FleetIntegrityError) as err:
            health.note_weight_fingerprint(0xBBB)
        assert err.value.exit_code == EXIT_INTEGRITY_EVICT
        assert err.value.suspect == 0
        assert health.violations == 1
        verdict = integ.read_verdict(tmp_path)
        assert verdict is not None
        assert verdict["kind"] == integ.KIND_SDC
        assert verdict["suspect"] == 0
        engine.close()
        events = [json.loads(line) for line in
                  open(tmp_path / "t" / "events-rank0.jsonl")]
        evict = [e for e in events if e["type"] == "serving"
                 and e["data"].get("kind") == "evict"]
        assert evict and evict[0]["data"]["suspect"] == 0
        integ_events = [e for e in events if e["type"] == "integrity"]
        assert any(e["data"]["verdict"] == "outlier" for e in integ_events)

    def test_majority_agreement_is_ok(self, model_and_params, tmp_path):
        engine = _mk_engine(model_and_params)
        health = sres.ServingHealth(engine, tmp_path, 0, 3)
        integ.publish_rank_fingerprint(
            tmp_path, 1, {sres.SERVING_FINGERPRINT_STEP: "00000bbb"})
        integ.publish_rank_fingerprint(
            tmp_path, 2, {sres.SERVING_FINGERPRINT_STEP: "00000bbb"})
        v = health.note_weight_fingerprint(0xBBB)
        assert v["verdict"] == integ.VERDICT_OK
        assert health.violations == 0
        assert integ.read_verdict(tmp_path) is None
        engine.close()

    def test_lone_replica_is_pending_not_convicted(self, model_and_params,
                                                   tmp_path):
        # fleet_size 1 (or peers not yet published): nobody to vote
        # with — the verdict is pending, never an eviction
        engine = _mk_engine(model_and_params)
        health = sres.ServingHealth(engine, tmp_path, 0, 1)
        v = health.note_weight_fingerprint(0x123)
        assert v["verdict"] == integ.VERDICT_PENDING
        engine.close()

    def test_no_majority_poisons(self, model_and_params, tmp_path):
        engine = _mk_engine(model_and_params)
        health = sres.ServingHealth(engine, tmp_path, 0, 2)
        integ.publish_rank_fingerprint(
            tmp_path, 1, {sres.SERVING_FINGERPRINT_STEP: "00000ccc"})
        with pytest.raises(TrainingDivergedError):
            health.note_weight_fingerprint(0xDDD)
        engine.close()

    def test_warn_action_only_counts(self, model_and_params, tmp_path):
        engine = _mk_engine(model_and_params)
        health = sres.ServingHealth(engine, tmp_path, 0, 3,
                                    action="warn")
        integ.publish_rank_fingerprint(
            tmp_path, 1, {sres.SERVING_FINGERPRINT_STEP: "00000aaa"})
        integ.publish_rank_fingerprint(
            tmp_path, 2, {sres.SERVING_FINGERPRINT_STEP: "00000aaa"})
        v = health.note_weight_fingerprint(0xBBB)
        assert v["verdict"] == integ.VERDICT_OUTLIER
        assert health.violations == 1
        assert integ.read_verdict(tmp_path) is None  # telemetry only
        engine.close()


class TestHangEviction:
    def test_stale_peer_convicted_through_heartbeat_monitor(
            self, model_and_params, tmp_path):
        """End-to-end through FleetHeartbeat with the serving quorum
        injected: rank 1's beat goes stale while ranks 0 and 2 keep
        beating (the strict fresh majority) — rank 0's monitor must
        write a hang verdict naming 1 and request the respawnable
        eviction exit."""
        engine = _mk_engine(model_and_params, tmp_path=tmp_path / "t")
        codes = []
        health = sres.ServingHealth(engine, tmp_path, 0, 3,
                                    peer_timeout_secs=0.4,
                                    poll_interval=0.05,
                                    exit_fn=codes.append)
        integ.publish_rank_heartbeat(tmp_path, 1, 3)  # beats once, wedges
        engine.attach_health(health)
        deadline = time.monotonic() + 5.0
        step = 0
        while not health.heartbeat.fired and time.monotonic() < deadline:
            step += 1
            health.beat(step)                     # this rank stays live...
            integ.publish_rank_heartbeat(tmp_path, 2, step)  # ...peer 2 too
            time.sleep(0.05)
        assert health.heartbeat.fired, "hang quorum never fired"
        assert codes == [EXIT_INTEGRITY_EVICT]
        verdict = integ.read_verdict(tmp_path)
        assert verdict is not None
        assert verdict["kind"] == integ.KIND_HANG
        assert verdict["suspect"] == 1
        engine.close()
        events = [json.loads(line) for line in
                  open(tmp_path / "t" / "events-rank0.jsonl")]
        assert any(e["type"] == "serving"
                   and e["data"].get("kind") == "evict"
                   and e["data"].get("suspect") == 1 for e in events)


# ---------------------------------------------------------------------------
# drain deadline contract + SIGTERM preemption
# ---------------------------------------------------------------------------

class TestDrainDeadline:
    def test_explicit_value_wins(self, monkeypatch):
        monkeypatch.setenv("DS_TERM_DRAIN_DEADLINE_SECS", "7.5")
        assert sres.drain_deadline_secs() == 7.5

    def test_default_is_ninety_percent_of_grace(self, monkeypatch):
        monkeypatch.delenv("DS_TERM_DRAIN_DEADLINE_SECS", raising=False)
        monkeypatch.setenv("DS_TERM_GRACE_SECS", "10")
        assert sres.drain_deadline_secs() == pytest.approx(9.0)

    def test_malformed_degrades_never_aborts(self, monkeypatch):
        monkeypatch.setenv("DS_TERM_DRAIN_DEADLINE_SECS", "90s")
        monkeypatch.setenv("DS_TERM_GRACE_SECS", "20")
        assert sres.drain_deadline_secs() == pytest.approx(18.0)

    def test_zero_disables_the_bound(self, monkeypatch):
        monkeypatch.setenv("DS_TERM_DRAIN_DEADLINE_SECS", "0")
        assert sres.drain_deadline_secs() == 0.0


class _FakeEngine:
    """Stdlib stand-in for the duck-typed drain contract."""

    def __init__(self):
        self.closed_with = []

    def close(self, reason="?"):
        self.closed_with.append(reason)


class TestServingPreemption:
    def test_sigterm_drains_then_exits_respawnable(self):
        fake = _FakeEngine()
        codes = []
        old = signal.getsignal(signal.SIGTERM)
        try:
            sres.arm_serving_preemption(fake, exit_fn=codes.append)
            signal.raise_signal(signal.SIGTERM)
        finally:
            signal.signal(signal.SIGTERM, old)
        assert fake.closed_with == ["preempt_drain"]
        assert codes == [128 + signal.SIGTERM]

    def test_drain_failure_still_exits_respawnable(self):
        class Exploding:
            def close(self, reason="?"):
                raise RuntimeError("drain blew up")

        codes = []
        old = signal.getsignal(signal.SIGTERM)
        try:
            sres.arm_serving_preemption(Exploding(), exit_fn=codes.append)
            signal.raise_signal(signal.SIGTERM)
        finally:
            signal.signal(signal.SIGTERM, old)
        assert codes == [128 + signal.SIGTERM]


class TestEngineDrainClose:
    def test_drain_finishes_inflight_and_stops_admission(
            self, model_and_params):
        engine = _mk_engine(model_and_params)
        prompts = seeded_prompts(3, seed=21)
        for i, p in enumerate(prompts):
            engine.submit(p, max_new_tokens=4, request_id=f"r{i}")
        engine.step()                      # admit + first decode
        drained = engine.drain()
        assert engine.scheduler.active_count == 0
        assert {r.request_id for r in drained} == {"r0", "r1", "r2"}
        assert all(len(r.generated) == 4 for r in drained)
        with pytest.raises(RuntimeError, match="draining"):
            engine.submit(prompts[0], max_new_tokens=2)
        engine.close()

    def test_drain_deadline_abandons_rather_than_hangs(
            self, model_and_params, monkeypatch):
        engine = _mk_engine(model_and_params)
        engine.submit(seeded_prompts(1, seed=22)[0], max_new_tokens=8)
        engine.step()
        # a deadline already in the past: drain must give up instantly
        # (the router re-serves), not loop the remaining decodes
        monkeypatch.setattr(
            "deepspeed_tpu.inference.resilience.drain_deadline_secs",
            lambda grace=None: 1e-9)
        before = engine.decode_iterations
        engine.drain(deadline_secs=1e-9)
        assert engine.decode_iterations <= before + 1
        assert engine.scheduler.active_count == 1   # abandoned, not lost
        engine.close()

    def test_close_is_idempotent_and_emits_run_end(self, model_and_params,
                                                   tmp_path):
        engine = _mk_engine(model_and_params, tmp_path=tmp_path)
        rid = engine.submit(seeded_prompts(1, seed=23)[0],
                            max_new_tokens=3)
        engine.step()      # admit: the request now holds KV state
        engine.close(reason="preempt_drain")
        engine.close(reason="preempt_drain")    # second call: no-op
        results = {r: req.result() for r, req in engine._results.items()}
        assert len(results[rid]["tokens"]) == 3
        events = [json.loads(line) for line in
                  open(tmp_path / "events-rank0.jsonl")]
        ends = [e for e in events if e["type"] == "run_end"]
        assert len(ends) == 1
        assert ends[0]["data"]["reason"] == "preempt_drain"
        assert any(e["type"] == "serving"
                   and e["data"].get("kind") == "drain" for e in events)


# ---------------------------------------------------------------------------
# zero added syncs with the FULL resilience plane armed
# ---------------------------------------------------------------------------

def test_zero_added_host_syncs_with_health_armed(model_and_params,
                                                 tmp_path, monkeypatch):
    """Heartbeats every decode iteration + the weight fingerprint on
    every print cadence (steps_per_print=1: EVERY iteration) must add
    ZERO jax.device_get calls over the bare serve loop — the
    fingerprint scalar rides the next-token fetch."""
    model, params = model_and_params
    prompts = seeded_prompts(4, seed=31)

    def count_gets(health_run_dir):
        config = serve_config()
        config["steps_per_print"] = 1
        engine = InferenceEngine(model, params, config=config)
        if health_run_dir is not None:
            engine.attach_health(sres.ServingHealth(
                engine, health_run_dir, 0, 1, peer_timeout_secs=60.0))
        counts = {"n": 0}
        real_get = jax.device_get

        def counting_get(x):
            counts["n"] += 1
            return real_get(x)

        monkeypatch.setattr(jax, "device_get", counting_get)
        try:
            for i, p in enumerate(prompts):
                engine.submit(p, max_new_tokens=4, request_id=f"r{i}")
            engine.run()
        finally:
            monkeypatch.setattr(jax, "device_get", real_get)
        engine.close()
        return counts["n"]

    base = count_gets(None)
    armed = count_gets(tmp_path)
    assert base > 0
    assert armed == base, (
        f"the serving health plane added host syncs: {armed} device_get "
        f"calls vs {base} baseline")
    # and it genuinely ran: the fingerprint was published to the run dir
    fleet = sres.read_fleet_weight_fingerprints(tmp_path, 1)
    assert 0 in fleet and sres.SERVING_FINGERPRINT_STEP in fleet[0]


# ---------------------------------------------------------------------------
# launcher integration: SIGTERM drain in a real child process
# ---------------------------------------------------------------------------

def test_sigterm_drain_through_real_launcher(tmp_path, monkeypatch):
    """The launcher SIGTERMs its children on shutdown; an armed serving
    replica must drain (close(reason="preempt_drain") runs, marker
    lands) and die by the re-raised signal — the launcher reads an
    ordinary preemption death (128+15), not a tangle."""
    from .test_launcher import _launch_main

    monkeypatch.setenv("DS_MONITOR_POLL_SECS", "0.1")
    repo = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "..", "..")
    marker = tmp_path / "drained.json"
    child = f"""
import json, os, signal, sys, time
sys.path.insert(0, {repo!r})
from deepspeed_tpu.inference.resilience import arm_serving_preemption

class Engine:                       # duck-typed drain target
    def close(self, reason="?"):
        json.dump({{"reason": reason, "pid": os.getpid()}},
                  open({str(marker)!r}, "w"))

arm_serving_preemption(Engine())
os.kill(os.getppid(), signal.SIGTERM)   # preempt the launcher
for _ in range(600):
    time.sleep(0.1)
"""
    code = _launch_main(tmp_path, child)
    assert code == 128 + signal.SIGTERM
    payload = json.loads(marker.read_text())
    assert payload["reason"] == "preempt_drain"


def test_report_serving_resilience_summary_counts_and_details():
    """The report CLI's serving-resilience block: resilience kinds are
    counted (deadline/degrade counted only; shed/requeue/evict/drain
    itemized with their detail lines), decode-plane kinds and other
    event types are ignored, and a run with no resilience events skips
    the section entirely (empty list)."""
    from deepspeed_tpu.telemetry.report import serving_resilience_summary

    def ev_(kind, ts, **data):
        return {"type": "serving", "rank": 0, "ts": ts, "_stream": "r0",
                "data": dict(data, kind=kind)}

    records = [
        ev_("admit", 1.0, request="req-0"),            # decode plane
        ev_("shed", 2.0, queue_depth=4, max_queue_depth=4),
        ev_("degrade", 2.5, queue_depth=3, capped_to=2),
        ev_("deadline", 3.0, request="req-1"),
        ev_("requeue", 4.0, request="req-2", replica=1, requeues=1,
            backoff_secs=0.5),
        ev_("evict", 5.0, suspect=1, reason="hang_quorum"),
        ev_("drain", 6.0, active=2, queued=1, deadline_secs=9.0),
        {"type": "integrity", "rank": 0, "ts": 7.0, "_stream": "r0",
         "data": {"kind": "evict"}},                   # wrong type
    ]
    lines = serving_resilience_summary(records)
    assert lines[0].split() == ["deadline=1", "shed=1", "degrade=1",
                                "requeue=1", "evict=1", "drain=1"]
    body = "\n".join(lines[1:])
    assert "requeue: request req-2 off dead replica 1" in body
    assert "shed: queue depth 4 at max_queue_depth 4" in body
    assert "evict: replica 1 convicted (hang_quorum)" in body
    assert "drain: 2 active + 1 queued" in body
    # deadline/degrade events are counted, never itemized: nothing in
    # the body names their requests or caps
    assert "req-1" not in body and "capped_to" not in body

    assert serving_resilience_summary(
        [ev_("admit", 1.0), ev_("finish", 2.0)]) == []
