"""Self-healing serving plane, proven end-to-end on the real launcher.

THE serving chaos trio (PR acceptance): a 3-replica serving fleet
(``serving_replica_script.py``) serves one shared seeded request set
under the elastic supervisor while ONE replica takes a fault —

- **kill**: SIGKILL mid-decode.  The supervisor sees the signal death
  and resizes 3 -> 2; survivors drain under SIGTERM (their in-flight
  results commit to the ledger), and the resized fleet re-serves the
  dead replica's remainder.
- **hang**: the replica wedges mid-serving (beats stop).  The serving/
  parked majority's freshness quorum convicts it, exits 87 with a
  verdict, and the supervisor aims the resize at its slot (blocklist).
- **bitflip**: one seeded bit of the replica's weights flips.  The next
  fingerprint cadence names it, the fleet exits 87, the SUSPECT deletes
  its own current-life ledger (every token since the flip is suspect),
  and the resized fleet re-serves its requests.

In all three: the union of the per-life ledgers holds EVERY request
EXACTLY ONCE, with tokens bit-identical to an uninterrupted in-process
greedy reference — requeue loses nothing, duplicates nothing, and never
serves corrupt output."""

import json
import os

import pytest

from .test_integrity_e2e import _launch_main, _launcher_events

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
SCRIPT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "serving_replica_script.py")

N_REQUESTS = 9
SEED = 71
MAX_NEW = 6
TARGET = 1      # the faulted replica: middle rank, slot 1

# worlds 1..3 all valid (24 = micro x accum x world for micro in {2,4}):
# the planner must be able to land on 3 at launch and 2 after a failure
SERVING_ELASTIC = {"enabled": True, "max_train_batch_size": 24,
                   "micro_batch_sizes": [2, 4], "min_gpus": 1,
                   "max_gpus": 8, "version": 0.1}

_SERVE_ENV = ("DS_SERVE_REQUESTS", "DS_SERVE_SEED", "DS_SERVE_MAX_NEW",
              "DS_SERVE_PEER_TIMEOUT", "DS_SERVE_CHAOS_KIND",
              "DS_SERVE_CHAOS_STEP", "DS_SERVE_CHAOS_TARGET",
              "DS_SERVE_CHAOS_SEED")


@pytest.fixture(scope="module")
def reference(tmp_path_factory):
    """The uninterrupted greedy reference, computed in-process on the
    identical model/params/prompts the replicas build: rid -> tokens."""
    import jax

    from deepspeed_tpu.inference import reference_generate
    from .test_inference import seeded_prompts, tiny_model

    model = tiny_model()
    params = model.init(jax.random.PRNGKey(0))
    prompts = seeded_prompts(N_REQUESTS, seed=SEED)
    return {f"req-{i:03d}": reference_generate(model, params, p, MAX_NEW)
            for i, p in enumerate(prompts)}


@pytest.fixture(scope="module")
def compile_cache(tmp_path_factory):
    # one warm cache across all three legs: lives 2..n skip compilation
    return str(tmp_path_factory.mktemp("serving-xla-cache"))


def _chaos_env(monkeypatch, kind, peer_timeout, step=3):
    monkeypatch.setenv("DS_MONITOR_POLL_SECS", "0.05")
    monkeypatch.setenv("DS_RESTART_BACKOFF_SECS", "0.05")
    monkeypatch.setenv("DS_TERM_GRACE_SECS", "5")
    monkeypatch.setenv("DS_TERM_DRAIN_DEADLINE_SECS", "2")
    monkeypatch.setenv("DS_ELASTIC_DEVICES_PER_FAILURE", "1")
    monkeypatch.delenv("DS_INTEGRITY_MAX_EVICTIONS", raising=False)
    for k in _SERVE_ENV:
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("DS_SERVE_REQUESTS", str(N_REQUESTS))
    monkeypatch.setenv("DS_SERVE_SEED", str(SEED))
    monkeypatch.setenv("DS_SERVE_MAX_NEW", str(MAX_NEW))
    monkeypatch.setenv("DS_SERVE_PEER_TIMEOUT", str(peer_timeout))
    monkeypatch.setenv("DS_SERVE_CHAOS_KIND", kind)
    monkeypatch.setenv("DS_SERVE_CHAOS_STEP", str(step))
    monkeypatch.setenv("DS_SERVE_CHAOS_TARGET", str(TARGET))
    monkeypatch.setenv("DS_SERVE_CHAOS_SEED", "19")


def _launch_fleet(tmp_path, compile_cache):
    cfg = tmp_path / "elastic.json"
    cfg.write_text(json.dumps({"elasticity": SERVING_ELASTIC}))
    out = tmp_path / "out"
    code = _launch_main(
        tmp_path, script_path=SCRIPT, slots=(0, 1, 2),
        script_args=(str(out),), max_restarts=2,
        extra_argv=["--elastic-config", str(cfg), "--elastic-devices",
                    "3", "--telemetry-dir", str(tmp_path / "tel"),
                    "--compile-cache-dir", compile_cache])
    return code, out


def _ledger(out_dir):
    """rid -> [parsed records] across every life's ledger (torn lines
    skipped, as the replicas themselves skip them)."""
    recs = {}
    for name in sorted(os.listdir(out_dir)):
        if not name.startswith("results-"):
            continue
        for line in open(os.path.join(out_dir, name)):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            recs.setdefault(rec["rid"], []).append(rec)
    return recs


def _assert_exactly_once_with_parity(out_dir, reference):
    recs = _ledger(out_dir)
    assert sorted(recs) == sorted(reference), (
        f"served {sorted(recs)} != requested {sorted(reference)}")
    for rid, hits in recs.items():
        assert len(hits) == 1, (
            f"{rid} served {len(hits)} times (lives "
            f"{[h['life'] for h in hits]}): exactly-once violated")
        assert hits[0]["tokens"] == reference[rid], (
            f"{rid} tokens diverged from the uninterrupted reference "
            f"(served by rank {hits[0]['rank']})")


def _merged_events(run_dir, event_type):
    from deepspeed_tpu.telemetry import read_events

    return [r for r in read_events(str(run_dir))
            if r["type"] == event_type]


def test_serving_chaos_kill_resize_exactly_once(tmp_path, monkeypatch,
                                                reference,
                                                compile_cache):
    """SIGKILL on replica 1 mid-decode: the supervisor resizes 3 -> 2
    (signal-death trigger — the quorum is silenced with a loose peer
    timeout to pin WHICH detector recovered), survivors drain under
    SIGTERM, and the resized fleet completes the set exactly once with
    reference-identical tokens."""
    _chaos_env(monkeypatch, "kill", peer_timeout=60)
    code, out = _launch_fleet(tmp_path, compile_cache)
    assert code == 0
    _assert_exactly_once_with_parity(out, reference)

    phases = [(p["data"]["phase"], p["data"])
              for p in _launcher_events(tmp_path, "elastic")]
    # a raw SIGKILL carries no verdict: the resize is blind (no evict
    # phase, no blocklist) — aimed eviction is the hang/bitflip legs'
    assert [p for p, _ in phases] == ["plan", "resize"]
    assert phases[0][1]["trigger"].startswith("signal death")
    assert phases[1][1]["world_size"] == 2
    exits = [(r["data"]["code"], r["data"]["signal"])
             for r in _launcher_events(tmp_path, "proc_exit")]
    assert (137, "SIGKILL") in exits


def test_serving_chaos_hang_quorum_evicts_exactly_once(tmp_path,
                                                       monkeypatch,
                                                       reference,
                                                       compile_cache):
    """Replica 1 wedges mid-serving: the freshness-majority quorum of
    the serving/PARKED peers convicts it (a clean early finisher keeps
    beating, so it votes instead of reading as hung itself), the fleet
    exits 87, and the supervisor aims the resize at slot 1."""
    _chaos_env(monkeypatch, "hang", peer_timeout=3.0)
    code, out = _launch_fleet(tmp_path, compile_cache)
    assert code == 0
    _assert_exactly_once_with_parity(out, reference)

    phases = [(p["data"]["phase"], p["data"])
              for p in _launcher_events(tmp_path, "elastic")]
    assert [p for p, _ in phases] == ["evict", "plan", "resize"]
    evict = phases[0][1]
    assert evict["suspect"] == TARGET and evict["slot"] == TARGET
    assert evict["kind"] == "hang_quorum"
    assert phases[2][1]["evicted_slots"] == [TARGET]
    assert phases[2][1]["world_size"] == 2
    codes = [r["data"]["code"]
             for r in _launcher_events(tmp_path, "proc_exit")]
    assert 87 in codes          # the detecting accusers, not the victim
    # the accusers narrated the eviction into the merged stream before
    # dying (flush-on-fire)
    evicts = [r for r in _merged_events(tmp_path / "tel", "serving")
              if r["data"]["kind"] == "evict"]
    assert evicts and all(r["data"]["suspect"] == TARGET
                          for r in evicts)
    assert any(r["data"]["fault"] == "hang_quorum" for r in evicts)


def test_serving_chaos_bitflip_consensus_evicts_exactly_once(
        tmp_path, monkeypatch, reference, compile_cache):
    """One seeded bit flips in replica 1's weights mid-serving: the
    weight-fingerprint consensus names it at the next vote cadence, the
    fleet exits 87, the suspect WITHDRAWS its current life's ledger
    (everything it served since the flip is untrusted), and the resized
    fleet re-serves those requests — the final union is exactly-once
    AND bit-identical to the reference, proving corrupt output never
    reached the ledger it left behind."""
    _chaos_env(monkeypatch, "bitflip", peer_timeout=60)
    code, out = _launch_fleet(tmp_path, compile_cache)
    assert code == 0
    _assert_exactly_once_with_parity(out, reference)

    phases = [(p["data"]["phase"], p["data"])
              for p in _launcher_events(tmp_path, "elastic")]
    assert [p for p, _ in phases] == ["evict", "plan", "resize"]
    evict = phases[0][1]
    assert evict["suspect"] == TARGET and evict["slot"] == TARGET
    assert evict["kind"] == "sdc_outlier"
    assert phases[2][1]["evicted_slots"] == [TARGET]
    codes = [r["data"]["code"]
             for r in _launcher_events(tmp_path, "proc_exit")]
    assert 87 in codes
    # the outlier verdict rode the merged stream naming the target
    outliers = [r for r in _merged_events(tmp_path / "tel", "integrity")
                if r["data"].get("verdict") == "outlier"]
    assert outliers and all(r["data"]["suspects"] == [TARGET]
                            for r in outliers)
    # the suspect's ledger withdrawal is observable: no surviving
    # record was written by the evicted rank's faulted life
    recs = _ledger(out)
    flipped_life_ranks = {h["rank"] for hits in recs.values()
                          for h in hits}
    assert flipped_life_ranks  # sanity: somebody served
