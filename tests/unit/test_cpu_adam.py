"""Native host Adam kernel (reference ``tests/unit/test_cpu_adam.py``:
CPU-Adam vs torch Adam; here vs FusedAdam, which is itself reference-
checked)."""

import shutil

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh

from .simple_model import SimpleModel, base_config, random_batches

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no g++ for native kernel JIT build")

HIDDEN = 16


def test_kernel_matches_fused_adam():
    from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
    from deepspeed_tpu.ops.adam.fused_adam import FusedAdam

    rng = np.random.default_rng(0)
    flat = jnp.asarray(rng.normal(size=(64, 128)).astype(np.float32))
    cpu = DeepSpeedCPUAdam(lr=1e-2, weight_decay=0.01)
    gpu = FusedAdam(lr=1e-2, weight_decay=0.01)
    sc, sg = cpu.init_state(flat), gpu.init_state(flat)
    pc = pg = flat
    for i in range(4):
        g = jnp.asarray(rng.normal(size=flat.shape).astype(np.float32))
        pc, sc = cpu.update(sc, pc, g, cpu.hyperparams())
        pg, sg = gpu.update(sg, pg, g, gpu.hyperparams())
    np.testing.assert_allclose(np.asarray(pc), np.asarray(pg), rtol=2e-6,
                               atol=1e-7)
    np.testing.assert_allclose(np.asarray(sc.exp_avg_sq),
                               np.asarray(sg.exp_avg_sq), rtol=2e-6,
                               atol=1e-8)


def test_kernel_l2_mode():
    from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
    from deepspeed_tpu.ops.adam.fused_adam import FusedAdam

    flat = jnp.ones((8, 128), jnp.float32)
    g = jnp.full((8, 128), 0.5, jnp.float32)
    cpu = DeepSpeedCPUAdam(lr=1e-2, weight_decay=0.1, adamw_mode=False)
    gpu = FusedAdam(lr=1e-2, weight_decay=0.1, adam_w_mode=False)
    pc, _ = cpu.update(cpu.init_state(flat), flat, g, cpu.hyperparams())
    pg, _ = gpu.update(gpu.init_state(flat), flat, g, gpu.hyperparams())
    np.testing.assert_allclose(np.asarray(pc), np.asarray(pg), rtol=2e-6)


def test_engine_trains_with_cpu_adam(cpu_devices):
    """'CPUAdam' optimizer config: the jitted step calls the native kernel
    via pure_callback; trajectory matches the Adam config."""
    mesh = make_mesh({"data": 8}, devices=cpu_devices[:8])

    def run(opt_type):
        config = base_config(optimizer={"type": opt_type,
                                        "params": {"lr": 1e-2}})
        engine, *_ = deepspeed.initialize(model=SimpleModel(HIDDEN, nlayers=2),
                                          config=config, mesh=mesh)
        batch = random_batches(1, engine.train_micro_batch_size_per_gpu() * 8,
                               HIDDEN, seed=0)[0]
        return [float(np.asarray(engine.train_batch(iter([batch]))))
                for _ in range(4)]

    host = run("CPUAdam")
    dev = run("Adam")
    np.testing.assert_allclose(host, dev, rtol=1e-5)


def test_cpu_adam_under_zero2_sharded_callback(cpu_devices):
    """ZeRO-2 + CPUAdam: per-shard callbacks inside shard_map — trajectory
    matches FusedAdam under the same sharding (no cross-device gather of
    the sharded master through one host)."""
    mesh = make_mesh({"data": 8}, devices=cpu_devices[:8])

    def run(opt_type):
        config = base_config(optimizer={"type": opt_type,
                                        "params": {"lr": 1e-2}},
                             zero_optimization={"stage": 2})
        engine, *_ = deepspeed.initialize(model=SimpleModel(HIDDEN, nlayers=2),
                                          config=config, mesh=mesh)
        batch = random_batches(1, engine.train_micro_batch_size_per_gpu() * 8,
                               HIDDEN, seed=0)[0]
        return [float(np.asarray(engine.train_batch(iter([batch]))))
                for _ in range(3)]

    np.testing.assert_allclose(run("CPUAdam"), run("Adam"), rtol=1e-5)


def test_adam_w_mode_alias():
    from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam

    opt = DeepSpeedCPUAdam(adam_w_mode=False)
    assert opt.adamw_mode is False
    opt2 = DeepSpeedCPUAdam(adamw_mode=False)
    assert opt2.adamw_mode is False
