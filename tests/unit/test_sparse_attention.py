"""Sparse attention tests: layout parity against the reference
implementation (loaded standalone) + block-sparse numerics vs dense
attention (model: reference ``tests/unit/test_sparse_attention.py``
approach of checking against a dense equivalent)."""

import importlib.util
import math
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (
    BertSparseSelfAttention, BigBirdSparsityConfig, BSLongformerSparsityConfig,
    DenseSparsityConfig, FixedSparsityConfig, SparseAttentionUtils,
    SparseSelfAttention, SparsityConfig, VariableSparsityConfig,
    block_sparse_attention, layout_gather_indices)

REF_PATH = "/root/reference/deepspeed/ops/sparse_attention/sparsity_config.py"


@pytest.fixture(scope="module")
def ref_configs():
    spec = importlib.util.spec_from_file_location("ref_sparsity_config", REF_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


CASES = [
    ("dense", "DenseSparsityConfig", dict(num_heads=4, block=16)),
    ("fixed_bi", "FixedSparsityConfig",
     dict(num_heads=4, block=16, num_local_blocks=4, num_global_blocks=1)),
    ("fixed_uni", "FixedSparsityConfig",
     dict(num_heads=4, block=16, num_local_blocks=4, num_global_blocks=2,
          attention="unidirectional")),
    ("fixed_horiz", "FixedSparsityConfig",
     dict(num_heads=4, block=16, num_local_blocks=4, num_global_blocks=1,
          horizontal_global_attention=True)),
    ("fixed_perhead", "FixedSparsityConfig",
     dict(num_heads=4, block=16, num_local_blocks=4, num_global_blocks=1,
          different_layout_per_head=True, num_different_global_patterns=4)),
    ("variable", "VariableSparsityConfig",
     dict(num_heads=4, block=16, num_random_blocks=0,
          local_window_blocks=[2, 4], global_block_indices=[0, 5])),
    ("variable_span", "VariableSparsityConfig",
     dict(num_heads=4, block=16, num_random_blocks=0,
          global_block_indices=[0], global_block_end_indices=[2],
          horizontal_global_attention=True)),
    ("variable_uni", "VariableSparsityConfig",
     dict(num_heads=4, block=16, num_random_blocks=0,
          attention="unidirectional")),
    ("bigbird", "BigBirdSparsityConfig",
     dict(num_heads=4, block=16, num_random_blocks=1,
          num_sliding_window_blocks=3, num_global_blocks=1)),
    ("longformer", "BSLongformerSparsityConfig",
     dict(num_heads=4, block=16, num_sliding_window_blocks=3,
          global_block_indices=[0, 7])),
]


@pytest.mark.parametrize("name,cls,kwargs", CASES, ids=[c[0] for c in CASES])
@pytest.mark.parametrize("seq_len", [128, 256])
def test_layout_matches_reference(name, cls, kwargs, seq_len, ref_configs):
    """Byte-identical layouts vs the reference implementation (randomness
    pinned by seeding python's `random`, which both use)."""
    random.seed(1234)
    ours = getattr(
        __import__("deepspeed_tpu.ops.sparse_attention", fromlist=[cls]),
        cls)(**kwargs).make_layout(seq_len)
    random.seed(1234)
    theirs = getattr(ref_configs, cls)(**kwargs).make_layout(seq_len).numpy()
    assert ours.shape == theirs.shape
    assert (ours == theirs).all(), (
        f"{name}: layouts differ in {(ours != theirs).sum()} cells")


def test_layout_validation_errors():
    with pytest.raises(ValueError):
        FixedSparsityConfig(num_heads=2, num_local_blocks=4,
                            num_global_blocks=3)
    with pytest.raises(ValueError):
        FixedSparsityConfig(num_heads=2, num_different_global_patterns=2)
    with pytest.raises(ValueError):
        SparsityConfig(num_heads=2, block=16).setup_layout(100)
    with pytest.raises(NotImplementedError):
        FixedSparsityConfig(num_heads=2, attention="diagonal")


def _dense_reference(q, k, v, layout, block, causal=False,
                     key_padding_mask=None):
    """Dense attention with the layout expanded to an element mask."""
    b, s, h, d = q.shape
    lay = np.asarray(layout)
    if lay.shape[0] == 1 and h > 1:
        lay = np.broadcast_to(lay, (h,) + lay.shape[1:])
    el = np.kron(lay, np.ones((block, block)))  # [h, s, s]
    mask = el.astype(bool)
    if causal:
        mask &= np.tril(np.ones((s, s), bool))
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(d)
    scores = jnp.where(jnp.asarray(mask)[None], scores, -1e9)
    if key_padding_mask is not None:
        scores = scores + jnp.asarray(key_padding_mask)[:, None, None, :]
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


@pytest.mark.parametrize("cfg", [
    FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2),
    FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2,
                        attention="unidirectional"),
    BigBirdSparsityConfig(num_heads=2, block=16, num_random_blocks=1,
                          num_sliding_window_blocks=3, num_global_blocks=1),
    BSLongformerSparsityConfig(num_heads=2, block=16,
                               num_sliding_window_blocks=3),
], ids=["fixed", "fixed_uni", "bigbird", "longformer"])
def test_block_sparse_matches_dense(cfg):
    random.seed(0)
    s, b, h, d = 128, 2, 2, 32
    layout = cfg.make_layout(s)
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    causal = getattr(cfg, "attention", "bidirectional") == "unidirectional"

    out = block_sparse_attention(q, k, v, layout, causal=causal)
    ref = _dense_reference(q, k, v, layout, cfg.block, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_block_sparse_gradients_match_dense():
    random.seed(0)
    s, b, h, d = 64, 1, 2, 16
    cfg = FixedSparsityConfig(num_heads=h, block=16, num_local_blocks=2)
    layout = cfg.make_layout(s)
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)

    g1 = jax.grad(lambda q, k, v: jnp.sum(
        block_sparse_attention(q, k, v, layout) ** 2), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: jnp.sum(
        _dense_reference(q, k, v, layout, cfg.block) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=3e-4, atol=3e-5)


def test_block_sparse_key_padding_mask():
    random.seed(0)
    s, b, h, d = 64, 2, 2, 16
    cfg = BSLongformerSparsityConfig(num_heads=h, block=16)
    layout = cfg.make_layout(s)
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    kpm = np.zeros((b, s), np.float32)
    kpm[:, 48:] = -1e9  # mask the tail

    out = block_sparse_attention(q, k, v, layout, key_padding_mask=kpm)
    ref = _dense_reference(q, k, v, layout, cfg.block, key_padding_mask=kpm)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_sparse_self_attention_module():
    random.seed(0)
    s, b, h, d = 64, 2, 4, 16
    attn = SparseSelfAttention(
        FixedSparsityConfig(num_heads=h, block=16, num_local_blocks=2),
        max_seq_length=128)
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, s, d)), jnp.float32)
    kpm = np.ones((b, s), np.float32)  # 'mul' mode... default is add
    out = attn(q, k, v, key_padding_mask=kpm * 0.0)
    assert out.shape == (b, h, s, d)
    # layout caching: same object returned
    assert attn.get_layout(s) is attn.get_layout(s)
    # seq beyond master layout rejected
    with pytest.raises(ValueError):
        attn.get_layout(256)


def test_bert_sparse_self_attention():
    random.seed(0)

    class Cfg:
        hidden_size = 64
        num_attention_heads = 4
        initializer_range = 0.02

    layer = BertSparseSelfAttention(
        Cfg(), FixedSparsityConfig(num_heads=4, block=16, num_local_blocks=2))
    params = layer.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(2, 64, 64)), jnp.float32)
    mask = np.ones((2, 64), np.float32)
    out = layer.apply(params, x, mask)
    assert out.shape == (2, 64, 64)
    assert np.isfinite(np.asarray(out)).all()


def test_pad_unpad_roundtrip():
    ids = np.arange(2 * 30, dtype=np.int32).reshape(2, 30)
    am = np.ones((2, 30), np.int32)
    pad_len, pids, pam, ptt, ppos, pemb = SparseAttentionUtils.pad_to_block_size(
        block_size=16, input_ids=ids, attention_mask=am, pad_token_id=9)
    assert pad_len == 2
    assert pids.shape == (2, 32) and int(pids[0, -1]) == 9
    assert pam.shape == (2, 32) and int(pam[0, -1]) == 0
    seq_out = np.zeros((2, 32, 8))
    unp = SparseAttentionUtils.unpad_sequence_output(pad_len, seq_out)
    assert unp.shape == (2, 30, 8)


def test_extend_position_embedding():
    table = np.arange(12, dtype=np.float32).reshape(4, 3)
    out = SparseAttentionUtils.extend_position_embedding(table, 10)
    assert out.shape == (10, 3)
    np.testing.assert_allclose(np.asarray(out[4:8]), table)


def test_layout_gather_indices():
    layout = np.zeros((1, 4, 4), np.int64)
    layout[0, 0, 0] = 1
    layout[0, 2, [1, 3]] = 1
    idx, valid = layout_gather_indices(layout)
    assert idx.shape == (1, 4, 2)
    assert valid[0, 0].tolist() == [True, False]
    assert idx[0, 2].tolist() == [1, 3]
    assert valid[0, 1].tolist() == [False, False]


def test_fully_masked_rows_yield_zero():
    """Queries whose every key is padded out produce exactly zero output
    (and no NaN), matching the flash kernel's fully-masked-row contract."""
    rng = np.random.default_rng(0)
    b, s, h, d, blk = 2, 64, 2, 16, 16
    q, k, v = (jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
               for _ in range(3))
    layout = np.ones((1, s // blk, s // blk), np.int64)
    # batch 1: every key masked -> all rows fully masked
    kpm = np.zeros((b, s), np.float32)
    kpm[1, :] = -1e9
    out = block_sparse_attention(q, k, v, layout,
                                 key_padding_mask=jnp.asarray(kpm))
    out = np.asarray(out)
    assert np.isfinite(out).all(), "NaN/inf leaked from fully-masked rows"
    np.testing.assert_array_equal(out[1], np.zeros_like(out[1]))
    assert np.abs(out[0]).max() > 0


# ---------------------------------------------------------------------------
# Pallas LUT-driven block-sparse flash kernel (interpret mode) vs the
# gather-based reference implementation
# ---------------------------------------------------------------------------

def _rand_qkv(b, s, h, d, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, s, h, d), jnp.float32) for k in ks)


def _random_layout(h, nb, density=0.4, seed=0, diagonal=True):
    rng = np.random.default_rng(seed)
    layout = (rng.random((h, nb, nb)) < density).astype(np.int64)
    if diagonal:
        for hi in range(h):
            np.fill_diagonal(layout[hi], 1)
    return layout


def test_build_block_luts():
    from deepspeed_tpu.ops.sparse_attention import build_block_luts

    layout = np.zeros((1, 3, 3), np.int64)
    layout[0, 0, [0, 2]] = 1
    layout[0, 1, 1] = 1
    layout[0, 2, :] = 1
    lut, cnt, tlut, tcnt = build_block_luts(layout)
    assert cnt.tolist() == [[2, 1, 3]]
    assert lut[0, 0, :2].tolist() == [0, 2]
    # transpose: key block 0 is attended by q blocks 0 and 2
    assert tcnt.tolist() == [[2, 2, 2]]
    assert tlut[0, 0, :2].tolist() == [0, 2]


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("per_head", [False, True])
def test_flash_block_sparse_matches_gather(causal, per_head):
    """LUT-driven Pallas kernel (interpret) == gather-based reference, fwd
    and grads, for a random irregular layout."""
    from deepspeed_tpu.ops.sparse_attention import (
        block_sparse_attention, flash_block_sparse_attention)

    b, s, h, d, nb = 2, 128, 2, 64, 4
    q, k, v = _rand_qkv(b, s, h, d, seed=11)
    layout = _random_layout(h if per_head else 1, nb, seed=5)

    out_ref = block_sparse_attention(q, k, v, layout, causal=causal)
    out = flash_block_sparse_attention(q, k, v, layout, causal=causal,
                                       interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=2e-5, rtol=2e-5)

    def loss_kernel(q, k, v):
        return jnp.sum(flash_block_sparse_attention(
            q, k, v, layout, causal=causal, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(block_sparse_attention(q, k, v, layout,
                                              causal=causal) ** 2)

    g = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gk, gr, name in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gk), np.asarray(gr),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


def test_flash_block_sparse_bigbird_layout():
    """The BigBird config's layout runs through the kernel and matches the
    gather path (the reference's marquee sparse pattern)."""
    from deepspeed_tpu.ops.sparse_attention import (
        BigBirdSparsityConfig, block_sparse_attention,
        flash_block_sparse_attention)

    b, s, h, d = 1, 256, 4, 64
    cfg = BigBirdSparsityConfig(num_heads=h, block=32,
                                num_random_blocks=1, num_sliding_window_blocks=3,
                                num_global_blocks=1)
    layout = cfg.make_layout(s)
    q, k, v = _rand_qkv(b, s, h, d, seed=3)
    out_ref = block_sparse_attention(q, k, v, layout)
    out = flash_block_sparse_attention(q, k, v, layout, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=2e-5, rtol=2e-5)


def test_build_super_luts():
    """2-D aggregation LUTs: super-tile activity, counts, and G·G-bit
    sub-block masks (bit = row_g·G + col_g)."""
    from deepspeed_tpu.ops.sparse_attention.flash_block_sparse import (
        build_super_luts)

    layout = np.zeros((1, 4, 4), np.int64)
    layout[0, 0, [0, 2]] = 1
    layout[0, 1, [1]] = 1
    layout[0, 2, [2, 3]] = 1
    layout[0, 3, [3]] = 1
    slut, scnt, smask, stlut, stcnt, stmask = build_super_luts(layout, G=2)
    # super tile (0,0) = rows {0,1} x cols {0,1}: (0,0) bit0, (1,1) bit3
    # super tile (0,1) = rows {0,1} x cols {2,3}: (0,2) bit0
    assert scnt[0, 0] == 2 and slut[0, 0, :2].tolist() == [0, 1]
    assert smask[0, 0, :2].tolist() == [0b1001, 0b0001]
    # super row 1 touches only super col 1: (2,2) b0, (2,3) b1, (3,3) b3
    assert scnt[0, 1] == 1 and slut[0, 1, 0] == 1
    assert smask[0, 1, 0] == 0b1011
    # transpose: super col 0 attended only by super row 0
    assert stcnt[0, 0] == 1 and stlut[0, 0, 0] == 0
    assert stmask[0, 0, 0] == 0b1001
    assert stcnt[0, 1] == 2 and stlut[0, 1, :2].tolist() == [0, 1]
    assert stmask[0, 1, :2].tolist() == [0b0001, 0b1011]


@pytest.mark.parametrize("q_agg", ["never", "auto", 2])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_block_sparse_q_agg_parity(q_agg, causal):
    """Aggregated (multi-row-per-tile) kernel == unaggregated == gather
    reference, fwd and grads — the masking must be exactly equivalent to
    running each layout row in its own tile."""
    from deepspeed_tpu.ops.sparse_attention import (
        block_sparse_attention, flash_block_sparse_attention)

    b, s, h, d, nb = 1, 256, 2, 64, 8
    q, k, v = _rand_qkv(b, s, h, d, seed=21)
    layout = _random_layout(h, nb, density=0.3, seed=13)

    out_ref = block_sparse_attention(q, k, v, layout, causal=causal)
    out = flash_block_sparse_attention(q, k, v, layout, causal=causal,
                                       interpret=True, q_agg=q_agg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=2e-5, rtol=2e-5)

    def loss_kernel(q, k, v):
        return jnp.sum(flash_block_sparse_attention(
            q, k, v, layout, causal=causal, interpret=True,
            q_agg=q_agg) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(block_sparse_attention(q, k, v, layout,
                                              causal=causal) ** 2)

    g = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gk, gr, name in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gk), np.asarray(gr),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch (q_agg={q_agg})")


def test_flash_block_sparse_empty_row_zero_output():
    """A query block with NO active key blocks must produce zero output
    (same contract as the gather implementation's fully-masked guard)."""
    from deepspeed_tpu.ops.sparse_attention import flash_block_sparse_attention

    b, s, h, d, nb = 1, 64, 1, 64, 4
    q, k, v = _rand_qkv(b, s, h, d, seed=9)
    layout = np.ones((1, nb, nb), np.int64)
    layout[0, 2, :] = 0  # q block 2 attends to nothing
    out = flash_block_sparse_attention(q, k, v, layout, interpret=True)
    blk = s // nb
    np.testing.assert_allclose(np.asarray(out[:, 2 * blk:3 * blk]), 0.0,
                               atol=1e-6)
    assert np.abs(np.asarray(out[:, :2 * blk])).max() > 0
