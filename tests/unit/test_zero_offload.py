"""ZeRO-Offload: fp32 master + optimizer state in pinned host memory with
device-streamed updates (reference capability: CPU-resident optimizer,
``stage2.py:326-342`` + ``csrc/adam/cpu_adam.cpp``)."""

import jax
import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 16


def _engine(cpu_devices, dp=8, **cfg):
    mesh = make_mesh({"data": dp}, devices=cpu_devices[:dp])
    config = base_config(**cfg)
    engine, *_ = deepspeed.initialize(model=SimpleModel(HIDDEN, nlayers=2),
                                      config=config, mesh=mesh)
    return engine


def _losses(engine, steps=4, seed=0):
    batch = random_batches(1, engine.train_micro_batch_size_per_gpu()
                           * engine.dp_world_size, HIDDEN, seed=seed)[0]
    return [float(np.asarray(engine.train_batch(iter([batch]))))
            for _ in range(steps)]


def test_offload_state_lives_in_pinned_host(cpu_devices):
    engine = _engine(cpu_devices,
                     zero_optimization={"stage": 2, "cpu_offload": True})
    assert engine.state["master"].sharding.memory_kind == "pinned_host"
    for leaf in jax.tree_util.tree_leaves(engine.state["opt"]):
        if leaf.shape == engine.segments.shape:
            assert leaf.sharding.memory_kind == "pinned_host", leaf.shape
    losses = _losses(engine)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses
    # state must STAY host-resident after fused steps (donation preserved it)
    assert engine.state["master"].sharding.memory_kind == "pinned_host"


def test_offload_loss_parity(cpu_devices):
    """Offload is a memory-placement choice, not a numerics change."""
    on = _losses(_engine(cpu_devices,
                         zero_optimization={"stage": 2, "cpu_offload": True}))
    off = _losses(_engine(cpu_devices, zero_optimization={"stage": 2}))
    np.testing.assert_allclose(on, off, rtol=2e-5)


def test_offload_stage3(cpu_devices):
    """Stage 3 + offload: params re-materialized from the host-resident
    sharded master inside the step."""
    engine = _engine(cpu_devices,
                     zero_optimization={"stage": 3, "cpu_offload": True},
                     bf16={"enabled": True})
    assert engine.state["master"].sharding.memory_kind == "pinned_host"
    losses = _losses(engine)
    assert losses[-1] < losses[0], losses


def test_offload_forward_backward_step_api(cpu_devices):
    """The step-wise API also works with host-resident state."""
    engine = _engine(cpu_devices,
                     zero_optimization={"stage": 2, "cpu_offload": True})
    batch = random_batches(1, engine.train_micro_batch_size_per_gpu() * 8,
                           HIDDEN, seed=0)[0]
    l0 = engine.forward(batch)
    engine.backward(l0)
    engine.step()
    assert np.isfinite(float(np.asarray(l0)))
    assert engine.state["master"].sharding.memory_kind == "pinned_host"
