"""Fleet integrity plane (``deepspeed_tpu/resilience/integrity``):
state-fingerprint consensus, hang quorum, eviction verdicts, the
supervisor's EvictionLedger, the chaos bitflip/hang injectors, and the
engine wiring — SDC detection by majority vote with the fingerprint
riding the existing batched ``steps_per_print`` fetch.

The real-launcher chaos e2e (bitflip → evict → resize → parity, hang →
quorum exit → one resize) lives in ``test_integrity_e2e.py``; these are
the cheap in-process halves."""

import json
import os
import threading
import time

import numpy as np
import pytest

import deepspeed_tpu as deepspeed
from deepspeed_tpu.elasticity.supervisor import EvictionLedger
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.resilience import (EXIT_DIVERGENCE_ABORT,
                                      EXIT_INTEGRITY_EVICT, ChaosMonkey,
                                      FleetIntegrityError,
                                      POISON_EXIT_CODES,
                                      TrainingDivergedError)
from deepspeed_tpu.resilience import integrity as integ
from deepspeed_tpu.resilience.config import DeepSpeedResilienceConfig

from .simple_model import SimpleModel, base_config, random_batches

HIDDEN = 16


# --------------------------------------------------------------- config
def test_integrity_config_defaults_and_parse():
    cfg = DeepSpeedResilienceConfig({})
    assert cfg.integrity is False
    assert cfg.integrity_window == 8
    assert cfg.integrity_action == "evict"
    assert cfg.integrity_peer_timeout_secs == 0.0

    cfg = DeepSpeedResilienceConfig({"resilience": {
        "enabled": True, "integrity": True, "integrity_window": 3,
        "integrity_action": "warn", "integrity_peer_timeout_secs": 2.5}})
    assert cfg.integrity and cfg.integrity_window == 3
    assert cfg.integrity_action == "warn"
    assert cfg.integrity_peer_timeout_secs == 2.5

    with pytest.raises(AssertionError, match="integrity_action"):
        DeepSpeedResilienceConfig({"resilience": {
            "integrity_action": "explode"}})
    with pytest.raises(AssertionError, match="integrity_window"):
        DeepSpeedResilienceConfig({"resilience": {"integrity_window": 0}})


def test_exit_code_contract():
    """87 is respawnable (the supervisor resizes on it); the poison set
    is untouched — no-majority and repeated eviction escalate to 86,
    which never respawns."""
    assert EXIT_INTEGRITY_EVICT == 87
    assert EXIT_INTEGRITY_EVICT not in POISON_EXIT_CODES
    assert EXIT_DIVERGENCE_ABORT in POISON_EXIT_CODES
    err = FleetIntegrityError("x", suspect=3, kind=integ.KIND_SDC)
    assert err.exit_code == EXIT_INTEGRITY_EVICT
    assert err.suspect == 3 and err.kind == "sdc_outlier"


# --------------------------------------------- fingerprint consensus
def _publish(run_dir, rank, hist):
    integ.publish_rank_fingerprint(
        str(run_dir), rank,
        {s: integ.canonical_fingerprint(v) for s, v in hist.items()})


def test_canonical_fingerprint_is_uint32_hex():
    assert integ.canonical_fingerprint(0) == "00000000"
    assert integ.canonical_fingerprint(0xDEADBEEF) == "deadbeef"
    # wraps like the device-side uint32 accumulator
    assert integ.canonical_fingerprint(2 ** 32 + 5) == "00000005"


def test_consensus_all_agree_is_ok(tmp_path):
    for r in range(4):
        _publish(tmp_path, r, {7: 111, 8: 222})
    fleet = integ.read_fleet_fingerprints(str(tmp_path), world_size=4)
    assert set(fleet) == {0, 1, 2, 3}
    v = integ.fingerprint_consensus(fleet, 4)
    assert v["verdict"] == integ.VERDICT_OK
    assert v["step"] == 8 and v["voters"] == 4 and v["suspects"] == []
    assert v["fingerprint"] == integ.canonical_fingerprint(222)


def test_consensus_names_the_outlier(tmp_path):
    for r in range(4):
        _publish(tmp_path, r, {8: 222 if r != 2 else 999})
    fleet = integ.read_fleet_fingerprints(str(tmp_path), world_size=4)
    v = integ.fingerprint_consensus(fleet, 4)
    assert v["verdict"] == integ.VERDICT_OUTLIER
    assert v["suspects"] == [2]
    assert v["fingerprint"] == integ.canonical_fingerprint(222)


def test_consensus_catches_lagging_outlier_in_window(tmp_path):
    """A suspect whose publishes lag the fleet head is still judged:
    corruption propagates, so the older step's disagreement stands."""
    _publish(tmp_path, 3, {7: 999})                      # stuck at 7, wrong
    for r in range(3):
        _publish(tmp_path, r, {7: 111, 8: 222})
    fleet = integ.read_fleet_fingerprints(str(tmp_path), world_size=4)
    v = integ.fingerprint_consensus(fleet, 4)
    # step 8 has only 3 voters (quorum ok, all agree) -> candidate ok;
    # step 7 has 4 voters with rank 3 disagreeing -> outlier wins
    assert v["verdict"] == integ.VERDICT_OUTLIER
    assert v["suspects"] == [3] and v["step"] == 7


def test_consensus_no_majority_is_unrecoverable(tmp_path):
    for r in range(4):
        _publish(tmp_path, r, {8: 111 if r < 2 else 222})
    fleet = integ.read_fleet_fingerprints(str(tmp_path), world_size=4)
    v = integ.fingerprint_consensus(fleet, 4)
    assert v["verdict"] == integ.VERDICT_NO_MAJORITY
    assert v["suspects"] == [0, 1, 2, 3]     # nobody can say who is right
    assert v["fingerprint"] is None


def test_consensus_below_quorum_is_pending(tmp_path):
    _publish(tmp_path, 0, {8: 111})
    fleet = integ.read_fleet_fingerprints(str(tmp_path), world_size=4)
    assert integ.fingerprint_consensus(fleet, 4)["verdict"] == \
        integ.VERDICT_PENDING
    # a 2-rank fleet still needs BOTH ranks (min quorum floor of 2):
    # one rank alone can never convict its peer
    assert integ.fingerprint_consensus(fleet, 2)["verdict"] == \
        integ.VERDICT_PENDING


def test_fleet_read_drops_foreign_stale_and_torn(tmp_path):
    _publish(tmp_path, 0, {8: 111})
    _publish(tmp_path, 9, {8: 111})                      # beyond world
    (tmp_path / "integrity-rank1.json").write_text('{"rank": 1, "fing')
    (tmp_path / "latency-rank0.json").write_text("{}")   # other family
    old = {"rank": 2, "ts": time.time() - 10_000,
           "fingerprints": {"8": "deadbeef"}}
    (tmp_path / "integrity-rank2.json").write_text(json.dumps(old))
    fleet = integ.read_fleet_fingerprints(str(tmp_path), world_size=4,
                                          max_age_secs=600)
    assert set(fleet) == {0}


def test_fleet_read_skips_non_numeric_ts(tmp_path):
    """Valid JSON with a garbage ts (foreign tool, operator debris)
    must be SKIPPED, not crash every voting rank's step loop through
    read_fleet_fingerprints -> note_fingerprint -> train_batch."""
    _publish(tmp_path, 0, {8: 111})
    bad = {"rank": 1, "ts": "yesterday", "fingerprints": {"8": "aa"}}
    (tmp_path / "integrity-rank1.json").write_text(json.dumps(bad))
    worse = {"rank": 2, "ts": [1, 2], "fingerprints": {"8": "aa"}}
    (tmp_path / "integrity-rank2.json").write_text(json.dumps(worse))
    fleet = integ.read_fleet_fingerprints(str(tmp_path), world_size=4,
                                          max_age_secs=600)
    assert set(fleet) == {0}
    # without a max_age filter the ts is never parsed: files readable
    assert set(integ.read_fleet_fingerprints(str(tmp_path),
                                             world_size=4)) == {0, 1, 2}


def test_integrity_plane_votes_and_trims_window(tmp_path):
    plane = integ.IntegrityPlane(tmp_path, rank=0, fleet_size=3, window=2)
    for r in (1, 2):
        _publish(tmp_path, r, {1: 10, 2: 20})
    v = plane.note_fingerprint(1, 10)
    # newest quorum step is 2 (the two peers ahead of us agree there)
    assert v["verdict"] == integ.VERDICT_OK
    assert v["step"] == 2 and v["voters"] == 2
    plane.note_fingerprint(2, 20)
    plane.note_fingerprint(3, 30)
    assert sorted(plane.history) == [2, 3]               # window trimmed
    own = json.load(open(tmp_path / "integrity-rank0.json"))
    assert sorted(own["fingerprints"]) == ["2", "3"]


# ------------------------------------------------- heartbeat + quorum
def test_hang_quorum_names_the_stale_laggard(tmp_path):
    now = time.time()
    for r in range(3):
        integ.publish_rank_heartbeat(str(tmp_path), r, 5)
    # rank 3 never entered step 5 and its beat is stale
    integ.publish_rank_heartbeat(str(tmp_path), 3, 4)
    beats = integ.read_fleet_heartbeats(str(tmp_path), world_size=4)
    beats[3]["ts"] = now - 60
    v = integ.hang_quorum(beats, self_rank=0, fleet_size=4,
                          peer_timeout_secs=5, now=now)
    assert v is not None and v["suspect"] == 3
    assert v["suspect_step"] == 4 and v["head_step"] == 5
    assert v["leaders"] == 3


def test_hang_quorum_abstains_when_not_at_head_or_no_majority():
    now = 1000.0
    fleet = {0: {"step": 4, "ts": now - 60},
             1: {"step": 5, "ts": now}, 2: {"step": 5, "ts": now},
             3: {"step": 5, "ts": now}}
    # rank 0 lags: IT must not vote (its local watchdog owns its fate)
    assert integ.hang_quorum(fleet, 0, 4, 5, now=now) is None
    # leaders are not a strict majority of the FLEET: abstain
    small = {0: {"step": 5, "ts": now}, 1: {"step": 4, "ts": now - 60}}
    assert integ.hang_quorum(small, 0, 4, 5, now=now) is None
    # a lagging peer with a FRESH beat is slow, not hung
    fresh = {0: {"step": 5, "ts": now}, 1: {"step": 5, "ts": now},
             2: {"step": 5, "ts": now}, 3: {"step": 4, "ts": now - 1}}
    assert integ.hang_quorum(fresh, 0, 4, 5, now=now) is None


def test_fleet_heartbeat_fires_verdict_and_eviction_exit(tmp_path):
    """Healthy ranks at the head detect the stale laggard, commit the
    verdict file, run the flush hook, and exit 87 — instead of blocking
    in a collective until N local watchdogs time out."""
    exits, fired = [], []
    hb = integ.FleetHeartbeat(
        tmp_path, rank=0, fleet_size=3, peer_timeout_secs=0.2,
        poll_interval=0.05, exit_fn=exits.append,
        on_fire=lambda v: fired.append(v))
    integ.publish_rank_heartbeat(str(tmp_path), 1, 7)
    stale = {"rank": 2, "step": 6, "ts": time.time() - 60}
    (tmp_path / "heartbeat-rank2.json").write_text(json.dumps(stale))
    hb.start()
    time.sleep(0.2)
    assert not hb.fired          # not armed before OUR first beat
    hb.beat(7)
    deadline = time.time() + 5
    while not hb.fired and time.time() < deadline:
        time.sleep(0.05)
    assert hb.fired and exits == [EXIT_INTEGRITY_EVICT]
    assert fired and fired[0]["suspect"] == 2
    v = integ.read_verdict(str(tmp_path))
    assert v["kind"] == integ.KIND_HANG and v["suspect"] == 2
    hb.stop()


def test_fleet_heartbeat_warn_action_does_not_evict(tmp_path):
    """integrity_action='warn' is the operator's explicit opt-out of
    automated eviction: a hang-quorum conviction runs the telemetry
    hook but writes NO verdict file and never exits — a momentary
    stall on a sharded mesh must not tear the fleet down."""
    exits, fired = [], []
    hb = integ.FleetHeartbeat(
        tmp_path, rank=0, fleet_size=3, peer_timeout_secs=0.2,
        poll_interval=0.05, exit_fn=exits.append, action="warn",
        on_fire=lambda v: fired.append(v))
    integ.publish_rank_heartbeat(str(tmp_path), 1, 7)
    stale = {"rank": 2, "step": 6, "ts": time.time() - 60}
    (tmp_path / "heartbeat-rank2.json").write_text(json.dumps(stale))
    hb.start()
    hb.beat(7)
    deadline = time.time() + 5
    while not hb.fired and time.time() < deadline:
        time.sleep(0.05)
    assert hb.fired and fired and fired[0]["suspect"] == 2
    assert exits == []                                   # no eviction
    assert integ.read_verdict(str(tmp_path)) is None     # no verdict
    hb.stop()
    with pytest.raises(AssertionError, match="integrity action"):
        integ.FleetHeartbeat(tmp_path, rank=0, fleet_size=3,
                             peer_timeout_secs=1.0, action="explode")


def test_integrity_plane_reset_history_unpublishes(tmp_path):
    """After an in-process rollback the abandoned timeline's published
    fingerprints must disappear immediately — a mixed stale/replayed
    window could convict a rank the rollback already fixed."""
    plane = integ.IntegrityPlane(tmp_path, rank=0, fleet_size=3)
    plane.note_fingerprint(1, 111)
    plane.note_fingerprint(2, 222)
    assert (tmp_path / "integrity-rank0.json").exists()
    plane.reset_history()
    assert plane.history == {} and plane.last_verdict is None
    assert not (tmp_path / "integrity-rank0.json").exists()
    assert integ.read_fleet_fingerprints(str(tmp_path)) == {}


def test_fleet_heartbeat_pause_disarms(tmp_path):
    exits = []
    hb = integ.FleetHeartbeat(tmp_path, rank=0, fleet_size=3,
                              peer_timeout_secs=0.1, poll_interval=0.02,
                              exit_fn=exits.append)
    integ.publish_rank_heartbeat(str(tmp_path), 1, 7)
    stale = {"rank": 2, "step": 6, "ts": time.time() - 60}
    (tmp_path / "heartbeat-rank2.json").write_text(json.dumps(stale))
    hb.beat(7)
    hb.pause()                   # restore/final-save window
    hb.start()
    time.sleep(0.3)
    assert not hb.fired and exits == []
    hb.stop()


def test_fleet_heartbeat_pause_keeps_own_beat_fresh(tmp_path):
    """Conviction happens on the PEERS' side, so a paused rank (long
    sync save, restore) must keep republishing its last beat with a
    fresh timestamp — going silent past the peers' timeout would get a
    healthy host evicted for a routine save."""
    hb = integ.FleetHeartbeat(tmp_path, rank=0, fleet_size=3,
                              peer_timeout_secs=5.0, poll_interval=0.02,
                              exit_fn=lambda c: None)
    hb.beat(7)
    first_ts = integ.read_fleet_heartbeats(str(tmp_path))[0]["ts"]
    hb.pause()
    hb.start()
    deadline = time.time() + 5
    while time.time() < deadline:
        beats = integ.read_fleet_heartbeats(str(tmp_path))
        if beats[0]["ts"] > first_ts:
            break
        time.sleep(0.02)
    refreshed = integ.read_fleet_heartbeats(str(tmp_path))[0]
    assert refreshed["ts"] > first_ts, "paused rank went silent"
    assert refreshed["step"] == 7          # still the pre-pause step
    hb.stop()


def test_fleet_heartbeat_publish_is_time_throttled(tmp_path):
    """beat() per optimizer step must NOT mean one file write per step:
    sub-min_publish_secs steps coalesce (time-based throttle only; the
    MONITOR thread — not started here — owns catching the published
    beat up to a swallowed step advance, off the hot path)."""
    hb = integ.FleetHeartbeat(tmp_path, rank=0, fleet_size=2,
                              peer_timeout_secs=60.0,
                              min_publish_secs=30.0,
                              exit_fn=lambda c: None)
    for step in range(1, 50):
        hb.beat(step)
    published = integ.read_fleet_heartbeats(str(tmp_path))[0]
    assert published["step"] == 1          # only the first beat wrote
    assert hb._last_step == 49             # the monitor still tracks us


def test_fleet_heartbeat_monitor_catches_up_throttled_beat(tmp_path):
    """A long step FOLLOWING a sub-throttle one must not leave this
    rank published one step behind the head with a growing-stale ts —
    the exact shape the quorum convicts, so without catch-up a healthy
    rank blocked behind a genuinely hung peer could be named instead of
    the peer.  The monitor thread republishes the swallowed step
    advance within one poll_interval; only real main-thread progress
    triggers it, so afterwards the timestamp ages normally and a
    genuine mid-step hang still reads stale."""
    hb = integ.FleetHeartbeat(tmp_path, rank=0, fleet_size=3,
                              peer_timeout_secs=60.0, poll_interval=0.02,
                              min_publish_secs=30.0,
                              exit_fn=lambda c: None)
    hb.beat(7)                             # published
    hb.beat(8)                             # swallowed by the throttle
    assert integ.read_fleet_heartbeats(str(tmp_path))[0]["step"] == 7
    hb.start()
    deadline = time.time() + 5
    while time.time() < deadline:
        if integ.read_fleet_heartbeats(str(tmp_path))[0]["step"] == 8:
            break
        time.sleep(0.02)
    published = integ.read_fleet_heartbeats(str(tmp_path))[0]
    assert published["step"] == 8, "monitor never caught up the beat"
    ts = published["ts"]
    time.sleep(0.2)                        # > several poll intervals
    assert integ.read_fleet_heartbeats(str(tmp_path))[0]["ts"] == ts, (
        "monitor refreshed the ts without progress — a real hang "
        "would be masked from the peers' staleness check")
    hb.stop()


def test_consensus_tie_with_lagging_publisher_is_not_poison(tmp_path):
    """fleet=5, 4 voters split 2-2: a tie among the VOTERS, but rank
    4's pending vote could still make either value a 3/5 fleet
    majority — poisoning here (exit 86, never respawns) would tear
    down a run one more publish could have saved by eviction.  The
    step is undecidable (pending), and once the straggler votes the
    minority bloc IS convicted."""
    _publish(tmp_path, 0, {4: 0xAA})
    _publish(tmp_path, 1, {4: 0xAA})
    _publish(tmp_path, 2, {4: 0xBB})
    _publish(tmp_path, 3, {4: 0xBB})
    fleet = integ.read_fleet_fingerprints(str(tmp_path), world_size=5)
    v = integ.fingerprint_consensus(fleet, 5)
    assert v["verdict"] == integ.VERDICT_PENDING, v
    # the lagging rank breaks the tie: 3/5 fleet majority -> outlier
    _publish(tmp_path, 4, {4: 0xAA})
    fleet = integ.read_fleet_fingerprints(str(tmp_path), world_size=5)
    v = integ.fingerprint_consensus(fleet, 5)
    assert v["verdict"] == integ.VERDICT_OUTLIER
    assert v["suspects"] == [2, 3]
    # full participation with no possible fleet majority stays poison
    fleet = {0: {4: "aa"}, 1: {4: "aa"}, 2: {4: "bb"}, 3: {4: "bb"}}
    v = integ.fingerprint_consensus(fleet, 4)
    assert v["verdict"] == integ.VERDICT_NO_MAJORITY


def test_consensus_plurality_of_voters_cannot_evict(tmp_path):
    """fleet=5, only 3 published, split 2-1: the pair is a majority of
    the VOTERS but not of the fleet — convicting would let 2/5 ranks
    evict a peer the unpublished rest may agree with.  The step is
    skipped (pending here), NOT an outlier and NOT a poison split."""
    _publish(tmp_path, 0, {4: 0xAA})
    _publish(tmp_path, 1, {4: 0xAA})
    _publish(tmp_path, 2, {4: 0xBB})
    fleet = integ.read_fleet_fingerprints(str(tmp_path), world_size=5)
    v = integ.fingerprint_consensus(fleet, 5)
    assert v["verdict"] == integ.VERDICT_PENDING, v
    # once a fleet majority holds the value, the outlier IS convicted
    _publish(tmp_path, 3, {4: 0xAA})
    fleet = integ.read_fleet_fingerprints(str(tmp_path), world_size=5)
    v = integ.fingerprint_consensus(fleet, 5)
    assert v["verdict"] == integ.VERDICT_OUTLIER and v["suspects"] == [2]


# ------------------------------------------------------- verdict file
def test_verdict_first_writer_wins(tmp_path):
    p1 = integ.write_verdict(str(tmp_path), integ.KIND_SDC, 2, "first",
                             rank=0, step=9)
    p2 = integ.write_verdict(str(tmp_path), integ.KIND_HANG, 3, "second")
    assert p1 == p2
    v = integ.read_verdict(str(tmp_path))
    assert v["kind"] == "sdc_outlier" and v["suspect"] == 2
    assert v["rank"] == 0 and v["step"] == 9


def test_verdict_commit_is_atomic_over_torn_first_writer(tmp_path):
    """A first writer killed mid-dump must not suppress every other
    accuser: the verdict only ever appears fully written (per-writer
    tmp + os.link), and a pre-existing TORN file at the verdict path
    is the pathology the link commit avoids — simulate the old
    open('x') torn state and show a reader sees None (the launcher
    resizes blind), then show the new commit path never produces it."""
    # new path: the committed file is complete JSON even while a
    # concurrent .w<pid> tmp exists
    p = integ.write_verdict(str(tmp_path), integ.KIND_SDC, 2, "full")
    assert p and integ.read_verdict(str(tmp_path))["suspect"] == 2
    assert not [n for n in os.listdir(tmp_path) if ".w" in n]  # tmp gone
    # second accuser: first writer still wins, no tmp debris
    integ.write_verdict(str(tmp_path), integ.KIND_HANG, 3, "late")
    assert integ.read_verdict(str(tmp_path))["suspect"] == 2
    assert not [n for n in os.listdir(tmp_path) if ".w" in n]
    # full clear scrubs a mid-commit writer's orphaned tmp too
    (tmp_path / (integ.VERDICT_FILE + ".w12345")).write_text("{")
    integ.clear_fleet_state(str(tmp_path))
    assert os.listdir(tmp_path) == []


def test_verdict_tmp_path_is_unique_per_writer(tmp_path, monkeypatch):
    """Accusers on DIFFERENT nodes share the run dir and can share a
    pid (pid_max wraps): the per-writer tmp must be unique per WRITE,
    not per pid, or two colliding writers truncate each other's
    in-progress JSON and os.link publishes a torn verdict — which
    reads as no-verdict and un-aims every node's resize."""
    seen = []
    real_link = os.link
    monkeypatch.setattr(
        os, "link", lambda src, dst: (seen.append(src),
                                      real_link(src, dst)))
    integ.write_verdict(str(tmp_path), integ.KIND_SDC, 1, "a")
    (tmp_path / integ.VERDICT_FILE).unlink()
    integ.write_verdict(str(tmp_path), integ.KIND_SDC, 1, "b")
    assert len(seen) == 2 and seen[0] != seen[1]


def test_read_verdict_rejects_unaimable_debris(tmp_path):
    """A "verdict" without an int-coercible suspect is shared-run-dir
    debris (foreign writer, other schema version): the supervisor
    cannot aim a resize with it, and passing it through would
    TypeError the launcher monitor loop — the one process that must
    outlive everything.  read_verdict validates, so the launcher
    resizes blind instead of dying."""
    path = tmp_path / integ.VERDICT_FILE
    for debris in ('{"kind": "sdc_outlier"}',            # no suspect
                   '{"suspect": null, "kind": "x"}',     # null suspect
                   '{"suspect": "rank two"}',            # non-numeric
                   '[1, 2, 3]',                          # non-dict
                   '{"torn'):                            # torn JSON
        path.write_text(debris)
        assert integ.read_verdict(str(tmp_path)) is None, debris
    path.write_text('{"suspect": "2", "kind": "sdc_outlier"}')
    v = integ.read_verdict(str(tmp_path))
    assert v is not None and v["suspect"] == 2           # coerced int


def test_verdict_consumed_marker_sibling_contract(tmp_path):
    """Consumption RENAMES the verdict to the consumed marker instead
    of deleting it: deletion races sibling nodes' monitor polls in a
    shared run dir and the node that owns the suspect's slot would
    resize blind.  The rename frees VERDICT_FILE for the next life's
    first-writer-wins commit, the resize-path clear preserves the
    marker, and the default (startup) clear scrubs it."""
    integ.write_verdict(str(tmp_path), integ.KIND_SDC, 2, "first")
    assert integ.mark_verdict_consumed(str(tmp_path)) is not None
    # fresh file gone, marker readable only via the sibling fallback
    assert integ.read_verdict(str(tmp_path)) is None
    sibling = integ.read_verdict(str(tmp_path), include_consumed=True)
    assert sibling is not None and sibling["suspect"] == 2
    # the fresh path is free again: a NEW conviction commits (the old
    # open-'x'-blocked-forever shape is gone) and shadows the marker
    integ.write_verdict(str(tmp_path), integ.KIND_HANG, 3, "second")
    fresh = integ.read_verdict(str(tmp_path), include_consumed=True)
    assert fresh["suspect"] == 3 and fresh["kind"] == integ.KIND_HANG
    integ.mark_verdict_consumed(str(tmp_path))           # overwrites
    assert integ.read_verdict(
        str(tmp_path), include_consumed=True)["suspect"] == 3
    # resize-path clear keeps the marker, scrubs everything else
    _publish(tmp_path, 0, {1: 1})
    integ.publish_rank_heartbeat(str(tmp_path), 0, 1)
    integ.clear_fleet_state(str(tmp_path), keep_consumed=True)
    assert os.listdir(tmp_path) == [integ.VERDICT_CONSUMED_FILE]
    # startup clear (reused run dir) scrubs the marker with the rest
    integ.clear_fleet_state(str(tmp_path))
    assert os.listdir(tmp_path) == []
    # nothing to rename: fail-soft
    assert integ.mark_verdict_consumed(str(tmp_path)) is None


def test_eviction_ledger_malformed_env_degrades(monkeypatch):
    """A malformed DS_INTEGRITY_MAX_EVICTIONS must degrade to the
    default, never kill the launcher at startup."""
    monkeypatch.setenv("DS_INTEGRITY_MAX_EVICTIONS", "one")
    ledger = EvictionLedger()
    assert ledger.max_evictions == 1


def test_clear_fleet_state_removes_every_artifact(tmp_path):
    _publish(tmp_path, 0, {1: 1})
    integ.publish_rank_heartbeat(str(tmp_path), 0, 1)
    integ.write_verdict(str(tmp_path), integ.KIND_SDC, 1, "x")
    (tmp_path / "integrity-rank3.json.tmp").write_text("{")
    (tmp_path / "events-rank0.jsonl").write_text("{}\n")  # NOT ours
    removed = integ.clear_fleet_state(str(tmp_path))
    assert removed == 4
    assert sorted(os.listdir(tmp_path)) == ["events-rank0.jsonl"]
    assert integ.read_verdict(str(tmp_path)) is None


def test_clear_fleet_state_targeted_rank(tmp_path):
    """An ordinary (non-87) single-rank respawn clears only THAT rank's
    fingerprint/heartbeat files: the dead life's stale beat would
    otherwise read as a hang through the backoff + re-init window and
    the quorum would falsely evict the new life — while peers' state
    and any committed verdict must survive the targeted clear."""
    for r in (0, 1):
        _publish(tmp_path, r, {1: 1})
        integ.publish_rank_heartbeat(str(tmp_path), r, 1)
    integ.write_verdict(str(tmp_path), integ.KIND_SDC, 9, "x")
    (tmp_path / "heartbeat-rank1.json.tmp").write_text("{")
    removed = integ.clear_fleet_state(str(tmp_path), rank=1)
    assert removed == 3          # rank 1's fp + beat + beat .tmp
    assert set(integ.read_fleet_fingerprints(str(tmp_path))) == {0}
    assert set(integ.read_fleet_heartbeats(str(tmp_path))) == {0}
    assert integ.read_verdict(str(tmp_path)) is not None


# ---------------------------------------------------- eviction ledger
def test_eviction_ledger_blocklist_and_budget(monkeypatch):
    monkeypatch.delenv("DS_INTEGRITY_MAX_EVICTIONS", raising=False)
    ledger = EvictionLedger()
    assert ledger.max_evictions == 1
    assert ledger.filter_slots([0, 1, 2, 3]) == [0, 1, 2, 3]
    assert ledger.record(suspect=2, slot=2, kind="sdc_outlier")
    assert ledger.blocked_slots == {2}
    assert ledger.filter_slots([0, 1, 2, 3]) == [0, 1, 3]
    # the SECOND verdict is a repeated eviction: poison, not resize
    assert not ledger.record(suspect=1, slot=1, kind="hang_quorum")
    assert ledger.blocked_slots == {1, 2}


def test_eviction_ledger_env_budget(monkeypatch):
    monkeypatch.setenv("DS_INTEGRITY_MAX_EVICTIONS", "2")
    ledger = EvictionLedger()
    assert ledger.record(0, 0, "sdc_outlier")
    assert ledger.record(1, 1, "sdc_outlier")
    assert not ledger.record(2, 2, "sdc_outlier")
    # a verdict whose suspect has no live slot still charges the budget
    assert EvictionLedger(max_evictions=1).record(5, None, "hang_quorum")


# ------------------------------------------------------ chaos injectors
def _make_engine(cpu_devices, dp=4, **overrides):
    cfg = base_config(steps_per_print=10 ** 9)
    cfg.update(overrides)
    mesh = make_mesh({"data": dp}, devices=cpu_devices[:dp])
    engine, *_ = deepspeed.initialize(model=SimpleModel(HIDDEN, nlayers=2),
                                      config=cfg, mesh=mesh)
    return engine


@pytest.fixture
def fleet_of_two(monkeypatch):
    """Launcher-style fleet identity: the fingerprint consensus only
    arms for >= 2 ranks (a single process can never reach quorum)."""
    monkeypatch.setenv("DS_PROCESS_ID", "0")
    monkeypatch.setenv("DS_NUM_PROCESSES", "2")


def test_chaos_bitflip_changes_one_element(cpu_devices):
    import jax

    engine = _make_engine(cpu_devices)
    engine.train_batch(iter([random_batches(1, 16, HIDDEN, seed=0)[0]]))
    before = np.array(jax.device_get(engine.state["master"]))
    monkey = ChaosMonkey(seed=5)
    idx, bit = monkey.bitflip_state(engine)
    after = np.array(jax.device_get(engine.state["master"]))
    diff = np.flatnonzero(before.reshape(-1).view(np.uint32)
                          != after.reshape(-1).view(np.uint32))
    assert list(diff) == [idx]
    assert 0 <= bit < 32
    assert monkey.log == [(f"master[{idx}]", "bitflip")]
    # same seed -> same flip (the fleet-reproducibility contract)
    assert ChaosMonkey(seed=5).bitflip_state(engine) == (idx, bit)
    engine.close()


def test_chaos_bitflip_changes_the_fingerprint(cpu_devices):
    """The injected SDC is invisible to loss/NaN guards but MUST move
    the state checksum — the detectability contract."""
    import jax

    engine = _make_engine(cpu_devices)
    engine.train_batch(iter([random_batches(1, 16, HIDDEN, seed=0)[0]]))
    engine._integrity = integ.IntegrityPlane(".", 0, 1)  # arm the jit
    clean = int(jax.device_get(engine._integrity_fingerprint_device()))
    ChaosMonkey(seed=1).bitflip_state(engine)
    flipped = int(jax.device_get(engine._integrity_fingerprint_device()))
    assert clean != flipped
    engine._integrity = None
    engine.close()


def test_fingerprint_sees_every_single_bit_flip(cpu_devices):
    """The checksum's position weights are forced ODD, so flipping ANY
    single bit of ANY element moves the uint32 sum — including the MSB
    (fp32 sign bit) at ODD flat indices, which an even weight (the
    naive ``i*K + 1`` form: even for odd ``i``) would make invisible
    mod 2^32.  Exactly the silent-SDC class the plane exists for."""
    import jax

    engine = _make_engine(cpu_devices)
    engine.train_batch(iter([random_batches(1, 16, HIDDEN, seed=0)[0]]))
    engine._integrity = integ.IntegrityPlane(".", 0, 1)  # arm the jit
    clean = int(jax.device_get(engine._integrity_fingerprint_device()))
    for idx, bit in ((1, 31), (3, 31), (0, 31), (2, 0)):
        before = engine.state["master"]
        host = np.array(jax.device_get(before))
        flat = host.reshape(-1).view(np.uint32)
        flat[idx] ^= np.uint32(1 << bit)
        engine.state["master"] = jax.device_put(host, before.sharding)
        flipped = int(jax.device_get(
            engine._integrity_fingerprint_device()))
        assert flipped != clean, (
            f"MSB/bit-{bit} flip at flat index {idx} left the "
            f"fingerprint unchanged — even position weight?")
        engine.state["master"] = before
    engine._integrity = None
    engine.close()


def test_integrity_fingerprint_disabled_under_offload(cpu_devices,
                                                      tmp_path,
                                                      fleet_of_two):
    """ZeRO-Offload homes (master, opt) on the host BECAUSE it does not
    fit on device: the in-jit checksum would re-upload it every print
    cadence, so the fingerprint consensus refuses to arm (loud warning)
    while the config still validates — heartbeat-only integrity."""
    engine = _make_engine(
        cpu_devices,
        **{"steps_per_print": 1,
           "zero_optimization": {"stage": 2, "cpu_offload": True},
           "telemetry": {"enabled": True, "run_dir": str(tmp_path)},
           "resilience": {"enabled": True, "integrity": True}})
    assert engine._integrity is None
    engine.train_batch(iter([random_batches(1, 16, HIDDEN, seed=0)[0]]))
    assert not (tmp_path / "integrity-rank0.json").exists()
    engine.close()


def test_drain_watchdog_malformed_env_degrades(monkeypatch):
    """A malformed DS_TERM_DRAIN_DEADLINE_SECS inside the SIGTERM
    handler must fall back to the default, never raise and abort the
    drain + final save it protects."""
    from deepspeed_tpu.checkpoint.manager import _arm_drain_watchdog

    monkeypatch.setenv("DS_TERM_DRAIN_DEADLINE_SECS", "90s")
    timer = _arm_drain_watchdog(grace=30.0)
    assert timer is not None            # default: 90% of the grace
    timer.cancel()
    monkeypatch.setenv("DS_TERM_DRAIN_DEADLINE_SECS", "0")
    assert _arm_drain_watchdog(grace=30.0) is None


def test_chaos_bitflip_and_hang_target_a_specific_rank(cpu_devices):
    engine = _make_engine(cpu_devices)
    engine.train_batch(iter([random_batches(1, 16, HIDDEN, seed=0)[0]]))

    # non-victim rank: the schedule passes through untouched
    monkey = ChaosMonkey(seed=3)
    out = list(monkey.wrap_iter(iter(range(4)), bitflip_steps=[1],
                                bitflip_engine=engine, hang_steps=[2],
                                hang_event=threading.Event(),
                                rank=1, target_rank=0))
    assert out == list(range(4)) and monkey.log == []

    # victim rank: bitflip lands at pull 1, hang at pull 2 (pre-set
    # event = released hang: returns immediately but logs the block)
    released = threading.Event()
    released.set()
    victim = ChaosMonkey(seed=3)
    out = list(victim.wrap_iter(iter(range(4)), bitflip_steps=[1],
                                bitflip_engine=engine, hang_steps=[2],
                                hang_event=released, rank=0,
                                target_rank=0))
    assert out == list(range(4))
    assert [k for _, k in victim.log] == ["bitflip", "hang"]
    engine.close()


def test_chaos_bitflip_requires_engine():
    with pytest.raises(AssertionError, match="bitflip_engine"):
        list(ChaosMonkey(0).wrap_iter(iter([1]), bitflip_steps=[0]))


# ----------------------------------------------------- engine wiring
def _tel_res_config(run_dir, **res):
    res.setdefault("enabled", True)
    res.setdefault("integrity", True)
    return base_config(steps_per_print=1,
                       telemetry={"enabled": True, "run_dir": str(run_dir)},
                       resilience=res)


def _read_events(run_dir, event_type):
    from deepspeed_tpu.telemetry import read_events

    return [r for r in read_events(run_dir) if r["type"] == event_type]


def test_engine_heartbeat_arming_needs_three_ranks(cpu_devices, tmp_path,
                                                   monkeypatch):
    """A 2-rank fleet can never reach a convicting hang majority (both
    at head = no suspect; one lagging = no majority), so the engine
    must not pay an inert monitor thread — and a 3-rank fleet arms
    with the configured action."""
    monkeypatch.setenv("DS_PROCESS_ID", "0")
    for n, armed in (("2", False), ("3", True)):
        monkeypatch.setenv("DS_NUM_PROCESSES", n)
        engine = _make_engine(
            cpu_devices,
            **_tel_res_config(tmp_path / n, integrity_action="warn",
                              integrity_peer_timeout_secs=30.0))
        if armed:
            assert engine._fleet_heartbeat is not None
            assert engine._fleet_heartbeat.action == "warn"
        else:
            assert engine._fleet_heartbeat is None
        engine.close()


def test_engine_fingerprint_is_replica_deterministic(cpu_devices,
                                                     tmp_path,
                                                     fleet_of_two):
    """Two same-seed engines (simulated dp replicas) publish BIT-EXACT
    fingerprints step for step — the property the majority vote rests
    on — and a bitflip on one desyncs it."""
    batches = random_batches(2, 16, HIDDEN, seed=0)
    fps = []
    for sub in ("a", "b"):
        engine = _make_engine(
            cpu_devices, **{"steps_per_print": 1,
                            "telemetry": {"enabled": True,
                                          "run_dir": str(tmp_path / sub)},
                            "resilience": {"enabled": True,
                                           "integrity": True}})
        for b in batches:
            engine.train_batch(iter([b]))
        own = json.load(open(tmp_path / sub / "integrity-rank0.json"))
        fps.append(own["fingerprints"])
        engine.close()
    assert fps[0] == fps[1] and sorted(fps[0]) == ["1", "2"]


def test_engine_sdc_outlier_evicts_with_verdict(cpu_devices, tmp_path,
                                                fleet_of_two):
    """The tentpole loop, in process: three simulated peers agree, this
    rank's corrupted state disagrees -> FleetIntegrityError(87), the
    supervisor-facing verdict file names the suspect, telemetry carries
    EVENT_INTEGRITY, and the watchdog threads are stopped first."""
    engine = _make_engine(cpu_devices,
                          **_tel_res_config(tmp_path))
    batches = random_batches(2, 16, HIDDEN, seed=0)
    engine.train_batch(iter([batches[0]]))
    engine._integrity.fleet_size = 4          # simulate the fleet
    for r in (1, 2, 3):
        integ.publish_rank_fingerprint(
            str(tmp_path), r, {1: "deadbeef", 2: "deadbeef"})
    with pytest.raises(FleetIntegrityError) as exc:
        engine.train_batch(iter([batches[1]]))
    assert exc.value.exit_code == EXIT_INTEGRITY_EVICT
    assert exc.value.suspect == 0 and exc.value.kind == "sdc_outlier"
    v = integ.read_verdict(str(tmp_path))
    assert v["kind"] == "sdc_outlier" and v["suspect"] == 0
    events = _read_events(tmp_path, "integrity")
    assert events and events[-1]["data"]["verdict"] == "outlier"
    assert events[-1]["data"]["suspects"] == [0]
    assert events[-1]["data"]["kind"] == "fingerprint"
    snap = json.load(open(tmp_path / "metrics-rank0.json"))
    assert snap["integrity/violations"]["value"] >= 1.0
    engine.close()


def test_engine_no_majority_poisons(cpu_devices, tmp_path,
                                    fleet_of_two):
    """A 2-2 split leaves nobody to trust: TrainingDivergedError (86,
    poison — the launcher never respawns it), and NO eviction verdict
    is written."""
    engine = _make_engine(cpu_devices, **_tel_res_config(tmp_path))
    batches = random_batches(2, 16, HIDDEN, seed=0)
    engine.train_batch(iter([batches[0]]))
    engine._integrity.fleet_size = 4
    integ.publish_rank_fingerprint(str(tmp_path), 1, {1: "deadbeef",
                                                      2: "deadbeef"})
    own = json.load(open(tmp_path / "integrity-rank0.json"))
    fp1 = own["fingerprints"]["1"]
    for r in (2, 3):
        integ.publish_rank_fingerprint(str(tmp_path), r, {1: fp1})
    # step 1 now has votes {me: fp1, 1: dead, 2: fp1, 3: fp1} -> ok...
    # make step 2 the split: two agree with whatever I compute is
    # impossible to prearrange, so split the OLDER step instead
    integ.publish_rank_fingerprint(str(tmp_path), 2, {1: "deadbeef"})
    with pytest.raises(TrainingDivergedError) as exc:
        engine.train_batch(iter([batches[1]]))
    assert exc.value.exit_code == EXIT_DIVERGENCE_ABORT
    assert integ.read_verdict(str(tmp_path)) is None
    engine.close()


def test_engine_warn_action_continues(cpu_devices, tmp_path,
                                      fleet_of_two):
    """integrity_action=warn (sharded meshes, future per-shard work):
    the outlier verdict is telemetry-only — training continues, nothing
    raises, no verdict file."""
    engine = _make_engine(
        cpu_devices, **_tel_res_config(tmp_path, integrity_action="warn"))
    batches = random_batches(3, 16, HIDDEN, seed=0)
    engine.train_batch(iter([batches[0]]))
    engine._integrity.fleet_size = 4
    for r in (1, 2, 3):
        integ.publish_rank_fingerprint(str(tmp_path), r, {1: "deadbeef"})
    engine.train_batch(iter([batches[1]]))
    engine.train_batch(iter([batches[2]]))
    assert integ.read_verdict(str(tmp_path)) is None
    events = _read_events(tmp_path, "integrity")
    assert any(e["data"]["verdict"] == "outlier" for e in events)
    engine.close()


def test_engine_consensus_ok_across_simulated_fleet(cpu_devices,
                                                    tmp_path,
                                                    fleet_of_two):
    """Peers that agree with this rank's real fingerprints produce ok
    verdicts and no escalation."""
    engine = _make_engine(cpu_devices, **_tel_res_config(tmp_path))
    batches = random_batches(2, 16, HIDDEN, seed=0)
    engine.train_batch(iter([batches[0]]))
    own = json.load(open(tmp_path / "integrity-rank0.json"))
    engine._integrity.fleet_size = 4
    for r in (1, 2, 3):
        integ.publish_rank_fingerprint(
            str(tmp_path), r,
            {int(s): fp for s, fp in own["fingerprints"].items()})
    engine.train_batch(iter([batches[1]]))   # votes: step 1 unanimous
    events = _read_events(tmp_path, "integrity")
    assert events[-1]["data"]["verdict"] == "ok"
    assert events[-1]["data"]["voters"] == 4
    engine.close()


def test_report_integrity_section_and_json(tmp_path):
    """The report CLI's fleet-integrity section: non-ok verdicts and
    hang fires reconstructed from run-dir artifacts alone (text + the
    structured ``--json`` document), and the launcher's ``evict`` phase
    spelled out in the elastic timeline."""
    from deepspeed_tpu.telemetry import report as report_mod
    from deepspeed_tpu.telemetry.events import EventLog

    w = EventLog(str(tmp_path), rank=0)
    w.emit("integrity", step=1, verdict="ok", kind="fingerprint",
           suspects=[], voters=4, voted_step=1,
           majority_fingerprint="aa", fingerprint="aa")
    w.emit("integrity", step=2, verdict="outlier", kind="fingerprint",
           suspects=[2], voters=4, voted_step=2,
           majority_fingerprint="bb", fingerprint="bb")
    w.emit("integrity", step=2, verdict="outlier", kind="hang_quorum",
           suspects=[3], stalled_secs=4.2, suspect_step=1, head_step=2,
           voters=3)
    w.emit("elastic", phase="evict", suspect=2, slot=2,
           kind="sdc_outlier", detail="fp", eviction=1, exit_code=87)
    w.close()

    text, records = report_mod.generate_report(str(tmp_path))
    assert "fleet integrity" in text
    assert "fingerprint votes: 2 (1 ok/pending, 1 flagged)" in text
    assert "fingerprint outlier: rank(s) [2]" in text
    assert "hang quorum: rank(s) [3] stalled 4.2s" in text
    assert "integrity verdict (sdc_outlier): rank 2 / slot 2" in text
    for r in records:
        from deepspeed_tpu.telemetry.events import validate_event
        assert validate_event(r) == [], r

    # an integrity-typed line WITHOUT "data" (older/foreign writer,
    # hand-patched artifact) must not crash the report — every section
    # reads defensively
    ev_file = next(tmp_path.glob("events-rank0*.jsonl"))
    with open(ev_file, "a") as f:
        f.write(json.dumps({"type": "integrity", "ts": 1.0, "rank": 0,
                            "seq": 999}) + "\n")
    text_d, _ = report_mod.generate_report(str(tmp_path))
    assert "fingerprint votes: 2 (1 ok/pending, 1 flagged)" in text_d

    doc = report_mod.report_json(str(tmp_path))
    # only non-ok verdicts ride the structured section (the ok votes
    # stay in the raw event list)
    assert [d["suspects"] for d in doc["integrity"]] == [[2], [3]]
    assert doc["elastic"][0]["phase"] == "evict"

    # a run with no integrity events prints no section at all
    other = tmp_path / "plain"
    other.mkdir()
    w2 = EventLog(str(other), rank=0)
    w2.emit("run_start", world_size=1)
    w2.close()
    text2, _ = report_mod.generate_report(str(other))
    assert "fleet integrity" not in text2


def test_engine_integrity_requires_telemetry(cpu_devices):
    """No run dir = no exchange medium: the plane disables itself with
    a warning instead of crashing or silently pretending to guard."""
    engine = _make_engine(cpu_devices,
                          resilience={"enabled": True, "integrity": True})
    assert engine._integrity is None and engine._fleet_heartbeat is None
    engine.train_batch(iter([random_batches(1, 16, HIDDEN, seed=0)[0]]))
    engine.close()
