"""Packaging / install story (reference ``setup.py:70-197``): the package
must be pip-installable with working console entry points and its native
kernel sources shipped as package data, so the CLI tools work with the
repo nowhere on ``sys.path``."""
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))


@pytest.fixture(scope="module")
def installed_tree(tmp_path_factory):
    """pip-install the repo into an isolated --target tree (builds the
    wheel via setuptools, no network: --no-deps --no-build-isolation)."""
    target = tmp_path_factory.mktemp("site")
    proc = subprocess.run(
        [sys.executable, "-m", "pip", "install", "--quiet", "--no-deps",
         "--no-build-isolation", "--target", str(target), REPO_ROOT],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr
    return target


def test_install_ships_package_and_native_sources(installed_tree):
    pkg = installed_tree / "deepspeed_tpu"
    assert (pkg / "__init__.py").is_file()
    # the JIT-built host Adam kernel source must ride along (op_builder
    # resolves sources relative to the installed package dir)
    assert (pkg / "csrc" / "adam" / "cpu_adam.cpp").is_file()


@pytest.mark.parametrize("script", ["deepspeed", "ds", "ds_report",
                                    "ds_ssh", "ds_elastic", "dslint"])
def test_console_scripts_run_off_tree(installed_tree, script, tmp_path):
    """Each console script must import and print help using ONLY the
    installed tree — cwd is outside the repo and sys.path excludes it."""
    env = dict(os.environ,
               PYTHONPATH=str(installed_tree),
               JAX_PLATFORMS="cpu",
               # don't let the user site or repo leak in
               PYTHONNOUSERSITE="1")
    exe = installed_tree / "bin" / script
    assert exe.is_file(), f"pip --target did not create bin/{script}"
    proc = subprocess.run([sys.executable, str(exe), "--help"],
                          capture_output=True, text=True, timeout=120,
                          cwd=tmp_path, env=env)
    assert proc.returncode == 0, proc.stderr
    out = (proc.stdout + proc.stderr).lower()
    # ds_report has no arg parser — it just prints the report
    assert "usage" in out or "environment report" in out
