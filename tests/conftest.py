"""Test harness configuration.

The reference exercised "multi-node" logic as multi-process NCCL on one host
(``tests/unit/common.py:16-105``).  Here the analogous trick is a *virtual
multi-chip mesh*: ``--xla_force_host_platform_device_count=8`` gives 8 CPU
devices in one process, and meshes/shardings built over them execute the
same SPMD programs (same collectives, same partitioning) that run on a real
pod.  These env vars must be set before jax initializes its backends, hence
the module-level code in conftest.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# Prefer CPU for tests: compiles are fast and results deterministic.  A
# site hook may have imported jax at interpreter startup with a TPU
# platform forced (e.g. JAX_PLATFORMS=axon), in which case mutating
# os.environ here is too late — jax.config.update is the only switch
# that still takes effect, and it avoids initializing (and dialing) the
# TPU backend at all.  An explicit non-axon JAX_PLATFORMS (e.g. a
# developer running the suite on real hardware) is honored.
if os.environ.get("JAX_PLATFORMS", "axon") in ("axon", "", "axon,cpu"):
    jax.config.update("jax_platforms", "cpu")
    os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses tests spawn


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices("cpu")
    assert len(devs) >= 8, f"expected >=8 virtual cpu devices, got {len(devs)}"
    return devs


@pytest.fixture(autouse=True)
def _default_cpu():
    """Run unsharded computations on CPU regardless of the default backend."""
    cpu0 = jax.devices("cpu")[0]
    with jax.default_device(cpu0):
        yield
