"""Test harness configuration.

The reference exercised "multi-node" logic as multi-process NCCL on one host
(``tests/unit/common.py:16-105``).  Here the analogous trick is a *virtual
multi-chip mesh*: ``--xla_force_host_platform_device_count=8`` gives 8 CPU
devices in one process, and meshes/shardings built over them execute the
same SPMD programs (same collectives, same partitioning) that run on a real
pod.  These env vars must be set before jax initializes its backends, hence
the module-level code in conftest.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# Prefer CPU for tests: compiles are fast and results deterministic.  A
# site hook may have imported jax at interpreter startup with a TPU
# platform forced (e.g. JAX_PLATFORMS=axon), in which case mutating
# os.environ here is too late — jax.config.update is the only switch
# that still takes effect, and it avoids initializing (and dialing) the
# TPU backend at all.  An explicit non-axon JAX_PLATFORMS (e.g. a
# developer running the suite on real hardware) is honored, and
# DS_TEST_TPU=1 opts in to the real accelerator for the ``-m tpu``
# compiled-kernel suite (``DS_TEST_TPU=1 pytest -m tpu``).
_want_tpu = os.environ.get("DS_TEST_TPU") == "1"
if (not _want_tpu
        and os.environ.get("JAX_PLATFORMS", "axon") in ("axon", "",
                                                        "axon,cpu")):
    jax.config.update("jax_platforms", "cpu")
    os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses tests spawn


# Persistent XLA compilation cache: the suite's cost is overwhelmingly
# compiling the same tiny programs over and over; warm runs skip it.
_cache_dir = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
try:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:  # older jax without the knobs — run uncached
    pass


def _tpu_usable():
    """Whether a real TPU device can actually run work — gate for the
    ``tpu`` marker (checking devices, not jax.default_backend(): the
    platform pinning above makes the default backend CPU either way)."""
    try:
        return len(jax.devices("tpu")) > 0
    except Exception:
        return False


def pytest_collection_modifyitems(config, items):
    if _tpu_usable():
        return
    skip_tpu = pytest.mark.skip(
        reason="needs a usable TPU (run: DS_TEST_TPU=1 pytest -m tpu)")
    for item in items:
        if "tpu" in item.keywords:
            item.add_marker(skip_tpu)


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices("cpu")
    assert len(devs) >= 8, f"expected >=8 virtual cpu devices, got {len(devs)}"
    return devs


@pytest.fixture(autouse=True)
def _default_cpu(request):
    """Run unsharded computations on CPU regardless of the default backend —
    EXCEPT for ``-m tpu`` tests, which exist precisely to exercise compiled
    kernels on the real chip (pinning them to CPU made pallas_call fail
    with 'Only interpret mode is supported on CPU backend')."""
    if request.node.get_closest_marker("tpu"):
        with jax.default_device(jax.devices("tpu")[0]):
            yield
        return
    cpu0 = jax.devices("cpu")[0]
    with jax.default_device(cpu0):
        yield
