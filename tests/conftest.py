"""Test harness configuration.

The reference exercised "multi-node" logic as multi-process NCCL on one host
(``tests/unit/common.py:16-105``).  Here the analogous trick is a *virtual
multi-chip mesh*: ``--xla_force_host_platform_device_count=8`` gives 8 CPU
devices in one process, and meshes/shardings built over them execute the
same SPMD programs (same collectives, same partitioning) that run on a real
pod.  These env vars must be set before jax initializes its backends, hence
the module-level code in conftest.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# Prefer CPU for tests: compiles are fast and results deterministic.  (The
# axon TPU plugin may still register; tests pin meshes to cpu devices.)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices("cpu")
    assert len(devs) >= 8, f"expected >=8 virtual cpu devices, got {len(devs)}"
    return devs


@pytest.fixture(autouse=True)
def _default_cpu():
    """Run unsharded computations on CPU regardless of the default backend."""
    cpu0 = jax.devices("cpu")[0]
    with jax.default_device(cpu0):
        yield
