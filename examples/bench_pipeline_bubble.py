#!/usr/bin/env python
"""Pipeline schedule cost on the virtual 8-device CPU mesh.

The compiled fill-drain schedule EXECUTES its bubble ticks (masked work),
so the interleaved schedule's tick reduction — ``(mb + p − 1)·v`` →
``v·mb + p − 1`` chunk-ticks — shows up directly as less executed work and
less wall time, even on CPU devices.  This prints analytic tick counts,
bubble fractions, and measured wall time per train_batch for
interleave ∈ {1, 2, 4} at pipe=4.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python examples/bench_pipeline_bubble.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh
from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule

HIDDEN, MB, MB_SIZE, LAYERS, P_STAGES = 512, 8, 4, 16, 4
STEPS = 8


class Linear:
    def __init__(self, d):
        self.d = d

    def init(self, rng):
        return {"w": jax.random.normal(rng, (self.d, self.d),
                                       jnp.float32) * 0.05}

    def apply(self, p, x):
        return jnp.tanh(x @ p["w"])


def mse(out, lab):
    return jnp.mean((out - lab) ** 2)


def run(interleave):
    mesh = make_mesh({"pipe": P_STAGES}, devices=jax.devices("cpu")[:P_STAGES])
    module = PipelineModule([LayerSpec(Linear, HIDDEN) for _ in range(LAYERS)],
                            loss_fn=mse, partition_method="uniform",
                            interleave=interleave)
    engine, *_ = deepspeed.initialize(
        model=module, mesh=mesh,
        config={"train_micro_batch_size_per_gpu": MB_SIZE,
                "gradient_accumulation_steps": MB,
                "steps_per_print": 10 ** 9,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    rng = np.random.default_rng(0)
    data = [(rng.normal(size=(MB_SIZE, HIDDEN)).astype(np.float32),
             rng.normal(size=(MB_SIZE, HIDDEN)).astype(np.float32))
            for _ in range(MB)]
    loss = engine.train_batch(iter(data))  # compile
    float(np.asarray(loss))
    t0 = time.perf_counter()
    for _ in range(STEPS):
        loss = engine.train_batch(iter(data))
    float(np.asarray(loss))
    dt = (time.perf_counter() - t0) / STEPS

    v, p = interleave, P_STAGES
    chunk_ticks = v * MB + p - 1
    work_ticks = v * MB
    bubble = (chunk_ticks - work_ticks) / chunk_ticks
    # normalize to stage-equivalents so v=1 and v>1 compare directly
    stage_equiv = chunk_ticks / v
    return dt, chunk_ticks, bubble, stage_equiv


def main():
    print(f"# pipe={P_STAGES} micro_batches={MB} layers={LAYERS} "
          f"hidden={HIDDEN} ({P_STAGES}-device virtual CPU mesh)")
    base = None
    for v in (1, 2, 4):
        dt, ticks, bubble, se = run(v)
        base = base or dt
        print(f"interleave={v}: {ticks:3d} chunk-ticks "
              f"({se:5.2f} stage-equivalents, bubble {bubble:.1%})  "
              f"wall {dt * 1e3:7.1f} ms/batch  ({base / dt:.2f}x vs v=1)")


if __name__ == "__main__":
    main()
