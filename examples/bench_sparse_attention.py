#!/usr/bin/env python
"""Block-sparse vs dense flash attention wall-time on TPU.

The reference markets sparse attention as a SPEED feature ("up to 6.3x
faster", docs/_posts/2020-09-09-sparse-attention.md:32); this measures the
Pallas LUT-driven block-sparse kernel against the dense flash kernel at
long sequence lengths (BigBird layout, block 128) so PERF.md can carry
measured numbers instead of a numerics-only claim.

Measurement discipline (PERF.md methodology): the op iterates inside ONE
jit via lax.scan with results folded into the carry (per-dispatch tunnel
latency here is ~70 ms and would otherwise dominate), and every timing
boundary is a host round-trip on a scalar.

Usage: python examples/bench_sparse_attention.py [seq ...]
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                                flash_block_sparse_attention)
from deepspeed_tpu.ops.transformer.flash_attention import flash_attention

H, D = 16, 64  # BERT-large head geometry
STEPS = int(os.environ.get("BENCH_STEPS", "10"))
WARMUP = int(os.environ.get("BENCH_WARMUP", "2"))
# Layout block size.  The kernel's tiles ARE the layout blocks: 128-wide
# tiles starve the MXU pipeline (measured 0.76x vs dense at seq 4096),
# 512-wide tiles are the efficient shape — use long sequences where the
# window covers a small fraction of the row.
BLOCK = int(os.environ.get("BENCH_BLOCK", "512"))


REPEATS = int(os.environ.get("BENCH_REPEATS", "3"))


def make_runner(attn_fn, q, k, v, steps):
    """Compile + warm a scan-of-``steps`` runner; returns a zero-arg
    timed call (ONE dispatch, fenced by a host round-trip, seconds per
    step).  Splitting build from timing lets callers interleave repeats
    across kernels — the PERF.md methodology: a single timed shot
    swings ±50% on the remote attachment, and back-to-back repeats let
    one load spike mis-rank a whole kernel (the round-5 driver-vs-
    example sparse discrepancy, VERDICT r5 item 3)."""

    @jax.jit
    def run(q, k, v):
        def body(carry, _):
            cq, ck, cv = carry
            loss, (gq, gk, gv) = jax.value_and_grad(
                lambda a, b_, c: jnp.sum(attn_fn(a, b_, c) ** 2),
                argnums=(0, 1, 2))(cq, ck, cv)
            # fold grads into the carry so XLA cannot hoist the iteration
            eps = jnp.bfloat16(1e-8)
            return ((cq - eps * gq).astype(cq.dtype),
                    (ck - eps * gk).astype(ck.dtype),
                    (cv - eps * gv).astype(cv.dtype)), loss

        (cq, _, _), losses = jax.lax.scan(body, (q, k, v), None, length=steps)
        return jnp.sum(losses) + jnp.sum(cq[0, 0, 0])

    float(jax.device_get(run(q, k, v)))  # compile + warm
    for _ in range(WARMUP):
        float(jax.device_get(run(q, k, v)))

    def timed():
        t0 = time.perf_counter()
        r = float(jax.device_get(run(q, k, v)))
        dt = time.perf_counter() - t0
        assert np.isfinite(r)
        return dt / steps

    return timed


def timed_min_interleaved(runners, repeats=REPEATS):
    """Min-aggregated per-step seconds for each warmed runner, repeats
    INTERLEAVED across runners so ambient load cancels in the ratio."""
    results = [[] for _ in runners]
    for _ in range(repeats):
        for i, timed in enumerate(runners):
            results[i].append(timed())
    return [min(rs) for rs in results]


def timed_fwd_bwd(attn_fn, q, k, v, steps):
    """Min-of-repeats fwd+bwd wall seconds per step (single-kernel
    form; pairwise comparisons should interleave via make_runner +
    timed_min_interleaved)."""
    return timed_min_interleaved([make_runner(attn_fn, q, k, v, steps)])[0]


def main():
    seqs = [int(a) for a in sys.argv[1:]] or [4096, 8192, 16384]
    dev = jax.devices()[0]
    print(f"# device={getattr(dev, 'device_kind', dev)} b=1 h={H} d={D} "
          f"steps={STEPS}")
    for s in seqs:
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, (1, s, H, D), jnp.bfloat16)
                   for kk in ks)
        cfg = BigBirdSparsityConfig(num_heads=H, block=BLOCK,
                                    num_random_blocks=1,
                                    num_sliding_window_blocks=3,
                                    num_global_blocks=1)
        layout = cfg.make_layout(s)
        active = layout[0].sum() / layout[0].size

        t_dense, t_sparse = timed_min_interleaved([
            make_runner(lambda a, b_, c: flash_attention(a, b_, c),
                        q, k, v, STEPS),
            make_runner(lambda a, b_, c: flash_block_sparse_attention(
                a, b_, c, layout), q, k, v, STEPS)])
        print(f"seq {s:6d}: dense {t_dense * 1e3:8.2f} ms  "
              f"sparse {t_sparse * 1e3:8.2f} ms  "
              f"speedup {t_dense / t_sparse:5.2f}x  "
              f"(layout density {active:.3f})")


if __name__ == "__main__":
    main()
