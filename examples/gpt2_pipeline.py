#!/usr/bin/env python
"""GPT-2 with hybrid pipeline x data parallelism (the reference's
Megatron+pipeline tutorial flow, compiled-SPMD style).

Builds the LM as a PipelineModule (tied embedding/LM head via
TiedLayerSpec) and trains on a pipe x data mesh with per-tick
rematerialization.  Synthetic tokens; single- or multi-host via
bin/deepspeed.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import deepspeed_tpu as deepspeed  # noqa: E402
from deepspeed_tpu.models.layers import (TransformerLayer,  # noqa: E402
                                         cross_entropy_with_logits,
                                         embedding_init, layer_norm)
from deepspeed_tpu.parallel import make_mesh  # noqa: E402
from deepspeed_tpu.runtime.pipe import (LayerSpec, PipelineModule,  # noqa: E402
                                        TiedLayerSpec)


class Embedding:
    def __init__(self, vocab, hidden, max_pos):
        self.vocab, self.hidden, self.max_pos = vocab, hidden, max_pos

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        return {"wte": embedding_init(k1, self.vocab, self.hidden),
                "wpe": embedding_init(k2, self.max_pos, self.hidden)}

    def apply(self, params, ids):
        s = ids.shape[1]
        return jnp.take(params["wte"], ids, axis=0) + params["wpe"][None, :s]


def lm_head(params, x):
    # decode with the TIED token embedding (wte), transposed
    return x @ params["wte"].T.astype(x.dtype)


class FinalNorm:
    def init(self, rng):
        return {"scale": jnp.ones((HIDDEN,), jnp.float32),
                "bias": jnp.zeros((HIDDEN,), jnp.float32)}

    def apply(self, params, x):
        return layer_norm(params, x)


HIDDEN = 256


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--pipe", type=int, default=2)
    parser.add_argument("--data", type=int, default=2)
    parser.add_argument("--layers", type=int, default=8)
    parser.add_argument("--micro_batch", type=int, default=4)
    parser.add_argument("--grad_acc", type=int, default=4)
    parser.add_argument("--seq", type=int, default=64)
    parser.add_argument("--vocab", type=int, default=1024)
    parser.add_argument("--interleave", type=int, default=1,
                        help="virtual stages per rank (Megatron interleaved "
                             "schedule; needs grad_acc %% pipe == 0)")
    args = parser.parse_args()

    specs = (
        [TiedLayerSpec("embed", Embedding, args.vocab, HIDDEN, args.seq,
                       tied_weight_attr="wte")]
        + [LayerSpec(TransformerLayer, HIDDEN, 8, causal=True,
                     attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0,
                     pre_layer_norm=True) for _ in range(args.layers)]
        + [LayerSpec(FinalNorm),
           TiedLayerSpec("embed", Embedding, args.vocab, HIDDEN, args.seq,
                         forward_fn=lm_head, tied_weight_attr="wte")]
    )

    def loss_fn(logits, labels):
        return cross_entropy_with_logits(logits, labels)

    module = PipelineModule(specs, loss_fn=loss_fn, seed_layers=True,
                            partition_method="uniform",
                            activation_checkpoint_interval=1,
                            interleave=args.interleave)
    config = {
        "train_micro_batch_size_per_gpu": args.micro_batch,
        "gradient_accumulation_steps": args.grad_acc,
        "steps_per_print": 10,
        "optimizer": {"type": "Adam", "params": {"lr": 3e-4}},
    }
    mesh = make_mesh({"pipe": args.pipe, "data": args.data})
    engine, *_ = deepspeed.initialize(model=module, config=config, mesh=mesh)

    rng = np.random.default_rng(0)
    bs = args.micro_batch * args.data

    def batches():
        while True:
            ids = rng.integers(0, args.vocab,
                               size=(bs, args.seq + 1)).astype(np.int32)
            yield ids[:, :-1], ids[:, 1:]

    it = batches()
    for step in range(args.steps):
        loss = engine.train_batch(it)
    print(f"final loss: {float(np.asarray(jax.device_get(loss))):.4f}")


if __name__ == "__main__":
    main()
