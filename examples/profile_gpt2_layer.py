"""Micro-profile of ONE GPT-2-medium layer's components at the bench shape.

Attributes the trunk's wall time (the step profile's dominant scope) to
QKV/attention/FFN/layernorm/dropout at [b=8, s=1024, h=1024, heads=16].
Every probe runs inside one jitted lax.scan (tunnel dispatch ~70 ms would
otherwise swamp sub-ms ops) with operands passed as arguments (NOT
closures — large closure constants stall XLA compiles).

Usage: python examples/profile_gpt2_layer.py
"""

import os

import numpy as np

B, S, H, HEADS = 8, 1024, 1024, 16
D = H // HEADS
STEPS = int(os.environ.get("PROF_STEPS", "20"))


def main():
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.models.layers import TransformerLayer
    from deepspeed_tpu.ops.transformer.flash_attention import flash_attention
    from deepspeed_tpu.profiling.step_profiler import grad_fold, timed_scan

    rng = jax.random.PRNGKey(0)
    layer = TransformerLayer(hidden_size=H, heads=HEADS, causal=True,
                             attn_dropout_ratio=0.1, hidden_dropout_ratio=0.1,
                             pre_layer_norm=True)
    params = layer.init(rng)
    params = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
        params)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, S, H), jnp.bfloat16)
    qkvh = jax.random.normal(jax.random.PRNGKey(2), (B, S, HEADS, D),
                             jnp.bfloat16)

    def t(name, fn, ops, bwd=True):
        fwd_ms = timed_scan(fn, ops, steps=STEPS) * 1e3
        line = f"  {name:>28}: fwd {fwd_ms:7.3f} ms"
        if bwd:
            def fb(o, i):
                val, grads = jax.value_and_grad(
                    lambda oo: fn(oo, i))(o)
                return val + 1e-30 * grad_fold(grads)

            fb_ms = timed_scan(fb, ops, steps=STEPS) * 1e3
            line += f"   fwd+bwd {fb_ms:8.3f} ms"
        print(line, flush=True)

    # full layer, dropout on/off
    def layer_drop(ops, i):
        p, xx = ops
        r = jax.random.fold_in(jax.random.PRNGKey(7), i)
        return jnp.sum(layer.apply(p, xx, rng=r, deterministic=False)
                       .astype(jnp.float32)) * 1e-9

    def layer_nodrop(ops, i):
        p, xx = ops
        return jnp.sum(layer.apply(p, xx, deterministic=True)
                       .astype(jnp.float32)) * 1e-9

    t("layer (dropout 0.1)", layer_drop, (params, x))
    t("layer (no dropout)", layer_nodrop, (params, x))

    # attention core alone (flash kernel, causal)
    def attn_only(ops, i):
        q, k, v = ops
        o = flash_attention(q, k, v, causal=True)
        return jnp.sum(o.astype(jnp.float32)) * 1e-9

    t("flash attention (causal)", attn_only, (qkvh, qkvh, qkvh))

    # the GEMMs at layer shapes
    def gemm(shape_b):
        w = jax.random.normal(jax.random.PRNGKey(3), (H, shape_b),
                              jnp.bfloat16)

        def f(ops, i):
            xx, ww = ops
            y = jax.lax.dot_general(
                xx.reshape(-1, H), ww, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return jnp.sum(y) * 1e-9

        return f, (x, w)

    for name, nout in (("QKV GEMM [1024->3072]", 3 * H),
                       ("attn-out GEMM [1024->1024]", H),
                       ("FC1 GEMM [1024->4096]", 4 * H)):
        f, ops = gemm(nout)
        t(name, f, ops)

    # FC2 [4096 -> 1024]
    xi = jax.random.normal(jax.random.PRNGKey(4), (B, S, 4 * H), jnp.bfloat16)
    w2 = jax.random.normal(jax.random.PRNGKey(5), (4 * H, H), jnp.bfloat16)

    def fc2(ops, i):
        xx, ww = ops
        y = jax.lax.dot_general(xx.reshape(-1, 4 * H), ww,
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        return jnp.sum(y) * 1e-9

    t("FC2 GEMM [4096->1024]", fc2, (xi, w2))

    # layernorm at [8, 1024, 1024]
    from deepspeed_tpu.models.layers import layer_norm
    ln_p = {"scale": jnp.ones((H,), jnp.float32),
            "bias": jnp.zeros((H,), jnp.float32)}

    def ln(ops, i):
        p, xx = ops
        return jnp.sum(layer_norm(p, xx, 1e-5).astype(jnp.float32)) * 1e-9

    t("layernorm", ln, (ln_p, x))

    # one dropout site at [8, 1024, 1024]
    from deepspeed_tpu.models.layers import dropout as ds_dropout

    def drop(ops, i):
        xx, = ops
        r = jax.random.fold_in(jax.random.PRNGKey(9), i)
        return jnp.sum(ds_dropout(r, xx, 0.1, False)
                       .astype(jnp.float32)) * 1e-9

    t("dropout site [8,1024,1024]", drop, (x,))


if __name__ == "__main__":
    main()
