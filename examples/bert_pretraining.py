#!/usr/bin/env python
"""BERT pretraining example (the reference's bing_bert flow, TPU-native).

Synthetic data; swap ``synthetic_dataset`` for your tokenized corpus.

Single host:   python examples/bert_pretraining.py --steps 50
Multi host:    bin/deepspeed --hostfile H examples/bert_pretraining.py
ZeRO/offload/remat are plain config edits below (docs/config.md).
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import deepspeed_tpu as deepspeed  # noqa: E402
from deepspeed_tpu.models import BertConfig, BertForPreTrainingTPU  # noqa: E402
from deepspeed_tpu.parallel import make_mesh  # noqa: E402


def synthetic_dataset(n, seq, vocab, seed=0, n_pred=0):
    """Exactly ``n_pred`` masked positions per sample when set — the
    bing_bert max_predictions_per_seq contract the gather head assumes."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        ids = rng.integers(0, vocab, size=(seq,)).astype(np.int32)
        labels = np.full((seq,), -100, np.int32)
        if n_pred:
            pos = rng.permutation(seq)[:n_pred]
            labels[pos] = ids[pos]
        else:
            labels = np.where(rng.random(seq) < 0.15, ids, -100).astype(np.int32)
        out.append({
            "input_ids": ids,
            "attention_mask": np.ones((seq,), np.int32),
            "token_type_ids": np.zeros((seq,), np.int32),
            "masked_lm_labels": labels,
            "next_sentence_labels": np.int32(rng.integers(0, 2)),
        })
    return out


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--batch", type=int, default=32)
    parser.add_argument("--seq", type=int, default=128)
    parser.add_argument("--model", choices=["tiny", "base", "large"],
                        default="large")
    parser.add_argument("--zero", type=int, default=0)
    parser.add_argument("--data_parallel", type=int, default=-1)
    parser.add_argument("--ckpt_dir", type=str, default="")
    parser.add_argument("--max_predictions", type=int, default=20,
                        help="MLM positions per sample; the head + final "
                             "encoder layer compute only these (0 = full)")
    deepspeed.add_config_arguments(parser)
    args = parser.parse_args()

    # --deepspeed_config, if given, wins over the inline dict below
    config = None if getattr(args, "deepspeed_config", None) else {
        "train_batch_size": args.batch,
        "steps_per_print": 10,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4,
                                                 "weight_decay": 0.01}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_num_steps": 100}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": args.zero},
        "gradient_clipping": 1.0,
    }

    n_pred = max(args.max_predictions, 0) or None
    if args.model == "tiny":
        bert_cfg = BertConfig(vocab_size=1024, hidden_size=128,
                              num_hidden_layers=2, num_attention_heads=4,
                              max_position_embeddings=max(args.seq, 128),
                              max_predictions_per_seq=n_pred)
    elif args.model == "base":
        bert_cfg = BertConfig.bert_base(max_predictions_per_seq=n_pred)
    else:
        bert_cfg = BertConfig.bert_large(max_predictions_per_seq=n_pred)

    mesh = make_mesh({"data": args.data_parallel})
    model = BertForPreTrainingTPU(bert_cfg)
    dataset = synthetic_dataset(args.batch * 4, args.seq, bert_cfg.vocab_size,
                                n_pred=min(n_pred or 0, args.seq))
    engine, _, loader, _ = deepspeed.initialize(
        args=args, model=model, config=config, mesh=mesh,
        training_data=dataset)

    for step in range(args.steps):
        loss = engine.train_batch()
    print(f"final loss: {float(np.asarray(loss)):.4f}")
    if args.ckpt_dir:
        engine.save_checkpoint(args.ckpt_dir)


if __name__ == "__main__":
    main()
