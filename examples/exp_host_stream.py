"""Feasibility probe: in-jit chunked streaming of pinned_host buffers.

The round-4 capacity ladder exposed that in-jit offload moves the WHOLE
fp32 master + m + v to device for the update (peak HBM 21.8 G at
GPT-2-large — offload trained a SMALLER max model than device mode).
The fix needs XLA to support, inside one jit:

  1. slicing a pinned_host-space operand in host memory space,
  2. device_put of the slice to device space (copy-start/done),
  3. device_put of a result back to pinned_host,
  4. building the host-space output from chunk results
     (concatenate in host space), with input/output aliasing.

This probes each piece on the real backend and times a chunked Adam-style
sweep vs the full-buffer form at a size where full-form peak would be
~3x the buffer.

Usage: python examples/exp_host_stream.py [rows] [chunks]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

ROWS = int(sys.argv[1]) if len(sys.argv) > 1 else 200_000  # 0.8 GB fp32
CHUNKS = int(sys.argv[2]) if len(sys.argv) > 2 else 8
LANES = 1024


def main():
    dev = jax.devices()[0]
    mesh = jax.sharding.Mesh(np.array([dev]), ("data",))
    host = NamedSharding(mesh, P(), memory_kind="pinned_host")
    devs = NamedSharding(mesh, P(), memory_kind="device")

    rows = (ROWS // CHUNKS) * CHUNKS
    cr = rows // CHUNKS
    g = jax.device_put(jnp.full((rows, LANES), 1e-3, jnp.float32), devs)

    def fresh():
        # per-variant buffers: donation consumes them
        return (jax.device_put(jnp.ones((rows, LANES), jnp.float32), host),
                jax.device_put(jnp.zeros((rows, LANES), jnp.float32), host))

    def full_update(x, m, g):
        xd = jax.device_put(x, devs)
        md = jax.device_put(m, devs)
        m2 = 0.9 * md + 0.1 * g
        x2 = xd - 0.01 * m2
        # device-scalar fence output: indexing a pinned_host array EAGERLY
        # (x2[0, 0] outside jit) compiles a tiny host-space program that
        # SIGABRTs this toolchain — fence on a device scalar instead
        return (jax.device_put(x2, host), jax.device_put(m2, host),
                jnp.sum(m2[0, :8]))

    def chunked_update(x, m, g):
        xs, ms = [], []
        token = jnp.float32(0.0)
        for c in range(CHUNKS):
            sl = slice(c * cr, (c + 1) * cr)
            # chain chunks: without the barrier the pipelines are
            # independent and XLA schedules them ALL at once — peak HBM
            # equals the full buffers again (the engine's _after fence)
            xh, mh = jax.lax.optimization_barrier(
                ((jax.lax.slice_in_dim(x, c * cr, (c + 1) * cr),
                  jax.lax.slice_in_dim(m, c * cr, (c + 1) * cr)), token))[0]
            xd = jax.device_put(xh, devs)
            md = jax.device_put(mh, devs)
            m2 = 0.9 * md + 0.1 * g[sl]
            x2 = xd - 0.01 * m2
            token = m2[0, 0]
            xs.append(jax.device_put(x2, host))
            ms.append(jax.device_put(m2, host))
        return (jnp.concatenate(xs, axis=0), jnp.concatenate(ms, axis=0),
                token)

    for name, fn in (("full", full_update), ("chunked", chunked_update)):
        try:
            x, m = fresh()
            f = jax.jit(fn, donate_argnums=(0, 1),
                        out_shardings=(host, host, devs))
            x2, m2, s = f(x, m, g)
            float(jax.device_get(s))
            print(f"{name}: compiles+runs; out kinds "
                  f"{x2.sharding.memory_kind}/{m2.sharding.memory_kind}")
            t0 = time.perf_counter()
            for _ in range(5):
                x2, m2, s = f(x2, m2, g)
            float(jax.device_get(s))
            print(f"{name}: {(time.perf_counter() - t0) / 5 * 1e3:.1f} ms "
                  f"per sweep ({rows * LANES * 4 / 1e9:.2f} GB buffer)")
        except Exception as e:  # noqa: BLE001
            print(f"{name}: FAILED {e!r:.300}")


if __name__ == "__main__":
    main()
