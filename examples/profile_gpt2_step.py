"""GPT-2-medium step profile: per-scope wall attribution + ablations.

Finds where the GPT-2 training step's wall time goes on the real chip
(the bench.py row: h1024 L24 seq1024 vocab 50257, batch 8, ZeRO-2 + Lamb,
bf16, dropout 0.1).  Two instruments:

1. ``wall_breakdown`` — the engine's sub-programs (fwd / bwd / optimizer
   + flatten / param cast);
2. ``model_scope_breakdown`` — nested model scopes (embed → trunk →
   +head/CE), differenced to attribute the LM head;
3. ablation engines — one knob changed each (dropout off, Adam, XLA
   attention, chunked CE, ZeRO stage 0), train_batch wall deltas.

Usage: python examples/profile_gpt2_step.py [quick]
"""

import os
import sys
import time

import numpy as np

STEPS = int(os.environ.get("PROF_STEPS", "10"))
WARMUP = int(os.environ.get("PROF_WARMUP", "3"))
BATCH = int(os.environ.get("PROF_BATCH", "8"))
SEQ = 1024


def build_engine(deepspeed, mesh, dropout=0.1, optimizer="Lamb", zero=2,
                 loss_chunk=0, attn_impl="auto", hidden=1024, layers=24,
                 heads=16):
    from deepspeed_tpu.models import GPT2Config, GPT2LMHeadTPU

    cfg = GPT2Config(hidden_size=hidden, num_layers=layers, num_heads=heads,
                     max_position_embeddings=SEQ, embd_dropout=dropout,
                     attn_dropout=dropout, resid_dropout=dropout,
                     loss_chunk=loss_chunk, attn_impl=attn_impl)
    model = GPT2LMHeadTPU(cfg)
    engine, *_ = deepspeed.initialize(
        model=model, mesh=mesh,
        config={"train_batch_size": BATCH, "steps_per_print": 10 ** 9,
                "optimizer": {"type": optimizer, "params": {"lr": 1e-4}},
                "zero_optimization": {"stage": zero},
                "bf16": {"enabled": True}})
    return engine, model, cfg


def main():
    import jax

    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.parallel import make_mesh
    from deepspeed_tpu.profiling import (model_scope_breakdown, timed_loop,
                                         wall_breakdown)

    quick = "quick" in sys.argv[1:]
    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, 50257, size=(BATCH, SEQ)).astype(np.int32)}

    print(f"== GPT-2-medium step profile (batch {BATCH}, seq {SEQ}, "
          f"steps {STEPS}) ==", flush=True)

    # -- baseline engine: sub-program breakdown -------------------------
    t0 = time.perf_counter()
    engine, model, cfg = build_engine(deepspeed, mesh)
    print(f"[engine built in {time.perf_counter() - t0:.0f}s]", flush=True)
    t0 = time.perf_counter()
    bd = wall_breakdown(engine, batch, steps=STEPS, warmup=WARMUP)
    print(f"[breakdown took {time.perf_counter() - t0:.0f}s]")
    for k, v in bd.items():
        print(f"  {k:>22}: {v:8.2f} ms")
    total = bd["train_step"]
    sps = BATCH / (total / 1e3)
    print(f"  baseline throughput: {sps:.1f} samples/s")

    # -- model scopes ---------------------------------------------------
    import jax.numpy as jnp

    base_rng = engine._next_rng()

    def sc_embed(p, i):
        ids = jnp.asarray(batch["input_ids"])
        x = jnp.take(p["wte"], ids, axis=0) + p["wpe"][None, :SEQ]
        return jnp.sum(x.astype(jnp.float32) ** 2) * 1e-9

    def sc_hidden(p, i):
        r = jax.random.fold_in(base_rng, i)
        x = model.hidden(p, jnp.asarray(batch["input_ids"]), rng=r,
                         deterministic=False)
        return jnp.sum(x.astype(jnp.float32) ** 2) * 1e-9

    def sc_full(p, i):
        r = jax.random.fold_in(base_rng, i)
        return model.apply(p, batch, rng=r, train=True)

    scopes = model_scope_breakdown(
        engine, {"embed": sc_embed, "hidden(trunk)": sc_hidden,
                 "full(+head/CE)": sc_full},
        steps=max(STEPS // 2, 4), warmup=2)
    for name, d in scopes.items():
        print(f"  scope {name:>16}: fwd {d['fwd']:7.2f} ms   "
              f"fwd+bwd {d['fwd_bwd']:8.2f} ms")
    head = (scopes["full(+head/CE)"]["fwd_bwd"]
            - scopes["hidden(trunk)"]["fwd_bwd"])
    print(f"  derived LM head + CE (fwd+bwd): {head:.2f} ms")
    del engine, model

    if quick:
        return

    # -- ablations: one knob each --------------------------------------
    def steptime(**kw):
        e, m, _ = build_engine(deepspeed, mesh, **kw)
        t = timed_loop(lambda: e.train_batch(iter([batch])),
                       steps=STEPS, warmup=WARMUP) * 1e3
        del e, m
        return t

    ablations = {
        "dropout=0": dict(dropout=0.0),
        "optimizer=Adam": dict(optimizer="Adam"),
        "zero_stage=0": dict(zero=0),
        "loss_chunk=256": dict(loss_chunk=256),
        "attn=XLA (no flash)": dict(attn_impl="auto"),  # env flip below
    }
    for name, kw in ablations.items():
        if "attn=" in name:
            os.environ["DS_FLASH_ATTENTION"] = "never"
        try:
            t = steptime(**kw)
        finally:
            os.environ.pop("DS_FLASH_ATTENTION", None)
        print(f"  ablation {name:>20}: {t:8.2f} ms  "
              f"(delta {t - total:+7.2f} ms vs baseline)", flush=True)


if __name__ == "__main__":
    main()
