"""Block-size sweep for the flash attention kernel at the GPT-2 bench
shape (b8 s1024 h16 d64, causal) — the step profile shows attention at
~42% of the layer's fwd+bwd wall, running far below the GEMMs'
efficiency, so block geometry is the first lever to re-audit.

Also times jax.experimental.pallas.ops.tpu flash attention (if present)
and XLA's batched attention at the same shape for reference.

Usage: python examples/tune_flash_attention.py [b s h d]
"""

import os
import sys

import numpy as np

STEPS = int(os.environ.get("PROF_STEPS", "30"))


def main():
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.ops.transformer.flash_attention import flash_attention
    from deepspeed_tpu.profiling.step_profiler import grad_fold, timed_scan

    args = [int(a) for a in sys.argv[1:]] or [8, 1024, 16, 64]
    B, S, H, D = args
    causal = True
    qkv = tuple(jax.random.normal(k, (B, S, H, D), jnp.bfloat16)
                for k in jax.random.split(jax.random.PRNGKey(0), 3))

    def t(name, fn, bwd=True):
        fwd_ms = timed_scan(fn, qkv, steps=STEPS) * 1e3

        def fb(o, i):
            val, grads = jax.value_and_grad(lambda oo: fn(oo, i))(o)
            return val + 1e-30 * grad_fold(grads)

        fb_ms = timed_scan(fb, qkv, steps=STEPS) * 1e3
        print(f"  {name:>34}: fwd {fwd_ms:7.3f} ms   fwd+bwd {fb_ms:7.3f} ms",
              flush=True)
        return fwd_ms, fb_ms

    print(f"== flash attention sweep b{B} s{S} h{H} d{D} causal ==",
          flush=True)

    def ours(bq, bk):
        def f(o, i):
            q, k, v = o
            out = flash_attention(q, k, v, causal=causal, block_q=bq,
                                  block_k=bk)
            return jnp.sum(out.astype(jnp.float32)) * 1e-9

        return f

    t("auto blocks", lambda o, i: ours(None, None)(o, i))
    for bq in (256, 512, 1024):
        for bk in (256, 512, 1024):
            if bq > S or bk > S:
                continue
            try:
                t(f"block_q={bq} block_k={bk}", ours(bq, bk))
            except Exception as e:  # noqa: BLE001
                print(f"  block_q={bq} block_k={bk}: FAILED {e!r:.120}",
                      flush=True)

    # stock pallas kernel
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as jax_flash)

        def stock(o, i):
            q, k, v = o
            # stock kernel wants [b, h, s, d]
            qt, kt, vt = (a.transpose(0, 2, 1, 3) for a in (q, k, v))
            out = jax_flash(qt, kt, vt, causal=causal)
            return jnp.sum(out.astype(jnp.float32)) * 1e-9

        t("jax.experimental pallas flash", stock)
    except Exception as e:  # noqa: BLE001
        print(f"  stock pallas flash unavailable: {e!r:.120}")

    # splash attention (the newer tuned kernel family)
    try:
        from jax.experimental.pallas.ops.tpu.splash_attention import (
            splash_attention_kernel as sak,
            splash_attention_mask as sam)

        mask = sam.CausalMask((S, S))
        multi = sam.MultiHeadMask([mask] * H)
        kernel = sak.make_splash_mha(
            multi, head_shards=1, q_seq_shards=1)

        def splash(o, i):
            q, k, v = o
            qt, kt, vt = (a.transpose(0, 2, 1, 3) for a in (q, k, v))
            out = jax.vmap(kernel)(qt, kt, vt)
            return jnp.sum(out.astype(jnp.float32)) * 1e-9

        t("splash attention (causal)", splash)
    except Exception as e:  # noqa: BLE001
        print(f"  splash attention unavailable: {e!r:.120}")


if __name__ == "__main__":
    main()
