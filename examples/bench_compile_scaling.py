"""Compile-time scaling of the streamed-offload update: unrolled vs scan.

The round-5 capacity ceiling was COMPILE WALL TIME: the unrolled
chunk-streamed update lowers one full update pipeline per chunk, so
program size grows linearly in chunk count and compile time grows
super-linearly (gpt2-xl, 37 chunks: ~35 min on the tunneled toolchain;
2.7B never finished in 30 min).  The uniform-chunk scan update
(``runtime/zero/stream.py``, ``"offload_uniform_chunks"``) traces the
chunk body once — this script measures both forms' lower+compile wall
at growing chunk counts over a FIXED model, so the scaling (not the
absolute seconds, which are backend-dependent) is the receipt.

Runs on any backend: on CPU (no pinned_host memory space) it forces the
in-jit program structure (DS_OFFLOAD_FORCE_INJIT) with placements
compiled as no-ops — program SHAPE, and therefore compile-cost scaling,
is what this benchmark is about.

Usage: python examples/bench_compile_scaling.py [chunk_mb ...]
"""

import os
import sys
import time

if os.environ.get("JAX_PLATFORMS", "") in ("", "cpu"):
    os.environ.setdefault("DS_OFFLOAD_FORCE_INJIT", "1")
# a process-local cache would hide recompiles of the SAME program; each
# (mode, chunk_mb) program here is distinct, but keep runs hermetic
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import jax
import jax.numpy as jnp

import deepspeed_tpu as deepspeed
from deepspeed_tpu.parallel import make_mesh

HIDDEN = int(os.environ.get("SCALING_HIDDEN", "1024"))
LAYERS = int(os.environ.get("SCALING_LAYERS", "32"))


class _Stack:
    """Minimal linear stack conforming to the engine model contract."""

    def init(self, rng):
        params = {}
        for i in range(LAYERS):
            k, rng = jax.random.split(rng)
            params[f"l{i}"] = {"w": jax.random.normal(
                k, (HIDDEN, HIDDEN), jnp.float32) * 0.02}
        return params

    def apply(self, params, batch, rng=None, train=True, **kw):
        h = batch
        for i in range(LAYERS):
            h = jnp.tanh(h @ params[f"l{i}"]["w"])
        return jnp.mean(h ** 2)


def measure(uniform, chunk_mb):
    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    engine, *_ = deepspeed.initialize(
        model=_Stack(), mesh=mesh,
        config={"train_batch_size": 4, "steps_per_print": 10 ** 9,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                "zero_optimization": {"stage": 2, "cpu_offload": True,
                                      "offload_chunk_mb": chunk_mb,
                                      "offload_uniform_chunks": uniform},
                # compiles ARE the measurement here — never cache them
                "compilation": {"cache": False},
                "bf16": {"enabled": True}})
    rows = engine.segments.rows
    chunks = -(-rows * 4096 // (chunk_mb << 20))
    flat_g = jnp.zeros(engine.segments.shape, jnp.float32)
    hp = engine._device_hyperparams()
    t0 = time.perf_counter()
    lowered = engine._apply_fn.lower(
        engine.state["master"], engine.state["opt"], engine.state["scale"],
        engine.state["skipped"], flat_g, hp, None)
    t_lower = time.perf_counter() - t0
    t0 = time.perf_counter()
    lowered.compile()
    t_compile = time.perf_counter() - t0
    hlo_lines = lowered.as_text().count("\n")
    return chunks, hlo_lines, t_lower, t_compile


def main():
    chunk_mbs = [int(a) for a in sys.argv[1:]] or [16, 4, 1]
    print(f"model: {LAYERS}x{HIDDEN}^2 linear stack, "
          f"state rows vary with chunk alignment; backend="
          f"{jax.devices()[0].platform}")
    print(f"{'mode':>9} {'chunk_mb':>8} {'chunks':>6} {'hlo_lines':>9} "
          f"{'lower_s':>8} {'compile_s':>9}")
    for uniform in (False, True):
        for cmb in chunk_mbs:
            chunks, lines, tl, tc = measure(uniform, cmb)
            mode = "scan" if uniform else "unrolled"
            print(f"{mode:>9} {cmb:>8} {chunks:>6} {lines:>9} "
                  f"{tl:>8.2f} {tc:>9.2f}", flush=True)


if __name__ == "__main__":
    main()
