"""A/B microbench for the flat-space LAMB update (the measured ~11 ms/step
GPT-2-medium tax over Adam — PERF.md step breakdown).

LAMB's extra HBM traffic over Adam is bounded below by 3 sweeps of the
flat buffer (write update, read update for norms, read update for apply);
anything above that is XLA scheduling slack this bench exists to find.
Times each variant as a two-point (N vs 2N) scanned loop inside one jit so
the ~100 ms dispatch fence cancels.

Usage: python examples/bench_lamb_update.py [rows]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
from deepspeed_tpu.ops.lamb.fused_lamb import FusedLamb
from deepspeed_tpu.ops.op_common import LANES, build_segments

# GPT-2-medium-ish: 355M params
ROWS = int(sys.argv[1]) if len(sys.argv) > 1 else 347_000


def time_update(opt, segments, rows, n=8):
    hp = opt.hyperparams()
    p0 = jnp.ones((rows, LANES), jnp.float32) * 0.01
    g0 = jnp.full((rows, LANES), 1e-4, jnp.float32)
    st0 = opt.init_state(p0)

    def loop(steps, p, st, g):
        def body(carry, _):
            p_, st_ = carry
            # perturb the grad by the step counter so the scan body cannot
            # be hoisted as loop-invariant
            gg = g + st_.step.astype(jnp.float32) * 1e-9
            p2, st2 = opt.update(st_, p_, gg, hp, segments=segments,
                                 segment_ids=None)
            return (p2, st2), ()

        (p, st), _ = jax.lax.scan(body, (p, st), None, length=steps)
        return p, st

    f = jax.jit(loop, static_argnums=(0,))

    def run(steps):
        t0 = time.perf_counter()
        p, st = f(steps, p0, st0, g0)
        float(jax.device_get(st.step))
        float(jax.device_get(p[0, 0]))
        return time.perf_counter() - t0

    run(n)  # compile + warm
    run(2 * n)
    t1, t2 = run(n), run(2 * n)
    return (t2 - t1) / n * 1e3


class BarrierLamb(FusedLamb):
    """Two-pass variant: materialize (m, v, update) exactly once behind an
    optimization barrier, then norms + apply read the materialized buffers.
    Lower bound on LAMB-over-Adam HBM: +3 sweeps (write u, read u for
    norms, read u for apply)."""

    def update(self, state, flat_master, flat_grads, hp, segments=None,
               segment_ids=None):
        from deepspeed_tpu.ops.op_common import segment_l2_norms_rows
        lr, beta1, beta2, wd = (hp["lr"], hp["beta1"], hp["beta2"],
                                hp["weight_decay"])
        g = jnp.asarray(flat_grads, jnp.float32)
        p = flat_master
        step = state.step + 1
        m = beta1 * state.exp_avg + (1.0 - beta1) * g
        v = beta2 * state.exp_avg_sq + (1.0 - beta2) * (g * g)
        tf = step.astype(jnp.float32)
        m_hat = m / (1.0 - beta1 ** tf)
        v_hat = v / (1.0 - beta2 ** tf)
        update = m_hat / (jnp.sqrt(v_hat) + self.eps) + wd * p
        m, v, update = jax.lax.optimization_barrier((m, v, update))
        w_norms = segment_l2_norms_rows(p, segments)
        u_norms = segment_l2_norms_rows(update, segments)
        ratio = jnp.where((w_norms > 0) & (u_norms > 0),
                          jnp.clip(w_norms / u_norms, self.min_coeff,
                                   self.max_coeff),
                          jnp.ones_like(w_norms))
        ratio_full = jnp.concatenate([ratio, jnp.ones((1,), jnp.float32)])
        scale = ratio_full[jnp.asarray(segments.row_segment_ids())][:, None]
        new_p = p - lr * scale * update
        from deepspeed_tpu.ops.lamb.fused_lamb import LambState
        return new_p, LambState(exp_avg=m, exp_avg_sq=v, step=step)


def main():
    # ~300 tensors with GPT-2-ish size mix
    sizes = []
    per_layer = [1024 * 3072, 3072, 1024 * 1024, 1024, 1024 * 4096, 4096,
                 4096 * 1024, 1024, 1024, 1024, 1024, 1024]
    for _ in range(24):
        sizes += per_layer
    sizes += [50257 * 1024, 1024 * 1024, 1024, 1024]
    segments = build_segments(sizes)
    rows = max(segments.rows, ROWS)
    segments = segments._replace(rows=rows)
    print(f"buffer: {rows} rows x {LANES} = {rows * LANES / 1e6:.0f}M f32 "
          f"({rows * LANES * 4 / 1e9:.2f} GB), {len(sizes)} tensors")

    adam_ms = time_update(FusedAdam(lr=1e-4), segments, rows)
    print(f"adam:         {adam_ms:7.2f} ms/step")
    lamb_ms = time_update(FusedLamb(lr=1e-4), segments, rows)
    print(f"lamb:         {lamb_ms:7.2f} ms/step  (+{lamb_ms - adam_ms:.2f})")
    bar_ms = time_update(BarrierLamb(lr=1e-4), segments, rows)
    print(f"barrier-lamb: {bar_ms:7.2f} ms/step  (+{bar_ms - adam_ms:.2f})")


if __name__ == "__main__":
    main()
