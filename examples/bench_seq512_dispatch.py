"""seq-512 attention dispatch audit: XLA vs Pallas inside the full BERT step.

Seq 512 sits exactly on the dispatch boundary in
``ops/transformer/attention.py`` (XLA batched attention below 512, the
Pallas flash kernel at 512+).  This A/Bs the two impls inside the
END-TO-END BERT-large seq-512 pretraining step — the bench secondary —
rather than at the isolated-op level, because the winner can differ once
XLA schedules attention against the rest of the layer.

Each cell runs in a fresh subprocess (DS_FLASH_ATTENTION binds at trace
time; co-resident engines distort HBM).

Usage: python examples/bench_seq512_dispatch.py [batch ...]
"""

import os
import subprocess
import sys

_TRIAL = r"""
import os, time, math, numpy as np, jax
import deepspeed_tpu as deepspeed
from deepspeed_tpu.models import BertConfig, BertForPreTrainingTPU
from deepspeed_tpu.parallel import make_mesh

b = int(os.environ["T_B"]); steps = int(os.environ["T_S"])
dropout_p = 0.1
VOCAB = 30528
mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
cfg = BertConfig.bert_large(max_position_embeddings=512, vocab_size=VOCAB,
                            hidden_dropout_prob=dropout_p,
                            attention_probs_dropout_prob=dropout_p,
                            max_predictions_per_seq=80)
model = BertForPreTrainingTPU(cfg, compute_dtype=None)
engine, *_ = deepspeed.initialize(
    model=model, mesh=mesh,
    config={"train_batch_size": b, "steps_per_print": 10 ** 9,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "bf16": {"enabled": True}})
rng = np.random.default_rng(0)
ids = rng.integers(0, VOCAB, size=(b, 512)).astype(np.int32)
from bench import exact_count_mlm_labels
batch = {"input_ids": ids,
         "attention_mask": np.ones((b, 512), np.int32),
         "token_type_ids": np.zeros((b, 512), np.int32),
         "masked_lm_labels": exact_count_mlm_labels(rng, ids, 80),
         "next_sentence_labels": rng.integers(0, 2, size=(b,)).astype(np.int32)}
for _ in range(4):
    loss = engine.train_batch(iter([batch]))
float(jax.device_get(loss))
t0 = time.perf_counter()
for _ in range(steps):
    loss = engine.train_batch(iter([batch]))
v = float(jax.device_get(loss))
dt = time.perf_counter() - t0
assert math.isfinite(v)
print(f"AB_RESULT {b * steps / dt:.2f}")
"""


def run_cell(mode, batch, steps=12):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # APPEND to PYTHONPATH: clobbering it drops the site dir that
    # registers the TPU attachment plugin on this environment
    pp = os.environ.get("PYTHONPATH", "")
    env = dict(os.environ, DS_FLASH_ATTENTION=mode, T_B=str(batch),
               T_S=str(steps),
               PYTHONPATH=f"{repo}:{pp}" if pp else repo)
    proc = subprocess.run([sys.executable, "-u", "-c", _TRIAL], env=env,
                          capture_output=True, text=True, timeout=1800,
                          cwd=repo)
    for line in proc.stdout.splitlines():
        if line.startswith("AB_RESULT "):
            return float(line.split()[1])
    tail = (proc.stdout + proc.stderr)[-300:].replace("\n", " ")
    oom = "RESOURCE_EXHAUSTED" in tail or "Out of memory" in tail
    return "OOM" if oom else f"fail: {tail[-120:]}"


def main():
    batches = [int(a) for a in sys.argv[1:]] or [16, 32]
    print("BERT-large seq512, dropout 0.1, Adam — samples/s by attention impl")
    for b in batches:
        for mode in ("always", "never"):
            label = {"always": "pallas", "never": "xla   "}[mode]
            r = run_cell(mode, b)
            print(f"  batch {b:3d}  {label}: {r}", flush=True)


if __name__ == "__main__":
    main()
