"""ZeRO-Offload capacity headline: largest model trainable on ONE chip.

The reference's ZeRO-Offload claim is "10× bigger models on one GPU —
13B params on a single V100-32GB" (``docs/_posts/2020-09-09-
ZeRO-Offload.md:10``).  This measures the TPU framework's analog on the
single v5e (16 GB HBM): walk GPT-2-family configs upward, try a few
training steps with ``cpu_offload`` off vs on, record the largest config
that trains and the offload step-time tax.

Each trial runs in a FRESH SUBPROCESS: compiled executables and buffers
from a previous trial linger in-process (observed: a config that OOMs
after prior same-process trials trains fine alone), so isolation is the
only way to get truthful capacity numbers.  All trials share one
persistent XLA compile cache (exported via JAX_COMPILATION_CACHE_DIR),
so a re-run — or a retry of a flaked trial — warm-starts its programs;
each trial prints its cold/warm compile-wall split.

Rows past gpt2-xl ride the round-6 O(1)-compile configuration: the
uniform-chunk scan update ("offload_uniform_chunks": auto engages past
24 chunks) keeps program size constant in chunk count — the round-5
blocker at 2.7B was >30 min of REMOTE-COMPILE wall for the unrolled
chunk programs, not memory.

Round 12 adds an **overlap mode** (``overlap`` argument): A/B the
double-buffered chunk pipeline (``offload_overlap`` on vs off) on the
gpt2-large offload row and emit ONE ``bench_schema``-validated JSON
record as the last line — ``offload_gpt2_large_ms_per_step`` (the
serialized control), ``offload_gpt2_large_overlap_ms_per_step`` (the
headline; target ≤ ~0.5 s/step on the bench attachment), plus both
schedules' static exposed-wire receipts so the bench JSON alone shows
the exposure drop.  On a non-TPU backend the same harness path runs
end-to-end at toy geometry under ``DS_OFFLOAD_FORCE_INJIT`` and the
record carries ``note: "dryrun"`` — a CPU box proves the plumbing, the
bench attachment proves the milliseconds.

Usage: python examples/bench_offload_capacity.py [quick|overlap [quick]]
"""

import json
import os
import subprocess
import sys

SEQ = 1024
BATCH = int(os.environ.get("CAP_BATCH", "4"))
STEPS = int(os.environ.get("CAP_STEPS", "6"))
TIMEOUT = int(os.environ.get("CAP_TIMEOUT", "3600"))

# (name, hidden, layers, heads) — params ≈ 12·L·h² + vocab·h
LADDER = [
    ("gpt2-medium-0.35B", 1024, 24, 16),
    ("gpt2-large-0.77B", 1280, 36, 20),
    ("gpt2-1.0B", 1408, 40, 22),
    ("gpt2-xl-1.5B", 1600, 48, 25),
    ("gpt2-2.7B", 2560, 32, 32),
    ("gpt2-4.2B", 3072, 36, 32),
    ("gpt2-6.7B", 4096, 32, 32),
]

_TRIAL = r"""
import time, numpy as np, jax
from deepspeed_tpu.runtime.compilation import CompileStats
import deepspeed_tpu as deepspeed
from deepspeed_tpu.models import GPT2Config, GPT2LMHeadTPU
from deepspeed_tpu.parallel import make_mesh
import os
stats = CompileStats()
h = int(os.environ["T_H"]); L = int(os.environ["T_L"])
heads = int(os.environ["T_HEADS"]); off = os.environ["T_OFF"] == "1"
batch = int(os.environ["T_B"]); steps = int(os.environ["T_S"])
cfg = GPT2Config(hidden_size=h, num_layers=L, num_heads=heads,
                 max_position_embeddings=1024, embd_dropout=0.0,
                 attn_dropout=0.0, resid_dropout=0.0,
                 remat=True, loss_chunk=256)
mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
model = GPT2LMHeadTPU(cfg)
og = os.environ.get("T_OG") == "1"
zero = {"stage": 2, "cpu_offload": off, "offload_gradients": og and off}
gmb = int(os.environ.get("T_GMB", "0"))
if gmb:
    # manual escape hatch only: the coordinator auto-derives the group
    # layout by capping total buffer COUNT since round 6 (the round-5
    # many-buffer AOT crash mode; gpt2-xl needed a manual 3584 then)
    zero["offload_group_mb"] = gmb
sdt = os.environ.get("T_SDT", "")
if sdt:
    # reduced-precision host state ("bf16"/"fp16"): halves state wire
    zero["offload_state_dtype"] = sdt
ov = os.environ.get("T_OV", "")
cfg_extra = {}
if ov:
    # overlap A/B mode: pin the issue schedule explicitly and enable
    # the comm ledger so the trial can print the static exposed-wire
    # receipt next to the measured milliseconds
    zero["offload_overlap"] = ov == "on"
    cfg_extra["profiling"] = {"comm_ledger": True}
cmb = os.environ.get("T_CMB", "")
if cmb:
    zero["offload_chunk_mb"] = int(cmb)
engine, *_ = deepspeed.initialize(model=model, mesh=mesh,
    config={"train_batch_size": batch, "steps_per_print": 10 ** 9,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "zero_optimization": zero,
            "bf16": {"enabled": True}, **cfg_extra})
rng = np.random.default_rng(0)
b = {"input_ids": rng.integers(0, cfg.vocab_size,
                               size=(batch, 1024)).astype(np.int32)}
# TWO fenced warmups: the engine compiles a second program on step 1
for _ in range(2):
    loss = engine.train_batch(iter([b]))
    float(np.asarray(jax.device_get(loss)))
t0 = time.perf_counter()
for _ in range(steps):
    loss = engine.train_batch(iter([b]))
v = float(np.asarray(jax.device_get(loss)))
dt = (time.perf_counter() - t0) / steps
assert np.isfinite(v)
s = stats.as_dict()
print(f"CAP_COMPILE cold={s['compile_seconds_cold']} "
      f"warm={s['compile_seconds_warm']} hits={s['compile_cache_hits']} "
      f"misses={s['compile_cache_misses']}")
if off:
    print(f"CAP_STATE dtype={engine.host_state_dtype()} "
          f"bytes_per_step={engine.host_state_bytes_per_step()} "
          f"groups={len(engine.flat.host_group_bounds or ((0, 0),))}")
if ov:
    rcpt = engine.overlap_receipt() or {}
    sched = engine.host_stream_schedule() or {}
    print("CAP_OVERLAP " + __import__("json").dumps({
        "overlap": sched.get("overlap"),
        "prefetch_depth": sched.get("prefetch_depth"),
        "chunks": sched.get("chunks"),
        "exposed_wire_seconds": rcpt.get("exposed_wire_seconds"),
        "overlap_fraction": rcpt.get("overlap_fraction"),
        "host_state_bytes_per_step": engine.host_state_bytes_per_step(),
    }))
print(f"CAP_RESULT {dt * 1e3:.0f}")
"""


def param_count(h, L, vocab=50257, pos=SEQ):
    return 12 * L * h * h + (vocab + pos) * h + 2 * h


def try_step(offload, hidden, layers, heads, offload_grads=False,
             params=0, extra_env=None):
    env = dict(os.environ, T_H=str(hidden), T_L=str(layers),
               T_HEADS=str(heads), T_OFF="1" if offload else "0",
               T_B=str(BATCH), T_S=str(STEPS),
               T_OG="1" if offload_grads else "0")
    env.update(extra_env or {})
    # no T_GMB default: the coordinator's buffer-count cap derives the
    # round-5 3584 layout (and beyond) automatically; export T_GMB to
    # force a manual group size, T_SDT=bf16 for reduced host state
    # one shared warm cache across every fresh-subprocess trial
    env.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache"))
    try:
        proc = subprocess.run([sys.executable, "-u", "-c", _TRIAL], env=env,
                              capture_output=True, text=True,
                              timeout=TIMEOUT)
    except subprocess.TimeoutExpired:
        return False, f"TIMEOUT ({TIMEOUT // 60} min)", "", None
    compile_line = ""
    overlap = None
    result = None
    for line in proc.stdout.splitlines():
        if line.startswith("CAP_COMPILE "):
            compile_line = line[len("CAP_COMPILE "):]
        if line.startswith("CAP_STATE "):
            compile_line = (compile_line + "  " if compile_line
                            else "") + line[len("CAP_STATE "):]
        if line.startswith("CAP_OVERLAP "):
            try:
                overlap = json.loads(line[len("CAP_OVERLAP "):])
            except ValueError:
                overlap = None
        if line.startswith("CAP_RESULT "):
            result = float(line.split()[1]) / 1e3
    if result is not None:
        return True, result, compile_line, overlap
    err = proc.stdout[-300:] + proc.stderr[-300:]
    oom = ("RESOURCE_EXHAUSTED" in err or "memory space hbm" in err
           or "Out of memory" in err or "ResourceExhausted" in err)
    return False, ("OOM" if oom else err.replace("\n", " ")[-200:]), \
        compile_line, overlap


def _backend_platform():
    """Default jax backend of a fresh subprocess (the parent stays
    jax-free so every trial keeps its isolation)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=120)
        return proc.stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def overlap_mode():
    """A/B the overlapped vs serialized chunk schedule and emit the
    bench record (see module docstring).  The LAST stdout line is the
    JSON record — drivers capture it like every other bench."""
    platform = _backend_platform()
    dryrun = platform != "tpu"
    if dryrun:
        # toy geometry through the identical harness path: fresh
        # subprocess, forced in-jit streaming, chunked scan, receipts
        h, L, heads = 256, 4, 4
        extra = {"T_CMB": "1", "T_SDT": "bf16",
                 "DS_OFFLOAD_FORCE_INJIT": "1",
                 "T_B": os.environ.get("CAP_BATCH", "1"),
                 "T_S": os.environ.get("CAP_STEPS", "2")}
    else:
        h, L, heads = 1280, 36, 20  # gpt2-large, the headline row
        extra = {"T_SDT": "bf16"}
    record = {"metric": "offload_overlap", "device": platform,
              "offload_gpt2_large_params_b": round(
                  param_count(h, L) / 1e9, 3)}
    if dryrun:
        record["offload_gpt2_large_overlap_note"] = (
            "dryrun: non-TPU backend, toy geometry (hidden "
            f"{h}, {L} layers) under DS_OFFLOAD_FORCE_INJIT — harness "
            "receipt only; the ms/step target needs the bench "
            "attachment")
    rows = {}
    for tag, ov in (("off", "off"), ("on", "on")):
        ok, info, compile_line, overlap = try_step(
            True, h, L, heads, extra_env={**extra, "T_OV": ov})
        suffix = f"  [{compile_line}]" if compile_line else ""
        if not ok:
            print(f"[overlap={tag}] FAIL {info}{suffix}", flush=True)
            record[f"offload_gpt2_large_overlap_error" if ov == "on"
                   else "offload_gpt2_large_error"] = str(info)[:300]
            continue
        rows[tag] = (info, overlap or {})
        print(f"[overlap={tag}] OK {info * 1e3:.0f} ms/step "
              f"{json.dumps(overlap)}{suffix}", flush=True)
    if "off" in rows:
        ms, ov_d = rows["off"]
        record["offload_gpt2_large_ms_per_step"] = round(ms * 1e3, 3)
        if ov_d.get("exposed_wire_seconds") is not None:
            record["offload_gpt2_large_exposed_wire_seconds"] = float(
                ov_d["exposed_wire_seconds"])
            record["offload_gpt2_large_overlap_fraction"] = float(
                ov_d["overlap_fraction"])
    if "on" in rows:
        ms, ov_d = rows["on"]
        record["offload_gpt2_large_overlap_ms_per_step"] = round(
            ms * 1e3, 3)
        for src, dst in (("exposed_wire_seconds",
                          "offload_gpt2_large_overlap_exposed_wire_seconds"),
                         ("overlap_fraction",
                          "offload_gpt2_large_overlap_overlap_fraction")):
            if ov_d.get(src) is not None:
                record[dst] = float(ov_d[src])
        if ov_d.get("host_state_bytes_per_step") is not None:
            record["offload_gpt2_large_overlap_host_state_bytes_per_step"] \
                = int(ov_d["host_state_bytes_per_step"])
    # schema check (fail-soft: drift reports to stderr, the record
    # always prints — the standing measurement rule)
    try:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from deepspeed_tpu.tools.bench_schema import validate_record

        for problem in validate_record(record):
            print(f"bench-schema: {problem}", file=sys.stderr)
    except Exception as e:  # pragma: no cover
        print(f"bench-schema unavailable: {e!r}", file=sys.stderr)
    print(json.dumps(record))
    return record


def main():
    if "overlap" in sys.argv[1:]:
        overlap_mode()
        return
    quick = "quick" in sys.argv[1:]
    ladder = LADDER[:3] if quick else LADDER
    # three modes: device-resident, offload (state only), offload+grads
    # (offload_gradients — the capacity configuration: bf16 params are
    # the only per-param device cost)
    modes = (("device", False, False), ("offload", True, False),
             ("offload+grads", True, True))
    results = {}
    for mode, offload, og in modes:
        for name, h, L, heads in ladder:
            n = param_count(h, L)
            ok, info, compile_line, _ = try_step(offload, h, L, heads,
                                                 offload_grads=og,
                                                 params=n)
            suffix = f"  [{compile_line}]" if compile_line else ""
            if ok:
                print(f"[{mode}] {name}: OK  {info * 1e3:.0f} ms/step "
                      f"({BATCH * SEQ / info:.0f} tok/s, {n / 1e9:.2f}B)"
                      f"{suffix}", flush=True)
                results[(mode, name)] = info
            else:
                print(f"[{mode}] {name}: FAIL {info} ({n / 1e9:.2f}B)"
                      f"{suffix}", flush=True)
                break  # ladder is monotone in memory need

    order = [name for name, *_ in LADDER]
    print("\nsummary:")
    for mode, *_ in modes:
        ok_names = [n for n in order if (mode, n) in results]
        if ok_names:
            largest = ok_names[-1]
            print(f"  {mode}: largest trainable = {largest} "
                  f"({results[(mode, largest)] * 1e3:.0f} ms/step)")
        else:
            print(f"  {mode}: nothing trained")


if __name__ == "__main__":
    main()
